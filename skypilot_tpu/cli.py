"""Command-line interface: ``skytpu`` / ``python -m skypilot_tpu.cli``.

Role of reference ``sky/cli.py`` (5.5k LoC of click commands): the same
verb surface — launch/exec/status/start/stop/down/autostop/queue/logs/
cancel/check/cost-report/optimize, plus the ``jobs`` and ``serve``
subcommand groups and the accelerator-catalog browser (``show-tpus``,
the TPU-first counterpart of ``sky show-gpus`` ``sky/cli.py:3085``).
Every command is a thin shell over the SDK in ``skypilot_tpu.core`` /
``execution`` / ``jobs.core`` / ``serve.core`` — the CLI owns parsing,
confirmation prompts, and table rendering only.
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

import click

import skypilot_tpu as sky
from skypilot_tpu import exceptions
from skypilot_tpu.task import Task


# ------------------------------------------------------------------ helpers
def _fmt_table(rows: List[List[str]], headers: List[str]) -> str:
    widths = [len(h) for h in headers]
    for row in rows:
        for i, cell in enumerate(row):
            widths[i] = max(widths[i], len(str(cell)))
    fmt = '  '.join(f'{{:<{w}}}' for w in widths)
    lines = [fmt.format(*headers)]
    lines += [fmt.format(*[str(c) for c in row]) for row in rows]
    return '\n'.join(lines)


def _fmt_age(ts: Optional[float]) -> str:
    if not ts:
        return '-'
    secs = max(0, time.time() - ts)
    for unit, div in (('d', 86400), ('h', 3600), ('m', 60)):
        if secs >= div:
            return f'{int(secs // div)}{unit} ago'
    return f'{int(secs)}s ago'


def _load_task(entrypoint: Optional[str],
               env: Tuple[str, ...] = (),
               name: Optional[str] = None) -> Task:
    """YAML path -> Task; no entrypoint -> empty (provision-only) task.

    --env overrides are merged into the YAML's ``envs:`` BEFORE the Task
    is constructed, so ``${VAR}`` interpolation anywhere in the config
    (resources, workdir, file_mounts — not just run/setup) sees the
    overridden values."""
    overrides = {}
    for item in env:
        if '=' not in item:
            raise click.UsageError(f'--env must be KEY=VALUE, got {item!r}')
        k, v = item.split('=', 1)
        overrides[k] = v
    if entrypoint is None:
        task = Task(name=name or 'sky-cmd')
        if overrides:
            task.update_envs(overrides)
    else:
        import os

        import yaml
        with open(os.path.expanduser(entrypoint), encoding='utf-8') as f:
            config = yaml.safe_load(f) or {}
        if overrides:
            envs = dict(config.get('envs') or {})
            envs.update(overrides)
            config['envs'] = envs
        task = Task.from_yaml_config(config)
    if name:
        task.name = name
    return task


def _confirm(message: str, yes: bool) -> None:
    if not yes:
        click.confirm(message, abort=True)


@click.group()
@click.version_option(sky.__version__, '--version', '-v')
def cli():
    """skypilot_tpu: run, manage, and serve workloads on TPU slices."""


# ----------------------------------------------------------------- clusters
@cli.command()
@click.argument('entrypoint', required=False, type=click.Path(exists=True))
@click.option('--cluster', '-c', default=None, help='Cluster name.')
@click.option('--dryrun', is_flag=True, help='Print the plan; launch nothing.')
@click.option('--yes', '-y', is_flag=True, help='Skip confirmation.')
@click.option('--detach-run', '-d', is_flag=True,
              help='Submit and return; do not stream job logs.')
@click.option('--idle-minutes-to-autostop', '-i', type=int, default=None,
              help='Autostop after this many idle minutes.')
@click.option('--down', is_flag=True,
              help='Autostop tears the cluster DOWN instead of stopping.')
@click.option('--retry-until-up', is_flag=True,
              help='Keep retrying across zones/regions until provisioned.')
@click.option('--no-setup', is_flag=True, help='Skip the setup phase.')
@click.option('--env', multiple=True, metavar='KEY=VALUE',
              help='Override task env vars (repeatable).')
def launch(entrypoint, cluster, dryrun, yes, detach_run,
           idle_minutes_to_autostop, down, retry_until_up, no_setup, env):
    """Launch a task YAML on a new or existing cluster."""
    task = _load_task(entrypoint, env)
    if not dryrun:
        _confirm(f'Launching task on cluster {cluster or "<new>"}. Proceed?',
                 yes)
    job_id, handle = sky.launch(
        task, cluster_name=cluster, dryrun=dryrun,
        detach_run=detach_run, stream_logs=not detach_run,
        idle_minutes_to_autostop=idle_minutes_to_autostop, down=down,
        retry_until_up=retry_until_up, no_setup=no_setup)
    if dryrun:
        return
    if job_id is not None:
        click.echo(f'Job submitted (id: {job_id}) on cluster '
                   f'{handle.cluster_name}.')


@cli.command(name='exec')
@click.argument('entrypoint', type=click.Path(exists=True))
@click.option('--cluster', '-c', required=True, help='Target cluster.')
@click.option('--detach-run', '-d', is_flag=True)
@click.option('--env', multiple=True, metavar='KEY=VALUE')
def exec_(entrypoint, cluster, detach_run, env):
    """Run a task on an existing cluster (skips provision/setup)."""
    task = _load_task(entrypoint, env)
    job_id, _ = getattr(sky, 'exec')(task, cluster,
                                     detach_run=detach_run)
    click.echo(f'Job submitted (id: {job_id}) on cluster {cluster}.')


@cli.command()
@click.argument('clusters', nargs=-1)
@click.option('--refresh', '-r', is_flag=True,
              help='Reconcile against the cloud before printing.')
def status(clusters, refresh):
    """Show clusters (reference ``sky status``)."""
    records = sky.status(list(clusters) or None, refresh=refresh)
    if not records:
        click.echo('No existing clusters.')
        return
    rows = []
    for r in records:
        handle = r.get('handle')
        res = (str(handle.launched_resources)
               if handle is not None and
               getattr(handle, 'launched_resources', None) is not None
               else '-')
        autostop = f"{r['autostop']}m" if r.get('autostop', -1) >= 0 else '-'
        rows.append([r['name'], _fmt_age(r.get('launched_at')), res,
                     r['status'].value, autostop])
    click.echo(_fmt_table(rows, ['NAME', 'LAUNCHED', 'RESOURCES', 'STATUS',
                                 'AUTOSTOP']))


@cli.command()
@click.argument('cluster')
@click.option('--idle-minutes-to-autostop', '-i', type=int, default=None)
@click.option('--retry-until-up', is_flag=True)
def start(cluster, idle_minutes_to_autostop, retry_until_up):
    """Restart a stopped cluster."""
    sky.start(cluster, idle_minutes_to_autostop=idle_minutes_to_autostop,
              retry_until_up=retry_until_up)
    click.echo(f'Cluster {cluster} started.')


@cli.command()
@click.argument('clusters', nargs=-1)
@click.option('--all', '-a', 'stop_all', is_flag=True)
@click.option('--yes', '-y', is_flag=True)
def stop(clusters, stop_all, yes):
    """Stop cluster(s) (preserves disk; billing stops for TPU time)."""
    names = _select_clusters(clusters, stop_all, 'stop')
    _confirm(f'Stopping {len(names)} cluster(s): {", ".join(names)}. '
             'Proceed?', yes)
    for name in names:
        sky.stop(name)
        click.echo(f'Cluster {name} stopped.')


@cli.command()
@click.argument('clusters', nargs=-1)
@click.option('--all', '-a', 'down_all', is_flag=True)
@click.option('--yes', '-y', is_flag=True)
def down(clusters, down_all, yes):
    """Tear down cluster(s)."""
    names = _select_clusters(clusters, down_all, 'down')
    _confirm(f'Tearing down {len(names)} cluster(s): {", ".join(names)}. '
             'Proceed?', yes)
    for name in names:
        sky.down(name)
        click.echo(f'Cluster {name} terminated.')


_CONTROLLER_CLUSTERS = ('skytpu-jobs-controller', 'skytpu-serve-controller')


def _select_clusters(clusters, select_all: bool, verb: str) -> List[str]:
    if select_all:
        # Control-plane clusters are excluded from --all (killing them
        # orphans managed jobs / serve state); name them explicitly to
        # act on them — same contract as the reference's `sky down -a`.
        names = [r['name'] for r in sky.status()
                 if r['name'] not in _CONTROLLER_CLUSTERS]
        if not names:
            click.echo('No existing clusters.')
            raise SystemExit(0)
        return names
    if not clusters:
        raise click.UsageError(f'Specify cluster(s) to {verb}, or --all.')
    return list(clusters)


@cli.command()
@click.argument('cluster')
@click.option('--idle-minutes', '-i', type=int, default=5,
              help='Idle minutes before autostop.')
@click.option('--down', is_flag=True,
              help='Tear down instead of stopping when idle.')
@click.option('--cancel', is_flag=True, help='Disable autostop.')
def autostop(cluster, idle_minutes, down, cancel):
    """Arm (or cancel) idle autostop on a cluster."""
    sky.autostop(cluster, -1 if cancel else idle_minutes, down=down)
    if cancel:
        click.echo(f'Autostop cancelled on {cluster}.')
    else:
        click.echo(f'{cluster}: autostop after {idle_minutes} idle '
                   f'minute(s) ({"down" if down else "stop"}).')


@cli.command()
@click.argument('cluster')
def queue(cluster):
    """Show a cluster's job queue."""
    jobs = sky.queue(cluster)
    if not jobs:
        click.echo(f'No jobs on {cluster}.')
        return
    rows = [[j['job_id'], j.get('name') or '-',
             _fmt_age(j.get('submitted_at')), j['status']]
            for j in jobs]
    click.echo(_fmt_table(rows, ['ID', 'NAME', 'SUBMITTED', 'STATUS']))


@cli.command()
@click.argument('cluster')
@click.argument('job_id', type=int)
@click.option('--no-follow', is_flag=True, help='Print and exit.')
def logs(cluster, job_id, no_follow):
    """Tail a job's logs."""
    sky.tail_logs(cluster, job_id, follow=not no_follow)


@cli.command()
@click.argument('cluster')
@click.argument('job_ids', nargs=-1, type=int)
@click.option('--all', '-a', 'cancel_all', is_flag=True)
@click.option('--yes', '-y', is_flag=True)
def cancel(cluster, job_ids, cancel_all, yes):
    """Cancel job(s) on a cluster."""
    if not cancel_all and not job_ids:
        raise click.UsageError('Specify job id(s) or --all.')
    _confirm(f'Cancelling {"ALL jobs" if cancel_all else str(job_ids)} on '
             f'{cluster}. Proceed?', yes)
    if cancel_all:
        sky.cancel(cluster, all=True)
    else:
        for jid in job_ids:
            sky.cancel(cluster, jid)
    click.echo('Cancelled.')


@cli.command(name='cost-report')
def cost_report():
    """Estimated cost per (live or historical) cluster."""
    report = sky.cost_report()
    if not report:
        click.echo('No clusters.')
        return
    rows = [[r['name'],
             f"{r.get('duration_hours', 0):.2f}h",
             f"${r.get('cost_per_hour', 0):.2f}",
             f"${r.get('total_cost', 0):.2f}"] for r in report]
    click.echo(_fmt_table(rows, ['NAME', 'DURATION', '$/HR (est.)',
                                 'TOTAL COST (est.)']))
    click.echo('Note: dollar amounts are ESTIMATES from the checked-in '
               'catalog\n(approximate list prices); billing truth lives '
               'with your cloud provider.')


@cli.command()
def check():
    """Probe cloud credentials; list enabled clouds."""
    from skypilot_tpu import check as check_lib
    enabled = check_lib.check()
    if enabled:
        click.echo('Enabled clouds: ' + ', '.join(enabled))
    else:
        click.echo('No clouds enabled.')


@cli.command(name='show-tpus')
@click.option('--cloud', default='gcp')
@click.option('--all', '-a', 'show_all', is_flag=True,
              help='Include GPU/CPU instance types.')
def show_tpus(cloud, show_all):
    """Browse the accelerator catalog (TPU-first ``sky show-gpus``)."""
    from skypilot_tpu.catalog import catalog
    entries = catalog.get_catalog(cloud)
    rows = []
    for e in entries:
        if not show_all and not e.is_tpu:
            continue
        rows.append([e.instance_type, e.accelerator_name or '-',
                     e.accelerator_count or '-', e.region,
                     f'${e.price:.2f}',
                     f'${e.spot_price:.2f}' if e.spot_price else '-'])
    if not rows:
        click.echo('No catalog entries.')
        return
    click.echo(_fmt_table(
        rows, ['INSTANCE', 'ACCELERATOR', 'COUNT', 'REGION', '$/HR',
               'SPOT $/HR']))


@cli.command()
@click.argument('entrypoint', type=click.Path(exists=True))
@click.option('--env', multiple=True, metavar='KEY=VALUE')
def optimize(entrypoint, env):
    """Print the optimizer's plan for a task YAML without launching."""
    task = _load_task(entrypoint, env)
    sky.launch(task, dryrun=True)


# --------------------------------------------------------------------- jobs
@cli.group()
def jobs():
    """Managed jobs: launch-with-recovery on preemptible capacity."""


@jobs.command(name='launch')
@click.argument('entrypoint', type=click.Path(exists=True))
@click.option('--name', '-n', default=None)
@click.option('--yes', '-y', is_flag=True)
@click.option('--env', multiple=True, metavar='KEY=VALUE')
def jobs_launch(entrypoint, name, yes, env):
    """Submit a managed job (controller monitors + recovers it)."""
    task = _load_task(entrypoint, env, name=name)
    _confirm('Submitting managed job. Proceed?', yes)
    job_id = sky.jobs.launch(task, name=name)
    click.echo(f'Managed job submitted (id: {job_id}).')


@jobs.command(name='queue')
def jobs_queue():
    """List managed jobs."""
    try:
        records = sky.jobs.queue()
    except exceptions.ClusterNotUpError:
        records = []                      # no controller -> no jobs yet
    if not records:
        click.echo('No managed jobs.')
        return
    rows = [[r['job_id'], r.get('name') or '-',
             _fmt_age(r.get('submitted_at')), r['status'],
             r.get('recovery_count', 0)] for r in records]
    click.echo(_fmt_table(rows, ['ID', 'NAME', 'SUBMITTED', 'STATUS',
                                 'RECOVERIES']))


@jobs.command(name='cancel')
@click.argument('job_id', type=int)
@click.option('--yes', '-y', is_flag=True)
def jobs_cancel(job_id, yes):
    """Cancel a managed job (tears its task cluster down)."""
    _confirm(f'Cancelling managed job {job_id}. Proceed?', yes)
    ok = sky.jobs.cancel(job_id)
    click.echo('Cancelled.' if ok else 'Job not found or already terminal.')


@jobs.command(name='logs')
@click.argument('job_id', type=int)
@click.option('--no-follow', is_flag=True)
def jobs_logs(job_id, no_follow):
    """Stream a managed job's controller log."""
    if no_follow:
        click.echo(sky.jobs.logs(job_id))
    else:
        sky.jobs.tail_logs(job_id, follow=True)


# -------------------------------------------------------------------- bench
@cli.group()
def bench():
    """Benchmark a task across candidate resources (``sky bench``)."""


@bench.command(name='launch')
@click.argument('entrypoint', type=click.Path(exists=True))
@click.option('--benchmark', '-b', 'bench_name', required=True)
@click.option('--candidate', 'candidates', multiple=True, required=True,
              metavar='YAML_DICT',
              help='Candidate resources as YAML, e.g. '
                   '"{cloud: gcp, tpu: v5e-8}" (repeatable).')
@click.option('--yes', '-y', is_flag=True)
@click.option('--env', multiple=True, metavar='KEY=VALUE')
def bench_launch(entrypoint, bench_name, candidates, yes, env):
    """Launch the task once per candidate resource."""
    import yaml as yaml_lib

    from skypilot_tpu import Resources
    from skypilot_tpu.benchmark import benchmark_utils
    task = _load_task(entrypoint, env)
    res = [Resources.from_yaml_config(yaml_lib.safe_load(c))
           for c in candidates]
    _confirm(f'Launching benchmark {bench_name!r} on {len(res)} '
             'candidate(s). Proceed?', yes)
    clusters = benchmark_utils.launch_benchmark(task, res, bench_name)
    click.echo(f'Benchmark {bench_name!r} launched on: '
               f'{", ".join(clusters)}')


@bench.command(name='show')
@click.argument('bench_name')
def bench_show(bench_name):
    """Show per-candidate status/duration/cost."""
    from skypilot_tpu.benchmark import benchmark_utils
    rows = benchmark_utils.summary(bench_name)
    table = [[r['cluster'], r['resources'], r['status'],
              f"{r['duration_s']:.1f}s" if r['duration_s'] else '-',
              f"${r['cost']:.4f}" if r['cost'] else '-'] for r in rows]
    click.echo(_fmt_table(table, ['CLUSTER', 'RESOURCES', 'STATUS',
                                  'DURATION', 'COST']))


@bench.command(name='down')
@click.argument('bench_name')
@click.option('--yes', '-y', is_flag=True)
def bench_down(bench_name, yes):
    """Tear down a benchmark's clusters."""
    from skypilot_tpu.benchmark import benchmark_utils
    _confirm(f'Tearing down benchmark {bench_name!r}. Proceed?', yes)
    benchmark_utils.teardown(bench_name)
    click.echo(f'Benchmark {bench_name!r} removed.')


@bench.command(name='list')
def bench_list():
    """List benchmarks."""
    from skypilot_tpu.benchmark import benchmark_utils
    names = benchmark_utils.list_benchmarks()
    click.echo('\n'.join(names) if names else 'No benchmarks.')


# -------------------------------------------------------------------- serve
@cli.group()
def serve():
    """Autoscaled serving: replicas behind a load balancer."""


@serve.command(name='up')
@click.argument('entrypoint', type=click.Path(exists=True))
@click.option('--service-name', '-n', default=None)
@click.option('--yes', '-y', is_flag=True)
@click.option('--env', multiple=True, metavar='KEY=VALUE')
def serve_up(entrypoint, service_name, yes, env):
    """Spin up a service from a task YAML with a ``service:`` section."""
    task = _load_task(entrypoint, env)
    _confirm(f'Starting service {service_name or task.name!r}. Proceed?',
             yes)
    result = sky.serve.up(task, service_name=service_name)
    click.echo(f"Service {result['name']!r} endpoint: {result['endpoint']}")


@serve.command(name='update')
@click.argument('entrypoint', type=click.Path(exists=True))
@click.option('--service-name', '-n', required=True)
@click.option('--yes', '-y', is_flag=True)
@click.option('--env', multiple=True, metavar='KEY=VALUE')
def serve_update(entrypoint, service_name, yes, env):
    """Blue-green update: new replicas launch with the new task; old
    ones drain once enough new replicas are ready."""
    task = _load_task(entrypoint, env)
    _confirm(f'Updating service {service_name!r}. Proceed?', yes)
    result = sky.serve.update(task, service_name)
    click.echo(f"Service {service_name!r} updating to "
               f"v{result['version']}.")


@serve.command(name='status')
@click.argument('service_names', nargs=-1)
def serve_status(service_names):
    """Show services and their replicas."""
    try:
        services = sky.serve.status(list(service_names) or None)
    except exceptions.ClusterNotUpError:
        services = []                     # no controller -> no services
    if not services:
        click.echo('No services.')
        return
    rows = [[s['name'], s['status'], s.get('version', 1),
             sum(1 for r in s['replicas'] if r['status'] == 'READY'),
             len(s['replicas']), s['endpoint']] for s in services]
    click.echo(_fmt_table(rows, ['NAME', 'STATUS', 'VERSION', 'READY',
                                 'REPLICAS', 'ENDPOINT']))
    for s in services:
        if not s['replicas']:
            continue
        click.echo(f"\nReplicas of {s['name']}:")
        rrows = [[r['replica_id'], r['cluster_name'], r['status'],
                  r.get('url') or '-'] for r in s['replicas']]
        click.echo(_fmt_table(rrows, ['ID', 'CLUSTER', 'STATUS', 'URL']))


@serve.command(name='down')
@click.argument('service_name')
@click.option('--purge', '-p', is_flag=True,
              help='Best-effort cleanup even if the controller is gone.')
@click.option('--yes', '-y', is_flag=True)
def serve_down(service_name, purge, yes):
    """Tear down a service and its replicas."""
    _confirm(f'Tearing down service {service_name!r}. Proceed?', yes)
    sky.serve.down(service_name, purge=purge)
    click.echo(f'Service {service_name!r} torn down.')


@serve.command(name='logs')
@click.argument('service_name')
@click.option('--no-follow', is_flag=True)
def serve_logs(service_name, no_follow):
    """Stream a service's controller/LB log."""
    sky.serve.tail_logs(service_name, follow=not no_follow)


@cli.command(name='model-server')
@click.option('--model', default='tiny',
              help='Preset config name (random weights).')
@click.option('--model-path', default=None,
              help='HF checkpoint dir (real weights + tokenizer).')
@click.option('--quantize', default=None,
              type=click.Choice(['int8', 'int4']),
              help='Weight quantization: int8 halves the decode '
                   'weight stream (KV cache follows via '
                   '--kv-cache-dtype auto); int4 packs two codes per '
                   'byte with fused dequant — half the streamed bytes '
                   'again on top of int8 (KV stays int8).')
@click.option('--tp', type=int, default=None,
              help='Tensor-parallel degree (shard weights + KV heads '
                   'over tp chips; ~linear decode TPOT win). Default: '
                   'SKYTPU_TP env, else 1.')
@click.option('--dp', type=int, default=None,
              help='Data-parallel degree (decode batch over chip '
                   'groups; aggregate tok/s). Default: SKYTPU_DP env, '
                   'else 1.')
@click.option('--kv-cache', default='paged',
              type=click.Choice(['slot', 'paged']),
              help='paged (default) = shared page pool with prefix '
                   'caching; slot = fixed per-slot reservations.')
@click.option('--kv-cache-dtype', default=None,
              type=click.Choice(['bf16', 'int8']),
              help='KV cache storage dtype; default follows --quantize. '
                   'int8 halves decode KV HBM traffic and ~doubles '
                   'paged pool token capacity.')
@click.option('--page-size', type=int, default=None,
              help='Paged-cache page granularity (tokens; auto).')
@click.option('--prefill-chunk-tokens', type=int, default=None,
              help='Chunked-prefill chunk width (0 = monolithic).')
@click.option('--decode-priority-ratio', type=float, default=None,
              help='Decode share of the interleaved token budget.')
@click.option('--decode-steps-per-call', type=int, default=None,
              help='Multi-step on-device decode: fuse EXACTLY this '
                   'many decode steps (with on-device sampling) into '
                   'each jitted call — per-step dispatch, readback '
                   'and sampling host-syncs amortize k x. Default: '
                   'adaptive horizon.')
@click.option('--prefill-w8a8', is_flag=True,
              help='int8 activations on the compute-bound prefill.')
@click.option('--speculate-k', type=int, default=0,
              help='Speculative decoding: propose up to K tokens per '
                   'verify step via prompt-lookup (n-gram) matching '
                   '(0 = off). Greedy outputs are identical to vanilla '
                   'decode; sampling keeps the output distribution.')
@click.option('--slo-tier-default', default='latency',
              type=click.Choice(['latency', 'throughput']),
              help='SLO tier for requests that declare none '
                   '(per-request: "slo_tier" body field or X-SLO-Tier '
                   'header). latency = interactive TTFT contract; '
                   'throughput = batch tokens/s contract.')
@click.option('--max-queue-tokens', type=int, default=None,
              help='Per-tier admission bound in work tokens; overflow '
                   'is shed with HTTP 429 + Retry-After instead of '
                   'queueing. Default: 2x KV pool token capacity.')
@click.option('--latency-admit-frac', type=float, default=0.7,
              help='Share of admitted work tokens reserved for the '
                   'latency tier while both tiers are backlogged.')
@click.option('--drain-deadline-s', type=float, default=30.0,
              help='Graceful-drain deadline: POST /drain stops '
                   'admission (retryable 503 + Retry-After) and lets '
                   'in-flight requests finish before teardown.')
@click.option('--step-watchdog-s', type=float, default=None,
              help='Wedge-watchdog deadline (seconds) on each engine '
                   'step: a step stuck longer flips /readiness to a '
                   'degraded 503 and fails in-flight requests over '
                   '(retryable). Default: SKYTPU_STEP_WATCHDOG_S env, '
                   'else 120; 0 disables.')
@click.option('--fault-spec', default=None,
              help='Deterministic fault-injection spec (JSON or '
                   '@/path; default SKYTPU_FAULT_SPEC env var).')
@click.option('--role', default=None,
              type=click.Choice(['colocated', 'prefill', 'decode']),
              help='Disaggregated-serving phase role: prefill workers '
                   'hand each finished prefill\'s KV (int8 stays int8 '
                   'on the wire) to a decode worker via POST '
                   '/kv/ingest and relay its token stream; decode '
                   'workers run high-batch decode without prefill '
                   'stalls. Default: SKYTPU_ROLE env, else colocated.')
@click.option('--handoff-targets', default=None,
              help='Comma-separated decode-worker base URLs a prefill '
                   'replica may hand off to when no router supplied '
                   'X-Handoff-Target (picked by live KV-pool '
                   'headroom). Default: SKYTPU_HANDOFF_TARGETS env.')
@click.option('--checkpoint-path', default=None,
              help='Local prefix-cache checkpoint file (default: '
                   'SKYTPU_KV_CHECKPOINT_PATH env). A drain/preemption '
                   'warning persists hot prefix chains here; a '
                   '(re)booting server warms its cache from the file '
                   'before declaring readiness.')
@click.option('--gang-rank', type=int, default=None,
              help='Multi-host gang rank (0 = leader: HTTP front end '
                   '+ scheduler; >0 = follower loop replaying the '
                   'leader\'s op log). Default: SKYTPU_RANK env.')
@click.option('--gang-world', type=int, default=None,
              help='Gang size (processes per replica; 1 = not a '
                   'gang). Default: SKYTPU_WORLD env.')
@click.option('--gang-coordinator', default=None,
              help='Rank 0\'s base URL (the gang bus; required on '
                   'nonzero ranks). Default: SKYTPU_COORDINATOR env.')
@click.option('--gang-id', default=None,
              help='Shared gang identity (the replica manager\'s unit '
                   'of drain/checkpoint/teardown). Default: '
                   'SKYTPU_GANG_ID env.')
@click.option('--max-batch', type=int, default=8)
@click.option('--max-seq', type=int, default=1024)
@click.option('--port', type=int, default=8081)
def model_server(model, model_path, quantize, tp, dp, kv_cache,
                 kv_cache_dtype, page_size, prefill_chunk_tokens,
                 decode_priority_ratio, decode_steps_per_call,
                 prefill_w8a8, speculate_k,
                 slo_tier_default, max_queue_tokens, latency_admit_frac,
                 drain_deadline_s, step_watchdog_s, fault_spec, role,
                 handoff_targets, checkpoint_path, gang_rank,
                 gang_world, gang_coordinator, gang_id, max_batch,
                 max_seq, port):
    """Run the in-tree replica model server on this host (the process
    a service task's ``run`` command starts on each replica; same
    knobs as ``python -m skypilot_tpu.serve.server``). With
    ``--gang-world N`` the replica is a gang of N processes: rank 0
    serves HTTP, nonzero ranks run follower loops and the whole gang
    launches, drains, checkpoints, and dies together."""
    if kv_cache != 'paged' and page_size is not None:
        raise click.UsageError(
            '--page-size only applies with --kv-cache paged')
    from skypilot_tpu.serve import gang as gang_lib
    gang_spec = gang_lib.GangSpec.from_env(
        rank=gang_rank, world=gang_world, coordinator=gang_coordinator,
        gang_id=gang_id)
    if gang_spec.is_gang and not gang_spec.is_leader:
        import argparse
        from skypilot_tpu.serve import server as server_lib
        click.echo(f'Gang follower rank {gang_spec.rank}/'
                   f'{gang_spec.world} -> {gang_spec.coordinator}')
        server_lib.run_follower(gang_spec, argparse.Namespace(
            model=model, model_path=model_path, quantize=quantize,
            tp=tp, dp=dp, kv_cache=kv_cache,
            kv_cache_dtype=kv_cache_dtype, page_size=page_size,
            prefill_w8a8=prefill_w8a8,
            prefill_chunk_tokens=prefill_chunk_tokens,
            decode_priority_ratio=decode_priority_ratio,
            decode_steps_per_call=decode_steps_per_call,
            speculate_k=speculate_k, fault_spec=fault_spec,
            max_batch=max_batch, max_seq=max_seq))
        return
    from skypilot_tpu.serve.server import ModelServer
    server = ModelServer(model, max_batch=max_batch, max_seq=max_seq,
                         port=port, model_path=model_path,
                         quantize=quantize, tp=tp, dp=dp,
                         kv_cache=kv_cache,
                         kv_cache_dtype=kv_cache_dtype,
                         page_size=page_size,
                         prefill_w8a8=prefill_w8a8,
                         prefill_chunk_tokens=prefill_chunk_tokens,
                         decode_priority_ratio=decode_priority_ratio,
                         decode_steps_per_call=decode_steps_per_call,
                         speculate_k=speculate_k,
                         slo_tier_default=slo_tier_default,
                         max_queue_tokens=max_queue_tokens,
                         latency_admit_frac=latency_admit_frac,
                         drain_deadline_s=drain_deadline_s,
                         fault_spec=fault_spec,
                         role=role,
                         handoff_targets=(handoff_targets.split(',')
                                          if handoff_targets else None),
                         checkpoint_path=checkpoint_path,
                         gang=gang_spec,
                         step_watchdog_s=step_watchdog_s)
    click.echo(f'Model server on :{port} '
               f'(kv_cache={kv_cache}, speculate_k={speculate_k}, '
               f'tp={server.tp}, dp={server.dp}, role={server.role}, '
               f'gang_world={server.gang.world})')
    server.start(block=True)


# --------------------------------------------------------------- storage
@cli.group()
def storage():
    """Managed storage buckets (reference ``sky storage``,
    ``sky/cli.py:3474``)."""


@storage.command(name='ls')
def storage_ls():
    """List managed storage objects."""
    from skypilot_tpu import global_state
    records = global_state.get_storage()
    if not records:
        click.echo('No existing storage.')
        return
    rows = []
    for r in records:
        h = r.get('handle') or {}
        rows.append([r['name'],
                     ','.join(h.get('stores', [])) or '-',
                     str(h.get('source') or '-'),
                     _fmt_age(r.get('launched_at')),
                     r['status'].value])
    click.echo(_fmt_table(rows, ['NAME', 'STORE', 'SOURCE', 'CREATED',
                                 'STATUS']))


@storage.command(name='delete')
@click.argument('names', nargs=-1)
@click.option('--all', '-a', 'delete_all', is_flag=True)
@click.option('--yes', '-y', is_flag=True)
def storage_delete(names, delete_all, yes):
    """Delete managed storage (bucket contents included)."""
    from skypilot_tpu import global_state
    from skypilot_tpu.data import storage as storage_lib
    records = global_state.get_storage()
    if delete_all:
        targets = [r['name'] for r in records]
    else:
        targets = list(names)
    if not targets:
        click.echo('No storage to delete.')
        return
    if not yes:
        click.confirm(f'Delete storage: {", ".join(targets)}?', abort=True)
    by_name = {r['name']: r for r in records}
    for name in targets:
        rec = by_name.get(name)
        if rec is None:
            click.echo(f'Storage {name!r} not found.')
            continue
        h = rec.get('handle') or {}
        stores = [storage_lib.StoreType.from_str(s)
                  for s in h.get('stores', [])] or None
        obj = storage_lib.Storage(name=name, source=h.get('source'),
                                  stores=stores)
        obj.delete()
        click.echo(f'Storage {name!r} deleted.')


# ------------------------------------------------------------ telemetry
@cli.group()
def telemetry():
    """Unified telemetry: metrics registry, request traces, profiler."""


@telemetry.command(name='dump')
@click.option('--url', default=None, metavar='http://HOST:PORT',
              help='Fetch a running server\'s /metrics instead of this '
                   'process\'s registry (model server, dashboard — any '
                   'endpoint speaking the telemetry exposition).')
@click.option('--format', 'fmt', default='prom',
              type=click.Choice(['prom', 'json']),
              help='Prometheus text exposition (default) or JSON.')
@click.option('--debug-requests', is_flag=True,
              help='With --url: dump /debug/requests (completed '
                   'request span timelines) instead of /metrics.')
@click.option('--fleet', 'fleet_view', is_flag=True,
              help='With --url (a controller): dump the aggregated '
                   'fleet plane (GET /fleet/metrics) instead of the '
                   'per-process /metrics.')
@click.option('--trace', 'trace_id', default=None, metavar='TRACE_ID',
              help='With --url (a controller): dump one assembled '
                   'cross-process trace (GET /fleet/trace/<id>); '
                   'combine with --chrome-trace PATH to write it as a '
                   'chrome://tracing file instead.')
@click.option('--chrome-trace', default=None, metavar='PATH',
              help='Also export this process\'s completed request '
                   'traces as a chrome://tracing file (or, with '
                   '--trace, the fetched fleet trace).')
def telemetry_dump(url, fmt, debug_requests, fleet_view, trace_id,
                   chrome_trace):
    """Dump telemetry: the local process registry, or a remote
    server's /metrics, /debug/requests, or a controller's fleet
    plane (/fleet/metrics, /fleet/trace/<id>)."""
    import urllib.request

    from skypilot_tpu import telemetry as telemetry_lib
    if debug_requests and not url:
        raise click.UsageError('--debug-requests requires --url')
    if (fleet_view or trace_id) and not url:
        raise click.UsageError('--fleet/--trace require --url '
                               '(a controller URL)')
    if url:
        base = url.rstrip('/')
        if trace_id:
            suffix = '?format=chrome' if chrome_trace else ''
            with urllib.request.urlopen(
                    f'{base}/fleet/trace/{trace_id}{suffix}',
                    timeout=10) as r:
                body = r.read().decode()
            if chrome_trace:
                with open(chrome_trace, 'w', encoding='utf-8') as f:
                    f.write(body)
                click.echo(f'chrome trace: {chrome_trace}')
            else:
                click.echo(body)
            return
        if debug_requests:
            path = '/debug/requests'
        elif fleet_view:
            path = ('/fleet/metrics?format=json' if fmt == 'json'
                    else '/fleet/metrics')
        elif fmt == 'json':
            path = '/metrics?format=json'
        else:
            path = '/metrics'
        with urllib.request.urlopen(base + path, timeout=10) as r:
            click.echo(r.read().decode())
        return
    reg = telemetry_lib.get_registry()
    if fmt == 'json':
        import json as json_lib
        click.echo(json_lib.dumps(reg.render_json(), indent=2))
    else:
        click.echo(reg.render_prometheus(), nl=False)
    if chrome_trace:
        out = telemetry_lib.export_chrome_trace(chrome_trace)
        click.echo(f'chrome trace: {out or "no completed traces"}')


# ---------------------------------------------------------------- fleet
@cli.group()
def fleet():
    """Fleet observability plane: aggregated metrics, SLO burn rates,
    and assembled cross-process request traces from a controller."""


def _fleet_get(url: str, path: str):
    import json as json_lib
    import urllib.request
    with urllib.request.urlopen(url.rstrip('/') + path,
                                timeout=10) as r:
        return json_lib.loads(r.read().decode())


_CONTROLLER_URL_OPT = click.option(
    '--url', required=True, metavar='http://HOST:PORT',
    help='Controller URL (the process serving /fleet/metrics).')


@fleet.command(name='top')
@_CONTROLLER_URL_OPT
def fleet_top(url):
    """Fleet at a glance: scraped sources, per-tier traffic and
    latency, SLO attainment and burn."""
    data = _fleet_get(url, '/fleet/metrics?format=json')

    def gauge(name, default=0.0):
        series = (data.get(name) or {}).get('series') or []
        return series[0].get('value', default) if series else default

    click.echo(f'sources   {int(gauge("skytpu_fleet_sources"))}')
    click.echo(f'scrapes   '
               f'{int(gauge("skytpu_fleet_scrapes_total"))}')
    click.echo(f'traces    {int(gauge("skytpu_fleet_traces"))}')
    rows = []
    for entry in (data.get('skytpu_request_ttft_ms') or {}) \
            .get('series') or []:
        tier = (entry.get('labels') or {}).get('tier', '-')
        count = int(entry.get('count', 0))
        mean = entry.get('sum', 0.0) / count if count else 0.0
        rows.append((tier, count, mean))
    if rows:
        click.echo(f'{"TIER":12s} {"REQUESTS":>10s} '
                   f'{"TTFT_MEAN_MS":>13s}')
        for tier, count, mean in sorted(rows):
            click.echo(f'{tier:12s} {count:10d} {mean:13.1f}')
    slo = data.get('_slo') or {}
    for tier, vals in sorted(slo.items()):
        burns = ' '.join(
            f'burn_{k.split("_", 1)[1]}={v:.2f}'
            for k, v in sorted(vals.items()) if k.startswith('burn_'))
        click.echo(f'slo {tier:12s} '
                   f'attainment={vals.get("attainment", 1.0):.4f} '
                   f'{burns}')


@fleet.command(name='slo')
@_CONTROLLER_URL_OPT
def fleet_slo(url):
    """Per-tier SLO burn rates and attainment, as JSON."""
    import json as json_lib
    data = _fleet_get(url, '/fleet/metrics?format=json')
    click.echo(json_lib.dumps(data.get('_slo') or {}, indent=2))


@fleet.command(name='trace')
@_CONTROLLER_URL_OPT
@click.argument('trace_id', required=False)
@click.option('--chrome', default=None, metavar='PATH',
              help='Write the assembled trace as a chrome://tracing '
                   'file instead of printing JSON.')
def fleet_trace(url, trace_id, chrome):
    """Show one assembled multi-process trace (or, with no TRACE_ID,
    list the ids the controller holds)."""
    import json as json_lib
    if not trace_id:
        data = _fleet_get(url, '/fleet/traces')
        for tid in data.get('traces') or []:
            click.echo(tid)
        return
    suffix = '?format=chrome' if chrome else ''
    try:
        data = _fleet_get(url, f'/fleet/trace/{trace_id}{suffix}')
    except Exception as e:  # urllib HTTPError on unknown id
        raise click.ClickException(
            f'trace {trace_id!r} not found at {url}: {e}')
    if chrome:
        with open(chrome, 'w', encoding='utf-8') as f:
            json_lib.dump(data, f)
        click.echo(f'chrome trace: {chrome}')
        return
    click.echo(json_lib.dumps(data, indent=2))


# ------------------------------------------------------------------- lb
@cli.command(name='lb')
@click.option('--controller-url', required=True, metavar='URL',
              help='Controller to sync the replica set (and the LB '
                   'peer ring) from.')
@click.option('--port', required=True, type=int,
              help='Port this LB listens on.')
@click.option('--policy', default='prefix_affinity',
              type=click.Choice(['round_robin', 'least_load',
                                 'queue_depth', 'phase_aware',
                                 'prefix_affinity']),
              help='Load-balancing policy for this LB process.')
@click.option('--lb-id', default=None, metavar='NAME',
              help='Stable identity in the consistent-hash ring '
                   '(default: SKYTPU_LB_ID env or a random id).')
@click.option('--advertise-url', default=None, metavar='URL',
              help='URL peer LBs reach this LB at for idempotency-key '
                   'handoff (default: http://127.0.0.1:<port>).')
def lb(controller_url, port, policy, lb_id, advertise_url):
    """Run one load balancer of a horizontal LB tier.

    Every LB started against the same controller registers on the
    sync feed and joins the consistent-hash ring: session/idempotency
    keys get exactly one owner, affinity survives any single LB
    crash, and a replayed request answered via one LB is deduped at
    every other (docs/serving.md "A horizontal LB tier").
    """
    import signal
    import threading

    from skypilot_tpu.serve import load_balancer as lb_lib
    balancer = lb_lib.SkyServeLoadBalancer(
        controller_url=controller_url, port=port, policy_name=policy,
        lb_id=lb_id, advertise_url=advertise_url)
    balancer.start()
    click.echo(f'LB {balancer.lb_id} serving on port {port} '
               f'(policy {policy}); Ctrl-C to stop.')
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    try:
        stop.wait()
    except KeyboardInterrupt:
        pass
    balancer.stop()


# ------------------------------------------------------------------ sim
@cli.command()
@click.option('--scenario', '-s', default='smoke', metavar='NAME',
              help='Chaos scenario to run (see --list).')
@click.option('--seed', default=0, type=int,
              help='Determinism seed: same seed, byte-identical event '
                   'log (the report carries its SHA-256).')
@click.option('--policy', default=None,
              type=click.Choice(['round_robin', 'least_load',
                                 'queue_depth', 'phase_aware',
                                 'prefix_affinity']),
              help='Override the scenario\'s LB policy (the REAL '
                   'policy object routes every simulated request).')
@click.option('--list', 'list_scenarios', is_flag=True,
              help='List the scenario library and exit.')
@click.option('--event-log', default=None, metavar='PATH',
              help='Also write the full event log to PATH (lines of '
                   '"<t>|<kind>|<detail>"; its SHA-256 is the '
                   'determinism fingerprint in the report).')
def sim(scenario, seed, policy, list_scenarios, event_log):
    """Fleet-scale control-plane simulation: drive the REAL
    autoscaler/forecaster/placement/LB-policy/drain machinery through
    failure storms at up to 1000 simulated replicas and millions of
    requests in seconds of wall time (docs/simulation.md).

    Prints the scenario report as JSON: SLO attainment per tier, shed/
    lost/migrated counts (lost MUST be 0 in recovery-covered
    scenarios), recovery p50/p90, chip-seconds, and the event-log
    SHA-256 (same seed => byte-identical log).
    """
    import json as json_lib
    import logging as logging_lib

    from skypilot_tpu.serve.sim import scenarios as sim_scenarios
    # The control plane narrates every launch/drain/READY at INFO — a
    # 1000-replica storm would drown the JSON report (and corrupt
    # stdout for pipelines). Warnings still surface.
    logging_lib.getLogger('skytpu').setLevel(logging_lib.ERROR)
    if list_scenarios:
        for name in sorted(sim_scenarios.SCENARIOS):
            scn = sim_scenarios.SCENARIOS[name]
            click.echo(f'{name:22s} {scn.description}')
        return
    try:
        scn = sim_scenarios.get_scenario(scenario)
    except ValueError as e:
        raise click.UsageError(str(e))
    keep = {'keep_log': True} if event_log and scn.runner is None \
        else {}
    if scn.runner is None:
        fleet = scn.build(seed=seed, policy=policy, **keep)
        report = fleet.run()
        report['scenario'] = scn.name
        report['recovery_covered'] = scn.recovery_covered
        if event_log:
            with open(event_log, 'w', encoding='utf-8') as f:
                f.write(fleet.event_log())
            report['event_log_path'] = event_log
    else:
        report = scn.run(seed=seed, policy=policy)
        if event_log:
            raise click.UsageError(
                '--event-log is not supported for comparison '
                f'scenarios ({scenario})')
    click.echo(json_lib.dumps(report, indent=2))
    if report.get('recovery_covered') and \
            report['requests'].get('lost', 0) > 0:
        raise SystemExit(
            f'LOST {report["requests"]["lost"]} request(s) in a '
            'recovery-covered scenario — the zero-lost contract is '
            'broken')


@cli.command()
@click.option('--port', default=8500, help='Port to serve the dashboard.')
@click.option('--no-browser', is_flag=True, hidden=True)
def dashboard(port, no_browser):
    """Serve the live jobs/serve/cluster dashboard
    (reference ``sky/jobs/dashboard/``)."""
    del no_browser
    from skypilot_tpu import dashboard as dash
    click.echo(f'Dashboard: http://127.0.0.1:{port} (Ctrl-C to stop)')
    dash.serve_forever(port)


def main() -> None:
    import sys

    from skypilot_tpu.usage import usage_lib
    usage_lib.record('cli', argv=sys.argv[1:2])   # command name only
    try:
        cli(standalone_mode=True)
    except exceptions.SkyTpuError as e:       # pragma: no cover - passthru
        raise SystemExit(f'Error: {e}')


if __name__ == '__main__':
    main()
