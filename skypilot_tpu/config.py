"""Layered user config (role of reference ``sky/skypilot_config.py:84``).

Optional ``~/.skytpu/config.yaml`` (override path via ``SKYTPU_CONFIG``),
jsonschema-validated, read through dotted-path ``get_nested``. Infra knobs
live here (controller resources, gcp project/network, autostop defaults),
never in task YAML.
"""
from __future__ import annotations

import copy
import os
import threading
from typing import Any, Dict, Iterable, Optional, Tuple

import yaml

from skypilot_tpu import tpu_logging
from skypilot_tpu.utils import schemas

logger = tpu_logging.init_logger(__name__)

ENV_VAR = 'SKYTPU_CONFIG'
_DEFAULT_PATH = '~/.skytpu/config.yaml'

_lock = threading.Lock()
_config: Optional[Dict[str, Any]] = None
_loaded_path: Optional[str] = None


def _config_path() -> str:
    return os.path.expanduser(os.environ.get(ENV_VAR, _DEFAULT_PATH))


def _load() -> Dict[str, Any]:
    global _config, _loaded_path
    path = _config_path()
    with _lock:
        if _config is not None and _loaded_path == path:
            return _config
        config: Dict[str, Any] = {}
        if os.path.exists(path):
            with open(path, encoding='utf-8') as f:
                loaded = yaml.safe_load(f)
            if loaded:
                schemas.validate(loaded, schemas.CONFIG_SCHEMA,
                                 f'config file {path}: ')
                config = loaded
        _config = config
        _loaded_path = path
        return _config


def loaded() -> bool:
    return bool(_load())


def get_nested(keys: Iterable[str], default_value: Any = None) -> Any:
    """config.get_nested(('gcp', 'project_id'), None)"""
    cur: Any = _load()
    for key in keys:
        if not isinstance(cur, dict) or key not in cur:
            return default_value
        cur = cur[key]
    return copy.deepcopy(cur)


def set_nested(keys: Tuple[str, ...], value: Any) -> Dict[str, Any]:
    """Return a copy of the config with keys set (does not persist)."""
    config = copy.deepcopy(_load())
    cur = config
    for key in keys[:-1]:
        cur = cur.setdefault(key, {})
    cur[keys[-1]] = value
    return config


def to_dict() -> Dict[str, Any]:
    return copy.deepcopy(_load())


def reload() -> None:
    """Drop the cache (tests point SKYTPU_CONFIG at a new file)."""
    global _config, _loaded_path
    with _lock:
        _config = None
        _loaded_path = None
