"""Benchmark subsystem: launch one task across candidate resources and
compare duration/cost.

Role of reference ``sky/benchmark/benchmark_utils.py`` + ``sky bench``:
fan the same task out to N single-candidate clusters, then aggregate
per-candidate wall time, price, and (when the task wrote one via the
callbacks' TimerCallback) steps/sec into a comparison table. State is a
JSON record per benchmark under ``{state_dir}/benchmarks/``.
"""
from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import tpu_logging
from skypilot_tpu.resources import Resources
from skypilot_tpu.task import Task
from skypilot_tpu.utils import common_utils

logger = tpu_logging.init_logger(__name__)


def _bench_dir() -> str:
    d = os.path.join(common_utils.state_dir(), 'benchmarks')
    os.makedirs(d, exist_ok=True)
    return d


def _bench_path(name: str) -> str:
    return os.path.join(_bench_dir(), f'{name}.json')


def _save(name: str, record: Dict[str, Any]) -> None:
    with open(_bench_path(name), 'w', encoding='utf-8') as f:
        json.dump(record, f, indent=1)


def get_benchmark(name: str) -> Optional[Dict[str, Any]]:
    try:
        with open(_bench_path(name), encoding='utf-8') as f:
            return json.load(f)
    except FileNotFoundError:
        return None


def list_benchmarks() -> List[str]:
    return sorted(p[:-5] for p in os.listdir(_bench_dir())
                  if p.endswith('.json'))


def launch_benchmark(task: Task, candidates: List[Resources],
                     name: str) -> List[str]:
    """Launch ``task`` once per candidate resource on clusters
    ``{name}-{i}``; returns the cluster names. Clusters stay up until
    ``teardown`` so logs/artifacts can be inspected."""
    from skypilot_tpu import execution
    if get_benchmark(name) is not None:
        raise ValueError(f'Benchmark {name!r} already exists; tear it '
                         'down first.')
    # The record is persisted BEFORE the first launch and re-saved after
    # each one: a mid-loop launch failure must leave already-provisioned
    # clusters discoverable by `bench show`/`bench down`, not orphaned.
    record = {'name': name, 'task_name': task.name, 'entries': [],
              'created_at': time.time()}
    _save(name, record)
    clusters = []
    for i, res in enumerate(candidates):
        cluster = f'{name}-{i}'
        bench_task = Task.from_yaml_config(task.to_yaml_config())
        bench_task.set_resources(res)
        try:
            job_id, _ = execution.launch(bench_task, cluster_name=cluster,
                                         detach_run=True,
                                         stream_logs=False)
        except Exception:
            logger.warning(
                f'Benchmark candidate {i} ({res}) failed to launch; '
                f'{len(clusters)} earlier candidate(s) remain up — '
                f'inspect with `bench show {name}`, clean up with '
                f'`bench down {name}`.')
            raise
        record['entries'].append({
            'cluster': cluster,
            'resources': str(res),
            'job_id': job_id,
            'launched_at': time.time(),
        })
        _save(name, record)
        clusters.append(cluster)
    return clusters


def summary(name: str) -> List[Dict[str, Any]]:
    """Per-candidate status/duration/cost rows (reference
    ``sky bench show``)."""
    from skypilot_tpu import core
    record = get_benchmark(name)
    if record is None:
        raise ValueError(f'No benchmark named {name!r}.')
    try:
        report = {r['name']: r for r in core.cost_report()}
    except Exception:  # pylint: disable=broad-except
        report = {}
    rows = []
    for entry in record['entries']:
        row = dict(entry)
        row.update(status='UNKNOWN', duration_s=None, cost=None)
        try:
            jobs = core.queue(entry['cluster'])
            job = next(j for j in jobs if j['job_id'] == entry['job_id'])
            row['status'] = job['status']
            start, end = job.get('start_at'), job.get('end_at')
            if start:
                row['duration_s'] = round((end or time.time()) - start, 2)
        except Exception as e:  # pylint: disable=broad-except
            row['status'] = f'UNREACHABLE ({type(e).__name__})'
        if entry['cluster'] in report:
            row['cost'] = round(report[entry['cluster']]['total_cost'], 4)
        rows.append(row)
    return rows


def teardown(name: str) -> None:
    from skypilot_tpu import core
    record = get_benchmark(name)
    if record is None:
        return
    for entry in record['entries']:
        try:
            core.down(entry['cluster'])
        except Exception as e:  # pylint: disable=broad-except
            logger.warning(f'bench teardown of {entry["cluster"]} failed: '
                           f'{e}')
    os.remove(_bench_path(name))
