"""Benchmark subsystem: fan a task out over candidate resources and
compare duration/cost (reference ``sky/benchmark/``)."""
from skypilot_tpu.benchmark.benchmark_utils import (get_benchmark,
                                                    launch_benchmark,
                                                    list_benchmarks, summary,
                                                    teardown)

__all__ = ['get_benchmark', 'launch_benchmark', 'list_benchmarks',
           'summary', 'teardown']
