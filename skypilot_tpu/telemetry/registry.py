"""Process-wide, thread-safe metrics registry.

One registry per process (``get_registry()``); every subsystem —
model server, engines, dashboard, load balancer, replica manager,
jobs layer — registers its series here and the scrape endpoints render
the whole registry, so no component assembles a private metrics dict
(the pre-telemetry ``/metrics`` duplication between ``serve/server.py``
and ``dashboard.py``).

Three metric types:

- :class:`Counter` — monotonically increasing (requests served,
  probe failures).
- :class:`Gauge` — set-to-current-value (queue depth, active slots).
- :class:`Histogram` — fixed cumulative buckets (Prometheus
  exposition) PLUS a bounded window of raw observations for exact
  rolling quantiles. The window is THE windowed-quantile
  implementation: TTFT, TPOT and queue-wait median/p90 all read from
  it (one implementation, not three ad-hoc deques), and it is bounded
  so a long-lived replica's quantiles reflect current traffic.

Series identity is ``(name, sorted(labels))``; re-registering an
existing series returns the same object (handles are cheap to look up
in hot-ish paths). Rendering:

- :meth:`MetricsRegistry.render_prometheus` — text exposition format
  0.0.4 (``# HELP`` / ``# TYPE`` once per family, ``_bucket``/``_sum``/
  ``_count`` for histograms, cumulative ``le`` buckets ending in
  ``+Inf``). Every registered series renders, zeros included — the
  stable-schema guarantee scrapers rely on.
- :meth:`MetricsRegistry.render_json` — the same data as nested JSON
  (the dashboard and ``/metrics?format=json`` compat surface).
"""
from __future__ import annotations

import collections
import math
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

# Default buckets: millisecond-scale latencies (TTFT/TPOT/queue-wait).
DEFAULT_MS_BUCKETS: Tuple[float, ...] = (
    1, 2.5, 5, 10, 25, 50, 100, 250, 500, 1000, 2500, 5000, 10000,
    30000, 60000)
# Second-scale durations (engine step phases, jit first calls).
DEFAULT_SECONDS_BUCKETS: Tuple[float, ...] = (
    .0005, .001, .0025, .005, .01, .025, .05, .1, .25, .5, 1, 2.5, 5,
    10, 30, 60)
DEFAULT_WINDOW = 512


def _fmt(v: float) -> str:
    """Prometheus sample value: integral floats print as ints."""
    if v == math.inf:
        return '+Inf'
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    return repr(float(v)) if isinstance(v, float) else str(v)


def _label_str(labels: Dict[str, str]) -> str:
    if not labels:
        return ''
    inner = ','.join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return '{' + inner + '}'


class _Metric:
    kind = 'untyped'

    def __init__(self, name: str, help_text: str,
                 labels: Dict[str, str]):
        self.name = name
        self.help = help_text
        self.labels = dict(labels)
        self._lock = threading.Lock()


class Counter(_Metric):
    kind = 'counter'

    def __init__(self, name, help_text, labels):
        super().__init__(name, help_text, labels)
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f'counter {self.name} cannot decrease')
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge(_Metric):
    kind = 'gauge'

    def __init__(self, name, help_text, labels):
        super().__init__(name, help_text, labels)
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram(_Metric):
    """Fixed cumulative buckets + bounded raw-observation window.

    The buckets serve Prometheus (aggregatable across replicas); the
    window serves exact in-process rolling quantiles
    (:meth:`quantile`) — the one windowed-quantile implementation the
    serve layer uses for TTFT, TPOT, and queue-wait."""
    kind = 'histogram'

    def __init__(self, name, help_text, labels,
                 buckets: Sequence[float] = DEFAULT_MS_BUCKETS,
                 window: int = DEFAULT_WINDOW):
        super().__init__(name, help_text, labels)
        uppers = sorted(float(b) for b in buckets)
        if not uppers:
            raise ValueError('histogram needs at least one bucket')
        self.buckets: Tuple[float, ...] = tuple(uppers)
        self._counts = [0] * (len(uppers) + 1)   # +1 = +Inf
        self._sum = 0.0
        self._count = 0
        self._window: 'collections.deque[float]' = collections.deque(
            maxlen=max(1, int(window)))

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self._sum += v
            self._count += 1
            self._window.append(v)
            for i, upper in enumerate(self.buckets):
                if v <= upper:
                    self._counts[i] += 1
                    return
            self._counts[-1] += 1

    def snapshot(self) -> Dict[str, Any]:
        """(cumulative bucket counts, sum, count, window copy) under one
        lock acquisition — rendering must not tear mid-observe."""
        with self._lock:
            cum, acc = [], 0
            for c in self._counts:
                acc += c
                cum.append(acc)
            return {'cumulative': cum, 'sum': self._sum,
                    'count': self._count,
                    'window': list(self._window)}

    def merge_cumulative(self, cumulative: Sequence[int], sum_: float,
                         count: int) -> None:
        """Merge another histogram's cumulative snapshot (SAME bucket
        bounds) in: exact elementwise addition of the de-cumulated
        counts; sum and count add. The raw-observation window is NOT
        merged — fleet-level quantiles read from the merged buckets
        (:func:`skypilot_tpu.telemetry.fleet.bucket_quantile`)."""
        if len(cumulative) != len(self._counts):
            raise ValueError(
                f'{self.name}: cannot merge {len(cumulative)} '
                f'cumulative buckets into {len(self._counts)}')
        with self._lock:
            prev = 0
            for i, cum in enumerate(cumulative):
                self._counts[i] += cum - prev
                prev = cum
            self._sum += float(sum_)
            self._count += int(count)

    def quantile(self, q: float) -> float:
        """Exact quantile over the bounded rolling window (0 when
        empty) — zeros-not-omitted, like every other gauge."""
        with self._lock:
            window = sorted(self._window)
        if not window:
            return 0.0
        idx = min(len(window) - 1, int(q * len(window)))
        return window[idx]

    @property
    def window_len(self) -> int:
        with self._lock:
            return len(self._window)

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum


class MetricsRegistry:
    """Thread-safe collection of metric series, keyed by
    ``(name, labels)``. ``counter``/``gauge``/``histogram`` are
    get-or-create: safe to call from multiple subsystems for the same
    series (they share the object)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._series: Dict[Tuple[str, Tuple[Tuple[str, str], ...]],
                           _Metric] = {}
        self._families: Dict[str, Tuple[str, str]] = {}  # name->(kind,help)

    # ------------------------------------------------------------ create
    def _get_or_create(self, cls, name: str, help_text: str,
                       labels: Dict[str, str], **kwargs) -> _Metric:
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            metric = self._series.get(key)
            if metric is not None:
                if not isinstance(metric, cls):
                    raise TypeError(
                        f'{name} already registered as {metric.kind}')
                return metric
            fam = self._families.get(name)
            if fam is not None and fam[0] != cls.kind:
                raise TypeError(
                    f'{name} already registered as a {fam[0]} family')
            metric = cls(name, help_text, labels, **kwargs)
            self._series[key] = metric
            if fam is None or (not fam[1] and help_text):
                self._families[name] = (cls.kind, help_text)
            return metric

    def counter(self, name: str, help_text: str = '',
                **labels: str) -> Counter:
        return self._get_or_create(Counter, name, help_text, labels)

    def gauge(self, name: str, help_text: str = '',
              **labels: str) -> Gauge:
        return self._get_or_create(Gauge, name, help_text, labels)

    def histogram(self, name: str, help_text: str = '',
                  buckets: Sequence[float] = DEFAULT_MS_BUCKETS,
                  window: int = DEFAULT_WINDOW,
                  **labels: str) -> Histogram:
        return self._get_or_create(Histogram, name, help_text, labels,
                                   buckets=buckets, window=window)

    # ------------------------------------------------------------ access
    def families(self) -> Dict[str, List[_Metric]]:
        """name -> series, names sorted, series sorted by labels."""
        with self._lock:
            series = list(self._series.items())
        out: Dict[str, List[_Metric]] = {}
        for (name, _), metric in sorted(series, key=lambda kv: kv[0]):
            out.setdefault(name, []).append(metric)
        return out

    def get(self, name: str, **labels: str) -> Optional[_Metric]:
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            return self._series.get(key)

    # ------------------------------------------------------------ render
    def render_prometheus(self) -> str:
        """Text exposition format 0.0.4. Every registered series is
        emitted, zeros included — the stable-schema contract."""
        lines: List[str] = []
        for name, series in self.families().items():
            kind, help_text = self._families.get(name, ('untyped', ''))
            if help_text:
                lines.append(f'# HELP {name} {help_text}')
            lines.append(f'# TYPE {name} {kind}')
            for m in series:
                if isinstance(m, Histogram):
                    snap = m.snapshot()
                    for upper, cum in zip(
                            list(m.buckets) + [math.inf],
                            snap['cumulative']):
                        labels = dict(m.labels)
                        labels['le'] = _fmt(upper)
                        lines.append(f'{name}_bucket'
                                     f'{_label_str(labels)} {cum}')
                    ls = _label_str(m.labels)
                    lines.append(f'{name}_sum{ls} '
                                 f'{_fmt(snap["sum"])}')
                    lines.append(f'{name}_count{ls} {snap["count"]}')
                else:
                    lines.append(f'{name}{_label_str(m.labels)} '
                                 f'{_fmt(m.value)}')
        return '\n'.join(lines) + '\n'

    def export_wire(self) -> Dict[str, Any]:
        """Merge-ready snapshot for the fleet aggregation plane: every
        series with its kind, labels and raw values — histograms carry
        their EXACT bucket bounds plus cumulative counts (unlike
        :meth:`render_json`, which pre-digests quantiles), so the
        controller-side merge is exact elementwise addition, not an
        approximation. Shape::

            {name: {'kind': ..., 'help': ...,
                    'series': [{'labels': {...},
                                'value': v}                  # counter/gauge
                               {'labels': {...},            # histogram
                                'buckets': [...uppers...],
                                'cumulative': [...],         # +Inf last
                                'sum': s, 'count': n}]}}
        """
        out: Dict[str, Any] = {}
        for name, series in self.families().items():
            kind, help_text = self._families.get(name, ('untyped', ''))
            entries = []
            for m in series:
                entry: Dict[str, Any] = {'labels': dict(m.labels)}
                if isinstance(m, Histogram):
                    snap = m.snapshot()
                    entry.update(buckets=list(m.buckets),
                                 cumulative=snap['cumulative'],
                                 sum=snap['sum'],
                                 count=snap['count'])
                else:
                    entry['value'] = m.value
                entries.append(entry)
            out[name] = {'kind': kind, 'help': help_text,
                         'series': entries}
        return out

    def render_json(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for name, series in self.families().items():
            kind, help_text = self._families.get(name, ('untyped', ''))
            entries = []
            for m in series:
                entry: Dict[str, Any] = {'labels': dict(m.labels)}
                if isinstance(m, Histogram):
                    snap = m.snapshot()
                    entry.update(
                        count=snap['count'], sum=snap['sum'],
                        p50=m.quantile(0.5), p90=m.quantile(0.9),
                        p99=m.quantile(0.99),
                        window=len(snap['window']))
                else:
                    entry['value'] = m.value
                entries.append(entry)
            out[name] = {'type': kind, 'help': help_text,
                         'series': entries}
        return out


_global_lock = threading.Lock()
_global_registry: Optional[MetricsRegistry] = None


def get_registry() -> MetricsRegistry:
    """THE process-wide registry (created on first use)."""
    global _global_registry
    with _global_lock:
        if _global_registry is None:
            _global_registry = MetricsRegistry()
        return _global_registry


def reset_registry() -> MetricsRegistry:
    """Swap in a fresh process registry (tests)."""
    global _global_registry
    with _global_lock:
        _global_registry = MetricsRegistry()
        return _global_registry
