"""Controller-side fleet telemetry aggregation.

The per-process registry (:mod:`skypilot_tpu.telemetry.registry`) and
trace buffer (:mod:`skypilot_tpu.telemetry.tracing`) answer "what is
THIS replica doing"; :class:`FleetAggregator` answers the fleet-level
questions SLO-aware orchestration needs ("what is the latency tier's
TTFT p90 across all replicas right now?", "where did request X's
latency go across its LB -> prefill -> handoff -> decode -> migration
odyssey?"). It lives on the controller and is fed on the existing
sync/probe path:

- replicas expose ``GET /telemetry/summary`` (registry wire export +
  completed-trace summaries behind a cursor + their wall clock); the
  replica manager scrapes it right after each successful readiness
  probe and hands the payload here,
- LBs piggyback their own completed trace legs (dispatch/migration
  spans) on the ``/controller/load_balancer_sync`` body.

Aggregation semantics (the exactness contract tests pin down):

- **counters** sum across replicas, with per-(source, series)
  high-water marks for reset detection — a rebooted replica's counter
  restarting at 0 adds its pre-reboot total as a base instead of
  subtracting from the fleet sum,
- **histograms** with identical bucket bounds merge EXACTLY
  (elementwise addition of de-cumulated bucket counts, sums and
  counts add); quantiles from the merged buckets are within one
  bucket width of pooled-sample truth,
- **gauges** are not summable in general — each keeps its source as a
  ``replica`` label.

Clock skew: every scrape records ``offset = controller_now -
replica_wall`` and trace assembly applies the per-source offset to
every span, so a multi-process odyssey renders in causal order even
when replica clocks disagree.

SLO burn rates: the service spec's ``slos:`` block declares per-tier
TTFT/TPOT/shed-rate objectives; the aggregator samples per-tier fleet
totals into a bounded time-series ring on every ingest and evaluates
multi-window (5 min / 1 h) burn rates — ``burn = bad_fraction /
(1 - target)``, so burn > 1 means the error budget is being spent
faster than sustainable. Exposed as
``skytpu_slo_burn_rate{tier,window}`` + ``skytpu_slo_attainment{tier}``
and in :meth:`FleetAggregator.slo_status` (controller status + LB
sync).

Everything is driven through the controller's ``ControlPlaneEnv``
clock, so the simulator runs the identical code on the virtual clock
(deterministic same-seed reports) and memory stays bounded at
1000-replica scale (bounded rings, bounded trace store, capped
per-source series).
"""
from __future__ import annotations

import bisect
import collections
import dataclasses
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

from skypilot_tpu.telemetry import registry as registry_lib

# Burn-rate windows (seconds -> exposition label). Multi-window per
# Google SRE practice: the short window catches an active burst, the
# long window filters one-off blips.
BURN_WINDOWS: Tuple[Tuple[float, str], ...] = ((300.0, '5m'),
                                               (3600.0, '1h'))

# Bounded-memory caps (1000-replica sims must not grow unboundedly).
DEFAULT_RING_POINTS = 1024        # covers 1h+ at a 5s sync cadence
DEFAULT_TRACE_CAPACITY = 512      # assembled-trace store (fleet-wide)
MAX_SOURCES = 4096                # scraped processes tracked
MAX_SERIES_PER_SOURCE = 1024      # per-process series kept for merging
MAX_LEGS_PER_TRACE = 64

# The metric names the SLO evaluator reads (the scheduler emits these
# on live replicas; SimReplica emits the same names so the identical
# aggregator code runs in the simulator).
TTFT_METRIC = 'skytpu_request_ttft_ms'
TPOT_METRIC = 'skytpu_request_tpot_ms'
SHED_METRIC = 'skytpu_sched_shed_total'
ADMIT_METRIC = 'skytpu_sched_admitted_total'


@dataclasses.dataclass
class TierSLO:
    """One tier's objectives from the service spec ``slos:`` block."""
    tier: str
    ttft_ms: Optional[float] = None
    tpot_ms: Optional[float] = None
    shed_rate: Optional[float] = None     # max tolerated shed fraction
    target: float = 0.99                  # attainment objective

    @property
    def error_budget(self) -> float:
        return max(1e-9, 1.0 - self.target)


def slos_from_config(config: Optional[Dict[str, Any]]) -> List[TierSLO]:
    """Parse the validated ``slos:`` spec block into :class:`TierSLO`
    rows (sorted by tier name — iteration order is part of the
    determinism contract)."""
    out: List[TierSLO] = []
    for tier in sorted(config or {}):
        obj = config[tier] or {}
        out.append(TierSLO(
            tier=tier,
            ttft_ms=obj.get('ttft_ms'),
            tpot_ms=obj.get('tpot_ms'),
            shed_rate=obj.get('shed_rate'),
            target=float(obj.get('target', 0.99))))
    return out


def bucket_quantile(buckets: List[float], cumulative: List[int],
                    q: float) -> float:
    """Quantile estimated from cumulative fixed buckets (linear
    interpolation inside the landing bucket) — within one bucket width
    of the pooled-sample truth, which is the best any
    bucket-aggregated store can promise."""
    total = cumulative[-1] if cumulative else 0
    if total <= 0:
        return 0.0
    target = q * total
    prev_cum = 0
    prev_upper = 0.0
    for upper, cum in zip(buckets, cumulative):
        if cum >= target:
            span = cum - prev_cum
            frac = (target - prev_cum) / span if span else 1.0
            return prev_upper + (upper - prev_upper) * frac
        prev_cum = cum
        prev_upper = upper
    return buckets[-1] if buckets else 0.0


def _series_key(labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted(labels.items()))


class _CounterState:
    """Reset-proof counter accumulation for one (source, series):
    ``base`` carries totals from before the last observed reset."""
    __slots__ = ('base', 'last')

    def __init__(self) -> None:
        self.base = 0.0
        self.last = 0.0

    def update(self, value: float) -> None:
        if value < self.last:        # the source process restarted
            self.base += self.last
        self.last = value

    @property
    def total(self) -> float:
        return self.base + self.last


class _HistogramState:
    """Reset-proof histogram accumulation for one (source, series)."""
    __slots__ = ('buckets', 'base_cum', 'base_sum', 'base_count',
                 'last_cum', 'last_sum', 'last_count')

    def __init__(self, buckets: List[float]) -> None:
        self.buckets = list(buckets)
        n = len(buckets) + 1
        self.base_cum = [0] * n
        self.base_sum = 0.0
        self.base_count = 0
        self.last_cum = [0] * n
        self.last_sum = 0.0
        self.last_count = 0

    def update(self, cumulative: List[int], sum_: float,
               count: int) -> bool:
        """Returns False (no merge) on a bucket-layout mismatch."""
        if len(cumulative) != len(self.last_cum):
            return False
        if count < self.last_count:          # restart
            self.base_cum = [b + l for b, l in
                             zip(self.base_cum, self.last_cum)]
            self.base_sum += self.last_sum
            self.base_count += self.last_count
        self.last_cum = list(cumulative)
        self.last_sum = float(sum_)
        self.last_count = int(count)
        return True

    @property
    def total_cum(self) -> List[int]:
        return [b + l for b, l in zip(self.base_cum, self.last_cum)]

    @property
    def total_sum(self) -> float:
        return self.base_sum + self.last_sum

    @property
    def total_count(self) -> int:
        return self.base_count + self.last_count


class FleetAggregator:
    """Merges scraped per-process telemetry into the fleet view.

    ``clock`` is the controller env's wall-time callable — on the sim
    seam that is the virtual clock, so burn-rate windows and skew
    offsets are deterministic under a fixed seed."""

    def __init__(self, *, clock: Callable[[], float],
                 slos: Optional[List[TierSLO]] = None,
                 ring_points: int = DEFAULT_RING_POINTS,
                 trace_capacity: int = DEFAULT_TRACE_CAPACITY):
        self._clock = clock
        self._slos = list(slos or [])
        self._lock = threading.Lock()
        # source -> series-name -> series-key -> state/value
        self._counters: Dict[str, Dict[str, Dict[Any, _CounterState]]] \
            = {}
        self._hists: Dict[str, Dict[str, Dict[Any, _HistogramState]]] \
            = {}
        self._gauges: Dict[str, Dict[str, Dict[Any, float]]] = {}
        self._families: Dict[str, Tuple[str, str]] = {}
        self._series_per_source: Dict[str, int] = {}
        self._skew: Dict[str, float] = {}        # source -> offset (s)
        self._scrapes = 0
        self._dropped_series = 0
        self._merge_skipped = 0
        # trace_id -> list of leg dicts (insertion-ordered store,
        # oldest trace evicted first).
        self._traces: 'collections.OrderedDict[str, List[Dict[str, Any]]]' \
            = collections.OrderedDict()
        self._trace_capacity = max(1, int(trace_capacity))
        self._traces_evicted = 0
        # Burn-rate rings: tier -> deque of (t, measured, bad, admitted,
        # shed) cumulative fleet totals.
        self._rings: Dict[str, 'collections.deque'] = {}
        self._ring_points = max(8, int(ring_points))
        self._slo_values: Dict[str, Dict[str, float]] = {}

    # ---------------------------------------------------------- ingest
    def ingest(self, source: str, payload: Dict[str, Any]) -> None:
        """One scraped ``/telemetry/summary`` payload (or an LB's sync
        piggyback): ``{'clock': {'wall': ...}, 'registry': <wire
        export>, 'traces': [...]}`` — every block optional."""
        now = self._clock()
        with self._lock:
            self._scrapes += 1
            clk = payload.get('clock') or {}
            if isinstance(clk.get('wall'), (int, float)):
                self._skew[source] = now - float(clk['wall'])
            wire = payload.get('registry')
            if isinstance(wire, dict):
                self._ingest_registry_locked(source, wire)
        # Trace ingestion re-reads the recorded skew under the lock.
        traces = payload.get('traces')
        if traces:
            self.ingest_traces(source, traces)
        self._sample_slos()

    def _ingest_registry_locked(self, source: str,
                                wire: Dict[str, Any]) -> None:
        if (source not in self._skew
                and len(self._skew) >= MAX_SOURCES):
            return
        budget = self._series_per_source
        for name in sorted(wire):
            fam = wire[name]
            if not isinstance(fam, dict):
                continue
            kind = fam.get('kind', 'untyped')
            if name not in self._families or not \
                    self._families[name][1]:
                self._families[name] = (kind, fam.get('help', ''))
            for entry in fam.get('series') or []:
                if budget.get(source, 0) >= MAX_SERIES_PER_SOURCE:
                    self._dropped_series += 1
                    continue
                labels = entry.get('labels') or {}
                key = _series_key(labels)
                if kind == 'counter':
                    st = self._counters.setdefault(
                        source, {}).setdefault(name, {})
                    if key not in st:
                        budget[source] = budget.get(source, 0) + 1
                    st.setdefault(key, _CounterState()).update(
                        float(entry.get('value', 0.0)))
                elif kind == 'histogram':
                    st = self._hists.setdefault(
                        source, {}).setdefault(name, {})
                    hs = st.get(key)
                    if hs is None:
                        hs = _HistogramState(
                            [float(b) for b in
                             entry.get('buckets') or []])
                        st[key] = hs
                        budget[source] = budget.get(source, 0) + 1
                    ok = hs.update(entry.get('cumulative') or [],
                                   float(entry.get('sum', 0.0)),
                                   int(entry.get('count', 0)))
                    if not ok:
                        self._merge_skipped += 1
                else:                 # gauge / untyped: labelled, not summed
                    st = self._gauges.setdefault(
                        source, {}).setdefault(name, {})
                    if key not in st:
                        budget[source] = budget.get(source, 0) + 1
                    st[key] = float(entry.get('value', 0.0))

    def ingest_traces(self, source: str,
                      traces: List[Dict[str, Any]]) -> None:
        """Completed-trace summaries from one process. Legs from the
        same process for the same trace id accumulate; the store is
        bounded (oldest trace evicted)."""
        with self._lock:
            skew = self._skew.get(source, 0.0)
            for t in traces:
                if not isinstance(t, dict):
                    continue
                tid = t.get('trace_id')
                if not tid:
                    continue
                legs = self._traces.get(tid)
                if legs is None:
                    while len(self._traces) >= self._trace_capacity:
                        self._traces.popitem(last=False)
                        self._traces_evicted += 1
                    legs = []
                    self._traces[tid] = legs
                if len(legs) >= MAX_LEGS_PER_TRACE:
                    continue
                leg = dict(t)
                leg['source'] = source
                leg['skew_s'] = skew
                legs.append(leg)

    def set_slos(self, slos: Optional[List[TierSLO]]) -> None:
        """Replace the objective set (a service ``update`` changed the
        ``slos:`` block). Rings for tiers that remain keep their
        history — burn windows survive a spec bump."""
        with self._lock:
            self._slos = list(slos or [])
            keep = {s.tier for s in self._slos}
            for tier in [t for t in self._rings if t not in keep]:
                del self._rings[tier]
            self._slo_values = {
                t: v for t, v in self._slo_values.items() if t in keep}

    def source_count(self) -> int:
        with self._lock:
            return len(self._skew)

    def forget_source(self, source: str) -> None:
        """Drop a removed replica's per-source state (its already
        merged history stays in the rings/trace store)."""
        with self._lock:
            self._counters.pop(source, None)
            self._hists.pop(source, None)
            self._gauges.pop(source, None)
            self._series_per_source.pop(source, None)
            self._skew.pop(source, None)

    # --------------------------------------------------- merged values
    def _fleet_counter_locked(self, name: str
                              ) -> Dict[Any, Tuple[Dict[str, str],
                                                   float]]:
        out: Dict[Any, Tuple[Dict[str, str], float]] = {}
        for source in sorted(self._counters):
            for key, st in self._counters[source].get(name,
                                                      {}).items():
                if key in out:
                    out[key] = (out[key][0], out[key][1] + st.total)
                else:
                    out[key] = (dict(key), st.total)
        return out

    def _fleet_hist_locked(self, name: str
                           ) -> Dict[Any, Tuple[Dict[str, str],
                                                List[float],
                                                List[int], float, int]]:
        out: Dict[Any, Any] = {}
        for source in sorted(self._hists):
            for key, hs in self._hists[source].get(name, {}).items():
                cur = out.get(key)
                if cur is None:
                    out[key] = [dict(key), list(hs.buckets),
                                hs.total_cum, hs.total_sum,
                                hs.total_count]
                elif cur[1] == hs.buckets:
                    cur[2] = [a + b for a, b in
                              zip(cur[2], hs.total_cum)]
                    cur[3] += hs.total_sum
                    cur[4] += hs.total_count
                else:
                    self._merge_skipped += 1
        return {k: tuple(v) for k, v in out.items()}

    # ------------------------------------------------------------- SLO
    def _tier_totals_locked(self, slo: TierSLO
                            ) -> Tuple[float, float, float, float]:
        """(measured, bad, admitted, shed) cumulative fleet totals for
        one tier under its objectives. ``measured`` counts latency
        observations; ``bad`` those over an objective threshold
        (evaluated at the first bucket bound >= threshold — the
        resolution a fixed-bucket store affords)."""
        measured = bad = 0.0
        for metric, threshold in ((TTFT_METRIC, slo.ttft_ms),
                                  (TPOT_METRIC, slo.tpot_ms)):
            for source in sorted(self._hists):
                for key, hs in self._hists[source].get(metric,
                                                       {}).items():
                    if dict(key).get('tier') != slo.tier:
                        continue
                    count = hs.total_count
                    if metric == TTFT_METRIC:
                        measured += count
                    if threshold is None or count == 0:
                        continue
                    idx = bisect.bisect_left(hs.buckets,
                                             float(threshold))
                    cum = hs.total_cum
                    good = cum[idx] if idx < len(cum) else count
                    bad += count - good
        admitted = shed = 0.0
        for source in sorted(self._counters):
            for key, st in self._counters[source].get(
                    ADMIT_METRIC, {}).items():
                if dict(key).get('tier') == slo.tier:
                    admitted += st.total
            for key, st in self._counters[source].get(
                    SHED_METRIC, {}).items():
                if dict(key).get('tier') == slo.tier:
                    shed += st.total
        return measured, bad, admitted, shed

    def _sample_slos(self) -> None:
        """Append a ring point per tier and refresh burn gauges."""
        if not self._slos:
            return
        now = self._clock()
        with self._lock:
            for slo in self._slos:
                ring = self._rings.get(slo.tier)
                if ring is None:
                    ring = collections.deque(maxlen=self._ring_points)
                    self._rings[slo.tier] = ring
                ring.append((now,) + self._tier_totals_locked(slo))
            values = {slo.tier: self._evaluate_tier_locked(slo)
                      for slo in self._slos}
            self._slo_values = values

    def _evaluate_tier_locked(self, slo: TierSLO) -> Dict[str, float]:
        ring = self._rings.get(slo.tier)
        out: Dict[str, float] = {}
        if not ring:
            out['attainment'] = 1.0
            for _, label in BURN_WINDOWS:
                out[f'burn_{label}'] = 0.0
            return out
        now, cur_measured, cur_bad, cur_admitted, cur_shed = ring[-1]
        for window_s, label in BURN_WINDOWS:
            # Oldest point still inside the window = the baseline the
            # deltas are taken against (the ring is append-ordered).
            base = None
            for point in ring:
                if point[0] >= now - window_s:
                    base = point
                    break
            if base is None:
                base = ring[0]
            d_measured = cur_measured - base[1]
            d_bad = cur_bad - base[2]
            d_admitted = cur_admitted - base[3]
            d_shed = cur_shed - base[4]
            burn = 0.0
            if d_measured > 0:
                burn = (d_bad / d_measured) / slo.error_budget
            if slo.shed_rate and (d_admitted + d_shed) > 0:
                shed_frac = d_shed / (d_admitted + d_shed)
                burn = max(burn, shed_frac / max(1e-9, slo.shed_rate))
            out[f'burn_{label}'] = burn
            if label == BURN_WINDOWS[0][1]:
                out['attainment'] = (1.0 - d_bad / d_measured
                                     if d_measured > 0 else 1.0)
        return out

    def slo_status(self) -> Dict[str, Dict[str, float]]:
        """Per-tier burn/attainment — what controller status and LB
        sync surface for autoscalers and fleet schedulers."""
        with self._lock:
            return {tier: dict(vals)
                    for tier, vals in sorted(self._slo_values.items())}

    # ----------------------------------------------------- trace views
    def trace_ids(self) -> List[str]:
        with self._lock:
            return list(self._traces)

    def assemble_trace(self, trace_id: str
                       ) -> Optional[Dict[str, Any]]:
        """The multi-process odyssey for one trace id: every shipped
        leg's spans on one skew-adjusted wall-clock axis, in causal
        order."""
        with self._lock:
            legs = self._traces.get(trace_id)
            if legs is None:
                return None
            legs = [dict(leg) for leg in legs]
        spans: List[Dict[str, Any]] = []
        for leg in legs:
            base_wall = (float(leg.get('submitted_at', 0.0))
                         + float(leg.get('skew_s', 0.0)))
            for span in leg.get('spans') or []:
                start = base_wall + float(span.get('start_ms',
                                                   0.0)) / 1e3
                out = {'name': span.get('name'),
                       'source': leg.get('source'),
                       'request_id': leg.get('request_id'),
                       't_wall': start}
                if 'dur_ms' in span:
                    out['dur_ms'] = span['dur_ms']
                if span.get('meta'):
                    out['meta'] = span['meta']
                spans.append(out)
        spans.sort(key=lambda s: (s['t_wall'], str(s['name'])))
        return {'trace_id': trace_id,
                'legs': legs,
                'spans': spans}

    def chrome_events(self, trace_id: str
                      ) -> Optional[List[Dict[str, Any]]]:
        """Chrome trace-event dicts for one assembled trace (one pid
        per source process, tid = that leg's request id), feedable to
        ``utils/timeline.write_trace``."""
        assembled = self.assemble_trace(trace_id)
        if assembled is None:
            return None
        pids = {leg['source']: i + 1 for i, leg in
                enumerate({leg['source']: leg
                           for leg in assembled['legs']}.values())}
        events: List[Dict[str, Any]] = []
        for span in assembled['spans']:
            args = {k: str(v) for k, v in
                    (span.get('meta') or {}).items()}
            args['trace_id'] = trace_id
            args['source'] = str(span.get('source'))
            events.append({
                'name': span['name'],
                'ph': 'X',
                'ts': span['t_wall'] * 1e6,
                'dur': float(span.get('dur_ms', 0.0)) * 1e3,
                'pid': pids.get(span.get('source'), 0),
                'tid': span.get('request_id') or 0,
                'args': args,
            })
        return events

    # ------------------------------------------------------- rendering
    def _build_merged(self) -> registry_lib.MetricsRegistry:
        reg = registry_lib.MetricsRegistry()
        with self._lock:
            fam = dict(self._families)
            counter_names = sorted({n for per in self._counters.values()
                                    for n in per})
            hist_names = sorted({n for per in self._hists.values()
                                 for n in per})
            gauge_rows: List[Tuple[str, str, Dict[str, str], float]] \
                = []
            for source in sorted(self._gauges):
                for name in sorted(self._gauges[source]):
                    for key, val in sorted(
                            self._gauges[source][name].items()):
                        gauge_rows.append((name, source, dict(key),
                                           val))
            counters = {n: self._fleet_counter_locked(n)
                        for n in counter_names}
            hists = {n: self._fleet_hist_locked(n)
                     for n in hist_names}
            scrapes = self._scrapes
            n_sources = len(self._skew)
            n_traces = len(self._traces)
            evicted = self._traces_evicted
            dropped = self._dropped_series
            skipped = self._merge_skipped
            slo_values = {t: dict(v)
                          for t, v in self._slo_values.items()}
        for name in counter_names:
            help_text = fam.get(name, ('', ''))[1]
            for key in sorted(counters[name]):
                labels, total = counters[name][key]
                reg.counter(name, help_text, **labels).inc(total)
        for name in hist_names:
            help_text = fam.get(name, ('', ''))[1]
            for key in sorted(hists[name]):
                labels, buckets, cum, sum_, count = hists[name][key]
                h = reg.histogram(name, help_text, buckets=buckets,
                                  window=1, **labels)
                h.merge_cumulative(cum, sum_, count)
        for name, source, labels, val in gauge_rows:
            help_text = fam.get(name, ('', ''))[1]
            reg.gauge(name, help_text, replica=source,
                      **labels).set(val)
        # Fleet-plane series of the aggregator itself.
        reg.gauge('skytpu_fleet_sources',
                  'Processes contributing to the fleet view'
                  ).set(n_sources)
        reg.counter('skytpu_fleet_scrapes_total',
                    'Telemetry payloads ingested').inc(scrapes)
        reg.gauge('skytpu_fleet_traces', 'Assembled-trace store size'
                  ).set(n_traces)
        reg.counter('skytpu_fleet_traces_evicted_total',
                    'Traces evicted from the bounded store'
                    ).inc(evicted)
        reg.counter('skytpu_fleet_series_dropped_total',
                    'Series dropped by the per-source cap'
                    ).inc(dropped)
        reg.counter('skytpu_fleet_merge_skipped_total',
                    'Histogram series skipped on bucket-layout '
                    'mismatch').inc(skipped)
        for tier, vals in sorted(slo_values.items()):
            reg.gauge('skytpu_slo_attainment',
                      'Fleet SLO attainment (short window)',
                      tier=tier).set(vals.get('attainment', 1.0))
            for _, label in BURN_WINDOWS:
                reg.gauge('skytpu_slo_burn_rate',
                          'Error-budget burn rate (>1 = unsustainable)',
                          tier=tier, window=label
                          ).set(vals.get(f'burn_{label}', 0.0))
        return reg

    def render_prometheus(self) -> str:
        return self._build_merged().render_prometheus()

    def render_json(self) -> Dict[str, Any]:
        out = self._build_merged().render_json()
        out['_slo'] = self.slo_status()
        return out
