"""Per-request lifecycle tracing.

A :class:`RequestTrace` is minted when a request enters an engine
(``add_request``) and carried on the ``Request`` object through its
whole life: queue-wait → prefill (one span per chunk in chunked mode) →
decode → speculative propose/verify rounds → finish or cancel. Spans
are HOST-DISPATCH-ALIGNED: a span covers the host-side time of the
stage (the device executes asynchronously behind the dispatch
pipeline), which is exactly the latency a client observes and what the
"where did this request's latency go" question needs.

Completed traces land in a bounded ring buffer (:class:`TraceBuffer`,
default 256 — a long-lived replica keeps CURRENT traffic, memory
bounded) served by the model server at ``/debug/requests`` and
exportable as a chrome trace through the existing
``utils/timeline.py`` writer (:func:`export_chrome_trace`).

Engines only ever touch traces from their single engine thread, so
span mutation is unlocked; the buffer (crossed by HTTP handler
threads) is locked.
"""
from __future__ import annotations

import collections
import itertools
import os
import re
import threading
from typing import Any, Dict, List, Optional, Tuple

from skypilot_tpu.telemetry import clock

DEFAULT_BUFFER = int(os.environ.get('SKYTPU_TRACE_BUFFER', '256'))

_trace_seq = itertools.count(1)

# ------------------------------------------------- cross-process trace ids
# The wire header every skytpu process propagates on outbound hops
# (LB -> replica /generate, prefill -> decode /kv/ingest, LB <-> LB
# idempotency pushes, migration/retry legs). Value:
# ``<trace_id>[;<parent_span>]`` — trace_id is 128-bit hex, parent_span
# names the span on the SENDING process this hop is causally under.
TRACE_HEADER = 'X-Skytpu-Trace'

_TRACE_ID_RE = re.compile(r'^[0-9a-f]{8,64}$')
_PARENT_RE = re.compile(r'^[\w.:/-]{1,128}$')


def mint_trace_id(rng: Optional[Any] = None) -> str:
    """A 128-bit hex trace id. Pass a seeded ``random.Random`` (the
    sim env's RNG stream) for deterministic ids; without one the id is
    drawn from ``os.urandom`` — pid-recycle-proof, unlike the old
    ``pid-seq`` locals that collided across replica restarts."""
    if rng is not None:
        return f'{rng.getrandbits(128):032x}'
    return os.urandom(16).hex()


def format_trace_header(trace_id: str,
                        parent_span: Optional[str] = None) -> str:
    """The ``X-Skytpu-Trace`` header value for one outbound hop."""
    if parent_span:
        return f'{trace_id};{parent_span}'
    return trace_id


def parse_trace_header(value: Optional[str]
                       ) -> Optional[Dict[str, Optional[str]]]:
    """Parse an incoming ``X-Skytpu-Trace`` value into
    ``{'trace_id', 'parent_span'}``; None for absent/garbage values
    (a malformed header must never break request handling — the
    receiver just mints a fresh local trace)."""
    if not value or not isinstance(value, str):
        return None
    trace_id, _, parent = value.strip().partition(';')
    trace_id = trace_id.strip().lower()
    if not _TRACE_ID_RE.match(trace_id):
        return None
    parent = parent.strip() or None
    if parent is not None and not _PARENT_RE.match(parent):
        parent = None
    return {'trace_id': trace_id, 'parent_span': parent}


class Span:
    __slots__ = ('name', 't0', 't1', 'wall0', 'meta')

    def __init__(self, name: str, t0: float, wall0: float,
                 meta: Optional[Dict[str, Any]] = None):
        self.name = name
        self.t0 = t0                # monotonic
        self.t1: Optional[float] = None
        self.wall0 = wall0          # wall clock (chrome-trace ts)
        self.meta = meta or {}

    @property
    def dur_ms(self) -> Optional[float]:
        if self.t1 is None:
            return None
        return (self.t1 - self.t0) * 1e3


class RequestTrace:
    """One request's span timeline. Engine-thread-only mutation."""

    def __init__(self, request_id: int,
                 trace_id: Optional[str] = None,
                 parent_span: Optional[str] = None):
        self.request_id = request_id
        # The process-local id survives one release as ``legacy_id``
        # (pids recycle across replica restarts, so it is NOT unique
        # fleet-wide — the controller keys its trace store by the
        # 128-bit ``trace_id`` only).
        self.legacy_id = f'{os.getpid():x}-{next(_trace_seq):x}'
        self.trace_id = trace_id or mint_trace_id()
        self.parent_span = parent_span
        self.t0 = clock.monotonic()
        self.wall0 = clock.now()
        self.spans: List[Span] = []
        self.done = False
        self.meta: Dict[str, Any] = {}

    def adopt_wire_context(self, trace_id: Optional[str] = None,
                           parent_span: Optional[str] = None) -> None:
        """Adopt a wire-supplied trace context (an upstream hop's
        ``X-Skytpu-Trace``): the request joins the fleet-wide trace
        instead of keeping its locally minted id."""
        if trace_id:
            self.trace_id = trace_id
        if parent_span:
            self.parent_span = parent_span

    # ------------------------------------------------------------- spans
    def begin(self, name: str, **meta: Any) -> Span:
        span = Span(name, clock.monotonic(), clock.now(), meta or None)
        self.spans.append(span)
        return span

    def end(self, name: str) -> None:
        """Close the most recent still-open span named ``name``
        (no-op when none is open — re-admission paths may re-begin)."""
        for span in reversed(self.spans):
            if span.name == name and span.t1 is None:
                span.t1 = clock.monotonic()
                return

    def add(self, name: str, t0: float, t1: float, **meta: Any) -> Span:
        """Record a pre-timed span (monotonic endpoints)."""
        span = Span(name, t0, clock.now() - (clock.monotonic() - t0),
                    meta or None)
        span.t1 = t1
        self.spans.append(span)
        return span

    def instant(self, name: str, **meta: Any) -> None:
        t = clock.monotonic()
        span = Span(name, t, clock.now(), meta or None)
        span.t1 = t
        self.spans.append(span)

    def finish(self, **meta: Any) -> None:
        """Close every open span and mark the trace complete."""
        t1 = clock.monotonic()
        for span in self.spans:
            if span.t1 is None:
                span.t1 = t1
        self.meta.update(meta)
        self.done = True

    # ----------------------------------------------------------- queries
    def span_ms(self, name: str) -> Optional[float]:
        """Duration of the FIRST completed span named ``name``."""
        for span in self.spans:
            if span.name == name and span.t1 is not None:
                return span.dur_ms
        return None

    def to_dict(self) -> Dict[str, Any]:
        spans = []
        for span in self.spans:
            d: Dict[str, Any] = {
                'name': span.name,
                'start_ms': round((span.t0 - self.t0) * 1e3, 3),
            }
            if span.t1 is not None:
                d['dur_ms'] = round((span.t1 - span.t0) * 1e3, 3)
            if span.meta:
                d['meta'] = dict(span.meta)
            spans.append(d)
        d = {'trace_id': self.trace_id,
             'legacy_id': self.legacy_id,
             'request_id': self.request_id,
             'submitted_at': self.wall0,
             'done': self.done,
             'meta': dict(self.meta),
             'spans': spans}
        if self.parent_span is not None:
            d['parent_span'] = self.parent_span
        return d


class TraceBuffer:
    """Bounded ring of COMPLETED traces (oldest evicted first).

    Each added trace gets a monotonically increasing sequence number
    so the controller's sync-time scrape (``summaries_since``) ships
    each completed trace at most once — the cursor survives ring
    eviction (missed traces are simply gone, never re-sent)."""

    # Span cap per shipped summary: a pathological chunked-prefill
    # request must not blow up the controller's bounded trace store.
    SUMMARY_MAX_SPANS = 64

    def __init__(self, maxlen: int = DEFAULT_BUFFER):
        self._lock = threading.Lock()
        self._traces: 'collections.deque[RequestTrace]' = \
            collections.deque(maxlen=max(1, maxlen))
        self._seqs: 'collections.deque[int]' = \
            collections.deque(maxlen=max(1, maxlen))
        self._next_seq = 1

    def add(self, trace: RequestTrace) -> None:
        with self._lock:
            self._traces.append(trace)
            self._seqs.append(self._next_seq)
            self._next_seq += 1

    def snapshot(self) -> List[RequestTrace]:
        with self._lock:
            return list(self._traces)

    def summaries_since(self, cursor: int,
                        limit: int = 128
                        ) -> Tuple[int, List[Dict[str, Any]]]:
        """(new_cursor, completed-trace dicts added after ``cursor``),
        oldest first, at most ``limit`` — the bounded payload a replica
        ships to the controller on the sync/probe path."""
        with self._lock:
            pairs = [(s, t) for s, t in zip(self._seqs, self._traces)
                     if s > cursor]
            tail_cursor = self._next_seq - 1
        trimmed = pairs[:max(0, int(limit))]
        out = []
        for _, trace in trimmed:
            d = trace.to_dict()
            if len(d['spans']) > self.SUMMARY_MAX_SPANS:
                d['spans'] = d['spans'][:self.SUMMARY_MAX_SPANS]
                d['meta']['spans_truncated'] = True
            out.append(d)
        if len(trimmed) < len(pairs):
            # ``limit`` trimmed the batch: resume from the last shipped
            # trace, not the ring head — the rest ships next sync.
            return trimmed[-1][0] if trimmed else cursor, out
        return max(cursor, tail_cursor), out

    def to_json(self, limit: Optional[int] = None) -> List[Dict[str, Any]]:
        """Newest-first trace dicts (the ``/debug/requests`` body)."""
        traces = self.snapshot()[::-1]
        if limit is not None:
            traces = traces[:max(0, int(limit))]
        return [t.to_dict() for t in traces]

    def find(self, request_id: int) -> Optional[RequestTrace]:
        for t in reversed(self.snapshot()):
            if t.request_id == request_id:
                return t
        return None

    def find_trace(self, trace_id: str) -> Optional[RequestTrace]:
        """Lookup by 128-bit trace id (or, for one release, the old
        ``pid-seq`` legacy id)."""
        for t in reversed(self.snapshot()):
            if t.trace_id == trace_id or t.legacy_id == trace_id:
                return t
        return None

    def clear(self) -> None:
        with self._lock:
            self._traces.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._traces)


_buffer_lock = threading.Lock()
_buffer: Optional[TraceBuffer] = None


def get_trace_buffer() -> TraceBuffer:
    """THE process-wide completed-request trace buffer."""
    global _buffer
    with _buffer_lock:
        if _buffer is None:
            _buffer = TraceBuffer()
        return _buffer


def export_chrome_trace(path: str,
                        traces: Optional[List[RequestTrace]] = None
                        ) -> Optional[str]:
    """Write traces as a ``chrome://tracing`` file via the existing
    ``utils/timeline.py`` writer. One chrome thread (tid) per request;
    span args carry the meta. Returns the path (None when empty)."""
    from skypilot_tpu.utils import timeline
    if traces is None:
        traces = get_trace_buffer().snapshot()
    events: List[Dict[str, Any]] = []
    for trace in traces:
        base_wall_us = trace.wall0 * 1e6
        for span in trace.spans:
            if span.t1 is None:
                continue
            ev: Dict[str, Any] = {
                'name': span.name,
                'ph': 'X',
                'ts': base_wall_us + (span.t0 - trace.t0) * 1e6,
                'dur': (span.t1 - span.t0) * 1e6,
                'pid': os.getpid(),
                'tid': trace.request_id,
            }
            args = {k: str(v) for k, v in span.meta.items()}
            args['trace_id'] = trace.trace_id
            ev['args'] = args
            events.append(ev)
    if not events:
        return None
    return timeline.write_trace(path, events)
