"""Unified telemetry: metrics registry, per-request tracing, and the
engine step-phase profiler.

Three legs, one subsystem (the instrumentation layer SLO-aware serving
policies — SageServe/ThunderServe-class autoscaling and placement,
PAPERS.md — are built on):

- :mod:`skypilot_tpu.telemetry.registry` — a process-wide, thread-safe
  metrics registry (counters, gauges, fixed-bucket histograms with a
  bounded window for exact quantiles), rendered in Prometheus text
  exposition format or JSON. The model server's ``GET /metrics``, the
  dashboard, the load balancer, the replica manager, and the jobs
  layer all write here — one registry, no private JSON blobs.
- :mod:`skypilot_tpu.telemetry.tracing` — per-request lifecycle spans
  (queue-wait → prefill chunks → decode → speculative rounds →
  finish/cancel) minted at ``add_request`` and carried on ``Request``;
  completed timelines land in a bounded ring buffer served at
  ``/debug/requests`` and exportable as a chrome trace through the
  ``utils/timeline.py`` writer.
- :mod:`skypilot_tpu.telemetry.profiler` — engine step-phase wall
  times (admit, prefill-chunk, decode-enqueue, spec-verify, sanctioned
  readback) and first-call-per-jit-key (compile) events, using
  monotonic clocks strictly OUTSIDE jit bodies and device syncs — the
  jaxpr audit's ``telemetry`` preset proves telemetry-on adds zero
  d2h transfers and zero compiles versus telemetry-off.

``clock`` holds the sanctioned wall/monotonic time sources for the
inference hot paths (graftcheck GC109 bans ad-hoc ``time.time()`` /
``perf_counter()`` there).
"""
from skypilot_tpu.telemetry import clock
from skypilot_tpu.telemetry.fleet import FleetAggregator
from skypilot_tpu.telemetry.fleet import TierSLO
from skypilot_tpu.telemetry.profiler import NullProfiler
from skypilot_tpu.telemetry.profiler import StepProfiler
from skypilot_tpu.telemetry.registry import Counter
from skypilot_tpu.telemetry.registry import Gauge
from skypilot_tpu.telemetry.registry import Histogram
from skypilot_tpu.telemetry.registry import MetricsRegistry
from skypilot_tpu.telemetry.registry import get_registry
from skypilot_tpu.telemetry.tracing import RequestTrace
from skypilot_tpu.telemetry.tracing import TRACE_HEADER
from skypilot_tpu.telemetry.tracing import TraceBuffer
from skypilot_tpu.telemetry.tracing import export_chrome_trace
from skypilot_tpu.telemetry.tracing import get_trace_buffer
from skypilot_tpu.telemetry.tracing import mint_trace_id

__all__ = [
    'clock', 'Counter', 'Gauge', 'Histogram', 'MetricsRegistry',
    'get_registry', 'RequestTrace', 'TraceBuffer', 'get_trace_buffer',
    'export_chrome_trace', 'StepProfiler', 'NullProfiler', 'enabled',
    'FleetAggregator', 'TierSLO', 'TRACE_HEADER', 'mint_trace_id',
]


def enabled() -> bool:
    """Process-wide telemetry kill switch (``SKYTPU_TELEMETRY=0``).
    Engines AND this with their ``telemetry=`` constructor knob."""
    import os
    return os.environ.get('SKYTPU_TELEMETRY', '1') != '0'
