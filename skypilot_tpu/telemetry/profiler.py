"""Engine step-phase profiler.

Answers "which step phase regressed": per-phase wall time of the
engine scheduling loop (admit, prefill-chunk, decode-enqueue,
spec-verify, sanctioned readback) and first-call-per-jit-key events
(the call that pays XLA compilation).

Measurement discipline — the reason this is safe on the hot path and
the jaxpr audit's ``telemetry`` preset stays green:

- Monotonic clocks only (``telemetry.clock``), taken strictly on the
  HOST side AROUND jitted dispatches — never inside a jit body (that
  would trace a constant) and never forcing a device sync (a phase
  ends when the dispatch returns, not when the device finishes; device
  completion is visible in the ``readback`` phase, which wraps the
  engines' one sanctioned ``host_sync``).
- First-compile events ride the engines' existing jit-key bookkeeping:
  a key never seen before has its first dispatch timed (jit compiles
  synchronously at first call, so the wall time ≈ trace+compile);
  seen keys pay one set lookup.

Per-phase times accumulate BOTH locally (``phase_stats()`` — bench's
per-engine latency decomposition) and into the process registry
(``skypilot_tpu_engine_step_phase_seconds{phase=...}`` — the
``/metrics`` surface). :class:`NullProfiler` is the telemetry-off
no-op twin with the same API.
"""
from __future__ import annotations

import contextlib
import threading
from typing import Any, Dict, List, Optional, Tuple

from skypilot_tpu.telemetry import clock
from skypilot_tpu.telemetry import registry as registry_lib

PHASE_METRIC = 'skytpu_engine_step_phase_seconds'
COMPILE_METRIC = 'skytpu_jit_first_call_seconds'
SUBSTEP_METRIC = 'skytpu_engine_decode_substeps_total'


class NullProfiler:
    """Telemetry-off profiler: same API, zero work."""

    compile_events: List[Dict[str, Any]] = []

    @contextlib.contextmanager
    def phase(self, name: str):
        del name
        yield

    @contextlib.contextmanager
    def jit_key(self, fn: str, key: Tuple):
        del fn, key
        yield

    def note_substeps(self, name: str, n: int) -> None:
        del name, n

    def phase_stats(self) -> Dict[str, Any]:
        return {}


class StepProfiler:
    """Per-engine step-phase + first-compile recorder. The phase/jit
    context managers are called from the single engine thread;
    ``phase_stats()`` may be read from other threads (bench, handlers)
    — the small accumulator dict is guarded."""

    def __init__(self, engine: str = '',
                 registry: Optional[registry_lib.MetricsRegistry] = None):
        self.engine = engine
        self._reg = registry or registry_lib.get_registry()
        self._lock = threading.Lock()
        # phase -> [count, total_s, max_s]
        self._acc: Dict[str, List[float]] = {}
        # phase -> device SUBSTEPS its dispatches covered (multi-step
        # decode: one decode_enqueue dispatch fuses k substeps, so the
        # per-substep split = total_s / substeps — the number that
        # shows dispatch amortization instead of hiding it in a
        # fatter per-call mean).
        self._substeps: Dict[str, int] = {}
        # Registered at construction: zeros from the first scrape.
        self._substep_counter = self._reg.counter(
            SUBSTEP_METRIC,
            'Device decode substeps covered by enqueued dispatches '
            '(k per call under multi-step decode)')
        self._hists: Dict[str, registry_lib.Histogram] = {}
        self._seen_keys: Dict[str, set] = {}
        self.compile_events: List[Dict[str, Any]] = []

    def _phase_hist(self, name: str) -> registry_lib.Histogram:
        hist = self._hists.get(name)
        if hist is None:
            hist = self._reg.histogram(
                PHASE_METRIC,
                'Engine scheduling-loop phase wall time (host-side, '
                'around async dispatches)',
                buckets=registry_lib.DEFAULT_SECONDS_BUCKETS,
                phase=name)
            self._hists[name] = hist
        return hist

    @contextlib.contextmanager
    def phase(self, name: str):
        t0 = clock.monotonic()
        try:
            yield
        finally:
            dt = clock.monotonic() - t0
            self._phase_hist(name).observe(dt)
            with self._lock:
                acc = self._acc.setdefault(name, [0, 0.0, 0.0])
                acc[0] += 1
                acc[1] += dt
                acc[2] = max(acc[2], dt)

    @contextlib.contextmanager
    def jit_key(self, fn: str, key: Tuple):
        """Time the FIRST dispatch of each (fn, static key) — the call
        that pays compilation. Subsequent calls: one set lookup."""
        seen = self._seen_keys.setdefault(fn, set())
        if key in seen:
            yield
            return
        t0 = clock.monotonic()
        try:
            yield
        finally:
            dt = clock.monotonic() - t0
            seen.add(key)
            self._reg.histogram(
                COMPILE_METRIC,
                'Wall time of the first dispatch per jit static key '
                '(trace + XLA compile)',
                buckets=registry_lib.DEFAULT_SECONDS_BUCKETS,
                fn=fn).observe(dt)
            with self._lock:
                self.compile_events.append(
                    {'fn': fn, 'key': repr(key),
                     'seconds': round(dt, 6)})

    def note_substeps(self, name: str, n: int) -> None:
        """Record that the NEXT/current ``name`` dispatch covers ``n``
        device substeps (multi-step decode's per-substep attribution).
        Host-side counter bump only — nothing touches the device."""
        if n <= 0:
            return
        self._substep_counter.inc(n)
        with self._lock:
            self._substeps[name] = self._substeps.get(name, 0) + n

    def phase_stats(self) -> Dict[str, Any]:
        """Per-phase summary for THIS engine (bench's latency
        decomposition): phase -> count/total_s/mean_ms/max_ms (+
        substeps/per_substep_ms where dispatches fuse multiple device
        substeps), plus the first-compile event list."""
        with self._lock:
            acc = {k: list(v) for k, v in self._acc.items()}
            subs = dict(self._substeps)
            compiles = list(self.compile_events)
        out: Dict[str, Any] = {'phases': {}, 'compiles': compiles}
        for name, (count, total, mx) in sorted(acc.items()):
            entry = {
                'count': int(count),
                'total_s': round(total, 6),
                'mean_ms': round(total / count * 1e3, 3) if count else 0.0,
                'max_ms': round(mx * 1e3, 3),
            }
            if subs.get(name):
                entry['substeps'] = int(subs[name])
                entry['per_substep_ms'] = round(
                    total / subs[name] * 1e3, 4)
            out['phases'][name] = entry
        return out
