"""Sanctioned time sources for the inference hot paths.

graftcheck rule GC109 bans ad-hoc ``time.time()`` / ``perf_counter()``
calls inside ``inference/`` — every wall-clock stamp and every duration
measurement there routes through these two functions instead. Why a
module and not a convention: the lint can then PROVE no stray timing
call sits on the hot path (a mis-placed ``perf_counter()`` pair around
a jitted dispatch is how accidental host syncs and misleading
"device time" numbers historically crept in), and a future
trace-overhead kill switch has exactly one seam to hook.

``now()`` is wall time (request timestamps, cross-process alignment);
``monotonic()`` is for durations (immune to NTP steps).
"""
from __future__ import annotations

import time as _time


def now() -> float:
    """Wall-clock seconds since the epoch (request timestamps)."""
    return _time.time()


def monotonic() -> float:
    """Monotonic seconds (span/phase durations)."""
    return _time.monotonic()
