"""Download commands for cloud URIs used as file_mounts sources.

Role of reference ``sky/cloud_stores.py`` (561 LoC of per-store
CloudStorage classes): given ``gs://...``/``s3://...``/``https://...``,
emit the shell command that fetches it onto a cluster host.
"""
from __future__ import annotations

import shlex


def _q(path: str) -> str:
    """Quote a remote path, keeping a leading ~ expandable by the remote
    shell (plain shlex.quote would make it a literal '~' directory)."""
    if path.startswith('~/'):
        return '"$HOME"/' + shlex.quote(path[2:])
    return shlex.quote(path)


def make_download_command(src: str, dst: str) -> str:
    """Shell command to download src URI to dst path on a host."""
    q_dst = _q(dst)
    q_src = shlex.quote(src)
    mkdir = f'mkdir -p $(dirname {q_dst})'
    if src.startswith('gs://'):
        return (f'{mkdir} && (gsutil -m cp -r {q_src} {q_dst} || '
                f'gcloud storage cp -r {q_src} {q_dst})')
    if src.startswith('s3://'):
        return f'{mkdir} && aws s3 cp --recursive {q_src} {q_dst}'
    if src.startswith('r2://'):
        import os
        path = src[len('r2://'):]
        # Resolve the endpoint client-side when available (cluster hosts
        # don't inherit the client env); fall back to the remote env var.
        endpoint = os.environ.get('R2_ENDPOINT')
        ep = (shlex.quote(endpoint) if endpoint else '"$R2_ENDPOINT"')
        return (f'{mkdir} && aws s3 cp --recursive s3://{shlex.quote(path)} '
                f'{q_dst} --endpoint-url {ep}')
    if src.startswith(('https://', 'http://')):
        return f'{mkdir} && curl -fsSL {q_src} -o {q_dst}'
    if src.startswith('file://'):
        # LOCAL-store bucket (shared-filesystem clusters / tests).
        path = shlex.quote(src[len('file://'):])
        return (f'{mkdir} && mkdir -p {q_dst} && '
                f'cp -r {path}/. {q_dst}/')
    raise ValueError(f'Unsupported URI scheme: {src}')
