"""Execute storage mounts on a provisioned cluster.

Role of reference ``_execute_storage_mounts``
(``sky/backends/cloud_vm_ray_backend.py:4832``): for each
``path -> Storage``, ensure the bucket exists + source is synced, then on
every host either download (COPY) or mount (MOUNT) at the path.
"""
from __future__ import annotations

import os
from typing import Any, Dict

from skypilot_tpu import tpu_logging
from skypilot_tpu.data import storage as storage_lib
from skypilot_tpu.utils import subprocess_utils

logger = tpu_logging.init_logger(__name__)


def resolve_storage(value: Any) -> storage_lib.Storage:
    if isinstance(value, storage_lib.Storage):
        return value
    if isinstance(value, dict):
        return storage_lib.Storage.from_yaml_config(value)
    raise ValueError(f'Cannot resolve storage spec: {value!r}')


def execute_storage_mounts(handle,
                           storage_mounts: Dict[str, Any]) -> None:
    resolved = {path: resolve_storage(cfg)
                for path, cfg in storage_mounts.items()}
    for storage in resolved.values():
        storage.sync_to_stores()

    runners = handle.runners()

    def mount_on_host(runner) -> None:
        for path, storage in resolved.items():
            store = storage.primary_store
            if storage.mode == storage_lib.StorageMode.COPY:
                cmd = store.make_download_command(path)
            else:
                cmd = store.make_mount_command(path)
            runner.check_run(cmd, log_path=os.devnull)

    subprocess_utils.run_in_parallel(mount_on_host, runners)
    logger.debug(f'Storage mounts ready on {len(runners)} host(s): '
                 f'{list(resolved)}')
