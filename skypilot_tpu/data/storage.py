"""Storage: bucket lifecycle (create/sync/mount/delete) + store impls.

Role of reference ``sky/data/storage.py`` (``Storage`` ``:473``,
``AbstractStore`` ``:248``, ``StorageMode`` ``:243``, ``GcsStore``
``:1725``). TPU-first scope: GCS is the first-class store (checkpoints
ride gcsfuse); a LOCAL store (a directory pretending to be a bucket)
makes the whole contract — including managed-job checkpoint recovery —
hermetically testable, which the reference cannot do offline.
"""
from __future__ import annotations

import enum
import os
import shlex
import shutil
import subprocess
from typing import Any, Dict, List, Optional, Union

from skypilot_tpu import exceptions
from skypilot_tpu import global_state
from skypilot_tpu import tpu_logging
from skypilot_tpu.utils import common_utils

logger = tpu_logging.init_logger(__name__)


class StoreType(enum.Enum):
    GCS = 'GCS'
    S3 = 'S3'
    R2 = 'R2'
    AZURE = 'AZURE'
    IBM = 'IBM'
    OCI = 'OCI'
    LOCAL = 'LOCAL'

    @classmethod
    def from_str(cls, s: str) -> 'StoreType':
        try:
            return cls(s.upper())
        except ValueError:
            raise exceptions.StorageSpecError(
                f'Unknown store type {s!r}; supported: '
                f'{[t.value for t in cls]}') from None

    @classmethod
    def from_uri(cls, uri: str) -> 'StoreType':
        scheme = uri.split('://', 1)[0].lower()
        if scheme == 'https' and '.blob.core.windows.net' in uri:
            return cls.AZURE
        try:
            return {'gs': cls.GCS, 's3': cls.S3, 'r2': cls.R2,
                    'azure': cls.AZURE, 'cos': cls.IBM,
                    'oci': cls.OCI, 'file': cls.LOCAL}[scheme]
        except KeyError:
            raise exceptions.StorageSpecError(
                f'Unknown bucket URI scheme {uri!r}') from None


class StorageMode(enum.Enum):
    MOUNT = 'MOUNT'
    COPY = 'COPY'


class AbstractStore:
    """One bucket in one store backend."""

    store_type: StoreType

    def __init__(self, name: str, source: Optional[str] = None):
        self.name = name
        self.source = source

    # lifecycle
    def ensure_bucket(self) -> None:
        raise NotImplementedError

    def upload(self) -> None:
        """Sync ``source`` into the bucket."""
        raise NotImplementedError

    def delete_bucket(self) -> None:
        raise NotImplementedError

    # consumption on cluster hosts
    def uri(self) -> str:
        raise NotImplementedError

    def make_download_command(self, dst: str) -> str:
        raise NotImplementedError

    def make_mount_command(self, mount_path: str) -> str:
        raise NotImplementedError


class GcsStore(AbstractStore):
    """GCS via gsutil/gcloud + gcsfuse (reference ``GcsStore``
    ``sky/data/storage.py:1725`` + ``mounting_utils.py:25-245``)."""

    store_type = StoreType.GCS

    def uri(self) -> str:
        return f'gs://{self.name}'

    def ensure_bucket(self) -> None:
        # ``name`` may carry a subpath ('bucket/sub'); only the bucket
        # itself is created.
        bucket = f'gs://{self.name.split("/", 1)[0]}'
        rc = subprocess.run(['gsutil', 'ls', '-b', bucket],
                            capture_output=True, check=False).returncode
        if rc == 0:
            return
        proc = subprocess.run(['gsutil', 'mb', bucket],
                              capture_output=True, text=True, check=False)
        if proc.returncode != 0:
            raise exceptions.StorageBucketCreateError(
                f'gsutil mb {bucket} failed: {proc.stderr[-500:]}')

    def upload(self) -> None:
        if not self.source:
            return
        src = os.path.expanduser(self.source)
        proc = subprocess.run(
            ['gsutil', '-m', 'rsync', '-r', src, self.uri()],
            capture_output=True, text=True, check=False)
        if proc.returncode != 0:
            raise exceptions.StorageUploadError(
                f'gsutil rsync to {self.uri()} failed: '
                f'{proc.stderr[-500:]}')

    def delete_bucket(self) -> None:
        subprocess.run(['gsutil', '-m', 'rm', '-r', self.uri()],
                       capture_output=True, check=False)

    def make_download_command(self, dst: str) -> str:
        from skypilot_tpu.data.cloud_stores import _q
        q_dst = _q(dst)
        q_uri = shlex.quote(self.uri())
        return (f'mkdir -p {q_dst} && '
                f'(gsutil -m rsync -r {q_uri} {q_dst} || '
                f'gcloud storage rsync --recursive {q_uri} {q_dst})')

    def make_mount_command(self, mount_path: str) -> str:
        """gcsfuse with implicit dirs; install-on-demand like the
        reference's mounting_utils."""
        from skypilot_tpu.data.cloud_stores import _q
        q_mp = _q(mount_path)
        install = (
            'which gcsfuse >/dev/null 2>&1 || '
            '(curl -fsSL https://github.com/GoogleCloudPlatform/gcsfuse'
            '/releases/download/v2.5.1/gcsfuse_2.5.1_amd64.deb '
            '-o /tmp/gcsfuse.deb && sudo dpkg -i /tmp/gcsfuse.deb)')
        mount = (f'mkdir -p {q_mp} && '
                 f'mountpoint -q {q_mp} || '
                 f'gcsfuse --implicit-dirs {shlex.quote(self.name)} {q_mp}')
        return f'{install} && {mount}'


class S3Store(AbstractStore):
    """S3 via aws cli (kept for parity; TPU workloads live on GCS)."""

    store_type = StoreType.S3

    def uri(self) -> str:
        return f's3://{self.name}'

    def ensure_bucket(self) -> None:
        rc = subprocess.run(
            ['aws', 's3api', 'head-bucket', '--bucket', self.name],
            capture_output=True, check=False).returncode
        if rc == 0:
            return
        proc = subprocess.run(['aws', 's3', 'mb', self.uri()],
                              capture_output=True, text=True, check=False)
        if proc.returncode != 0:
            raise exceptions.StorageBucketCreateError(
                f'aws s3 mb {self.uri()} failed: {proc.stderr[-500:]}')

    def upload(self) -> None:
        if not self.source:
            return
        proc = subprocess.run(
            ['aws', 's3', 'sync', os.path.expanduser(self.source),
             self.uri()],
            capture_output=True, text=True, check=False)
        if proc.returncode != 0:
            raise exceptions.StorageUploadError(
                f'aws s3 sync failed: {proc.stderr[-500:]}')

    def delete_bucket(self) -> None:
        subprocess.run(['aws', 's3', 'rb', '--force', self.uri()],
                       capture_output=True, check=False)

    def make_download_command(self, dst: str) -> str:
        from skypilot_tpu.data.cloud_stores import _q
        q_dst = _q(dst)
        return (f'mkdir -p {q_dst} && aws s3 sync '
                f'{shlex.quote(self.uri())} {q_dst}')

    def make_mount_command(self, mount_path: str) -> str:
        from skypilot_tpu.data.cloud_stores import _q
        q_mp = _q(mount_path)
        return (f'mkdir -p {q_mp} && '
                f'mountpoint -q {q_mp} || '
                f'goofys {shlex.quote(self.name)} {q_mp}')


class R2Store(AbstractStore):
    """Cloudflare R2 via the aws cli against the R2 endpoint (reference
    ``R2Store`` ``sky/data/storage.py:3071``). The endpoint comes from
    the ``R2_ENDPOINT`` env var (``https://<account>.r2.cloudflarestorage
    .com``), credentials from the standard aws config chain."""

    store_type = StoreType.R2

    @staticmethod
    def _endpoint_args() -> List[str]:
        endpoint = os.environ.get('R2_ENDPOINT')
        if not endpoint:
            raise exceptions.StorageSpecError(
                'R2 store needs the R2_ENDPOINT env var '
                '(https://<account>.r2.cloudflarestorage.com)')
        return ['--endpoint-url', endpoint]

    def uri(self) -> str:
        return f'r2://{self.name}'

    def _s3_uri(self) -> str:
        return f's3://{self.name}'

    def ensure_bucket(self) -> None:
        ep = self._endpoint_args()
        rc = subprocess.run(
            ['aws', 's3api', 'head-bucket', '--bucket',
             self.name.split('/', 1)[0]] + ep,
            capture_output=True, check=False).returncode
        if rc == 0:
            return
        proc = subprocess.run(
            ['aws', 's3', 'mb', f's3://{self.name.split("/", 1)[0]}'] + ep,
            capture_output=True, text=True, check=False)
        if proc.returncode != 0:
            raise exceptions.StorageBucketCreateError(
                f'aws s3 mb (r2) failed: {proc.stderr[-500:]}')

    def upload(self) -> None:
        if not self.source:
            return
        proc = subprocess.run(
            ['aws', 's3', 'sync', os.path.expanduser(self.source),
             self._s3_uri()] + self._endpoint_args(),
            capture_output=True, text=True, check=False)
        if proc.returncode != 0:
            raise exceptions.StorageUploadError(
                f'aws s3 sync (r2) failed: {proc.stderr[-500:]}')

    def delete_bucket(self) -> None:
        subprocess.run(['aws', 's3', 'rb', '--force', self._s3_uri()]
                       + self._endpoint_args(),
                       capture_output=True, check=False)

    def make_download_command(self, dst: str) -> str:
        from skypilot_tpu.data.cloud_stores import _q
        q_dst = _q(dst)
        # The endpoint is resolved CLIENT-side and inlined: cluster
        # hosts don't inherit the client's R2_ENDPOINT env.
        endpoint = self._endpoint_args()[1]
        return (f'mkdir -p {q_dst} && aws s3 sync '
                f'{shlex.quote(self._s3_uri())} {q_dst} '
                f'--endpoint-url {shlex.quote(endpoint)}')

    def make_mount_command(self, mount_path: str) -> str:
        raise exceptions.StorageSpecError(
            'R2 MOUNT mode is not supported; use COPY '
            '(goofys has no R2 endpoint support in this build)')


class LocalStore(AbstractStore):
    """A directory pretending to be a bucket: upload = copy in, mount =
    symlink. Survives cluster teardown (it lives in the client state
    dir), so checkpoint/recovery semantics are faithfully simulated."""

    store_type = StoreType.LOCAL

    def _bucket_dir(self) -> str:
        return os.path.join(common_utils.state_dir(), 'local_buckets',
                            self.name)

    def uri(self) -> str:
        return f'file://{self._bucket_dir()}'

    def ensure_bucket(self) -> None:
        os.makedirs(self._bucket_dir(), exist_ok=True)

    def upload(self) -> None:
        if not self.source:
            return
        src = os.path.expanduser(self.source)
        if not os.path.exists(src):
            raise exceptions.StorageUploadError(
                f'Source {self.source!r} does not exist.')
        if os.path.isdir(src):
            shutil.copytree(src, self._bucket_dir(), dirs_exist_ok=True)
        else:
            shutil.copy2(src, self._bucket_dir())

    def delete_bucket(self) -> None:
        shutil.rmtree(self._bucket_dir(), ignore_errors=True)

    def make_download_command(self, dst: str) -> str:
        # One implementation for file:// downloads (tilde-safe dst).
        from skypilot_tpu.data import cloud_stores
        return cloud_stores.make_download_command(self.uri(), dst)

    def make_mount_command(self, mount_path: str) -> str:
        from skypilot_tpu.data.cloud_stores import _q
        q = shlex.quote
        q_mp = _q(mount_path)
        bucket = self._bucket_dir()
        return (f'mkdir -p $(dirname {q_mp}) {q(bucket)} && '
                f'([ -L {q_mp} ] || [ -e {q_mp} ] || '
                f'ln -s {q(bucket)} {q_mp})')


class _CliGatedStore(AbstractStore):
    """Base for stores whose backing CLI/SDK may be absent in this
    environment (reference impls: ``sky/data/storage.py:2232`` Azure,
    ``:3517`` IBM COS, ``:3971`` OCI). All command GENERATION works
    without the CLI (remote clusters run the commands); operations the
    CLIENT must run locally (bucket create/upload/delete) check for the
    CLI and fail with an actionable install message."""

    cli: str = ''
    install_hint: str = ''

    def _require_cli(self, op: str) -> None:
        if shutil.which(self.cli) is None:
            raise exceptions.StorageError(
                f'{type(self).__name__}.{op} needs the {self.cli!r} CLI '
                f'which is not installed. {self.install_hint}')


class AzureBlobStore(_CliGatedStore):
    """Azure Blob via az CLI + blobfuse2 (reference ``AzureBlobStore``
    ``sky/data/storage.py:2232``). Name: 'account/container[/path]'."""

    store_type = StoreType.AZURE
    cli = 'az'
    install_hint = 'pip install azure-cli'

    def __init__(self, name: str, source: Optional[str] = None):
        name = self._normalize(name)
        super().__init__(name, source)
        if '/' not in name:
            raise exceptions.StorageSpecError(
                'Azure store name must be "account/container[/path]", '
                f'got {name!r}')
        self.account, rest = name.split('/', 1)
        parts = rest.split('/', 1)
        self.container = parts[0]
        self.path = parts[1] if len(parts) > 1 else ''

    @staticmethod
    def _normalize(name: str) -> str:
        """Accept the https URL and azure:// forms ``from_uri`` routes
        here and reduce them to 'account/container[/path]'."""
        if name.startswith('azure://'):
            name = name[len('azure://'):]
        if '.blob.core.windows.net' in name:
            name = name.split('://', 1)[-1]
            host, _, rest = name.partition('/')
            account = host.split('.blob.core.windows.net')[0]
            name = f'{account}/{rest}' if rest else account
        return name

    def uri(self) -> str:
        rest = self.name.split('/', 1)[1]
        return (f'https://{self.account}.blob.core.windows.net/{rest}')

    def ensure_bucket(self) -> None:
        self._require_cli('ensure_bucket')
        proc = subprocess.run(
            ['az', 'storage', 'container', 'create', '--name',
             self.container, '--account-name', self.account],
            capture_output=True, text=True, check=False)
        if proc.returncode != 0:
            raise exceptions.StorageBucketCreateError(
                f'az container create failed: {proc.stderr[-500:]}')

    def upload(self) -> None:
        if not self.source:
            return
        self._require_cli('upload')
        cmd = ['az', 'storage', 'blob', 'upload-batch', '--destination',
               self.container, '--account-name', self.account,
               '--source', os.path.expanduser(self.source)]
        if self.path:
            # sub-path prefix keeps multiple stores in one container
            # disjoint (job workdirs collide at the container root).
            cmd += ['--destination-path', self.path]
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              check=False)
        if proc.returncode != 0:
            raise exceptions.StorageUploadError(
                f'az blob upload-batch failed: {proc.stderr[-500:]}')

    def delete_bucket(self) -> None:
        self._require_cli('delete_bucket')
        subprocess.run(['az', 'storage', 'container', 'delete', '--name',
                        self.container, '--account-name', self.account],
                       capture_output=True, check=False)

    def make_download_command(self, dst: str) -> str:
        from skypilot_tpu.data.cloud_stores import _q
        q_dst = _q(dst)
        cmd = (f'mkdir -p {q_dst} && az storage blob download-batch '
               f'--destination {q_dst} --source '
               f'{shlex.quote(self.container)} --account-name '
               f'{shlex.quote(self.account)}')
        if self.path:
            cmd += f' --pattern {shlex.quote(self.path + "/*")}'
        return cmd

    def make_mount_command(self, mount_path: str) -> str:
        from skypilot_tpu.data.cloud_stores import _q
        q_mp = _q(mount_path)
        install = ('which blobfuse2 >/dev/null 2>&1 || '
                   'sudo apt-get install -y blobfuse2')
        mount = (f'mkdir -p {q_mp} && mountpoint -q {q_mp} || '
                 f'AZURE_STORAGE_ACCOUNT={shlex.quote(self.account)} '
                 f'blobfuse2 mount {q_mp} --container-name '
                 f'{shlex.quote(self.container)}')
        return f'{install} && {mount}'


class IbmCosStore(_CliGatedStore):
    """IBM Cloud Object Storage via rclone (reference ``IBMCosStore``
    ``sky/data/storage.py:3517``, which also mounts via rclone).
    Requires an ``[ibmcos]`` rclone remote configured on the host."""

    store_type = StoreType.IBM
    cli = 'rclone'
    install_hint = 'curl https://rclone.org/install.sh | sudo bash'

    def uri(self) -> str:
        return f'cos://{self.name}'

    def _remote(self) -> str:
        return f'ibmcos:{self.name}'

    def ensure_bucket(self) -> None:
        self._require_cli('ensure_bucket')
        proc = subprocess.run(['rclone', 'mkdir', self._remote()],
                              capture_output=True, text=True, check=False)
        if proc.returncode != 0:
            raise exceptions.StorageBucketCreateError(
                f'rclone mkdir {self._remote()} failed: '
                f'{proc.stderr[-500:]}')

    def upload(self) -> None:
        if not self.source:
            return
        self._require_cli('upload')
        proc = subprocess.run(
            ['rclone', 'sync', os.path.expanduser(self.source),
             self._remote()],
            capture_output=True, text=True, check=False)
        if proc.returncode != 0:
            raise exceptions.StorageUploadError(
                f'rclone sync to {self._remote()} failed: '
                f'{proc.stderr[-500:]}')

    def delete_bucket(self) -> None:
        self._require_cli('delete_bucket')
        subprocess.run(['rclone', 'purge', self._remote()],
                       capture_output=True, check=False)

    def make_download_command(self, dst: str) -> str:
        from skypilot_tpu.data.cloud_stores import _q
        q_dst = _q(dst)
        return (f'mkdir -p {q_dst} && rclone sync '
                f'{shlex.quote(self._remote())} {q_dst}')

    def make_mount_command(self, mount_path: str) -> str:
        from skypilot_tpu.data.cloud_stores import _q
        q_mp = _q(mount_path)
        return (f'mkdir -p {q_mp} && mountpoint -q {q_mp} || '
                f'rclone mount {shlex.quote(self._remote())} {q_mp} '
                f'--daemon --vfs-cache-mode writes')


class OciStore(_CliGatedStore):
    """OCI Object Storage via the oci CLI (reference ``OciStore``
    ``sky/data/storage.py:3971``); mounts via rclone's oci backend."""

    store_type = StoreType.OCI
    cli = 'oci'
    install_hint = 'pip install oci-cli'

    def __init__(self, name: str, source: Optional[str] = None):
        super().__init__(name, source)
        parts = name.split('/', 1)
        self.bucket = parts[0]
        self.path = parts[1] if len(parts) > 1 else ''

    def uri(self) -> str:
        return f'oci://{self.name}'

    def ensure_bucket(self) -> None:
        self._require_cli('ensure_bucket')
        proc = subprocess.run(
            ['oci', 'os', 'bucket', 'create', '--name', self.bucket],
            capture_output=True, text=True, check=False)
        if proc.returncode != 0:
            raise exceptions.StorageBucketCreateError(
                f'oci bucket create failed: {proc.stderr[-500:]}')

    def upload(self) -> None:
        if not self.source:
            return
        self._require_cli('upload')
        cmd = ['oci', 'os', 'object', 'bulk-upload', '--bucket-name',
               self.bucket, '--src-dir',
               os.path.expanduser(self.source), '--overwrite']
        if self.path:
            cmd += ['--object-prefix', self.path + '/']
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              check=False)
        if proc.returncode != 0:
            raise exceptions.StorageUploadError(
                f'oci bulk-upload failed: {proc.stderr[-500:]}')

    def delete_bucket(self) -> None:
        self._require_cli('delete_bucket')
        subprocess.run(['oci', 'os', 'bucket', 'delete', '--name',
                        self.bucket, '--force'],
                       capture_output=True, check=False)

    def make_download_command(self, dst: str) -> str:
        from skypilot_tpu.data.cloud_stores import _q
        q_dst = _q(dst)
        cmd = (f'mkdir -p {q_dst} && oci os object bulk-download '
               f'--bucket-name {shlex.quote(self.bucket)} '
               f'--download-dir {q_dst}')
        if self.path:
            cmd += f' --prefix {shlex.quote(self.path + "/")}'
        return cmd

    def make_mount_command(self, mount_path: str) -> str:
        from skypilot_tpu.data.cloud_stores import _q
        q_mp = _q(mount_path)
        return (f'mkdir -p {q_mp} && mountpoint -q {q_mp} || '
                f'rclone mount oci:{shlex.quote(self.name)} {q_mp} '
                f'--daemon --vfs-cache-mode writes')


_STORE_CLASSES = {
    StoreType.GCS: GcsStore,
    StoreType.S3: S3Store,
    StoreType.R2: R2Store,
    StoreType.AZURE: AzureBlobStore,
    StoreType.IBM: IbmCosStore,
    StoreType.OCI: OciStore,
    StoreType.LOCAL: LocalStore,
}


def make_store(store_type: StoreType, name: str,
               source: Optional[str] = None) -> AbstractStore:
    cls = _STORE_CLASSES.get(store_type)
    if cls is None:
        raise exceptions.StorageSpecError(
            f'Store {store_type.value} is not supported yet; supported: '
            f'{[t.value for t in _STORE_CLASSES]}')
    return cls(name, source)


class Storage:
    """User-facing storage object: name + optional source + stores.

    YAML form (reference-compatible)::

        file_mounts:
          /checkpoints:
            name: my-ckpt-bucket
            store: gcs        # or s3 / local
            mode: MOUNT
    """

    def __init__(self,
                 name: Optional[str] = None,
                 source: Optional[Union[str, List[str]]] = None,
                 stores: Optional[List[StoreType]] = None,
                 persistent: bool = True,
                 mode: StorageMode = StorageMode.MOUNT):
        if name is None and source is None:
            raise exceptions.StorageSpecError(
                'Storage needs a name or a source.')
        if name is None:
            base = os.path.basename(str(source).rstrip('/')) or 'storage'
            name = f'skytpu-{common_utils.get_user_hash()}-{base}'.lower()
        self.name = name
        self.source = source if not isinstance(source, list) else None
        self.persistent = persistent
        self.mode = mode
        self.stores: Dict[StoreType, AbstractStore] = {}
        for st in (stores or [StoreType.GCS]):
            self.add_store(st)

    @classmethod
    def from_yaml_config(cls, config: Dict[str, Any]) -> 'Storage':
        mode = StorageMode(config.get('mode', 'MOUNT').upper())
        store = config.get('store', 'gcs')
        return cls(name=config.get('name'),
                   source=config.get('source'),
                   stores=[StoreType.from_str(store)],
                   persistent=config.get('persistent', True),
                   mode=mode)

    def add_store(self, store_type: Union[str, StoreType]) -> AbstractStore:
        if isinstance(store_type, str):
            store_type = StoreType.from_str(store_type)
        if store_type in self.stores:
            return self.stores[store_type]
        cls = _STORE_CLASSES.get(store_type)
        if cls is None:
            raise exceptions.StorageSpecError(
                f'Store {store_type} not supported yet.')
        store = cls(self.name, self.source)
        self.stores[store_type] = store
        return store

    @property
    def primary_store(self) -> AbstractStore:
        return next(iter(self.stores.values()))

    def sync_to_stores(self) -> None:
        """Create buckets + upload source; record in global state."""
        for store in self.stores.values():
            store.ensure_bucket()
            try:
                store.upload()
            except exceptions.StorageUploadError:
                global_state.add_or_update_storage(
                    self.name, self._handle(),
                    global_state.StorageStatus.UPLOAD_FAILED)
                raise
        global_state.add_or_update_storage(
            self.name, self._handle(), global_state.StorageStatus.READY)

    def _handle(self) -> Dict[str, Any]:
        return {
            'name': self.name,
            'source': self.source,
            'stores': [t.value for t in self.stores],
            'mode': self.mode.value,
            'persistent': self.persistent,
        }

    def delete(self) -> None:
        for store in self.stores.values():
            store.delete_bucket()
        global_state.remove_storage(self.name)
