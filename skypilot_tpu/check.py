"""Credentials probe: which clouds are usable (reference ``sky/check.py:19``,
``get_cached_enabled_clouds_or_refresh`` ``:164``)."""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from skypilot_tpu import clouds as clouds_lib
from skypilot_tpu import config as config_lib
from skypilot_tpu import global_state


def check(quiet: bool = False) -> List[str]:
    """Probe every registered cloud; cache and return the enabled list."""
    allowed: Optional[List[str]] = config_lib.get_nested(
        ('allowed_clouds',))
    results: Dict[str, Tuple[bool, Optional[str]]] = {}
    for name, cls in sorted(clouds_lib.CLOUD_REGISTRY.items()):
        if allowed is not None and name not in [a.lower() for a in allowed]:
            continue
        try:
            results[name] = cls.check_credentials()
        except Exception as e:  # pylint: disable=broad-except
            results[name] = (False, f'{type(e).__name__}: {e}')
    enabled = [name for name, (ok, _) in results.items() if ok]
    global_state.set_enabled_clouds(enabled)
    if not quiet:
        for name, (ok, reason) in results.items():
            mark = 'enabled' if ok else f'disabled: {reason}'
            print(f'  {name}: {mark}')
    return enabled


def get_cached_enabled_clouds_or_refresh() -> List[str]:
    enabled = global_state.get_enabled_clouds()
    if not enabled:
        enabled = check(quiet=True)
    return enabled
