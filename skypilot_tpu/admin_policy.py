"""Pluggable admin policy applied to every request before execution.

Role of reference ``sky/admin_policy.py`` + ``admin_policy_utils.apply``
(``sky/execution.py:172-180``): the config key ``admin_policy`` names a
``module.path:ClassName`` whose ``validate_and_mutate(UserRequest)``
returns a mutated request or raises to reject.
"""
from __future__ import annotations

import dataclasses
import importlib
from typing import Optional

from skypilot_tpu import config as config_lib
from skypilot_tpu import exceptions
from skypilot_tpu.dag import Dag


@dataclasses.dataclass
class UserRequest:
    dag: Dag
    config: dict


@dataclasses.dataclass
class MutatedUserRequest:
    dag: Dag
    config: dict


class AdminPolicy:
    """Subclass and point the ``admin_policy`` config key at it."""

    @classmethod
    def validate_and_mutate(cls, request: UserRequest
                            ) -> MutatedUserRequest:
        raise NotImplementedError


def _load_policy() -> Optional[type]:
    spec = config_lib.get_nested(('admin_policy',))
    if not spec:
        return None
    module_path, _, class_name = spec.partition(':')
    if not class_name:
        module_path, _, class_name = spec.rpartition('.')
    try:
        module = importlib.import_module(module_path)
        return getattr(module, class_name)
    except (ImportError, AttributeError) as e:
        raise exceptions.UserRequestRejectedByPolicy(
            f'Cannot load admin policy {spec!r}: {e}') from e


def apply(dag: Dag) -> Dag:
    policy = _load_policy()
    if policy is None:
        return dag
    request = UserRequest(dag=dag, config=config_lib.to_dict())
    try:
        mutated = policy.validate_and_mutate(request)
    except exceptions.UserRequestRejectedByPolicy:
        raise
    except Exception as e:  # pylint: disable=broad-except
        raise exceptions.UserRequestRejectedByPolicy(
            f'Admin policy rejected the request: {e}') from e
    return mutated.dag
