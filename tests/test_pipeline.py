"""Pipeline parallelism on the virtual 8-device CPU mesh: GPipe schedule
equivalence (forward + gradients) and trainer integration at pp>1."""
import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu.models import configs
from skypilot_tpu.parallel import mesh as mesh_lib
from skypilot_tpu.parallel.pipeline import pipeline_layers
from skypilot_tpu.train.trainer import TrainConfig, Trainer

pytestmark = pytest.mark.slow


def _mesh(pp: int, fsdp: int = 1, tp: int = 1) -> jax.sharding.Mesh:
    spec = mesh_lib.MeshSpec(pp=pp, fsdp=fsdp, tp=tp,
                             dp=8 // (pp * fsdp * tp))
    return mesh_lib.make_mesh(spec)


def _toy_stack(n_layers=4, d=16, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 2)
    return {
        'w': jax.random.normal(ks[0], (n_layers, d, d)) * 0.3,
        'b': jax.random.normal(ks[1], (n_layers, d)) * 0.1,
    }


def _stage_fn(params, x):
    def one(carry, layer):
        return jnp.tanh(carry @ layer['w'] + layer['b']), None
    out, _ = jax.lax.scan(one, x, params)
    return out


def _sequential(params, x):
    return _stage_fn(params, x)


@pytest.mark.parametrize('pp,n_micro', [(2, 2), (2, 4), (4, 4)])
def test_forward_matches_sequential(pp, n_micro):
    mesh = _mesh(pp)
    params = _toy_stack()
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 4, 16))
    ref = _sequential(params, x)
    with mesh:
        out = jax.jit(functools.partial(
            pipeline_layers, stage_fn=_stage_fn, mesh=mesh,
            num_microbatches=n_micro))(params, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_gradients_match_sequential():
    mesh = _mesh(pp=2)
    params = _toy_stack()
    x = jax.random.normal(jax.random.PRNGKey(2), (4, 4, 16))

    def loss_pipe(p):
        return jnp.sum(pipeline_layers(p, x, _stage_fn, mesh,
                                       num_microbatches=2) ** 2)

    def loss_seq(p):
        return jnp.sum(_sequential(p, x) ** 2)

    with mesh:
        g_pipe = jax.jit(jax.grad(loss_pipe))(params)
    g_seq = jax.grad(loss_seq)(params)
    for key in ('w', 'b'):
        np.testing.assert_allclose(np.asarray(g_pipe[key]),
                                   np.asarray(g_seq[key]),
                                   rtol=1e-4, atol=1e-4)


def test_batch_divisibility_enforced():
    mesh = _mesh(pp=2)
    params = _toy_stack()
    x = jnp.zeros((3, 4, 16))
    with mesh, pytest.raises(ValueError, match='microbatch'):
        pipeline_layers(params, x, _stage_fn, mesh, num_microbatches=2)


class TestTrainerIntegration:

    def _loss_after_step(self, pp: int) -> float:
        cfg = dataclasses.replace(configs.TINY, remat='none')
        trainer = Trainer(
            cfg,
            mesh_spec=mesh_lib.MeshSpec(pp=pp, dp=1, fsdp=4 // pp, sp=1,
                                        tp=2),
            train_config=TrainConfig(warmup_steps=1, total_steps=4,
                                     attn_impl='xla'))
        state = trainer.init(jax.random.PRNGKey(0))
        rng = np.random.RandomState(0)
        data = rng.randint(0, 250, size=(8, 17))
        batch = {'inputs': jnp.asarray(data[:, :-1], jnp.int32),
                 'targets': jnp.asarray(data[:, 1:], jnp.int32)}
        _, metrics = trainer.step(state, batch)
        return float(metrics['loss'])

    def test_pp2_matches_pp1_loss(self):
        """Same data + init: the pipelined layer stack must produce the
        same training loss as the plain scan."""
        loss_pp = self._loss_after_step(pp=2)
        loss_ref = self._loss_after_step(pp=1)
        assert abs(loss_pp - loss_ref) < 2e-2, (loss_pp, loss_ref)

    def test_params_sharded_over_stages(self):
        trainer = Trainer(configs.TINY,
                          mesh_spec=mesh_lib.MeshSpec(pp=2, fsdp=2, tp=2))
        state = trainer.init(jax.random.PRNGKey(0))
        spec = state.params['layers']['wq'].sharding.spec
        assert 'pp' in str(spec)


def test_with_aux_plumbs_scalar():
    """stage_fn returning (y, aux): pipeline returns the mean over
    (stage, microbatch) contributions."""
    mesh = _mesh(pp=2)
    params = _toy_stack()
    x = jax.random.normal(jax.random.PRNGKey(2), (8, 4, 16))

    def stage_aux(p, xx):
        return _stage_fn(p, xx), jnp.float32(2.5)

    with mesh:
        out, aux = jax.jit(functools.partial(
            pipeline_layers, stage_fn=stage_aux, mesh=mesh,
            with_aux=True))(params, x)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(_sequential(params, x)),
                               rtol=1e-5, atol=1e-5)
    # every live (stage, mb) contributes 2.5 -> mean is 2.5
    np.testing.assert_allclose(float(aux), 2.5, rtol=1e-6)


class TestMoePP:
    """MoE + pipeline (round-3 gap: aux loss now flows through the
    schedule)."""

    def test_moe_pp2_train_step(self):
        cfg = dataclasses.replace(configs.TINY_MOE, n_layers=4)
        trainer = Trainer(cfg,
                          mesh_spec=mesh_lib.MeshSpec(pp=2, dp=4),
                          train_config=TrainConfig(warmup_steps=1,
                                                   total_steps=10))
        state = trainer.init(jax.random.PRNGKey(0))
        batch = {'inputs': jnp.ones((4, 8), jnp.int32),
                 'targets': jnp.ones((4, 8), jnp.int32)}
        state, metrics = trainer.step(state, batch)
        assert np.isfinite(float(metrics['loss']))
        # the aux loss actually reached the metrics (MoE balancing)
        assert float(metrics['moe_aux_loss']) > 0.0

    def test_moe_pp_aux_matches_no_pp(self):
        """Same params: pp=2 aux == mean of per-MICROBATCH aux (the
        balancing loss is nonlinear in batch composition, so the
        reference must use the same mb split the pipeline does)."""
        from skypilot_tpu.models import llama
        cfg = dataclasses.replace(configs.TINY_MOE, n_layers=4)
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        toks = jnp.arange(32).reshape(4, 8) % cfg.vocab_size
        # pp=2 defaults to 2 microbatches of 2 rows each
        auxs = [llama.forward(params, toks[i:i + 2], cfg,
                              return_aux=True)[2] for i in (0, 2)]
        aux_ref = jnp.mean(jnp.stack(auxs))
        mesh = _mesh(pp=2)
        with mesh:
            shardings = mesh_lib.tree_shardings(
                llama.param_logical_axes(cfg), mesh, shapes=params)
            sharded = jax.device_put(params, shardings)
            _, _, aux_pp = jax.jit(
                lambda p, t: llama.forward(p, t, cfg, return_aux=True)
            )(sharded, toks)
        np.testing.assert_allclose(float(aux_pp), float(aux_ref),
                                   rtol=2e-2)


class TestDecodePP:
    """pp-sharded decode: forward's cached path chains through the
    stages instead of all-gathering layers (round-3 gap)."""

    def test_cached_forward_pp2_matches_pp1(self):
        from skypilot_tpu.models import llama
        cfg = dataclasses.replace(configs.TINY, n_layers=4)
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        toks = (jnp.arange(12).reshape(2, 6) % cfg.vocab_size) + 1

        def greedy_two_steps(params, mesh=None):
            ctx = mesh if mesh is not None else jax.sharding.Mesh(
                np.array(jax.devices()[:1]).reshape(1, 1, 1, 1, 1, 1),
                mesh_lib.MESH_AXES)
            cache = llama.KVCache.create(cfg, batch=2, max_seq=32)
            if mesh is not None:
                p_sh = mesh_lib.tree_shardings(
                    llama.param_logical_axes(cfg), mesh, shapes=params)
                c_sh = mesh_lib.tree_shardings(
                    llama.cache_logical_axes(), mesh, shapes=cache)
                params = jax.device_put(params, p_sh)
                cache = jax.device_put(cache, c_sh)
            outs = []
            with ctx:
                logits, cache = jax.jit(functools.partial(
                    llama.forward, cfg=cfg, attn_impl='xla'))(
                        params, toks, cache=cache)
                nxt = jnp.argmax(logits[:, -1], -1).astype(jnp.int32)
                outs.append(np.asarray(nxt))
                for _ in range(3):
                    logits, cache = jax.jit(functools.partial(
                        llama.forward, cfg=cfg, attn_impl='xla'))(
                            params, nxt[:, None], cache=cache)
                    nxt = jnp.argmax(logits[:, -1], -1).astype(jnp.int32)
                    outs.append(np.asarray(nxt))
            return np.stack(outs)

        ref = greedy_two_steps(params)
        got = greedy_two_steps(params, _mesh(pp=2))
        np.testing.assert_array_equal(got, ref)


def test_pp_with_fsdp_inside_stage():
    """pp x fsdp: collectives inside the stage body force the
    unconditional-bubble path; results still match sequential."""
    cfg = dataclasses.replace(configs.TINY, n_layers=4)
    trainer = Trainer(cfg,
                      mesh_spec=mesh_lib.MeshSpec(pp=2, fsdp=2, dp=2),
                      train_config=TrainConfig(warmup_steps=1,
                                               total_steps=10))
    ref = Trainer(cfg, mesh_spec=mesh_lib.MeshSpec(dp=8),
                  train_config=TrainConfig(warmup_steps=1,
                                           total_steps=10))
    batch = {'inputs': jnp.ones((8, 8), jnp.int32),
             'targets': jnp.ones((8, 8), jnp.int32)}
    s1 = trainer.init(jax.random.PRNGKey(0))
    s2 = ref.init(jax.random.PRNGKey(0))
    _, m1 = trainer.step(s1, batch)
    _, m2 = ref.step(s2, batch)
    np.testing.assert_allclose(float(m1['loss']), float(m2['loss']),
                               rtol=2e-2)


def test_pp_x_fsdp_bubble_skip_no_deadlock():
    """Round-5: the skip engages under pp x fsdp (the per-tick param
    all-gather is hoisted OUT of the cond so every rank runs the same
    collective schedule) — forward must match sequential, no rendezvous
    deadlock."""
    mesh = _mesh(pp=2, fsdp=2)
    params = _toy_stack()
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 4, 16))
    ref = _sequential(params, x)
    with mesh:
        out = jax.jit(functools.partial(
            pipeline_layers, stage_fn=_stage_fn, mesh=mesh,
            num_microbatches=2, skip_bubbles=True))(params, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_bubble_skip_saves_compute_pp_x_fsdp():
    """On the shared-core CPU mesh, skipped bubble ticks are visibly
    cheaper than computed ones: pp=4 with ONE microbatch is almost all
    bubbles (4 of 16 stage-ticks live, ~4x ideal ratio), so even a very
    generous 0.9 threshold with best-of-5 runs distinguishes
    skip-engaged (expected ~0.3-0.5) from skip-broken (~1.0) without
    flaking under CI load. (Static FLOP counts cannot test this — cost
    analysis sums both cond branches.)"""
    import time

    mesh = _mesh(pp=4, fsdp=2)
    params = _toy_stack(n_layers=4, d=512)
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 64, 512))

    def run(skip):
        fn = jax.jit(functools.partial(
            pipeline_layers, stage_fn=_stage_fn, mesh=mesh,
            num_microbatches=1, skip_bubbles=skip))
        with mesh:
            jax.block_until_ready(fn(params, x))      # compile
            best = float('inf')
            for _ in range(5):
                t0 = time.perf_counter()
                jax.block_until_ready(fn(params, x))
                best = min(best, time.perf_counter() - t0)
        return best

    t_skip, t_full = run(True), run(False)
    assert t_skip < 0.9 * t_full, (t_skip, t_full)
