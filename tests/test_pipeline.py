"""Pipeline parallelism on the virtual 8-device CPU mesh: GPipe schedule
equivalence (forward + gradients) and trainer integration at pp>1."""
import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu.models import configs
from skypilot_tpu.parallel import mesh as mesh_lib
from skypilot_tpu.parallel.pipeline import pipeline_layers
from skypilot_tpu.train.trainer import TrainConfig, Trainer

pytestmark = pytest.mark.slow


def _mesh(pp: int, fsdp: int = 1, tp: int = 1) -> jax.sharding.Mesh:
    spec = mesh_lib.MeshSpec(pp=pp, fsdp=fsdp, tp=tp,
                             dp=8 // (pp * fsdp * tp))
    return mesh_lib.make_mesh(spec)


def _toy_stack(n_layers=4, d=16, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 2)
    return {
        'w': jax.random.normal(ks[0], (n_layers, d, d)) * 0.3,
        'b': jax.random.normal(ks[1], (n_layers, d)) * 0.1,
    }


def _stage_fn(params, x):
    def one(carry, layer):
        return jnp.tanh(carry @ layer['w'] + layer['b']), None
    out, _ = jax.lax.scan(one, x, params)
    return out


def _sequential(params, x):
    return _stage_fn(params, x)


@pytest.mark.parametrize('pp,n_micro', [(2, 2), (2, 4), (4, 4)])
def test_forward_matches_sequential(pp, n_micro):
    mesh = _mesh(pp)
    params = _toy_stack()
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 4, 16))
    ref = _sequential(params, x)
    with mesh:
        out = jax.jit(functools.partial(
            pipeline_layers, stage_fn=_stage_fn, mesh=mesh,
            num_microbatches=n_micro))(params, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_gradients_match_sequential():
    mesh = _mesh(pp=2)
    params = _toy_stack()
    x = jax.random.normal(jax.random.PRNGKey(2), (4, 4, 16))

    def loss_pipe(p):
        return jnp.sum(pipeline_layers(p, x, _stage_fn, mesh,
                                       num_microbatches=2) ** 2)

    def loss_seq(p):
        return jnp.sum(_sequential(p, x) ** 2)

    with mesh:
        g_pipe = jax.jit(jax.grad(loss_pipe))(params)
    g_seq = jax.grad(loss_seq)(params)
    for key in ('w', 'b'):
        np.testing.assert_allclose(np.asarray(g_pipe[key]),
                                   np.asarray(g_seq[key]),
                                   rtol=1e-4, atol=1e-4)


def test_batch_divisibility_enforced():
    mesh = _mesh(pp=2)
    params = _toy_stack()
    x = jnp.zeros((3, 4, 16))
    with mesh, pytest.raises(ValueError, match='microbatch'):
        pipeline_layers(params, x, _stage_fn, mesh, num_microbatches=2)


class TestTrainerIntegration:

    def _loss_after_step(self, pp: int) -> float:
        cfg = dataclasses.replace(configs.TINY, remat='none')
        trainer = Trainer(
            cfg,
            mesh_spec=mesh_lib.MeshSpec(pp=pp, dp=1, fsdp=4 // pp, sp=1,
                                        tp=2),
            train_config=TrainConfig(warmup_steps=1, total_steps=4,
                                     attn_impl='xla'))
        state = trainer.init(jax.random.PRNGKey(0))
        rng = np.random.RandomState(0)
        data = rng.randint(0, 250, size=(8, 17))
        batch = {'inputs': jnp.asarray(data[:, :-1], jnp.int32),
                 'targets': jnp.asarray(data[:, 1:], jnp.int32)}
        _, metrics = trainer.step(state, batch)
        return float(metrics['loss'])

    def test_pp2_matches_pp1_loss(self):
        """Same data + init: the pipelined layer stack must produce the
        same training loss as the plain scan."""
        loss_pp = self._loss_after_step(pp=2)
        loss_ref = self._loss_after_step(pp=1)
        assert abs(loss_pp - loss_ref) < 2e-2, (loss_pp, loss_ref)

    def test_params_sharded_over_stages(self):
        trainer = Trainer(configs.TINY,
                          mesh_spec=mesh_lib.MeshSpec(pp=2, fsdp=2, tp=2))
        state = trainer.init(jax.random.PRNGKey(0))
        spec = state.params['layers']['wq'].sharding.spec
        assert 'pp' in str(spec)
