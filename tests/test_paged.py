"""Paged KV cache engine: equivalence vs the slot engine, prefix
caching, chunked prefill, pool accounting (VERDICT r4 task 3; reference
capability anchor: vLLM paged attention, llm/vllm/README.md:10)."""
import jax
import numpy as np
import pytest

from skypilot_tpu.inference.engine import InferenceEngine
from skypilot_tpu.inference.paged import (PageAllocator,
                                          PagedInferenceEngine)
from skypilot_tpu.models import configs, llama

# Compile-heavy (jit of full models): slow tier — the fast sweep is
# the orchestration layer (SURVEY §4 offline tier analog).
pytestmark = pytest.mark.slow


@pytest.fixture(scope='module')
def setup():
    cfg = configs.TINY
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def _greedy_slot_engine(cfg, params, prompts, n_new, **kw):
    eng = InferenceEngine(cfg, params, max_batch=4, max_seq=256,
                          attn_impl='xla', **kw)
    rids = [eng.add_request(p, max_new_tokens=n_new) for p in prompts]
    done = eng.run_to_completion(horizon=4)
    return [done[r].output for r in rids]


class TestPagedEquivalence:

    def test_greedy_matches_slot_engine(self, setup):
        cfg, params = setup
        prompts = [[3, 1, 4, 1, 5], [2, 7, 1, 8, 2, 8, 1, 8], [9]]
        want = _greedy_slot_engine(cfg, params, prompts, 8)
        eng = PagedInferenceEngine(cfg, params, max_batch=4, max_seq=256,
                                   page_size=8, attn_impl='xla')
        rids = [eng.add_request(p, max_new_tokens=8) for p in prompts]
        done = eng.run_to_completion(horizon=4)
        got = [done[r].output for r in rids]
        assert got == want, (got, want)

    def test_long_prompt_chunked_prefill(self, setup):
        """Prompt far longer than the chunk size prefills in pieces and
        still matches the slot engine."""
        cfg, params = setup
        prompt = [(i * 7 + 3) % cfg.vocab_size for i in range(150)]
        want = _greedy_slot_engine(cfg, params, [prompt], 6)[0]
        eng = PagedInferenceEngine(cfg, params, max_batch=2, max_seq=256,
                                   page_size=8, chunk=32,
                                   attn_impl='xla')
        rid = eng.add_request(prompt, max_new_tokens=6)
        done = eng.run_to_completion(horizon=4)
        assert eng.chunks_prefilled >= 5       # 150/32 -> 5 chunks
        assert done[rid].output == want

    def test_int8_paged_generates(self, setup):
        cfg, params = setup
        eng = PagedInferenceEngine(cfg, params, max_batch=2, max_seq=128,
                                   page_size=8, quantize='int8',
                                   attn_impl='xla')
        assert eng.cache.quantized
        rid = eng.add_request(list(range(1, 12)), max_new_tokens=6)
        done = eng.run_to_completion(horizon=4)
        assert len(done[rid].output) == 6

    def test_sampling_runs(self, setup):
        cfg, params = setup
        eng = PagedInferenceEngine(cfg, params, max_batch=2, max_seq=128,
                                   page_size=8, attn_impl='xla')
        rid = eng.add_request([1, 2, 3], max_new_tokens=16,
                              temperature=1.5, top_k=40)
        done = eng.run_to_completion(horizon=4)
        assert len(set(done[rid].output)) > 1

    def test_top_p_and_stop(self, setup):
        """top_p -> 0 equals greedy under hot sampling; stop sequences
        finish early with the matched suffix trimmed (paged engine)."""
        cfg, params = setup
        eng = PagedInferenceEngine(cfg, params, max_batch=2, max_seq=128,
                                   page_size=8, attn_impl='xla')
        g = eng.add_request([3, 1, 4], max_new_tokens=12)
        n = eng.add_request([3, 1, 4], max_new_tokens=12,
                            temperature=2.0, top_p=1e-6)
        done = eng.run_to_completion(horizon=4)
        assert done[g].output == done[n].output
        full = done[g].output
        eng2 = PagedInferenceEngine(cfg, params, max_batch=2,
                                    max_seq=128, page_size=8,
                                    attn_impl='xla')
        rid = eng2.add_request([3, 1, 4], max_new_tokens=12,
                               stop=[full[2:4]])
        req = eng2.run_to_completion(horizon=4)[rid]
        assert req.stop_hit and req.output == full[:2]


class TestPrefixCache:

    def test_shared_prefix_reuses_pages(self, setup):
        """Second request with the same long prefix prefills fewer
        chunks (the shared pages are not recomputed) and still decodes
        identically."""
        cfg, params = setup
        shared = [(i * 5 + 2) % cfg.vocab_size for i in range(64)]
        p1 = shared + [11, 12]
        p2 = shared + [13, 14, 15]
        want = _greedy_slot_engine(cfg, params, [p2], 6)[0]

        eng = PagedInferenceEngine(cfg, params, max_batch=1, max_seq=256,
                                   page_size=8, chunk=16,
                                   attn_impl='xla')
        r1 = eng.add_request(p1, max_new_tokens=4)
        eng.run_to_completion(horizon=4)
        chunks_before = eng.chunks_prefilled
        assert eng.alloc.prefix_misses == 1
        r2 = eng.add_request(p2, max_new_tokens=6)
        done = eng.run_to_completion(horizon=4)
        delta = eng.chunks_prefilled - chunks_before
        # 64 shared tokens = 8 full pages reused; only the 3-token tail
        # prefills -> exactly 1 chunk vs 5 without reuse.
        assert eng.alloc.prefix_hits == 1
        assert delta == 1, delta
        assert done[r2].output == want

    def test_prefix_hit_byte_identical_to_cold(self, setup):
        """A prefix-cache hit must emit byte-identical output to a cold
        run of the same request. Resuming chunked prefill at an
        arbitrary page boundary (instead of the cold run's chunk grid)
        regroups cached_attention's two softmax partial sums, and the
        few-ULP denominator drift flips greedy argmax on near-tie
        logits — the engine quantizes resume points to chunk-multiple
        boundaries to keep both paths bitwise equal. Prompt [14]+S+[8]
        below is a known near-tie under TINY init: without the
        quantization its hit-path bytes diverge from cold."""
        cfg, _ = setup
        shared = [7 + (j % 50) for j in range(40)]
        for lead, tail in [(11, 5), (14, 8)]:
            prompt = [lead] + shared + [tail]
            eng = PagedInferenceEngine(cfg, max_batch=2, max_seq=256)
            r1 = eng.add_request(list(prompt), max_new_tokens=6)
            cold = eng.run_to_completion()[r1].output
            r2 = eng.add_request(list(prompt), max_new_tokens=6)
            hit = eng.run_to_completion()[r2].output
            assert eng.alloc.prefix_hits == 1
            assert hit == cold, (lead, hit, cold)

    def test_aligned_prefix_hit_keeps_reuse_and_identity(self, setup):
        """When the matched prefix covers whole chunk multiples, resume
        quantization keeps the pages: chunk work drops AND the output
        stays byte-identical to the cold run."""
        cfg, params = setup
        shared = [(i * 5 + 2) % cfg.vocab_size for i in range(64)]
        prompt = shared + [21, 22, 23]
        eng = PagedInferenceEngine(cfg, params, max_batch=1, max_seq=256,
                                   page_size=8, chunk=16,
                                   attn_impl='xla')
        r1 = eng.add_request(list(prompt), max_new_tokens=6)
        cold = eng.run_to_completion(horizon=4)[r1].output
        before = eng.chunks_prefilled
        r2 = eng.add_request(list(prompt), max_new_tokens=6)
        done = eng.run_to_completion(horizon=4)
        # 64 shared tokens = 4 chunk-aligned boundaries survive
        # quantization; only the tail re-prefills.
        assert eng.chunks_prefilled - before <= 1
        assert done[r2].output == cold

    def test_prefix_pages_survive_slot_free_until_pressure(self, setup):
        cfg, params = setup
        eng = PagedInferenceEngine(cfg, params, max_batch=1, max_seq=128,
                                   page_size=8, attn_impl='xla')
        prompt = list(range(1, 26))            # 3 full pages
        eng.add_request(prompt, max_new_tokens=2)
        eng.run_to_completion(horizon=2)
        stats = eng.memory_stats()
        assert stats['pages_retained_prefix'] >= 3
        # a re-submit hits the retained pages
        eng.add_request(prompt + [30], max_new_tokens=2)
        eng.run_to_completion(horizon=2)
        assert eng.alloc.prefix_hits == 1

    def test_memory_stats_accounting(self, setup):
        cfg, params = setup
        eng = PagedInferenceEngine(cfg, params, max_batch=2, max_seq=128,
                                   page_size=8, attn_impl='xla')
        s0 = eng.memory_stats()
        assert s0['pages_in_use'] == 0
        assert s0['pool_bytes'] > 0
        eng.add_request(list(range(1, 20)), max_new_tokens=64)
        eng.step(horizon=2)
        s1 = eng.memory_stats()
        assert s1['pages_in_use'] >= 3         # 19 tokens / 8 per page
        eng.run_to_completion(horizon=8)
        s2 = eng.memory_stats()
        assert s2['pages_in_use'] == 0         # all freed or retained
        assert (s2['pages_free'] + s2['pages_retained_prefix']
                == s2['n_pages'] - 1)


class TestAllocator:

    def test_exhaustion_and_lru_eviction(self):
        a = PageAllocator(n_pages=5, page_size=4)     # 4 usable
        pages = [a.alloc() for _ in range(4)]
        with pytest.raises(MemoryError):
            a.alloc()
        # register 2 pages as prefix pages, then free them -> retained
        a.page_hash[pages[0]] = b'h0'
        a.by_hash[b'h0'] = pages[0]
        a.page_hash[pages[1]] = b'h1'
        a.by_hash[b'h1'] = pages[1]
        a.release(pages[0])
        a.release(pages[1])
        assert a.available == 2
        # allocation evicts the LRU retained page (pages[0] first)
        p = a.alloc()
        assert p == pages[0]
        assert b'h0' not in a.by_hash          # hash forgotten
        assert a.by_hash[b'h1'] == pages[1]    # newer one survives

    def test_refcount_sharing(self):
        a = PageAllocator(n_pages=4, page_size=4)
        p = a.alloc()
        a.retain(p)
        a.release(p)
        assert a.refcount[p] == 1              # still held by one user
        a.release(p)
        assert p in a.free                     # unregistered -> free list


class TestPallasDecodeKernel:
    """Paged-attention Pallas kernel (interpret mode on CPU): the
    engine's pallas decode path matches the gather path exactly."""

    def test_pallas_decode_matches_gather(self, setup):
        cfg, params = setup
        prompts = [[3, 1, 4, 1, 5, 9, 2], [2, 7]]
        outs = {}
        for impl in ('gather', 'pallas'):
            eng = PagedInferenceEngine(cfg, params, max_batch=2,
                                       max_seq=64, page_size=8,
                                       attn_impl='xla',
                                       decode_impl=impl)
            rids = [eng.add_request(p, max_new_tokens=5)
                    for p in prompts]
            done = eng.run_to_completion(horizon=2)
            outs[impl] = [done[r].output for r in rids]
        assert outs['pallas'] == outs['gather'], outs

    def test_pallas_decode_int8(self, setup):
        cfg, params = setup
        eng = PagedInferenceEngine(cfg, params, max_batch=2, max_seq=64,
                                   page_size=8, quantize='int8',
                                   attn_impl='xla',
                                   decode_impl='pallas')
        rid = eng.add_request(list(range(1, 12)), max_new_tokens=4)
        done = eng.run_to_completion(horizon=2)
        assert len(done[rid].output) == 4


class TestContinuousAdmission:
    """Round-5: admission interleaves prefill chunks with decode (the
    wave-synchronous form stalled running requests for a whole wave)."""

    def test_active_request_decodes_between_chunks(self, setup):
        cfg, params = setup
        eng = PagedInferenceEngine(cfg, params, max_batch=2, max_seq=256,
                                   page_size=8, chunk=16,
                                   decode_impl='gather')
        # Request A fully admitted and decoding.
        a = eng.add_request(list(range(1, 20)), max_new_tokens=64)
        while eng._prefill_off or eng._queue:
            eng.step(horizon=1)
        # Long prompt B needs ~10 chunks; each step runs at most ONE
        # chunk and then decodes — A must gain tokens while B prefill
        # is still in flight (bounded TPOT during admission).
        eng.add_request(list(range(1, 160)), max_new_tokens=4)
        saw_interleave = False
        for _ in range(6):
            events = eng.step(horizon=2)
            if eng._prefill_off and any(rid == a for rid, _, _ in events):
                saw_interleave = True
        assert saw_interleave
        eng.run_to_completion(horizon=4)

    def test_preemption_by_recompute_matches_uninterrupted(self, setup):
        """Pool pressure preempts the newest request and recomputes it
        via prompt+output; the final output must equal an uninterrupted
        run."""
        cfg, params = setup
        ref = _greedy_slot_engine(cfg, params,
                                  [list(range(1, 30))], 24)[0]
        # Tiny pool: 2 slots' growth collides mid-decode.
        eng = PagedInferenceEngine(cfg, params, max_batch=2, max_seq=256,
                                   page_size=8, n_pages=12,
                                   decode_impl='gather')
        r1 = eng.add_request(list(range(1, 30)), max_new_tokens=24)
        r2 = eng.add_request(list(range(1, 30)), max_new_tokens=24)
        done = eng.run_to_completion(horizon=4)
        assert eng.preemptions >= 1
        assert done[r1].output == ref
        assert done[r2].output == ref

    def test_preemption_event_stream_complete(self, setup):
        """Every generated token must surface as a step() event even
        when pool pressure forces a pipeline drain + preemption (the
        serve layer streams from events; a dropped event is a lost
        streamed token or a hung client). Regression: the drain path
        once collected events into an aliased list and lost them."""
        cfg, params = setup
        eng = PagedInferenceEngine(cfg, params, max_batch=2, max_seq=256,
                                   page_size=8, n_pages=12,
                                   decode_impl='gather')
        r1 = eng.add_request(list(range(1, 30)), max_new_tokens=24)
        r2 = eng.add_request(list(range(1, 30)), max_new_tokens=24)
        events = []
        while eng.has_work() or eng._pending:
            events.extend(eng.step(horizon=4))
        assert eng.preemptions >= 1
        for rid in (r1, r2):
            streamed = [t for r, t, _ in events if r == rid]
            out = eng.get_finished(rid).output
            # A preempted request's regenerated tokens stream twice
            # (recompute); the final output must be a SUFFIX of the
            # stream and every output token must have been streamed.
            assert streamed[-len(out):] == out


class TestEarlyRecycle:
    """Host-known completion frees slots at ENQUEUE: a budget-bound
    request's slot recycles while its tail tokens are still riding the
    async pipeline. These pin the lifecycle contracts around that
    window (the serve loop and disconnecting clients both hit it)."""

    def _engine(self, cfg, params):
        eng = PagedInferenceEngine(cfg, params, max_batch=2,
                                   max_seq=256, page_size=8,
                                   n_pages=32, decode_impl='gather')
        # Pin the recycle WINDOW: on CPU every result is instantly
        # ready, so the opportunistic drain would collapse the lag
        # these tests exist to exercise.
        eng._eager_drain = False
        return eng

    def test_lagging_tail_tokens_surface(self, setup):
        cfg, params = setup
        eng = self._engine(cfg, params)
        rid = eng.add_request([1, 2, 3, 4] * 3, max_new_tokens=4)
        eng.step(horizon=8)            # prefill + covering decode call
        # Budget covered at enqueue: slot freed, tail still in flight.
        assert all(r is None for r in eng._slots)
        assert eng._pending
        assert eng.has_work()          # lagging request keeps it awake
        done = eng.run_to_completion(horizon=8)
        assert len(done[rid].output) == 4
        assert not eng.has_work() and not eng._lagging

    def test_cancel_in_recycle_window(self, setup):
        cfg, params = setup
        eng = self._engine(cfg, params)
        rid = eng.add_request([5, 6, 7, 8] * 3, max_new_tokens=4)
        eng.step(horizon=8)
        assert all(r is None for r in eng._slots)
        # Early-freed but unfinished: cancel must still find it (a
        # disconnecting client in this window once leaked the request
        # into _finished forever).
        assert eng.cancel(rid) is True
        eng.run_to_completion(horizon=8)
        assert eng.get_finished(rid) is None
        assert not eng.has_work() and not eng._lagging

    def test_stop_sequences_disable_early_free(self, setup):
        cfg, params = setup
        eng = self._engine(cfg, params)
        rid = eng.add_request([1, 2] * 4, max_new_tokens=4,
                              stop=[[99999]])
        eng.step(horizon=8)
        # Completion is data-dependent: the slot must NOT recycle early.
        assert any(r is not None for r in eng._slots)
        done = eng.run_to_completion(horizon=8)
        assert len(done[rid].output) == 4
