"""Full launch spine against the kubernetes provider via the kubectl
shim: `skytpu launch` provisions pods, ships the runtime, starts agentd,
and fans the job out with the gang env — no cluster, no mocks inside
skypilot_tpu itself (the shim sits at the kubectl binary boundary, the
same place a real cluster would).
"""
import os
import stat
import sys
import time

import pytest

import skypilot_tpu as sky
from skypilot_tpu import core, execution
from skypilot_tpu.task import Task

pytestmark = pytest.mark.usefixtures('tmp_state_dir')


@pytest.fixture()
def kubectl_shim(tmp_path, monkeypatch):
    shim_dir = tmp_path / 'bin'
    shim_dir.mkdir()
    shim = shim_dir / 'kubectl'
    src = os.path.join(os.path.dirname(__file__), 'kubectl_shim.py')
    shim.write_text(f'#!/bin/sh\nexec {sys.executable} {src} "$@"\n')
    shim.chmod(shim.stat().st_mode | stat.S_IEXEC)
    monkeypatch.setenv('PATH', f'{shim_dir}{os.pathsep}'
                               f'{os.environ.get("PATH", "")}')
    monkeypatch.setenv('SKYTPU_K8S_FAKE_DIR', str(tmp_path / 'k8s'))
    monkeypatch.setenv('SKYTPU_AGENT_TICK', '0.1')
    monkeypatch.setenv('SKYTPU_AGENT_READY_TIMEOUT', '30')
    # A kubeconfig must exist for `skytpu check` to enable the cloud;
    # the shim ignores its contents.
    kubeconfig = tmp_path / 'kubeconfig'
    kubeconfig.write_text('apiVersion: v1\nkind: Config\n')
    monkeypatch.setenv('KUBECONFIG', str(kubeconfig))
    # Enable the cloud the same way a user does: `skytpu check` probes
    # credentials (the shim answers `kubectl version`) and caches it.
    from skypilot_tpu import check
    assert 'kubernetes' in check.check(quiet=True)


def _wait_job(cluster: str, job_id: int, timeout=60.0) -> str:
    deadline = time.time() + timeout
    while time.time() < deadline:
        jobs = {j['job_id']: j for j in core.queue(cluster)}
        st = jobs.get(job_id, {}).get('status')
        if st in ('SUCCEEDED', 'FAILED', 'FAILED_SETUP'):
            return st
        time.sleep(0.3)
    raise AssertionError(f'job {job_id} did not finish')


def test_k8s_launch_cpu_pod(kubectl_shim):
    task = Task(name='k8s-hello', run='echo "hello from pod $HOSTNAME"')
    task.set_resources(sky.Resources(cloud='kubernetes', cpus='1+'))
    job_id, handle = execution.launch(task, cluster_name='k8s-basic',
                                      detach_run=True)
    try:
        assert handle.cluster_info.provider_name == 'kubernetes'
        assert _wait_job('k8s-basic', job_id) == 'SUCCEEDED'
        from skypilot_tpu.backend import tpu_backend
        logs = tpu_backend.TpuVmBackend().get_job_logs(handle, job_id)
        assert 'hello from pod' in logs
    finally:
        core.down('k8s-basic')
    assert core.status() == []


def test_k8s_launch_tpu_slice_gang_env(kubectl_shim):
    """A 2-host GKE TPU slice: both pods run the job with the rank/gang
    env contract, exactly like the local and GCP providers."""
    task = Task(name='k8s-gang', run=(
        'echo "R=$SKYTPU_NODE_RANK N=$SKYTPU_NUM_NODES '
        'S=$SKYTPU_SLICE_ID/$SKYTPU_NUM_SLICES C=$SKYTPU_NUM_CHIPS_PER_NODE"'))
    task.set_resources(sky.Resources(cloud='kubernetes',
                                     accelerators='tpu-v5e-16'))
    job_id, handle = execution.launch(task, cluster_name='k8s-gang',
                                      detach_run=True)
    try:
        assert handle.num_hosts == 2
        assert _wait_job('k8s-gang', job_id) == 'SUCCEEDED'
        from skypilot_tpu.backend import tpu_backend
        logs = tpu_backend.TpuVmBackend().get_job_logs(handle, job_id)
        assert 'R=0 N=2 S=0/1 C=8' in logs
        assert 'R=1 N=2 S=0/1 C=8' in logs
    finally:
        core.down('k8s-gang')
