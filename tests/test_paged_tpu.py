"""On-TPU validation of the manual-DMA paged-attention kernel.

CI runs on the virtual CPU mesh where the kernel's async-copy path
cannot execute (interpret mode rides the grid variant, covered in
``test_paged.py``); this module runs only when pytest executes on a
real TPU backend and pins the compiled manual path against a numpy
reference — the check that was run by hand when the kernel landed.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = [
    pytest.mark.slow,
    pytest.mark.skipif(jax.default_backend() != 'tpu',
                       reason='compiled Pallas kernel needs a TPU'),
]


def _reference(q, kd, vd, table, lengths, page, slot):
    hq, d = q.shape[1], q.shape[2]
    hkv = kd.shape[2]
    g = hq // hkv
    ln = int(lengths[slot])
    pages = [int(table[slot, j]) for j in range((ln + page - 1) // page)]
    kk = np.concatenate([kd[p] for p in pages])[:ln]
    vv = np.concatenate([vd[p] for p in pages])[:ln]
    qs = np.asarray(q[slot], np.float32) * d ** -0.5
    logits = np.einsum('hd,phd->hp', qs,
                       np.repeat(kk, g, axis=1).reshape(ln, hq, d))
    m = logits.max(-1)
    p = np.exp(logits - m[:, None])
    out = np.einsum('hp,phd->hd', p,
                    np.repeat(vv, g, axis=1).reshape(ln, hq, d))
    return m, out


def test_manual_kernel_bf16_matches_reference():
    from skypilot_tpu.ops.paged_attention import paged_decode_attention
    L, n_pages, page, hkv, d, hq, slots = 2, 9, 64, 2, 128, 4, 3
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    # Reference data is token-major [.., page, hkv, d]; the pool stores
    # pages head-major [.., hkv, page, d].
    kt = jax.random.normal(ks[0], (L, n_pages, page, hkv, d),
                           jnp.float32).astype(jnp.bfloat16)
    vt = jax.random.normal(ks[1], (L, n_pages, page, hkv, d),
                           jnp.float32).astype(jnp.bfloat16)
    pool_k = jnp.swapaxes(kt, 2, 3)
    pool_v = jnp.swapaxes(vt, 2, 3)
    q = jax.random.normal(ks[2], (slots, hq, d), jnp.float32)
    table = jnp.array([[1, 2, 3, 4], [5, 6, 0, 0], [7, 8, 0, 0]],
                      jnp.int32)
    lengths = jnp.array([250, 70, 0], jnp.int32)
    acc, m, l = jax.jit(
        lambda q, pk, pv: paged_decode_attention(
            q, pk, pv, table, lengths, layer=1))(q, pool_k, pool_v)
    acc, m = np.asarray(acc), np.asarray(m)
    kd = np.asarray(kt[1], np.float32)
    vd = np.asarray(vt[1], np.float32)
    for s in range(2):
        m_ref, out_ref = _reference(q, kd, vd, table, lengths, page, s)
        got = acc[s] * np.exp(m[s] - m_ref)[:, None]
        np.testing.assert_allclose(got, out_ref, rtol=3e-2, atol=3e-2)
    # empty slot: (0, -inf) partial, a no-op under merging
    assert np.all(acc[2] == 0) and np.all(m[2] < -1e29)


def test_manual_kernel_int8_matches_reference():
    from skypilot_tpu.ops.paged_attention import paged_decode_attention
    L, n_pages, page, hkv, d, hq, slots = 2, 9, 128, 8, 128, 32, 3
    ks = jax.random.split(jax.random.PRNGKey(1), 3)
    kf = jax.random.normal(ks[0], (L, n_pages, page, hkv, d),
                           jnp.float32)
    vf = jax.random.normal(ks[1], (L, n_pages, page, hkv, d),
                           jnp.float32)

    def q8(x):
        s = jnp.max(jnp.abs(x), -1, keepdims=True) / 127.0
        return (jnp.clip(jnp.round(x / s), -127, 127).astype(jnp.int8),
                s[..., 0])

    pk, sk = q8(kf)                    # token-major codes + scales
    pv, sv = q8(vf)
    q = jax.random.normal(ks[2], (slots, hq, d), jnp.float32)
    table = jnp.array([[1, 2, 3, 4], [5, 6, 0, 0], [7, 8, 0, 0]],
                      jnp.int32)
    lengths = jnp.array([400, 140, 0], jnp.int32)
    # Pool layout is head-major: codes [.., hkv, page, d], scales
    # [.., hkv, page].
    kd = np.asarray(pk[1], np.float32) * np.asarray(sk[1],
                                                    np.float32)[..., None]
    vd = np.asarray(pv[1], np.float32) * np.asarray(sv[1],
                                                    np.float32)[..., None]
    # K=1 (default, unpredicated DMAs) AND K=4 (multi-page blocks:
    # lengths 400/140 need 4/2 pages, so the K=4 block has skipped
    # tail-page DMAs reading zero-initialized scratch — the predicate
    # + stale-buffer-masking path gets real coverage).
    for kpb in (1, 4):
        acc, m, l = jax.jit(
            lambda q, pk, pv, skt, svt: paged_decode_attention(
                q, pk, pv, table, lengths, skt, svt, layer=1,
                pages_per_block=kpb))(
            q, jnp.swapaxes(pk, 2, 3), jnp.swapaxes(pv, 2, 3),
            jnp.swapaxes(sk, -1, -2), jnp.swapaxes(sv, -1, -2))
        acc, m = np.asarray(acc), np.asarray(m)
        for s in range(2):
            m_ref, out_ref = _reference(q, kd, vd, table, lengths,
                                        page, s)
            got = acc[s] * np.exp(m[s] - m_ref)[:, None]
            # int8 rounding differs slightly between scale-on-logits
            # (kernel) and scale-on-k (reference): ~1% of output scale.
            np.testing.assert_allclose(got, out_ref, rtol=6e-2,
                                       atol=6e-2, err_msg=f'K={kpb}')
