"""Serving hardening (VERDICT r2 item 10): token streaming (SSE)
through server and LB, transparent LB retry when a replica dies
mid-request, and TLS on the public endpoint (reference
``SkyServiceSpec`` tls, ``sky/serve/service_spec.py:18``)."""
import http.server
import json
import socket
import ssl
import subprocess
import threading
import time
import urllib.request

import jax
import pytest

from skypilot_tpu.serve.load_balancer import SkyServeLoadBalancer
from skypilot_tpu.utils import common_utils

jax.config.update('jax_platforms', 'cpu')

pytestmark = pytest.mark.usefixtures('tmp_state_dir')


# ----------------------------------------------------------- helpers
class _FakeController:
    """Answers the LB's sync POST with a fixed replica list."""

    def __init__(self, replica_urls):
        self.replica_urls = list(replica_urls)
        outer = self

        class H(http.server.BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_POST(self):  # noqa: N802
                body = json.dumps(
                    {'ready_replica_urls': outer.replica_urls}).encode()
                self.send_response(200)
                self.send_header('Content-Length', str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.port = common_utils.find_free_port(18700)
        self.httpd = http.server.ThreadingHTTPServer(('127.0.0.1',
                                                      self.port), H)
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()

    @property
    def url(self):
        return f'http://127.0.0.1:{self.port}'


class _EchoReplica:
    def __init__(self, tag):
        outer = self

        class H(http.server.BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):  # noqa: N802
                body = json.dumps({'replica': outer.tag}).encode()
                self.send_response(200)
                self.send_header('Content-Length', str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.tag = tag
        self.port = common_utils.find_free_port(18750)
        self.httpd = http.server.ThreadingHTTPServer(('127.0.0.1',
                                                      self.port), H)
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()

    @property
    def url(self):
        return f'http://127.0.0.1:{self.port}'


def _start_lb(controller_url, **kwargs):
    port = common_utils.find_free_port(18800)
    lb = SkyServeLoadBalancer(controller_url=controller_url, port=port,
                              **kwargs)
    lb.start()
    lb._sync_once()
    return lb, port


# ------------------------------------------------------------- tests
def test_lb_retries_dead_replica_transparently(monkeypatch):
    live = _EchoReplica('live')
    dead_port = common_utils.find_free_port(18780)
    dead_url = f'http://127.0.0.1:{dead_port}'     # nothing listening
    ctrl = _FakeController([dead_url, live.url])
    monkeypatch.setenv('SKYTPU_LB_SYNC', '3600')   # no background churn
    lb, port = _start_lb(ctrl.url)
    try:
        # Round-robin starts at the dead replica for at least one of
        # several sequential requests; every one must still succeed.
        for _ in range(4):
            with urllib.request.urlopen(
                    f'http://127.0.0.1:{port}/x', timeout=10) as r:
                assert json.loads(r.read())['replica'] == 'live'
    finally:
        lb.stop()


def test_lb_returns_502_when_all_replicas_dead(monkeypatch):
    dead = [f'http://127.0.0.1:{common_utils.find_free_port(18780 + i * 7)}'
            for i in range(2)]
    ctrl = _FakeController(dead)
    monkeypatch.setenv('SKYTPU_LB_SYNC', '3600')
    lb, port = _start_lb(ctrl.url)
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f'http://127.0.0.1:{port}/x', timeout=10)
        assert ei.value.code == 502
        assert 'unreachable' in json.loads(ei.value.read())['error']
    finally:
        lb.stop()


def test_lb_tls_endpoint(tmp_path, monkeypatch):
    cert = tmp_path / 'cert.pem'
    key = tmp_path / 'key.pem'
    subprocess.run(
        ['openssl', 'req', '-x509', '-newkey', 'rsa:2048', '-nodes',
         '-keyout', str(key), '-out', str(cert), '-days', '1',
         '-subj', '/CN=localhost'],
        check=True, capture_output=True)
    live = _EchoReplica('tls-live')
    ctrl = _FakeController([live.url])
    monkeypatch.setenv('SKYTPU_LB_SYNC', '3600')
    lb, port = _start_lb(ctrl.url, tls_certfile=str(cert),
                         tls_keyfile=str(key))
    try:
        ctx = ssl.create_default_context()
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_NONE
        with urllib.request.urlopen(f'https://127.0.0.1:{port}/x',
                                    timeout=10, context=ctx) as r:
            assert json.loads(r.read())['replica'] == 'tls-live'
        # Plain http against the TLS port fails.
        with pytest.raises(Exception):
            urllib.request.urlopen(f'http://127.0.0.1:{port}/x', timeout=5)
    finally:
        lb.stop()


def test_service_spec_tls_roundtrip():
    from skypilot_tpu.serve.service_spec import SkyServiceSpec
    spec = SkyServiceSpec.from_yaml_config({
        'readiness_probe': '/readiness',
        'tls': {'certfile': '/etc/cert.pem', 'keyfile': '/etc/key.pem'},
    })
    assert spec.tls_certfile == '/etc/cert.pem'
    cfg = spec.to_yaml_config()
    assert cfg['tls'] == {'certfile': '/etc/cert.pem',
                          'keyfile': '/etc/key.pem'}


def test_generate_top_p_and_stop_over_http():
    """The /generate API accepts top_p and stop (token-id lists) and
    returns the trimmed output."""
    from skypilot_tpu.serve.server import ModelServer
    sport = common_utils.find_free_port(18910)
    server = ModelServer('tiny', max_batch=2, max_seq=64, port=sport)
    server.start(block=False)
    deadline = time.time() + 120
    while time.time() < deadline:
        try:
            with urllib.request.urlopen(
                    f'http://127.0.0.1:{sport}/readiness', timeout=5) as r:
                if r.status == 200:
                    break
        except Exception:
            time.sleep(0.3)

    def gen(payload):
        req = urllib.request.Request(
            f'http://127.0.0.1:{sport}/generate',
            data=json.dumps(payload).encode(),
            headers={'Content-Type': 'application/json'})
        with urllib.request.urlopen(req, timeout=60) as r:
            return json.loads(r.read())
    try:
        full = gen({'prompt': [3, 1, 4], 'max_new_tokens': 8})['tokens']
        # nucleus collapse: hot sampling with top_p~0 equals greedy
        nuc = gen({'prompt': [3, 1, 4], 'max_new_tokens': 8,
                   'temperature': 2.0, 'top_p': 1e-6})['tokens']
        assert nuc == full, (nuc, full)
        stopped = gen({'prompt': [3, 1, 4], 'max_new_tokens': 8,
                       'stop': [full[2:4]]})['tokens']
        assert stopped == full[:2], (stopped, full)
        # a stop completing exactly at max_new_tokens still trims
        boundary = gen({'prompt': [3, 1, 4], 'max_new_tokens': 4,
                        'stop': [full[2:4]]})['tokens']
        assert boundary == full[:2], (boundary, full)
        # STRING stops ride the tokenizer (byte tokenizer for 'tiny',
        # 1 token <-> 1 byte); encoding must not prepend BOS or they
        # could never match generated output.
        text_full = gen({'prompt': 'ab', 'max_new_tokens': 8})
        # Response text is sanitized at the JSON boundary (lone
        # surrogates never reach the wire), so it is always valid
        # UTF-8 — possibly lossy for raw generated bytes...
        text_full['text'].encode('utf-8')
        # ...hence the byte-exact stop fragment comes from the token
        # ids. The REQUEST path keeps the surrogateescape round trip:
        # this string re-encodes to exactly those generated bytes.
        frag = bytes(text_full['tokens'][2:4]).decode(
            'utf-8', 'surrogateescape')
        text_stop = gen({'prompt': 'ab', 'max_new_tokens': 8,
                         'stop': frag})
        assert text_stop['tokens'] == text_full['tokens'][:2], \
            (text_stop, text_full)
        # malformed stop payloads return 400, not a dropped connection
        try:
            gen({'prompt': [3, 1, 4], 'max_new_tokens': 4, 'stop': 13})
            raise AssertionError('expected HTTP 400')
        except urllib.error.HTTPError as e:
            assert e.code == 400
    finally:
        server.stop()


def test_openai_compatible_api():
    """/v1/completions, /v1/chat/completions, /v1/models speak the
    OpenAI wire format (the reference's serving recipes expose vLLM's
    OpenAI server; clients built against it must work here)."""
    from skypilot_tpu.serve.server import ModelServer
    sport = common_utils.find_free_port(18920)
    server = ModelServer('tiny', max_batch=2, max_seq=64, port=sport)
    server.start(block=False)
    deadline = time.time() + 120
    while time.time() < deadline:
        try:
            with urllib.request.urlopen(
                    f'http://127.0.0.1:{sport}/readiness', timeout=5) as r:
                if r.status == 200:
                    break
        except Exception:
            time.sleep(0.3)

    def post(path, payload):
        req = urllib.request.Request(
            f'http://127.0.0.1:{sport}{path}',
            data=json.dumps(payload).encode(),
            headers={'Content-Type': 'application/json'})
        with urllib.request.urlopen(req, timeout=60) as r:
            return json.loads(r.read())
    try:
        with urllib.request.urlopen(
                f'http://127.0.0.1:{sport}/v1/models', timeout=10) as r:
            models = json.loads(r.read())
        assert models['data'][0]['id'] == 'tiny'

        comp = post('/v1/completions',
                    {'model': 'tiny', 'prompt': 'ab', 'max_tokens': 6})
        assert comp['object'] == 'text_completion'
        assert comp['choices'][0]['finish_reason'] == 'length'
        assert comp['usage']['completion_tokens'] == 6
        assert isinstance(comp['choices'][0]['text'], str)

        chat = post('/v1/chat/completions',
                    {'model': 'tiny', 'max_tokens': 4,
                     'messages': [{'role': 'user', 'content': 'hi'}]})
        assert chat['object'] == 'chat.completion'
        assert chat['choices'][0]['message']['role'] == 'assistant'

        # streaming: OpenAI chunk objects then [DONE]
        req = urllib.request.Request(
            f'http://127.0.0.1:{sport}/v1/completions',
            data=json.dumps({'prompt': 'ab', 'max_tokens': 4,
                             'stream': True}).encode(),
            headers={'Content-Type': 'application/json'})
        events = []
        with urllib.request.urlopen(req, timeout=60) as r:
            assert 'text/event-stream' in r.headers.get('Content-Type', '')
            for raw in r:
                line = raw.decode().strip()
                if line.startswith('data: '):
                    events.append(line[len('data: '):])
        assert events[-1] == '[DONE]'
        chunks = [json.loads(e) for e in events[:-1]]
        # 4 content chunks + the terminal finish_reason chunk (the
        # OpenAI truncation-detection contract).
        assert len(chunks) == 5
        assert all(c['object'] == 'text_completion' for c in chunks)
        assert all(c['choices'][0]['finish_reason'] is None
                   for c in chunks[:-1])
        assert chunks[-1]['choices'][0]['finish_reason'] == 'length'
        assert chunks[-1]['choices'][0]['text'] == ''

        # chat stream: role delta first, then content, then reason
        req = urllib.request.Request(
            f'http://127.0.0.1:{sport}/v1/chat/completions',
            data=json.dumps({'max_tokens': 3, 'stream': True,
                             'messages': [{'role': 'user',
                                           'content': 'x'}]}).encode(),
            headers={'Content-Type': 'application/json'})
        events = []
        with urllib.request.urlopen(req, timeout=60) as r:
            for raw in r:
                line = raw.decode().strip()
                if line.startswith('data: '):
                    events.append(line[len('data: '):])
        assert events[-1] == '[DONE]'
        cchunks = [json.loads(e) for e in events[:-1]]
        assert cchunks[0]['choices'][0]['delta'] == {'role': 'assistant'}
        assert cchunks[-1]['choices'][0]['finish_reason'] == 'length'
        # OpenAI-style prompt variants: [str] and [[int]] unwrap
        one = post('/v1/completions', {'prompt': ['ab'],
                                       'max_tokens': 2})
        assert len(one['choices'][0]['text']) >= 0
        two = post('/v1/completions', {'prompt': [[3, 1, 4]],
                                       'max_tokens': 2})
        assert two['usage']['prompt_tokens'] == 3

        # colon-bearing model tags (e.g. ollama-style 'llama3:8b')
        # were always ignored on adapter-free deployments; the
        # 'base:adapter' spelling must not start rejecting them.
        tag = post('/v1/completions', {'model': 'llama3:8b',
                                       'prompt': 'ab', 'max_tokens': 2})
        assert tag['usage']['completion_tokens'] == 2
        # ...but a colon tag whose prefix names the SERVED model is an
        # unambiguous adapter request and fails loudly (no bank here).
        try:
            post('/v1/completions', {'model': 'tiny:ad0',
                                     'prompt': 'ab', 'max_tokens': 2})
            raise AssertionError('expected adapter rejection')
        except urllib.error.HTTPError as e:
            assert e.code in (400, 500)
            assert 'adapter' in json.loads(
                e.read())['error']['message']

        # bad request -> OpenAI error envelope
        try:
            post('/v1/completions', {'max_tokens': 4})
            raise AssertionError('expected 400')
        except urllib.error.HTTPError as e:
            assert e.code == 400
            assert json.loads(e.read())['error']['type'] == \
                'invalid_request_error'
    finally:
        server.stop()


def test_sse_streaming_through_server_and_lb(monkeypatch):
    """E2e: the model server streams tokens as SSE; the LB passes the
    stream through unbuffered; the client sees per-token events then the
    done event."""
    from skypilot_tpu.serve.server import ModelServer
    sport = common_utils.find_free_port(18900)
    server = ModelServer('tiny', max_batch=2, max_seq=64, port=sport)
    server.start(block=False)
    deadline = time.time() + 120
    while time.time() < deadline:
        try:
            with urllib.request.urlopen(
                    f'http://127.0.0.1:{sport}/readiness', timeout=5) as r:
                if r.status == 200:
                    break
        except Exception:
            time.sleep(0.3)
    ctrl = _FakeController([f'http://127.0.0.1:{sport}'])
    monkeypatch.setenv('SKYTPU_LB_SYNC', '3600')
    lb, lport = _start_lb(ctrl.url)
    try:
        req = urllib.request.Request(
            f'http://127.0.0.1:{lport}/generate',
            data=json.dumps({'prompt': [1, 2, 3], 'max_new_tokens': 5,
                             'stream': True}).encode(),
            headers={'Content-Type': 'application/json'})
        events = []
        with urllib.request.urlopen(req, timeout=60) as r:
            assert 'text/event-stream' in r.headers.get('Content-Type', '')
            for raw in r:
                line = raw.decode().strip()
                if line.startswith('data: '):
                    events.append(json.loads(line[len('data: '):]))
        token_events = [e for e in events if 'token' in e]
        done = [e for e in events if e.get('done')]
        assert len(token_events) >= 2, events
        assert done and done[0]['tokens'] == \
            [e['token'] for e in token_events]
    finally:
        lb.stop()
        server.stop()
