"""Test configuration.

Multi-chip logic is tested on a virtual 8-device CPU mesh (the approach
SURVEY.md §4 recommends over the reference's monkeypatched-catalog-only
strategy). The kernel environment pins ``JAX_PLATFORMS=axon`` (real TPU via a
tunnel) and a sitecustomize registers that backend, so setting the env var is
not enough — we also override via jax.config before any backend initializes.
"""
import os

os.environ['JAX_PLATFORMS'] = 'cpu'
# The kernel env's sitecustomize imports jax + registers the axon TPU
# backend in EVERY python process when this var is set (~5s/process).
# Tests run on the virtual CPU mesh; dropping it here keeps the test
# process AND every subprocess it spawns (agentd, RPCs, job drivers) on
# the fast path.
os.environ.pop('PALLAS_AXON_POOL_IPS', None)
prev = os.environ.get('XLA_FLAGS', '')
if 'xla_force_host_platform_device_count' not in prev:
    os.environ['XLA_FLAGS'] = (
        prev + ' --xla_force_host_platform_device_count=8').strip()

import jax  # noqa: E402

jax.config.update('jax_platforms', 'cpu')

import pytest  # noqa: E402


@pytest.fixture()
def tmp_state_dir(tmp_path, monkeypatch):
    """Isolate global sqlite state per test."""
    monkeypatch.setenv('SKYTPU_STATE_DIR', str(tmp_path / 'state'))
    yield tmp_path / 'state'
