"""Test configuration.

Multi-chip logic is tested on a virtual 8-device CPU mesh (the approach
SURVEY.md §4 recommends over the reference's monkeypatched-catalog-only
strategy). The kernel environment pins ``JAX_PLATFORMS=axon`` (real TPU via a
tunnel) and a sitecustomize registers that backend, so setting the env var is
not enough — we also override via jax.config before any backend initializes.
"""
import os

os.environ['JAX_PLATFORMS'] = 'cpu'
# The kernel env's sitecustomize imports jax + registers the axon TPU
# backend in EVERY python process when this var is set (~5s/process).
# Tests run on the virtual CPU mesh; dropping it here keeps the test
# process AND every subprocess it spawns (agentd, RPCs, job drivers) on
# the fast path.
os.environ.pop('PALLAS_AXON_POOL_IPS', None)
prev = os.environ.get('XLA_FLAGS', '')
if 'xla_force_host_platform_device_count' not in prev:
    os.environ['XLA_FLAGS'] = (
        prev + ' --xla_force_host_platform_device_count=8').strip()

import jax  # noqa: E402

jax.config.update('jax_platforms', 'cpu')

# Persistent compilation cache shared by every test AND every
# subprocess they spawn (model-server replicas, job drivers — each is a
# fresh python paying full XLA compiles otherwise). The env var reaches
# subprocesses; the config.update covers this process, whose jax is
# already imported. Round-4's 21-minute slow tier was dominated by
# recompiling the same tiny-model programs per test/process.
_cache_dir = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), '.bench_cache', 'jax_test_cache')
os.environ.setdefault('JAX_COMPILATION_CACHE_DIR', _cache_dir)
os.environ.setdefault('JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS', '1')
jax.config.update('jax_compilation_cache_dir',
                  os.environ['JAX_COMPILATION_CACHE_DIR'])
jax.config.update('jax_persistent_cache_min_compile_time_secs', 1.0)

import pytest  # noqa: E402


@pytest.fixture(scope='session', autouse=True)
def _sweep_stray_control_plane():
    """Kill control-plane processes leaked by a previous CRASHED test
    run (a SIGABRT'd pytest never runs its cleanup, and a leftover
    agentd/replica server squatting on localhost ports poisons every
    later serve/jobs test).

    Scoped to TEST-spawned processes only: their state/agent dirs always
    live under the system tempdir (tmp_state_dir / mktemp fixtures), so
    a process whose env points elsewhere — a real local deployment — is
    left alone."""
    import tempfile

    import psutil
    me = os.getpid()
    tmp = tempfile.gettempdir()
    needles = ('skypilot_tpu.agent', 'skypilot_tpu.serve.service',
               'skypilot_tpu.jobs.controller', 'replica_server.py')
    for proc in psutil.process_iter(['pid', 'cmdline']):
        try:
            if proc.pid == me:
                continue
            cmd = ' '.join(proc.info['cmdline'] or ())
            if not any(n in cmd for n in needles):
                continue
            env = proc.environ()
            markers = (env.get('SKYTPU_STATE_DIR', ''),
                       env.get('SKYTPU_AGENT_DIR', ''),
                       env.get('HOME', ''))
            if any(m.startswith(tmp) for m in markers if m):
                proc.kill()
        except (psutil.NoSuchProcess, psutil.AccessDenied):
            continue
    yield


@pytest.fixture()
def tmp_state_dir(tmp_path, monkeypatch):
    """Isolate global sqlite state (and ssh keys) per test."""
    monkeypatch.setenv('SKYTPU_STATE_DIR', str(tmp_path / 'state'))
    monkeypatch.setenv('SKYTPU_KEYS_DIR', str(tmp_path / 'keys'))
    yield tmp_path / 'state'


@pytest.fixture()
def tp_devices():
    """Devices for tensor-parallel (multi-chip serving) tests. This
    conftest forces an 8-device virtual CPU mesh before jax
    initializes, so the skip below should never fire in CI — when it
    does (XLA_FLAGS overridden, or a real single-chip backend won the
    platform race), it says so LOUDLY instead of letting the TP suite
    vanish silently."""
    if jax.device_count() < 2:
        pytest.skip(
            'tensor-parallel tests need >= 2 devices but only '
            f'{jax.device_count()} visible. tests/conftest.py forces '
            'XLA_FLAGS=--xla_force_host_platform_device_count=8; this '
            'environment overrode it — run with that flag (and '
            'JAX_PLATFORMS=cpu) to exercise the TP serving path.')
    return jax.devices()
