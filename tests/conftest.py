"""Test configuration.

Multi-chip logic is tested on a virtual 8-device CPU mesh (the approach
SURVEY.md §4 recommends over the reference's monkeypatched-catalog-only
strategy): env vars must be set before jax initializes its backends.
"""
import os

os.environ.setdefault('JAX_PLATFORMS', 'cpu')
prev = os.environ.get('XLA_FLAGS', '')
if 'xla_force_host_platform_device_count' not in prev:
    os.environ['XLA_FLAGS'] = (
        prev + ' --xla_force_host_platform_device_count=8').strip()

import pytest  # noqa: E402


@pytest.fixture()
def tmp_state_dir(tmp_path, monkeypatch):
    """Isolate global sqlite state per test."""
    monkeypatch.setenv('SKYTPU_STATE_DIR', str(tmp_path / 'state'))
    yield tmp_path / 'state'
