"""Controller crash-safety (round 15): journaled lifecycle state,
restart reconciliation with orphan-replica adoption, and LB autonomy
during a controller outage.

The contract under test: kill the controller at ANY point and bring a
new one up — zero requests lost, zero replicas torn down twice, every
healthy replica ADOPTED (never relaunched), interrupted drains resumed
at their *remaining* deadline, unacked teardowns replayed exactly
once, zombie clusters reaped, and the LB serving its last-synced view
(stale-while-revalidate, local dead-replica eviction) the whole time.
"""
import json
import random
import threading
import time
import urllib.request

import pytest

from skypilot_tpu import exceptions
from skypilot_tpu.serve import control_env
from skypilot_tpu.serve import replica_managers
from skypilot_tpu.serve import serve_state
from skypilot_tpu.serve.replica_managers import ReplicaInfo
from skypilot_tpu.serve.replica_managers import ReplicaManager
from skypilot_tpu.serve.service_spec import SkyServiceSpec
from skypilot_tpu.utils import common_utils

ReplicaStatus = serve_state.ReplicaStatus


# ---------------------------------------------------------------- helpers
class _FakeEnv(control_env.ControlPlaneEnv):
    """Dict-backed ControlPlaneEnv: a virtual clock, a scripted
    replica HTTP surface, recorded cluster ops, and a persistence
    layer that survives "controller restarts" (new managers over the
    same env — the env IS the serve DB here)."""

    name = 'fake'

    def __init__(self):
        self.now = 1000.0
        self.rows = {}
        self.ops = []
        self.notes = {}
        self._op_seq = 0
        self.spawned = []        # (fn, args) — inspect or run later
        self.run_spawns = True   # False = "the thread died with us"
        self.launches = []
        self.downs = []
        self.gone = set()        # cluster names whose cluster is gone
        self.http = {}           # path -> payload (or Exception)
        self.posts = []          # recorded http_post_bytes paths
        self.post_responses = {}
        self.probe_ok = set()    # base urls whose readiness passes

    # ------------------------------------------------------------- time
    def time(self):
        return self.now

    def monotonic(self):
        return self.now

    def sleep(self, seconds):
        self.now += seconds

    def spawn(self, fn, *args):
        self.spawned.append((fn, args))
        if self.run_spawns:
            fn(*args)

    def run_parallel(self, fns):
        for fn in fns:
            fn()

    def rng(self):
        return random.Random(0)

    # ------------------------------------------------------------- HTTP
    @staticmethod
    def _path(url):
        return '/' + url.split('/', 3)[3]

    def http_json(self, url, payload=None, timeout=10.0):
        del timeout
        path = self._path(url)
        key = (path, 'POST' if payload is not None else 'GET')
        resp = self.http.get(key, self.http.get(path))
        if resp is None:
            raise ConnectionRefusedError(f'no handler for {key}')
        if isinstance(resp, Exception):
            raise resp
        return resp

    def http_post_bytes(self, url, data, content_type='x',
                        timeout=30.0):
        del content_type, timeout
        path = self._path(url)
        self.posts.append(path)
        resp = self.post_responses.get(path)
        if resp is None:
            raise ConnectionRefusedError(f'no POST handler for {path}')
        if isinstance(resp, Exception):
            raise resp
        return resp

    def probe_http(self, url, post_data, timeout):
        del post_data, timeout
        return any(url.startswith(base) for base in self.probe_ok)

    # ---------------------------------------------------------- clusters
    def launch_cluster(self, task, cluster_name):
        self.launches.append(cluster_name)

    def cluster_head_ip(self, cluster_name):
        return '127.0.0.1'

    def down_cluster(self, cluster_name):
        self.downs.append(cluster_name)
        if cluster_name in self.gone:
            raise exceptions.ClusterDoesNotExist(cluster_name)

    def cluster_gone(self, cluster_name):
        return cluster_name in self.gone

    # ------------------------------------------------------- persistence
    def persist_replica(self, service_name, replica_id, cluster_name,
                        status, url, version, is_spot, port):
        del service_name
        self.rows[replica_id] = {
            'replica_id': replica_id, 'cluster_name': cluster_name,
            'status': status, 'url': url, 'version': version,
            'is_spot': is_spot, 'launched_at': self.now, 'port': port,
        }

    def remove_replica(self, service_name, replica_id):
        del service_name
        self.rows.pop(replica_id, None)

    def load_replica_rows(self, service_name):
        del service_name
        return [dict(self.rows[rid]) for rid in sorted(self.rows)]

    def journal_op_start(self, service_name, kind, replica_id,
                         gang_id, payload=None, deadline_at=None):
        del service_name
        self._op_seq += 1
        self.ops.append({
            'op_id': self._op_seq, 'kind': kind,
            'replica_id': replica_id, 'gang_id': gang_id,
            'payload': dict(payload or {}),
            'started_at': self.now, 'deadline_at': deadline_at,
            'state': 'pending'})
        return self._op_seq

    def journal_op_finish(self, service_name, op_id):
        del service_name
        self.ops = [op for op in self.ops if op['op_id'] != op_id]

    def pending_ops(self, service_name):
        del service_name
        return [dict(op) for op in self.ops]

    def put_note(self, service_name, key, value):
        del service_name
        self.notes[key] = value

    def del_note(self, service_name, key):
        del service_name
        self.notes.pop(key, None)

    def get_notes(self, service_name):
        del service_name
        return dict(self.notes)

    def fault_injector(self):
        return None


def _spec(**kw):
    kw.setdefault('readiness_path', '/readiness')
    return SkyServiceSpec(**kw)


def _mgr(env, **spec_kw):
    return ReplicaManager('svc', _spec(**spec_kw), {}, env=env)


def _seed_replica(mgr, rid, status, url='http://10.0.0.{rid}:8081',
                  port=None, is_spot=False):
    """Build the state a live manager would have persisted before the
    'crash': an in-memory info + its row, through the journaled
    helpers (the same code path the real flows use)."""
    info = ReplicaInfo(rid, f'svc-replica-{rid}', 1, is_spot,
                       port if port is not None else 8000 + rid)
    info.url = url.format(rid=rid)
    info.status = status
    with mgr._lock:
        mgr._replicas[rid] = info
        mgr._next_replica_id = max(mgr._next_replica_id, rid + 1)
    mgr._persist(info)
    return info


# --------------------------------------------------------- WAL satellite
def test_serve_state_sqlite_wal_and_busy_timeout(tmp_path, monkeypatch):
    monkeypatch.setenv('SKYTPU_SERVE_DIR', str(tmp_path / 'serve'))
    conn = serve_state._conn()
    mode = conn.execute('PRAGMA journal_mode').fetchone()[0]
    assert mode == 'wal'
    assert conn.execute('PRAGMA busy_timeout').fetchone()[0] == \
        serve_state.BUSY_TIMEOUT_MS


def test_jobs_state_sqlite_wal_and_busy_timeout(tmp_path, monkeypatch):
    monkeypatch.setenv('SKYTPU_MANAGED_JOBS_DIR', str(tmp_path / 'jobs'))
    from skypilot_tpu.jobs import state as jobs_state
    conn = jobs_state._conn()
    assert conn.execute('PRAGMA journal_mode').fetchone()[0] == 'wal'
    assert conn.execute('PRAGMA busy_timeout').fetchone()[0] == 10000


# -------------------------------------------------------- journal (live)
def test_lifecycle_journal_roundtrip(tmp_path, monkeypatch):
    monkeypatch.setenv('SKYTPU_SERVE_DIR', str(tmp_path / 'serve'))
    op1 = serve_state.journal_op_start(
        'svc', 'drain', 3, None, {'deadline_s': 30.0},
        deadline_at=1234.5)
    op2 = serve_state.journal_op_start('svc', 'teardown', 4, 'g-1')
    pending = serve_state.pending_ops('svc')
    assert [p['op_id'] for p in pending] == [op1, op2]
    assert pending[0]['kind'] == 'drain'
    assert pending[0]['deadline_at'] == 1234.5
    assert pending[0]['payload'] == {'deadline_s': 30.0}
    assert pending[1]['gang_id'] == 'g-1'
    serve_state.journal_op_finish('svc', op1)
    assert [p['op_id'] for p in serve_state.pending_ops('svc')] == [op2]
    # Other services are isolated.
    assert serve_state.pending_ops('other') == []
    # Strict kind validation (a typo'd kind must never silently
    # journal an op no replay branch handles).
    with pytest.raises(ValueError, match='unknown journal op kind'):
        serve_state.journal_op_start('svc', 'lunch', 1, None)
    # Notes round-trip JSON values.
    serve_state.put_note('svc', 'ckpt_done:g-1', True)
    serve_state.put_note('svc', 'autoscaler_state', {'t': 3})
    assert serve_state.get_notes('svc') == {
        'ckpt_done:g-1': True, 'autoscaler_state': {'t': 3}}
    serve_state.del_note('svc', 'ckpt_done:g-1')
    assert 'ckpt_done:g-1' not in serve_state.get_notes('svc')
    # Seeding helpers see rows AND journal history.
    serve_state.add_or_update_replica(
        'svc', 7, 'c7', ReplicaStatus.READY, 'http://x:1', 1,
        port=10007)
    assert serve_state.max_replica_id('svc') == 7
    assert serve_state.replica_ports('svc') == {10007}
    # remove_service clears journal + notes with the rows.
    serve_state.add_service('svc', {}, 1, 2)
    serve_state.remove_service('svc')
    assert serve_state.pending_ops('svc') == []
    assert serve_state.get_notes('svc') == {}


# ------------------------------------------------- reconciliation matrix
def test_reconcile_adopts_healthy_replica_without_relaunch():
    env = _FakeEnv()
    mgr1 = _mgr(env)
    info = _seed_replica(mgr1, 3, ReplicaStatus.READY)
    env.probe_ok.add(info.url)
    env.http['/metrics?format=json'] = {'disagg': {'role': 'decode'}}
    # --- controller restarts: a fresh manager over the same DB.
    mgr2 = _mgr(env)
    stats = mgr2.reconcile()
    assert stats['adopted'] == 1
    assert sum(stats.values()) == 1
    assert mgr2.ready_urls() == [info.url]
    adopted = mgr2.replicas()[0]
    assert adopted.replica_id == 3
    assert adopted.role == 'decode'       # recovered from the live probe
    assert adopted.warmed                 # never re-warmed over live KV
    assert env.launches == [] and env.downs == []
    # The counter moved.
    from skypilot_tpu import telemetry
    assert telemetry.get_registry().get(
        'skytpu_replicas_adopted_total', outcome='adopted').value >= 1


def test_reconcile_adopt_recovers_gang_identity():
    env = _FakeEnv()
    mgr1 = _mgr(env)
    info = _seed_replica(mgr1, 5, ReplicaStatus.READY)
    env.probe_ok.add(info.url)
    env.http['/gang/status'] = {'gang_id': 'svc-gang-5-v1', 'rank': 0,
                                'world': 2}
    mgr2 = _mgr(env)
    assert mgr2.reconcile()['adopted'] == 1
    adopted = mgr2.replicas()[0]
    assert adopted.gang_id == 'svc-gang-5-v1'
    assert adopted.gang_world == 2


def test_reconcile_resumes_drain_at_remaining_deadline():
    env = _FakeEnv()
    mgr1 = _mgr(env)
    info = _seed_replica(mgr1, 2, ReplicaStatus.READY)
    env.probe_ok.add(info.url)
    # The drain starts (journal + DRAINING row) but its thread "dies
    # with the controller" before doing anything.
    env.run_spawns = False
    assert mgr1.drain(2, deadline_s=30.0) is True
    assert env.rows[2]['status'] == ReplicaStatus.DRAINING
    (op,) = env.pending_ops('svc')
    assert op['kind'] == 'drain'
    assert op['deadline_at'] == pytest.approx(env.now + 30.0)
    env.sleep(12.0)          # outage: 12 s of the deadline burn away
    env.spawned.clear()
    mgr2 = _mgr(env)
    stats = mgr2.reconcile()
    assert stats['drain_resumed'] == 1
    (fn, args) = env.spawned[-1]
    assert fn.__name__ == '_drain_then_down'
    assert args[1] == pytest.approx(18.0)      # REMAINING, not 30
    # Run the resumed drain to completion: replica acks, drains,
    # tears down once, journal empties.
    env.http[('/drain', 'POST')] = {'draining': True, 'inflight': 1}
    env.http[('/drain', 'GET')] = {'draining': True, 'drained': True,
                                   'inflight': 0}
    env.run_spawns = True
    fn(*args)
    assert env.downs == ['svc-replica-2']
    assert env.rows == {} and env.pending_ops('svc') == []


def test_reconcile_replays_unacked_teardown_exactly_once():
    env = _FakeEnv()
    mgr1 = _mgr(env)
    info = _seed_replica(mgr1, 4, ReplicaStatus.READY)
    # Crash between the teardown journal write and the teardown
    # itself: SHUTTING_DOWN row + pending op, no _down ever ran.
    env.run_spawns = False
    mgr1._scale_down_one(4)
    assert env.rows[4]['status'] == ReplicaStatus.SHUTTING_DOWN
    assert env.pending_ops('svc')[0]['kind'] == 'teardown'
    env.run_spawns = True
    mgr2 = _mgr(env)
    stats = mgr2.reconcile()
    assert stats['teardown_replayed'] == 1
    assert env.downs == ['svc-replica-4']      # exactly once
    assert env.rows == {} and env.pending_ops('svc') == []
    del info
    # A third boot finds nothing: replay is idempotent, not repeated.
    mgr3 = _mgr(env)
    assert sum(mgr3.reconcile().values()) == 0
    assert env.downs == ['svc-replica-4']


def test_reconcile_kills_zombie_clusters_from_crashed_launches():
    env = _FakeEnv()
    mgr1 = _mgr(env)
    # A launch that crashed mid-flight: PROVISIONING row + pending
    # launch op (scale_up journals before it spawns).
    env.run_spawns = False
    rid = mgr1.scale_up()
    assert env.rows[rid]['status'] == ReplicaStatus.PROVISIONING
    assert env.pending_ops('svc')[0]['kind'] == 'launch'
    # And a launch the journal recorded but whose row write was lost.
    env.journal_op_start('svc', 'launch', 99, None,
                         {'cluster_name': 'svc-replica-99'})
    env.run_spawns = True
    mgr2 = _mgr(env)
    stats = mgr2.reconcile()
    assert stats['zombie_killed'] == 2
    assert sorted(env.downs) == [f'svc-replica-{rid}',
                                 'svc-replica-99']
    assert env.rows == {} and env.pending_ops('svc') == []
    assert env.launches == []          # reconcile never launches


def test_reconcile_marks_replicas_lost_during_outage_preempted():
    env = _FakeEnv()
    mgr1 = _mgr(env)
    info = _seed_replica(mgr1, 6, ReplicaStatus.READY, is_spot=True)
    env.gone.add(info.cluster_name)    # vanished during the outage
    mgr2 = _mgr(env)
    stats = mgr2.reconcile()
    assert stats['preempted'] == 1
    assert env.downs == [info.cluster_name]
    assert env.rows == {} and env.pending_ops('svc') == []


def test_reconcile_unprobeable_replica_reenters_starting_grace():
    env = _FakeEnv()
    mgr1 = _mgr(env)
    info = _seed_replica(mgr1, 8, ReplicaStatus.READY)
    # Cluster alive, app not answering (it may be rebooting).
    mgr2 = _mgr(env)
    stats = mgr2.reconcile()
    assert stats['probe_pending'] == 1
    again = mgr2.replicas()[0]
    assert again.status == ReplicaStatus.STARTING
    assert again.first_probe_time == env.now
    assert env.downs == []             # NOT killed: grace window owns it
    del info


def test_reconcile_restores_canary_digest_and_ckpt_dedupe():
    env = _FakeEnv()
    mgr1 = _mgr(env)
    mgr1.configure_canary(1.0)
    info = _seed_replica(mgr1, 3, ReplicaStatus.READY)
    env.http[('/generate', 'POST')] = {'tokens': [5, 7, 11]}
    env.sleep(2.0)
    assert mgr1._canary_check(info) is False     # learns the reference
    digest = replica_managers.canary_digest([5, 7, 11])
    assert env.notes[f'canary_digest:v1'] == digest
    # Checkpoint-once dedupe key persisted alongside.
    env.post_responses['/checkpoint'] = b'SKCKblob'
    mgr1._checkpoint_replica(info)
    assert env.notes['ckpt_done:replica-3'] is True
    assert env.posts == ['/checkpoint']
    # --- restart
    env.probe_ok.add(info.url)
    mgr2 = _mgr(env)
    mgr2.configure_canary(1.0)
    mgr2.reconcile()
    assert mgr2._canary_learned == digest
    # A warning re-delivered after the restart must NOT re-checkpoint.
    mgr2._checkpoint_replica(mgr2.replicas()[0])
    assert env.posts == ['/checkpoint']
    # ... and a byzantine answer is judged against the RESTORED
    # reference, not relearned from the byzantine first answerer.
    env.http[('/generate', 'POST')] = {'tokens': [9, 9, 9]}
    env.sleep(2.0)
    assert mgr2._canary_check(mgr2.replicas()[0]) is True  # quarantined


def test_reconcile_seeds_replica_id_counter_and_ports():
    env = _FakeEnv()
    mgr1 = _mgr(env)
    _seed_replica(mgr1, 3, ReplicaStatus.READY, port=10003)
    _seed_replica(mgr1, 7, ReplicaStatus.READY, port=10007)
    mgr2 = _mgr(env)
    assert mgr2._next_replica_id == 1       # the restart collision bug
    mgr2.reconcile()
    assert mgr2._next_replica_id == 8
    assert {10003, 10007} <= mgr2._reserved_ports
    env.run_spawns = False
    assert mgr2.scale_up() == 8             # never a duplicate id


def test_double_scale_down_tears_down_once():
    env = _FakeEnv()
    mgr = _mgr(env)
    info = _seed_replica(mgr, 1, ReplicaStatus.READY)
    mgr._scale_down_one(1)
    mgr._scale_down_one(1)                 # racing second decision
    mgr.scale_down(1)
    assert env.downs == [info.cluster_name]


# ------------------------------------------- autoscaler/forecaster state
def test_autoscaler_state_snapshot_roundtrip():
    from skypilot_tpu.serve import autoscalers as asc_lib
    t = [10_000.0]
    spec = _spec(min_replicas=1, max_replicas=10,
                 target_qps_per_replica=2.0, forecast_enabled=True,
                 forecast_bucket_seconds=10.0,
                 forecast_season_seconds=300.0,
                 forecast_horizon_seconds=60.0)
    asc1 = asc_lib.Autoscaler.from_spec(spec, clock=lambda: t[0])
    assert isinstance(asc1, asc_lib.ForecastRequestRateAutoscaler)
    asc1.collect_request_information(
        [t[0] - 40 + i * 0.2 for i in range(200)])
    asc1.note_provision_seconds(42.0)
    asc1.target_num_replicas = 5
    state = json.loads(json.dumps(asc1.export_state()))  # wire trip
    asc2 = asc_lib.Autoscaler.from_spec(spec, clock=lambda: t[0])
    asc2.restore_state(state)
    assert asc2.target_num_replicas == 5
    assert asc2._lead_s == pytest.approx(42.0)
    assert asc2.forecaster.forecast_qps(60.0, now=t[0]) == \
        pytest.approx(asc1.forecaster.forecast_qps(60.0, now=t[0]))
    # Restore clamps to the CURRENT spec bounds (an update between
    # crash and restart must win over the stale snapshot).
    asc3 = asc_lib.Autoscaler.from_spec(
        _spec(min_replicas=1, max_replicas=3,
              target_qps_per_replica=2.0), clock=lambda: t[0])
    asc3.restore_state(state)
    assert asc3.target_num_replicas == 3


def test_controller_recover_restores_autoscaler_and_counts_restart():
    from skypilot_tpu.serve import controller as controller_lib
    env = _FakeEnv()
    spec = _spec(min_replicas=1, max_replicas=10,
                 target_qps_per_replica=2.0)
    env.run_spawns = False
    c1 = controller_lib.ServeController('svc', spec, {}, port=1,
                                        env=env)
    c1.autoscaler.target_num_replicas = 6
    c1._persist_autoscaler_state()
    info = None
    mgr1 = c1.replica_manager
    info = _seed_replica(mgr1, 1, ReplicaStatus.READY)
    env.probe_ok.add(info.url)
    # --- restart
    c2 = controller_lib.ServeController('svc', spec, {}, port=1,
                                        env=env, recover=True)
    assert c2.autoscaler.target_num_replicas == 6
    assert c2.last_reconcile['adopted'] == 1
    # A fresh boot over an EMPTY db is a no-op and not a "restart".
    from skypilot_tpu import telemetry
    restarts = telemetry.get_registry().get(
        'skytpu_controller_restarts_total')
    before = restarts.value
    empty = _FakeEnv()
    empty.run_spawns = False
    c3 = controller_lib.ServeController('svc2', spec, {}, port=1,
                                        env=empty, recover=True)
    assert sum(c3.last_reconcile.values()) == 0
    assert restarts.value == before


def test_injected_controller_crash_kind_validates():
    from skypilot_tpu.serve import faults as faults_lib
    inj = faults_lib.FaultInjector({'rules': [
        {'kind': 'controller_crash', 'site': 'controller_tick',
         'at': 2},
        {'kind': 'controller_restart', 'site': 'sim_controller',
         'at': 1},
    ]})
    assert inj.fire('controller_tick') is None
    assert inj.fire('controller_tick').kind == 'controller_crash'
    assert inj.fire('sim_controller').kind == 'controller_restart'
    with pytest.raises(ValueError, match='unknown fault site'):
        faults_lib.FaultInjector({'rules': [
            {'kind': 'controller_crash', 'site': 'contoller_tick',
             'at': 1}]})


# ------------------------------------------------------------ LB autonomy
class _FakeController:
    """Settable /controller/load_balancer_sync endpoint."""

    def __init__(self, urls, port=None):
        import http.server as hs
        self.urls = list(urls)
        outer = self

        class H(hs.BaseHTTPRequestHandler):
            timeout = 30

            def log_message(self, *a):
                del a

            def do_POST(self):  # noqa: N802
                body = json.dumps({
                    'ready_replica_urls': outer.urls,
                    'retry_after_s': 5}).encode()
                self.send_response(200)
                self.send_header('Content-Length', str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.port = port or common_utils.find_free_port(20100)
        self.httpd = hs.ThreadingHTTPServer(('127.0.0.1', self.port), H)
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()

    @property
    def url(self):
        return f'http://127.0.0.1:{self.port}'

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def _make_lb(controller_url, monkeypatch):
    from skypilot_tpu.serve.load_balancer import SkyServeLoadBalancer
    monkeypatch.setenv('SKYTPU_LB_SYNC', '3600')
    port = common_utils.find_free_port(20200)
    lb = SkyServeLoadBalancer(controller_url=controller_url, port=port)
    return lb, port


def test_lb_stale_while_revalidate_and_alarm(monkeypatch):
    from skypilot_tpu import telemetry
    monkeypatch.setenv('SKYTPU_LB_MAX_STALENESS', '0.2')
    urls = ['http://10.9.9.1:1', 'http://10.9.9.2:1']
    ctrl = _FakeController(urls)
    lb, _ = _make_lb(ctrl.url, monkeypatch)
    try:
        lb._sync_once()
        assert lb.policy.ready_replicas == urls
        reg = telemetry.get_registry()
        assert reg.get('skytpu_lb_controller_up').value == 1
        # --- controller dies
        ctrl.stop()
        time.sleep(0.3)
        lb._sync_once()
        # Stale-while-revalidate: the last view keeps serving.
        assert lb.policy.ready_replicas == urls
        assert reg.get('skytpu_lb_controller_up').value == 0
        assert reg.get('skytpu_lb_sync_age_seconds').value > 0.2
        view = lb.replica_view()
        assert view['controller_up'] is False
        assert view['ready_replica_urls'] == urls
        # --- controller returns (same port): health recovers.
        ctrl2 = _FakeController(urls, port=ctrl.port)
        try:
            deadline = time.time() + 10
            while time.time() < deadline:
                lb._sync_once()
                if reg.get('skytpu_lb_controller_up').value == 1:
                    break
                time.sleep(0.1)
            assert reg.get('skytpu_lb_controller_up').value == 1
            assert lb.replica_view()['controller_up'] is True
        finally:
            ctrl2.stop()
    finally:
        lb.stop()


def test_lb_local_eviction_and_reconcile_on_return(monkeypatch):
    import http.server as hs

    class H(hs.BaseHTTPRequestHandler):
        timeout = 30

        def log_message(self, *a):
            del a

        def do_POST(self):  # noqa: N802
            body = json.dumps({'text': 'ok', 'tokens': [1]}).encode()
            self.send_response(200)
            self.send_header('Content-Length', str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    live_port = common_utils.find_free_port(20300)
    live = hs.ThreadingHTTPServer(('127.0.0.1', live_port), H)
    threading.Thread(target=live.serve_forever, daemon=True).start()
    live_url = f'http://127.0.0.1:{live_port}'
    dead_url = f'http://127.0.0.1:{common_utils.find_free_port(20350)}'
    ctrl = _FakeController([dead_url, live_url])
    lb, lport = _make_lb(ctrl.url, monkeypatch)
    try:
        lb.start()
        lb._sync_once()
        # Drive requests until the dead replica has provably been
        # tried: connect-refused ⇒ locally evicted, request retried
        # transparently on the live one.
        for _ in range(4):
            req = urllib.request.Request(
                f'http://127.0.0.1:{lport}/generate',
                json.dumps({'text': 'hi'}).encode(),
                {'Content-Type': 'application/json'})
            with urllib.request.urlopen(req, timeout=30) as r:
                assert json.loads(r.read())['text'] == 'ok'
            if dead_url in lb._evicted:
                break
        assert dead_url in lb._evicted
        assert lb.policy.ready_replicas == [live_url]
        from skypilot_tpu import telemetry
        assert telemetry.get_registry().get(
            'skytpu_lb_local_evictions_total').value >= 1
        # Controller still lists the dead replica (stale view):
        # reconcile keeps the local eviction — no clobber.
        lb._sync_once()
        assert lb.policy.ready_replicas == [live_url]
        # Controller catches up (drops the dead replica): the
        # eviction record is released.
        ctrl.urls = [live_url]
        lb._sync_once()
        assert lb._evicted == {}
        assert lb.policy.ready_replicas == [live_url]
        # TTL expiry: a false eviction heals even if the controller
        # keeps listing the replica.
        monkeypatch.setenv('SKYTPU_LB_EVICT_TTL', '0.05')
        ctrl.urls = [dead_url, live_url]
        lb._sync_once()
        lb.note_replica_dead(dead_url, 'test')
        assert lb.policy.ready_replicas == [live_url]
        time.sleep(0.1)
        lb._sync_once()
        assert dead_url in lb.policy.ready_replicas
    finally:
        lb.stop()
        ctrl.stop()
        live.shutdown()


# ----------------------------------------------------------- simulation
def test_sim_controller_crash_storm_zero_lost_and_adoption():
    from skypilot_tpu.serve.sim import scenarios
    rep = scenarios.run_scenario('controller_crash_storm', seed=0)
    assert rep['requests']['lost'] == 0
    assert rep['controller']['crashes'] == 1
    assert rep['controller']['restarts'] == 1
    rec = rep['controller']['reconciled']
    # The surviving fleet was ADOPTED, not relaunched...
    assert rec['adopted'] >= 3
    # ...and the launches the crash orphaned were reaped as zombies.
    assert rec['zombie_killed'] >= 1
    assert rep['faults_fired']['sim_controller:controller_crash'] == 1
    assert rep['faults_fired']['sim_controller:controller_restart'] == 1
    # The outage is visible in the event log: stale syncs between the
    # crash and the restart, adoption detail on the restart line.
    sim = scenarios.get_scenario('controller_crash_storm').build(seed=0)
    sim.run()
    kinds = [line.split('|')[1] for line in
             sim.event_log().splitlines()]
    i_crash = kinds.index('ctrl_crash')
    i_restart = kinds.index('ctrl_restart')
    assert i_crash < i_restart
    assert 'sync_stale' in kinds[i_crash:i_restart]
    assert 'sync_stale' not in kinds[i_restart:]


def test_sim_controller_crash_storm_same_seed_byte_identical():
    from skypilot_tpu.serve.sim import scenarios
    a = scenarios.run_scenario('controller_crash_storm', seed=11,
                               keep_log=False)
    b = scenarios.run_scenario('controller_crash_storm', seed=11,
                               keep_log=False)
    assert a['event_log_sha256'] == b['event_log_sha256']
    assert a['events'] == b['events']
    assert a['requests'] == b['requests']


def test_cli_sim_lists_controller_crash_storm():
    """Tier-1 CliRunner smoke (seconds): the scenario is registered
    and discoverable — controller recovery can never silently rot out
    of the library."""
    from click.testing import CliRunner

    from skypilot_tpu import cli as cli_mod
    out = CliRunner().invoke(cli_mod.cli, ['sim', '--list'])
    assert out.exit_code == 0
    assert 'controller_crash_storm' in out.output


# ------------------------------------------------------------- telemetry
def test_crash_safety_series_registered_at_construction(tmp_path,
                                                        monkeypatch):
    """Stable-schema contract: constructing the controller (its
    manager) and the LB registers every crash-safety series — zeros
    from the first scrape, before any restart/adoption/outage."""
    monkeypatch.setenv('SKYTPU_SERVE_DIR', str(tmp_path / 'serve'))
    from skypilot_tpu import telemetry
    from skypilot_tpu.telemetry import registry as registry_lib
    registry_lib.reset_registry()
    try:
        from skypilot_tpu.serve import controller as controller_lib
        from skypilot_tpu.serve.load_balancer import \
            SkyServeLoadBalancer
        env = _FakeEnv()
        env.run_spawns = False
        controller_lib.ServeController('svc', _spec(), {}, port=1,
                                       env=env)
        SkyServeLoadBalancer('http://127.0.0.1:1', port=1)
        prom = telemetry.get_registry().render_prometheus()
    finally:
        registry_lib.reset_registry()
    assert '# TYPE skytpu_controller_restarts_total counter' in prom
    assert 'skytpu_controller_restarts_total 0' in prom
    assert '# TYPE skytpu_reconcile_seconds histogram' in prom
    assert 'skytpu_reconcile_seconds_bucket{le="+Inf"} 0' in prom
    assert '# TYPE skytpu_replicas_adopted_total counter' in prom
    for outcome in replica_managers.ADOPT_OUTCOMES:
        assert (f'skytpu_replicas_adopted_total{{outcome="{outcome}"}}'
                ' 0' in prom), outcome
    assert '# TYPE skytpu_lb_sync_age_seconds gauge' in prom
    assert 'skytpu_lb_sync_age_seconds 0' in prom
    assert '# TYPE skytpu_lb_controller_up gauge' in prom
    assert '# TYPE skytpu_lb_local_evictions_total counter' in prom
    assert 'skytpu_lb_local_evictions_total 0' in prom


# ------------------------------------------------- live e2e (model srv)
def test_kill_controller_mid_drain_e2e_zero_lost(tmp_path, monkeypatch):
    """THE live contract: a REAL controller managing two REAL tiny
    model servers dies mid-drain while streams run through the live
    LB. A new controller boots with recover=True: it ADOPTS the
    healthy replica (no relaunch), RESUMES the interrupted drain at
    its remaining deadline (in-flight work on the draining replica
    finishes), and no cluster is ever torn down twice. Every stream
    completes byte-identical to an uninterrupted run — zero lost."""
    import jax
    jax.config.update('jax_platforms', 'cpu')
    monkeypatch.setenv('SKYTPU_SERVE_DIR', str(tmp_path / 'serve'))
    monkeypatch.setenv('SKYTPU_SERVE_TICK', '0.5')
    monkeypatch.setenv('SKYTPU_LB_SYNC', '3600')
    from skypilot_tpu.serve import controller as controller_lib
    from skypilot_tpu.serve.load_balancer import SkyServeLoadBalancer
    from skypilot_tpu.serve.server import ModelServer

    class CrashableEnv(control_env.LiveControlPlaneEnv):
        """Live env whose cluster ops are recorded stubs and whose
        spawns can be suppressed — `crashed=True` models the instant
        the controller process dies (its threads die with it)."""

        def __init__(self):
            self.crashed = False
            self.downs = []
            self.launches = []

        def spawn(self, fn, *args):
            if self.crashed:
                return
            super().spawn(fn, *args)

        def launch_cluster(self, task, cluster_name):
            self.launches.append(cluster_name)

        def cluster_head_ip(self, cluster_name):
            return '127.0.0.1'

        def down_cluster(self, cluster_name):
            self.downs.append(cluster_name)

        def cluster_gone(self, cluster_name):
            return False

    pa = common_utils.find_free_port(20400)
    pb = common_utils.find_free_port(pa + 1)
    sa = ModelServer('tiny', port=pa, max_batch=2, max_seq=128)
    sb = ModelServer('tiny', port=pb, max_batch=2, max_seq=128)
    sa.start(block=False)
    sb.start(block=False)
    lb = ctrl2 = None
    spec = _spec(min_replicas=2)
    try:
        assert sa._ready.wait(180) and sb._ready.wait(180)
        env1 = CrashableEnv()
        cport = common_utils.find_free_port(20450)
        ctrl1 = controller_lib.ServeController(
            'e2e-svc', spec, {}, port=cport, env=env1)
        mgr1 = ctrl1.replica_manager
        url_a, url_b = (f'http://127.0.0.1:{pa}',
                        f'http://127.0.0.1:{pb}')
        ia = _seed_replica(mgr1, 1, ReplicaStatus.READY, url=url_a,
                           port=pa)
        ib = _seed_replica(mgr1, 2, ReplicaStatus.READY, url=url_b,
                           port=pb)
        ctrl1.start()
        lbport = common_utils.find_free_port(20500)
        lb = SkyServeLoadBalancer(
            controller_url=f'http://127.0.0.1:{cport}', port=lbport)
        lb.start()
        lb._sync_once()
        assert set(lb.policy.ready_replicas) == {url_a, url_b}

        # Byte-identity reference, computed directly on replica B
        # (gen=24 like test_chaos: long tiny-model generations can hit
        # documented bf16 near-tie argmax flips under co-batching,
        # which is a numerics caveat, not a recovery property).
        prompts = [[11 + i, 3, 5, 7 + i] for i in range(5)]
        gen = 24

        def generate(base, p):
            req = urllib.request.Request(
                base + '/generate',
                json.dumps({'prompt': p,
                            'max_new_tokens': gen}).encode(),
                {'Content-Type': 'application/json'})
            with urllib.request.urlopen(req, timeout=180) as r:
                return json.loads(r.read())['tokens']

        reference = {tuple(p): generate(url_b, p) for p in prompts}

        results, errors = {}, {}

        def stream_one(p):
            try:
                req = urllib.request.Request(
                    f'http://127.0.0.1:{lbport}/generate',
                    json.dumps({'prompt': p, 'max_new_tokens': gen,
                                'stream': True}).encode(),
                    {'Content-Type': 'application/json'})
                tokens, done, error = [], None, None
                with urllib.request.urlopen(req, timeout=180) as r:
                    for raw in r:
                        if not raw.startswith(b'data:'):
                            continue
                        ev = json.loads(raw[5:].strip())
                        if 'token' in ev:
                            tokens.append(int(ev['token']))
                        if ev.get('done'):
                            done = ev
                        if 'error' in ev:
                            error = ev
                results[tuple(p)] = (tokens, done, error)
            except Exception as e:  # noqa: BLE001 — asserted below
                errors[tuple(p)] = f'{type(e).__name__}: {e}'

        threads = [threading.Thread(target=stream_one, args=(p,))
                   for p in prompts]
        for t in threads:
            t.start()
            time.sleep(0.02)

        # --- mid-load: the controller loop dies, then a drain of
        # replica A gets as far as its journal + row write before its
        # thread "dies with the process" — the crash-mid-drain moment.
        env1.crashed = True            # threads die with the process
        ctrl1.crash()
        for t in ctrl1._threads:
            t.join(timeout=10)
        assert mgr1.drain(1, deadline_s=30.0) is True
        (op,) = serve_state.pending_ops('e2e-svc')
        assert op['kind'] == 'drain'
        # The row usually reads DRAINING; a probe sweep racing the
        # crash can leave it READY — either way the journaled drain op
        # is what reconciliation resumes from.
        assert serve_state.get_replicas('e2e-svc')[0]['status'] in (
            ReplicaStatus.DRAINING, ReplicaStatus.READY)
        # The LB's next sync fails: stale-while-revalidate.
        lb._sync_once()
        assert set(lb.policy.ready_replicas) == {url_a, url_b}

        # --- a NEW controller boots and reconciles.
        env2 = CrashableEnv()
        cport2 = common_utils.find_free_port(20550)
        ctrl2 = controller_lib.ServeController(
            'e2e-svc', spec, {}, port=cport2, env=env2, recover=True)
        stats = ctrl2.last_reconcile
        assert stats['adopted'] == 1           # B re-owned, no relaunch
        assert stats['drain_resumed'] == 1     # A's drain continues
        assert env2.launches == []
        mgr2 = ctrl2.replica_manager
        assert mgr2._next_replica_id == 3
        assert mgr2.ready_urls() == [url_b]
        ctrl2.start()
        # Re-point the LB (in production the controller address is
        # stable; the test re-binds): reconcile, don't clobber.
        lb.controller_url = f'http://127.0.0.1:{cport2}'
        lb._sync_once()
        assert lb.policy.ready_replicas == [url_b]

        for t in threads:
            t.join(timeout=180)
        assert not errors, errors
        lost = []
        for p in prompts:
            tokens, done, error = results[tuple(p)]
            if error is not None or done is None:
                lost.append((p, error))
                continue
            assert tokens == reference[tuple(p)], (p, tokens)
        assert lost == [], lost

        # The resumed drain runs A to drained and tears it down
        # EXACTLY once; B is never touched.
        deadline = time.time() + 60
        while time.time() < deadline and 1 in mgr2._replicas:
            time.sleep(0.2)
        assert 1 not in mgr2._replicas
        assert env2.downs == [ia.cluster_name]
        assert env1.downs == []

        # The drain + teardown ops ack shortly after untrack. (A
        # pending LAUNCH op may legitimately appear: the autoscaler
        # replaces the drained replica — that is the control plane
        # working, not a leak.)
        def recovery_ops():
            return [op for op in serve_state.pending_ops('e2e-svc')
                    if op['kind'] in ('drain', 'teardown')]

        deadline = time.time() + 30
        while time.time() < deadline and recovery_ops():
            time.sleep(0.1)
        assert recovery_ops() == []
        ids = [r['replica_id'] for r in
               serve_state.get_replicas('e2e-svc')]
        assert 1 not in ids and 2 in ids
        del ib
    finally:
        if lb is not None:
            lb.stop()
        if ctrl2 is not None:
            ctrl2.crash()
        sa.stop()
        sb.stop()
