"""MoE capacity-dispatch tests: exactness vs a dense masked reference
when capacity is ample, drop semantics when it is not, capacity math,
and balanced-routing aux loss."""
import pytest
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from skypilot_tpu.models import configs, llama, moe

# Compile-heavy (jit of full models): slow tier — the fast sweep is
# the orchestration layer (SURVEY §4 offline tier analog).
pytestmark = pytest.mark.slow


def _dense_reference(layer, x, cfg):
    """The round-1 all-experts masked dispatch, as ground truth."""
    k, E = cfg.n_experts_per_token, cfg.n_experts
    logits = jnp.einsum('bsd,de->bse', x, layer['router'],
                        preferred_element_type=jnp.float32)
    topk_vals, topk_idx = jax.lax.top_k(logits, k)
    topk_w = jax.nn.softmax(topk_vals, axis=-1)
    onehot = jax.nn.one_hot(topk_idx, E, dtype=jnp.float32)
    combine = jnp.einsum('bsk,bske->bse', topk_w, onehot)
    gate = jnp.einsum('bsd,edf->ebsf', x, layer['moe_gate'])
    up = jnp.einsum('bsd,edf->ebsf', x, layer['moe_up'])
    h = jax.nn.silu(gate.astype(jnp.float32)).astype(x.dtype) * up
    expert_out = jnp.einsum('ebsf,efd->ebsd', h, layer['moe_down'])
    return jnp.einsum('ebsd,bse->bsd', expert_out,
                      combine.astype(expert_out.dtype))


def _layer_params(cfg, seed=0):
    params = moe.init_moe_params(jax.random.PRNGKey(seed), cfg)
    return jax.tree.map(lambda p: p[0], params)     # layer 0 slice


class TestCapacityDispatch:

    def test_matches_dense_reference_with_ample_capacity(self):
        # capacity_factor E/k => every assignment fits; outputs must be
        # identical to computing all experts densely.
        cfg = dataclasses.replace(configs.TINY_MOE,
                                  moe_capacity_factor=float(
                                      configs.TINY_MOE.n_experts))
        layer = _layer_params(cfg)
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, cfg.dim),
                              jnp.float32)
        out, aux = moe.moe_ffn(layer, x, cfg)
        ref = _dense_reference(layer, x, cfg)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)
        assert np.isfinite(float(aux))

    def test_tight_capacity_drops_but_stays_finite(self):
        cfg = dataclasses.replace(configs.TINY_MOE,
                                  moe_capacity_factor=0.25)
        layer = _layer_params(cfg)
        x = jax.random.normal(jax.random.PRNGKey(2), (2, 16, cfg.dim),
                              jnp.float32)
        out, aux = moe.moe_ffn(layer, x, cfg)
        assert bool(jnp.all(jnp.isfinite(out)))
        assert np.isfinite(float(aux))
        # Dropping must reduce (not inflate) total output mass vs ample
        # capacity.
        ample = dataclasses.replace(cfg, moe_capacity_factor=8.0)
        out_full, _ = moe.moe_ffn(layer, x, ample)
        assert float(jnp.sum(jnp.abs(out))) <= \
            float(jnp.sum(jnp.abs(out_full))) + 1e-3

    def test_capacity_scales_with_k_over_e(self):
        cfg = configs.TINY_MOE                       # E=4, k=2, cf=1.25
        assert moe.expert_capacity(64, cfg) == 40    # 64*2/4*1.25
        half_k = dataclasses.replace(cfg, n_experts_per_token=1)
        assert moe.expert_capacity(64, half_k) == 20
        assert moe.expert_capacity(1, cfg) == cfg.n_experts_per_token

    def test_grad_flows_through_dispatch(self):
        cfg = configs.TINY_MOE
        layer = _layer_params(cfg)
        x = jax.random.normal(jax.random.PRNGKey(3), (2, 8, cfg.dim),
                              jnp.float32)

        def loss(layer):
            out, aux = moe.moe_ffn(layer, x, cfg)
            return jnp.sum(out ** 2) + aux
        grads = jax.grad(loss)(layer)
        for name in ('router', 'moe_gate', 'moe_up', 'moe_down'):
            g = grads[name]
            assert bool(jnp.all(jnp.isfinite(g))), name
            assert float(jnp.sum(jnp.abs(g))) > 0, name

    def test_balanced_routing_aux_near_one(self):
        cfg = configs.TINY_MOE
        # Uniform router logits => perfectly balanced expected load.
        logits = jnp.zeros((2, 32, cfg.n_experts))
        idx = jnp.tile(jnp.arange(2)[None, None, :], (2, 32, 1))
        aux = moe.load_balancing_loss(logits, idx, cfg.n_experts)
        assert abs(float(aux) - 1.0) < 0.3

    def test_moe_forward_in_model(self):
        cfg = configs.TINY_MOE
        params = llama.init_params(jax.random.PRNGKey(1), cfg)
        logits, _ = llama.forward(params, jnp.ones((2, 8), jnp.int32), cfg)
        assert bool(jnp.all(jnp.isfinite(logits)))
