"""Multi-chip tensor-parallel serving (the production (tp, dp) path).

Runs on the virtual CPU mesh tests/conftest.py forces (8 devices); the
``tp_devices`` fixture skips LOUDLY if that override was defeated.
Covers the round-8 contract:

- tp=2 greedy decode byte-identical to tp=1 on BOTH engines,
- sharded prefix-cache hit reuse,
- pool-pressure preemption/resume under tp,
- per-shard pool/byte accounting + the placement policy,
- scheduler work-token scaling with mesh shape,
- an e2e model-server boot with --tp 2 serving a streamed completion
  with the mesh reported through /metrics.
"""
import json
import urllib.request

import jax
import pytest

from skypilot_tpu.inference.engine import (InferenceEngine,
                                           kv_shard_degree,
                                           kv_token_bytes)
from skypilot_tpu.inference.paged import PagedInferenceEngine
from skypilot_tpu.models import configs
from skypilot_tpu.models import llama
from skypilot_tpu.parallel import mesh as mesh_lib

PROMPTS = ([1, 2, 3] * 9, [4, 5] * 10, [7] * 21)


@pytest.fixture(scope='module')
def setup():
    cfg = configs.TINY
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def _run(cls, cfg, params, *, gen=8, prompts=PROMPTS, **kw):
    eng = cls(cfg, params, max_batch=4, max_seq=128,
              prefill_chunk_tokens=16, attn_impl='xla', **kw)
    rids = [eng.add_request(list(p), max_new_tokens=gen)
            for p in prompts]
    done = eng.run_to_completion(horizon=8)
    return [done[r].output for r in rids], eng


# ---------------------------------------------------------- mesh helpers
def test_serving_mesh_shapes(tp_devices):
    assert mesh_lib.serving_mesh(1, 1) is None     # meshless fast path
    m = mesh_lib.serving_mesh(tp=2)
    assert mesh_lib.mesh_axis_sizes(m)['tp'] == 2
    assert mesh_lib.mesh_axis_sizes(None) == {
        a: 1 for a in mesh_lib.MESH_AXES}
    with pytest.raises(ValueError):
        mesh_lib.serving_mesh(tp=1024)


def test_serving_spec_from_env(monkeypatch):
    monkeypatch.setenv('SKYTPU_TP', '2')
    monkeypatch.setenv('SKYTPU_DP', '3')
    spec = mesh_lib.serving_spec_from_env()
    assert (spec.tp, spec.dp) == (2, 3)
    # Explicit args beat the env (the --tp/--dp contract).
    spec = mesh_lib.serving_spec_from_env(tp=4, dp=1)
    assert (spec.tp, spec.dp) == (4, 1)


def test_axis_shard_degree_divisibility(tp_devices):
    m = mesh_lib.serving_mesh(tp=2)
    assert mesh_lib.axis_shard_degree(m, 'tp', 4) == 2
    # MQA-style: tp does not divide the dim -> replicated, degree 1.
    assert mesh_lib.axis_shard_degree(m, 'tp', 3) == 1
    assert mesh_lib.axis_shard_degree(None, 'tp', 4) == 1


# ------------------------------------------------- byte-identical decode
def test_tp2_greedy_byte_identical_both_engines(setup, tp_devices):
    """The acceptance bar: tp=2 greedy decode equals tp=1 exactly, on
    the slot AND the paged engine."""
    cfg, params = setup
    mesh = mesh_lib.serving_mesh(tp=2)
    for cls in (InferenceEngine, PagedInferenceEngine):
        ref, _ = _run(cls, cfg, params)
        tp2, _ = _run(cls, cfg, params, mesh=mesh)
        assert tp2 == ref, cls.__name__


def test_tp2_dp2_paged_byte_identical(setup, tp_devices):
    cfg, params = setup
    if jax.device_count() < 4:
        pytest.skip('needs 4 devices for (tp=2, dp=2)')
    mesh = mesh_lib.serving_mesh(tp=2, dp=2)
    ref, _ = _run(PagedInferenceEngine, cfg, params)
    out, _ = _run(PagedInferenceEngine, cfg, params, mesh=mesh)
    assert out == ref


def test_tp2_int8_kv_byte_identical(setup, tp_devices):
    cfg, params = setup
    mesh = mesh_lib.serving_mesh(tp=2)
    ref, _ = _run(PagedInferenceEngine, cfg, params,
                  kv_cache_dtype='int8')
    out, _ = _run(PagedInferenceEngine, cfg, params,
                  kv_cache_dtype='int8', mesh=mesh)
    assert out == ref


# ------------------------------------------------------- prefix caching
def test_sharded_prefix_cache_hit_reuse(setup, tp_devices):
    """A second request sharing full pages must hit the prefix index
    under tp — no recompute of the shared pages, tail-only prefill —
    and still decode correctly on the head-sharded pool."""
    cfg, params = setup
    mesh = mesh_lib.serving_mesh(tp=2)
    eng = PagedInferenceEngine(cfg, params, max_batch=2, max_seq=96,
                               chunk=16, attn_impl='xla', mesh=mesh)
    shared = list(range(1, 3 * eng.page + 1))      # 3 full pages
    r1 = eng.add_request(shared + [40], max_new_tokens=4)
    eng.run_to_completion(horizon=4)
    chunks_before = eng.chunks_prefilled
    r2 = eng.add_request(shared + [41], max_new_tokens=4)
    done = eng.run_to_completion(horizon=4)
    assert eng.alloc.prefix_hits >= 1
    assert eng.chunks_prefilled - chunks_before <= 1
    assert len(done[r2].output) == 4
    del r1


# ---------------------------------------------------- preemption under tp
def test_preemption_resume_under_tp(setup, tp_devices):
    """Pool pressure on the SHARDED pool: the newest request preempts,
    re-registers its written pages, and resumes byte-identically to an
    uninterrupted single-chip run."""
    cfg, params = setup
    mesh = mesh_lib.serving_mesh(tp=2)
    # Reference: SAME geometry (page size, mesh) with an ample pool —
    # the one variable is pool pressure. (TINY is bf16: a different
    # page/gather bucket would reorder reductions and legitimately
    # flip near-tie argmaxes, which is not what this test pins.)
    ref = PagedInferenceEngine(cfg, params, max_batch=2, max_seq=256,
                               page_size=8, n_pages=64,
                               attn_impl='xla', mesh=mesh)
    rr = ref.add_request(list(range(1, 30)), max_new_tokens=24)
    ref_out = ref.run_to_completion(horizon=4)[rr].output
    assert ref.preemptions == 0
    eng = PagedInferenceEngine(cfg, params, max_batch=2, max_seq=256,
                               page_size=8, n_pages=12,
                               attn_impl='xla', mesh=mesh)
    r1 = eng.add_request(list(range(1, 30)), max_new_tokens=24)
    r2 = eng.add_request(list(range(1, 30)), max_new_tokens=24)
    done = eng.run_to_completion(horizon=4)
    assert eng.preemptions >= 1
    assert done[r1].output == ref_out
    assert done[r2].output == ref_out


# ------------------------------------------------- per-shard accounting
def test_kv_token_bytes_per_shard(setup, tp_devices):
    cfg, _ = setup
    mesh = mesh_lib.serving_mesh(tp=2)
    assert kv_shard_degree(cfg, mesh) == 2         # TINY: 4 kv heads
    assert kv_token_bytes(cfg, False, mesh=mesh) == \
        kv_token_bytes(cfg, False) // 2
    # dp replicates: no per-shard credit beyond tp.
    if jax.device_count() >= 4:
        mesh_dp = mesh_lib.serving_mesh(tp=2, dp=2)
        assert kv_token_bytes(cfg, False, mesh=mesh_dp) == \
            kv_token_bytes(cfg, False) // 2


def test_pool_stats_per_shard_under_tp(setup, tp_devices):
    """Token capacities stay GLOBAL (a token is a token at any mesh
    shape); byte views halve per shard under tp=2."""
    cfg, params = setup
    mesh = mesh_lib.serving_mesh(tp=2)
    _, single = _run(PagedInferenceEngine, cfg, params, gen=2,
                     prompts=([1, 2, 3],))
    _, sharded = _run(PagedInferenceEngine, cfg, params, gen=2,
                      prompts=([1, 2, 3],), mesh=mesh)
    s1, s2 = single.kv_pool_stats(), sharded.kv_pool_stats()
    assert s2['pool_token_capacity'] == s1['pool_token_capacity']
    assert s2['kv_token_bytes'] == s1['kv_token_bytes']
    assert s2['kv_token_bytes_per_shard'] == s1['kv_token_bytes'] // 2
    assert s2['kv_shards'] == 2
    assert single.mesh_axes()['tp'] == 1
    assert sharded.mesh_axes()['tp'] == 2


# ----------------------------------------------------- placement policy
def test_adaptive_tp_placement_policy():
    from skypilot_tpu.serve import placement
    gb = int(1e9)
    # Fits one chip: latency tier still maxes tp for TPOT; throughput
    # tier spends the chips on dp replicas instead.
    lat = placement.choose_parallelism(7 * gb, 4, slo_tier='latency')
    assert (lat.tp, lat.dp) == (4, 1)
    thr = placement.choose_parallelism(7 * gb, 4,
                                       slo_tier='throughput')
    assert (thr.tp, thr.dp) == (1, 4)
    # 26 GB of weights (13B bf16) on 16 GB chips: min tp=4 even for
    # the throughput tier; the rest goes dp.
    big = placement.choose_parallelism(26 * gb, 8,
                                       slo_tier='throughput')
    assert (big.tp, big.dp) == (4, 2)
    with pytest.raises(ValueError):
        placement.choose_parallelism(26 * gb, 1)
    plan = placement.plan_for_model('llama3-8b', 4,
                                    slo_tier='throughput')
    assert plan.tp * plan.dp == 4
    assert plan.as_env() == {'SKYTPU_TP': str(plan.tp),
                             'SKYTPU_DP': str(plan.dp)}


def test_plan_for_spec_modes():
    from skypilot_tpu.serve import placement
    from skypilot_tpu.serve.service_spec import SkyServiceSpec
    fixed = SkyServiceSpec(readiness_path='/readiness',
                           parallelism_policy='fixed', tp=2, dp=3)
    p = placement.plan_for_spec(fixed)
    assert (p.tp, p.dp) == (2, 3)
    bare = SkyServiceSpec(readiness_path='/readiness')
    assert placement.plan_for_spec(bare).chips == 1
    adaptive = SkyServiceSpec(readiness_path='/readiness',
                              chips_per_replica=4,
                              parallelism_model='llama3-1b',
                              slo_tier='latency')
    p = placement.plan_for_spec(adaptive)
    assert (p.tp, p.dp) == (4, 1)


def test_service_spec_parallelism_yaml():
    from skypilot_tpu.serve.service_spec import SkyServiceSpec
    spec = SkyServiceSpec.from_yaml_config({
        'readiness_probe': '/readiness',
        'parallelism': {'policy': 'adaptive', 'chips_per_replica': 2,
                        'slo_tier': 'throughput',
                        'model': 'llama3-1b'},
    })
    assert spec.chips_per_replica == 2
    assert spec.slo_tier == 'throughput'
    assert spec.parallelism_model == 'llama3-1b'


# ------------------------------------------------- scheduler mesh scaling
def test_scheduler_work_token_scaling(setup, tp_devices):
    """The cold-meter Retry-After fallback scales with the mesh's
    tp x dp: a sharded replica chews the same work tokens faster, so
    the quoted backoff must shrink accordingly."""
    import threading

    from skypilot_tpu.serve import scheduler as scheduler_lib

    class FakeEngine:
        max_batch = 8

        def __init__(self, axes):
            self._axes = axes

        def mesh_axes(self):
            return self._axes

        def kv_pool_stats(self):
            return {'pool_token_capacity': 1024}

        def remaining_work_tokens(self):
            return 0

    def retry_for(axes):
        sched = scheduler_lib.RequestScheduler(threading.Lock())
        sched.bind_engine(FakeEngine(axes))
        # Small enough to stay inside the [1, 120] s clamp at tp=1:
        # 4000 tokens / (8 tok/s x 8 slots) = 62.5 s.
        return sched.retry_after_s('latency', work=4000)

    single = retry_for({'tp': 1, 'dp': 1})
    tp2 = retry_for({'tp': 2, 'dp': 1})
    tp2dp2 = retry_for({'tp': 2, 'dp': 2})
    assert tp2 < single
    assert tp2dp2 < tp2
    assert tp2 <= single // 2 + 1
    # The factor is surfaced for operators.
    sched = scheduler_lib.RequestScheduler(threading.Lock())
    sched.bind_engine(FakeEngine({'tp': 2, 'dp': 2}))
    assert sched.mesh_speedup == 4
    assert sched.json_stats()['mesh_speedup'] == 4


# ------------------------------------------------------------ e2e server
def test_e2e_server_tp2_streamed_completion(tp_devices):
    """Boot the model server with --tp 2 (the ModelServer tp knob),
    stream a completion, and read the mesh shape back through BOTH
    /metrics formats — the whole multi-chip serving path end to end."""
    from skypilot_tpu.serve.server import ModelServer
    from skypilot_tpu.utils import common_utils
    port = common_utils.find_free_port(19500)
    server = ModelServer('tiny', max_batch=2, max_seq=64, port=port,
                         tp=2)
    server.start(block=False)
    try:
        assert server._ready.wait(180)
        assert server.engine.mesh is not None
        assert server.engine.mesh_axes()['tp'] == 2
        body = json.dumps({'prompt': [1, 2, 3], 'max_new_tokens': 6,
                           'stream': True}).encode()
        req = urllib.request.Request(
            f'http://127.0.0.1:{port}/generate', body,
            {'Content-Type': 'application/json'})
        with urllib.request.urlopen(req, timeout=60) as r:
            assert 'text/event-stream' in r.headers.get(
                'Content-Type', '')
            events = [json.loads(ln[5:]) for ln in r
                      if ln.startswith(b'data:')]
        tokens = [e['token'] for e in events if 'token' in e]
        assert len(tokens) == 6
        assert events[-1].get('done') is True
        # tp=1 reference: byte-identical through the server too.
        ref = PagedInferenceEngine(configs.TINY, max_batch=2,
                                   max_seq=64, attn_impl='xla')
        rid = ref.add_request([1, 2, 3], max_new_tokens=6)
        assert ref.run_to_completion(horizon=4)[rid].output == tokens
        with urllib.request.urlopen(
                f'http://127.0.0.1:{port}/metrics?format=json',
                timeout=10) as r:
            payload = json.loads(r.read())
        assert payload['mesh']['tp'] == 2
        assert payload['mesh']['devices'] == 2
        assert payload['sched']['mesh_speedup'] == 2
        with urllib.request.urlopen(
                f'http://127.0.0.1:{port}/metrics', timeout=10) as r:
            prom = r.read().decode()
        assert 'skytpu_mesh_shape{axis="tp"} 2' in prom
    finally:
        server.stop()
