"""Managed jobs end-to-end on the local provisioner: controller-on-a-
cluster, preemption recovery with the checkpoint contract, cancel.

This is the hermetic version of the reference's managed-job smoke tests
(``tests/smoke_tests/test_managed_job.py``), which terminate real VMs
out-of-band to force recovery — here we terminate the local task cluster
out-of-band the same way.
"""
import os
import time

import pytest

import skypilot_tpu as sky
from skypilot_tpu import global_state
from skypilot_tpu import jobs
from skypilot_tpu.provision.local import instance as local_instance
from skypilot_tpu.task import Task

pytestmark = [pytest.mark.usefixtures('tmp_state_dir', 'fast_jobs'), pytest.mark.slow]

TERMINAL = ('SUCCEEDED', 'FAILED', 'FAILED_SETUP', 'FAILED_NO_RESOURCE',
            'FAILED_CONTROLLER', 'CANCELLED')


@pytest.fixture()
def fast_jobs(monkeypatch):
    monkeypatch.setenv('SKYTPU_AGENT_TICK', '0.1')
    monkeypatch.setenv('SKYTPU_AGENT_READY_TIMEOUT', '30')
    monkeypatch.setenv('SKYTPU_JOBS_POLL', '0.2')


def _wait_managed(job_id: int, timeout: float = 90.0) -> str:
    deadline = time.time() + timeout
    status = None
    while time.time() < deadline:
        status = jobs.job_status(job_id)
        if status in TERMINAL:
            return status
        time.sleep(0.2)
    return status or 'TIMEOUT'


def _down_controller():
    from skypilot_tpu import core
    try:
        core.down(jobs.core.CONTROLLER_CLUSTER_NAME)
    except Exception:
        pass


def _local_task(name: str, run: str, envs=None) -> Task:
    task = Task(name=name, run=run, envs=envs or {})
    task.set_resources(sky.Resources(cloud='local', cpus='1+'))
    return task


def test_managed_job_end_to_end(tmp_path):
    out = tmp_path / 'out.txt'
    task = _local_task('mj', f'echo done-$((6*7)) > {out}')
    try:
        job_id = jobs.launch(task, name='mj')
        assert job_id == 1
        assert _wait_managed(job_id) == 'SUCCEEDED'
        assert out.read_text().strip() == 'done-42'
        table = jobs.queue()
        rec = [r for r in table if r['job_id'] == job_id][0]
        assert rec['status'] == 'SUCCEEDED'
        assert rec['recovery_count'] == 0
        # The task cluster was cleaned up by the controller.
        assert global_state.get_cluster_from_name('mj-1') is None
        # Controller log shows the lifecycle.
        log_text = jobs.logs(job_id)
        assert 'mj-1' in log_text
    finally:
        _down_controller()


def test_managed_job_recovery_resumes_from_checkpoint(tmp_path):
    """Kill the task cluster mid-run; the controller must detect the
    preemption, relaunch, and the task must RESUME (not restart) from its
    checkpoint — steps 1..8 each appear exactly once."""
    ckpt = tmp_path / 'bucket'
    ckpt.mkdir()
    progress = ckpt / 'progress'
    release = ckpt / 'release'
    # Resumable "training": continues from the last checkpointed step.
    # The first run BLOCKS after writing step 3 until the release file
    # appears — so the preemption deterministically lands mid-run no
    # matter how loaded the host is (a sleep-based window is a flake).
    run = (
        'i=1; '
        'if [ -f "$CKPT_DIR/progress" ]; then '
        '  i=$(( $(tail -1 "$CKPT_DIR/progress") + 1 )); fi; '
        'while [ $i -le 8 ]; do '
        '  echo $i >> "$CKPT_DIR/progress"; '
        '  if [ $i -eq 3 ]; then '
        '    while [ ! -f "$CKPT_DIR/release" ]; do sleep 0.2; done; fi; '
        '  i=$((i+1)); sleep 0.1; '
        'done')
    task = _local_task('train', run, envs={'CKPT_DIR': str(ckpt)})
    try:
        job_id = jobs.launch(task, name='train')
        cluster_name = f'train-{job_id}'

        # Wait until the task is provably mid-run (blocked at step 3),
        # then preempt out-of-band and release the gate.
        deadline = time.time() + 90
        while time.time() < deadline:
            if progress.exists() and \
                    len(progress.read_text().split()) >= 3:
                break
            time.sleep(0.1)
        assert progress.exists(), 'task never started writing steps'
        local_instance.terminate_instances('local', cluster_name)
        release.write_text('go')

        assert _wait_managed(job_id, timeout=120) == 'SUCCEEDED'
        steps = [int(s) for s in progress.read_text().split()]
        assert steps == list(range(1, 9)), (
            f'steps re-ran or were skipped after recovery: {steps}')
        rec = [r for r in jobs.queue() if r['job_id'] == job_id][0]
        assert rec['recovery_count'] >= 1
    finally:
        _down_controller()


def test_managed_job_cancel(tmp_path):
    task = _local_task('cj', 'sleep 120')
    try:
        job_id = jobs.launch(task, name='cj')
        deadline = time.time() + 60
        while time.time() < deadline:
            if jobs.job_status(job_id) == 'RUNNING':
                break
            time.sleep(0.2)
        assert jobs.job_status(job_id) == 'RUNNING'
        assert jobs.cancel(job_id)
        assert _wait_managed(job_id) == 'CANCELLED'
        # Task cluster torn down by the controller.
        deadline = time.time() + 30
        while time.time() < deadline:
            if global_state.get_cluster_from_name(f'cj-{job_id}') is None:
                break
            time.sleep(0.2)
        assert global_state.get_cluster_from_name(f'cj-{job_id}') is None
    finally:
        _down_controller()


def test_managed_job_pipeline_chain(tmp_path):
    """Two-task chain: task B starts only after task A succeeds."""
    out = tmp_path / 'chain.txt'
    a = _local_task('a', f'echo A >> {out}')
    b = _local_task('b', f'echo B >> {out}')
    with sky.Dag(name='pipe') as dag:
        dag.add(a)
        dag.add(b)
        dag.add_edge(a, b)
    try:
        job_id = jobs.launch(dag, name='pipe')
        assert _wait_managed(job_id, timeout=120) == 'SUCCEEDED'
        assert out.read_text().split() == ['A', 'B']
    finally:
        _down_controller()


def test_managed_job_user_failure_is_not_recovered(tmp_path):
    """User-code failure (non-zero exit on a healthy cluster) must fail
    the job, not trigger recovery (reference discrimination:
    FAILED vs cluster-gone)."""
    task = _local_task('bad', 'exit 3')
    try:
        job_id = jobs.launch(task, name='bad')
        assert _wait_managed(job_id) == 'FAILED'
        rec = [r for r in jobs.queue() if r['job_id'] == job_id][0]
        assert rec['recovery_count'] == 0
    finally:
        _down_controller()


def test_managed_job_translates_local_workdir_and_mounts(tmp_path):
    """A managed job with a local workdir and file_mount: the dag is
    rewritten to bucket URIs before controller submission (reference
    ``controller_utils.maybe_translate_local_file_mounts_and_sync_up``,
    ``sky/utils/controller_utils.py:663``), so a controller on another
    machine could launch it — and the task still sees its files."""
    workdir = tmp_path / 'proj'
    workdir.mkdir()
    (workdir / 'hello.txt').write_text('from-workdir')
    datadir = tmp_path / 'data'
    datadir.mkdir()
    (datadir / 'd.txt').write_text('from-mount')

    out = tmp_path / 'out.txt'
    task = Task(name='mjt',
                run=(f'cat hello.txt > {out} && '
                     f'cat ~/mounted/d.txt >> {out}'),
                workdir=str(workdir),
                file_mounts={'~/mounted': str(datadir)})
    task.set_resources(sky.Resources(cloud='local', cpus='1+'))
    try:
        job_id = jobs.launch(task, name='mjt')
        # The submitted task no longer references the client-local paths.
        assert task.workdir is None
        assert all('://' in src for src in task.file_mounts.values()), \
            task.file_mounts
        assert _wait_managed(job_id) == 'SUCCEEDED'
        assert out.read_text() == 'from-workdirfrom-mount'
    finally:
        _down_controller()
