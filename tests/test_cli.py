"""CLI tests: click commands driven through CliRunner against the local
provisioner (hermetic counterpart of the reference's CLI smoke tests;
command surface per ``sky/cli.py``)."""
import time

import pytest
from click.testing import CliRunner

from skypilot_tpu import cli

pytestmark = pytest.mark.usefixtures('tmp_state_dir', 'fast_agent')


@pytest.fixture()
def fast_agent(monkeypatch):
    monkeypatch.setenv('SKYTPU_AGENT_TICK', '0.1')
    monkeypatch.setenv('SKYTPU_AGENT_READY_TIMEOUT', '30')


@pytest.fixture()
def runner():
    return CliRunner()


@pytest.fixture()
def task_yaml(tmp_path):
    p = tmp_path / 'task.yaml'
    p.write_text(
        'name: clitask\n'
        'resources:\n'
        '  cloud: local\n'
        '  cpus: 1+\n'
        f'run: echo cli-out-$((40+2)) > {tmp_path}/out.txt\n')
    return str(p)


def _ok(result):
    assert result.exit_code == 0, result.output
    return result.output


class TestBasics:

    def test_help_lists_commands(self, runner):
        out = _ok(runner.invoke(cli.cli, ['--help']))
        for cmd in ('launch', 'status', 'queue', 'logs', 'down', 'jobs',
                    'serve', 'show-tpus', 'check', 'cost-report'):
            assert cmd in out

    def test_version(self, runner):
        assert '0.1.0' in _ok(runner.invoke(cli.cli, ['--version']))

    def test_model_server_help_and_validation(self, runner):
        out = _ok(runner.invoke(cli.cli, ['model-server', '--help']))
        for opt in ('--speculate-k', '--kv-cache', '--quantize',
                    '--prefill-chunk-tokens', '--page-size'):
            assert opt in out
        # --page-size only applies to the paged cache (mirrors the
        # serve/server.py argparse contract).
        bad = runner.invoke(cli.cli, ['model-server', '--kv-cache',
                                      'slot', '--page-size', '128'])
        assert bad.exit_code != 0
        assert 'page-size' in bad.output

    def test_status_empty(self, runner):
        assert 'No existing clusters' in _ok(
            runner.invoke(cli.cli, ['status']))

    def test_jobs_queue_without_controller(self, runner):
        assert 'No managed jobs' in _ok(
            runner.invoke(cli.cli, ['jobs', 'queue']))

    def test_serve_status_without_controller(self, runner):
        assert 'No services' in _ok(
            runner.invoke(cli.cli, ['serve', 'status']))

    def test_show_tpus(self, runner):
        out = _ok(runner.invoke(cli.cli, ['show-tpus']))
        assert 'tpu-v5litepod-8' in out or 'tpu-v' in out

    def test_check(self, runner):
        out = _ok(runner.invoke(cli.cli, ['check']))
        assert 'local: enabled' in out

    def test_down_requires_target(self, runner):
        result = runner.invoke(cli.cli, ['down'])
        assert result.exit_code != 0
        assert '--all' in result.output

    def test_env_validation(self, runner, task_yaml):
        result = runner.invoke(
            cli.cli, ['launch', task_yaml, '--dryrun', '--env', 'NOEQUALS'])
        assert result.exit_code != 0
        assert 'KEY=VALUE' in result.output

    def test_env_override_interpolates_outside_run(self, tmp_path):
        """--env must take effect before ${VAR} interpolation, so it can
        steer fields like workdir, not just the run script's env."""
        (tmp_path / 'wd-b').mkdir()
        p = tmp_path / 'envtask.yaml'
        p.write_text(
            'name: envtask\n'
            'envs:\n'
            '  WD: wd-a\n'
            f'workdir: {tmp_path}/${{WD}}\n'
            'run: echo hi\n')
        task = cli._load_task(str(p), env=('WD=wd-b',))
        assert task.workdir == f'{tmp_path}/wd-b'

    def test_all_excludes_controller_clusters(self, runner, monkeypatch):
        import skypilot_tpu as sky
        # Patch the sky-module bindings (the lazy SDK caches resolved
        # attrs in skypilot_tpu's globals, which is what cli calls).
        monkeypatch.setattr(
            sky, 'status',
            lambda *a, **k: [{'name': 'skytpu-jobs-controller'},
                             {'name': 'skytpu-serve-controller'},
                             {'name': 'usercluster'}], raising=False)
        downed = []
        monkeypatch.setattr(sky, 'down', downed.append, raising=False)
        out = _ok(runner.invoke(cli.cli, ['down', '--all', '-y']))
        assert downed == ['usercluster'], out

    def test_all_with_no_clusters_is_noop(self, runner):
        out = _ok(runner.invoke(cli.cli, ['down', '--all', '-y']))
        assert 'No existing clusters' in out


class TestLifecycle:

    def test_launch_dryrun(self, runner, task_yaml):
        out = _ok(runner.invoke(cli.cli, ['launch', task_yaml, '--dryrun']))
        assert 'Optimizer plan' in out

    def test_launch_status_queue_logs_down(self, runner, task_yaml,
                                           tmp_path):
        out = _ok(runner.invoke(
            cli.cli, ['launch', task_yaml, '-c', 'clic', '-y', '-d']))
        assert 'Job submitted (id: 1)' in out

        out = _ok(runner.invoke(cli.cli, ['status']))
        assert 'clic' in out and 'UP' in out

        deadline = time.time() + 45
        while time.time() < deadline:
            out = _ok(runner.invoke(cli.cli, ['queue', 'clic']))
            if 'SUCCEEDED' in out:
                break
            time.sleep(0.5)
        assert 'SUCCEEDED' in out
        assert (tmp_path / 'out.txt').read_text().strip() == 'cli-out-42'

        out = _ok(runner.invoke(
            cli.cli, ['logs', 'clic', '1', '--no-follow']))
        assert 'cli-out' in out or 'SUCCEEDED' in out

        out = _ok(runner.invoke(cli.cli, ['cost-report']))
        assert 'clic' in out

        out = _ok(runner.invoke(cli.cli, ['down', 'clic', '-y']))
        assert 'terminated' in out
        assert 'No existing clusters' in _ok(
            runner.invoke(cli.cli, ['status']))

    def test_autostop_arm_and_cancel(self, runner, task_yaml):
        _ok(runner.invoke(
            cli.cli, ['launch', task_yaml, '-c', 'autoc', '-y', '-d']))
        out = _ok(runner.invoke(
            cli.cli, ['autostop', 'autoc', '-i', '30']))
        assert 'autostop after 30' in out
        out = _ok(runner.invoke(cli.cli, ['autostop', 'autoc', '--cancel']))
        assert 'cancelled' in out
        _ok(runner.invoke(cli.cli, ['down', 'autoc', '-y']))
