"""CLI tests: click commands driven through CliRunner against the local
provisioner (hermetic counterpart of the reference's CLI smoke tests;
command surface per ``sky/cli.py``)."""
import time

import pytest
from click.testing import CliRunner

from skypilot_tpu import cli

pytestmark = pytest.mark.usefixtures('tmp_state_dir', 'fast_agent')


@pytest.fixture()
def fast_agent(monkeypatch):
    monkeypatch.setenv('SKYTPU_AGENT_TICK', '0.1')
    monkeypatch.setenv('SKYTPU_AGENT_READY_TIMEOUT', '30')


@pytest.fixture()
def runner():
    return CliRunner()


@pytest.fixture()
def task_yaml(tmp_path):
    p = tmp_path / 'task.yaml'
    p.write_text(
        'name: clitask\n'
        'resources:\n'
        '  cloud: local\n'
        '  cpus: 1+\n'
        f'run: echo cli-out-$((40+2)) > {tmp_path}/out.txt\n')
    return str(p)


def _ok(result):
    assert result.exit_code == 0, result.output
    return result.output


class TestBasics:

    def test_help_lists_commands(self, runner):
        out = _ok(runner.invoke(cli.cli, ['--help']))
        for cmd in ('launch', 'status', 'queue', 'logs', 'down', 'jobs',
                    'serve', 'show-tpus', 'check', 'cost-report'):
            assert cmd in out

    def test_version(self, runner):
        assert '0.1.0' in _ok(runner.invoke(cli.cli, ['--version']))

    def test_model_server_help_and_validation(self, runner):
        out = _ok(runner.invoke(cli.cli, ['model-server', '--help']))
        for opt in ('--speculate-k', '--kv-cache', '--quantize',
                    '--prefill-chunk-tokens', '--page-size'):
            assert opt in out
        # --page-size only applies to the paged cache (mirrors the
        # serve/server.py argparse contract).
        bad = runner.invoke(cli.cli, ['model-server', '--kv-cache',
                                      'slot', '--page-size', '128'])
        assert bad.exit_code != 0
        assert 'page-size' in bad.output

    def test_status_empty(self, runner):
        assert 'No existing clusters' in _ok(
            runner.invoke(cli.cli, ['status']))

    def test_jobs_queue_without_controller(self, runner):
        assert 'No managed jobs' in _ok(
            runner.invoke(cli.cli, ['jobs', 'queue']))

    def test_serve_status_without_controller(self, runner):
        assert 'No services' in _ok(
            runner.invoke(cli.cli, ['serve', 'status']))

    def test_show_tpus(self, runner):
        out = _ok(runner.invoke(cli.cli, ['show-tpus']))
        assert 'tpu-v5litepod-8' in out or 'tpu-v' in out

    def test_check(self, runner):
        out = _ok(runner.invoke(cli.cli, ['check']))
        assert 'local: enabled' in out

    def test_down_requires_target(self, runner):
        result = runner.invoke(cli.cli, ['down'])
        assert result.exit_code != 0
        assert '--all' in result.output

    def test_env_validation(self, runner, task_yaml):
        result = runner.invoke(
            cli.cli, ['launch', task_yaml, '--dryrun', '--env', 'NOEQUALS'])
        assert result.exit_code != 0
        assert 'KEY=VALUE' in result.output

    def test_env_override_interpolates_outside_run(self, tmp_path):
        """--env must take effect before ${VAR} interpolation, so it can
        steer fields like workdir, not just the run script's env."""
        (tmp_path / 'wd-b').mkdir()
        p = tmp_path / 'envtask.yaml'
        p.write_text(
            'name: envtask\n'
            'envs:\n'
            '  WD: wd-a\n'
            f'workdir: {tmp_path}/${{WD}}\n'
            'run: echo hi\n')
        task = cli._load_task(str(p), env=('WD=wd-b',))
        assert task.workdir == f'{tmp_path}/wd-b'

    def test_all_excludes_controller_clusters(self, runner, monkeypatch):
        import skypilot_tpu as sky
        # Patch the sky-module bindings (the lazy SDK caches resolved
        # attrs in skypilot_tpu's globals, which is what cli calls).
        monkeypatch.setattr(
            sky, 'status',
            lambda *a, **k: [{'name': 'skytpu-jobs-controller'},
                             {'name': 'skytpu-serve-controller'},
                             {'name': 'usercluster'}], raising=False)
        downed = []
        monkeypatch.setattr(sky, 'down', downed.append, raising=False)
        out = _ok(runner.invoke(cli.cli, ['down', '--all', '-y']))
        assert downed == ['usercluster'], out

    def test_all_with_no_clusters_is_noop(self, runner):
        out = _ok(runner.invoke(cli.cli, ['down', '--all', '-y']))
        assert 'No existing clusters' in out


class TestFleetCli:
    """`skytpu fleet` / `skytpu telemetry dump --fleet` against a REAL
    controller whose aggregator was populated by the simulator (the
    sim drives the identical FleetAggregator code on the virtual
    clock), served over its real HTTP handler."""

    @pytest.fixture()
    def fleet_controller_url(self):
        import http.server as hs
        import threading

        from skypilot_tpu.serve import replica_managers
        from skypilot_tpu.serve.service_spec import SkyServiceSpec
        from skypilot_tpu.serve.sim import replica as sim_replica
        from skypilot_tpu.serve.sim import traffic as sim_traffic
        from skypilot_tpu.serve.sim.fleet import FleetSimulator
        from skypilot_tpu.utils import common_utils
        sim = FleetSimulator(
            spec=SkyServiceSpec(
                readiness_path='/readiness', min_replicas=2,
                max_replicas=2,
                slos={'latency': {'ttft_ms': 2000.0, 'target': 0.9}}),
            trace=sim_traffic.constant(4.0, 120.0), seed=0,
            curve=sim_replica.ServiceCurve(
                ttft_base_s=0.1, warm_ttft_base_s=0.05,
                prefill_tok_per_s=2000.0, tpot_s=0.02, slots=4,
                max_queue_wait_s=5.0, kv_pool_tokens=4000),
            provision_s=10.0, provision_jitter=0.0, keep_log=False)
        sim.run()
        assert sim.controller.fleet.source_count() > 0
        port = common_utils.find_free_port(21500)
        httpd = hs.ThreadingHTTPServer(('127.0.0.1', port),
                                       sim.controller._make_handler())
        threading.Thread(target=httpd.serve_forever,
                         daemon=True).start()
        try:
            yield f'http://127.0.0.1:{port}'
        finally:
            httpd.shutdown()
            httpd.server_close()

    def test_fleet_top_smoke(self, runner, fleet_controller_url):
        out = _ok(runner.invoke(
            cli.cli, ['fleet', 'top', '--url', fleet_controller_url]))
        assert 'sources' in out and 'scrapes' in out
        assert 'TTFT_MEAN_MS' in out           # sim traffic was scraped
        assert 'slo latency' in out
        assert 'burn_5m=' in out and 'burn_1h=' in out

    def test_fleet_slo_and_trace_listing(self, runner,
                                         fleet_controller_url):
        import json
        out = _ok(runner.invoke(
            cli.cli, ['fleet', 'slo', '--url', fleet_controller_url]))
        slo = json.loads(out)
        assert 'latency' in slo
        assert {'attainment', 'burn_5m', 'burn_1h'} <= set(
            slo['latency'])
        out = _ok(runner.invoke(
            cli.cli, ['fleet', 'trace', '--url', fleet_controller_url]))
        ids = [line for line in out.splitlines() if line]
        assert ids                              # completed traces shipped
        assembled = json.loads(_ok(runner.invoke(
            cli.cli, ['fleet', 'trace', '--url', fleet_controller_url,
                      ids[0]])))
        assert assembled['trace_id'] == ids[0]
        assert assembled['spans']

    def test_fleet_trace_unknown_id_fails(self, runner,
                                          fleet_controller_url):
        result = runner.invoke(
            cli.cli, ['fleet', 'trace', '--url', fleet_controller_url,
                      'ff' * 16])
        assert result.exit_code != 0
        assert 'not found' in result.output

    def test_telemetry_dump_fleet_flags_require_url(self, runner):
        for args in (['telemetry', 'dump', '--fleet'],
                     ['telemetry', 'dump', '--trace', 'ab' * 16]):
            result = runner.invoke(cli.cli, args)
            assert result.exit_code != 0
            assert 'require --url' in result.output

    def test_telemetry_dump_fleet_view(self, runner,
                                       fleet_controller_url):
        out = _ok(runner.invoke(
            cli.cli, ['telemetry', 'dump', '--fleet', '--url',
                      fleet_controller_url]))
        assert 'skytpu_fleet_sources' in out    # prometheus exposition
        assert 'skytpu_slo_burn_rate' in out


class TestLifecycle:

    def test_launch_dryrun(self, runner, task_yaml):
        out = _ok(runner.invoke(cli.cli, ['launch', task_yaml, '--dryrun']))
        assert 'Optimizer plan' in out

    def test_launch_status_queue_logs_down(self, runner, task_yaml,
                                           tmp_path):
        out = _ok(runner.invoke(
            cli.cli, ['launch', task_yaml, '-c', 'clic', '-y', '-d']))
        assert 'Job submitted (id: 1)' in out

        out = _ok(runner.invoke(cli.cli, ['status']))
        assert 'clic' in out and 'UP' in out

        deadline = time.time() + 45
        while time.time() < deadline:
            out = _ok(runner.invoke(cli.cli, ['queue', 'clic']))
            if 'SUCCEEDED' in out:
                break
            time.sleep(0.5)
        assert 'SUCCEEDED' in out
        assert (tmp_path / 'out.txt').read_text().strip() == 'cli-out-42'

        out = _ok(runner.invoke(
            cli.cli, ['logs', 'clic', '1', '--no-follow']))
        assert 'cli-out' in out or 'SUCCEEDED' in out

        out = _ok(runner.invoke(cli.cli, ['cost-report']))
        assert 'clic' in out

        out = _ok(runner.invoke(cli.cli, ['down', 'clic', '-y']))
        assert 'terminated' in out
        assert 'No existing clusters' in _ok(
            runner.invoke(cli.cli, ['status']))

    def test_autostop_arm_and_cancel(self, runner, task_yaml):
        _ok(runner.invoke(
            cli.cli, ['launch', task_yaml, '-c', 'autoc', '-y', '-d']))
        out = _ok(runner.invoke(
            cli.cli, ['autostop', 'autoc', '-i', '30']))
        assert 'autostop after 30' in out
        out = _ok(runner.invoke(cli.cli, ['autostop', 'autoc', '--cancel']))
        assert 'cancelled' in out
        _ok(runner.invoke(cli.cli, ['down', 'autoc', '-y']))
