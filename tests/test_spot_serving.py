"""Spot-resilient serving (round 10): forecast-aware autoscaling,
preemption-survivable replicas, and prefix-cache checkpoint/warmup.

The contracts under test:

- The forecaster is pure and clock-injected (no sleeps): synthetic
  diurnal/bursty traces replay to identical forecasts, and the
  forecast autoscaler pre-scales *ahead* of a ramp by the learned
  provisioning lead time (strictly fewer modeled sheds than the
  reactive autoscaler on the identical trace).
- ``max_replicas: None`` means UNBOUNDED autoscaling — the target must
  never silently collapse to ``min_replicas``.
- On a preemption warning the replica's hot prefix-cache chains (and
  in-flight request snapshots) checkpoint through the SKKV/SKPF wire
  codec, and a recovered replica lands them BEFORE it enters rotation:
  the first prefix-hit continuation is byte-identical to the
  pre-preemption run, on both engines.
- Seeded spot kills through the LB lose ZERO requests.
"""
import json
import os
import threading
import time
import urllib.error
import urllib.request

import jax
import numpy as np
import pytest

from skypilot_tpu import telemetry
from skypilot_tpu.inference import kv_transfer
from skypilot_tpu.serve import autoscalers as asc_lib
from skypilot_tpu.serve import faults as faults_lib
from skypilot_tpu.serve import forecaster as forecaster_lib
from skypilot_tpu.serve.autoscalers import DecisionOperator, ReplicaView
from skypilot_tpu.serve.service_spec import SkyServiceSpec
from skypilot_tpu.utils import common_utils

jax.config.update('jax_platforms', 'cpu')


def _spec(**kw):
    defaults = dict(readiness_path='/readiness', min_replicas=1,
                    max_replicas=4, target_qps_per_replica=1.0,
                    upscale_delay_seconds=20.0,
                    downscale_delay_seconds=40.0)
    defaults.update(kw)
    return SkyServiceSpec(**defaults)


def _diurnal_trace(t0, seasons=3, season_s=300.0, burst_s=60.0,
                   base_qps=0.5, burst_qps=6.0):
    """Deterministic 'diurnal' arrivals: a quiet base rate with one
    burst window per season. Returns sorted timestamps."""
    out = []
    t = t0
    end = t0 + seasons * season_s
    while t < end:
        phase = (t - t0) % season_s
        rate = burst_qps if phase < burst_s else base_qps
        out.append(t)
        t += 1.0 / rate
    return out


# ---------------------------------------------------------------- forecaster
class TestForecaster:

    def test_flat_traffic_level(self):
        f = forecaster_lib.TrafficForecaster(bucket_s=10.0,
                                             season_s=300.0)
        t0 = 10_000.0
        f.observe([t0 + i * 0.5 for i in range(600)])   # 2 qps, 300 s
        now = t0 + 300.0
        assert f.qps('all', now) == pytest.approx(2.0, rel=0.15)
        # Flat traffic: every horizon forecasts ~the level.
        for h in (0.0, 30.0, 120.0):
            assert f.forecast_qps(h, 'all', now) == pytest.approx(
                2.0, rel=0.25), h

    def test_ramp_trend_projects_ahead(self):
        f = forecaster_lib.TrafficForecaster(bucket_s=10.0,
                                             season_s=10_000.0)
        t0 = 50_000.0
        # Linearly accelerating arrivals: bucket i carries i+1 events.
        ts = []
        for i in range(12):
            ts.extend(t0 + i * 10.0 + j * (10.0 / (i + 1))
                      for j in range(i + 1))
        f.observe(ts)
        now = t0 + 120.0
        level = f.qps('all', now)
        ahead = f.forecast_qps(60.0, 'all', now)
        assert ahead > level          # the trend projects the ramp on

    def test_seasonal_burst_predicted_before_it_lands(self):
        season = 300.0
        f = forecaster_lib.TrafficForecaster(bucket_s=10.0,
                                             season_s=season)
        t0 = 100_000.0
        f.observe(_diurnal_trace(t0, seasons=2, season_s=season))
        # Now sits in the QUIET phase just before season 3's burst.
        now = t0 + 2 * season - 30.0
        quiet = f.qps('all', now)
        # 40 s ahead lands inside the (seasonal) burst window.
        ahead = f.forecast_qps(40.0, 'all', now)
        assert quiet < 1.5
        assert ahead > 3.0            # seasonal component saw the burst
        assert ahead > 2 * quiet

    def test_ring_is_bounded(self):
        f = forecaster_lib.TrafficForecaster(bucket_s=1.0,
                                             season_s=10.0,
                                             ring_buckets=32)
        f.observe([float(i) for i in range(10_000)])
        assert len(f._counts['all']) <= 32

    def test_per_tier_series(self):
        f = forecaster_lib.TrafficForecaster(bucket_s=10.0,
                                             season_s=300.0)
        t0 = 1_000.0
        ts = [t0 + i * 0.5 for i in range(200)]
        tiers = ['latency' if i % 4 == 0 else 'throughput'
                 for i in range(200)]
        f.observe(ts, tiers)
        now = t0 + 100.0
        assert f.qps('all', now) > 0
        assert f.qps('throughput', now) > f.qps('latency', now) > 0

    def test_deterministic_replay(self):
        trace = _diurnal_trace(5_000.0)
        outs = []
        for _ in range(2):
            f = forecaster_lib.TrafficForecaster(bucket_s=10.0,
                                                 season_s=300.0)
            f.observe(trace)
            outs.append([f.forecast_qps(h, 'all', 5_000.0 + 700.0)
                         for h in (0, 30, 60, 120)])
        assert outs[0] == outs[1]


# -------------------------------------------------------- autoscaler units
class TestUnboundedMaxReplicas:

    def test_none_max_means_unbounded(self):
        # Satellite fix: the raw target used to collapse to
        # min_replicas whenever max_replicas was None.
        asc = asc_lib.RequestRateAutoscaler(
            _spec(max_replicas=None, upscale_delay_seconds=20.0))
        now = 1000.0
        asc.collect_request_information(
            [now - i * 0.01 for i in range(6000)])    # ~100 qps
        assert asc.evaluate_scaling([ReplicaView(1, True, False)],
                                    now=now) == []    # breach t0
        decisions = asc.evaluate_scaling([ReplicaView(1, True, False)],
                                         now=now + 20.0)
        ups = [d for d in decisions
               if d.operator == DecisionOperator.SCALE_UP]
        assert len(ups) >= 50         # NOT clamped back to min=1

    def test_update_spec_none_max_keeps_target(self):
        asc = asc_lib.RequestRateAutoscaler(_spec(max_replicas=8))
        asc.target_num_replicas = 6
        asc.update_spec(_spec(max_replicas=None), version=2)
        assert asc.target_num_replicas == 6   # not collapsed to 1
        asc.update_spec(_spec(max_replicas=3), version=3)
        assert asc.target_num_replicas == 3   # explicit bound applies

    def test_spec_yaml_unbounded_roundtrip(self):
        spec = SkyServiceSpec.from_yaml_config({
            'readiness_probe': '/readiness',
            'replica_policy': {'min_replicas': 2,
                               'target_qps_per_replica': 1.5},
        })
        assert spec.autoscaling_enabled
        assert spec.max_replicas is None
        spec2 = SkyServiceSpec.from_yaml_config(spec.to_yaml_config())
        assert spec2.max_replicas is None
        assert spec2 == spec

    def test_pending_timestamps_bounded_between_trims(self):
        asc = asc_lib.RequestRateAutoscaler(_spec())
        asc.MAX_PENDING_TIMESTAMPS = 500
        base = 10_000.0
        for wave in range(10):
            asc.collect_request_information(
                [base + wave + i * 1e-4 for i in range(200)])
        assert len(asc._request_timestamps) <= 500
        # The newest timestamps survive the cap.
        assert max(asc._request_timestamps) >= base + 9


class TestForecastAutoscaler:

    def _forecast_spec(self, **kw):
        defaults = dict(forecast_enabled=True,
                        forecast_bucket_seconds=10.0,
                        forecast_season_seconds=300.0,
                        forecast_horizon_seconds=60.0,
                        upscale_delay_seconds=10.0,
                        downscale_delay_seconds=20.0,
                        initial_delay_seconds=40.0)
        defaults.update(kw)
        return _spec(**defaults)

    def test_from_spec_selects_forecast_classes(self):
        asc = asc_lib.Autoscaler.from_spec(self._forecast_spec())
        assert isinstance(asc, asc_lib.ForecastRequestRateAutoscaler)
        asc = asc_lib.Autoscaler.from_spec(
            self._forecast_spec(dynamic_ondemand_fallback=True))
        assert isinstance(asc, asc_lib.ForecastFallbackAutoscaler)

    def test_lead_time_learned_from_provision_observations(self):
        asc = asc_lib.Autoscaler.from_spec(self._forecast_spec())
        assert asc.provision_lead_s() == 40.0      # spec default
        asc.note_provision_seconds(100.0)
        assert asc.provision_lead_s() == pytest.approx(100.0)
        asc.note_provision_seconds(20.0)           # EWMA moves toward it
        assert 20.0 < asc.provision_lead_s() < 100.0

    def test_prescales_ahead_of_seasonal_burst(self):
        """The headline behavior: at a QUIET moment whose lead window
        contains the (seasonal) burst, the forecast autoscaler's raw
        target already exceeds the reactive one."""
        season = 300.0
        asc = asc_lib.Autoscaler.from_spec(self._forecast_spec())
        t0 = 100_000.0
        asc.collect_request_information(
            _diurnal_trace(t0, seasons=2, season_s=season))
        asc.note_provision_seconds(40.0)
        now = t0 + 2 * season - 30.0   # quiet; burst lands in ~30 s
        reactive = asc._reactive_target(now)
        raw = asc._raw_target(now)
        assert reactive == 1           # the window sees only quiet
        assert raw >= 3                # the forecast sees the burst

    def test_never_drains_midburst(self):
        season = 300.0
        asc = asc_lib.Autoscaler.from_spec(self._forecast_spec())
        t0 = 100_000.0
        asc.collect_request_information(
            _diurnal_trace(t0, seasons=2, season_s=season))
        asc.note_provision_seconds(40.0)
        asc.target_num_replicas = 4
        now = t0 + 2 * season - 30.0   # burst inside the lead window
        assert not asc._downscale_allowed(1, now)
        # Deep inside the quiet phase with no burst in the window,
        # scale-down clears.
        quiet_now = t0 + 2 * season + 120.0
        asc.collect_request_information(
            [quiet_now - 60 + i * 2.0 for i in range(30)])
        assert asc._downscale_allowed(3, quiet_now)

    def test_forecast_sheds_strictly_fewer_than_reactive(self):
        """Capacity simulation over the identical diurnal trace:
        arrivals beyond (replicas x target_qps) in any second count as
        shed. Forecast pre-scaling must shed strictly less — the bench
        `spot` block records the same comparison on live servers."""
        season = 300.0
        trace = _diurnal_trace(0.0, seasons=4, season_s=season,
                               burst_qps=8.0)
        qps_per = 2.0

        def simulate(asc, lead_known):
            if lead_known and hasattr(asc, 'note_provision_seconds'):
                asc.note_provision_seconds(30.0)
            shed = 0
            replicas = [ReplicaView(1, True, False)]
            pending_ready = []      # (ready_at, view)
            next_id = 2
            idx = 0
            for now in np.arange(0.0, 4 * season, 10.0):
                batch = []
                while idx < len(trace) and trace[idx] < now:
                    batch.append(trace[idx])
                    idx += 1
                asc.collect_request_information(batch)
                # Replicas provision with a 30 s lead.
                pending_ready = [(t, v) for t, v in pending_ready
                                 if t > now or replicas.append(v)]
                decisions = asc.evaluate_scaling(
                    replicas + [v for _, v in pending_ready], now=now)
                for d in decisions:
                    if d.operator == DecisionOperator.SCALE_UP:
                        pending_ready.append(
                            (now + 30.0,
                             ReplicaView(next_id, True, False)))
                        next_id += 1
                    else:
                        rid = d.target['replica_id']
                        replicas = [v for v in replicas
                                    if v.replica_id != rid]
                # Shed accounting: arrivals this tick beyond capacity.
                cap = len(replicas) * qps_per * 10.0
                shed += max(0, len(batch) - int(cap))
            return shed

        reactive = asc_lib.RequestRateAutoscaler(
            _spec(target_qps_per_replica=qps_per, max_replicas=8,
                  upscale_delay_seconds=10.0,
                  downscale_delay_seconds=60.0))
        forecast = asc_lib.Autoscaler.from_spec(self._forecast_spec(
            target_qps_per_replica=qps_per, max_replicas=8,
            upscale_delay_seconds=10.0, downscale_delay_seconds=60.0,
            forecast_season_seconds=season))
        shed_reactive = simulate(reactive, lead_known=False)
        shed_forecast = simulate(forecast, lead_known=True)
        assert shed_forecast < shed_reactive, (shed_forecast,
                                               shed_reactive)


class TestFallbackBackfillMatrix:
    """Dynamic on-demand backfill decision matrix: (ready spot,
    pending spot, on-demand) in -> (spot ups, od ups, downs) out."""

    def _asc(self, target=3, base=0):
        spec = _spec(min_replicas=3, max_replicas=6,
                     base_ondemand_fallback_replicas=base,
                     dynamic_ondemand_fallback=True)
        asc = asc_lib.Autoscaler.from_spec(spec)
        assert isinstance(asc, asc_lib.FallbackRequestRateAutoscaler)
        asc.target_num_replicas = target
        return asc

    @staticmethod
    def _classify(decisions):
        spot_up = sum(1 for d in decisions
                      if d.operator == DecisionOperator.SCALE_UP
                      and d.target['use_spot'])
        od_up = sum(1 for d in decisions
                    if d.operator == DecisionOperator.SCALE_UP
                    and not d.target['use_spot'])
        downs = [d.target['replica_id'] for d in decisions
                 if d.operator == DecisionOperator.SCALE_DOWN]
        return spot_up, od_up, downs

    def test_all_spot_ready_no_backfill(self):
        views = [ReplicaView(i, True, True) for i in (1, 2, 3)]
        assert self._classify(self._asc().evaluate_scaling(
            views, now=1e3)) == (0, 0, [])

    def test_one_spot_preempted_backfills_od_and_respawns_spot(self):
        views = [ReplicaView(1, True, True), ReplicaView(2, True, True),
                 ReplicaView(3, False, True, is_terminal=True)]
        spot_up, od_up, downs = self._classify(
            self._asc().evaluate_scaling(views, now=1e3))
        assert (spot_up, od_up, downs) == (1, 1, [])

    def test_spot_recovering_not_ready_keeps_backfill(self):
        # Replacement spot is provisioning (alive, not ready): the
        # temporary on-demand replica must NOT be drained yet.
        views = [ReplicaView(1, True, True), ReplicaView(2, True, True),
                 ReplicaView(3, False, True),       # provisioning spot
                 ReplicaView(4, True, False)]       # od backfill
        spot_up, od_up, downs = self._classify(
            self._asc().evaluate_scaling(views, now=1e3))
        assert (spot_up, od_up, downs) == (0, 0, [])

    def test_spot_recovered_drains_backfill(self):
        views = [ReplicaView(i, True, True) for i in (1, 2, 3)]
        views.append(ReplicaView(4, True, False))   # od now excess
        spot_up, od_up, downs = self._classify(
            self._asc().evaluate_scaling(views, now=1e3))
        assert (spot_up, od_up, downs) == (0, 0, [4])

    def test_base_ballast_survives_spot_drought(self):
        asc = self._asc(target=3, base=1)
        views = [ReplicaView(1, True, False)]       # ballast od only
        spot_up, od_up, downs = self._classify(
            asc.evaluate_scaling(views, now=1e3))
        # 2 spot wanted + 2 od backfill for the unready spot (capped
        # at target 3 total od: 1 ballast + 2 backfill, have 1).
        assert spot_up == 2 and od_up == 2 and downs == []


# ------------------------------------------------------------ wire codec
class TestCheckpointCodec:

    def _entry(self, n_rows=8, dtype='bf16'):
        import ml_dtypes
        shape = (2, n_rows, 2, 4)
        if dtype == 'int8':
            rng = np.random.RandomState(0)
            return {
                'kv_cache_dtype': 'int8', 'n_rows': n_rows,
                'model': {'n_layers': 2, 'n_kv_heads': 2,
                          'head_dim': 4},
                'tokens': list(range(1, n_rows + 2)),
                'k': rng.randint(-127, 127, shape).astype(np.int8),
                'v': rng.randint(-127, 127, shape).astype(np.int8),
                'k_scale': rng.rand(2, n_rows, 2).astype(np.float32),
                'v_scale': rng.rand(2, n_rows, 2).astype(np.float32),
            }
        rng = np.random.RandomState(1)
        return {
            'kv_cache_dtype': 'bf16', 'n_rows': n_rows,
            'model': {'n_layers': 2, 'n_kv_heads': 2, 'head_dim': 4},
            'tokens': list(range(1, n_rows + 2)),
            'k': rng.rand(*shape).astype(ml_dtypes.bfloat16),
            'v': rng.rand(*shape).astype(ml_dtypes.bfloat16),
            'k_scale': None, 'v_scale': None,
        }

    @pytest.mark.parametrize('dtype', ['bf16', 'int8'])
    def test_prefix_roundtrip_exact(self, dtype):
        entry = self._entry(dtype=dtype)
        out = kv_transfer.decode_prefix_chain(
            kv_transfer.encode_prefix_chain(entry))
        assert out['tokens'] == entry['tokens']
        assert out['n_rows'] == entry['n_rows']
        for key in ('k', 'v'):
            np.testing.assert_array_equal(out[key], entry[key])
            assert out[key].dtype == entry[key].dtype
        if dtype == 'int8':
            np.testing.assert_array_equal(out['k_scale'],
                                          entry['k_scale'])

    def test_prefix_token_count_strict(self):
        entry = self._entry()
        entry['tokens'] = entry['tokens'][:-2]      # != n_rows + 1
        with pytest.raises(ValueError, match='n_rows'):
            kv_transfer.encode_prefix_chain(entry)

    def test_checkpoint_container_mixed_kinds(self):
        prefix = self._entry()
        request = {
            'kv_cache_dtype': 'bf16', 'n_rows': 8,
            'model': {'n_layers': 2, 'n_kv_heads': 2, 'head_dim': 4},
            'prompt': list(range(1, 8)), 'output': [9, 10],
            'max_new_tokens': 16, 'temperature': 0.0, 'top_k': 0,
            'top_p': 1.0, 'eos_id': None, 'stop': None, 'priority': 0,
            'k': prefix['k'], 'v': prefix['v'],
            'k_scale': None, 'v_scale': None,
        }
        blob = kv_transfer.encode_checkpoint([prefix, request])
        out = kv_transfer.decode_checkpoint(blob)
        assert [e['entry_kind'] for e in out] == ['prefix', 'request']
        # A request entry views as a prefix entry with ctx tokens.
        as_p = kv_transfer.as_prefix_entry(out[1])
        assert as_p['tokens'] == request['prompt'] + request['output']
        # Empty checkpoints are valid (cold replica answered anyway).
        assert kv_transfer.decode_checkpoint(
            kv_transfer.encode_checkpoint([])) == []

    def test_checkpoint_strict_rejections(self):
        blob = kv_transfer.encode_checkpoint([self._entry()])
        with pytest.raises(ValueError, match='magic'):
            kv_transfer.decode_checkpoint(b'XXXX' + blob[4:])
        with pytest.raises(ValueError, match='trailing'):
            kv_transfer.decode_checkpoint(blob + b'junk')
        with pytest.raises(ValueError):
            kv_transfer.decode_checkpoint(blob[:-3])   # truncated


# ------------------------------------------- engine checkpoint/recovery
def _make_engine(kind, **kw):
    from skypilot_tpu.models import configs
    cfg = configs.get_config('tiny')
    if kind == 'paged':
        from skypilot_tpu.inference.paged import PagedInferenceEngine
        return PagedInferenceEngine(cfg, max_batch=2, max_seq=256,
                                    telemetry=False, **kw)
    from skypilot_tpu.inference.engine import InferenceEngine
    return InferenceEngine(cfg, max_batch=2, max_seq=256,
                           telemetry=False, **kw)


SHARED_PREFIX = [7 + (j % 50) for j in range(40)]


@pytest.mark.parametrize('kind', ['slot', 'paged'])
def test_preempt_checkpoint_recover_byte_identical(kind):
    """The full preemption->checkpoint->recovery loop at engine level,
    both engines: a request mid-decode checkpoints (SKKV) and resumes
    BYTE-IDENTICALLY on a fresh engine; on the paged engine the hot
    prefix chains additionally checkpoint (SKPF) and a warmed fresh
    engine serves a shared-prefix prompt with a prefix HIT and the
    identical continuation."""
    eng = _make_engine(kind)
    prompt = SHARED_PREFIX + [3, 4, 5]
    rid = eng.add_request(list(prompt), max_new_tokens=12)
    while True:
        eng.step(horizon=1)
        req = next((r for r in eng._slots
                    if r is not None and r.request_id == rid), None)
        if req is not None and len(req.output) >= 4:
            break
    snap, _ = eng.export_kv_snapshot(rid)
    assert snap is not None
    entries = [snap]
    if kind == 'paged':
        pentries, _ = eng.export_prefix_snapshots()
        assert pentries, 'hot prefix chains must export'
        entries += pentries
    blob = kv_transfer.encode_checkpoint(entries)
    # Reference: the uninterrupted run.
    eng.run_to_completion(horizon=8)
    ref = list(eng.pop_finished(rid).output)

    decoded = kv_transfer.decode_checkpoint(blob)
    # (a) In-flight resume: byte-identical continuation on a FRESH
    # engine (both engines).
    eng2 = _make_engine(kind)
    req_entry = next(e for e in decoded
                     if e['entry_kind'] == 'request')
    rid2 = eng2.ingest_kv_snapshot(req_entry)
    eng2.run_to_completion(horizon=8)
    assert list(eng2.pop_finished(rid2).output) == ref

    # (b) Prefix warmup: a warmed fresh paged engine prefix-HITS the
    # shared prefix and continues byte-identically; the slot engine
    # honestly lands nothing (no prefix cache).
    eng3 = _make_engine(kind)
    rows = sum(eng3.warm_prefix(e) for e in decoded)
    if kind == 'slot':
        assert rows == 0
        return
    assert rows > 0
    hits0 = eng3.alloc.prefix_hits
    rid3 = eng3.add_request(list(prompt), max_new_tokens=12)
    eng3.run_to_completion(horizon=8)
    out3 = list(eng3.pop_finished(rid3).output)
    assert eng3.alloc.prefix_hits > hits0   # warm, not recomputed
    # Byte-identical to the pre-preemption engine's continuation of
    # the same prompt.
    rid_ref = eng.add_request(list(prompt), max_new_tokens=12)
    eng.run_to_completion(horizon=8)
    assert out3 == list(eng.pop_finished(rid_ref).output)


def test_warm_prefix_idempotent_and_validated():
    eng = _make_engine('paged')
    prompt = SHARED_PREFIX + [9, 9]
    rid = eng.add_request(list(prompt), max_new_tokens=4)
    eng.run_to_completion(horizon=8)
    eng.pop_finished(rid)
    entries, _ = eng.export_prefix_snapshots()
    assert entries
    eng2 = _make_engine('paged')
    assert sum(eng2.warm_prefix(e) for e in entries) > 0
    # Idempotent: a second warmup of the same chains lands nothing.
    assert sum(eng2.warm_prefix(e) for e in entries) == 0
    # Model mismatch is a loud permanent refusal.
    bad = dict(entries[0])
    bad['model'] = dict(bad['model'], n_kv_heads=99)
    with pytest.raises(ValueError, match='model mismatch'):
        eng2.warm_prefix(bad)


def test_warm_prefix_capacity_refusal_is_retryable():
    from skypilot_tpu.inference.kv_transfer import HandoffCapacityError
    eng = _make_engine('paged')
    long_prompt = [3 + (j % 90) for j in range(150)]
    rid = eng.add_request(list(long_prompt), max_new_tokens=4)
    eng.run_to_completion(horizon=8)
    eng.pop_finished(rid)
    entries, _ = eng.export_prefix_snapshots()
    assert entries
    # A pool too small for the chain refuses retryably.
    tiny = _make_engine('paged', n_pages=3)
    with pytest.raises(HandoffCapacityError):
        for e in entries:
            tiny.warm_prefix(e)


# ------------------------------------------------- replica manager flows
def _make_manager(tmp_path, monkeypatch, **spec_kw):
    monkeypatch.setenv('SKYTPU_SERVE_DIR', str(tmp_path / 'serve'))
    from skypilot_tpu.serve.replica_managers import ReplicaManager
    spec = SkyServiceSpec(readiness_path='/readiness', **spec_kw)
    return ReplicaManager('spot-test', spec, {})


class _FakeReplica:
    """A minimal replica model server: /readiness, /checkpoint (serves
    a canned container), /kv/warmup (records the landing and whether
    the manager had already marked any replica READY), /drain."""

    def __init__(self, ckpt_blob=b'', manager=None):
        import http.server
        outer = self
        self.warmup_calls = []
        self.checkpoint_calls = 0
        self.ready_urls_at_warmup = None

        class H(http.server.BaseHTTPRequestHandler):
            timeout = 30

            def log_message(self, *a):
                del a

            def _send(self, code, body, ctype='application/json'):
                self.send_response(code)
                self.send_header('Content-Type', ctype)
                self.send_header('Content-Length', str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802
                self._send(200, json.dumps(
                    {'status': 'ready', 'draining': True,
                     'drained': True, 'inflight': 0}).encode())

            def do_POST(self):  # noqa: N802
                length = int(self.headers.get('Content-Length', 0))
                data = self.rfile.read(length) if length else b''
                if self.path == '/checkpoint':
                    outer.checkpoint_calls += 1
                    self._send(200, ckpt_blob,
                               'application/octet-stream')
                elif self.path == '/kv/warmup':
                    outer.warmup_calls.append(len(data))
                    if manager is not None:
                        outer.ready_urls_at_warmup = \
                            manager.ready_urls()
                    self._send(200, json.dumps(
                        {'entries': 1, 'warmed_rows': 32,
                         'landed': 1}).encode())
                elif self.path == '/drain':
                    self._send(200, json.dumps(
                        {'draining': True, 'drained': True,
                         'inflight': 0}).encode())
                else:
                    self._send(404, b'{}')

        import http.server as hs
        self.port = common_utils.find_free_port(19800)
        self.httpd = hs.ThreadingHTTPServer(('127.0.0.1', self.port), H)
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()

    @property
    def url(self):
        return f'http://127.0.0.1:{self.port}'

    def stop(self):
        self.httpd.shutdown()


def test_preemption_warning_checkpoints_then_drains(tmp_path,
                                                    monkeypatch):
    from skypilot_tpu.serve import serve_state
    from skypilot_tpu.serve.replica_managers import ReplicaInfo
    mgr = _make_manager(tmp_path, monkeypatch)
    fake = _FakeReplica(ckpt_blob=kv_transfer.encode_checkpoint([]))
    try:
        info = ReplicaInfo(1, 'spot-warn-c', 1, True, fake.port)
        info.url = fake.url
        info.status = serve_state.ReplicaStatus.READY
        with mgr._lock:
            mgr._replicas[1] = info
        preempt0 = mgr._m_spot_preempt.value
        assert mgr.handle_preemption_warning(1, deadline_s=5) is True
        assert fake.checkpoint_calls == 1
        assert mgr.checkpoint_for_warmup() is not None
        assert mgr._m_spot_preempt.value == preempt0 + 1
        deadline = time.time() + 20
        while time.time() < deadline and 1 in mgr._replicas:
            time.sleep(0.1)
        assert 1 not in mgr._replicas
    finally:
        fake.stop()


def test_preemption_warning_racefree_with_inflight_drain(tmp_path,
                                                         monkeypatch):
    """A warning landing while a drain is ALREADY running still
    checkpoints exactly once and never double-drains."""
    from skypilot_tpu.serve import serve_state
    from skypilot_tpu.serve.replica_managers import ReplicaInfo
    mgr = _make_manager(tmp_path, monkeypatch)
    fake = _FakeReplica(ckpt_blob=kv_transfer.encode_checkpoint([]))
    try:
        info = ReplicaInfo(2, 'spot-race-c', 1, True, fake.port)
        info.url = fake.url
        info.status = serve_state.ReplicaStatus.READY
        with mgr._lock:
            mgr._replicas[2] = info
        assert mgr.drain(2, deadline_s=10) is True     # scale-down drain
        # The warning arrives mid-drain: drain() refuses a second
        # drain (idempotent), but the checkpoint still runs.
        assert mgr.handle_preemption_warning(2, deadline_s=10) is False
        assert fake.checkpoint_calls == 1
        assert mgr.checkpoint_for_warmup() is not None
        # And a re-delivered warning does not re-checkpoint.
        mgr.handle_preemption_warning(2, deadline_s=10)
        assert fake.checkpoint_calls == 1
    finally:
        fake.stop()


def test_spot_preemption_site_counts_only_spot(tmp_path, monkeypatch):
    """The seeded spot-kill schedule: `at: 2` on the spot_preemption
    site kills the SECOND SPOT sweep — on-demand replicas never
    advance the counter."""
    from skypilot_tpu.serve import serve_state
    from skypilot_tpu.serve.replica_managers import ReplicaInfo
    mgr = _make_manager(tmp_path, monkeypatch)
    fake = _FakeReplica(ckpt_blob=kv_transfer.encode_checkpoint([]))
    try:
        spot = ReplicaInfo(1, 'spot-a', 1, True, fake.port)
        od = ReplicaInfo(2, 'od-b', 1, False, fake.port)
        for i, info in ((1, spot), (2, od)):
            info.url = fake.url
            info.status = serve_state.ReplicaStatus.READY
            with mgr._lock:
                mgr._replicas[i] = info
        mgr._faults = faults_lib.FaultInjector({'rules': [
            {'kind': 'preempt_signal', 'site': 'spot_preemption',
             'at': 2}]})
        monkeypatch.setattr(mgr, '_check_preempted', lambda info: False)
        monkeypatch.setattr(mgr, '_probe_one', lambda info: True)
        mgr.probe_all()                  # spot sweep #1: no fire
        assert spot.status == serve_state.ReplicaStatus.READY
        assert mgr._faults.site_count('spot_preemption') == 1  # spot only
        mgr.probe_all()                  # spot sweep #2: fires
        assert spot.status in (serve_state.ReplicaStatus.DRAINING,
                               serve_state.ReplicaStatus.SHUTTING_DOWN)
        assert od.status == serve_state.ReplicaStatus.READY
        assert fake.checkpoint_calls == 1
    finally:
        fake.stop()


def test_recovered_replica_warms_before_ready(tmp_path, monkeypatch):
    """The recovery-warmup ordering contract: the stored checkpoint
    lands via /kv/warmup BEFORE the replica is marked READY — it never
    enters ready_urls cold — and the provision latency is observed."""
    from skypilot_tpu.serve import serve_state
    from skypilot_tpu.serve.replica_managers import ReplicaInfo
    mgr = _make_manager(tmp_path, monkeypatch)
    fake = _FakeReplica(manager=mgr)
    try:
        with mgr._ckpt_lock:
            mgr._ckpt_bytes = kv_transfer.encode_checkpoint([])
            mgr._ckpt_time = time.time()
        info = ReplicaInfo(3, 'spot-recover-c', 1, True, fake.port)
        info.url = fake.url
        info.status = serve_state.ReplicaStatus.STARTING
        info.created_time = time.time() - 2.0
        with mgr._lock:
            mgr._replicas[3] = info
        monkeypatch.setattr(mgr, '_check_preempted', lambda i: False)
        monkeypatch.setattr(mgr, '_probe_one', lambda i: True)
        h_warm = telemetry.get_registry().get(
            'skytpu_prefix_warmup_seconds')
        h_prov = telemetry.get_registry().get(
            'skytpu_replica_provision_seconds')
        warm0, prov0 = h_warm.count, h_prov.count
        mgr.probe_all()
        assert info.status == serve_state.ReplicaStatus.READY
        assert fake.warmup_calls == [len(mgr._ckpt_bytes)]
        # At warmup time NO replica was in rotation yet.
        assert fake.ready_urls_at_warmup == []
        assert h_warm.count == warm0 + 1
        assert h_prov.count == prov0 + 1
        assert mgr.pop_provision_observations() == [pytest.approx(
            2.0, abs=1.5)]
        # Warmup runs once per replica, not on every sweep.
        mgr.probe_all()
        assert len(fake.warmup_calls) == 1
    finally:
        fake.stop()


# -------------------------------------------------------- server e2e
def _start_server(port, **kw):
    from skypilot_tpu.serve.server import ModelServer
    kw.setdefault('max_batch', 2)
    kw.setdefault('max_seq', 256)
    srv = ModelServer('tiny', port=port, **kw)
    srv.start(block=False)
    return srv


def _generate(base, payload, timeout=120, headers=None):
    h = {'Content-Type': 'application/json'}
    h.update(headers or {})
    req = urllib.request.Request(base + '/generate',
                                 json.dumps(payload).encode(), h)
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def test_server_checkpoint_warmup_e2e():
    """POST /checkpoint on a warm replica -> POST /kv/warmup on a cold
    one -> the cold replica serves a shared-prefix prompt with the
    byte-identical continuation."""
    p1 = common_utils.find_free_port(19900)
    p2 = common_utils.find_free_port(19950)
    srv1 = _start_server(p1)
    srv2 = _start_server(p2)
    try:
        base1 = f'http://127.0.0.1:{p1}'
        base2 = f'http://127.0.0.1:{p2}'
        srv1._ready.wait(120)
        srv2._ready.wait(120)
        prompt = SHARED_PREFIX + [3, 4, 5]
        ref = _generate(base1, {'prompt': prompt,
                                'max_new_tokens': 8})['tokens']
        req = urllib.request.Request(
            base1 + '/checkpoint', json.dumps({}).encode(),
            {'Content-Type': 'application/json'})
        with urllib.request.urlopen(req, timeout=60) as r:
            blob = r.read()
            n_entries = int(r.headers['X-Checkpoint-Entries'])
        assert n_entries >= 1
        kv_transfer.decode_checkpoint(blob)     # well-formed container
        req = urllib.request.Request(
            base2 + '/kv/warmup', blob,
            {'Content-Type': 'application/octet-stream'})
        with urllib.request.urlopen(req, timeout=60) as r:
            res = json.loads(r.read())
        assert res['warmed_rows'] > 0
        out = _generate(base2, {'prompt': prompt,
                                'max_new_tokens': 8})['tokens']
        assert out == ref
        # Malformed container: loud 400, nothing landed.
        req = urllib.request.Request(
            base2 + '/kv/warmup', b'garbage',
            {'Content-Type': 'application/octet-stream'})
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(req, timeout=30)
        assert err.value.code == 400
    finally:
        srv1.stop()
        srv2.stop()


def test_server_warm_boot_from_checkpoint_file(tmp_path):
    """The standalone restart path: a drain persists the checkpoint
    file; a fresh server with the same --checkpoint-path warms itself
    BEFORE readiness and serves the shared prefix byte-identically."""
    ckpt = str(tmp_path / 'kv.ckpt')
    p1 = common_utils.find_free_port(20000)
    srv1 = _start_server(p1, checkpoint_path=ckpt)
    try:
        base1 = f'http://127.0.0.1:{p1}'
        srv1._ready.wait(120)
        prompt = SHARED_PREFIX + [8, 8, 8]
        ref = _generate(base1, {'prompt': prompt,
                                'max_new_tokens': 8})['tokens']
        req = urllib.request.Request(
            base1 + '/drain', json.dumps({}).encode(),
            {'Content-Type': 'application/json'})
        with urllib.request.urlopen(req, timeout=30) as r:
            json.loads(r.read())
        deadline = time.time() + 30
        while time.time() < deadline and not os.path.exists(ckpt):
            time.sleep(0.1)
        assert os.path.exists(ckpt)
    finally:
        srv1.stop()
    p2 = common_utils.find_free_port(20050)
    srv2 = _start_server(p2, checkpoint_path=ckpt)
    try:
        base2 = f'http://127.0.0.1:{p2}'
        srv2._ready.wait(120)
        hits0 = srv2.engine.alloc.prefix_hits
        out = _generate(base2, {'prompt': prompt,
                                'max_new_tokens': 8})['tokens']
        assert out == ref
        assert srv2.engine.alloc.prefix_hits > hits0   # served warm
    finally:
        srv2.stop()


# ------------------------------------------- zero lost through the LB
class _FakeController:
    """Answers the LB's sync POST with a settable replica list."""

    def __init__(self, replica_urls):
        import http.server
        self.replica_urls = list(replica_urls)
        outer = self

        class H(http.server.BaseHTTPRequestHandler):
            timeout = 30

            def log_message(self, *a):
                del a

            def do_POST(self):  # noqa: N802
                body = json.dumps({
                    'ready_replica_urls': outer.replica_urls,
                    'retry_after_s': 2,
                }).encode()
                self.send_response(200)
                self.send_header('Content-Length', str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        import http.server as hs
        self.port = common_utils.find_free_port(20100)
        self.httpd = hs.ThreadingHTTPServer(('127.0.0.1', self.port), H)
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()

    @property
    def url(self):
        return f'http://127.0.0.1:{self.port}'

    def stop(self):
        self.httpd.shutdown()


def test_seeded_spot_kills_zero_lost_through_lb(monkeypatch):
    """2 spot + 1 on-demand replica behind the LB; both spot replicas
    die mid-run (checkpoint -> drain -> gone, exactly the
    spot_preemption path). Every request completes with the
    byte-identical greedy answer — zero lost.

    Ordering is event-gated, not wall-clock-raced: each kill fires
    only after the LB has observably served at least one request of
    the current wave (a Condition on completion counts), and each
    victim drains with a completion-gated deadline so accepted
    requests are never failed over on a 30s wall clock under
    full-suite CPU load. Wall-clock timeouts remain only as generous
    hang insurance. Whether the remaining wave requests are still in
    flight at kill time is load-dependent — the zero-lost contract
    must hold either way."""
    from skypilot_tpu.serve.load_balancer import SkyServeLoadBalancer
    monkeypatch.setenv('SKYTPU_LB_SYNC', '3600')
    ports = [common_utils.find_free_port(20200 + i * 37)
             for i in range(3)]
    servers = [_start_server(p) for p in ports]
    urls = [f'http://127.0.0.1:{p}' for p in ports]
    ctrl = _FakeController(urls)
    lb_port = common_utils.find_free_port(20400)
    lb = SkyServeLoadBalancer(controller_url=ctrl.url, port=lb_port,
                              max_attempts=4)
    lb.start()
    lb._sync_once()
    lb_base = f'http://127.0.0.1:{lb_port}'
    try:
        for s in servers:
            assert s._ready.wait(120)
        prompts = [[11 + i] + SHARED_PREFIX + [5 + i]
                   for i in range(8)]
        # Reference outputs (greedy, deterministic across replicas).
        refs = [_generate(urls[2], {'prompt': p,
                                    'max_new_tokens': 6})['tokens']
                for p in prompts]

        results = [None] * len(prompts)
        errors = []
        cv = threading.Condition()
        wave_done = [0, 0]            # completions per wave (A, B)

        def one(i):
            try:
                results[i] = _generate(
                    lb_base, {'prompt': prompts[i],
                              'max_new_tokens': 6},
                    timeout=300)['tokens']
            except Exception as e:  # pylint: disable=broad-except
                errors.append((i, repr(e)))
            finally:
                with cv:
                    wave_done[0 if i < 4 else 1] += 1
                    cv.notify_all()

        def await_wave(wave, n):
            """Event gate: block until ``n`` wave completions landed
            (deadline is hang insurance only, never the scheduler)."""
            with cv:
                assert cv.wait_for(lambda: wave_done[wave] >= n,
                                   timeout=300), (wave, n, wave_done)

        def spot_preempt(kill):
            """The spot_preemption flow a manager drives: checkpoint
            -> completion-gated drain -> out of the controller list.
            The drain deadline is generous so stragglers accepted by
            the victim run to completion instead of being failed over
            on a wall clock mid-assert."""
            victim = urls[kill]
            req = urllib.request.Request(
                victim + '/checkpoint', json.dumps({}).encode(),
                {'Content-Type': 'application/json'})
            with urllib.request.urlopen(req, timeout=120):
                pass
            req = urllib.request.Request(
                victim + '/drain',
                json.dumps({'deadline_s': 600}).encode(),
                {'Content-Type': 'application/json'})
            with urllib.request.urlopen(req, timeout=120):
                pass
            ctrl.replica_urls = urls[kill + 1:]
            lb._sync_once()

        threads = [threading.Thread(target=one, args=(i,))
                   for i in range(len(prompts))]
        for t in threads[:4]:
            t.start()
        # Kill #1 only once the LB has demonstrably served wave A
        # traffic — never racing replica warmup/compilation.
        await_wave(0, 1)
        spot_preempt(0)
        for t in threads[4:]:
            t.start()
        # Kill #2 gated on wave B progress the same way.
        await_wave(1, 1)
        spot_preempt(1)
        await_wave(0, 4)
        await_wave(1, 4)
        for t in threads:
            t.join(timeout=30)        # all done per the gates above
        servers[0].stop()
        servers[1].stop()
        assert not errors, errors
        assert results == refs        # zero lost, byte-identical
    finally:
        ctrl.stop()
        lb.stop()
        for s in servers:
            s.stop()
