"""Speculative decoding: n-gram proposer unit tests, device acceptance
math, and the greedy-equivalence contract — speculative decode at any
``k`` must produce byte-identical token streams to vanilla greedy
decode on BOTH engines (fast smoke in tier-1; the parameterized
engine/k/int8 matrix rides the slow tier with the other engine
suites). Sampling correctness is pinned by the top_p->0 collapse (the
rejection-sampling verify path must degenerate to greedy exactly)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu.inference import speculative
from skypilot_tpu.inference.engine import InferenceEngine
from skypilot_tpu.inference.paged import PagedInferenceEngine
from skypilot_tpu.models import configs, llama


@pytest.fixture(scope='module')
def setup():
    cfg = configs.TINY
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


REPETITIVE = [3, 1, 4, 1, 5, 9, 2, 6] * 4
MIXED = [(i * 7 + 3) % 256 for i in range(40)]


def _run(eng, prompts, n_new, **req_kw):
    rids = [eng.add_request(list(p), max_new_tokens=n_new, **req_kw)
            for p in prompts]
    done = eng.run_to_completion(horizon=8)
    return [done[r].output for r in rids]


# ---------------------------------------------------------------------------
# Fast tier: proposer + acceptance units, one smoke per engine
# ---------------------------------------------------------------------------
class TestNGramProposer:

    def test_repetitive_prompt_proposes_continuation(self):
        hist = [1, 2, 3, 4] * 5          # ...1,2,3,4 | next: 1,2,3,4
        prop = speculative.ngram_propose(hist, k=4)
        assert prop.tolist() == [1, 2, 3, 4]

    def test_most_recent_match_wins(self):
        # "7 8" occurs twice with different continuations; the later
        # occurrence (-> 9) must win over the earlier one (-> 5).
        hist = [7, 8, 5, 0, 7, 8, 9, 1, 7, 8]
        prop = speculative.ngram_propose(hist, k=2)
        assert prop.tolist() == [9, 1]

    def test_longest_ngram_preferred(self):
        # trailing 3-gram "1 2 3" matches at the start (-> 4); the
        # shorter trailing 1-gram "3" also matches elsewhere (-> 7) but
        # the longer match must be tried first.
        hist = [1, 2, 3, 4, 3, 7, 1, 2, 3]
        prop = speculative.ngram_propose(hist, k=1, max_ngram=3)
        assert prop.tolist() == [4]

    def test_no_match_returns_empty(self):
        prop = speculative.ngram_propose([1, 2, 3, 4, 5, 6], k=4)
        assert prop.size == 0
        assert speculative.ngram_propose([5], k=4).size == 0
        assert speculative.ngram_propose([1, 1, 1], k=0).size == 0

    def test_truncated_continuation(self):
        # Match near the end of history: fewer than k tokens follow.
        hist = [4, 5, 6, 9, 4, 5]
        prop = speculative.ngram_propose(hist, k=4)
        assert prop.tolist() == [6, 9, 4, 5][:4]


class TestVerifyTokens:
    """Direct unit test of the device acceptance math with crafted
    logits: position i's argmax is token (i+1)*10."""

    def _logits(self, b, k1, vocab=64):
        logits = np.full((b, k1, vocab), -5.0, np.float32)
        for i in range(k1):
            logits[:, i, (i + 1) * 10] = 5.0
        return jnp.asarray(logits)

    def test_greedy_full_accept_and_bonus(self):
        k = 3
        logits = self._logits(1, k + 1)
        proposals = jnp.asarray([[10, 20, 30]], jnp.int32)
        commit, n = speculative.verify_tokens(
            logits, proposals, jnp.asarray([3], jnp.int32), None,
            None, None, None, sample=False)
        assert int(n[0]) == 4                       # k accepted + bonus
        assert np.asarray(commit)[0, :4].tolist() == [10, 20, 30, 40]

    def test_greedy_first_mismatch_corrects(self):
        k = 3
        logits = self._logits(1, k + 1)
        proposals = jnp.asarray([[10, 99, 30]], jnp.int32)   # d2 wrong
        commit, n = speculative.verify_tokens(
            logits, proposals, jnp.asarray([3], jnp.int32), None,
            None, None, None, sample=False)
        assert int(n[0]) == 2                       # d1 + correction
        assert np.asarray(commit)[0, :2].tolist() == [10, 20]

    def test_padding_proposals_reject(self):
        k = 3
        logits = self._logits(1, k + 1)
        # Drafts all match the argmax chain but only 1 is valid.
        proposals = jnp.asarray([[10, 20, 30]], jnp.int32)
        commit, n = speculative.verify_tokens(
            logits, proposals, jnp.asarray([1], jnp.int32), None,
            None, None, None, sample=False)
        assert int(n[0]) == 2
        assert np.asarray(commit)[0, :2].tolist() == [10, 20]

    def test_sampled_peaked_dist_accepts_like_greedy(self):
        k = 2
        logits = self._logits(2, k + 1)
        proposals = jnp.asarray([[10, 20], [10, 99]], jnp.int32)
        temps = jnp.asarray([1.0, 1.0], jnp.float32)
        topks = jnp.zeros(2, jnp.int32)
        topps = jnp.ones(2, jnp.float32)
        commit, n = speculative.verify_tokens(
            logits, proposals, jnp.full((2,), 2, jnp.int32),
            jax.random.PRNGKey(0), temps, topks, topps, sample=True)
        # Peaked logits (margin 10): p(argmax) ~ 1, so acceptance
        # mirrors greedy and the resample lands on the argmax.
        assert int(n[0]) == 3
        assert np.asarray(commit)[0, :3].tolist() == [10, 20, 30]
        assert int(n[1]) == 2
        assert np.asarray(commit)[1, :2].tolist() == [10, 20]


class TestSpeculativeSmoke:
    """Tier-1 greedy-equivalence smoke: one prompt mix, k=4, both
    engines, byte-identical to vanilla greedy decode."""

    def test_slot_greedy_equivalence(self, setup):
        cfg, params = setup
        want = _run(InferenceEngine(cfg, params, max_batch=4,
                                    max_seq=256, attn_impl='xla'),
                    [REPETITIVE, MIXED], 16)
        eng = InferenceEngine(cfg, params, max_batch=4, max_seq=256,
                              attn_impl='xla', speculate_k=4)
        got = _run(eng, [REPETITIVE, MIXED], 16)
        assert got == want
        m = eng.spec_metrics()
        assert m['spec_rounds'] > 0
        # The repetitive prompt must actually exercise acceptance —
        # otherwise this smoke proves nothing about commit merging.
        assert m['spec_accepted'] > 0
        assert 0.0 <= m['spec_accept_rate'] <= 1.0
        assert 1.0 <= m['spec_tokens_per_step'] <= 5.0

    def test_paged_greedy_equivalence(self, setup):
        cfg, params = setup
        want = _run(InferenceEngine(cfg, params, max_batch=4,
                                    max_seq=256, attn_impl='xla'),
                    [REPETITIVE, MIXED], 16)
        eng = PagedInferenceEngine(cfg, params, max_batch=4,
                                   max_seq=256, page_size=8,
                                   attn_impl='xla', speculate_k=4)
        got = _run(eng, [REPETITIVE, MIXED], 16)
        assert got == want
        assert eng.spec_metrics()['spec_accepted'] > 0

    def test_spec_off_by_default(self, setup):
        cfg, params = setup
        eng = InferenceEngine(cfg, params, max_batch=2, max_seq=128,
                              attn_impl='xla')
        assert eng.speculate_k == 0
        m = eng.spec_metrics()                  # stable zero schema
        assert m['spec_accept_rate'] == 0.0
        assert m['spec_tokens_per_step'] == 0.0

    def test_prepare_proposals_outside_lock_contract(self, setup):
        """The serve loop's lock-free prepare: results are consumed by
        the next step; a stale cache entry is recomputed (not used)."""
        cfg, params = setup
        eng = InferenceEngine(cfg, params, max_batch=2, max_seq=256,
                              attn_impl='xla', speculate_k=4)
        want = _run(InferenceEngine(cfg, params, max_batch=2,
                                    max_seq=256, attn_impl='xla'),
                    [REPETITIVE], 12)
        rid = eng.add_request(list(REPETITIVE), max_new_tokens=12)
        while eng.get_finished(rid) is None:
            eng.prepare_proposals()             # what the serve loop does
            eng.step(horizon=4)
        assert eng.get_finished(rid).output == want[0]


# ---------------------------------------------------------------------------
# Slow tier: the engine/k matrix + sampling collapse + capacity edges
# ---------------------------------------------------------------------------
@pytest.mark.slow
class TestSpeculativeMatrix:

    @pytest.mark.parametrize('engine_kind', ['slot', 'paged'])
    @pytest.mark.parametrize('k', [1, 2, 4, 8])
    def test_greedy_equivalence_matrix(self, setup, engine_kind, k):
        cfg, params = setup
        prompts = [REPETITIVE, MIXED, [9],
                   [(i * 11 + 7) % cfg.vocab_size for i in range(40)]]
        want = _run(InferenceEngine(cfg, params, max_batch=4,
                                    max_seq=256, attn_impl='xla'),
                    prompts, 12)
        if engine_kind == 'slot':
            eng = InferenceEngine(cfg, params, max_batch=4, max_seq=256,
                                  attn_impl='xla', speculate_k=k)
        else:
            eng = PagedInferenceEngine(cfg, params, max_batch=4,
                                       max_seq=256, page_size=8,
                                       attn_impl='xla', speculate_k=k)
        assert _run(eng, prompts, 12) == want

    def test_int8_spec_matches_int8_vanilla(self, setup):
        cfg, params = setup
        prompts = [REPETITIVE, MIXED]
        want = _run(InferenceEngine(cfg, params, max_batch=2,
                                    max_seq=256, quantize='int8'),
                    prompts, 10)
        got = _run(InferenceEngine(cfg, params, max_batch=2,
                                   max_seq=256, quantize='int8',
                                   speculate_k=4), prompts, 10)
        assert got == want

    def test_sampling_collapse_to_greedy(self, setup):
        """temp>0 with top_p->0 must collapse to greedy THROUGH the
        rejection-sampling verify path (acceptance + residual
        resampling both land on the argmax)."""
        cfg, params = setup
        eng = InferenceEngine(cfg, params, max_batch=2, max_seq=256,
                              attn_impl='xla', speculate_k=4,
                              rng_seed=7)
        g = eng.add_request(list(REPETITIVE), max_new_tokens=16)
        h = eng.add_request(list(REPETITIVE), max_new_tokens=16,
                            temperature=2.0, top_p=1e-6)
        done = eng.run_to_completion(horizon=8)
        assert done[g].output == done[h].output

    def test_hot_sampling_valid_tokens(self, setup):
        cfg, params = setup
        eng = PagedInferenceEngine(cfg, params, max_batch=1,
                                   max_seq=256, page_size=8,
                                   attn_impl='xla', speculate_k=4,
                                   rng_seed=3)
        rid = eng.add_request(list(REPETITIVE), max_new_tokens=20,
                              temperature=1.5, top_k=50)
        out = eng.run_to_completion(horizon=8)[rid].output
        assert len(out) == 20
        assert all(0 <= t < cfg.vocab_size for t in out)

    def test_eos_and_stop_equivalence(self, setup):
        """eos/stop hit mid-commit must truncate exactly like vanilla
        decode (extra committed tokens discarded)."""
        cfg, params = setup
        vanilla = InferenceEngine(cfg, params, max_batch=1, max_seq=256,
                                  attn_impl='xla')
        ref = _run(vanilla, [REPETITIVE], 24)[0]
        eos = ref[7]
        stop = ref[3:5]
        for kw in ({'eos_id': eos}, {'stop': [stop]}):
            v = InferenceEngine(cfg, params, max_batch=1, max_seq=256,
                                attn_impl='xla')
            s = InferenceEngine(cfg, params, max_batch=1, max_seq=256,
                                attn_impl='xla', speculate_k=4)
            assert (_run(s, [REPETITIVE], 24, **kw)
                    == _run(v, [REPETITIVE], 24, **kw))

    def test_capacity_edge_max_seq(self, setup):
        """Generation that exactly fills max_seq: proposals are capped
        so the committed stream never overruns the cache, matching
        vanilla decode's capacity stop."""
        cfg, params = setup
        prompt = REPETITIVE[:24]
        budget = 64 - len(prompt)               # exact max_seq fill
        v = _run(InferenceEngine(cfg, params, max_batch=1, max_seq=64,
                                 attn_impl='xla'), [prompt], budget)[0]
        s = _run(InferenceEngine(cfg, params, max_batch=1, max_seq=64,
                                 attn_impl='xla', speculate_k=4),
                 [prompt], budget)[0]
        assert len(s) == budget
        assert s == v

    def test_spec_interleaves_with_chunked_prefill(self, setup):
        """A long prompt admits in chunks while another slot speculates
        — mid-prefill slots are masked out of verify rounds and both
        outputs match vanilla."""
        cfg, params = setup
        long_prompt = [(i * 5 + 2) % cfg.vocab_size for i in range(150)]
        want = _run(InferenceEngine(cfg, params, max_batch=2,
                                    max_seq=256, attn_impl='xla'),
                    [REPETITIVE, long_prompt], 8)
        eng = InferenceEngine(cfg, params, max_batch=2, max_seq=256,
                              attn_impl='xla', speculate_k=4,
                              prefill_chunk_tokens=32)
        a = eng.add_request(list(REPETITIVE), max_new_tokens=8)
        eng.step(horizon=1)
        b = eng.add_request(list(long_prompt), max_new_tokens=8)
        done = eng.run_to_completion(horizon=4)
        assert [done[a].output, done[b].output] == want

    def test_paged_pool_pressure_sheds_then_preempts(self, setup):
        """A pool too small for every slot's k+1 reservation still
        completes every request correctly (proposals shed / newest
        preempted, never a crash or wrong tokens)."""
        cfg, params = setup
        want = _run(InferenceEngine(cfg, params, max_batch=4,
                                    max_seq=128, attn_impl='xla'),
                    [REPETITIVE] * 4, 16)
        eng = PagedInferenceEngine(cfg, params, max_batch=4,
                                   max_seq=128, page_size=8,
                                   n_pages=24, attn_impl='xla',
                                   speculate_k=4)
        got = _run(eng, [REPETITIVE] * 4, 16)
        assert got == want

    def test_cancel_during_speculation(self, setup):
        cfg, params = setup
        eng = InferenceEngine(cfg, params, max_batch=2, max_seq=256,
                              attn_impl='xla', speculate_k=4)
        rid = eng.add_request(list(REPETITIVE), max_new_tokens=200)
        keep = eng.add_request(list(MIXED), max_new_tokens=8)
        for _ in range(3):
            eng.step()
        assert eng.cancel(rid)
        done = eng.run_to_completion(horizon=4)
        assert rid not in done and len(done[keep].output) == 8


# ---------------------------------------------------------------------------
# Serve-layer integration: /metrics schema + the lock-free proposer loop
# ---------------------------------------------------------------------------
SPEC_METRIC_KEYS = ('speculate_k', 'spec_accept_rate',
                    'spec_tokens_per_step', 'spec_proposed',
                    'spec_accepted', 'spec_rounds', 'ttft_ms_median',
                    'ttft_ms_p90')


def _boot_server(port, **kw):
    import time
    import urllib.request

    from skypilot_tpu.serve.server import ModelServer
    server = ModelServer('tiny', max_batch=2, max_seq=64, port=port,
                         **kw)
    server.start(block=False)
    deadline = time.time() + 120
    while time.time() < deadline:
        try:
            with urllib.request.urlopen(
                    f'http://127.0.0.1:{port}/readiness',
                    timeout=5) as r:
                if r.status == 200:
                    return server
        except Exception:  # pylint: disable=broad-except
            time.sleep(0.3)
    raise RuntimeError('server did not become ready')


@pytest.mark.slow
def test_metrics_schema_stable_spec_on_and_off():
    """/metrics must expose the SAME numeric gauge keys whether
    speculation is on or off (zeros, never omitted keys), and with
    speculation on the accept-rate gauges must move after traffic.
    Also exercises the serve loop's lock-free prepare_proposals path
    end to end."""
    import json
    import urllib.request

    from skypilot_tpu.utils import common_utils

    def gen(port, payload):
        req = urllib.request.Request(
            f'http://127.0.0.1:{port}/generate',
            data=json.dumps(payload).encode(),
            headers={'Content-Type': 'application/json'})
        with urllib.request.urlopen(req, timeout=60) as r:
            return json.loads(r.read())

    def metrics(port):
        # The stable-schema JSON gauge block moved behind ?format=json
        # when /metrics switched to Prometheus exposition by default.
        with urllib.request.urlopen(
                f'http://127.0.0.1:{port}/metrics?format=json',
                timeout=10) as r:
            return json.loads(r.read())

    port_off = common_utils.find_free_port(18940)
    srv_off = _boot_server(port_off)
    try:
        m_off = metrics(port_off)
        for key in SPEC_METRIC_KEYS:
            assert key in m_off, key
            assert isinstance(m_off[key], (int, float)), key
        assert m_off['speculate_k'] == 0
        assert m_off['spec_accept_rate'] == 0.0
        assert m_off['scheduler']['speculate_k'] == 0
        off_tokens = gen(port_off, {'prompt': [3, 1, 4, 1, 5, 9] * 4,
                                    'max_new_tokens': 12})['tokens']
    finally:
        srv_off.stop()

    port_on = common_utils.find_free_port(18960)
    srv_on = _boot_server(port_on, speculate_k=4)
    try:
        on_tokens = gen(port_on, {'prompt': [3, 1, 4, 1, 5, 9] * 4,
                                  'max_new_tokens': 12})['tokens']
        assert on_tokens == off_tokens        # greedy equivalence e2e
        m_on = metrics(port_on)
        assert set(SPEC_METRIC_KEYS) <= set(m_on)
        assert m_on['speculate_k'] == 4
        assert m_on['spec_rounds'] > 0
        assert m_on['spec_tokens_per_step'] >= 1.0
    finally:
        srv_on.stop()
