"""Runtime package shipping: hash-addressed zip build, importability
from the archive, version-skew detection snippet."""
import os
import subprocess
import sys

import pytest

from skypilot_tpu.utils import pkg_utils


@pytest.fixture(autouse=True)
def tmp_wheel_dir(tmp_path, monkeypatch):
    monkeypatch.setenv('SKYTPU_WHEEL_DIR', str(tmp_path / 'wheels'))


def test_build_is_hash_addressed_and_cached():
    path1, digest1 = pkg_utils.build_package()
    assert digest1 in path1 and os.path.exists(path1)
    mtime = os.path.getmtime(path1)
    path2, digest2 = pkg_utils.build_package()
    assert (path2, digest2) == (path1, digest1)
    assert os.path.getmtime(path2) == mtime          # reused, not rebuilt


def test_zip_is_importable_via_pythonpath():
    """The shipped artifact must work exactly as deployed: zipimport of
    skypilot_tpu from a clean interpreter with only the zip on path."""
    path, _ = pkg_utils.build_package()
    out = subprocess.run(
        [sys.executable, '-c',
         'import skypilot_tpu, skypilot_tpu.task; '
         'print(skypilot_tpu.__version__); '
         't = skypilot_tpu.Task(name="z", run="true"); print(t.name)'],
        capture_output=True, text=True,
        env={**os.environ, 'PYTHONPATH': path},
        cwd='/',                                     # not the repo
    )
    assert out.returncode == 0, out.stderr
    assert out.stdout.split() == ['0.1.0', 'z']


def test_setup_command_handles_version_skew():
    cmd = pkg_utils.remote_setup_command('abc123')
    assert 'PYTHONPATH' in cmd and '.profile' in cmd
    assert 'abc123' in cmd
    # Skew path kills the running agentd so it restarts on the new code.
    assert 'agentd.pid' in cmd and 'kill' in cmd


def test_ssh_runner_prefixes_runtime_pythonpath():
    """Every SSH remote command must carry the runtime-zip PYTHONPATH
    explicitly (shell init files can't be relied on non-interactively)."""
    from skypilot_tpu.utils import command_runner

    captured = {}

    class Probe(command_runner.SSHCommandRunner):
        def _popen(self, args, **kw):
            captured['cmd'] = args[-1]
            return 0

    runner = Probe('1.2.3.4', ssh_user='u', ssh_private_key='/dev/null')
    runner.run('echo hi')
    assert '.skytpu_runtime/skypilot_tpu.zip' in captured['cmd']
