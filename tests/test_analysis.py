"""graftcheck part A: rule unit tests on fixture snippets, plus the
whole-repo regression gate (zero violations outside the checked-in
baseline). The gate is what makes the concurrency/hot-path discipline
machine-checked: a PR reintroducing a blocking call under a lock or a
host sync in the decode loop fails HERE, not in a bench regression
three rounds later."""
import textwrap

from skypilot_tpu.analysis import lint as lint_lib
from skypilot_tpu.analysis import rules as rules_lib
from skypilot_tpu.analysis.cli import main as graftcheck_main


def check(src, path='skypilot_tpu/serve/x.py'):
    return rules_lib.check_source(path, textwrap.dedent(src))


def rule_ids(src, path='skypilot_tpu/serve/x.py'):
    return [v.rule for v in check(src, path)]


# ------------------------------------------------------------------ GC101
def test_gc101_unlocked_write_flagged():
    src = '''
    import threading
    class M:
        def __init__(self):
            self._lock = threading.Lock()
            self._n = 0          # init writes are setup, not races
        def locked(self):
            with self._lock:
                self._n += 1
        def racy(self):
            self._n = 5
    '''
    vs = check(src)
    assert [v.rule for v in vs] == ['GC101']
    assert vs[0].func == 'M.racy'


def test_gc101_consistently_unlocked_attr_not_flagged():
    # An attr never written under the lock isn't claimed by it.
    src = '''
    import threading
    class M:
        def __init__(self):
            self._lock = threading.Lock()
        def a(self):
            self._free = 1
        def b(self):
            self._free = 2
    '''
    assert rule_ids(src) == []


# ------------------------------------------------------------------ GC102
def test_gc102_sleep_and_urlopen_under_lock():
    src = '''
    import threading, time, urllib.request
    class M:
        def __init__(self):
            self._lock = threading.Lock()
        def bad(self):
            with self._lock:
                time.sleep(1)
                urllib.request.urlopen('http://x', timeout=5)
    '''
    ids = rule_ids(src)
    assert ids.count('GC102') == 2


def test_gc102_sqlite_state_under_thread_lock_flagged():
    src = '''
    import threading
    from skypilot_tpu.serve import serve_state
    class M:
        def __init__(self):
            self._lock = threading.Lock()
        def bad(self):
            with self._lock:
                serve_state.remove_replica('s', 1)
    '''
    assert 'GC102' in rule_ids(src)


def test_gc102_db_named_locks_exempt_for_state_calls():
    # A lock whose job is serializing DB access may hold it across the
    # DB call — that's the replica-manager _db_lock protocol and the
    # jobs scheduler's state.db_lock().
    src = '''
    import threading
    from skypilot_tpu.jobs import state
    class M:
        def __init__(self):
            self._db_lock = threading.Lock()
        def ok(self):
            with self._db_lock:
                state.set_schedule_state(1, 2)
        def also_ok(self):
            with state.db_lock():
                state.set_schedule_state(1, 2)
    '''
    assert rule_ids(src) == []


def test_gc102_filelock_local_exempt():
    src = '''
    import filelock
    from skypilot_tpu import global_state
    def f():
        lock = filelock.FileLock('/tmp/x')
        with lock:
            global_state.add_or_update_cluster('c', None)
    '''
    assert rule_ids(src) == []


def test_gc102_unbounded_wait_under_lock():
    src = '''
    import threading
    class M:
        def __init__(self):
            self._lock = threading.Lock()
            self.q = None
        def bad(self):
            with self._lock:
                self.q.get()
        def ok(self):
            with self._lock:
                self.q.get(timeout=5)
    '''
    assert rule_ids(src) == ['GC102']


# ------------------------------------------------------------------ GC103
def test_gc103_urlopen_without_timeout():
    src = '''
    import urllib.request
    def f(req):
        with urllib.request.urlopen(req) as r:
            return r.read()
    def g(req):
        with urllib.request.urlopen(req, timeout=10) as r:
            return r.read()
    '''
    assert rule_ids(src) == ['GC103']


# ------------------------------------------------------- GC104 / GC105
def test_gc104_bare_except():
    assert rule_ids('''
    def f():
        try:
            return 1
        except:
            return None
    ''') == ['GC104']


def test_gc104_bare_except_reraise_ok():
    assert rule_ids('''
    def f():
        try:
            return 1
        except:
            raise
    ''') == []


def test_gc105_swallowed_broad_except():
    assert rule_ids('''
    def f():
        try:
            return 1
        except Exception:
            pass
    ''') == ['GC105']


def test_gc105_logged_or_narrow_excepts_ok():
    assert rule_ids('''
    import logging
    def f():
        try:
            return 1
        except Exception as e:
            logging.warning('boom %s', e)
        try:
            return 2
        except KeyError:
            pass
    ''') == []


# ------------------------------------------------------------------ GC107
def test_gc107_handler_without_timeout():
    src = '''
    import http.server
    class H(http.server.BaseHTTPRequestHandler):
        pass
    class H2(http.server.BaseHTTPRequestHandler):
        timeout = 60
    '''
    vs = check(src)
    assert [v.rule for v in vs] == ['GC107']
    assert 'H ' in vs[0].message


# ------------------------------------------------------------------ GC108
def test_gc108_proposer_under_lock_flagged():
    src = '''
    import threading
    class S:
        def __init__(self):
            self._lock = threading.Lock()
        def loop(self):
            with self._lock:
                self.engine.prepare_proposals()
    '''
    vs = check(src)
    assert [v.rule for v in vs] == ['GC108']
    assert 'prepare_proposals' in vs[0].message


def test_gc108_proposer_outside_lock_ok():
    src = '''
    import threading
    class S:
        def __init__(self):
            self._lock = threading.Lock()
        def loop(self):
            self.engine.prepare_proposals()
            with self._lock:
                self.engine.step()
    '''
    assert rule_ids(src) == []


def test_gc108_ngram_propose_under_lock_flagged():
    src = '''
    import threading
    lock = threading.Lock()
    def f(eng, hist):
        from skypilot_tpu.inference.speculative import ngram_propose
        with lock:
            return ngram_propose(hist, 4)
    '''
    assert rule_ids(src) == ['GC108']


# ------------------------------------------------------------------ GC109
def test_gc109_adhoc_timing_in_inference_flagged():
    src = '''
    import time
    from time import perf_counter
    def step(self):
        t0 = time.time()
        t1 = perf_counter()
        t2 = time.monotonic()
        return t0, t1, t2
    '''
    assert rule_ids(src, 'skypilot_tpu/inference/x.py') == \
        ['GC109', 'GC109', 'GC109']


def test_gc109_only_applies_to_inference():
    src = '''
    import time
    def f():
        return time.time()
    '''
    # Fine in the serve layer / other compute dirs — only the
    # inference hot paths must route through telemetry.
    assert rule_ids(src, 'skypilot_tpu/serve/x.py') == []
    assert rule_ids(src, 'skypilot_tpu/models/x.py') == []


def test_gc109_telemetry_clock_spelling_ok():
    src = '''
    from skypilot_tpu.telemetry import clock
    def step(self):
        with self._prof.phase('admit'):
            return clock.now(), clock.monotonic()
    '''
    assert rule_ids(src, 'skypilot_tpu/inference/x.py') == []


def test_gc109_inside_jit_stays_gc201():
    """Inside a jit body GC201 already fires; GC109 must not
    double-flag the same call."""
    src = '''
    import functools, time, jax
    @functools.partial(jax.jit)
    def step(x):
        return time.time()
    '''
    assert rule_ids(src, 'skypilot_tpu/inference/x.py') == ['GC201']


# ------------------------------------------------------------------ GC110
def test_gc110_bare_int8_astype_in_compute_flagged():
    src = '''
    import jax.numpy as jnp
    def write_kv(cache, rows):
        return cache.at[0].set(rows.astype(jnp.int8))
    '''
    assert rule_ids(src, 'skypilot_tpu/inference/x.py') == ['GC110']
    assert rule_ids(src, 'skypilot_tpu/ops/x.py') == ['GC110']


def test_gc110_string_and_np_spellings_flagged():
    src = '''
    import numpy as np
    def write_kv(rows, other):
        a = rows.astype('int8')
        b = other.astype(np.int8)
        return a, b
    '''
    assert rule_ids(src, 'skypilot_tpu/models/x.py') == \
        ['GC110', 'GC110']


def test_gc110_quantize_scope_exempt():
    # Functions named *quantize* ARE the sanctioned write helpers the
    # rule routes everyone else to — including nested helpers.
    src = '''
    import jax.numpy as jnp
    def quantize_kv_rows(rows):
        scale = 1.0
        return (rows / scale).astype(jnp.int8), scale
    def _quantize_array(x):
        def inner(y):
            return y.astype(jnp.int8)
        return inner(x)
    '''
    assert rule_ids(src, 'skypilot_tpu/models/x.py') == []


def test_gc110_quantization_module_and_other_dtypes_exempt():
    src = '''
    import jax.numpy as jnp
    def pack(x):
        return x.astype(jnp.int8)
    '''
    # The quantization module is the sanctioned implementation.
    assert rule_ids(src, 'skypilot_tpu/models/quantization.py') == []
    # Only the int8 dtype is policed; other casts are fine anywhere.
    src_ok = '''
    import jax.numpy as jnp
    def widen(x):
        return x.astype(jnp.int32), x.astype(jnp.bfloat16)
    '''
    assert rule_ids(src_ok, 'skypilot_tpu/inference/x.py') == []


# ------------------------------------------------------------------ GC119
def test_gc119_int4_astype_in_compute_flagged():
    src = '''
    import jax.numpy as jnp
    def write_w(rows, other):
        a = rows.astype(jnp.int4)
        b = other.astype('uint4')
        return a, b
    '''
    assert rule_ids(src, 'skypilot_tpu/inference/x.py') == \
        ['GC119', 'GC119']
    assert rule_ids(src, 'skypilot_tpu/models/x.py') == \
        ['GC119', 'GC119']


def test_gc119_manual_nibble_twiddling_flagged():
    src = '''
    def repack(codes):
        lo = codes & 0xF
        hi = codes >> 4
        return lo | (hi << 4)
    '''
    assert rule_ids(src, 'skypilot_tpu/ops/x.py') == \
        ['GC119', 'GC119', 'GC119']
    # Outside the compute dirs the operators are unpoliced (bit math
    # is normal in e.g. serve/ hashing).
    assert rule_ids(src, 'skypilot_tpu/serve/x.py') == []


def test_gc119_sanctioned_helpers_exempt():
    # The quantization module IS the layout's home.
    src = '''
    def repack(codes):
        return (codes & 0xF) | ((codes >> 4) << 4)
    '''
    assert rule_ids(src, 'skypilot_tpu/models/quantization.py') == []
    # pack_int4/unpack_int4/quantize-named scopes are the sanctioned
    # spellings wherever they live (mirrors GC110's scope exemption).
    src_scoped = '''
    import jax.numpy as jnp
    def pack_int4(codes):
        return codes >> 4
    def _quantize_array4(w):
        return w.astype(jnp.int4)
    '''
    assert rule_ids(src_scoped, 'skypilot_tpu/models/x.py') == []
    # Non-nibble shifts/masks stay legal in compute dirs.
    src_ok = '''
    def hash_mix(x):
        return (x >> 7) & 0x3F
    '''
    assert rule_ids(src_ok, 'skypilot_tpu/inference/x.py') == []


# ------------------------------------------------------------------ GC121
def test_gc121_per_layer_pool_slice_in_decode_flagged():
    src = '''
    from jax import lax
    def paged_decode_horizon(cache, li, table_p):
        pool_k = cache.pool_k
        pk = lax.dynamic_index_in_dim(pool_k, li, 0, keepdims=False)
        sk = lax.dynamic_index_in_dim(cache.k_scale, li, 0)
        ck, sck = _gather_layer(pk, sk, table_p)
        return ck, sck
    '''
    assert rule_ids(src, 'skypilot_tpu/inference/paged.py') == \
        ['GC121', 'GC121', 'GC121']


def test_gc121_scalar_pool_subscript_in_decode_flagged():
    src = '''
    def decode_step(cache, li):
        a = cache.pool_k[li]
        b = cache.pool_v[0]
        c = cache.k_scale[li, :, :]
        return a, b, c
    '''
    assert rule_ids(src, 'skypilot_tpu/inference/paged.py') == \
        ['GC121', 'GC121', 'GC121']


def test_gc121_prefill_verify_and_helper_scopes_exempt():
    # Prefill/verify-shaped functions are compute-bound and
    # legitimately materialize contiguous rows; the gather helper is
    # the sanctioned materializer; non-pool slices stay legal in
    # decode scopes (the ring is per-horizon, not the pool).
    src = '''
    from jax import lax
    def paged_prefill_chunk(cache, li, table_p):
        pk = lax.dynamic_index_in_dim(cache.pool_k, li, 0)
        return _gather_layer(pk, None, table_p)
    def paged_spec_verify(cache, li, table_p):
        pv = cache.pool_v[li]
        return _gather_layer(pv, None, table_p)
    def _gather_layer(pool_layer, scale_layer, table_p):
        return pool_layer[table_p], scale_layer
    def paged_decode_horizon(ring_k, li, lengths):
        rk = lax.dynamic_index_in_dim(ring_k, li, 0)
        n = lengths[li]
        return rk, n
    '''
    assert rule_ids(src, 'skypilot_tpu/inference/paged.py') == []


def test_gc121_outside_inference_and_suppressions_clean():
    # The rule is scoped to inference/ (the ops kernels are the
    # sanctioned home of pool indexing), and the grandfathered legacy
    # fallback rides inline suppressions.
    src = '''
    from jax import lax
    def paged_decode_kernel(pool_k, li):
        return lax.dynamic_index_in_dim(pool_k, li, 0)
    '''
    assert rule_ids(src, 'skypilot_tpu/ops/x.py') == []
    src_sup = '''
    from jax import lax
    def paged_decode_horizon(pool_k, li):
        return lax.dynamic_index_in_dim(pool_k, li, 0)  # graftcheck: disable=GC121
    '''
    assert rule_ids(src_sup, 'skypilot_tpu/inference/paged.py') == []


# ------------------------------------------------------------------ GC111
def test_gc111_sync_engine_calls_in_coroutine_flagged():
    src = '''
    async def handler(engine, sched, prompt):
        sr = sched.submit(prompt, max_new_tokens=4)
        events = engine.step(horizon=8)
        engine.add_request(prompt)
        return sr, events
    '''
    assert rule_ids(src) == ['GC111', 'GC111', 'GC111']


def test_gc111_unbounded_wait_in_coroutine_flagged():
    src = '''
    async def consume(outbox, done):
        token, finished = outbox.get()
        done.wait()
        return token, finished
    '''
    vs = check(src)
    assert [v.rule for v in vs] == ['GC111', 'GC111']
    assert 'event loop' in vs[0].message


def test_gc111_async_adapters_and_executor_clean():
    # The sanctioned spellings: the async adapter, a wait handed to an
    # executor (the callable is passed, not called), bounded waits,
    # and asyncio's own primitives.
    src = '''
    import asyncio
    async def consume(outbox, loop, done):
        token, finished = await outbox.aget()
        more = await loop.run_in_executor(None, outbox.get)
        done.wait(timeout=5)
        await asyncio.wait([])
        return token, finished, more
    '''
    assert rule_ids(src) == []


def test_gc111_sync_functions_and_other_dirs_exempt():
    # The same calls are the NORMAL engine-loop idiom in sync code;
    # only serve/ coroutines are policed.
    src = '''
    def engine_loop(engine, outbox):
        events = engine.step(horizon=8)
        return outbox.get()
    '''
    assert rule_ids(src) == []
    src_async_elsewhere = '''
    async def run(engine):
        return engine.step(horizon=8)
    '''
    assert rule_ids(src_async_elsewhere,
                    'skypilot_tpu/inference/x.py') == []


def test_gc111_nested_sync_def_inside_coroutine_exempt():
    # A sync def nested in a coroutine is executor fodder — only the
    # IMMEDIATE enclosing function's asyncness decides.
    src = '''
    async def handler(engine, loop):
        def blocking():
            return engine.step(horizon=8)
        return await loop.run_in_executor(None, blocking)
    '''
    assert rule_ids(src) == []


def test_gc110_only_applies_to_compute_dirs():
    src = '''
    import numpy as np
    def shrink(x):
        return x.astype(np.int8)
    '''
    assert rule_ids(src, 'skypilot_tpu/serve/x.py') == []


# ------------------------------------------------------------------ GC112
def test_gc112_fixed_sleep_in_retry_loop_flagged():
    src = '''
    import time
    GAP = 5.0
    def poll():
        while True:
            time.sleep(0.2)
    def poll_const(deadline):
        while time.time() < deadline:
            time.sleep(GAP)
    '''
    vs = check(src)
    assert [v.rule for v in vs] == ['GC112', 'GC112']
    assert 'retry storms' in vs[0].message
    # jobs/ is policed too.
    assert rule_ids(src, 'skypilot_tpu/jobs/x.py') == \
        ['GC112', 'GC112']


def test_gc112_jitter_and_backoff_clean():
    src = '''
    import random, time
    def jittered(poll_seconds):
        while True:
            time.sleep(poll_seconds * (0.5 + random.random()))
    def rng_method(self, interval):
        while True:
            time.sleep(interval * (0.5 + self._rng.random()))
    def backoff():
        gap = 1.0
        while True:
            time.sleep(gap)
            gap = min(gap * 2, 300)
    def event_wait(stop, tick):
        while not stop.is_set():
            stop.wait(tick)
    def dynamic_accessor(tc):
        while True:
            time.sleep(tc.poll_interval())
    '''
    assert rule_ids(src) == []


def test_gc112_other_dirs_and_non_loop_sleeps_exempt():
    src = '''
    import time
    def poll():
        while True:
            time.sleep(0.2)
    '''
    assert rule_ids(src, 'skypilot_tpu/provision/x.py') == []
    src_no_loop = '''
    import time
    def settle():
        time.sleep(0.5)
    '''
    assert rule_ids(src_no_loop) == []


def test_gc112_suppression_and_for_loops():
    src = '''
    import time
    def retry(urls):
        for u in urls:
            time.sleep(1.0)
    '''
    assert rule_ids(src) == ['GC112']
    suppressed = '''
    import time
    def retry(urls):
        for u in urls:
            time.sleep(1.0)  # graftcheck: disable=GC112
    '''
    assert rule_ids(suppressed) == []


# ------------------------------------------------------------------ GC113
def test_gc113_device_put_in_step_path_flagged():
    src = '''
    import jax
    def _enqueue_decode(self, table, lengths):
        table_d, lengths_d = jax.device_put((table, lengths))
        return table_d, lengths_d
    '''
    assert rule_ids(src, 'skypilot_tpu/inference/x.py') == ['GC113']
    # Only inference/ is policed — serve/models code places freely.
    assert rule_ids(src, 'skypilot_tpu/serve/x.py') == []
    assert rule_ids(src, 'skypilot_tpu/models/x.py') == []


def test_gc113_placement_helpers_exempt():
    src = '''
    import jax
    def prepare_params(cfg, params, mesh):
        return jax.device_put(params, mesh)
    class Engine:
        def __init__(self, cache, sh):
            self.cache = jax.device_put(cache, sh)
        @classmethod
        def from_pretrained(cls, params):
            return jax.device_put(params)
    '''
    assert rule_ids(src, 'skypilot_tpu/inference/x.py') == []


def test_gc113_device_upload_spelling_fine():
    src = '''
    from skypilot_tpu.utils.host import device_upload
    def _prefill_chunk_batch(self, tokens, starts):
        return device_upload((tokens, starts))
    '''
    assert rule_ids(src, 'skypilot_tpu/inference/x.py') == []


def test_gc113_inline_suppression():
    src = '''
    import jax
    def _spec_verify_call(self, rows):
        return jax.device_put(rows)  # graftcheck: disable=GC113
    '''
    assert rule_ids(src, 'skypilot_tpu/inference/x.py') == []


def test_gc113_whole_repo_clean():
    # The engines' own step paths ride device_upload; any new bare
    # device_put in inference/ fails here before it ships.
    from skypilot_tpu.analysis import lint
    new, _ = lint.lint_paths(None, baseline=lint.load_baseline(None))
    assert [v for v in new if v.rule == 'GC113'] == []


# ------------------------------------------------------------------ GC114
def test_gc114_wide_float_astype_on_transfer_path_flagged():
    src = '''
    import jax.numpy as jnp
    import numpy as np
    def encode_rows(codes, scales):
        wide = codes.astype(jnp.bfloat16) * scales
        return wide.astype(np.float32)
    '''
    assert rule_ids(src, 'skypilot_tpu/inference/kv_transfer.py') == \
        ['GC114', 'GC114']
    # String dtype spellings count too.
    src2 = '''
    def pack(rows):
        return rows.astype('float32').tobytes()
    '''
    assert rule_ids(src2, 'skypilot_tpu/serve/disagg.py') == ['GC114']


def test_gc114_dequantize_call_on_transfer_path_flagged():
    src = '''
    from skypilot_tpu.models import quantization
    def export_rows(codes, scales):
        return quantization.dequantize_rows(codes, scales)
    '''
    assert rule_ids(src, 'skypilot_tpu/serve/disagg.py') == ['GC114']


def test_gc114_only_polices_transfer_paths():
    # The same spellings are legal elsewhere (attention kernels
    # legitimately widen for compute; GC114 is a WIRE discipline).
    src = '''
    import jax.numpy as jnp
    def attend(codes, scales):
        return codes.astype(jnp.bfloat16) * scales
    '''
    assert rule_ids(src, 'skypilot_tpu/models/x.py') == []
    assert rule_ids(src, 'skypilot_tpu/serve/server.py') == []


def test_gc114_stored_dtype_codec_clean():
    # The sanctioned codec shape: raw bytes in the stored dtype, no
    # conversion anywhere.
    src = '''
    import numpy as np
    def encode(arr):
        return np.ascontiguousarray(arr, dtype=np.int8).tobytes()
    def decode(buf, shape):
        return np.frombuffer(buf, dtype=np.int8).reshape(shape)
    '''
    assert rule_ids(src, 'skypilot_tpu/inference/kv_transfer.py') == []


def test_gc114_whole_repo_clean():
    # The real wire codec + handoff plumbing never widen KV.
    from skypilot_tpu.analysis import lint
    new, _ = lint.lint_paths(None, baseline=lint.load_baseline(None))
    assert [v for v in new if v.rule == 'GC114'] == []


# ------------------------------------------------------------------ GC115
def test_gc115_wallclock_call_in_autoscaler_flagged():
    src = '''
    import time
    def current_qps(self, now=None):
        now = time.time() if now is None else now
        return now
    def evaluate(self):
        t = time.monotonic()
        return t
    '''
    ids = rule_ids(src, 'skypilot_tpu/serve/autoscalers.py')
    assert ids == ['GC115', 'GC115']
    assert rule_ids(src, 'skypilot_tpu/serve/forecaster.py') == [
        'GC115', 'GC115']


def test_gc115_bare_monotonic_import_flagged():
    src = '''
    from time import monotonic
    def decide(self):
        return monotonic()
    '''
    assert rule_ids(src, 'skypilot_tpu/serve/forecaster.py') == ['GC115']


def test_gc115_injected_clock_default_arg_clean():
    # The injection mechanism itself: referencing time.time (no call)
    # as the default clock, and calling the injected clock.
    src = '''
    import time
    class Autoscaler:
        def __init__(self, spec, clock=time.time):
            self._clock = clock
        def evaluate(self, now=None):
            return self._clock() if now is None else now
    '''
    assert rule_ids(src, 'skypilot_tpu/serve/autoscalers.py') == []


def test_gc115_only_polices_scaling_paths():
    # The same calls are legal elsewhere in serve/ (servers measure
    # real wall time; only scaling DECISIONS must be replayable).
    src = '''
    import time
    def handler(self):
        return time.time()
    '''
    assert rule_ids(src, 'skypilot_tpu/serve/server.py') == []
    assert rule_ids(src, 'skypilot_tpu/serve/replica_managers.py') == []


def test_gc115_whole_repo_clean():
    # The shipped autoscalers/forecaster are fully clock-injected.
    from skypilot_tpu.analysis import lint
    new, _ = lint.lint_paths(None, baseline=lint.load_baseline(None))
    assert [v for v in new if v.rule == 'GC115'] == []


# ------------------------------------------------------------------ GC116
def test_gc116_unbounded_gang_joins_flagged():
    src = '''
    import threading
    def barrier_wait(self):
        self._joined.wait()
    def drain_gang(self, t):
        self._acked.wait()
        self._thread.join()
    '''
    ids = rule_ids(src, 'skypilot_tpu/serve/gang.py')
    assert ids == ['GC116', 'GC116', 'GC116']


def test_gc116_bounded_joins_clean():
    # timeout= kwargs, positional bounds (str.join's iterable counts
    # as one), and non-join calls are all fine.
    src = '''
    def barrier_wait(self, timeout):
        return self._joined.wait(timeout=timeout)
    def sleep(self):
        self._stop.wait(timeout=self.heartbeat_s)
    def tail(self, parts):
        return ",".join(parts)
    def pop_one(self, q):
        return q.get(timeout=5)
    '''
    assert rule_ids(src, 'skypilot_tpu/serve/gang.py') == []


def test_gc116_distributed_initialize_needs_timeout():
    src = '''
    import jax
    def boot(self, addr, world, rank):
        jax.distributed.initialize(coordinator_address=addr,
                                   num_processes=world,
                                   process_id=rank)
    '''
    assert rule_ids(src, 'skypilot_tpu/serve/gang.py') == ['GC116']
    bounded = '''
    import jax
    def boot(self, addr, world, rank):
        jax.distributed.initialize(coordinator_address=addr,
                                   num_processes=world,
                                   process_id=rank,
                                   initialization_timeout=120)
    '''
    assert rule_ids(bounded, 'skypilot_tpu/serve/gang.py') == []


def test_gc116_only_polices_gang_paths():
    # Unbounded waits elsewhere stay governed by the existing rules
    # (GC102 under locks, GC111 in coroutines) — GC116 is the gang
    # layer's file-wide fail-fast contract.
    src = '''
    def wait_done(self):
        self._done.wait()
    '''
    assert rule_ids(src, 'skypilot_tpu/serve/controller.py') == []
    assert rule_ids(src, 'skypilot_tpu/serve/gang.py') == ['GC116']


def test_gc116_whole_repo_clean():
    # The shipped gang layer carries a timeout on every join.
    from skypilot_tpu.analysis import lint
    new, _ = lint.lint_paths(None, baseline=lint.load_baseline(None))
    assert [v for v in new if v.rule == 'GC116'] == []


# ------------------------------------------------------------------ GC201
def test_gc201_impure_calls_inside_jit():
    src = '''
    import functools, time, jax
    import numpy as np
    @functools.partial(jax.jit, static_argnames=('n',))
    def step(x, n):
        t = time.time()
        y = np.asarray(x)
        return float(x)
    '''
    ids = rule_ids(src, 'skypilot_tpu/inference/x.py')
    assert ids == ['GC201', 'GC201', 'GC201']


def test_gc201_plain_jax_ops_fine():
    src = '''
    import jax
    import jax.numpy as jnp
    @jax.jit
    def step(x):
        return jnp.argmax(x, -1)
    '''
    assert rule_ids(src, 'skypilot_tpu/inference/x.py') == []


# ------------------------------------------------------------------ GC202
def test_gc202_bare_asarray_item_device_get_in_compute_dirs():
    src = '''
    import numpy as np
    import jax
    def f(x):
        a = np.asarray(x)          # bare: classic accidental sync
        b = x.item()
        c = jax.device_get(x)
        d = float(x)
        ok = np.asarray(x, np.int32)   # explicit host conversion
        return a, b, c, d, ok
    '''
    ids = rule_ids(src, 'skypilot_tpu/inference/x.py')
    assert ids == ['GC202'] * 4


def test_gc202_only_applies_to_compute_dirs():
    src = '''
    import numpy as np
    def f(x):
        return np.asarray(x)
    '''
    assert rule_ids(src, 'skypilot_tpu/serve/x.py') == []
    assert rule_ids(src, 'skypilot_tpu/models/x.py') == ['GC202']
    # The helper module itself is exempt.
    assert rule_ids(src, 'skypilot_tpu/utils/host.py') == []


# ------------------------------------------------- suppression / baseline
def test_inline_suppression():
    src = '''
    def f():
        try:
            return 1
        except Exception:   # graftcheck: disable=GC105
            pass
    '''
    assert rule_ids(src) == []


def test_fingerprint_is_line_number_stable():
    src1 = 'def f():\n    try:\n        pass\n    except:\n        pass\n'
    src2 = '\n\n' + src1     # shifted two lines down
    fp1 = rules_lib.check_source('p.py', src1)[0].fingerprint
    fp2 = rules_lib.check_source('p.py', src2)[0].fingerprint
    assert fp1 == fp2


def test_baseline_round_trip(tmp_path):
    v = rules_lib.check_source(
        'p.py', 'try:\n    pass\nexcept:\n    pass\n')[0]
    path = str(tmp_path / 'base')
    lint_lib.write_baseline([v], path)
    assert v.fingerprint in lint_lib.load_baseline(path)


# ------------------------------------------------------------ repo gate
def test_repo_is_clean_modulo_baseline():
    """THE gate: zero violations outside graftcheck.baseline. If this
    fails, fix the violation (preferred) or — for a reviewed,
    deliberate pattern — add its fingerprint to the baseline with a
    justification comment."""
    new, _old = lint_lib.lint_paths()
    assert not new, ('graftcheck found new violations:\n\n'
                     + '\n'.join(v.format() for v in new))


def test_baseline_has_no_stale_entries():
    """Baseline entries whose violation was fixed must be pruned, or
    the suppression could silently re-cover a future regression."""
    baseline = lint_lib.load_baseline()
    _new, old = lint_lib.lint_paths()
    stale = baseline - {v.fingerprint for v in old}
    assert not stale, f'stale graftcheck.baseline entries: {stale}'


def test_cli_smoke(capsys):
    assert graftcheck_main(['rules']) == 0
    out = capsys.readouterr().out
    assert 'GC202' in out
    assert graftcheck_main(['lint']) == 0


# ------------------------------------------------------------------ GC117
def test_gc117_wallclock_in_sim_flagged():
    src = '''
    import time
    def run_until(self, t_end):
        t0 = time.time()
        time.sleep(0.1)
        return time.monotonic() - t0
    '''
    ids = rule_ids(src, 'skypilot_tpu/serve/sim/core.py')
    assert ids == ['GC117', 'GC117', 'GC117']


def test_gc117_bare_from_import_spellings_flagged():
    src = '''
    from time import monotonic, perf_counter
    def tick(self):
        return monotonic() + perf_counter()
    '''
    assert rule_ids(src, 'skypilot_tpu/serve/sim/fleet.py') == [
        'GC117', 'GC117']


def test_gc117_virtual_clock_and_references_clean():
    # The sanctioned spellings: the EventLoop's own virtual clock,
    # method sleeps routed through the loop/env seam, and passing a
    # clock CALLABLE (name reference, no call).
    src = '''
    import time
    class EventLoop:
        def __init__(self):
            self.now = 0.0
        def sleep(self, s):
            self.now += s
    def drive(loop, env):
        loop.sleep(1.0)
        env.sleep(2.0)
        return loop.now
    def make_clock(fallback=time.time):
        return fallback
    '''
    assert rule_ids(src, 'skypilot_tpu/serve/sim/env.py') == []


def test_gc117_only_polices_sim_paths():
    # The same wall-clock calls outside serve/sim/ are not GC117's
    # business (other rules may still apply in their own dirs).
    src = '''
    import time
    def probe(self):
        return time.time()
    '''
    assert 'GC117' not in rule_ids(src,
                                   'skypilot_tpu/serve/server_x.py')
    assert rule_ids(src, 'skypilot_tpu/serve/sim/replica.py') == [
        'GC117']


# ------------------------------------------------------------------ GC118
def test_gc118_unknown_fault_site_flagged():
    src = '''
    class M:
        def loop(self):
            rule = self._faults.fire('engin_step')
    '''
    vs = check(src)
    assert [v.rule for v in vs] == ['GC118']
    assert 'engin_step' in vs[0].message


def test_gc118_kwarg_spelling_flagged():
    src = '''
    class M:
        def loop(self):
            rule = self._faults.fire(site='kv_wires')
    '''
    assert rule_ids(src) == ['GC118']


def test_gc118_registered_sites_clean():
    # Every registry member is legal, positional or kwarg, and
    # non-literal sites (the simulator's site-tuple sweep) are skipped
    # — their tuples hold registry members by construction.
    src = '''
    SITES = ('sim_storm', 'sim_gray')
    class M:
        def loop(self):
            a = self._faults.fire('engine_step')
            b = self._faults.fire(site='canary')
            c = inj.fire('kv_wire')
            for s in SITES:
                inj.fire(s)
    '''
    assert rule_ids(src) == []


def test_gc118_only_polices_serve():
    # A .fire() outside serve/ is somebody else's API.
    src = '''
    class Gun:
        def pull(self):
            self.trigger.fire('bullet')
    '''
    assert 'GC118' not in rule_ids(src, 'skypilot_tpu/jobs/gun.py')


def test_gc118_every_live_fire_site_is_registered():
    # The repo-wide gate (test_repo_is_clean_modulo_baseline) enforces
    # this transitively; pin the registry contents the sim site-tuples
    # rely on explicitly too.
    from skypilot_tpu.serve import faults as faults_lib
    from skypilot_tpu.serve.sim import fleet as sim_fleet
    for site in sim_fleet.SIM_FAULT_SITES:
        assert site in faults_lib.FAULT_SITES, site
    for kind in faults_lib.GRAY_FAILURE_KINDS:
        assert kind in faults_lib.FAULT_KINDS, kind


# ------------------------------------------------------------------ GC120
def test_gc120_direct_row_write_flagged():
    # A serve_state row write outside the journaled persist helpers.
    src = '''
    from skypilot_tpu.serve import serve_state
    class ReplicaManager:
        def scale_up(self):
            serve_state.add_or_update_replica('svc', 1, 'c', 'READY',
                                              None, 1, False)
    '''
    vs = check(src, 'skypilot_tpu/serve/replica_managers.py')
    assert [v.rule for v in vs] == ['GC120']
    assert 'add_or_update_replica' in vs[0].message


def test_gc120_env_seam_write_flagged():
    # The env-seam spelling of the same mutation is gated too — the
    # journal invariant is about the WRITE, not the module it routes
    # through.
    src = '''
    class ReplicaManager:
        def probe_all(self):
            self._env.persist_replica('svc', 1, 'c', 'READY', None,
                                      1, False, 8081)
        def tick(self):
            self._env.put_note('svc', 'k', 1)
    '''
    assert rule_ids(src, 'skypilot_tpu/serve/controller.py') == [
        'GC120', 'GC120']


def test_gc120_journaled_helpers_clean():
    # Inside the sanctioned helper scopes (nested closures included)
    # the same calls are THE implementation, not a violation; reads
    # are never gated.
    src = '''
    class ReplicaManager:
        def _persist(self, info):
            self._env.persist_replica('svc', 1, 'c', 'READY', None,
                                      1, False, 8081)
        def _untrack(self, rid):
            self._env.remove_replica('svc', rid)
        def _journal_start(self, kind, info):
            return self._env.journal_op_start('svc', kind, 1, None)
        def _journal_finish(self, op_id):
            self._env.journal_op_finish('svc', op_id)
        def _put_note(self, key, value):
            self._env.put_note('svc', key, value)
        def _persist_autoscaler_state(self):
            def retry():
                self._env.put_note('svc', 'autoscaler_state', {})
            retry()
        def reconcile(self):
            rows = self._env.load_replica_rows('svc')
            ops = self._env.pending_ops('svc')
            notes = self._env.get_notes('svc')
            return rows, ops, notes
    '''
    assert rule_ids(src, 'skypilot_tpu/serve/replica_managers.py') == []


def test_gc120_only_polices_lifecycle_modules():
    # control_env.py (the seam's live implementation) and everything
    # else keep calling serve_state directly — the rule gates the
    # state machines, not the seam.
    src = '''
    from skypilot_tpu.serve import serve_state
    def persist_replica(service_name, replica_id):
        serve_state.add_or_update_replica(service_name, replica_id,
                                          'c', 'READY', None, 1, False)
    '''
    assert 'GC120' not in rule_ids(src,
                                   'skypilot_tpu/serve/control_env.py')
    assert 'GC120' not in rule_ids(src, 'skypilot_tpu/serve/rpc.py')


def test_gc120_journal_kinds_registered():
    # The manager only journals kinds serve_state validates — a typo'd
    # kind would raise at journal time, never silently no-op.
    from skypilot_tpu.serve import serve_state
    for kind in ('launch', 'drain', 'teardown'):
        assert kind in serve_state.JOURNAL_OP_KINDS
    import pytest as _pytest
    with _pytest.raises(ValueError, match='unknown journal op kind'):
        serve_state.journal_op_start('svc', 'meteor', 1, None)


# ------------------------------------------------------------------ GC122
LB_POLICY_PATH = 'skypilot_tpu/serve/load_balancing_policies.py'


def test_gc122_raw_map_growth_flagged():
    # Per-key writes and growth-method calls on self.* containers in
    # the LB-policy module — sessions/replica URLs churn unboundedly,
    # so every runtime map must be a BoundedStore.
    src = '''
    class SomePolicy:
        def select(self, key):
            self._sessions[key] = 'url'
            self._counts[key] += 1
            self._urls.append(key)
            self._seen.add(key)
            self._merged.update({key: 1})
    '''
    assert rule_ids(src, LB_POLICY_PATH) == ['GC122'] * 5


def test_gc122_bounded_store_and_reassignment_clean():
    # Inside BoundedStore the raw mutations ARE the implementation;
    # wholesale reassignment replaces rather than grows; locals are
    # per-call.
    src = '''
    class BoundedStore:
        def put(self, key, value):
            self._d[key] = value
            self._order.append(key)
    class SomePolicy:
        def set_ready_replicas(self, urls):
            self._gangs = dict(self._planned_gangs)
        def select(self, key):
            pool = {}
            pool[key] = 1
            ranked = []
            ranked.append(key)
            return pool, ranked
    '''
    assert rule_ids(src, LB_POLICY_PATH) == []


def test_gc122_only_polices_lb_policy_module():
    # The same source elsewhere in serve/ is out of scope — the rule
    # gates the long-resident policy tables, not every dict in the
    # tree.
    src = '''
    class Tracker:
        def note(self, key):
            self._seen[key] = 1
    '''
    assert 'GC122' not in rule_ids(src, 'skypilot_tpu/serve/server.py')


def test_gc122_real_policy_module_clean():
    # The shipped module itself holds the invariant: zero GC122 (and
    # zero anything else) with only explicitly annotated suppressions.
    import pathlib
    mod = pathlib.Path(rules_lib.__file__).resolve()
    repo = mod.parents[2]
    src = (repo / LB_POLICY_PATH).read_text()
    vs = rules_lib.check_source(LB_POLICY_PATH, src)
    assert vs == [], [f'{v.rule}:{v.line}' for v in vs]


# ------------------------------------------------------------------ GC123
def test_gc123_request_with_body_flagged():
    # A body-carrying hop built straight on urllib under serve/ cannot
    # carry the X-Skytpu-Trace header — the trace loses the leg.
    src = '''
    import urllib.request
    def push(url, body):
        req = urllib.request.Request(url, data=body, method='POST')
        return urllib.request.urlopen(req, timeout=5)
    '''
    vs = check(src)
    assert [v.rule for v in vs] == ['GC123']
    assert 'wire' in vs[0].message


def test_gc123_positional_data_flagged():
    # The data arg smuggled positionally is the same untraced hop.
    src = '''
    from urllib import request
    def push(url, body):
        return request.Request(url, body)
    '''
    assert 'GC123' in rule_ids(src)


def test_gc123_bodyless_get_clean():
    # GETs carry no body; probes/scrapes stay on plain urlopen.
    src = '''
    import urllib.request
    def scrape(url):
        req = urllib.request.Request(url, data=None)
        with urllib.request.urlopen(url, timeout=2) as resp:
            return resp.read()
    '''
    assert 'GC123' not in rule_ids(src)


def test_gc123_probe_scope_exempt():
    # Readiness probes may POST post_data by spec — they are not part
    # of any request odyssey, so the helper is not required.
    src = '''
    import urllib.request
    def probe_http(url, post_data):
        req = urllib.request.Request(url, data=post_data)
        return urllib.request.urlopen(req, timeout=5)
    '''
    assert 'GC123' not in rule_ids(src)


def test_gc123_wire_helper_itself_exempt():
    # serve/wire.py IS the helper — the raw call lives there by design.
    src = '''
    import urllib.request
    def post_json(url, payload):
        req = urllib.request.Request(url, data=payload)
        return urllib.request.urlopen(req, timeout=5)
    '''
    assert 'GC123' not in rule_ids(src, 'skypilot_tpu/serve/wire.py')


def test_gc123_only_polices_serve():
    src = '''
    import urllib.request
    def report(url, body):
        urllib.request.urlopen(url, body, 5)
    '''
    assert 'GC123' not in rule_ids(src, 'skypilot_tpu/usage_lib.py')


# --------------------------------------------- aliased-import timing
def test_gc109_aliased_time_imports_flagged():
    # ``from time import time as now`` / ``import time as t`` must not
    # smuggle wall-clock reads past the inference timing rule — the
    # checker canonicalizes aliases before matching.
    src = '''
    import time as t
    from time import time as now
    def step(self):
        return now() + t.monotonic()
    '''
    ids = rule_ids(src, 'skypilot_tpu/inference/engine_x.py')
    assert ids == ['GC109', 'GC109']


def test_gc115_aliased_time_imports_flagged():
    src = '''
    import time as t
    from time import monotonic as mono
    def evaluate(self):
        return t.time() + mono()
    '''
    assert rule_ids(src, 'skypilot_tpu/serve/autoscalers.py') == [
        'GC115', 'GC115']


def test_gc117_aliased_time_imports_flagged():
    src = '''
    from time import time as wall
    import time as t
    def run_until(self, t_end):
        return wall() - t.perf_counter()
    '''
    assert rule_ids(src, 'skypilot_tpu/serve/sim/core_x.py') == [
        'GC117', 'GC117']


def test_time_alias_canonical_name_in_message():
    src = '''
    from time import time as now
    def step(self):
        return now()
    '''
    v = check(src, 'skypilot_tpu/inference/x.py')[0]
    assert 'time.time' in v.message


def test_non_time_aliases_not_canonicalized():
    # An alias of something that merely LOOKS like a clock must not
    # trip the rules: only the time module's names canonicalize.
    src = '''
    from mylib import time as now
    def step(self):
        return now()
    '''
    assert rule_ids(src, 'skypilot_tpu/inference/x.py') == []


# -------------------------------------------------- graftcheck --json
def test_cli_lint_json_schema(capsys):
    import json as json_lib
    assert graftcheck_main(['lint', '--json']) == 0
    doc = json_lib.loads(capsys.readouterr().out)
    assert set(doc) == {'ok', 'violations', 'baselined'}
    assert doc['ok'] is True and doc['violations'] == []
    assert isinstance(doc['baselined'], int)


def test_cli_lint_json_violation_fields(tmp_path, capsys):
    import json as json_lib
    bad = tmp_path / 'skypilot_tpu' / 'serve' / 'x.py'
    bad.parent.mkdir(parents=True)
    bad.write_text('try:\n    pass\nexcept:\n    pass\n')
    assert graftcheck_main(
        ['lint', '--json', '--baseline', str(tmp_path / 'empty'),
         str(bad)]) == 1
    doc = json_lib.loads(capsys.readouterr().out)
    assert doc['ok'] is False and len(doc['violations']) == 1
    v = doc['violations'][0]
    assert set(v) == {'rule', 'path', 'line', 'col', 'func',
                      'message', 'source'}
    assert v['rule'] == 'GC104'


# ----------------------------------------- byte-budget staleness gate
def test_byte_budgets_name_only_live_presets():
    """Same contract as the lint-baseline staleness gate, for byte
    budgets: a budget entry for a preset that no longer exists would
    silently gate nothing — fail loudly instead."""
    from skypilot_tpu.analysis import costmodel, jaxpr_audit
    stale = sorted(set(costmodel.BYTE_BUDGETS) -
                   set(jaxpr_audit.PRESETS))
    assert not stale, f'BYTE_BUDGETS names unknown presets: {stale}'


def test_byte_budget_classes_are_known():
    from skypilot_tpu.analysis import costmodel
    known = set(costmodel.ALL_CLASSES)
    for preset, labels in costmodel.BYTE_BUDGETS.items():
        for label, caps in labels.items():
            for key in caps:
                assert (key in known
                        or key.startswith('collective.')), (
                    preset, label, key)
