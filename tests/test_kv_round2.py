"""KV round two: cross-layer fused paged attention, int4 KV codes,
and in-scan speculative verify.

Contracts pinned here:

- **Cross-layer batching (op level).** ``paged_decode_attention_all_layers``
  (one pallas_call, layer axis on the grid) is byte-identical to L
  stacked per-layer ``paged_decode_attention`` calls — bf16, int8 AND
  packed-int4 pools; the packed grid kernel's in-VMEM nibble unpack
  exactly equals the unpacked int8-codes reference.
- **Fused merge (op level).** ``paged_decode_attention_fused`` (cache
  pages + ring + current token in ONE kernel) matches the per-layer
  partial + ``merge_partial_with_ring_self`` XLA merge to float ulps.
- **``decode_impl='cross_layer'`` (engine level).** Greedy decode is
  byte-identical to ``gather`` and ``pallas`` across every KV dtype.
- **int4 KV.** Packed uint8 nibble pools (head_dim/2 minor) with
  absmax/7 scales serve byte-identically to bf16 KV on the tiny model,
  and the full divergence matrix (chunked prefill, prefix-cache reuse,
  speculative commits) holds in the slow tier.
- **In-scan speculative verify.** ``speculate_k`` composed with
  ``decode_steps_per_call > 1`` fuses that many propose→verify→commit
  rounds into ONE dispatch; greedy output stays byte-identical to
  vanilla decode AND to single-round speculation on both engines; the
  device n-gram proposer matches the host proposer on the windowed
  history; paged pool pressure falls back to single-round verify with
  no output change.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu.inference.engine import (InferenceEngine,
                                           kv_token_bytes,
                                           resolve_kv_cache_dtype)
from skypilot_tpu.inference.paged import PagedInferenceEngine, PagedKVCache
from skypilot_tpu.models import configs, llama
from skypilot_tpu.models import quantization as q
from skypilot_tpu.ops.paged_attention import (
    merge_partial_with_ring_self, paged_decode_attention,
    paged_decode_attention_all_layers, paged_decode_attention_fused)

jax.config.update('jax_platforms', 'cpu')

PROMPTS = [[3, 1, 4, 1, 5, 9, 2], [2, 7]]
REPETITIVE = [3, 1, 4, 1, 5, 9, 2, 6] * 4


@pytest.fixture(scope='module')
def setup():
    cfg = configs.TINY
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def _greedy(engcls, cfg, params, prompts, n_new, **kw):
    eng = engcls(cfg, params, max_batch=len(prompts), max_seq=64,
                 attn_impl='xla', **kw)
    rids = [eng.add_request(list(p), max_new_tokens=n_new)
            for p in prompts]
    done = eng.run_to_completion(horizon=2)
    return [done[r].output for r in rids], eng


# ---------------------------------------------------------------------------
# int4 KV plumbing (fast tier)
# ---------------------------------------------------------------------------
def test_resolve_and_token_bytes_int4():
    """int4 weights pull the KV to int4 under auto; explicit dtypes
    always win; the per-token byte math (packed codes at head_dim/2
    plus a 4-byte fp32 scale per head) clears 3x vs bf16 at serving
    head dims and feeds page sizing exactly."""
    assert resolve_kv_cache_dtype('int4', None) == 'int4'
    assert resolve_kv_cache_dtype(None, 'int4') == 'int4'
    assert resolve_kv_cache_dtype('auto', 'int4') == 'int4'
    assert resolve_kv_cache_dtype('int8', 'int4') == 'int8'
    cfg = configs.LLAMA3_8B
    bf16 = kv_token_bytes(cfg, quantized=False)
    i4 = kv_token_bytes(cfg, 'int4')
    assert i4 == cfg.n_layers * cfg.n_kv_heads * (cfg.head_dim // 2
                                                  + 4) * 2
    assert bf16 / i4 >= 3.0
    assert PagedInferenceEngine._page_bytes(cfg, 128, 'int4') == i4 * 128


def test_packed_pool_layout():
    """Packed pools are uint8 at head_dim/2 with fp32 scales; the
    ``packed`` / ``quant_mode`` detection is dtype-driven on both cache
    kinds; odd head_dim is refused loudly."""
    cfg = configs.TINY
    pc = PagedKVCache.create(cfg, n_pages=4, page_size=8,
                             kv_dtype='int4')
    assert pc.pool_k.dtype == jnp.uint8
    assert pc.pool_k.shape[-1] == cfg.head_dim // 2
    assert pc.k_scale is not None and pc.k_scale.dtype == jnp.float32
    assert pc.packed and pc.quant_mode == 'int4'
    sc = llama.KVCache.create(cfg, 2, 16, kv_dtype='int4')
    assert sc.k.dtype == jnp.uint8
    assert sc.k.shape[-1] == cfg.head_dim // 2
    assert sc.packed and sc.quantized
    import dataclasses
    odd = dataclasses.replace(cfg, head_dim_override=3)
    with pytest.raises(ValueError):
        llama.KVCache.create(odd, 2, 16, kv_dtype='int4')


def test_quantize_kv_rows4_round_trip():
    """absmax/7 row quantization: codes stay in [-7, 7], packed low
    nibble first along head_dim, and unpack x scale reconstructs to
    within half a quantization step."""
    rng = np.random.default_rng(0)
    rows = jnp.asarray(rng.standard_normal((2, 5, 3, 8))
                       .astype(np.float32))
    codes, scale = llama.quantize_kv_rows4(rows)
    assert codes.dtype == jnp.uint8 and codes.shape[-1] == 4
    unpacked = q.unpack_int4(np.asarray(codes), axis=-1)
    assert unpacked.min() >= -7 and unpacked.max() <= 7
    recon = unpacked.astype(np.float32) * np.asarray(scale)
    err = np.abs(recon - np.asarray(rows))
    assert (err <= 0.5 * np.asarray(scale) + 1e-6).all()


# ---------------------------------------------------------------------------
# Cross-layer / fused kernels (op level, interpret mode)
# ---------------------------------------------------------------------------
def _make_pools(seed, L=2, n_pages=9, hkv=2, page=8, d=8, slots=3,
                P=2, mode='bf16'):
    rng = np.random.default_rng(seed)
    hq = 2 * hkv
    q_all = jnp.asarray(rng.standard_normal((L, slots, hq, d))
                        .astype(np.float32))
    # Distinct pages per slot (page 0 reserved, engine-style).
    ids = rng.permutation(np.arange(1, n_pages))[:slots * P]
    table = jnp.asarray(ids.reshape(slots, P).astype(np.int32))
    lengths = jnp.asarray(
        rng.integers(1, page * P + 1, slots).astype(np.int32))
    shape = (L, n_pages, hkv, page, d)
    if mode == 'bf16':
        pk = jnp.asarray(rng.standard_normal(shape).astype(np.float32))
        pv = jnp.asarray(rng.standard_normal(shape).astype(np.float32))
        return q_all, pk, pv, None, None, table, lengths
    lo = -7 if mode == 'int4' else -127
    hi = 8 if mode == 'int4' else 128
    ck = rng.integers(lo, hi, shape).astype(np.int8)
    cv = rng.integers(lo, hi, shape).astype(np.int8)
    ks = jnp.asarray(rng.random(shape[:-1]).astype(np.float32) + 0.1)
    vs = jnp.asarray(rng.random(shape[:-1]).astype(np.float32) + 0.1)
    if mode == 'int4':
        return (q_all, jnp.asarray(q.pack_int4(ck, axis=-1)),
                jnp.asarray(q.pack_int4(cv, axis=-1)), ks, vs,
                table, lengths), (jnp.asarray(ck), jnp.asarray(cv))
    return q_all, jnp.asarray(ck), jnp.asarray(cv), ks, vs, table, lengths


@pytest.mark.parametrize('mode', ['bf16', 'int8'])
def test_all_layers_kernel_matches_per_layer(mode):
    """ONE pallas_call over (slots, L, P) == L per-layer calls,
    bit-for-bit (same op sequence per page block)."""
    q_all, pk, pv, ks, vs, table, lengths = _make_pools(1, mode=mode)
    L = q_all.shape[0]
    acc, m, l = paged_decode_attention_all_layers(
        q_all, pk, pv, table, lengths, ks, vs, interpret=True)
    for li in range(L):
        a1, m1, l1 = paged_decode_attention(
            q_all[li], pk, pv, table, lengths, ks, vs, layer=li,
            interpret=True)
        np.testing.assert_array_equal(np.asarray(acc[li]),
                                      np.asarray(a1))
        np.testing.assert_array_equal(np.asarray(m[li]), np.asarray(m1))
        np.testing.assert_array_equal(np.asarray(l[li]), np.asarray(l1))


def test_all_layers_kernel_int4_packed_exact():
    """The packed-int4 grid kernel's in-VMEM nibble unpack is EXACTLY
    the unpacked int8-codes computation (scale-agnostic integer code
    math before the fold)."""
    (q_all, pk4, pv4, ks, vs, table, lengths), (ck, cv) = \
        _make_pools(2, mode='int4')
    got = paged_decode_attention_all_layers(
        q_all, pk4, pv4, table, lengths, ks, vs, interpret=True)
    want = paged_decode_attention_all_layers(
        q_all, ck, cv, table, lengths, ks, vs, interpret=True)
    for g, w in zip(got, want):
        np.testing.assert_array_equal(np.asarray(g), np.asarray(w))


@pytest.mark.parametrize('mode', ['bf16', 'int8'])
def test_fused_kernel_matches_xla_merge(mode):
    """The fused kernel (pages + ring + current token, one kernel) ==
    per-layer partial then ``merge_partial_with_ring_self`` to float
    ulps (the merge runs elementwise sums where XLA uses dots)."""
    q_all, pk, pv, ks, vs, table, lengths = _make_pools(3, mode=mode)
    rng = np.random.default_rng(4)
    L, slots, hq, d = q_all.shape
    hkv = pk.shape[2]
    H = 4
    k_self = jnp.asarray(rng.standard_normal((slots, hkv, d))
                         .astype(np.float32))
    v_self = jnp.asarray(rng.standard_normal((slots, hkv, d))
                         .astype(np.float32))
    ring_k = jnp.asarray(rng.standard_normal((slots, H, hkv, d))
                         .astype(np.float32))
    ring_v = jnp.asarray(rng.standard_normal((slots, H, hkv, d))
                         .astype(np.float32))
    for ring_len in (0, 2):
        for li in range(L):
            got = paged_decode_attention_fused(
                q_all[li], k_self, v_self, ring_k, ring_v, ring_len,
                pk, pv, table, lengths, ks, vs, layer=li,
                interpret=True)
            partial = paged_decode_attention(
                q_all[li], pk, pv, table, lengths, ks, vs, layer=li,
                interpret=True)
            want = merge_partial_with_ring_self(
                partial, q_all[li][:, None], k_self[:, None],
                v_self[:, None], ring_k, ring_v, ring_len)[:, 0]
            np.testing.assert_allclose(np.asarray(got),
                                       np.asarray(want),
                                       rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize('dtype', ['bf16', 'int8', 'int4'])
def test_cross_layer_engine_identity(setup, dtype):
    """``decode_impl='cross_layer'`` greedy decode is byte-identical to
    ``gather`` and ``pallas`` for every KV dtype (the fused kernel is
    the same math, one dispatch fewer per layer)."""
    cfg, params = setup
    outs = {}
    for impl in ('gather', 'pallas', 'cross_layer'):
        outs[impl], _ = _greedy(
            PagedInferenceEngine, cfg, params, PROMPTS, 5,
            page_size=8, kv_cache_dtype=dtype, decode_impl=impl)
    assert outs['cross_layer'] == outs['gather'], dtype
    assert outs['pallas'] == outs['gather'], dtype


def test_int4_greedy_smoke(setup):
    """Tier-1 smoke: int4 KV greedy decode matches bf16 KV on both
    engines (tiny model; the divergence matrix rides the slow tier)."""
    cfg, params = setup
    for engcls, kw in ((InferenceEngine, {}),
                       (PagedInferenceEngine, {'page_size': 8})):
        bf, _ = _greedy(engcls, cfg, params, PROMPTS, 8,
                        kv_cache_dtype='bf16', **kw)
        i4, eng = _greedy(engcls, cfg, params, PROMPTS, 8,
                          kv_cache_dtype='int4', **kw)
        assert i4 == bf, engcls.__name__
        assert eng.cache.packed and eng.kv_cache_dtype == 'int4'


# ---------------------------------------------------------------------------
# In-scan speculative verify (fast tier)
# ---------------------------------------------------------------------------
def test_ngram_propose_device_matches_host():
    """The device proposer == the host proposer run on the windowed
    (right-aligned, H-token) history — same match, same continuation,
    same count."""
    from skypilot_tpu.inference.speculative import (ngram_propose,
                                                    ngram_propose_device)
    rng = np.random.RandomState(0)
    H, k = 64, 4
    for _ in range(50):
        n = rng.randint(2, 80)
        vocab = int(rng.choice([3, 5, 50]))
        hist = rng.randint(0, vocab, size=n).tolist()
        row = np.full((1, H), -1, np.int32)
        t = hist[-H:]
        row[0, H - len(t):] = t
        prop, n_prop = ngram_propose_device(jnp.asarray(row), k)
        m = int(n_prop[0])
        want = ngram_propose(hist[-H:], k)
        assert m == len(want)
        assert np.asarray(prop)[0, :m].tolist() == want[:m].tolist()
        # Positions past n_prop are zeroed (fixed-shape contract).
        assert (np.asarray(prop)[0, m:] == 0).all()


@pytest.mark.parametrize('engcls,kw', [
    (InferenceEngine, {}),
    (PagedInferenceEngine, {'page_size': 8, 'decode_impl': 'gather'}),
])
def test_spec_fused_byte_identity(setup, engcls, kw):
    """THE composition contract: speculate_k x decode_steps_per_call
    fused rounds commit byte-identically to vanilla greedy decode AND
    to single-round speculation — the in-scan device proposer and
    budget carry change dispatch count only, never tokens."""
    cfg, params = setup
    prompts = [REPETITIVE[:16], [2, 7, 2, 7, 2, 7, 2, 7]]
    base, _ = _greedy(engcls, cfg, params, prompts, 12, **kw)
    single, e1 = _greedy(engcls, cfg, params, prompts, 12,
                         speculate_k=3, **kw)
    fused, e2 = _greedy(engcls, cfg, params, prompts, 12,
                        speculate_k=3, decode_steps_per_call=3, **kw)
    assert single == base
    assert fused == base
    # Both paths accept drafts on the repetitive prompts, and the
    # stable metrics schema keeps reporting.
    assert e1.spec_metrics()['spec_accepted'] > 0
    assert e2.spec_metrics()['spec_accepted'] > 0
    assert e2.spec_metrics()['spec_rounds'] >= e2.spec_metrics()[
        'speculate_k']


def test_spec_fused_int4_composes(setup):
    """All three fronts at once: int4 KV + fused spec rounds still
    match the bf16 vanilla output on the tiny model."""
    cfg, params = setup
    prompts = [REPETITIVE[:16], [2, 7, 2, 7, 2, 7, 2, 7]]
    want, _ = _greedy(PagedInferenceEngine, cfg, params, prompts, 10,
                      page_size=8, decode_impl='gather')
    got, eng = _greedy(PagedInferenceEngine, cfg, params, prompts, 10,
                       page_size=8, decode_impl='gather',
                       kv_cache_dtype='int4', speculate_k=3,
                       decode_steps_per_call=3)
    assert got == want
    assert eng.cache.packed


def test_spec_fused_pool_pressure_fallback(setup):
    """When the pool cannot reserve rounds x (k+1) rows up front, the
    fused step falls back to single-round verify — output unchanged,
    requests complete."""
    cfg, params = setup
    prompts = [REPETITIVE[:16], [2, 7, 2, 7, 2, 7, 2, 7]]
    want, _ = _greedy(PagedInferenceEngine, cfg, params, prompts, 10,
                      page_size=8, decode_impl='gather')
    eng = PagedInferenceEngine(cfg, params, max_batch=2, max_seq=64,
                               page_size=8, n_pages=10,
                               attn_impl='xla', decode_impl='gather',
                               speculate_k=3, decode_steps_per_call=4)
    rids = [eng.add_request(list(p), max_new_tokens=10)
            for p in prompts]
    done = eng.run_to_completion(horizon=2)
    assert [done[r].output for r in rids] == want


def test_spec_fused_budget_respected(setup):
    """The in-scan ``rem`` carry never overshoots ``max_new_tokens``
    even when rounds x (k+1) far exceeds the remaining budget."""
    cfg, params = setup
    got, _ = _greedy(InferenceEngine, cfg, params, [REPETITIVE[:16]],
                     3, speculate_k=4, decode_steps_per_call=4)
    want, _ = _greedy(InferenceEngine, cfg, params, [REPETITIVE[:16]],
                      3)
    assert got == want and len(got[0]) == 3


# ---------------------------------------------------------------------------
# Slow tier: the int4-vs-bf16 divergence matrix (mirrors test_kv_int8)
# ---------------------------------------------------------------------------
MATRIX_PROMPTS = [[3, 1, 4, 1, 5], [2, 7, 1, 8, 2, 8, 1, 8],
                  [(i * 7 + 3) % 256 for i in range(60)]]


@pytest.mark.slow
class TestKVInt4Equivalence:

    def _greedy4(self, engcls, cfg, params, prompts, n_new, **kw):
        eng = engcls(cfg, params, max_batch=4, max_seq=256,
                     attn_impl='xla', **kw)
        rids = [eng.add_request(list(p), max_new_tokens=n_new)
                for p in prompts]
        done = eng.run_to_completion(horizon=4)
        return [done[r].output for r in rids], eng

    def test_slot_chunked_prefill(self, setup):
        """Chunking contract under int4: prompts that fit in ONE chunk
        are byte-identical chunked vs monolithic (chunking is a no-op);
        for longer prompts later chunks attend over already-quantized
        rows where monolithic prefill rides full precision in-window —
        a REAL int4 perturbation, so the pin is first-token agreement
        and completion, not byte identity. (int8's finer grid kept the
        tiny model's argmax stable; int4's 15-level grid does not —
        divergence on random-init weights is the quantization error
        itself, same philosophy as test_int4.)"""
        cfg, params = setup
        i4, _ = self._greedy4(InferenceEngine, cfg, params,
                              MATRIX_PROMPTS, 12,
                              kv_cache_dtype='int4',
                              prefill_chunk_tokens=16)
        mono, _ = self._greedy4(InferenceEngine, cfg, params,
                                MATRIX_PROMPTS, 12,
                                kv_cache_dtype='int4',
                                prefill_chunk_tokens=0)
        assert i4[0] == mono[0] and i4[1] == mono[1]   # <= one chunk
        assert i4[2][0] == mono[2][0]                  # 60-token prompt
        assert all(len(o) == 12 for o in i4)
        # Against bf16 KV the short prompts keep a long exact prefix.
        bf, _ = self._greedy4(InferenceEngine, cfg, params,
                              MATRIX_PROMPTS, 12,
                              kv_cache_dtype='bf16',
                              prefill_chunk_tokens=16)
        for a, b in zip(i4[:2], bf[:2]):
            agree = sum(x == y for x, y in zip(a, b))
            assert agree >= 8, (a, b)

    def test_paged_chunked_prefill(self, setup):
        """Same contract on the paged pool: chunk-size invariance for
        sub-chunk prompts, first-token agreement beyond, and the chunk
        counter proves the 60-token prompt actually chunked."""
        cfg, params = setup
        c16, eng = self._greedy4(PagedInferenceEngine, cfg, params,
                                 MATRIX_PROMPTS, 12,
                                 kv_cache_dtype='int4', page_size=8,
                                 chunk=16)
        c8, _ = self._greedy4(PagedInferenceEngine, cfg, params,
                              MATRIX_PROMPTS, 12,
                              kv_cache_dtype='int4', page_size=8,
                              chunk=8)
        assert c16[0] == c8[0]                 # 5 tokens: <= any chunk
        assert c16[2][0] == c8[2][0]
        assert all(len(o) == 12 for o in c16)
        assert eng.chunks_prefilled >= 4       # 60-token prompt, chunk 16

    def test_prefix_cache_reuse(self, setup):
        """THE reuse contract: a prefix HIT serving from already-packed
        pages is byte-identical to a COLD run of the same request on
        the same engine config — reuse changes where bytes come from,
        never what they are."""
        cfg, params = setup
        shared = [(i * 5 + 2) % 256 for i in range(64)]
        p1, p2 = shared + [11, 12], shared + [13, 14, 15]
        cold, _ = self._greedy4(PagedInferenceEngine, cfg, params,
                                [p2], 8, kv_cache_dtype='int4',
                                page_size=8, chunk=16)
        eng = PagedInferenceEngine(cfg, params, max_batch=1,
                                   max_seq=256, page_size=8, chunk=16,
                                   attn_impl='xla',
                                   kv_cache_dtype='int4')
        eng.add_request(p1, max_new_tokens=4)
        eng.run_to_completion(horizon=4)
        assert eng.alloc.prefix_misses == 1
        r2 = eng.add_request(p2, max_new_tokens=8)
        done = eng.run_to_completion(horizon=4)
        assert eng.alloc.prefix_hits >= 1
        assert done[r2].output == cold[0]

    def test_speculative_commits(self, setup):
        """Spec verify with int4 KV: bounded divergence (in-window
        verify rows ride full precision vs requantized vanilla rows —
        same contract as int8 KV), nonzero acceptance."""
        cfg, params = setup
        for engcls, kw in ((InferenceEngine, {}),
                           (PagedInferenceEngine, {'page_size': 8})):
            want, _ = self._greedy4(engcls, cfg, params,
                                    [REPETITIVE, MATRIX_PROMPTS[2]],
                                    16, kv_cache_dtype='int4', **kw)
            got, eng = self._greedy4(engcls, cfg, params,
                                     [REPETITIVE, MATRIX_PROMPTS[2]],
                                     16, kv_cache_dtype='int4',
                                     speculate_k=4, **kw)
            for a, b in zip(want, got):
                assert a[:10] == b[:10], engcls.__name__
                agree = sum(x == y for x, y in zip(a, b))
                assert agree >= int(0.85 * len(a)), (engcls.__name__,
                                                     a, b)
            assert eng.spec_metrics()['spec_accepted'] > 0
