#!/usr/bin/env python3
"""A kubectl stand-in for hermetic kubernetes-cloud tests.

Pods are directories under $SKYTPU_K8S_FAKE_DIR; `exec` runs the command
locally with HOME pointed at the pod's directory — the same VM-isolation
trick the local provisioner uses, but reached through the REAL
KubernetesPodRunner/k8s_client kubectl surface, so the whole launch
spine (provision -> pkg ship -> agentd -> driver fan-out) is exercised
against the kubernetes provider with no cluster.

Supported argv subset (exactly what k8s_client + KubernetesPodRunner
emit): apply -f -, get pod/pods, delete pod / pods,services -l, exec
[-i] POD -- sh -c CMD, version.
"""
import fcntl
import json
import os
import subprocess
import sys


def state_dir():
    d = os.environ['SKYTPU_K8S_FAKE_DIR']
    os.makedirs(d, exist_ok=True)
    return d


def state_path():
    return os.path.join(state_dir(), 'state.json')


class State:
    def __enter__(self):
        self._fh = open(os.path.join(state_dir(), '.lock'), 'w')
        fcntl.flock(self._fh, fcntl.LOCK_EX)
        try:
            with open(state_path(), encoding='utf-8') as f:
                self.data = json.load(f)
        except FileNotFoundError:
            self.data = {'pods': {}, 'services': {}}
        return self

    def __exit__(self, *exc):
        with open(state_path(), 'w', encoding='utf-8') as f:
            json.dump(self.data, f)
        fcntl.flock(self._fh, fcntl.LOCK_UN)
        self._fh.close()


def parse(argv):
    flags, rest, i = {}, [], 0
    while i < len(argv):
        a = argv[i]
        if a in ('--namespace', '--context', '-l', '-o', '-f'):
            flags[a] = argv[i + 1]
            i += 2
        elif a == '-i':
            flags['-i'] = True
            i += 1
        elif a.startswith('--'):
            i += 1
        else:
            rest.append(a)
            i += 1
    return flags, rest


def matches(obj, selector):
    if not selector:
        return True
    key, val = selector.split('=', 1)
    return (obj.get('metadata', {}).get('labels', {}) or {}).get(key) == val


def pod_dir(name):
    d = os.path.join(state_dir(), 'pods', name)
    os.makedirs(d, exist_ok=True)
    return d


def main():
    flags, rest = parse(sys.argv[1:])
    verb = rest[0] if rest else ''

    if verb == 'version':
        print('{"clientVersion": {}}')
        return 0

    if verb == 'apply':
        manifest = json.load(sys.stdin)
        name = manifest['metadata']['name']
        with State() as s:
            if manifest['kind'] == 'Service':
                s.data['services'][name] = manifest
            else:
                idx = len(s.data['pods'])
                manifest['status'] = {'phase': 'Running',
                                      'podIP': f'10.0.0.{idx + 1}'}
                s.data['pods'][name] = manifest
                pod_dir(name)
        print(json.dumps(manifest))
        return 0

    if verb == 'get':
        with State() as s:
            if rest[1] == 'pods':
                items = [p for p in s.data['pods'].values()
                         if matches(p, flags.get('-l'))]
                print(json.dumps({'items': items}))
                return 0
            if rest[1] == 'pod':
                p = s.data['pods'].get(rest[2])
                if p is None:
                    print(f'pods "{rest[2]}" not found', file=sys.stderr)
                    return 1
                print(json.dumps(p))
                return 0
        return 1

    if verb == 'delete':
        with State() as s:
            sel = flags.get('-l')
            if sel:
                for name in [n for n, p in s.data['pods'].items()
                             if matches(p, sel)]:
                    del s.data['pods'][name]
                for name in [n for n, v in s.data['services'].items()
                             if matches(v, sel)]:
                    del s.data['services'][name]
            elif rest[1] == 'pod':
                s.data['pods'].pop(rest[2], None)
        return 0

    if verb == 'exec':
        pod = rest[1]
        if '--' not in sys.argv:
            print('exec needs --', file=sys.stderr)
            return 1
        cmd = sys.argv[sys.argv.index('--') + 1:]
        with State() as s:
            if pod not in s.data['pods']:
                print(f'pods "{pod}" not found', file=sys.stderr)
                return 1
        env = dict(os.environ)
        home = pod_dir(pod)
        env['HOME'] = home
        env['SKYTPU_AGENT_DIR'] = os.path.join(home, '.skytpu_agent')
        # The pod must resolve `python3` to this interpreter (venv).
        env.setdefault('PATH', '')
        env['PATH'] = (os.path.dirname(sys.executable) + os.pathsep +
                       env['PATH'])
        proc = subprocess.run(cmd, env=env, cwd=home,
                              stdin=(sys.stdin.buffer
                                     if flags.get('-i') else
                                     subprocess.DEVNULL))
        return proc.returncode

    print(f'kubectl shim: unsupported argv {sys.argv[1:]}',
          file=sys.stderr)
    return 1


if __name__ == '__main__':
    sys.exit(main())
