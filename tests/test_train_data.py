"""Training data pipeline: tokenize/pack determinism, dp sharding,
end-to-end train-on-a-text-file with checkpoint resume (VERDICT r3
task 9; reference counterpart: recipe-level HF-datasets pipelines,
``llm/llama-3_1-finetuning/lora.yaml``)."""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from skypilot_tpu.train.data import TokenStream, packed_batches

_CORPUS = ("the quick brown fox jumps over the lazy dog. " * 200 +
           "pack my box with five dozen liquor jugs. " * 200)


@pytest.fixture(scope='module')
def corpus_file(tmp_path_factory):
    p = tmp_path_factory.mktemp('corpus') / 'corpus.txt'
    p.write_text(_CORPUS)
    return str(p)


class TestPacking:

    def test_shapes_and_shift(self, corpus_file):
        stream = TokenStream(corpus_file)
        it = packed_batches(stream, batch=4, seq=32)
        b = next(it)
        assert b['inputs'].shape == (4, 32)
        assert b['targets'].shape == (4, 32)
        # next-token objective: targets are inputs shifted by one
        np.testing.assert_array_equal(b['inputs'][:, 1:],
                                      b['targets'][:, :-1])

    def test_deterministic_and_resumable(self, corpus_file):
        stream = TokenStream(corpus_file)
        full = [next(packed_batches(stream, batch=2, seq=16,
                                    start_step=s))
                for s in range(5)]
        it = packed_batches(stream, batch=2, seq=16)
        seq = [next(it) for _ in range(5)]
        for a, b in zip(full, seq):
            np.testing.assert_array_equal(a['inputs'], b['inputs'])

    def test_dp_ranks_disjoint(self, corpus_file):
        stream = TokenStream(corpus_file)
        b0 = next(packed_batches(stream, batch=2, seq=16, dp_rank=0,
                                 dp_size=2))
        b1 = next(packed_batches(stream, batch=2, seq=16, dp_rank=1,
                                 dp_size=2))
        assert not np.array_equal(b0['inputs'], b1['inputs'])
        # rank 1 step 0 reads the window right after rank 0's rows
        stream2 = TokenStream(corpus_file)
        g = next(packed_batches(stream2, batch=4, seq=16))
        np.testing.assert_array_equal(g['inputs'][:2], b0['inputs'])
        np.testing.assert_array_equal(g['inputs'][2:], b1['inputs'])

    def test_dir_and_glob_sources(self, tmp_path):
        (tmp_path / 'a.txt').write_text('aaaa ' * 50)
        (tmp_path / 'b.txt').write_text('bbbb ' * 50)
        s = TokenStream(str(tmp_path))
        assert len(s) > 100
        s2 = TokenStream(str(tmp_path / '*.txt'))
        assert len(s2) == len(s)

    def test_too_small_corpus_rejected(self, tmp_path):
        p = tmp_path / 'tiny.txt'
        p.write_text('hi')
        stream = TokenStream(str(p))
        with pytest.raises(ValueError, match='need >= seq\\+2'):
            next(packed_batches(stream, batch=1, seq=512))


@pytest.mark.slow
class TestTrainCli:

    def _run(self, args, cwd):
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env = dict(os.environ, JAX_PLATFORMS='cpu', PYTHONPATH=repo)
        env.pop('PALLAS_AXON_POOL_IPS', None)
        return subprocess.run(
            [sys.executable, '-m', 'skypilot_tpu.train'] + args,
            capture_output=True, text=True, cwd=cwd, env=env, check=False)

    def test_loss_decreases_and_resumes(self, corpus_file, tmp_path):
        """Train tiny model on a text file: loss decreases; a second
        invocation resumes from the checkpoint and continues to the step
        target (exactly-once: total steps match, data position follows
        the restored step)."""
        ckpt = str(tmp_path / 'ckpt')
        base = ['--model', 'tiny', '--data', corpus_file, '--batch', '8',
                '--seq', '64', '--lr', '1e-2', '--warmup-steps', '2',
                '--log-every', '2', '--ckpt-dir', ckpt]
        r1 = self._run(base + ['--steps', '6', '--save-every', '100'],
                       str(tmp_path))
        assert r1.returncode == 0, r1.stderr[-2000:]
        losses = [json.loads(l)['loss'] for l in r1.stdout.splitlines()
                  if l.startswith('{')]
        assert len(losses) >= 3
        assert losses[-1] < losses[0], losses
        assert os.path.exists(os.path.join(ckpt, 'LATEST'))

        # resume: step target extended; must continue from step 6
        r2 = self._run(base + ['--steps', '8'], str(tmp_path))
        assert r2.returncode == 0, r2.stderr[-2000:]
        assert 'resumed' in r2.stdout and 'step 6' in r2.stdout, r2.stdout
        assert 'done at step 8' in r2.stdout, r2.stdout
