"""Fleet-scale control-plane simulator (serve/sim/): the DES core,
the simulated-replica queueing model, the SimControlPlaneEnv seam
driving the REAL replica manager/controller/autoscaler/LB policies,
the chaos scenario library, determinism (same seed => byte-identical
event log), the zero-lost recovery contract, the drain-deadline
straggler path, and the `skytpu sim` CLI smoke (all fast tier-1)."""
import json
import logging

import pytest

from skypilot_tpu import telemetry
from skypilot_tpu.serve import faults as faults_lib
from skypilot_tpu.serve.service_spec import SkyServiceSpec
from skypilot_tpu.serve.sim import core as sim_core
from skypilot_tpu.serve.sim import replica as sim_replica
from skypilot_tpu.serve.sim import scenarios as sim_scenarios
from skypilot_tpu.serve.sim import traffic as sim_traffic
from skypilot_tpu.serve.sim.fleet import FleetSimulator


@pytest.fixture(autouse=True)
def _quiet_control_plane():
    root = logging.getLogger('skytpu')
    prev = root.level
    root.setLevel(logging.ERROR)
    yield
    root.setLevel(prev)


def _curve(**kw):
    base = dict(ttft_base_s=0.1, warm_ttft_base_s=0.05,
                prefill_tok_per_s=2000.0, tpot_s=0.02, slots=4,
                max_queue_wait_s=5.0, kv_pool_tokens=4000)
    base.update(kw)
    return sim_replica.ServiceCurve(**base)


# ------------------------------------------------------------- DES core
def test_event_loop_orders_callbacks_and_ties_by_schedule_order():
    loop = sim_core.EventLoop()
    seen = []
    loop.schedule(2.0, lambda: seen.append(('b', loop.now)))
    loop.schedule(1.0, lambda: seen.append(('a', loop.now)))
    loop.schedule(2.0, lambda: seen.append(('c', loop.now)))   # tie: after b
    loop.run_until(10.0)
    assert seen == [('a', 1.0), ('b', 2.0), ('c', 2.0)]
    assert loop.now == 10.0


def test_logical_task_sleeps_on_virtual_time():
    loop = sim_core.EventLoop()
    trail = []

    def task():
        trail.append(('t0', loop.now))
        loop.sleep(5.0)
        trail.append(('t1', loop.now))
        loop.sleep(2.5)
        trail.append(('t2', loop.now))

    loop.spawn(task, name='sleeper')
    loop.schedule(6.0, lambda: trail.append(('cb', loop.now)))
    loop.run_until(10.0)
    assert trail == [('t0', 0.0), ('t1', 5.0), ('cb', 6.0),
                     ('t2', 7.5)]
    loop.shutdown()


def test_callbacks_may_not_sleep():
    loop = sim_core.EventLoop()
    with pytest.raises(RuntimeError, match='outside a logical task'):
        loop.sleep(1.0)


def test_task_exception_propagates_to_the_run():
    loop = sim_core.EventLoop()

    def boom():
        loop.sleep(1.0)
        raise ValueError('sim task died')

    loop.spawn(boom, name='boom')
    with pytest.raises(ValueError, match='sim task died'):
        loop.run_until(5.0)
    loop.shutdown()


def test_tasks_interleave_deterministically():
    loop = sim_core.EventLoop()
    trail = []

    def worker(tag, delay):
        for _ in range(3):
            loop.sleep(delay)
            trail.append((tag, loop.now))

    loop.spawn(worker, 'a', 1.0, name='a')
    loop.spawn(worker, 'b', 1.5, name='b')
    loop.run_until(5.0)
    # The 3.0 tie breaks by schedule order: b registered its wake at
    # t=1.5, a registered its own later (t=2.0) — b runs first.
    assert trail == [('a', 1.0), ('b', 1.5), ('a', 2.0), ('b', 3.0),
                     ('a', 3.0), ('b', 4.5)]
    loop.shutdown()


# ----------------------------------------------------------- calibration
def test_service_curve_calibrates_from_bench_text():
    text = ('{"tpot_ms_median": 40.0, "ttft_ms_hit_median": 200.0, '
            '"ttft_ms_miss_median": 400.0, "batch": 16, '
            '"avg_prompt": 200}')
    c = sim_replica.ServiceCurve.from_bench([text])
    assert c.tpot_s == pytest.approx(0.04)
    assert c.slots == 16
    assert c.warm_ttft_base_s == pytest.approx(0.2)
    # miss = base + prompt/prefill_rate  =>  reassembles to 400 ms.
    assert c.ttft_base_s + 200 / c.prefill_tok_per_s == \
        pytest.approx(0.4)


def test_service_curve_falls_back_without_bench():
    c = sim_replica.ServiceCurve.from_bench([])
    assert c.tpot_s > 0 and c.slots >= 1 and c.prefill_tok_per_s > 0


# ------------------------------------------------------ replica model
def test_replica_fluid_queue_and_overload_shed():
    c = _curve()
    rep = sim_replica.SimReplica('c1', 'http://10.0.0.1:1', c,
                                 lambda: 0.0)
    svc = c.service_s(200, 100)           # 0.1 + 0.1 + 2.0 = 2.2 s
    j1 = rep.enqueue(0.0, 4, 200, 100, 'latency')
    assert j1.ttft_s == pytest.approx(0.2)          # empty queue
    assert j1.finish_t == pytest.approx(svc)
    assert rep.busy_until == pytest.approx(4 * svc / 4)
    # Fill past the admission bound: wait > max_queue_wait_s sheds.
    for _ in range(20):
        rep.enqueue(0.0, 4, 200, 100, 'latency')
        if rep.busy_until > c.max_queue_wait_s:
            break
    assert rep.enqueue(0.0, 1, 200, 100, 'latency') is None


def test_replica_drain_contract_and_histogram():
    c = _curve()
    now = {'t': 0.0}
    rep = sim_replica.SimReplica('c1', 'http://10.0.0.1:1', c,
                                 lambda: now['t'])
    job = rep.enqueue(0.0, 1, 100, 50, 'latency')
    assert rep.handle('/drain', {'deadline_s': 10}, None)['draining']
    with pytest.raises(sim_replica.SimHTTPError):
        rep.enqueue(0.1, 1, 100, 50, 'latency')       # 503 draining
    st = rep.handle('/drain', None, None)
    assert st['drained'] is False                      # job in flight
    h = telemetry.get_registry().histogram(
        'skytpu_replica_drain_seconds')
    n0 = h.count
    now['t'] = job.finish_t + 0.1
    rep.complete(job)
    st = rep.handle('/drain', None, None)
    assert st['drained'] is True
    assert h.count == n0 + 1                           # observed once
    assert rep.handle('/drain', None, None)['drained'] is True
    assert h.count == n0 + 1                           # ... only once


def test_replica_checkpoint_warmup_round_trip():
    c = _curve()
    rep = sim_replica.SimReplica('c1', 'http://10.0.0.1:1', c,
                                 lambda: 1.0)
    blob = rep.handle('/checkpoint', {}, None)
    assert isinstance(blob, bytes)
    rep2 = sim_replica.SimReplica('c2', 'http://10.0.0.2:1', c,
                                  lambda: 2.0)
    out = rep2.handle('/kv/warmup', None, blob)
    assert out['entries'] > 0 and rep2.warm
    # Warm prefix cache shortens TTFT (the PR-10 recovery contract).
    cold = rep.enqueue(1.0, 1, 200, 50, 'latency').ttft_s
    warmj = rep2.enqueue(2.0, 1, 200, 50, 'latency')
    assert warmj.ttft_s < cold
    with pytest.raises(sim_replica.SimHTTPError):
        rep2.handle('/kv/warmup', None, b'not json')


def test_replica_metrics_json_speaks_the_lb_probe_schema():
    rep = sim_replica.SimReplica('c1', 'http://10.0.0.1:1', _curve(),
                                 lambda: 0.0, role='prefill', tp=2)
    out = rep.handle('/metrics?format=json', None, None)
    assert set(out) == {'queue_tokens_total', 'kv_pool_tokens_free',
                        'mesh', 'disagg', 'prefix_digest'}
    assert out['mesh'] == {'tp': 2, 'dp': 1}
    assert out['disagg']['role'] == 'prefill'
    # Digest block: stable schema, empty while cold (round 18).
    assert out['prefix_digest']['page'] == sim_replica.SimReplica.PAGE
    assert out['prefix_digest']['entries'] == []
    rep.note_prefix('ab' * 20, 128)
    entries = rep.handle('/metrics?format=json', None,
                         None)['prefix_digest']['entries']
    assert entries == [{'hash': 'ab' * 20, 'len': 128, 'hits': 1}]


# ------------------------------------------------ faults (satellite)
def test_fault_rule_rejects_unknown_fields_loudly():
    with pytest.raises(ValueError, match='unknown fault-rule field'):
        faults_lib.FaultRule.from_dict(
            {'kind': 'replica_crash', 'site': 'engine_step',
             'att': 3})     # the typo'd-trigger trap


def test_fault_rule_rejects_triggerless_rules():
    with pytest.raises(ValueError, match='has no trigger'):
        faults_lib.FaultRule.from_dict(
            {'kind': 'replica_crash', 'site': 'engine_step'})


def test_fault_spec_rejects_unknown_top_level_keys():
    with pytest.raises(ValueError, match='unknown fault-spec key'):
        faults_lib.FaultInjector({'rulez': []})


def test_fault_rule_validates_trigger_ranges():
    with pytest.raises(ValueError, match='prob'):
        faults_lib.FaultRule.from_dict(
            {'kind': 'replica_crash', 'site': 'engine_step',
             'prob': 1.5})
    with pytest.raises(ValueError, match='1-based'):
        faults_lib.FaultRule.from_dict(
            {'kind': 'replica_crash', 'site': 'engine_step', 'at': 0})


def test_sim_fault_fields_parse():
    r = faults_lib.FaultRule.from_dict(
        {'kind': 'zone_outage', 'site': 'sim_zone_outage', 'at': 2,
         'zone': 'z1', 'n': 3, 'factor': 2.5})
    assert (r.zone, r.n, r.factor) == ('z1', 3, 2.5)


def test_unscoped_fire_matches_rank_targeted_rules():
    """The storm clock fires sites without a rank of its own; rules
    that carry a rank (the victim selector for sim_gang_churn) must
    still fire — only a caller that DECLARES a rank filters."""
    inj = faults_lib.FaultInjector({'rules': [
        {'kind': 'replica_crash', 'site': 'sim_gang_churn', 'at': 1,
         'rank': 1}]})
    assert inj.fire('sim_gang_churn') is not None
    # A caller that declares its rank still filters (the live gang
    # sites' semantics are unchanged).
    inj2 = faults_lib.FaultInjector({'rules': [
        {'kind': 'replica_crash', 'site': 'gang_member_crash',
         'at': 1, 'rank': 1}]})
    assert inj2.fire('gang_member_crash', rank=1) is not None
    inj3 = faults_lib.FaultInjector({'rules': [
        {'kind': 'replica_crash', 'site': 'gang_member_crash',
         'at': 1, 'rank': 1}]})
    assert inj3.fire('gang_member_crash', rank=0) is None


# ------------------------------------------- ckpt dedupe (satellite)
def test_ckpt_done_bounded_across_churn(tmp_path, monkeypatch):
    """1k simulated replica churns must not accumulate checkpoint-
    dedupe keys: _ckpt_done holds live keys only."""
    monkeypatch.setenv('SKYTPU_SERVE_DIR', str(tmp_path / 'serve'))
    from skypilot_tpu.serve.replica_managers import (ReplicaInfo,
                                                     ReplicaManager)
    mgr = ReplicaManager(
        'churn-test',
        SkyServiceSpec.from_yaml_config({'readiness_probe': '/r'}), {})
    for i in range(1, 1001):
        info = ReplicaInfo(i, f'c-{i}', 1, True, 10000 + i)
        info.url = f'http://10.0.0.{i % 250}:1'
        with mgr._lock:
            mgr._replicas[i] = info
            mgr._ckpt_done[mgr._ckpt_key(info)] = True
        mgr._untrack(i)
    assert len(mgr._ckpt_done) == 0
    # Gang keys evict when the LAST member leaves.
    a = ReplicaInfo(2001, 'g-a', 1, True, 1, gang_id='g1', gang_rank=0,
                    gang_world=2)
    b = ReplicaInfo(2002, 'g-b', 1, True, 2, gang_id='g1', gang_rank=1,
                    gang_world=2)
    with mgr._lock:
        mgr._replicas[2001] = a
        mgr._replicas[2002] = b
        mgr._ckpt_done['g1'] = True
    mgr._untrack(2001)
    assert 'g1' in mgr._ckpt_done       # rank 1 still tracked
    mgr._untrack(2002)
    assert 'g1' not in mgr._ckpt_done


# ------------------------------------------------------ fleet end-to-end
def test_smoke_scenario_zero_lost_and_migration():
    rep = sim_scenarios.run_scenario('smoke', seed=1)
    r = rep['requests']
    assert r['lost'] == 0
    assert r['completed'] > 0
    assert r['migrated'] > 0              # the zone kill hit in-flight
    assert rep['recovery_s']['n'] > 0
    assert rep['replicas']['peak_ready'] == 3
    assert rep['faults_fired'] == {'sim_zone_outage:zone_outage': 1}
    assert r['arrived'] == r['completed'] + sum(r['shed'].values())


def test_same_seed_byte_identical_event_log():
    scn = sim_scenarios.get_scenario('smoke')
    # Nonzero provision jitter makes the seed actually load-bearing
    # (smoke pins it to 0 for speed): same seed must replay to the
    # byte, a different seed must not.
    a = scn.build(seed=42, provision_jitter=0.3)
    b = scn.build(seed=42, provision_jitter=0.3)
    c = scn.build(seed=43, provision_jitter=0.3)
    ra, rb, rc = a.run(), b.run(), c.run()
    assert a.event_log() == b.event_log()
    assert ra['event_log_sha256'] == rb['event_log_sha256']
    assert ra['event_log_sha256'] != rc['event_log_sha256']


def test_real_autoscaler_scales_the_sim_fleet():
    """The REAL RequestRateAutoscaler + manager launch/probe path
    grows the fleet when simulated traffic exceeds capacity."""
    sim = FleetSimulator(
        spec=SkyServiceSpec(
            readiness_path='/readiness', min_replicas=1,
            max_replicas=6,
            target_qps_per_replica=2.0, upscale_delay_seconds=10.0,
            downscale_delay_seconds=600.0,
            initial_delay_seconds=120.0),
        trace=sim_traffic.constant(8.0, 400.0), seed=0,
        policy_name='queue_depth', curve=_curve(slots=10),
        provision_s=20.0, provision_jitter=0.0, keep_log=False)
    rep = sim.run()
    assert rep['replicas']['peak_ready'] >= 4     # 8 qps / 2 per rep
    assert rep['requests']['lost'] == 0


def test_spot_storm_scenario_recovery_contract():
    rep = sim_scenarios.run_scenario('spot_storm', seed=1)
    assert rep['requests']['lost'] == 0           # the hard contract
    assert rep['faults_fired'].get('sim_storm:preempt_signal') == 2
    assert rep['requests']['migrated'] > 0
    assert rep['recovery_s']['n'] > 0
    assert rep['slo']['throughput']['attainment'] > 0.9


def test_gang_churn_kills_and_replaces_whole_gangs():
    rep = sim_scenarios.run_scenario('gang_churn', seed=1)
    assert rep['requests']['lost'] == 0
    assert rep['faults_fired'].get(
        'sim_gang_churn:replica_crash') == 2
    # Two churn events, each killing a 2-host gang that is relaunched
    # as a unit: 3 initial gangs (6 clusters) + 2 replacements (4).
    assert rep['replicas']['launched'] == 10
    assert rep['requests']['migrated'] > 0


def test_straggler_scenario_queue_depth_routes_around():
    rep = sim_scenarios.run_scenario('stragglers', seed=1)
    assert rep['requests']['lost'] == 0
    assert rep['faults_fired'].get('sim_straggler:straggler') == 2
    assert rep['slo']['latency']['attainment'] > 0.8


def test_forecast_vs_reactive_sheds_strictly_fewer():
    rep = sim_scenarios.run_scenario('forecast_vs_reactive', seed=0)
    assert rep['forecast_sheds_strictly_fewer'] is True
    assert rep['reactive']['lost'] == 0
    assert rep['forecast']['lost'] == 0
    # Pre-scaling spends more chip-seconds — that is the trade.
    assert rep['forecast']['chip_seconds'] > 0


@pytest.mark.slow
def test_fleet_1k_scale_and_zero_lost():
    rep = sim_scenarios.run_scenario('fleet_1k', seed=1)
    assert rep['replicas']['peak_ready'] == 1000
    assert rep['requests']['arrived'] >= 1_000_000
    assert rep['requests']['lost'] == 0


# --------------------------------------- prefix affinity + LB tier
def test_lb_crash_scenario_zero_lost_and_reroute():
    """A 2-LB prefix-affinity tier loses one LB mid-trace: zero lost
    requests (the recovery contract), the survivor absorbs the dead
    LB's consistent-hash keys (reroutes counted), and multi-turn
    affinity keeps working through the crash."""
    rep = sim_scenarios.run_scenario('lb_crash', seed=1)
    assert rep['requests']['lost'] == 0
    assert rep['faults_fired'] == {'sim_lb_crash:lb_crash': 1}
    assert rep['lbs'] == {'n': 2, 'live': 1, 'crashed': 1,
                          'reroutes': rep['lbs']['reroutes']}
    assert rep['lbs']['reroutes'] > 0
    aff = rep['affinity']
    assert aff['session_requests'] > 0
    assert aff['ttft_hit_rate'] > 0.5     # affinity survives the kill
    assert (rep['requests']['arrived']
            == rep['requests']['completed']
            + sum(rep['requests']['shed'].values()))


def test_lb_crash_scenario_deterministic():
    """Same seed, byte-identical event log — the multi-LB session
    dealing, prefix chains and the LB kill all ride the virtual clock
    and seeded hashes only."""
    a = sim_scenarios.run_scenario('lb_crash', seed=7)
    b = sim_scenarios.run_scenario('lb_crash', seed=7)
    assert a['event_log_sha256'] == b['event_log_sha256']
    assert a['affinity'] == b['affinity']
    assert a['lbs'] == b['lbs']


def test_phase_aware_routing_with_real_role_placement():
    """The REAL placement.role_for_new_replica assigns disagg roles at
    scale_up; roles ride the launch env into sim replicas; the REAL
    PhaseAwarePolicy routes every request to the prefill pool and
    picks decode workers as handoff targets."""
    sim = FleetSimulator(
        spec=SkyServiceSpec(readiness_path='/readiness',
                            min_replicas=4,
                            disagg_prefill_replicas=2,
                            disagg_decode_replicas=2,
                            initial_delay_seconds=120.0),
        trace=sim_traffic.constant(2.0, 120.0), seed=0,
        policy_name='phase_aware', curve=_curve(slots=10),
        provision_s=10.0, provision_jitter=0.0, keep_log=True)
    rep = sim.run()
    assert rep['requests']['lost'] == 0
    roles = sorted(r.role for r in sim.world.replicas.values())
    assert roles == ['decode', 'decode', 'prefill', 'prefill']
    prefill_urls = {r.url for r in sim.world.replicas.values()
                    if r.role == 'prefill'}
    dispatch_urls = {line.split('url=')[1].split(' ')[0]
                     for line in sim.event_log().splitlines()
                     if line.split('|')[1] == 'dispatch'}
    assert dispatch_urls and dispatch_urls <= prefill_urls
    # Handoff targets come from the decode pool with most KV headroom.
    target = sim.policy.handoff_target()
    decode_urls = {r.url for r in sim.world.replicas.values()
                   if r.role == 'decode'}
    assert target in decode_urls


# -------------------------------------- drain straggler (satellite)
def test_drain_deadline_straggler_fails_over_exactly(monkeypatch):
    """A replica that acks /drain but never reports drained is torn
    down at EXACTLY SKYTPU_SERVE_DRAIN_S (virtual clock — exactness
    is assertable), its in-flight requests migrate with zero lost,
    and skytpu_replica_drain_seconds is still observed (by the clean
    drain running alongside)."""
    monkeypatch.setenv('SKYTPU_SERVE_DRAIN_S', '20')
    sim = FleetSimulator(
        spec=SkyServiceSpec(readiness_path='/readiness',
                            min_replicas=3,
                            initial_delay_seconds=120.0),
        trace=sim_traffic.constant(3.0, 200.0), seed=5,
        policy_name='queue_depth', curve=_curve(slots=10),
        provision_s=10.0, provision_jitter=0.0,
        never_drain_clusters={'idx:1'},     # second replica launched
        keep_log=True)
    mgr = sim.controller.replica_manager
    h = telemetry.get_registry().histogram(
        'skytpu_replica_drain_seconds')
    n0 = h.count
    drained_at = {}

    def start_drains():
        # Load the straggler with a deep decode backlog (long-running
        # in-flight work that cannot finish inside the deadline), then
        # drain it AND a clean replica through the REAL manager drain
        # state machine.
        srep = next(r for r in sim.world.replicas.values()
                    if r.never_drain)
        now = sim.loop.now
        job = srep.enqueue(now, 20, 220, 2000, 'throughput')
        assert job is not None
        sim.policy.pre_execute(srep.url)
        sim._inflight += job.count
        sim.loop.schedule(job.finish_t - now, sim._complete,
                          srep.url, job)
        drained_at['t'] = now
        srid = next(i.replica_id for i in mgr.replicas()
                    if i.url == srep.url)
        clean_id = next(i.replica_id for i in mgr.replicas()
                        if i.url != srep.url)
        assert mgr.drain(srid) is True
        assert mgr.drain(clean_id) is True

    sim.loop.schedule(60.0, lambda: sim.loop.spawn(start_drains,
                                                   name='drains'))
    rep = sim.run()
    assert rep['requests']['lost'] == 0
    # The straggler was failed over at exactly the drain deadline.
    straggler_url = next(
        r.url for r in sim.world.replicas.values() if r.never_drain)
    kills = [line for line in sim.event_log().splitlines()
             if line.split('|')[1] == 'replica_killed'
             and f'url={straggler_url}' in line]
    assert len(kills) == 1
    t_kill = float(kills[0].split('|')[0])
    assert t_kill == pytest.approx(drained_at['t'] + 20.0, abs=1e-6)
    # Its in-flight work migrated to survivors.
    assert rep['requests']['migrated'] > 0
    # The clean drain observed the drain-duration histogram.
    assert h.count > n0


# ------------------------------------------------------------ CLI smoke
def test_cli_sim_smoke_fast():
    """Tier-1 smoke gate: `skytpu sim -s smoke` must run in seconds
    and emit a parseable report with the zero-lost contract held (the
    simulator can never silently rot)."""
    from click.testing import CliRunner

    from skypilot_tpu import cli as cli_mod
    runner = CliRunner()
    out = runner.invoke(cli_mod.cli, ['sim', '-s', 'smoke',
                                      '--seed', '2'])
    assert out.exit_code == 0, out.output
    payload = json.loads(out.output[out.output.index('{'):])
    assert payload['scenario'] == 'smoke'
    assert payload['requests']['lost'] == 0
    assert payload['recovery_covered'] is True


def test_cli_sim_multi_turn_affinity_beats_queue_depth():
    """The round-18 acceptance gate: on the identical multi-turn
    1000-replica trace, ``prefix_affinity`` must beat ``queue_depth``
    on BOTH warm-TTFT hit rate (higher) and total prefix-recompute
    tokens (strictly fewer). The comparison is computed inside the
    scenario runner; the CLI smoke asserts the verdict end to end."""
    from click.testing import CliRunner

    from skypilot_tpu import cli as cli_mod
    runner = CliRunner()
    out = runner.invoke(cli_mod.cli, ['sim', '-s', 'multi_turn_affinity',
                                      '--seed', '0'])
    assert out.exit_code == 0, out.output
    payload = json.loads(out.output[out.output.index('{'):])
    assert payload['scenario'] == 'multi_turn_affinity'
    verdict = payload['affinity_beats_queue_depth']
    assert verdict['ttft_hit_rate'] is True
    assert verdict['recompute_tokens'] is True
    assert (payload['prefix_affinity']['recompute_tokens']
            < payload['queue_depth']['recompute_tokens'])
    assert payload['requests']['lost'] == 0


def test_cli_sim_list_and_unknown_scenario():
    from click.testing import CliRunner

    from skypilot_tpu import cli as cli_mod
    runner = CliRunner()
    out = runner.invoke(cli_mod.cli, ['sim', '--list'])
    assert out.exit_code == 0
    for name in sim_scenarios.SCENARIOS:
        assert name in out.output
    out = runner.invoke(cli_mod.cli, ['sim', '-s', 'nope'])
    assert out.exit_code != 0
    assert 'unknown scenario' in out.output
