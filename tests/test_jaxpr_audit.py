"""graftcheck part B: the runtime jaxpr-audit regression gate.

Asserts the invariants the serving tier's performance rests on: the
slot/paged engines' steady-state decode + chunked-prefill loops perform
ZERO device->host transfers outside the sanctioned host_sync readback,
and compile exactly once per (horizon, sample, kv_bucket) key —
repeated same-shaped calls never grow the jit caches. A regression here
is a silent multi-ms-per-step tax in production (100 ms+ through a
remote PJRT tunnel), which is why it hard-fails in CI instead of
waiting for a bench round to notice."""
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu.analysis import jaxpr_audit
from skypilot_tpu.utils import host as host_lib


# ------------------------------------------------------------ interceptor
def test_interceptor_flags_unsanctioned_sync():
    x = jnp.arange(4)
    events = []
    with jaxpr_audit.intercept_host_transfers(events):
        np.asarray(x)            # graftcheck: disable=GC202 (fixture)
        float(x[0])
    unsanctioned = [e for e in events if not e.sanctioned]
    assert len(unsanctioned) >= 2


def test_interceptor_marks_host_sync_sanctioned():
    x = jnp.arange(4)
    events = []
    with jaxpr_audit.intercept_host_transfers(events):
        out = host_lib.host_sync(x)
    assert isinstance(out, np.ndarray)
    assert events, 'host_sync itself must be counted'
    assert all(e.sanctioned for e in events)


def test_interceptor_restores_patches():
    before = type(jnp.zeros(())).__float__
    with jaxpr_audit.intercept_host_transfers([]):
        assert type(jnp.zeros(())).__float__ is not before
    assert type(jnp.zeros(())).__float__ is before


def test_host_scalars_unwraps():
    out = host_lib.host_scalars({'loss': jnp.float32(1.5), 'n': 3})
    assert out == {'loss': 1.5, 'n': 3}
    assert isinstance(out['loss'], float)


# ------------------------------------------------------------ jaxpr walk
def test_walk_jaxpr_finds_promotions_and_callbacks():
    import jax

    def f(a):
        b = a.astype(jnp.float32)           # bf16 -> f32 widening
        return jax.pure_callback(
            lambda v: np.asarray(v), jax.ShapeDtypeStruct((2,),
                                                          np.float32), b)

    jx = jax.make_jaxpr(f)(jnp.ones(2, jnp.bfloat16))
    callbacks, promotions = jaxpr_audit.walk_jaxpr(jx)
    assert 'pure_callback' in callbacks
    assert any('float32' in p for p in promotions)


def test_check_donation_runs():
    import jax
    fn = jax.jit(lambda a, b: a + b, donate_argnums=(0,))
    warns = jaxpr_audit.check_donation(fn, jnp.ones(3), jnp.ones(3))
    assert isinstance(warns, list)   # content is backend-dependent


# ----------------------------------------------------------- engine gates
def _assert_hot_loop_clean(report):
    assert not report.unsanctioned_transfers, '\n' + report.format()
    assert not any(report.recompiles.values()), '\n' + report.format()
    assert not report.callback_prims, '\n' + report.format()
    assert not report.f64_promotions, '\n' + report.format()


def test_slot_engine_decode_and_chunked_prefill_audit():
    """The decode step and the chunked-prefill step: zero d2h
    transfers outside host_sync, and exactly one compile per static
    key — the caches do not grow across repeated same-shaped calls."""
    report = jaxpr_audit.audit_engine('slot', chunked=True)
    _assert_hot_loop_clean(report)
    # The sanctioned lagged readback itself must still be present
    # (the engine DOES read tokens back — through host_sync).
    assert report.transfers, 'expected sanctioned pipeline readbacks'
    # The audit exercised the chunked-prefill path and the recompile
    # key was observed.
    assert 'chunk_prefill' in report.compile_counts
    assert any('kv_bucket' in k for k in report.static_keys)


@pytest.mark.slow
def test_slot_engine_monolithic_audit():
    _assert_hot_loop_clean(
        jaxpr_audit.audit_engine('slot', chunked=False))


def test_paged_engine_audit():
    report = jaxpr_audit.audit_engine('paged', chunked=True)
    _assert_hot_loop_clean(report)
    assert report.transfers, 'expected sanctioned pipeline readbacks'


def test_slot_engine_speculative_audit():
    """The speculative propose→verify→commit steady state: zero d2h
    transfers outside the sanctioned per-round commit sync, and the
    verify jit cache bounded by the (k, sample, kv_bucket) key set —
    per-slot variable acceptance rides masked commits, never fresh
    shapes."""
    report = jaxpr_audit.audit_engine('slot', chunked=True,
                                      speculate_k=4)
    _assert_hot_loop_clean(report)
    assert report.transfers, 'expected sanctioned commit readbacks'
    assert 'spec_verify' in report.compile_counts
    before, after = report.compile_counts['spec_verify']
    assert before >= 1 and after == before
    assert any('kv_bucket' in k and k.get('k') == 4
               for k in report.static_keys)


def test_paged_engine_speculative_audit():
    report = jaxpr_audit.audit_engine('paged', chunked=True,
                                      speculate_k=4)
    _assert_hot_loop_clean(report)
    assert 'spec_verify' in report.compile_counts
    before, after = report.compile_counts['spec_verify']
    assert before >= 1 and after == before


def test_llama_forward_jaxpr_audit():
    report = jaxpr_audit.audit_llama_forward()
    assert not report.callback_prims
    assert not report.f64_promotions


def test_telemetry_parity_audit():
    """Telemetry must be free at the device boundary: a
    telemetry-enabled engine run performs zero unsanctioned d2h
    transfers, zero steady-state recompiles, and its jit cache is
    byte-for-byte the same SIZE as a telemetry-off run's (profiling is
    host-side around dispatches, never inside programs)."""
    report = jaxpr_audit.audit_telemetry_parity('slot')
    assert report.ok(), report.format()
    off, on = report.compile_counts['jit cache size (off vs on)']
    assert off == on and on > 0
    # The telemetry-on run still performs its sanctioned readbacks.
    assert report.transfers
    assert not report.unsanctioned_transfers


@pytest.mark.slow
def test_telemetry_parity_audit_paged():
    report = jaxpr_audit.audit_telemetry_parity('paged')
    assert report.ok(), report.format()


def test_kv_int8_paged_audit():
    """int8 KV over bf16 weights (the decoupled kv_cache_dtype path):
    quantize-on-write in the chunked-prefill and decode scans plus the
    fused-dequant reads add zero unsanctioned d2h transfers and zero
    steady-state recompiles — the jit key set stays what the bf16
    engine observes."""
    report = jaxpr_audit.audit_engine('paged', chunked=True,
                                      kv_cache_dtype='int8')
    _assert_hot_loop_clean(report)
    assert report.transfers, 'expected sanctioned pipeline readbacks'


@pytest.mark.slow
def test_kv_int8_slot_audit():
    report = jaxpr_audit.audit_engine('slot', chunked=True,
                                      kv_cache_dtype='int8')
    _assert_hot_loop_clean(report)
    assert any('kv_bucket' in k for k in report.static_keys)


def test_kv_int8_presets_registered():
    """The kv-int8 presets gate CI through the default preset list."""
    assert 'kv-int8' in jaxpr_audit.PRESETS
    assert 'kv-int8-slot' in jaxpr_audit.PRESETS


# ----------------------------------------------------- prefix digest
def test_digest_export_audit():
    """hot_prefix_digest() on the probe path: a scrape after every
    wave (hotter than the real ~1 Hz probe cadence) adds zero
    unsanctioned d2h and zero steady-state recompiles — the digest is
    built from the host-side heat tracker only — and every scrape
    returns the chains the waves registered."""
    report = jaxpr_audit.audit_digest_export()
    _assert_hot_loop_clean(report)
    assert report.ok(), report.format()
    assert report.compile_counts['scrapes returning entries'] == (2, 2)


def test_digest_preset_registered():
    """The digest preset gates CI through the default preset list."""
    assert 'digest' in jaxpr_audit.PRESETS
    assert 'digest' in jaxpr_audit.DEFAULT_PRESETS


# ------------------------------------------------------------ sharded (tp)
def _need_devices(n: int) -> None:
    import jax
    if jax.device_count() < n:
        pytest.skip(
            f'tp audit needs {n} devices, have {jax.device_count()}: '
            'run under XLA_FLAGS=--xla_force_host_platform_device_'
            f'count={n} (tests/conftest.py forces 8 — a single-device '
            'run means the forced count was overridden)')


def test_paged_tp_audit():
    """The sharded serving path (tp=2 CPU mesh): zero steady-state
    recompiles, zero unsanctioned d2h, and the collective census shows
    ONLY the known decode set — per-layer all-reduces plus the
    tp-sharded argmax's tiny top-candidate all-gathers; the pool merge
    (shard_map per-shard scatters) must be collective-FREE. A pool- or
    ring-shaped gather appearing here means an output sharding stopped
    matching the next step's input sharding."""
    _need_devices(2)
    report = jaxpr_audit.audit_engine('paged', chunked=True, mesh_tp=2)
    _assert_hot_loop_clean(report)
    assert report.collectives, 'tp preset must census collectives'
    assert report.collective_violations() == [], report.format()
    assert report.collectives.get('merge') == {}, \
        'the shard_map pool merge must be collective-free'
    assert report.collectives['decode'].get('all-to-all', 0) == 0


@pytest.mark.slow
def test_paged_tp_int8_audit():
    _need_devices(2)
    report = jaxpr_audit.audit_engine('paged', chunked=True, mesh_tp=2,
                                      kv_cache_dtype='int8')
    _assert_hot_loop_clean(report)
    assert report.collective_violations() == [], report.format()
    assert report.collectives.get('merge') == {}


def test_paged_tp_presets_registered():
    """The tp presets ride the default list AND declare their device
    need so single-device drivers (graftcheck CLI) re-exec instead of
    silently skipping."""
    assert 'paged-tp' in jaxpr_audit.PRESETS
    assert 'paged-tp-int8' in jaxpr_audit.PRESETS
    assert 'paged-tp' in jaxpr_audit.DEFAULT_PRESETS
    assert 'paged-tp-int8' in jaxpr_audit.DEFAULT_PRESETS
    assert jaxpr_audit.MULTI_DEVICE_PRESETS['paged-tp'] == 2


@pytest.mark.slow
def test_paged_gang_audit():
    """The gang-shaped mesh (tp=2 x dp=2 over 4 devices — standing in
    for a 2-process gang x 2 chips/process; the compiled HLO is
    identical whether the dp axis crosses process boundaries):
    steady-state transfer/recompile gates hold, the decode census
    shows only the known set, and the dp>1 merge's in-body ring-row
    all-gathers stay within their explicit budget — no all-to-all /
    collective-permute anywhere across the process axis."""
    _need_devices(4)
    report = jaxpr_audit.PRESETS['paged-gang']()
    _assert_hot_loop_clean(report)
    assert report.collectives, 'gang preset must census collectives'
    assert report.collective_violations() == [], report.format()
    assert report.collectives['decode'].get('all-to-all', 0) == 0
    assert report.collectives['decode'].get('collective-permute',
                                            0) == 0
    # The dp merge all-gathers ring-rows INSIDE its shard_map body by
    # design (dp pool replicas must not diverge) — bounded, budgeted.
    assert 0 < report.collectives['merge'].get('all-gather', 0) <= \
        report.allowed_all_gathers_by_label['merge']


def test_paged_gang_preset_registered():
    assert 'paged-gang' in jaxpr_audit.PRESETS
    assert 'paged-gang' in jaxpr_audit.DEFAULT_PRESETS
    assert jaxpr_audit.MULTI_DEVICE_PRESETS['paged-gang'] == 4


# ------------------------------------------------- int4 + multi-step
def test_int4_paged_audit():
    """int4 fused-dequant weights: the packed-nibble unpack inside
    qeinsum adds zero unsanctioned d2h and zero steady-state jit-cache
    growth on the paged hot loop (the `int4` default preset)."""
    report = jaxpr_audit.audit_engine('paged', chunked=True,
                                      quantize='int4')
    _assert_hot_loop_clean(report)
    assert report.transfers, 'expected sanctioned pipeline readbacks'


@pytest.mark.slow
def test_int4_slot_audit():
    report = jaxpr_audit.audit_engine('slot', chunked=True,
                                      quantize='int4')
    _assert_hot_loop_clean(report)
    assert any('kv_bucket' in k for k in report.static_keys)


def test_multistep_audit():
    """decode_steps_per_call pinned at k: a lockstep budget-bound
    round costs exactly ONE decode dispatch per k tokens, every
    dispatch at static horizon k, zero recompiles / unsanctioned
    d2h — ok() fails on any of it (the dispatch counts ride
    compile_counts as (expected, actual) pairs)."""
    report = jaxpr_audit.audit_multistep(k=4)
    _assert_hot_loop_clean(report)
    assert report.ok(), '\n' + report.format()
    assert all(key['horizon'] == 4 for key in report.static_keys)
    expected, actual = report.compile_counts[
        'decode dispatches (ONE per 4 tokens)']
    assert expected == actual == 4        # 2 rounds x 2 dispatches


@pytest.mark.slow
def test_int4_multistep_audit():
    report = jaxpr_audit.audit_multistep(k=4, quantize='int4')
    _assert_hot_loop_clean(report)
    assert report.ok(), '\n' + report.format()


def test_int4_multistep_presets_registered():
    for name in ('int4', 'multistep', 'int4-multistep'):
        assert name in jaxpr_audit.PRESETS, name
        assert name in jaxpr_audit.DEFAULT_PRESETS, name
    assert 'int4-slot' in jaxpr_audit.PRESETS

# ------------------------------------------------------ KV round two
def test_kv_int4_paged_audit():
    """int4 KV codes (packed nibble rows + absmax/7 scales):
    quantize-on-write plus the in-kernel fused-dequant reads add zero
    unsanctioned d2h and zero steady-state jit-cache growth — halving
    KV bytes must not buy a single host round-trip."""
    report = jaxpr_audit.audit_engine('paged', chunked=True,
                                      kv_cache_dtype='int4')
    _assert_hot_loop_clean(report)
    assert report.transfers, 'expected sanctioned pipeline readbacks'


@pytest.mark.slow
def test_kv_int4_slot_audit():
    report = jaxpr_audit.audit_engine('slot', chunked=True,
                                      kv_cache_dtype='int4')
    _assert_hot_loop_clean(report)
    assert any('kv_bucket' in k for k in report.static_keys)


def test_fused_attn_audit():
    """Cross-layer fused decode attention (decode_impl='cross_layer'):
    folding the ring+current-token merge into the kernel's final grid
    step must be free at the dispatch boundary — same transfer and
    recompile gates as the stock paged preset."""
    report = jaxpr_audit.audit_engine('paged', chunked=True,
                                      decode_impl='cross_layer')
    _assert_hot_loop_clean(report)
    assert report.transfers, 'expected sanctioned pipeline readbacks'


def test_spec_multistep_audit():
    """In-scan speculative verify: speculate_k x decode_steps_per_call
    compose into ONE dispatch per `steps` verify rounds — pinned
    against a single-round reference engine's dispatch count (greedy
    byte-identity makes the round counts comparable), with zero
    single-round fallbacks and every fused jit key at rounds=steps."""
    report = jaxpr_audit.audit_spec_multistep(k=4, steps=3)
    _assert_hot_loop_clean(report)
    assert report.ok(), '\n' + report.format()
    key = next(k for k in report.compile_counts
               if k.startswith('fused dispatches'))
    expected, actual = report.compile_counts[key]
    assert expected == actual > 0
    assert report.compile_counts[
        'single-round fallback dispatches'] == (0, 0)
    assert all(k['rounds'] == 3 for k in report.static_keys)


def test_kv_round2_presets_registered():
    for name in ('kv-int4', 'kv-int4-slot', 'fused-attn',
                 'spec-multistep'):
        assert name in jaxpr_audit.PRESETS, name
        assert name in jaxpr_audit.DEFAULT_PRESETS, name
