"""Optimizer + failover tests (reference pattern:
``tests/test_optimizer_dryruns.py``) — all offline against the checked-in
catalog and the local provisioner's failure injector."""
import time

import pytest

import skypilot_tpu as sky
from skypilot_tpu import core, exceptions, execution, optimizer
from skypilot_tpu.dag import Dag
from skypilot_tpu.provision.local import instance as local_instance
from skypilot_tpu.task import Task

pytestmark = pytest.mark.usefixtures('tmp_state_dir', 'fast_agent')


@pytest.fixture()
def fast_agent(monkeypatch):
    monkeypatch.setenv('SKYTPU_AGENT_TICK', '0.1')
    monkeypatch.setenv('SKYTPU_AGENT_READY_TIMEOUT', '30')


@pytest.fixture(autouse=True)
def clear_injector():
    yield
    local_instance.set_failure_injector(None)


def _single_task_dag(resources, name='t', **task_kwargs):
    task = Task(name=name, run='echo hi', **task_kwargs)
    if isinstance(resources, list):
        task.set_resources(resources)
    else:
        task.set_resources(resources)
    dag = Dag()
    dag.add(task)
    return dag, task


def test_optimize_picks_cheapest_tpu_region():
    dag, task = _single_task_dag(sky.Resources(accelerators='tpu-v5e-8'))
    optimizer.optimize(dag)
    best = task.best_resources
    assert best.cloud == 'gcp'
    assert best.instance_type is not None
    assert best.region is not None


def test_optimize_tpu_vs_gpu_cost_comparison():
    """any_of candidates: the optimizer must pick the cheaper one."""
    tpu = sky.Resources(accelerators='tpu-v5e-8')
    gpu = sky.Resources(cloud='gcp', accelerators={'A100': 8})
    dag, task = _single_task_dag([tpu, gpu])
    optimizer.optimize(dag)
    from skypilot_tpu import clouds as clouds_lib
    gcp = clouds_lib.from_name('gcp')
    chosen = task.best_resources
    chosen_cost = gcp.instance_type_to_hourly_cost(chosen, False)
    # Compare against both candidates' cheapest concrete prices.
    costs = []
    for cand in (tpu, gpu):
        feas, _ = gcp.get_feasible_launchable_resources(cand)
        costs.extend(gcp.instance_type_to_hourly_cost(f, False)
                     for f in feas)
    assert chosen_cost == pytest.approx(min(costs))


def test_ordered_resources_respect_preference():
    expensive = sky.Resources(accelerators='tpu-v5p-8')
    cheap = sky.Resources(accelerators='tpu-v5e-8')
    dag, task = _single_task_dag([expensive, cheap])
    task._resources_ordered = True  # pylint: disable=protected-access
    optimizer.optimize(dag)
    assert task.best_resources.accelerators == {'tpu-v5p-8': 1}


def test_spot_is_cheaper_than_ondemand():
    dag_od, t_od = _single_task_dag(
        sky.Resources(accelerators='tpu-v5e-8'))
    dag_spot, t_spot = _single_task_dag(
        sky.Resources(accelerators='tpu-v5e-8', use_spot=True))
    optimizer.optimize(dag_od)
    optimizer.optimize(dag_spot)
    from skypilot_tpu import clouds as clouds_lib
    gcp = clouds_lib.from_name('gcp')
    od = gcp.instance_type_to_hourly_cost(t_od.best_resources, False)
    spot = gcp.instance_type_to_hourly_cost(t_spot.best_resources, True)
    assert spot < od


def test_unknown_accelerator_raises():
    with pytest.raises(exceptions.InvalidResourcesError):
        sky.Resources(accelerators='tpu-v9-8')


def test_no_feasible_resources_raises():
    dag, _ = _single_task_dag(
        sky.Resources(accelerators='tpu-v5e-8', zone='mars-central1-a'))
    with pytest.raises(exceptions.ResourcesUnavailableError):
        optimizer.optimize(dag)


def test_blocked_resources_exclude_zone_and_region():
    res = sky.Resources(accelerators='tpu-v5e-8')
    dag, task = _single_task_dag(res)
    optimizer.optimize(dag)
    first_region = task.best_resources.region
    blocked = [sky.Resources(cloud='gcp', region=first_region)]
    dag2, task2 = _single_task_dag(res)
    optimizer.optimize(dag2, blocked_resources=blocked)
    assert task2.best_resources.region != first_region


def test_chain_dp_assigns_all_tasks():
    with Dag() as dag:
        a = Task(name='a', run='echo a')
        a.set_resources(sky.Resources(accelerators='tpu-v5e-8'))
        b = Task(name='b', run='echo b')
        b.set_resources(sky.Resources(cpus='4+'))
        a >> b
    optimizer.optimize(dag)
    assert a.best_resources.instance_type is not None
    assert b.best_resources.instance_type is not None


def test_zone_failover_on_injected_stockout():
    """Zone local-a stocked out -> the retry loop lands in local-b."""
    failed_zones = []

    def injector(cluster_name, region, zone, config):
        del cluster_name, region, config
        if zone == 'local-a':
            failed_zones.append(zone)
            raise exceptions.InsufficientCapacityError(
                f'simulated stockout in {zone}')

    local_instance.set_failure_injector(injector)
    task = Task(name='fo', run='echo failover-ok')
    task.set_resources(sky.Resources(cloud='local'))
    job_id, handle = execution.launch(task, cluster_name='opt-failover')
    try:
        assert failed_zones == ['local-a']
        assert handle.cluster_info.zone == 'local-b'
        deadline = time.time() + 30
        while time.time() < deadline:
            if core.job_status('opt-failover', job_id) == 'SUCCEEDED':
                break
            time.sleep(0.15)
        assert core.job_status('opt-failover', job_id) == 'SUCCEEDED'
    finally:
        core.down('opt-failover')


def test_all_zones_stocked_out_raises_unavailable():
    def injector(cluster_name, region, zone, config):
        del cluster_name, region, config
        raise exceptions.InsufficientCapacityError(
            f'simulated stockout in {zone}')

    local_instance.set_failure_injector(injector)
    task = Task(name='fo2', run='echo hi')
    task.set_resources(sky.Resources(cloud='local'))
    with pytest.raises(exceptions.ResourcesUnavailableError):
        execution.launch(task, cluster_name='opt-stockout')


def test_queued_resource_timeout_is_failover_signal():
    """QueuedResourceTimeout (TPU-specific) behaves like a stockout."""
    calls = []

    def injector(cluster_name, region, zone, config):
        del cluster_name, region, config
        calls.append(zone)
        if len(calls) == 1:
            raise exceptions.QueuedResourceTimeoutError(
                'queued too long in ' + zone)

    local_instance.set_failure_injector(injector)
    task = Task(name='q', run='echo ok')
    task.set_resources(sky.Resources(cloud='local'))
    _, handle = execution.launch(task, cluster_name='opt-queued')
    try:
        assert len(calls) == 2
        assert handle.cluster_info.zone == 'local-b'
    finally:
        core.down('opt-queued')


def test_dryrun_provisions_nothing():
    task = Task(name='dry', run='echo hi')
    task.set_resources(sky.Resources(cloud='local'))
    job_id, handle = execution.launch(task, cluster_name='opt-dry',
                                      dryrun=True)
    assert job_id is None and handle is None
    assert core.status() == []


class TestIlpGeneralDag:
    """General-DAG placement via ILP (reference ``_optimize_by_ilp``,
    ``sky/optimizer.py:472``; fuzzed against brute force like the
    reference's ``test_optimizer_random_dag.py``)."""

    @staticmethod
    def _gpu_task(name, outputs_gb=0.0):
        t = Task(name=name, run='echo hi')
        t.set_resources(sky.Resources(cloud='gcp',
                                      accelerators={'A100': 1}))
        t.estimated_outputs_gb = outputs_gb
        return t

    def test_diamond_dag_assigns_all_tasks(self):
        dag = Dag()
        a = self._gpu_task('a', outputs_gb=100.0)
        b = self._gpu_task('b', outputs_gb=50.0)
        c = self._gpu_task('c', outputs_gb=50.0)
        d = self._gpu_task('d')
        for t in (a, b, c, d):
            dag.add(t)
        dag.add_edge(a, b)
        dag.add_edge(a, c)
        dag.add_edge(b, d)
        dag.add_edge(c, d)
        assert not dag.is_chain()
        optimizer.optimize(dag)
        for t in (a, b, c, d):
            assert t.best_resources is not None
            assert t.best_resources.region is not None

    def test_ilp_matches_brute_force_on_random_dags(self):
        import itertools
        import random

        from skypilot_tpu.optimizer import (_egress_cost, _estimate_cost,
                                            OptimizeTarget,
                                            fill_in_launchable_resources)
        rng = random.Random(7)
        for trial in range(4):
            n = rng.randint(3, 5)
            dag = Dag()
            tasks = [self._gpu_task(f't{i}', outputs_gb=rng.choice(
                [0.0, 200.0, 1000.0])) for i in range(n)]
            for t in tasks:
                dag.add(t)
            for i in range(n):
                for j in range(i + 1, n):
                    if rng.random() < 0.5:
                        dag.add_edge(tasks[i], tasks[j])
            if dag.is_chain() or not dag.edges():
                continue
            optimizer.optimize(dag)
            ilp_res = {t: t.best_resources for t in tasks}

            # Brute force over a TRUNCATED candidate set (keep it tiny),
            # re-optimizing with the same truncation for comparability.
            per_task = {t: fill_in_launchable_resources(t)[:3]
                        for t in tasks}

            def total(assign):
                cost = sum(
                    _estimate_cost(t, dict(per_task[t])[assign[t]],
                                   OptimizeTarget.COST)
                    for t in tasks)
                for (u, v) in dag.edges():
                    cost += _egress_cost(assign[u], assign[v],
                                         u.estimated_outputs_gb)
                return cost

            best = None
            for combo in itertools.product(
                    *[[r for r, _ in per_task[t]] for t in tasks]):
                assign = dict(zip(tasks, combo))
                c = total(assign)
                if best is None or c < best:
                    best = c
            from skypilot_tpu.optimizer import _optimize_by_ilp
            _optimize_by_ilp(dag, tasks, per_task, OptimizeTarget.COST)
            ilp_cost = total({t: t.best_resources for t in tasks})
            assert abs(ilp_cost - best) < 1e-6, (
                f'trial {trial}: ilp {ilp_cost} vs brute {best}')
            del ilp_res
