"""GCP TPU provisioner tests against a scripted fake transport.

Hermetic counterpart of the reference's googleapiclient-mocked tests for
``sky/provision/gcp/instance_utils.py:1191-1607``: the fake cloud keeps
node/queued-resource state in memory and can inject stockouts, quota
errors, queued-forever, and preemption per zone.
"""
import re

import pytest

from skypilot_tpu import exceptions
from skypilot_tpu.provision import common
from skypilot_tpu.provision.gcp import instance as gcp_instance
from skypilot_tpu.provision.gcp import tpu_client as tc

pytestmark = pytest.mark.usefixtures('tmp_state_dir', 'fast_gcp')


@pytest.fixture()
def fast_gcp(monkeypatch):
    monkeypatch.setenv('SKYTPU_GCP_POLL', '0.01')
    monkeypatch.setenv('SKYTPU_GCP_QR_TIMEOUT', '0.3')


class FakeGcp:
    """In-memory TPU + Compute API with per-zone behavior injection."""

    def __init__(self):
        self.nodes = {}              # (zone, id) -> node dict
        self.qrs = {}                # (zone, id) -> qr dict
        self.instances = {}          # (zone, name) -> gce dict
        self.fail_create = {}        # zone -> (status, payload)
        self.qr_script = {}          # zone -> list of states to emit
        self.requests = []

    def transport(self, method, url, body):
        self.requests.append((method, url))
        m = re.search(r'/locations/([^/]+)/nodes\?nodeId=([^&]+)', url)
        if m and method == 'POST':
            zone, node_id = m.groups()
            if zone in self.fail_create:
                return self.fail_create[zone]
            self.nodes[(zone, node_id)] = {
                'name': f'projects/p/locations/{zone}/nodes/{node_id}',
                'state': 'READY',
                'acceleratorType': body.get('acceleratorType', 'v5e-8'),
                'labels': body.get('labels', {}),
                'networkEndpoints': [
                    {'ipAddress': f'10.0.{len(self.nodes)}.{i}'}
                    for i in range(2)],
            }
            return 200, {'name': f'operations/op-{node_id}', 'done': True}
        m = re.search(r'/locations/([^/]+)/nodes/([^/:?]+)$', url)
        if m:
            zone, node_id = m.groups()
            node = self.nodes.get((zone, node_id))
            if method == 'GET':
                return (200, node) if node else (404, {})
            if method == 'DELETE':
                if node is None:
                    return 404, {}
                del self.nodes[(zone, node_id)]
                return 200, {'name': 'operations/del', 'done': True}
        m = re.search(r'/locations/([^/]+)/nodes/([^/]+):(stop|start)$', url)
        if m:
            zone, node_id, verb = m.groups()
            node = self.nodes[(zone, node_id)]
            node['state'] = 'STOPPED' if verb == 'stop' else 'READY'
            return 200, {'name': 'operations/sv', 'done': True}
        m = re.search(r'/locations/([^/]+)/nodes$', url)
        if m and method == 'GET':
            zone = m.group(1)
            return 200, {'nodes': [n for (z, _), n in self.nodes.items()
                                   if z == zone]}
        m = re.search(
            r'/locations/([^/]+)/queuedResources\?queuedResourceId=([^&]+)',
            url)
        if m and method == 'POST':
            zone, qr_id = m.groups()
            if zone in self.fail_create:
                return self.fail_create[zone]
            script = list(self.qr_script.get(zone, ['ACTIVE']))
            self.qrs[(zone, qr_id)] = {
                'name': f'projects/p/locations/{zone}/queuedResources/'
                        f'{qr_id}',
                'script': script,
                'body': body,
            }
            return 200, {'name': f'operations/qr-{qr_id}', 'done': True}
        m = re.search(r'/locations/([^/]+)/queuedResources/([^/?]+)', url)
        if m:
            zone, qr_id = m.groups()
            qr = self.qrs.get((zone, qr_id))
            if method == 'GET':
                if qr is None:
                    return 404, {}
                state = (qr['script'].pop(0) if len(qr['script']) > 1
                         else qr['script'][0])
                if state == 'ACTIVE':
                    # QR turning ACTIVE materializes its node.
                    spec = qr['body']['tpu']['nodeSpec'][0]
                    node_id = spec['nodeId']
                    if (zone, node_id) not in self.nodes:
                        node = dict(spec['node'])
                        node.update({
                            'name': f'projects/p/locations/{zone}/nodes/'
                                    f'{node_id}',
                            'state': 'READY',
                            'acceleratorType': node.get(
                                'acceleratorType', 'v5e-8'),
                            'networkEndpoints': [
                                {'ipAddress': f'10.1.0.{i}'}
                                for i in range(2)],
                        })
                        self.nodes[(zone, node_id)] = node
                return 200, {'state': {'state': state}}
            if method == 'DELETE':
                if qr is None:
                    return 404, {}
                del self.qrs[(zone, qr_id)]
                return 200, {'name': 'operations/qrdel', 'done': True}
        m = re.search(r'/locations/([^/]+)/queuedResources$', url)
        if m and method == 'GET':
            zone = m.group(1)
            return 200, {'queuedResources': [
                q for (z, _), q in self.qrs.items() if z == zone]}
        if '/zones/' in url and url.endswith('/instances') and \
                method == 'GET':
            zone = url.split('/zones/')[1].split('/')[0]
            return 200, {'items': [i for (z, _), i in
                                   self.instances.items() if z == zone]}
        if re.search(r'operations/', url):
            return 200, {'name': url.rsplit('/', 1)[-1], 'done': True}
        raise AssertionError(f'unhandled fake request: {method} {url}')


@pytest.fixture()
def fake():
    gcp = FakeGcp()
    tc.set_transport_factory(lambda: gcp.transport)
    yield gcp
    tc.set_transport_factory(None)


def _config(use_spot=False, count=1):
    return common.ProvisionConfig(
        provider_config={'project_id': 'proj'},
        node_config={
            'kind': 'tpu_vm',
            'accelerator': 'tpu-v5e-16',
            'accelerator_type': 'v5litepod-16',
            'runtime_version': 'tpu-ubuntu2204-base',
            'hosts_per_node': 2,
            'chips_per_host': 8,
            'use_spot': use_spot,
            'labels': {},
        },
        count=count)


class TestOnDemand:

    def test_create_query_info_terminate(self, fake):
        record = gcp_instance.run_instances('us-central1', 'us-central1-a',
                                            'c1', _config())
        assert record.created_instance_ids == ['c1-0']
        assert record.head_instance_id == 'c1-0'

        statuses = gcp_instance.query_instances('us-central1', 'c1')
        assert statuses == {'c1-0': common.STATUS_RUNNING}

        info = gcp_instance.get_cluster_info('us-central1', 'c1')
        assert info.num_hosts == 2                    # 2 workers per slice
        assert [h.rank for h in info.hosts] == [0, 1]
        assert info.chips_per_host == 8               # v5litepod
        assert info.accelerator == 'v5litepod-16'

        gcp_instance.terminate_instances('us-central1', 'c1')
        assert gcp_instance.query_instances('us-central1', 'c1') == {}

    def test_multislice_creates_n_nodes(self, fake):
        record = gcp_instance.run_instances('us-central1', 'us-central1-a',
                                            'ms', _config(count=2))
        assert record.created_instance_ids == ['ms-0', 'ms-1']
        info = gcp_instance.get_cluster_info('us-central1', 'ms')
        assert info.num_hosts == 4                    # 2 slices x 2 workers

    def test_stockout_maps_to_zone_scoped_error(self, fake):
        fake.fail_create['us-central1-a'] = (
            409, {'error': {'message':
                            'There is no more capacity in the zone'}})
        with pytest.raises(exceptions.InsufficientCapacityError) as ei:
            gcp_instance.run_instances('us-central1', 'us-central1-a',
                                       'so', _config())
        assert ei.value.blocklist_scope == 'zone'

    def test_quota_maps_to_region_scoped_error(self, fake):
        fake.fail_create['us-central1-a'] = (
            429, {'error': {'message': 'Quota exceeded for TPU v5e cores'}})
        with pytest.raises(exceptions.QuotaExceededError) as ei:
            gcp_instance.run_instances('us-central1', 'us-central1-a',
                                       'qt', _config())
        assert ei.value.blocklist_scope == 'region'

    def test_partial_failure_cleans_up(self, fake):
        """Gang semantics: node 0 creates, node 1 stockouts -> node 0 is
        deleted before the error propagates."""
        real = fake.transport

        def flaky(method, url, body):
            if 'nodes?nodeId=pf-1' in url:
                return 409, {'error': {'message': 'out of capacity'}}
            return real(method, url, body)
        tc.set_transport_factory(lambda: flaky)
        with pytest.raises(exceptions.InsufficientCapacityError):
            gcp_instance.run_instances('us-central1', 'us-central1-a',
                                       'pf', _config(count=2))
        assert ('us-central1-a', 'pf-0') not in fake.nodes

    def test_dead_node_is_recreated_on_relaunch(self, fake):
        gcp_instance.run_instances('us-central1', 'us-central1-a', 'dn',
                                   _config())
        fake.nodes[('us-central1-a', 'dn-0')]['state'] = 'PREEMPTED'
        record = gcp_instance.run_instances('us-central1', 'us-central1-a',
                                            'dn', _config())
        assert record.created_instance_ids == ['dn-0']
        statuses = gcp_instance.query_instances('us-central1', 'dn')
        assert statuses == {'dn-0': common.STATUS_RUNNING}


class TestQueuedResources:

    def test_spot_goes_active_via_qr(self, fake):
        fake.qr_script['us-central1-a'] = [
            'ACCEPTED', 'PROVISIONING', 'ACTIVE']
        record = gcp_instance.run_instances('us-central1', 'us-central1-a',
                                            'sp', _config(use_spot=True))
        assert record.created_instance_ids == ['sp-0']
        statuses = gcp_instance.query_instances('us-central1', 'sp')
        assert statuses == {'sp-0': common.STATUS_RUNNING}
        # The QR request carried the spot flag.
        qr_posts = [u for m, u in fake.requests
                    if m == 'POST' and 'queuedResources?' in u]
        assert len(qr_posts) == 1

    def test_qr_failed_state_fails_over(self, fake):
        fake.qr_script['us-central1-a'] = ['ACCEPTED', 'FAILED', 'FAILED']
        with pytest.raises(exceptions.InsufficientCapacityError) as ei:
            gcp_instance.run_instances('us-central1', 'us-central1-a',
                                       'qf', _config(use_spot=True))
        assert ei.value.blocklist_scope == 'zone'
        assert not fake.qrs                  # QR deleted on failure

    def test_queued_too_long_times_out_and_cleans_up(self, fake):
        fake.qr_script['us-central1-a'] = ['ACCEPTED', 'ACCEPTED']
        with pytest.raises(exceptions.QueuedResourceTimeoutError) as ei:
            gcp_instance.run_instances('us-central1', 'us-central1-a',
                                       'ql', _config(use_spot=True))
        assert ei.value.blocklist_scope == 'zone'
        assert not fake.qrs                  # abandoned QR deleted

    def test_preempted_node_reported_terminated(self, fake):
        gcp_instance.run_instances('us-central1', 'us-central1-a', 'pr',
                                   _config())
        fake.nodes[('us-central1-a', 'pr-0')]['state'] = 'PREEMPTED'
        statuses = gcp_instance.query_instances('us-central1', 'pr')
        assert statuses == {'pr-0': common.STATUS_TERMINATED}

    def test_terminate_deletes_pending_qrs_first(self, fake):
        fake.qr_script['us-central1-a'] = ['ACCEPTED', 'PROVISIONING',
                                           'ACTIVE']
        gcp_instance.run_instances('us-central1', 'us-central1-a', 'td',
                                   _config(use_spot=True))
        gcp_instance.terminate_instances('us-central1', 'td')
        assert not fake.qrs
        assert not [k for k in fake.nodes if k[1].startswith('td-')]


class TestLifecycle:

    def test_stop_and_query(self, fake):
        gcp_instance.run_instances('us-central1', 'us-central1-a', 'st',
                                   _config())
        gcp_instance.stop_instances('us-central1', 'st')
        statuses = gcp_instance.query_instances('us-central1', 'st')
        assert statuses == {'st-0': common.STATUS_STOPPED}

    def test_resume_stopped_node(self, fake):
        gcp_instance.run_instances('us-central1', 'us-central1-a', 're',
                                   _config())
        gcp_instance.stop_instances('us-central1', 're')
        record = gcp_instance.run_instances('us-central1', 'us-central1-a',
                                            're', _config())
        assert record.resumed_instance_ids == ['re-0']
        assert record.created_instance_ids == []
        statuses = gcp_instance.query_instances('us-central1', 're')
        assert statuses == {'re-0': common.STATUS_RUNNING}

    def test_wait_instances_reaches_running(self, fake):
        gcp_instance.run_instances('us-central1', 'us-central1-a', 'wi',
                                   _config())
        gcp_instance.wait_instances('us-central1', 'wi',
                                    common.STATUS_RUNNING, timeout=5)

    def test_ops_on_unknown_cluster_are_safe(self, fake):
        assert gcp_instance.query_instances('r', 'nope') == {}
        gcp_instance.terminate_instances('r', 'nope')
        with pytest.raises(exceptions.ClusterDoesNotExist):
            gcp_instance.get_cluster_info('r', 'nope')
