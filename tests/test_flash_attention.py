"""Flash-attention kernel vs XLA reference, interpret mode on CPU.

The same tests run compiled on a real TPU when one is the default backend
(bench/driver environment); here interpret=True exercises kernel logic.
"""
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu.ops.attention import reference_attention
from skypilot_tpu.ops.flash_attention import flash_attention

# Compile-heavy (jit of full models): slow tier — the fast sweep is
# the orchestration layer (SURVEY §4 offline tier analog).
pytestmark = pytest.mark.slow

_INTERPRET = jax.default_backend() != 'tpu'


def _rand_qkv(key, b, sq, skv, hq, hkv, d, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, sq, hq, d), dtype)
    k = jax.random.normal(kk, (b, skv, hkv, d), dtype)
    v = jax.random.normal(kv, (b, skv, hkv, d), dtype)
    return q, k, v


@pytest.mark.parametrize('causal', [True, False])
def test_forward_matches_reference(causal):
    q, k, v = _rand_qkv(jax.random.PRNGKey(0), b=2, sq=256, skv=256,
                        hq=4, hkv=4, d=128)
    out = flash_attention(q, k, v, causal=causal, interpret=_INTERPRET)
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-3, atol=2e-3)


def test_forward_gqa():
    q, k, v = _rand_qkv(jax.random.PRNGKey(1), b=1, sq=256, skv=256,
                        hq=8, hkv=2, d=128)
    out = flash_attention(q, k, v, causal=True, interpret=_INTERPRET)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-3, atol=2e-3)


def test_forward_multiblock():
    """seq > block size: exercises the online-softmax accumulation."""
    q, k, v = _rand_qkv(jax.random.PRNGKey(2), b=1, sq=512, skv=512,
                        hq=2, hkv=2, d=128)
    out = flash_attention(q, k, v, causal=True, block_q=128, block_k=128,
                          interpret=_INTERPRET)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-3, atol=2e-3)


@pytest.mark.parametrize('hq,hkv', [(2, 2), (4, 2)])
def test_gradients_match_reference(hq, hkv):
    q, k, v = _rand_qkv(jax.random.PRNGKey(3), b=1, sq=256, skv=256,
                        hq=hq, hkv=hkv, d=128)

    def loss_flash(q, k, v):
        return flash_attention(q, k, v, causal=True,
                               interpret=_INTERPRET).sum()

    def loss_ref(q, k, v):
        return reference_attention(q, k, v, causal=True).sum()

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_flash, g_ref, 'qkv'):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-3, atol=5e-3,
                                   err_msg=f'd{name} mismatch')


def test_bf16_forward_close():
    q, k, v = _rand_qkv(jax.random.PRNGKey(4), b=1, sq=256, skv=256,
                        hq=2, hkv=2, d=128, dtype=jnp.bfloat16)
    out = flash_attention(q, k, v, causal=True, interpret=_INTERPRET)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out, dtype=np.float32),
                               np.asarray(ref, dtype=np.float32),
                               rtol=3e-2, atol=3e-2)


def test_unaligned_seq_rejected_loudly():
    """sq not 128-divisible must raise a clear ValueError instead of
    reaching Mosaic with unaligned blocks (ADVICE r3)."""
    import pytest
    q = jnp.zeros((1, 300, 4, 64), jnp.float32)
    with pytest.raises(ValueError, match='8-aligned'):
        flash_attention(q, q, q, causal=True, interpret=True)


def test_quant_scale_consistency():
    """Weight codes are computed against the SAME (dtype-rounded) scale
    dequantization multiplies by: per-element error <= scale (ADVICE r3
    quantization.py finding)."""
    import numpy as np
    from skypilot_tpu.models.quantization import _quantize_array, deq
    w = jax.random.normal(jax.random.PRNGKey(0), (64, 32),
                          jnp.bfloat16) * 3.0
    qw = _quantize_array(w, (0,))
    assert qw.scale.dtype == jnp.bfloat16
    err = np.abs(np.asarray(deq(qw), np.float32) -
                 np.asarray(w, np.float32))
    # 0.5*scale from int8 rounding + up to ~0.5*scale from bf16 rounding
    # of the dequantized product (127*scale * 2^-8).
    bound = np.asarray(qw.scale, np.float32) * 1.05 + 1e-6
    assert (err <= np.broadcast_to(bound, err.shape)).all(), err.max()
