"""Multi-LoRA adapter bank: bank math, registry, engine contracts.

The correctness contracts pinned here (ISSUE 20):

- a zero-adapter slot is BYTE-identical to the base model — an engine
  built with a bank produces the same greedy stream as one without;
- a bank-served adapter matches its offline-merged reference
  (``W += scale * A @ B``) token-for-token under greedy decoding at
  fp32, on both engines, including the chunked-prefill path and the
  multi-step / speculative decode compositions;
- constrained decoding (satellite: per-slot vocab masks) only ever
  emits allowed tokens.
"""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu.inference import adapters as adapters_lib
from skypilot_tpu.inference.engine import InferenceEngine
from skypilot_tpu.inference.paged import PagedInferenceEngine
from skypilot_tpu.models import configs, llama, multilora

CFG = configs.TINY


def _rand_tree(cfg, rank, targets, seed, sigma=0.2):
    """Trainer-format adapter tree (lora.split_lora layout: the layer
    axis LEADS every a/b leaf)."""
    rng = np.random.default_rng(seed)
    L = cfg.n_layers
    tree = {}
    for t in targets:
        a_shape, b_shape = multilora.target_shapes(cfg, t, rank)
        tree[t] = {
            'a': rng.normal(0.0, sigma, (L,) + a_shape).astype(np.float32),
            'b': rng.normal(0.0, sigma, (L,) + b_shape).astype(np.float32),
        }
    return tree

# Offline merge folds: W += scale * (A contracted with B) per target,
# stacked over the leading layer axis.
_MERGE_EINSUM = {
    'wq': 'ldr,lrhk->ldhk', 'wk': 'ldr,lrhk->ldhk',
    'wv': 'ldr,lrhk->ldhk', 'wo': 'lhkr,lrd->lhkd',
    'w_gate': 'ldr,lrf->ldf', 'w_up': 'ldr,lrf->ldf',
    'w_down': 'lfr,lrd->lfd',
}


def _merged_params(params, tree, scale):
    """The offline-merged reference: base params with the adapter's
    delta folded into the target weights (same fold lora.merge does)."""
    layers = dict(params['layers'])
    for t, ab in tree.items():
        w = layers[t]
        delta = jnp.einsum(_MERGE_EINSUM[t],
                           jnp.asarray(ab['a'], jnp.float32),
                           jnp.asarray(ab['b'], jnp.float32))
        layers[t] = (w.astype(jnp.float32)
                     + float(scale) * delta).astype(w.dtype)
    out = dict(params)
    out['layers'] = layers
    return out


# --------------------------------------------------------------- units

class TestBankMath:

    def test_default_targets(self):
        assert multilora.default_targets(CFG) == (
            'wq', 'wk', 'wv', 'wo', 'w_gate', 'w_up', 'w_down')
        moe = dataclasses.replace(CFG, n_experts=4)
        assert multilora.default_targets(moe) == ('wq', 'wk', 'wv', 'wo')
        with pytest.raises(ValueError, match='dense FFN'):
            multilora.init_bank(moe, 2, 4, targets=('wq', 'w_gate'))

    def test_init_bank_shapes(self):
        bank = multilora.init_bank(CFG, 3, 4)
        L = CFG.n_layers
        assert bank['scale'].shape == (L, 3)
        assert bank['scale'].dtype == jnp.float32
        a_shape, b_shape = multilora.target_shapes(CFG, 'wq', 4)
        assert bank['wq']['a'].shape == (L, 3) + a_shape
        assert bank['wq']['b'].shape == (L, 3) + b_shape
        assert multilora.bank_slots(bank) == 3
        assert multilora.bank_targets(bank) == \
            multilora.default_targets(CFG)
        flat = jax.tree.leaves(bank)
        assert all(not np.asarray(leaf).any() for leaf in flat)
        with pytest.raises(ValueError):
            multilora.init_bank(CFG, 0, 4)
        with pytest.raises(ValueError, match='unknown'):
            multilora.init_bank(CFG, 2, 4, targets=('w_bogus',))

    def test_adjusted_zero_slot_is_bit_exact(self):
        bank = multilora.init_bank(CFG, 2, 4, dtype=jnp.float32)
        tree = _rand_tree(CFG, 4, ('wq',), seed=0)
        row = multilora.adapter_row_from_tree(
            CFG, tree, 4, 1.0, targets=multilora.bank_targets(bank))
        bank = multilora.set_bank_row(bank, row, jnp.asarray(0, jnp.int32))
        ml = jax.tree.map(lambda v: v[0], bank)      # one layer's slice
        rng = np.random.default_rng(7)
        x = jnp.asarray(rng.normal(size=(2, 3, CFG.dim)), jnp.float32)
        head_dim = CFG.dim // CFG.n_heads
        base = jnp.asarray(
            rng.normal(size=(2, 3, CFG.n_heads, head_dim)), jnp.float32)
        idx = jnp.asarray([-1, 0], jnp.int32)
        out = multilora.adjusted(ml, 'wq', x, base, idx)
        # idx=-1 row: bitwise-identical base (where-select, not +0).
        assert np.array_equal(np.asarray(out[0]), np.asarray(base[0]))
        # idx=0 row: the adapter delta actually lands.
        assert not np.array_equal(np.asarray(out[1]), np.asarray(base[1]))
        # No-bank / no-idx / untracked-target short-circuits return base.
        assert multilora.adjusted(None, 'wq', x, base, idx) is base
        assert multilora.adjusted(ml, 'wq', x, base, None) is base
        ml_no_wq = {k: v for k, v in ml.items() if k != 'wq'}
        assert multilora.adjusted(ml_no_wq, 'wq', x, base, idx) is base

    def test_set_and_clear_bank_row(self):
        bank = multilora.init_bank(CFG, 2, 4)
        targets = multilora.bank_targets(bank)
        tree = _rand_tree(CFG, 4, targets, seed=1)
        row = multilora.adapter_row_from_tree(
            CFG, tree, 4, 2.5, targets=targets)
        bank = multilora.set_bank_row(bank, row, jnp.asarray(1, jnp.int32))
        got_a = np.asarray(bank['wq']['a'][:, 1].astype(jnp.float32))
        want_a = np.asarray(
            jnp.asarray(row['wq']['a']).astype(bank['wq']['a'].dtype)
            .astype(jnp.float32))
        assert np.array_equal(got_a, want_a)
        assert np.allclose(np.asarray(bank['scale'][:, 1]), 2.5)
        # Slot 0 untouched.
        assert not np.asarray(bank['wq']['a'][:, 0]).any()
        bank = multilora.clear_bank_row(bank, jnp.asarray(1, jnp.int32))
        assert all(not np.asarray(leaf).any()
                   for leaf in jax.tree.leaves(bank))

    def test_adapter_row_pads_and_rejects(self):
        targets = multilora.default_targets(CFG)
        # rank 2 adapter into a rank-4 bank: zero-padded factor columns.
        tree = _rand_tree(CFG, 2, ('wq',), seed=2)
        row = multilora.adapter_row_from_tree(
            CFG, tree, 4, 1.0, targets=targets)
        assert row['wq']['a'].shape[-1] == 4
        assert not row['wq']['a'][..., 2:].any()
        assert not row['wq']['b'][:, 2:].any()
        assert row['wq']['a'][..., :2].any()
        # Targets the adapter lacks are zero rows (no-op slots).
        assert not row['w_up']['a'].any()
        assert np.array_equal(
            row['scale'], np.full((CFG.n_layers,), 1.0, np.float32))
        # Rank above the bank rank is a hard error.
        big = _rand_tree(CFG, 8, ('wq',), seed=3)
        with pytest.raises(ValueError, match='exceeds bank rank'):
            multilora.adapter_row_from_tree(CFG, big, 4, 1.0,
                                            targets=targets)
        # Layer-count mismatch is a hard error.
        wrong = {'wq': {'a': tree['wq']['a'][:1], 'b': tree['wq']['b'][:1]}}
        with pytest.raises(ValueError, match='layers'):
            multilora.adapter_row_from_tree(CFG, wrong, 4, 1.0,
                                            targets=targets)

    def test_save_load_roundtrip(self, tmp_path):
        tree = _rand_tree(CFG, 4, ('wq', 'w_down'), seed=4)
        path = str(tmp_path / 'ad.npz')
        multilora.save_adapter(path, CFG, tree, scale=0.75)
        got, scale = multilora.load_adapter(path)
        assert scale == 0.75
        assert set(got) == {'wq', 'w_down'}
        for t in got:
            assert np.array_equal(got[t]['a'], tree[t]['a'])
            assert np.array_equal(got[t]['b'], tree[t]['b'])
        # Default scale is the config's alpha/rank fold scale.
        path2 = str(tmp_path / 'ad2.npz')
        multilora.save_adapter(path2, CFG, tree)
        _, scale2 = multilora.load_adapter(path2)
        assert scale2 == pytest.approx(CFG.lora_alpha / 4)


class TestGrammar:

    def test_json_mode_mask(self):
        mask = adapters_lib.compile_grammar('json', 256, eos_id=200)
        assert mask.shape == (256,) and mask.dtype == np.bool_
        for ch in '{}[]":, \t\n0123456789truefalsenull':
            assert mask[ord(ch)], ch
        assert not mask[0] and not mask[0x7F]
        assert mask[200]          # eos always allowed to terminate

    def test_id_list_and_bool_masks(self):
        mask = adapters_lib.compile_grammar([5, 9], 256, eos_id=7)
        assert sorted(np.nonzero(mask)[0].tolist()) == [5, 7, 9]
        arr = np.zeros(256, bool)
        arr[3] = True
        mask = adapters_lib.compile_grammar(arr, 256, eos_id=4)
        assert sorted(np.nonzero(mask)[0].tolist()) == [3, 4]
        assert not arr[4]         # input mask not mutated

    def test_grammar_errors(self):
        assert adapters_lib.compile_grammar(None, 256) is None
        with pytest.raises(ValueError, match='unknown grammar'):
            adapters_lib.compile_grammar('regex', 256)
        with pytest.raises(ValueError, match='empty'):
            adapters_lib.compile_grammar([], 256)
        with pytest.raises(ValueError, match='out of vocab'):
            adapters_lib.compile_grammar([256], 256)
        with pytest.raises(ValueError, match='shape'):
            adapters_lib.compile_grammar(np.zeros(8, bool), 256)


# ------------------------------------------------------------ registry

@pytest.fixture(scope='module')
def base_params():
    return llama.init_params(jax.random.PRNGKey(0), CFG)


def _registry_engine(base_params, tmp_dir=None, slots=2):
    eng = InferenceEngine(CFG, base_params, max_batch=2, max_seq=64,
                          attn_impl='xla', adapter_slots=slots,
                          adapter_rank=4,
                          adapter_dir=tmp_dir, telemetry=False)
    return eng, eng.adapters


class TestRegistry:

    def test_lru_load_and_evict(self, base_params):
        _, reg = _registry_engine(base_params)
        targets = reg.targets
        for i in range(3):
            reg.register(f'ad{i}', _rand_tree(CFG, 4, targets, seed=i),
                         scale=1.0)
        reg.acquire('ad0'); reg.release('ad0')
        reg.acquire('ad1'); reg.release('ad1')
        assert reg.loaded() == ['ad0', 'ad1']
        # Bank is full and unpinned: ad2 evicts the coldest (ad0).
        reg.acquire('ad2'); reg.release('ad2')
        assert reg.loaded() == ['ad1', 'ad2']
        assert reg.loads_total == 3 and reg.evictions_total == 1
        # LRU hit: no new load, ad1 becomes hottest.
        slot = reg.acquire('ad1'); reg.release('ad1')
        assert slot == reg.slot_of('ad1')
        assert reg.loads_total == 3
        assert reg.loaded() == ['ad2', 'ad1']
        st = reg.stats()
        assert st['slots'] == 2 and st['used'] == 2 and st['free'] == 0
        assert st['rank'] == 4 and st['loads_total'] == 3
        assert st['evictions_total'] == 1 and st['last_load_ms'] >= 0.0

    def test_pins_block_eviction(self, base_params):
        _, reg = _registry_engine(base_params)
        for i in range(3):
            reg.register(f'ad{i}', _rand_tree(CFG, 4, reg.targets, seed=i),
                         scale=1.0)
        reg.acquire('ad0')
        reg.acquire('ad1')
        # Both slots pinned by live requests: retryable full error.
        with pytest.raises(adapters_lib.AdapterBankFullError):
            reg.acquire('ad2')
        reg.release('ad0')
        reg.acquire('ad2')    # now evicts the unpinned ad0
        assert reg.loaded() == ['ad1', 'ad2']
        assert reg.stats()['pinned'] == {'ad1': 1, 'ad2': 1}

    def test_bad_checkpoint_leaks_no_slot(self, base_params):
        """A rejected row (over-rank here) must fail BEFORE a slot is
        taken: repeated requests for a bad adapter must neither exhaust
        the bank nor evict healthy adapters as collateral."""
        _, reg = _registry_engine(base_params)
        reg.register('good', _rand_tree(CFG, 4, reg.targets, seed=0),
                     scale=1.0)
        reg.acquire('good'); reg.release('good')
        reg.register('fat', _rand_tree(CFG, 8, reg.targets, seed=1),
                     scale=1.0)
        for _ in range(3):             # more attempts than slots
            with pytest.raises(ValueError, match='exceeds bank rank'):
                reg.acquire('fat')
        assert reg.loaded() == ['good']
        assert reg.evictions_total == 0
        assert reg.stats()['free'] == 1
        # The bank stays fully serviceable.
        reg.register('ad2', _rand_tree(CFG, 4, reg.targets, seed=2),
                     scale=1.0)
        reg.acquire('good'); reg.release('good')
        reg.acquire('ad2'); reg.release('ad2')
        assert reg.loaded() == ['good', 'ad2']

    def test_unknown_and_illegal_names(self, base_params):
        _, reg = _registry_engine(base_params)
        with pytest.raises(ValueError, match='unknown adapter'):
            reg.acquire('nope')
        for bad in ('../evil', 'a/b', '', '.hidden'):
            with pytest.raises(ValueError, match='illegal|unknown'):
                reg.acquire(bad)
        with pytest.raises(ValueError):
            reg.register('a/b', _rand_tree(CFG, 4, ('wq',), seed=0))

    def test_adapter_dir_checkpoint_source(self, base_params, tmp_path):
        tree = _rand_tree(CFG, 4, ('wq', 'wo'), seed=5)
        multilora.save_adapter(str(tmp_path / 'disk1.npz'), CFG, tree,
                               scale=1.25)
        _, reg = _registry_engine(base_params, tmp_dir=str(tmp_path))
        slot = reg.acquire('disk1')
        assert reg.slot_of('disk1') == slot
        bank = reg.engine.params['layers']['mlora']
        assert np.allclose(np.asarray(bank['scale'][:, slot]), 1.25)
        assert np.asarray(
            bank['wq']['a'][:, slot].astype(jnp.float32)).any()


# ----------------------------------------------- engine contracts (slow)

def _make_engine(kind, cfg, params, **kw):
    if kind == 'paged':
        kw.setdefault('page_size', 8)
        return PagedInferenceEngine(cfg, params, max_batch=2, max_seq=128,
                                    attn_impl='xla', **kw)
    return InferenceEngine(cfg, params, max_batch=2, max_seq=128,
                           attn_impl='xla', **kw)


CFG32 = dataclasses.replace(CFG, dtype=jnp.float32)


@pytest.fixture(scope='module')
def adapter_setup():
    """fp32 config + params + one random adapter and its offline-merged
    reference params (fp32 pins greedy token-stream equality between
    the bank path ``x@W + s*(x@A)@B`` and the merged ``x@(W + s*A@B)``)."""
    params = llama.init_params(jax.random.PRNGKey(0), CFG32)
    tree = _rand_tree(CFG32, 4, multilora.default_targets(CFG32), seed=11)
    scale = 0.5
    merged = _merged_params(params, tree, scale)
    return params, tree, scale, merged


@pytest.mark.slow
class TestEngineContracts:

    @pytest.mark.parametrize('kind', ['slot', 'paged'])
    def test_zero_adapter_stream_identical_to_base(self, kind):
        """An engine carrying an (empty) bank is indistinguishable from
        one without: same greedy stream, request by request."""
        prompts = [[3, 1, 4, 1, 5], [9, 2, 6]]
        outs = {}
        for label, extra in (('base', {}),
                             ('bank', {'adapter_slots': 2,
                                       'adapter_rank': 4})):
            params = llama.init_params(jax.random.PRNGKey(0), CFG)
            eng = _make_engine(kind, CFG, params, **extra)
            rids = [eng.add_request(p, max_new_tokens=8) for p in prompts]
            done = eng.run_to_completion(horizon=4)
            outs[label] = [done[r].output for r in rids]
        assert outs['bank'] == outs['base'], outs

    @pytest.mark.parametrize('kind', ['slot', 'paged'])
    def test_adapter_matches_offline_merged(self, kind, adapter_setup):
        """Bank-served adapter == offline-merged reference, greedy at
        fp32 — while a base request sharing the SAME batch stays equal
        to the plain engine (zero-slot purity in a mixed batch)."""
        params, tree, scale, merged = adapter_setup
        prompt = [3, 1, 4, 1, 5, 9, 2, 6]
        n = 8

        eng = _make_engine(kind, CFG32, params,
                           adapter_slots=2, adapter_rank=4)
        eng.adapters.register('acme', tree, scale=scale)
        rid_a = eng.add_request(prompt, max_new_tokens=n, adapter='acme')
        rid_b = eng.add_request(prompt, max_new_tokens=n)
        done = eng.run_to_completion(horizon=4)
        got_adapter = done[rid_a].output
        got_base = done[rid_b].output

        ref = _make_engine(kind, CFG32, merged)
        rid = ref.add_request(prompt, max_new_tokens=n)
        want_adapter = ref.run_to_completion(horizon=4)[rid].output

        plain = _make_engine(kind, CFG32, params)
        rid = plain.add_request(prompt, max_new_tokens=n)
        want_base = plain.run_to_completion(horizon=4)[rid].output

        assert got_adapter == want_adapter, (got_adapter, want_adapter)
        assert got_base == want_base, (got_base, want_base)
        # The adapter is actually live (its delta moved the stream).
        assert got_adapter != got_base

    @pytest.mark.parametrize('kind', ['slot', 'paged'])
    def test_adapter_matches_merged_chunked_prefill(self, kind,
                                                    adapter_setup):
        """Same contract through the chunked-prefill path: adapter rows
        gather in every prefill chunk, not just monolithic prefill."""
        params, tree, scale, merged = adapter_setup
        prompt = ([3, 1, 4, 1, 5, 9, 2, 6] * 5)[:38]
        n = 6

        eng = _make_engine(kind, CFG32, params, prefill_chunk_tokens=16,
                           adapter_slots=2, adapter_rank=4)
        eng.adapters.register('acme', tree, scale=scale)
        rid = eng.add_request(prompt, max_new_tokens=n, adapter='acme')
        got = eng.run_to_completion(horizon=4)[rid].output

        ref = _make_engine(kind, CFG32, merged, prefill_chunk_tokens=16)
        rid = ref.add_request(prompt, max_new_tokens=n)
        want = ref.run_to_completion(horizon=4)[rid].output
        assert got == want, (got, want)

    def test_adapter_composes_with_multistep_and_spec(self, adapter_setup):
        """decode_steps_per_call and speculate_k reproduce the plain
        single-step adapter stream (the bank rides inside the k-step
        fused scan and the in-scan spec verify)."""
        params, tree, scale, _ = adapter_setup
        prompt = [3, 1, 4, 1, 5]
        n = 8

        outs = {}
        for label, extra in (('single', {}),
                             ('multistep', {'decode_steps_per_call': 2}),
                             ('spec', {'speculate_k': 2})):
            eng = _make_engine('slot', CFG32, params,
                               adapter_slots=2, adapter_rank=4, **extra)
            eng.adapters.register('acme', tree, scale=scale)
            rid = eng.add_request(prompt, max_new_tokens=n,
                                  adapter='acme')
            outs[label] = eng.run_to_completion(horizon=4)[rid].output
        assert outs['multistep'] == outs['single'], outs
        assert outs['spec'] == outs['single'], outs

    @pytest.mark.parametrize('kind', ['slot', 'paged'])
    def test_grammar_constrains_output(self, kind):
        """Satellite: per-slot vocab logit masks. A JSON-mode request
        only ever emits tokens from the JSON-mode set; an id-list
        grammar only emits listed ids — while an unconstrained request
        in the SAME batch is unaffected."""
        params = llama.init_params(jax.random.PRNGKey(0), CFG)
        plain = _make_engine(kind, CFG, params)
        rid = plain.add_request([3, 1, 4], max_new_tokens=8)
        free_want = plain.run_to_completion(horizon=4)[rid].output

        eng = _make_engine(kind, CFG, params)
        rid_json = eng.add_request([3, 1, 4], max_new_tokens=8,
                                   grammar='json')
        rid_free = eng.add_request([3, 1, 4], max_new_tokens=8)
        done = eng.run_to_completion(horizon=4)
        allowed = adapters_lib.compile_grammar('json', CFG.vocab_size)
        assert all(allowed[t] for t in done[rid_json].output), \
            done[rid_json].output
        assert done[rid_free].output == free_want

        eng2 = _make_engine(kind, CFG, params)
        rid = eng2.add_request([3, 1, 4], max_new_tokens=8,
                               grammar=[5, 9])
        out = eng2.run_to_completion(horizon=4)[rid].output
        assert out and set(out) <= {5, 9}, out

    def test_grammar_composes_with_adapter(self, adapter_setup):
        """One request can carry BOTH an adapter and a grammar: the
        mask applies on top of the adapter-shifted logits."""
        params, tree, scale, _ = adapter_setup
        eng = _make_engine('slot', CFG32, params,
                           adapter_slots=2, adapter_rank=4)
        eng.adapters.register('acme', tree, scale=scale)
        rid = eng.add_request([3, 1, 4], max_new_tokens=8,
                              adapter='acme', grammar=[5, 9, 17])
        out = eng.run_to_completion(horizon=4)[rid].output
        assert out and set(out) <= {5, 9, 17}, out
