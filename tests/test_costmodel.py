"""Static cost model (analysis/costmodel.py) against hand-computed
ground truth: eqn-level byte/FLOP attribution on programs small enough
to price by hand (a matmul, an int4 qeinsum, a paged-attention-style
gather), the budget-gate failure path (a fattened program must fail
naming the offending eqn), and the KV bytes/token parity contract the
``skytpu_kv_read_bytes_per_step`` gauge is held to."""
import jax
import jax.numpy as jnp
import pytest
from jax import lax

from skypilot_tpu.analysis import costmodel as cm
from skypilot_tpu.models import quantization as q

BF16 = jnp.bfloat16


def _analyze(fn, args, classes, label='t'):
    cj = jax.make_jaxpr(fn)(*args)
    return cm.analyze_closed_jaxpr(cj, classes, label=label)


# ------------------------------------------------- hand ground truth
def test_matmul_ground_truth():
    """x[8,64] @ w[64,128] in bf16: 2mnk FLOPs, each operand read
    once at its stored width, the output written once."""
    w = jax.ShapeDtypeStruct((64, 128), BF16)
    x = jax.ShapeDtypeStruct((8, 64), BF16)
    cost = _analyze(lambda w, x: x @ w, (w, x),
                    [cm.WEIGHT_BF16, cm.ACTIVATION], label='matmul')
    assert cost.flops == 2 * 8 * 64 * 128
    assert cost.read[cm.WEIGHT_BF16] == 64 * 128 * 2
    assert cost.read[cm.ACTIVATION] == 8 * 64 * 2
    assert cost.written[cm.ACTIVATION] == 8 * 128 * 2


def test_int4_qeinsum_reads_packed_bytes():
    """The fused-dequant qeinsum must be charged the PACKED nibble
    bytes (w.size/2) + the fp32 scales — not the bf16-materialized
    dequant (4x the codes). This is the asymmetry the byte gate
    exists to defend."""
    wq = q._quantize_array4(jnp.ones((64, 128), BF16),
                            reduce_axes=(0,))
    ws = jax.ShapeDtypeStruct(wq.packed.shape, wq.packed.dtype)
    ss = jax.ShapeDtypeStruct(wq.scale.shape, wq.scale.dtype)
    x = jax.ShapeDtypeStruct((8, 64), BF16)

    def g(packed, scale, x):
        w4 = q.QuantizedWeight4(packed=packed, scale=scale)
        return q.qeinsum('bd,df->bf', x, w4)

    cost = _analyze(g, (ws, ss, x),
                    [cm.WEIGHT_INT4, cm.WEIGHT_SCALE, cm.ACTIVATION],
                    label='qeinsum4')
    packed_b = wq.packed.size * wq.packed.dtype.itemsize
    scale_b = wq.scale.size * wq.scale.dtype.itemsize
    assert packed_b == 64 * 128 // 2
    assert cost.read[cm.WEIGHT_INT4] == packed_b
    assert cost.read[cm.WEIGHT_SCALE] == scale_b


def test_paged_gather_reads_touched_rows_only():
    """A paged-attention-style row gather from a [pages, page, d]
    pool: the slice family is charged the GATHERED output bytes in the
    pool's class plus the index tables — never the whole pool."""
    pool = jax.ShapeDtypeStruct((128, 16, 64), BF16)
    idx = jax.ShapeDtypeStruct((4,), jnp.int32)

    def f(pool, idx):
        return jnp.take(pool, idx, axis=0)

    cost = _analyze(f, (pool, idx), [cm.KV_POOL, cm.TABLE],
                    label='gather')
    gathered = 4 * 16 * 64 * 2
    assert cost.read[cm.KV_POOL] == gathered
    assert cost.read[cm.TABLE] == 4 * 4
    assert cost.read[cm.KV_POOL] < 128 * 16 * 64 * 2 / 8


# --------------------------------------------- budget-gate failure
def _thin_and_fat_costs():
    """The same logical computation twice: the sanctioned fused
    dequant (packed codes cross the scan boundary) vs a fattened
    variant that materializes the bf16 dequant once and re-reads it
    every scan step."""
    wq = q._quantize_array4(jnp.ones((64, 128), BF16),
                            reduce_axes=(0,))
    ws = jax.ShapeDtypeStruct(wq.packed.shape, wq.packed.dtype)
    ss = jax.ShapeDtypeStruct(wq.scale.shape, wq.scale.dtype)
    x = jax.ShapeDtypeStruct((8, 64), BF16)
    classes = [cm.WEIGHT_INT4, cm.WEIGHT_SCALE, cm.ACTIVATION]

    def thin(packed, scale, x):
        w4 = q.QuantizedWeight4(packed=packed, scale=scale)

        def body(c, _):
            return q.qeinsum('bd,df->bf', c, w4) @ jnp.zeros(
                (128, 64), BF16), None
        out, _ = lax.scan(body, x, None, length=4)
        return out

    def fat(packed, scale, x):
        w_full = (q.unpack_int4(packed, axis=0).astype(BF16)
                  * scale.astype(BF16))

        def body(c, _):
            return c @ w_full @ jnp.swapaxes(w_full, 0, 1) * 0.01, None
        out, _ = lax.scan(body, x, None, length=4)
        return out

    return (_analyze(thin, (ws, ss, x), classes, label='decode'),
            _analyze(fat, (ws, ss, x), classes, label='decode'))


def test_fat_dequant_fails_thin_budget_naming_eqn():
    thin, fat = _thin_and_fat_costs()
    budget = cm.budget_from_costs({'decode': thin})
    assert not cm.check_budget({'decode': thin}, budget)
    viol = cm.check_budget({'decode': fat}, budget)
    assert viol, 'fattened program must violate the thin budget'
    joined = '\n'.join(viol)
    assert cm.WEIGHT_INT4 in joined
    # Per-eqn attribution points at the materialization crossing the
    # loop boundary, not just a total.
    assert 'materialize' in joined or 'dot_general' in joined


def test_scan_boundary_materialization_attributed():
    _thin, fat = _thin_and_fat_costs()
    prims = [e.prim for e in fat.eqns]
    assert any('boundary materialize' in p for p in prims), prims


def test_missing_dispatch_is_loud():
    thin, _fat = _thin_and_fat_costs()
    budget = cm.budget_from_costs({'decode': thin})
    viol = cm.check_budget({}, budget)
    assert viol and 'never captured' in viol[0]


# ----------------------------------------------- KV parity contract
@pytest.mark.parametrize('kvd', ['bf16', 'int8', 'int4'])
def test_kv_bytes_per_token_matches_runtime(kvd):
    """The static stored-bytes/token (pool avals / capacity) must sit
    within KV_TOLERANCE of the runtime ``kv_token_bytes`` the
    telemetry gauge publishes — for every KV dtype."""
    from skypilot_tpu.inference.engine import kv_token_bytes
    from skypilot_tpu.models.configs import ModelConfig
    cfg = ModelConfig(name='cm-kv', vocab_size=512, dim=128,
                      n_layers=2, n_heads=4, n_kv_heads=2, ffn_dim=256)
    cost = cm.abstract_decode_cost(cfg, batch=2, avg_ctx=24,
                                   kv_cache_dtype=kvd)
    measured = kv_token_bytes(cfg, kvd)
    check = cm.kv_static_check(cfg, kvd, measured)
    assert check['ok'], check
    assert abs(cost.kv_bytes_per_token / measured - 1.0) \
        <= cm.KV_TOLERANCE


def test_kv_static_check_rejects_divergence():
    from skypilot_tpu.inference.engine import kv_token_bytes
    from skypilot_tpu.models.configs import ModelConfig
    cfg = ModelConfig(name='cm-kv2', vocab_size=512, dim=128,
                      n_layers=2, n_heads=4, n_kv_heads=2, ffn_dim=256)
    off = kv_token_bytes(cfg, 'int8') * 2
    assert not cm.kv_static_check(cfg, 'int8', off)['ok']


def test_roofline_step_bytes_decomposition():
    from skypilot_tpu.models.configs import ModelConfig
    cfg = ModelConfig(name='cm-roof', vocab_size=512, dim=128,
                      n_layers=2, n_heads=4, n_kv_heads=2, ffn_dim=256)
    rb = cm.roofline_step_bytes(cfg, batch=2, avg_ctx=24,
                                quantize='int4', kv_cache_dtype='int8')
    assert rb['step_bytes'] == rb['weight_bytes'] + rb['kv_bytes']
    assert rb['kv_bytes'] == rb['kv_bytes_per_token'] * 2 * 24
    assert rb['read_by_class'].get(cm.WEIGHT_INT4, 0) > 0
    # int4 packing actually shows up: codes stream at half a byte per
    # element, so the int4 class stays under the bf16 equivalent / 3.
    bf = cm.roofline_step_bytes(cfg, batch=2, avg_ctx=24)
    assert rb['weight_bytes'] < bf['weight_bytes'] / 1.5


# ------------------------------------------------ preset integration
def test_llama_preset_budget_green():
    """The llama preset (pure jaxpr, no engine warmup — fast) carries
    an armed byte budget and passes it."""
    from skypilot_tpu.analysis import jaxpr_audit
    report = jaxpr_audit.run_preset('llama')
    assert report.preset == 'llama'
    assert report.dispatch_costs, 'llama preset must price its forward'
    assert report.byte_budget, 'llama preset must declare a budget'
    assert report.byte_budget_violations() == []
    assert report.ok(), report.format()


def test_declared_budget_with_no_costs_is_loud():
    from skypilot_tpu.analysis import jaxpr_audit
    report = jaxpr_audit.AuditReport(name='x')
    report.byte_budget = {'decode': {cm.ACTIVATION: 1}}
    viol = report.byte_budget_violations()
    assert viol and 'no dispatch costs' in viol[0]
    assert not report.ok()


def test_all_default_presets_have_budgets():
    """Every default audit preset ships an armed byte budget — the
    contract the ISSUE's 'declared byte_budget gate' is about."""
    from skypilot_tpu.analysis import jaxpr_audit
    missing = [n for n in jaxpr_audit.DEFAULT_PRESETS
               if not cm.budget_for(n)]
    assert not missing, missing


# ------------------------------------------------------- CLI smoke
def test_cli_costmodel_table_smoke(capsys):
    """graftcheck costmodel on the llama preset (pure jaxpr — fast
    enough for tier-1): prints an attribution table and exits 0."""
    from skypilot_tpu.analysis.cli import main as graftcheck_main
    assert graftcheck_main(['costmodel', '--preset', 'llama']) == 0
    out = capsys.readouterr().out
    assert '=== costmodel [llama] ===' in out
    assert cm.WEIGHT_BF16 in out
    assert 'read' in out


def test_cli_costmodel_json_schema(capsys):
    import json
    from skypilot_tpu.analysis.cli import main as graftcheck_main
    assert graftcheck_main(
        ['costmodel', '--preset', 'llama', '--json']) == 0
    doc = json.loads(capsys.readouterr().out)
    assert set(doc) == {'ok', 'presets'}
    assert doc['ok'] is True
    entry = doc['presets']['llama']
    assert set(entry) == {'dispatches', 'byte_budget', 'violations'}
    assert entry['violations'] == []
    assert entry['byte_budget'], 'llama budget must be armed'
    (label, cost), = [next(iter(entry['dispatches'].items()))]
    assert set(cost) == {'collective_bytes', 'flops',
                         'kv_bytes_per_token', 'kv_token_capacity',
                         'label', 'notes', 'read_bytes', 'top_eqns',
                         'written_bytes'}
    assert cost['read_bytes'].get(cm.WEIGHT_BF16, 0) > 0
