"""Unified telemetry: registry exposition + thread safety, per-request
trace timelines (queue → prefill → decode span order), chrome-trace
export through the timeline writer, and the model server's Prometheus
``/metrics`` + ``/debug/requests`` surfaces."""
import json
import math
import threading
import urllib.request

import jax
import pytest

from skypilot_tpu.telemetry import registry as registry_lib
from skypilot_tpu.telemetry import tracing

jax.config.update('jax_platforms', 'cpu')


def test_spot_scaling_series_registered_at_construction(
        tmp_path, monkeypatch):
    """Round-10 controller-side stable schema: constructing the
    forecast autoscaler and the replica manager registers every
    forecast/target/provision series — zeros from the first scrape,
    before any traffic, preemption or provision ever happened."""
    monkeypatch.setenv('SKYTPU_SERVE_DIR', str(tmp_path / 'serve'))
    from skypilot_tpu import telemetry
    from skypilot_tpu.serve import autoscalers as asc_lib
    from skypilot_tpu.serve import forecaster as forecaster_lib
    from skypilot_tpu.serve.replica_managers import ReplicaManager
    from skypilot_tpu.serve.service_spec import SkyServiceSpec
    # Fresh process registry: the zeros-from-first-scrape claim is
    # about CONSTRUCTION, so earlier tests' legitimate traffic on the
    # shared registry must not bleed in (get-or-create makes the swap
    # safe — later servers/engines re-create their handles).
    registry_lib.reset_registry()
    try:
        spec = SkyServiceSpec(
            readiness_path='/readiness', min_replicas=1, max_replicas=4,
            target_qps_per_replica=1.0, forecast_enabled=True,
            dynamic_ondemand_fallback=True)
        asc = asc_lib.Autoscaler.from_spec(spec)
        assert isinstance(asc, asc_lib.ForecastFallbackAutoscaler)
        ReplicaManager('spot-schema-test', spec, {})
        prom = telemetry.get_registry().render_prometheus()
    finally:
        registry_lib.reset_registry()
    assert '# TYPE skytpu_forecast_qps gauge' in prom
    for tier in forecaster_lib.TIERS:
        for horizon in forecaster_lib.HORIZONS:
            assert ('skytpu_forecast_qps{horizon="%s",tier="%s"} 0'
                    % (horizon, tier)) in prom, (tier, horizon)
    assert '# TYPE skytpu_autoscaler_target_replicas gauge' in prom
    for kind in asc_lib.TARGET_KINDS:
        assert (f'skytpu_autoscaler_target_replicas{{kind="{kind}"}} 0'
                in prom), kind
    assert '# TYPE skytpu_spot_preemptions_total counter' in prom
    assert 'skytpu_spot_preemptions_total 0' in prom
    assert '# TYPE skytpu_prefix_warmup_seconds histogram' in prom
    assert 'skytpu_prefix_warmup_seconds_bucket{le="+Inf"} 0' in prom
    assert '# TYPE skytpu_replica_provision_seconds histogram' in prom
    assert 'skytpu_replica_provision_seconds_bucket{le="+Inf"} 0' \
        in prom
    # Round-13 gray-failure series: the quarantine counter and every
    # gray detection kind register at MANAGER construction — zeros
    # before any canary mismatch, NaN eviction or checksum refusal.
    assert '# TYPE skytpu_replicas_quarantined_total counter' in prom
    assert 'skytpu_replicas_quarantined_total 0' in prom
    assert '# TYPE skytpu_gray_failures_total counter' in prom
    from skypilot_tpu.serve import faults as faults_lib
    for kind in faults_lib.GRAY_FAILURE_KINDS:
        assert (f'skytpu_gray_failures_total{{kind="{kind}"}} 0'
                in prom), kind


def test_lb_affinity_series_registered_at_construction(tmp_path,
                                                       monkeypatch):
    """PR-18 stable schema: constructing a prefix-affinity LB (never
    started, never synced) registers every affinity / horizontal-tier
    series — zeros from the first scrape, every outcome label
    pre-registered."""
    monkeypatch.setenv('SKYTPU_SERVE_DIR', str(tmp_path / 'serve'))
    from skypilot_tpu import telemetry
    from skypilot_tpu.serve.load_balancer import SkyServeLoadBalancer
    registry_lib.reset_registry()
    try:
        SkyServeLoadBalancer(controller_url='http://127.0.0.1:1',
                             port=1, policy_name='prefix_affinity',
                             lb_id='lb-telemetry')
        prom = telemetry.get_registry().render_prometheus()
    finally:
        registry_lib.reset_registry()
    assert '# TYPE skytpu_lb_affinity_hits_total counter' in prom
    for outcome in ('hit', 'miss', 'migrated'):
        assert (f'skytpu_lb_affinity_hits_total{{outcome="{outcome}"}}'
                ' 0' in prom), outcome
    assert '# TYPE skytpu_prefix_recompute_tokens_total counter' in prom
    assert 'skytpu_prefix_recompute_tokens_total 0' in prom
    assert '# TYPE skytpu_lb_ring_size gauge' in prom
    # Pre-sync the ring is just this LB — the gauge starts at 0 and is
    # set on the first successful controller sync.
    assert 'skytpu_lb_ring_size 0' in prom
    assert '# TYPE skytpu_lb_handoff_total counter' in prom
    assert 'skytpu_lb_handoff_total 0' in prom


def test_gang_series_registered_at_construction():
    """Round-11 gang stable schema: ``gang.register_metrics()`` alone
    puts every gang series in the registry — zeros from the first
    scrape (gang_size 0 = not a gang), every failure cause
    pre-registered — and a GangCoordinator sets the live world size."""
    from skypilot_tpu import telemetry
    from skypilot_tpu.serve import gang as gang_lib
    registry_lib.reset_registry()
    try:
        gang_lib.register_metrics()
        prom = telemetry.get_registry().render_prometheus()
    finally:
        registry_lib.reset_registry()
    assert '# TYPE skytpu_gang_size gauge' in prom
    assert 'skytpu_gang_size 0' in prom
    assert '# TYPE skytpu_gang_join_seconds histogram' in prom
    assert 'skytpu_gang_join_seconds_bucket{le="+Inf"} 0' in prom
    assert '# TYPE skytpu_gang_failures_total counter' in prom
    for cause in gang_lib.FAILURE_CAUSES:
        assert (f'skytpu_gang_failures_total{{cause="{cause}"}} 0'
                in prom), cause
    assert '# TYPE skytpu_gang_heartbeat_age_seconds gauge' in prom
    assert 'skytpu_gang_heartbeat_age_seconds 0' in prom
    registry_lib.reset_registry()
    try:
        spec = gang_lib.GangSpec(gang_id='g-telemetry', rank=0, world=3)
        gang_lib.GangCoordinator(spec)
        prom = telemetry.get_registry().render_prometheus()
    finally:
        registry_lib.reset_registry()
    assert 'skytpu_gang_size 3' in prom


# ---------------------------------------------------------------------------
# Registry: Prometheus exposition golden test
# ---------------------------------------------------------------------------
def _golden_registry() -> registry_lib.MetricsRegistry:
    reg = registry_lib.MetricsRegistry()
    reg.counter('t_requests_total', 'Requests served').inc(3)
    reg.gauge('t_queue_depth', 'Queue depth')          # stays 0
    h = reg.histogram('t_latency_ms', 'Latency', buckets=(10, 100))
    h.observe(5)
    h.observe(50)
    h.observe(5000)
    reg.counter('t_probe_total', 'Probes', outcome='success').inc(2)
    reg.counter('t_probe_total', 'Probes', outcome='failure')
    return reg


def test_prometheus_exposition_golden():
    """Parse the exposition line by line: HELP/TYPE present once per
    family, every registered series emitted (zeros NOT omitted),
    histogram buckets cumulative and terminated by +Inf with matching
    _sum/_count."""
    text = _golden_registry().render_prometheus()
    lines = [ln for ln in text.splitlines() if ln]
    # Every family has exactly one HELP and one TYPE line.
    for fam, kind in [('t_requests_total', 'counter'),
                      ('t_queue_depth', 'gauge'),
                      ('t_latency_ms', 'histogram'),
                      ('t_probe_total', 'counter')]:
        assert lines.count(f'# TYPE {fam} {kind}') == 1, fam
        assert sum(1 for ln in lines
                   if ln.startswith(f'# HELP {fam} ')) == 1, fam
    # Samples are machine-parseable: "name{labels} value".
    samples = {}
    for ln in lines:
        if ln.startswith('#'):
            continue
        name, value = ln.rsplit(' ', 1)
        samples[name] = float(value)
    assert samples['t_requests_total'] == 3
    # Zero-valued gauge present, not omitted (stable schema).
    assert samples['t_queue_depth'] == 0
    # Histogram: cumulative buckets, +Inf terminator, sum/count.
    assert samples['t_latency_ms_bucket{le="10"}'] == 1
    assert samples['t_latency_ms_bucket{le="100"}'] == 2
    assert samples['t_latency_ms_bucket{le="+Inf"}'] == 3
    assert samples['t_latency_ms_count'] == 3
    assert samples['t_latency_ms_sum'] == 5055
    # Labeled series: both outcomes present, the zero one included.
    assert samples['t_probe_total{outcome="success"}'] == 2
    assert samples['t_probe_total{outcome="failure"}'] == 0
    # TYPE precedes its family's samples.
    type_idx = lines.index('# TYPE t_latency_ms histogram')
    first_sample = next(i for i, ln in enumerate(lines)
                        if ln.startswith('t_latency_ms_bucket'))
    assert type_idx < first_sample


def test_registry_json_rendering():
    data = _golden_registry().render_json()
    assert data['t_requests_total']['type'] == 'counter'
    assert data['t_requests_total']['series'][0]['value'] == 3
    hist = data['t_latency_ms']['series'][0]
    assert hist['count'] == 3 and hist['window'] == 3


def test_registry_get_or_create_and_type_conflict():
    reg = registry_lib.MetricsRegistry()
    c1 = reg.counter('x_total', 'X')
    c2 = reg.counter('x_total')
    assert c1 is c2
    with pytest.raises(TypeError):
        reg.gauge('x_total')
    with pytest.raises(ValueError):
        c1.inc(-1)


def test_registry_thread_safety():
    """Concurrent writers on one counter + one histogram: no lost
    increments or observations."""
    reg = registry_lib.MetricsRegistry()
    c = reg.counter('race_total')
    h = reg.histogram('race_ms', window=100000)
    n_threads, n_iter = 8, 2000

    def work():
        for i in range(n_iter):
            c.inc()
            h.observe(i % 50)

    threads = [threading.Thread(target=work) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == n_threads * n_iter
    assert h.count == n_threads * n_iter
    snap = h.snapshot()
    assert snap['cumulative'][-1] == n_threads * n_iter


def test_windowed_quantiles():
    """ONE windowed-quantile implementation: exact rolling median/p90
    over a bounded window (old values age out)."""
    reg = registry_lib.MetricsRegistry()
    h = reg.histogram('q_ms', window=100)
    assert h.quantile(0.5) == 0.0          # empty -> 0, not missing
    for v in range(1, 101):
        h.observe(float(v))
    assert h.quantile(0.5) == 51
    assert h.quantile(0.9) == 91
    for _ in range(100):                   # roll the window over
        h.observe(1000.0)
    assert h.quantile(0.5) == 1000.0
    assert h.window_len == 100


# ---------------------------------------------------------------------------
# Per-request tracing: e2e span order through the engines
# ---------------------------------------------------------------------------
def _span_names(trace):
    return [s['name'] for s in trace.to_dict()['spans']]


@pytest.mark.parametrize('kind', ['slot', 'paged'])
def test_request_trace_span_order_e2e(kind):
    """A finished request's trace holds queue → prefill (with per-chunk
    spans) → decode in order, all durations non-negative, published
    exactly once to the ring buffer."""
    from skypilot_tpu.models import configs
    cfg = configs.get_config('tiny')
    if kind == 'paged':
        from skypilot_tpu.inference.paged import PagedInferenceEngine
        eng = PagedInferenceEngine(cfg, max_batch=2, max_seq=64,
                                   prefill_chunk_tokens=8)
    else:
        from skypilot_tpu.inference.engine import InferenceEngine
        eng = InferenceEngine(cfg, max_batch=2, max_seq=64,
                              prefill_chunk_tokens=8)
    rid = eng.add_request([1, 2, 3] * 7, max_new_tokens=5)
    done = eng.run_to_completion(horizon=8)
    assert rid in done
    trace = tracing.get_trace_buffer().find(rid)
    assert trace is not None and trace.done
    d = trace.to_dict()
    names = [s['name'] for s in d['spans']]
    # Lifecycle order (by position in the span list).
    for earlier, later in [('queue', 'prefill'), ('prefill', 'decode')]:
        assert names.index(earlier) < names.index(later), names
    # 21 prompt tokens / chunk 8 -> at least 3 chunk spans.
    assert names.count('prefill_chunk') >= 3
    for span in d['spans']:
        assert span.get('dur_ms', 0.0) >= 0.0, span
        assert span['start_ms'] >= -1e-6, span
    assert d['meta']['output_tokens'] == 5
    # Queue-wait span is completed and measurable (the serve layer's
    # queue-wait histogram reads exactly this).
    assert trace.span_ms('queue') is not None


def test_trace_cancel_publishes_trace():
    from skypilot_tpu.inference.engine import InferenceEngine
    from skypilot_tpu.models import configs
    eng = InferenceEngine(configs.get_config('tiny'), max_batch=2,
                          max_seq=64)
    rid = eng.add_request([1, 2, 3, 4], max_new_tokens=30)
    eng.step(horizon=1)
    assert eng.cancel(rid)
    trace = tracing.get_trace_buffer().find(rid)
    assert trace is not None and trace.done
    assert trace.meta.get('cancelled') is True


def test_telemetry_off_no_traces_no_phases():
    from skypilot_tpu.inference.engine import InferenceEngine
    from skypilot_tpu.models import configs
    before = len(tracing.get_trace_buffer())
    eng = InferenceEngine(configs.get_config('tiny'), max_batch=2,
                          max_seq=64, telemetry=False)
    rid = eng.add_request([1, 2, 3], max_new_tokens=3)
    done = eng.run_to_completion(horizon=4)
    assert rid in done and done[rid].trace is None
    assert len(tracing.get_trace_buffer()) == before
    assert eng.phase_stats() == {}


def test_chrome_trace_export(tmp_path):
    """Completed traces export as a chrome://tracing file via the
    utils/timeline.py writer."""
    from skypilot_tpu.inference.engine import InferenceEngine
    from skypilot_tpu.models import configs
    eng = InferenceEngine(configs.get_config('tiny'), max_batch=2,
                          max_seq=64, prefill_chunk_tokens=8)
    rid = eng.add_request([5, 6, 7] * 5, max_new_tokens=4)
    eng.run_to_completion(horizon=8)
    out = tmp_path / 'req_trace.json'
    path = tracing.export_chrome_trace(
        str(out), traces=[tracing.get_trace_buffer().find(rid)])
    assert path == str(out)
    payload = json.loads(out.read_text())
    events = payload['traceEvents']
    assert events and all(
        ev['ph'] == 'X' and ev['dur'] >= 0 and 'ts' in ev
        for ev in events)
    assert any(ev['name'] == 'decode' for ev in events)


def test_step_phase_profiler_and_compile_events():
    """The engine records per-phase wall time and one first-call event
    per distinct jit key (steady state adds none)."""
    from skypilot_tpu.inference.engine import InferenceEngine
    from skypilot_tpu.models import configs
    eng = InferenceEngine(configs.get_config('tiny'), max_batch=2,
                          max_seq=64, prefill_chunk_tokens=8)
    for _ in range(2):
        eng.add_request([1, 2, 3] * 7, max_new_tokens=4)
        eng.run_to_completion(horizon=8)
    stats = eng.phase_stats()
    for phase in ('admit', 'decode_enqueue', 'readback',
                  'prefill_chunk'):
        assert phase in stats['phases'], stats
        assert stats['phases'][phase]['total_s'] >= 0
    n_compiles = len(stats['compiles'])
    assert n_compiles >= 2                  # >=1 prefill + >=1 decode key
    # Same shapes again: no new first-call events.
    eng.add_request([1, 2, 3] * 7, max_new_tokens=4)
    eng.run_to_completion(horizon=8)
    assert len(eng.phase_stats()['compiles']) == n_compiles


def test_kv_round2_series_registered_at_construction():
    """KV-round-two stable schema: constructing an engine alone puts
    the KV read-traffic gauge and BOTH attention-impl attribution
    series in the registry — zeros from the first scrape, before any
    decode dispatch."""
    from skypilot_tpu import telemetry
    from skypilot_tpu.inference.engine import InferenceEngine
    from skypilot_tpu.models import configs
    registry_lib.reset_registry()
    try:
        InferenceEngine(configs.get_config('tiny'), max_batch=2,
                        max_seq=64)
        prom = telemetry.get_registry().render_prometheus()
    finally:
        registry_lib.reset_registry()
    assert '# TYPE skytpu_kv_read_bytes_per_step gauge' in prom
    assert 'skytpu_kv_read_bytes_per_step 0' in prom
    assert '# TYPE skytpu_attn_kernel_ms gauge' in prom
    for impl in ('per_layer', 'cross_layer'):
        assert f'skytpu_attn_kernel_ms{{impl="{impl}"}} 0' in prom, impl


@pytest.mark.parametrize('kind', ['slot', 'paged'])
def test_kv_round2_series_updated_by_decode(kind):
    """After decode traffic the KV read gauge carries live-context x
    per-token bytes and exactly the attention impl that served the
    dispatches is non-zero (per_layer here — the slot engine has no
    cross-layer path and the paged engine defaults off it on CPU)."""
    from skypilot_tpu import telemetry
    from skypilot_tpu.inference.engine import kv_token_bytes
    from skypilot_tpu.models import configs
    registry_lib.reset_registry()
    try:
        cfg = configs.get_config('tiny')
        if kind == 'paged':
            from skypilot_tpu.inference.paged import PagedInferenceEngine
            eng = PagedInferenceEngine(cfg, max_batch=2, max_seq=64,
                                       decode_impl='gather')
        else:
            from skypilot_tpu.inference.engine import InferenceEngine
            eng = InferenceEngine(cfg, max_batch=2, max_seq=64)
        eng.add_request([1, 2, 3, 4, 5], max_new_tokens=4)
        eng.run_to_completion(horizon=4)
        reg = telemetry.get_registry()
        kv_gauge = reg.get('skytpu_kv_read_bytes_per_step')
        per_layer = reg.get('skytpu_attn_kernel_ms', impl='per_layer')
        cross = reg.get('skytpu_attn_kernel_ms', impl='cross_layer')
        assert kv_gauge is not None and kv_gauge.value > 0
        # live context x per-token stored cost: bounded by the full
        # sequence capacity of the whole batch.
        assert kv_gauge.value <= kv_token_bytes(cfg, None) * 2 * 64
        assert per_layer is not None and per_layer.value > 0
        assert cross is not None and cross.value == 0
    finally:
        registry_lib.reset_registry()


def test_kv_round2_cross_layer_attribution():
    """decode_impl='cross_layer' routes the wall-time attribution to
    the cross_layer series — the per_layer series stays zero."""
    from skypilot_tpu import telemetry
    from skypilot_tpu.inference.paged import PagedInferenceEngine
    from skypilot_tpu.models import configs
    registry_lib.reset_registry()
    try:
        eng = PagedInferenceEngine(configs.get_config('tiny'),
                                   max_batch=2, max_seq=64,
                                   decode_impl='cross_layer')
        eng.add_request([1, 2, 3, 4, 5], max_new_tokens=4)
        eng.run_to_completion(horizon=4)
        reg = telemetry.get_registry()
        assert reg.get('skytpu_attn_kernel_ms',
                       impl='cross_layer').value > 0
        assert reg.get('skytpu_attn_kernel_ms',
                       impl='per_layer').value == 0
        assert reg.get('skytpu_kv_read_bytes_per_step').value > 0
    finally:
        registry_lib.reset_registry()


def test_adapter_series_registered_at_construction():
    """PR-20 stable schema: an engine built with an adapter bank
    registers the bank-slot occupancy gauges, the load/eviction
    counters and the requests_total{adapter="none"} series at
    CONSTRUCTION — zeros (and full free slots) from the first scrape,
    before any adapter ever loads."""
    from skypilot_tpu import telemetry
    from skypilot_tpu.inference.engine import InferenceEngine
    from skypilot_tpu.models import configs
    registry_lib.reset_registry()
    try:
        InferenceEngine(configs.get_config('tiny'), max_batch=2,
                        max_seq=64, adapter_slots=3, adapter_rank=4)
        prom = telemetry.get_registry().render_prometheus()
    finally:
        registry_lib.reset_registry()
    assert '# TYPE skytpu_adapter_bank_slots gauge' in prom
    assert 'skytpu_adapter_bank_slots{state="used"} 0' in prom
    assert 'skytpu_adapter_bank_slots{state="free"} 3' in prom
    assert '# TYPE skytpu_adapter_loads_total counter' in prom
    assert 'skytpu_adapter_loads_total 0' in prom
    assert '# TYPE skytpu_adapter_evictions_total counter' in prom
    assert 'skytpu_adapter_evictions_total 0' in prom
    assert '# TYPE skytpu_requests_total counter' in prom
    assert 'skytpu_requests_total{adapter="none"} 0' in prom


def test_adapter_series_updated_by_traffic():
    """Adapter churn moves every series: loads/evictions count LRU
    misses/evictions, the occupancy gauges track used+free == slots,
    and per-adapter request counters appear as adapters are first
    seen."""
    import numpy as np
    from skypilot_tpu import telemetry
    from skypilot_tpu.inference.engine import InferenceEngine
    from skypilot_tpu.models import configs, multilora
    registry_lib.reset_registry()
    try:
        cfg = configs.get_config('tiny')
        eng = InferenceEngine(cfg, max_batch=2, max_seq=64,
                              adapter_slots=2, adapter_rank=4)
        reg = eng.adapters
        rng = np.random.default_rng(0)
        for i in range(3):
            tree = {}
            for t in reg.targets:
                a_shape, b_shape = multilora.target_shapes(cfg, t, 4)
                tree[t] = {
                    'a': rng.normal(0, 0.02, (cfg.n_layers,) + a_shape)
                    .astype(np.float32),
                    'b': rng.normal(0, 0.02, (cfg.n_layers,) + b_shape)
                    .astype(np.float32)}
            reg.register(f'ad{i}', tree, scale=1.0)
        rid0 = eng.add_request([1, 2, 3], max_new_tokens=2,
                               adapter='ad0')
        rid1 = eng.add_request([4, 5], max_new_tokens=2, adapter='ad1')
        done = eng.run_to_completion(horizon=4)
        assert set(done) == {rid0, rid1}
        # Bank full + both released: ad2 evicts the coldest.
        rid2 = eng.add_request([6], max_new_tokens=2, adapter='ad2')
        eng.add_request([7, 8], max_new_tokens=2)   # base-model request
        done = eng.run_to_completion(horizon=4)
        assert rid2 in done
        treg = telemetry.get_registry()
        assert treg.get('skytpu_adapter_loads_total').value == 3
        assert treg.get('skytpu_adapter_evictions_total').value == 1
        used = treg.get('skytpu_adapter_bank_slots', state='used').value
        free = treg.get('skytpu_adapter_bank_slots', state='free').value
        assert used == 2 and free == 0
        for label, want in (('ad0', 1), ('ad1', 1), ('ad2', 1),
                            ('none', 1)):
            c = treg.get('skytpu_requests_total', adapter=label)
            assert c is not None and c.value == want, label
    finally:
        registry_lib.reset_registry()


def test_adapter_request_labels_bounded():
    """The requests_total{adapter} label set is BOUNDED: past 4 x slots
    distinct names, new ones collapse into adapter="other" — a tenant
    flood cannot grow the metric cardinality without bound."""
    from skypilot_tpu import telemetry
    from skypilot_tpu.inference.engine import InferenceEngine
    from skypilot_tpu.models import configs
    registry_lib.reset_registry()
    try:
        eng = InferenceEngine(configs.get_config('tiny'), max_batch=2,
                              max_seq=64, adapter_slots=1,
                              adapter_rank=4)
        reg = eng.adapters
        for i in range(12):
            reg.note_request(f'tenant{i}')
        treg = telemetry.get_registry()
        prom = treg.render_prometheus()
        labels = [ln.split('adapter="')[1].split('"')[0]
                  for ln in prom.splitlines()
                  if ln.startswith('skytpu_requests_total{')]
        # 'none' (pre-registered) + cap(4 x 1 slots) incl. 'other'.
        assert len(labels) <= 1 + 4 * reg.slots + 1
        assert 'other' in labels
        assert treg.get('skytpu_requests_total',
                        adapter='other').value >= 12 - 4 * reg.slots
    finally:
        registry_lib.reset_registry()


# ---------------------------------------------------------------------------
# Model server: Prometheus /metrics + /debug/requests over HTTP
# ---------------------------------------------------------------------------
def _wait_ready(port, timeout=120.0):
    import time
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            with urllib.request.urlopen(
                    f'http://127.0.0.1:{port}/readiness', timeout=5) as r:
                if r.status == 200:
                    return
        except Exception:  # pylint: disable=broad-except
            time.sleep(0.3)
    raise RuntimeError('server did not become ready')


def test_server_prometheus_metrics_and_debug_requests():
    """e2e: serve one request, then (a) /metrics parses as Prometheus
    text with the TTFT/TPOT/queue-wait histograms, step-phase timings
    and spec gauges, (b) /metrics?format=json keeps the stable gauge
    schema, (c) /debug/requests returns the request's complete span
    timeline in lifecycle order."""
    from skypilot_tpu.serve.server import ModelServer
    from skypilot_tpu.utils import common_utils
    port = common_utils.find_free_port(18980)
    server = ModelServer('tiny', max_batch=2, max_seq=64, port=port)
    server.start(block=False)
    try:
        _wait_ready(port)
        body = json.dumps({'prompt': [3, 1, 4, 1, 5] * 4,
                           'max_new_tokens': 6}).encode()
        req = urllib.request.Request(
            f'http://127.0.0.1:{port}/generate', data=body,
            headers={'Content-Type': 'application/json'})
        with urllib.request.urlopen(req, timeout=60) as r:
            result = json.loads(r.read())
        assert len(result['tokens']) == 6

        # (a) Prometheus exposition.
        with urllib.request.urlopen(
                f'http://127.0.0.1:{port}/metrics', timeout=10) as r:
            assert 'text/plain' in r.headers.get('Content-Type', '')
            prom = r.read().decode()
        for needle in ('# TYPE skytpu_request_ttft_ms histogram',
                       '# TYPE skytpu_request_tpot_ms histogram',
                       '# TYPE skytpu_request_queue_wait_ms histogram',
                       '# TYPE skytpu_engine_step_phase_seconds '
                       'histogram',
                       '# TYPE skytpu_requests_served_total counter',
                       '# TYPE skytpu_spec_accept_rate gauge',
                       '# TYPE skytpu_queue_depth gauge',
                       '# TYPE skytpu_kv_pool_tokens gauge',
                       '# TYPE skytpu_kv_pool_preemptions_total gauge'):
            assert needle in prom, needle
        assert 'skytpu_request_ttft_ms_bucket{le="+Inf"}' in prom
        assert 'phase="decode_enqueue"' in prom
        # KV pool capacity/pressure gauges: both states present with
        # the kv_cache_dtype label, capacity nonzero once the engine
        # is up, used + free == capacity.
        pool = {}
        for ln in prom.splitlines():
            if ln.startswith('skytpu_kv_pool_tokens{'):
                assert 'kv_cache_dtype="bf16"' in ln, ln
                pool[ln.split('state="')[1].split('"')[0]] = \
                    float(ln.rsplit(' ', 1)[1])
        assert set(pool) == {'used', 'free'}
        cap_lines = [ln for ln in prom.splitlines()
                     if ln.startswith('skytpu_kv_pool_token_capacity')]
        cap = float(cap_lines[0].rsplit(' ', 1)[1])
        assert cap > 0 and pool['used'] + pool['free'] == cap
        # Every sample line parses.
        for ln in prom.splitlines():
            if not ln or ln.startswith('#'):
                continue
            value = float(ln.rsplit(' ', 1)[1])
            assert not math.isnan(value)

        # (b) Stable-schema JSON retained behind ?format=json.
        with urllib.request.urlopen(
                f'http://127.0.0.1:{port}/metrics?format=json',
                timeout=10) as r:
            m = json.loads(r.read())
        for key in ('requests_served', 'active_slots', 'queue_depth',
                    'prefill_inflight', 'max_batch', 'ttft_ms_median',
                    'ttft_ms_p90', 'ttft_window', 'tpot_ms_median',
                    'queue_wait_ms_median', 'speculate_k',
                    'spec_accept_rate', 'spec_tokens_per_step',
                    'spec_proposed', 'spec_accepted', 'spec_rounds',
                    'kv_pool_token_capacity', 'kv_pool_tokens_used',
                    'kv_pool_tokens_free', 'kv_pool_preemptions'):
            assert key in m, key
            assert isinstance(m[key], (int, float)), key
        assert m['kv_cache_dtype'] == 'bf16'
        assert m['kv_pool_token_capacity'] > 0
        assert m['scheduler']['speculate_k'] == 0
        assert m['requests_served'] >= 1
        assert m['ttft_window'] >= 1

        # (b2b) Multi-LoRA stable schema (PR 20): the `lora` block is
        # present even with no bank configured — stable zeros, so
        # dashboards never key-error on bankless replicas.
        lora = m['lora']
        for key in ('slots', 'used', 'free', 'rank', 'targets',
                    'loads_total', 'evictions_total', 'last_load_ms',
                    'loaded', 'pinned'):
            assert key in lora, key
        assert lora['slots'] == 0 and lora['loaded'] == []

        # (b3) Serving-mesh shape: one gauge series per logical axis
        # with 1s on a single-chip replica (stable — the series never
        # appear/disappear with mesh shape), and the JSON mesh block
        # the LB's replica view reads, present from the first scrape.
        from skypilot_tpu.parallel import mesh as mesh_lib
        assert '# TYPE skytpu_mesh_shape gauge' in prom
        for axis in mesh_lib.MESH_AXES:
            assert f'skytpu_mesh_shape{{axis="{axis}"}} 1' in prom, axis
        assert set(m['mesh']) == set(mesh_lib.MESH_AXES) | {'devices'}
        assert m['mesh']['tp'] == 1 and m['mesh']['devices'] == 1
        assert m['sched']['mesh_speedup'] == 1

        # (b2) SLO-scheduler stable schema: every per-tier series is
        # registered at construction, so both tiers (and every shed
        # reason) render from the FIRST scrape — zeros, never omitted.
        from skypilot_tpu.serve import scheduler as sched_lib
        for tier in sched_lib.TIERS:
            assert f'skytpu_sched_queue_tokens{{tier="{tier}"}}' \
                in prom, tier
            assert f'skytpu_sched_queue_depth{{tier="{tier}"}}' \
                in prom, tier
            for reason in sched_lib.SHED_REASONS:
                assert ('skytpu_sched_shed_total{reason="%s",tier="%s"}'
                        % (reason, tier)) in prom, (tier, reason)
            assert (f'# TYPE skytpu_request_ttft_ms histogram' in prom
                    and f'tier="{tier}"' in prom)
        assert '# TYPE skytpu_sched_shed_total counter' in prom
        assert '# TYPE skytpu_sched_queue_tokens gauge' in prom

        # (b3) Robustness series (round 7): faults / migrations /
        # drain / recovery all register at construction — every series
        # renders as zeros from the first scrape even though no fault,
        # migration or drain ever happened on this server.
        from skypilot_tpu.serve import faults as faults_lib
        assert '# TYPE skytpu_faults_injected_total counter' in prom
        for kind in faults_lib.FAULT_KINDS:
            assert (f'skytpu_faults_injected_total{{kind="{kind}"}} 0'
                    in prom), kind
        assert '# TYPE skytpu_requests_migrated_total counter' in prom
        for outcome in faults_lib.MIGRATION_OUTCOMES:
            assert ('skytpu_requests_migrated_total'
                    f'{{outcome="{outcome}"}} 0' in prom), outcome
        assert '# TYPE skytpu_replica_drain_seconds histogram' in prom
        assert 'skytpu_replica_drain_seconds_bucket{le="+Inf"} 0' \
            in prom
        assert '# TYPE skytpu_replica_recovery_seconds histogram' \
            in prom
        assert 'skytpu_replica_recovery_seconds_bucket{le="+Inf"} 0' \
            in prom
        # (b4) Disaggregation series (round 9): every handoff outcome,
        # transfer direction, the transfer-latency histogram and the
        # per-role gauge register at construction — zeros from the
        # first scrape on a colocated replica that never hands off.
        from skypilot_tpu.serve import disagg as disagg_lib
        assert '# TYPE skytpu_disagg_handoff_total counter' in prom
        for outcome in disagg_lib.HANDOFF_OUTCOMES:
            assert (f'skytpu_disagg_handoff_total'
                    f'{{outcome="{outcome}"}} 0' in prom), outcome
        for direction in disagg_lib.KV_TRANSFER_DIRECTIONS:
            assert (f'skytpu_kv_transfer_bytes_total'
                    f'{{direction="{direction}"}} 0' in prom), direction
        assert '# TYPE skytpu_kv_transfer_seconds histogram' in prom
        assert 'skytpu_kv_transfer_seconds_bucket{le="+Inf"} 0' in prom
        assert 'skytpu_replica_role{role="colocated"} 1' in prom
        assert 'skytpu_replica_role{role="prefill"} 0' in prom
        assert 'skytpu_replica_role{role="decode"} 0' in prom
        # (b5) Spot-resilience series (round 10): the model server
        # registers the prefix-warmup histogram and the preemption
        # counter at construction, so both series render on the first
        # scrape. (Zeros-from-fresh is pinned by
        # test_spot_scaling_series_registered_at_construction on a
        # reset registry — earlier tests in this process may have
        # legitimately moved the shared series.)
        assert '# TYPE skytpu_prefix_warmup_seconds histogram' in prom
        assert 'skytpu_prefix_warmup_seconds_bucket{le="+Inf"}' in prom
        assert '# TYPE skytpu_spot_preemptions_total counter' in prom
        assert 'skytpu_spot_preemptions_total ' in prom
        # (b6) Gang series (round 11): registered at ModelServer
        # construction on gang and non-gang replicas alike, every
        # failure cause pre-registered. (Zeros-from-fresh is pinned by
        # test_gang_series_registered_at_construction on a reset
        # registry — earlier tests in this process may have moved the
        # shared series legitimately.)
        from skypilot_tpu.serve import gang as gang_lib
        assert '# TYPE skytpu_gang_size gauge' in prom
        assert '# TYPE skytpu_gang_join_seconds histogram' in prom
        assert 'skytpu_gang_join_seconds_bucket{le="+Inf"}' in prom
        assert '# TYPE skytpu_gang_failures_total counter' in prom
        for cause in gang_lib.FAILURE_CAUSES:
            assert (f'skytpu_gang_failures_total{{cause="{cause}"}}'
                    in prom), cause
        assert '# TYPE skytpu_gang_heartbeat_age_seconds gauge' in prom
        # JSON gang block: stable schema, non-gang truth.
        assert m['gang']['world'] == 1
        assert m['gang']['barrier'] is True
        # (b7) Gray-failure series (round 13): every detection kind
        # registers at construction; the wedge-watchdog age gauge is 0
        # between steps from the first scrape.
        assert '# TYPE skytpu_gray_failures_total counter' in prom
        for kind in faults_lib.GRAY_FAILURE_KINDS:
            assert (f'skytpu_gray_failures_total{{kind="{kind}"}}'
                    in prom), kind
        assert ('# TYPE skytpu_engine_step_watchdog_age_seconds '
                'gauge') in prom
        assert 'skytpu_engine_step_watchdog_age_seconds 0' in prom
        # (b8) Multi-step decode series (round 14): the pinned
        # steps-per-call gauge (0 = adaptive horizon on this server)
        # and the decode-substeps counter render from the first scrape
        # — the server's warmup request already drove fused substeps,
        # so the counter is strictly positive and the per-substep
        # phase attribution is live.
        assert '# TYPE skytpu_decode_steps_per_call gauge' in prom
        assert 'skytpu_decode_steps_per_call 0' in prom
        assert ('# TYPE skytpu_engine_decode_substeps_total '
                'counter') in prom
        sub = [ln for ln in prom.splitlines()
               if ln.startswith('skytpu_engine_decode_substeps_total ')]
        assert sub and float(sub[0].rsplit(' ', 1)[1]) > 0
        assert m['decode_steps_per_call'] == 0
        assert m['scheduler']['decode_steps_per_call'] == 0
        phases = server.engine.phase_stats()['phases']
        assert phases['decode_enqueue']['substeps'] > 0
        assert phases['decode_enqueue']['per_substep_ms'] >= 0
        assert m['gang']['members'] == {}
        # JSON disagg block: stable schema, zeros when idle.
        assert m['disagg']['role'] == 'colocated'
        assert set(m['disagg']['handoffs']) == \
            set(disagg_lib.HANDOFF_OUTCOMES)
        assert all(v == 0 for v in m['disagg']['handoffs'].values())
        assert m['disagg']['kv_transfer_bytes'] == {'export': 0,
                                                    'ingest': 0}

        # JSON: per-tier latency quantile keys always present and
        # numeric — zeros for the tier no request used.
        assert set(m['sched']['tiers']) == set(sched_lib.TIERS)
        for tier, block in m['sched']['tiers'].items():
            for key in ('queue_depth', 'queue_tokens', 'admitted',
                        'admitted_tokens', 'admit_share', 'shed_total',
                        'ttft_ms_median', 'ttft_ms_p90',
                        'tpot_ms_median', 'queue_wait_ms_median',
                        'queue_wait_ms_p90'):
                assert key in block, (tier, key)
                assert isinstance(block[key], (int, float)), (tier, key)
        # The default tier served the request above; the other saw
        # nothing and still renders a full (zeroed) block.
        assert m['sched']['tiers']['latency']['admitted'] >= 1
        assert m['sched']['tiers']['throughput']['admitted'] == 0
        assert m['sched']['tiers']['throughput']['ttft_ms_median'] == 0
        assert m['queue_tokens_total'] >= 0
        assert m['sched']['max_queue_tokens'] > 0

        # (c) /debug/requests: the finished request's span timeline.
        with urllib.request.urlopen(
                f'http://127.0.0.1:{port}/debug/requests?limit=8',
                timeout=10) as r:
            traces = json.loads(r.read())['requests']
        assert traces
        ours = next(t for t in traces
                    if t['request_id'] == result['request_id'])
        names = [s['name'] for s in ours['spans']]
        assert names.index('queue') < names.index('prefill') \
            < names.index('decode')
        assert all(s.get('dur_ms', 0) >= 0 for s in ours['spans'])
        assert ours['done']
    finally:
        server.stop()
