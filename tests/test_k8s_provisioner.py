"""Kubernetes (GKE-TPU) provisioner against a fake kubectl: the same
hermetic matrix the GCP provisioner passes (create/query/terminate,
multi-slice gangs, stockout->failover taxonomy, partial-failure cleanup)
— proving the cloud abstraction holds a third implementation
(VERDICT r2 item 6; reference ``sky/provision/kubernetes/``).
"""
import json

import pytest

from skypilot_tpu import exceptions
from skypilot_tpu.provision import common
from skypilot_tpu.provision.kubernetes import instance as k8s_instance
from skypilot_tpu.provision.kubernetes import k8s_client as kc


class FakeK8s:
    """In-memory pods/services + a kubectl-argv interpreter."""

    def __init__(self):
        self.pods = {}        # name -> manifest (with injected status)
        self.services = {}
        self.fail_next_apply = None   # (rc, stderr) injected once
        self.schedulable = True

    # -- kubectl emulation -------------------------------------------
    def runner(self, args, stdin):
        a = list(args)
        # strip --namespace/--context pairs
        flags = {}
        i = 0
        rest = []
        while i < len(a):
            if a[i] in ('--namespace', '--context', '-l'):
                flags[a[i]] = a[i + 1]
                i += 2
            elif a[i].startswith('--'):
                i += 1
            elif a[i] == '-o':
                i += 2
            else:
                rest.append(a[i])
                i += 1
        verb = rest[0] if rest else ''
        if verb == 'apply':
            if self.fail_next_apply is not None:
                rc, err = self.fail_next_apply
                self.fail_next_apply = None
                return rc, '', err
            manifest = json.loads(stdin)
            return self._apply(manifest)
        if verb == 'get':
            return self._get(rest[1:], flags.get('-l'))
        if verb == 'delete':
            return self._delete(rest[1:], flags.get('-l'))
        if verb == 'version':
            return 0, '{"clientVersion": {}}', ''
        return 1, '', f'unknown verb {verb}'

    def _apply(self, manifest):
        kind = manifest['kind']
        name = manifest['metadata']['name']
        if kind == 'Service':
            self.services[name] = manifest
            return 0, json.dumps(manifest), ''
        manifest = json.loads(json.dumps(manifest))    # deep copy
        if self.schedulable:
            idx = len(self.pods)
            manifest['status'] = {
                'phase': 'Running',
                'podIP': f'10.0.0.{idx + 1}',
            }
        else:
            manifest['status'] = {
                'phase': 'Pending',
                'conditions': [{
                    'type': 'PodScheduled', 'status': 'False',
                    'reason': 'Unschedulable',
                    'message': ('0/3 nodes are available: insufficient '
                                'google.com/tpu'),
                }],
            }
        self.pods[name] = manifest
        return 0, json.dumps(manifest), ''

    def _get(self, rest, selector):
        if rest[0] == 'pods':
            items = [p for p in self.pods.values()
                     if self._match(p, selector)]
            return 0, json.dumps({'items': items}), ''
        if rest[0] == 'pod':
            name = rest[1]
            if name in self.pods:
                return 0, json.dumps(self.pods[name]), ''
            return 1, '', f'pods "{name}" not found'
        return 1, '', f'cannot get {rest}'

    def _delete(self, rest, selector):
        if selector is not None:
            for name in [n for n, p in self.pods.items()
                         if self._match(p, selector)]:
                del self.pods[name]
            for name in [n for n, s in self.services.items()
                         if self._match(s, selector)]:
                del self.services[name]
            return 0, '', ''
        if rest[0] == 'pod':
            self.pods.pop(rest[1], None)
            return 0, '', ''
        return 1, '', f'cannot delete {rest}'

    @staticmethod
    def _match(obj, selector):
        if not selector:
            return True
        key, val = selector.split('=', 1)
        return obj['metadata'].get('labels', {}).get(key) == val


@pytest.fixture()
def fake(tmp_path, monkeypatch):
    monkeypatch.setenv('SKYTPU_STATE_DIR', str(tmp_path))
    monkeypatch.setenv('SKYTPU_K8S_SCHEDULE_TIMEOUT', '0.2')
    k8s = FakeK8s()
    kc.set_runner_factory(lambda: k8s.runner)
    yield k8s
    kc.set_runner_factory(None)


def _config(count=1, hosts_per_node=2):
    return common.ProvisionConfig(
        provider_config={'namespace': 'default'},
        node_config={
            'accelerator': 'tpu-v5e-16',
            'generation': 'v5e',
            'num_chips': 16,
            'hosts_per_node': hosts_per_node,
            'chips_per_host': 8,
            'use_spot': False,
        },
        count=count)


def test_create_query_info_terminate(fake):
    rec = k8s_instance.run_instances('kubernetes', None, 'kc', _config())
    assert len(rec.created_instance_ids) == 2
    k8s_instance.wait_instances('kubernetes', 'kc', 'RUNNING')

    st = k8s_instance.query_instances('kubernetes', 'kc')
    assert set(st.values()) == {common.STATUS_RUNNING}

    info = k8s_instance.get_cluster_info('kubernetes', 'kc')
    assert info.num_hosts == 2 and info.num_slices == 1
    assert info.head_instance_id == 'kc-0-0'
    assert all(h.internal_ip for h in info.hosts)
    assert info.chips_per_host == 8
    # GKE node selectors on the pod manifests.
    pod = fake.pods['kc-0-0']
    sel = pod['spec']['nodeSelector']
    assert sel['cloud.google.com/gke-tpu-accelerator'] == \
        'tpu-v5-lite-podslice'
    assert sel['cloud.google.com/gke-tpu-topology'] == '4x4'
    res = pod['spec']['containers'][0]['resources']
    assert res['limits']['google.com/tpu'] == '8'

    k8s_instance.terminate_instances('kubernetes', 'kc')
    assert fake.pods == {} and fake.services == {}
    assert k8s_instance.query_instances('kubernetes', 'kc') == {}


def test_multislice_pods_and_slice_ids(fake):
    k8s_instance.run_instances('kubernetes', None, 'kms',
                               _config(count=2, hosts_per_node=2))
    info = k8s_instance.get_cluster_info('kubernetes', 'kms')
    assert info.num_hosts == 4 and info.num_slices == 2
    assert [h.slice_id for h in
            sorted(info.hosts, key=lambda h: h.rank)] == [0, 0, 1, 1]


def test_unschedulable_maps_to_capacity_error(fake):
    fake.schedulable = False
    k8s_instance.run_instances('kubernetes', None, 'kstock', _config())
    with pytest.raises(exceptions.InsufficientCapacityError) as ei:
        k8s_instance.wait_instances('kubernetes', 'kstock', 'RUNNING')
    assert 'insufficient google.com/tpu' in str(ei.value)
    assert ei.value.blocklist_scope == 'zone'


def test_quota_error_taxonomy(fake):
    fake.fail_next_apply = (1, 'pods "x" is forbidden: exceeded quota')
    with pytest.raises(exceptions.QuotaExceededError):
        k8s_instance.run_instances('kubernetes', None, 'kq', _config())


def test_partial_failure_cleans_up_gang(fake):
    created = []
    orig = fake._apply

    def flaky(manifest):
        if manifest['kind'] == 'Pod' and len(created) == 1:
            return 1, '', 'server error'
        if manifest['kind'] == 'Pod':
            created.append(manifest['metadata']['name'])
        return orig(manifest)

    fake._apply = flaky
    with pytest.raises(exceptions.ProvisionError):
        k8s_instance.run_instances('kubernetes', None, 'kpf',
                                   _config(count=1, hosts_per_node=2))
    # The successfully-created pod of the failed gang was deleted.
    assert fake.pods == {}


def test_stop_unsupported(fake):
    k8s_instance.run_instances('kubernetes', None, 'kstop', _config())
    with pytest.raises(exceptions.NotSupportedError):
        k8s_instance.stop_instances('kubernetes', 'kstop')


def test_terminated_pod_reported(fake):
    k8s_instance.run_instances('kubernetes', None, 'kdead', _config())
    fake.pods['kdead-0-1']['status']['phase'] = 'Failed'
    st = k8s_instance.query_instances('kubernetes', 'kdead')
    assert st['kdead-0-1'] == common.STATUS_TERMINATED
    assert st['kdead-0-0'] == common.STATUS_RUNNING


def test_gke_topology_strings():
    """Pinned GKE node-pool topology values (a wrong selector never
    schedules; VERDICT r4 task 8). Sources: cloud.google.com/tpu docs
    tables; ref sky/provision/kubernetes/utils.py:349-363."""
    cases = [
        ('v5e', 1, '1x1'), ('v5e', 4, '2x2'), ('v5e', 8, '2x4'),
        ('v5e', 16, '4x4'), ('v5e', 32, '4x8'), ('v5e', 64, '8x8'),
        ('v5e', 256, '16x16'),
        ('v6e', 8, '2x4'), ('v6e', 16, '4x4'),
        ('v4', 8, '2x2x2'),        # v4-16 (16 TensorCores = 8 chips)
        ('v4', 16, '2x2x4'), ('v4', 32, '2x4x4'), ('v4', 64, '4x4x4'),
        ('v5p', 4, '2x2x1'), ('v5p', 8, '2x2x2'), ('v5p', 512, '8x8x8'),
    ]
    for gen, chips, want in cases:
        assert k8s_instance.gke_topology(gen, chips, 4) == want, \
            (gen, chips)
    # unknown sizes fail loudly instead of inventing a selector
    import pytest as _pytest
    from skypilot_tpu import exceptions as _exc
    with _pytest.raises(_exc.InvalidResourcesError, match='valid sizes'):
        k8s_instance.gke_topology('v5e', 12, 4)
    with _pytest.raises(_exc.InvalidResourcesError, match='generation'):
        k8s_instance.gke_topology('v9x', 8, 4)


def test_cloud_feasibility_and_provision_config():
    import skypilot_tpu as sky
    from skypilot_tpu.clouds import Kubernetes
    cloud = Kubernetes()
    res = sky.Resources(cloud='kubernetes', accelerators='tpu-v5e-16')
    feasible, hints = cloud.get_feasible_launchable_resources(res)
    assert feasible and not hints
    cfg = cloud.make_provision_config(res, num_nodes=2, cluster_name='c')
    assert cfg.count == 2
    assert cfg.node_config['hosts_per_node'] == 2
    assert cfg.node_config['generation'] == 'v5e'
    assert cloud.instance_type_to_hourly_cost(res, use_spot=False) == 0.0


def test_pod_manifest_image_and_selectors():
    """A task image_id reaches the pod spec; the shipped Dockerfiles
    document the image contract (VERDICT r4 task 8)."""
    m = k8s_instance._pod_manifest(
        'c1', 0, 0, {'accelerator': 'tpu-v5litepod-8',
                     'generation': 'v5e', 'num_chips': 8,
                     'chips_per_host': 4,
                     'image': 'gcr.io/proj/skypilot-tpu-k8s:latest'})
    spec = m['spec']
    assert spec['containers'][0]['image'] == \
        'gcr.io/proj/skypilot-tpu-k8s:latest'
    assert spec['nodeSelector'][
        'cloud.google.com/gke-tpu-accelerator'] == 'tpu-v5-lite-podslice'
    assert spec['nodeSelector'][
        'cloud.google.com/gke-tpu-topology'] == '2x4'


def test_dockerfiles_ship():
    import os
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for name in ('Dockerfile', 'Dockerfile_k8s'):
        path = os.path.join(root, name)
        assert os.path.exists(path), name
        content = open(path, encoding='utf-8').read()
        assert content.startswith('#')
        assert 'FROM ' in content
    assert 'jax[tpu]' in open(os.path.join(root, 'Dockerfile'),
                              encoding='utf-8').read()
