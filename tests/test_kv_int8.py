"""int8 KV cache (``kv_cache_dtype``): the decoupled KV storage knob
and its equivalence contract — greedy decode with int8 KV must match
bf16 KV byte-for-byte on the tiny model across BOTH engines and every
KV write path (monolithic + chunked prefill, decode appends,
speculative masked commits, prefix-cache reuse, preemption recompute).
Fast tier: the per-token byte-cost math every capacity surface rides,
the knob resolution, the pool-stats schema, and one slot smoke; the
engine matrix rides the slow tier with the other engine suites."""
import jax
import pytest

from skypilot_tpu.inference.engine import (InferenceEngine,
                                           kv_token_bytes,
                                           resolve_kv_cache_dtype)
from skypilot_tpu.inference.paged import PagedInferenceEngine
from skypilot_tpu.models import configs, llama


@pytest.fixture(scope='module')
def setup():
    cfg = configs.TINY
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def _greedy(engcls, cfg, params, prompts, n_new, **kw):
    eng = engcls(cfg, params, max_batch=4, max_seq=256,
                 attn_impl='xla', **kw)
    rids = [eng.add_request(list(p), max_new_tokens=n_new)
            for p in prompts]
    done = eng.run_to_completion(horizon=4)
    return [done[r].output for r in rids], eng


# ---------------------------------------------------------------------------
# Fast tier
# ---------------------------------------------------------------------------
def test_resolve_kv_cache_dtype():
    """None/'auto' follows the weight quantize mode (the historical
    coupling); explicit values decouple in either direction."""
    assert resolve_kv_cache_dtype(None, None) == 'bf16'
    assert resolve_kv_cache_dtype(None, 'int8') == 'int8'
    assert resolve_kv_cache_dtype('auto', 'int8') == 'int8'
    assert resolve_kv_cache_dtype('auto', None) == 'bf16'
    assert resolve_kv_cache_dtype('bf16', 'int8') == 'bf16'
    assert resolve_kv_cache_dtype('int8', None) == 'int8'
    with pytest.raises(ValueError):
        resolve_kv_cache_dtype('fp8', None)


def test_kv_token_bytes_math():
    """The ONE per-token byte cost behind pool sizing, prefill caps,
    preemption pressure and the telemetry gauges: int8 rows are codes
    plus a 4-byte fp32 absmax scale. At serving head_dims (128) the
    bf16/int8 ratio clears the 1.8x pool-capacity acceptance bar."""
    cfg = configs.LLAMA3_8B
    bf16 = kv_token_bytes(cfg, quantized=False)
    i8 = kv_token_bytes(cfg, quantized=True)
    assert bf16 == cfg.n_layers * cfg.n_kv_heads * cfg.head_dim * 2 * 2
    assert i8 == cfg.n_layers * cfg.n_kv_heads * (cfg.head_dim + 4) * 2
    assert bf16 / i8 >= 1.8
    # Paged pages cost exactly page_size tokens at this rate — the pool
    # auto-size and the capacity gauges can never drift from it.
    assert PagedInferenceEngine._page_bytes(cfg, 128, True) == i8 * 128
    assert PagedInferenceEngine._page_bytes(cfg, 128, False) == bf16 * 128


def test_kv_pool_stats_schema(setup):
    """Both engines expose the same token-denominated pool schema the
    telemetry gauges and bench read; the paged side is page-granular
    and counts only allocatable pages (page 0 reserved)."""
    cfg, params = setup
    keys = {'kv_cache_dtype', 'pool_token_capacity', 'tokens_used',
            'tokens_free', 'preemptions', 'kv_token_bytes',
            'kv_token_bytes_per_shard', 'kv_shards'}
    slot = InferenceEngine(cfg, params, max_batch=2, max_seq=64,
                           attn_impl='xla', kv_cache_dtype='int8')
    s = slot.kv_pool_stats()
    assert set(s) == keys
    assert s['kv_cache_dtype'] == 'int8' and slot.cache.quantized
    assert s['pool_token_capacity'] == 2 * 64
    assert s['tokens_used'] + s['tokens_free'] == s['pool_token_capacity']
    assert s['kv_token_bytes'] == kv_token_bytes(cfg, True)

    paged = PagedInferenceEngine(cfg, params, max_batch=2, max_seq=64,
                                 page_size=8, attn_impl='xla',
                                 kv_cache_dtype='bf16', quantize='int8')
    p = paged.kv_pool_stats()
    assert set(p) == keys
    # Decoupled: int8 weights, bf16 KV.
    assert p['kv_cache_dtype'] == 'bf16' and not paged.cache.quantized
    assert p['pool_token_capacity'] == (paged.alloc.n_pages - 1) * 8
    assert p['kv_token_bytes'] == kv_token_bytes(cfg, False)


def test_slot_int8_kv_greedy_smoke(setup):
    """Tier-1 smoke: int8 KV greedy decode is byte-identical to bf16
    KV on the slot engine (prefill scatter + decode appends)."""
    cfg, params = setup
    prompts = [[3, 1, 4, 1, 5]]
    bf, _ = _greedy(InferenceEngine, cfg, params, prompts, 8,
                    kv_cache_dtype='bf16')
    i8, eng = _greedy(InferenceEngine, cfg, params, prompts, 8,
                      kv_cache_dtype='int8')
    assert i8 == bf
    assert eng.cache.quantized and eng.kv_cache_dtype == 'int8'


# ---------------------------------------------------------------------------
# Slow tier: the int8-vs-bf16 equivalence matrix
# ---------------------------------------------------------------------------
PROMPTS = [[3, 1, 4, 1, 5], [2, 7, 1, 8, 2, 8, 1, 8],
           [(i * 7 + 3) % 256 for i in range(60)]]
REPETITIVE = [3, 1, 4, 1, 5, 9, 2, 6] * 4


@pytest.mark.slow
class TestKVInt8Equivalence:

    def test_slot_chunked_prefill(self, setup):
        """Chunked prefill quantizes per chunk inside the layer scan;
        per-row absmax makes chunking invisible — byte-identical to
        bf16 KV AND to int8 monolithic prefill."""
        cfg, params = setup
        bf, _ = _greedy(InferenceEngine, cfg, params, PROMPTS, 12,
                        kv_cache_dtype='bf16', prefill_chunk_tokens=16)
        i8, _ = _greedy(InferenceEngine, cfg, params, PROMPTS, 12,
                        kv_cache_dtype='int8', prefill_chunk_tokens=16)
        mono, _ = _greedy(InferenceEngine, cfg, params, PROMPTS, 12,
                          kv_cache_dtype='int8', prefill_chunk_tokens=0)
        assert i8 == bf
        assert i8 == mono

    def test_paged_chunked_prefill(self, setup):
        cfg, params = setup
        bf, _ = _greedy(PagedInferenceEngine, cfg, params, PROMPTS, 12,
                        kv_cache_dtype='bf16', page_size=8, chunk=16)
        i8, eng = _greedy(PagedInferenceEngine, cfg, params, PROMPTS,
                          12, kv_cache_dtype='int8', page_size=8,
                          chunk=16)
        assert i8 == bf
        assert eng.chunks_prefilled >= 4      # 60-token prompt, chunk 16

    def test_speculative_commits(self, setup):
        """speculate_k>0 with int8 KV: the masked KV commit writes
        quantized rows and decode continues off them. Unlike bf16 KV
        (where spec greedy is byte-identical by construction), int8 KV
        rounds at different points in the verify forward (in-window
        rows ride full precision) than in vanilla decode — on the tiny
        random model's near-flat logits an occasional near-tie argmax
        flips. The contract here is bounded divergence: a long exact
        prefix, near-total agreement, nonzero acceptance."""
        cfg, params = setup
        for engcls, kw in ((InferenceEngine, {}),
                           (PagedInferenceEngine, {'page_size': 8})):
            want, _ = _greedy(engcls, cfg, params,
                              [REPETITIVE, PROMPTS[2]], 16,
                              kv_cache_dtype='int8', **kw)
            got, eng = _greedy(engcls, cfg, params,
                               [REPETITIVE, PROMPTS[2]], 16,
                               kv_cache_dtype='int8', speculate_k=4,
                               **kw)
            for a, b in zip(want, got):
                assert a[:10] == b[:10], engcls.__name__
                agree = sum(x == y for x, y in zip(a, b))
                assert agree >= int(0.85 * len(a)), (engcls.__name__,
                                                     a, b)
            assert eng.spec_metrics()['spec_accepted'] > 0

    def test_prefix_cache_reuse(self, setup):
        """A prefix-cache HIT reuses already-quantized pages — the
        second request's decode reads them through the fused-dequant
        kernel and still matches the slot engine's int8 output."""
        cfg, params = setup
        shared = [(i * 5 + 2) % 256 for i in range(64)]
        p1, p2 = shared + [11, 12], shared + [13, 14, 15]
        want, _ = _greedy(InferenceEngine, cfg, params, [p2], 8,
                          kv_cache_dtype='int8')
        eng = PagedInferenceEngine(cfg, params, max_batch=1,
                                   max_seq=256, page_size=8, chunk=16,
                                   attn_impl='xla',
                                   kv_cache_dtype='int8')
        r1 = eng.add_request(p1, max_new_tokens=4)
        eng.run_to_completion(horizon=4)
        assert eng.alloc.prefix_misses == 1
        r2 = eng.add_request(p2, max_new_tokens=8)
        done = eng.run_to_completion(horizon=4)
        assert eng.alloc.prefix_hits >= 1
        assert done[r2].output == want[0]

    def test_preemption_recompute(self, setup):
        """Pool pressure preempts + recomputes with quantized pages and
        the preemption count surfaces through kv_pool_stats (the
        telemetry/bench counter)."""
        cfg, params = setup
        want, _ = _greedy(PagedInferenceEngine, cfg, params, PROMPTS,
                          12, kv_cache_dtype='int8', page_size=8)
        eng = PagedInferenceEngine(cfg, params, max_batch=4,
                                   max_seq=256, page_size=8, n_pages=12,
                                   attn_impl='xla',
                                   kv_cache_dtype='int8')
        rids = [eng.add_request(list(p), max_new_tokens=12)
                for p in PROMPTS]
        done = eng.run_to_completion(horizon=4)
        assert eng.preemptions >= 1
        assert eng.kv_pool_stats()['preemptions'] == eng.preemptions
        assert [done[r].output for r in rids] == want
