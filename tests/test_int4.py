"""int4 fused-dequant weights (``quantize='int4'``).

Contracts pinned here:

- pack/unpack exactness (numpy AND jnp paths, every axis) — the one
  nibble layout (low nibble first, sign-extended, last contracted
  axis) graftcheck GC119 routes everyone to;
- per-channel and ``SKYTPU_INT4_GROUP`` group-wise scale math, and the
  fused ``qeinsum`` contraction matching an explicit
  unpack-dequantize-einsum reference;
- stored-bytes capacity: the quantize-eligible leaves pack to >= 1.8x
  smaller than int8 (0.5x codes + shared scale overhead);
- engine integration: slot + paged greedy smoke, int4 => int4 KV auto
  coupling, chunked == monolithic prefill byte-identity, prefix-cache
  reuse, tp=2 sharded packed codes byte-identical to tp=1;
- THE numerics contract: the int4 engine's greedy output is
  byte-identical to a bf16 engine serving the explicitly DEQUANTIZED
  int4 tree (same int4 KV) — the engine serves exactly the model its
  codes + scales define. (Divergence vs the unquantized bf16 model is
  the quantization error itself — unbounded in principle on
  random-init weights — so equivalence is pinned against the
  quantized model, not the parent.)
"""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu.inference.engine import (InferenceEngine,
                                           prepare_params,
                                           resolve_kv_cache_dtype)
from skypilot_tpu.inference.paged import PagedInferenceEngine
from skypilot_tpu.models import configs, llama
from skypilot_tpu.models import quantization as q

PROMPTS = [[3, 1, 4, 1, 5], [2, 7, 1, 8, 2, 8, 1, 8],
           [(i * 7 + 3) % 256 for i in range(60)]]


@pytest.fixture(scope='module')
def setup():
    cfg = configs.TINY
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def _greedy(engcls, cfg, params, prompts, n_new, **kw):
    eng = engcls(cfg, params, max_batch=4, max_seq=256,
                 attn_impl='xla', **kw)
    rids = [eng.add_request(list(p), max_new_tokens=n_new)
            for p in prompts]
    done = eng.run_to_completion(horizon=4)
    return [done[r].output for r in rids], eng


# ---------------------------------------------------------------------------
# Pack / unpack / quantize math
# ---------------------------------------------------------------------------
def test_pack_unpack_exact_numpy_and_jnp():
    rng = np.random.default_rng(0)
    codes = rng.integers(-7, 8, size=(6, 8, 10)).astype(np.int8)
    for ax in (0, 1, 2, -1):
        packed = q.pack_int4(codes, axis=ax)
        assert isinstance(packed, np.ndarray)
        assert packed.dtype == np.uint8
        assert packed.shape[ax] * 2 == codes.shape[ax] \
            or packed.shape[ax] == codes.shape[ax] // 2
        assert np.array_equal(q.unpack_int4(packed, axis=ax), codes)
    pj = q.pack_int4(jnp.asarray(codes), axis=1)
    assert np.array_equal(np.asarray(q.unpack_int4(pj, axis=1)), codes)
    # Full code range incl. -8 (never produced by quantize, but the
    # sign extension must be total).
    edge = np.arange(-8, 8, dtype=np.int8)
    assert np.array_equal(q.unpack_int4(q.pack_int4(edge)), edge)


def test_pack_odd_axis_raises():
    with pytest.raises(ValueError):
        q.pack_int4(np.zeros((3, 4), np.int8), axis=0)


def _dequant4_np(w4: q.QuantizedWeight4, reduce_axes) -> np.ndarray:
    """Explicit unpack + per-group scale reference (test-local)."""
    ax = reduce_axes[-1]
    codes = q.unpack_int4(np.asarray(w4.packed), axis=ax)
    scale = np.asarray(w4.scale, np.float32)
    rep = np.repeat(scale, codes.shape[ax] // scale.shape[ax], axis=ax)
    return codes.astype(np.float32) * rep


def test_quantize_array4_per_channel():
    rng = np.random.default_rng(1)
    w = jnp.asarray(rng.normal(size=(16, 4, 6)).astype(np.float32))
    w4 = q._quantize_array4(w, (0,))
    assert w4.packed.dtype == jnp.uint8
    assert w4.packed.shape == (8, 4, 6)
    assert w4.scale.shape == (1, 4, 6)
    codes = q.unpack_int4(np.asarray(w4.packed), axis=0)
    assert codes.min() >= -7 and codes.max() <= 7
    err = np.abs(_dequant4_np(w4, (0,)) - np.asarray(w))
    # Bounded by half a quantization step per channel.
    step = np.asarray(w4.scale, np.float32)
    assert (err <= 0.5 * step + 1e-6).all()


def test_group_scale_math(monkeypatch):
    rng = np.random.default_rng(2)
    w = jnp.asarray(rng.normal(size=(16, 4, 6)).astype(np.float32))
    g = q._quantize_array4(w, (0,), group=4)
    assert g.scale.shape == (4, 4, 6)         # G = 16/4 groups
    assert g.packed.shape == (8, 4, 6)
    x = jnp.asarray(rng.normal(size=(2, 3, 16)).astype(np.float32))
    y = q.qeinsum('bsd,dhk->bshk', x, g, out_dtype=jnp.float32)
    ref = np.einsum('bsd,dhk->bshk', np.asarray(x),
                    _dequant4_np(g, (0,)))
    assert np.allclose(np.asarray(y), ref, atol=1e-4)
    # Grouped multi-axis contraction (wo-shape: contract heads + hd).
    w2 = jnp.asarray(rng.normal(size=(4, 6, 16)).astype(np.float32))
    g2 = q._quantize_array4(w2, (0, 1), group=2)
    assert g2.scale.shape == (1, 3, 16)
    x2 = jnp.asarray(rng.normal(size=(2, 3, 4, 6)).astype(np.float32))
    y2 = q.qeinsum('bshk,hkd->bsd', x2, g2, out_dtype=jnp.float32)
    ref2 = np.einsum('bshk,hkd->bsd', np.asarray(x2),
                     _dequant4_np(g2, (0, 1)))
    assert np.allclose(np.asarray(y2), ref2, atol=1e-4)
    # Invalid group sizes fail loudly at quantize time.
    with pytest.raises(ValueError):
        q._quantize_array4(w, (0,), group=3)      # odd
    with pytest.raises(ValueError):
        q._quantize_array4(w, (0,), group=5)      # does not divide
    # The env knob feeds quantize_params.
    monkeypatch.setenv('SKYTPU_INT4_GROUP', '8')
    assert q.int4_group_size() == 8
    cfg = configs.TINY
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    p4 = q.quantize_params(params, mode='int4')
    wq = p4['layers']['wq']
    assert wq.scale.shape[1] == cfg.dim // 8      # grouped along d


def test_qeinsum4_matches_dequant_reference():
    rng = np.random.default_rng(3)
    w = jnp.asarray(rng.normal(size=(16, 4, 6)).astype(np.float32))
    w4 = q._quantize_array4(w, (0,))
    x = jnp.asarray(rng.normal(size=(2, 3, 16)).astype(np.float32))
    y = q.qeinsum('bsd,dhk->bshk', x, w4, out_dtype=jnp.float32)
    ref = np.einsum('bsd,dhk->bshk', np.asarray(x),
                    _dequant4_np(w4, (0,)))
    assert np.allclose(np.asarray(y), ref, atol=1e-4)
    # deq() refuses int4 leaves (packed axis is contraction-specific).
    with pytest.raises(TypeError):
        q.deq(w4)


def test_capacity_ratio_vs_int8(setup):
    """The quantize-eligible leaves (the stream the knob shrinks) pack
    to >= 1.8x smaller than int8 — 0.5x codes + shared scale
    overhead."""
    cfg, params = setup
    p8 = q.quantize_params(params, mode='int8')
    p4 = q.quantize_params(params, mode='int4')

    def quantizable_bytes(tree):
        total = 0
        for key, val in tree['layers'].items():
            if key in q.REDUCE_AXES:
                total += q.quantized_bytes({'x': val})
        if 'unembed' in tree:
            total += q.quantized_bytes({'x': tree['unembed']})
        return total

    ratio = quantizable_bytes(p8) / quantizable_bytes(p4)
    assert ratio >= 1.8, ratio
    # And the whole-tree stored bytes shrink too.
    assert q.quantized_bytes(p4) < q.quantized_bytes(p8)


def test_mode_detection_and_prepare_params(setup):
    cfg, params = setup
    p4 = q.quantize_params(params, mode='int4')
    assert q.quantized_mode(p4) == 'int4'
    assert q.is_quantized(p4)
    assert q.quantized_mode(params) is None
    # prepare_params: on-the-fly int4, and pass-through of a
    # pre-quantized int4 tree (quantize=None resolves to 'int4').
    _, tree, eff = prepare_params(cfg, params, quantize='int4')
    assert eff == 'int4'
    assert isinstance(tree['layers']['wq'], q.QuantizedWeight4)
    _, _, eff2 = prepare_params(cfg, p4, quantize=None)
    assert eff2 == 'int4'
    with pytest.raises(ValueError):
        prepare_params(cfg, params, quantize='int2')
    # int4 weights pull the KV down to int4 under auto (KV round two);
    # an explicit dtype always wins.
    assert resolve_kv_cache_dtype(None, 'int4') == 'int4'
    assert resolve_kv_cache_dtype('bf16', 'int4') == 'bf16'
    assert resolve_kv_cache_dtype('int8', 'int4') == 'int8'


def test_moe_leaves_stay_int8():
    """int4 mode quantizes the dense leaves to packed nibbles; MoE
    expert leaves (deq()-consumed in models/moe.py) stay int8."""
    cfg = configs.TINY_MOE
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    p4 = q.quantize_params(params, mode='int4')
    assert isinstance(p4['layers']['wq'], q.QuantizedWeight4)
    assert isinstance(p4['layers']['moe_gate'], q.QuantizedWeight)
    # And the engine serves it.
    outs, _ = _greedy(InferenceEngine, cfg, p4, [[1, 2, 3]], 4)
    assert len(outs[0]) == 4


def test_engine_greedy_smoke(setup):
    """Tier-1 smoke: both engines serve int4 weights (auto int4 KV —
    KV round two) and agree byte-for-byte with each other."""
    cfg, params = setup
    slot, seng = _greedy(InferenceEngine, cfg, params, PROMPTS, 8,
                         quantize='int4')
    paged, peng = _greedy(PagedInferenceEngine, cfg, params, PROMPTS,
                          8, quantize='int4', page_size=8, chunk=16)
    assert slot == paged
    assert seng.kv_cache_dtype == 'int4' and seng.cache.packed
    assert peng.kv_cache_dtype == 'int4' and peng.cache.packed
    assert isinstance(seng.params['layers']['w_up'],
                      q.QuantizedWeight4)


# ---------------------------------------------------------------------------
# Slow tier: equivalence matrix
# ---------------------------------------------------------------------------
def _dequantized_tree(cfg, p4):
    """bf16 tree carrying exactly the int4 model's values."""
    def leaf(key, v):
        if isinstance(v, q.QuantizedWeight4):
            return jnp.asarray(
                _dequant4_np(v, q.REDUCE_AXES[key]).astype(cfg.dtype))
        if isinstance(v, q.QuantizedWeight):
            return jnp.asarray(
                (np.asarray(v.int8, np.float32)
                 * np.asarray(v.scale, np.float32)).astype(cfg.dtype))
        return v

    out = {}
    for k, v in p4.items():
        if k == 'layers':
            out[k] = {kk: leaf(kk, vv) for kk, vv in v.items()}
        else:
            out[k] = leaf(k, v)
    return out


@pytest.mark.slow
class TestInt4Equivalence:

    def test_engine_matches_dequantized_reference(self, setup):
        """THE int4 numerics contract: the fused-dequant engine output
        is byte-identical to a bf16 engine serving the explicitly
        dequantized int4 tree — chunked prefill included. The engine
        serves exactly the model its codes + scales define.

        Pinned at int8 KV. The fused path folds the per-channel scale
        into the fp32 dot OUTPUT while the dequantized tree rounds
        every weight to bf16 first — sub-ULP projection differences by
        construction. int8's 1/127 KV grid absorbs them; int4's 1/7
        grid flips a code and the flip compounds, so at int4 KV the
        cross-representation pin is first-token agreement (byte
        identity WITHIN a representation is pinned in
        test_kv_round2.TestKVInt4Equivalence)."""
        cfg, params = setup
        p4 = q.quantize_params(params, mode='int4')
        ref_tree = _dequantized_tree(cfg, p4)
        for engcls, kw in ((InferenceEngine,
                            {'prefill_chunk_tokens': 16}),
                           (PagedInferenceEngine,
                            {'page_size': 8, 'chunk': 16})):
            got, _ = _greedy(engcls, cfg, params, PROMPTS, 16,
                             quantize='int4', kv_cache_dtype='int8',
                             **kw)
            want, _ = _greedy(engcls, cfg, ref_tree, PROMPTS, 16,
                              kv_cache_dtype='int8', **kw)
            assert got == want, engcls.__name__
            # int4 KV (the quantize='int4' auto-coupling): the two
            # weight representations serve the same model through the
            # coarse KV grid — first tokens agree, completions finish.
            g4, _ = _greedy(engcls, cfg, params, PROMPTS, 16,
                            quantize='int4', **kw)
            w4, _ = _greedy(engcls, cfg, ref_tree, PROMPTS, 16,
                            kv_cache_dtype='int4', **kw)
            for a, b in zip(g4, w4):
                assert a[0] == b[0] and len(a) == len(b) == 16

    def test_chunked_equals_monolithic(self, setup):
        cfg, params = setup
        mono, _ = _greedy(InferenceEngine, cfg, params, PROMPTS, 12,
                          quantize='int4', prefill_chunk_tokens=0)
        chunked, _ = _greedy(InferenceEngine, cfg, params, PROMPTS, 12,
                             quantize='int4', prefill_chunk_tokens=16)
        assert chunked == mono

    def test_prefix_cache_reuse(self, setup):
        """A prefix HIT reuses pages written under int4 weights; the
        continuation matches the slot engine's int4 output."""
        cfg, params = setup
        shared = [(i * 5 + 2) % 256 for i in range(64)]
        p1, p2 = shared + [11, 12], shared + [13, 14, 15]
        want, _ = _greedy(InferenceEngine, cfg, params, [p2], 8,
                          quantize='int4')
        eng = PagedInferenceEngine(cfg, params, max_batch=1,
                                   max_seq=256, page_size=8, chunk=16,
                                   attn_impl='xla', quantize='int4')
        eng.add_request(p1, max_new_tokens=4)
        eng.run_to_completion(horizon=4)
        assert eng.alloc.prefix_misses == 1
        r2 = eng.add_request(p2, max_new_tokens=8)
        done = eng.run_to_completion(horizon=4)
        assert eng.alloc.prefix_hits >= 1
        assert done[r2].output == want[0]

    def test_tp2_sharded_packed_codes(self, setup, tp_devices):
        """tp=2: packed nibble codes shard like their parents and the
        sharded engine's output — and the resident packed bytes — are
        byte-identical to tp=1."""
        from skypilot_tpu.parallel import mesh as mesh_lib
        from skypilot_tpu.utils.host import host_sync
        cfg, params = setup
        o1, e1 = _greedy(PagedInferenceEngine, cfg, params,
                         PROMPTS[:2], 8, quantize='int4',
                         prefill_chunk_tokens=16)
        o2, e2 = _greedy(PagedInferenceEngine, cfg, params,
                         PROMPTS[:2], 8, quantize='int4',
                         prefill_chunk_tokens=16,
                         mesh=mesh_lib.serving_mesh(tp=2))
        assert o1 == o2
        for key in ('wq', 'w_down'):
            a = np.asarray(host_sync(e1.params['layers'][key].packed))
            b = np.asarray(host_sync(e2.params['layers'][key].packed))
            assert a.dtype == np.uint8
            assert np.array_equal(a, b), key


@pytest.mark.slow
def test_load_checkpoint_int4(tmp_path, setup):
    """Host-side int4 quantization during checkpoint load: packed
    leaves byte-identical to the on-device quantizer's, the
    ``.int4_cache.bin`` round-trips, and the loaded tree serves."""
    from skypilot_tpu.models import weights
    cfg, params = setup
    path = str(tmp_path / 'ckpt')
    weights.save_hf_checkpoint(path, cfg, params)
    # fp32 load: checkpoint values, host scales and the on-device
    # comparison tree all share one dtype, so the host quantizer must
    # match the device quantizer BYTE-FOR-BYTE (same rounded-scale
    # contract, same round-half-even).
    cfg2, loaded = weights.load_checkpoint(path, dtype=jnp.float32,
                                           quantize='int4')
    wq = loaded['layers']['wq']
    assert isinstance(wq, q.QuantizedWeight4)
    fp32 = {k: (v if k != 'layers' else
                {kk: jnp.asarray(np.asarray(vv), jnp.float32)
                 if kk in q.REDUCE_AXES else vv
                 for kk, vv in v.items()})
            for k, v in params.items()}
    dev = q.quantize_params(
        {**fp32, 'layers': {**fp32['layers']}}, mode='int4')
    assert np.array_equal(np.asarray(wq.packed),
                          np.asarray(dev['layers']['wq'].packed))
    assert np.array_equal(np.asarray(wq.scale),
                          np.asarray(dev['layers']['wq'].scale))
    # Cache round-trip: second load reads .int4_cache.bin.
    assert (tmp_path / 'ckpt' / '.int4_cache.bin').exists()
    _, cached = weights.load_checkpoint(path, dtype=jnp.float32,
                                        quantize='int4')
    assert np.array_equal(np.asarray(cached['layers']['wq'].packed),
                          np.asarray(wq.packed))
    outs, _ = _greedy(InferenceEngine, cfg2, loaded, [[1, 2, 3]], 4)
    assert len(outs[0]) == 4
