"""LoRA fine-tuning tests (virtual 8-device CPU mesh).

Reference capability anchor: ``llm/llama-3_1-finetuning/lora.yaml``
(torchtune LoRA recipe); here the adapters are in-tree (models/lora.py)
and trained by the pjit trainer with a frozen base.
"""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu.models import configs, llama, lora
from skypilot_tpu.parallel import mesh as mesh_lib
from skypilot_tpu.train.trainer import TrainConfig, Trainer

pytestmark = pytest.mark.slow

TINY_LORA = dataclasses.replace(
    configs.TINY, lora_rank=4, lora_alpha=8.0,
    lora_targets=('wq', 'wk', 'wv', 'wo', 'w_gate', 'w_up', 'w_down'))


def _batch(rng, b=8, s=16, vocab=250):
    toks = jax.random.randint(rng, (b, s + 1), 0, vocab)
    return {'inputs': toks[:, :-1].astype(jnp.int32),
            'targets': toks[:, 1:].astype(jnp.int32)}


class TestAdapterMath:

    def test_zero_init_delta(self):
        """b = 0 at init => adapted forward == base forward exactly."""
        base = llama.init_params(jax.random.PRNGKey(0), configs.TINY)
        adapted = llama.init_params(jax.random.PRNGKey(0), TINY_LORA)
        toks = jnp.arange(16, dtype=jnp.int32)[None, :] % 250
        lb, _ = llama.forward(base, toks, configs.TINY)
        la, _ = llama.forward(adapted, toks, TINY_LORA)
        np.testing.assert_array_equal(np.asarray(lb), np.asarray(la))

    def test_merge_matches_unmerged(self):
        """After perturbing b, merged weights reproduce the low-rank
        path (the serving contract). fp32 so the comparison is tight —
        in bf16 the fold adds one rounding of (W + delta)."""
        f32 = dataclasses.replace(TINY_LORA, dtype=jnp.float32)
        params = llama.init_params(jax.random.PRNGKey(0), f32)
        lt = lora.split_lora(params)
        keys = iter(jax.random.split(jax.random.PRNGKey(7), 20))
        lt = jax.tree.map(
            lambda x: x + 0.05 * jax.random.normal(next(keys), x.shape,
                                                   x.dtype), lt)
        params = lora.with_lora(params, lt)
        toks = jnp.arange(16, dtype=jnp.int32)[None, :] % 250
        unmerged, _ = llama.forward(params, toks, f32)
        mcfg, mparams = lora.merge(f32, params)
        assert mcfg.lora_rank == 0
        assert 'lora' not in mparams['layers']
        merged, _ = llama.forward(mparams, toks, mcfg)
        np.testing.assert_allclose(np.asarray(unmerged),
                                   np.asarray(merged), atol=1e-4)
        # and the delta is genuinely nonzero
        f32_base = dataclasses.replace(configs.TINY, dtype=jnp.float32)
        base_only, _ = llama.forward(
            llama.init_params(jax.random.PRNGKey(0), f32_base),
            toks, f32_base)
        assert not np.allclose(np.asarray(merged), np.asarray(base_only),
                               atol=1e-3)

    def test_moe_mlp_targets_rejected(self):
        bad = dataclasses.replace(configs.TINY_MOE, lora_rank=4,
                                  lora_targets=('wq', 'w_up'))
        with pytest.raises(ValueError, match='dense FFN'):
            lora.resolve_targets(bad)

    def test_unknown_target_rejected(self):
        bad = dataclasses.replace(configs.TINY, lora_rank=4,
                                  lora_targets=('wx',))
        with pytest.raises(ValueError, match='unknown LoRA target'):
            lora.resolve_targets(bad)


class TestLoraTraining:

    def test_base_frozen_adapters_move_loss_drops(self):
        trainer = Trainer(TINY_LORA,
                          mesh_spec=mesh_lib.MeshSpec(dp=8),
                          train_config=TrainConfig(learning_rate=5e-2,
                                                   warmup_steps=2,
                                                   total_steps=40,
                                                   attn_impl='xla'))
        state = trainer.init(jax.random.PRNGKey(0))
        base_before = jax.tree.map(
            np.asarray, {k: v for k, v in state.params['layers'].items()
                         if k != 'lora'})
        embed_before = np.asarray(state.params['embed'])
        rng = jax.random.PRNGKey(1)
        batch = _batch(rng)                    # one batch: overfit it
        first = last = None
        for _ in range(30):
            state, metrics = trainer.step(state, batch)
            last = float(metrics['loss'])
            if first is None:
                first = last
        assert last < first * 0.9, (first, last)
        # Base exactly untouched (bit-for-bit), adapters moved.
        np.testing.assert_array_equal(embed_before,
                                      np.asarray(state.params['embed']))
        for k, v in base_before.items():
            jax.tree.map(
                lambda a, b: np.testing.assert_array_equal(a,
                                                           np.asarray(b)),
                v, state.params['layers'][k])
        b_leaf = np.asarray(state.params['layers']['lora']['wq']['b'])
        assert np.abs(b_leaf).max() > 0

    def test_optimizer_state_is_adapter_sized(self):
        trainer = Trainer(TINY_LORA, mesh_spec=mesh_lib.MeshSpec(dp=8),
                          train_config=TrainConfig(attn_impl='xla'))
        state = trainer.init(jax.random.PRNGKey(0))
        opt_elems = sum(x.size for x in jax.tree.leaves(state.opt_state)
                        if hasattr(x, 'size'))
        param_elems = sum(x.size for x in jax.tree.leaves(state.params))
        lora_elems = sum(
            x.size for x in jax.tree.leaves(
                lora.split_lora(state.params)))
        # mu + nu (+ a few scalars): ~2x the adapters, nowhere near 2x
        # the full params.
        assert opt_elems < 2 * lora_elems + 64
        assert opt_elems < param_elems

    def test_tp_mesh_step_matches_dp_mesh(self):
        tc = TrainConfig(learning_rate=1e-2, warmup_steps=1,
                         total_steps=10, attn_impl='xla')
        batch = _batch(jax.random.PRNGKey(3))
        losses = []
        for spec in (mesh_lib.MeshSpec(dp=8),
                     mesh_lib.MeshSpec(tp=2, fsdp=2, dp=2)):
            trainer = Trainer(TINY_LORA, mesh_spec=spec, train_config=tc)
            state = trainer.init(jax.random.PRNGKey(0))
            state, m = trainer.step(state, batch)
            state, m = trainer.step(state, batch)
            losses.append(float(m['loss']))
        assert abs(losses[0] - losses[1]) < 1e-3, losses

    def test_adapter_checkpoint_roundtrip(self, tmp_path):
        trainer = Trainer(TINY_LORA, mesh_spec=mesh_lib.MeshSpec(dp=8),
                          train_config=TrainConfig(learning_rate=5e-2,
                                                   warmup_steps=1,
                                                   total_steps=10,
                                                   attn_impl='xla'))
        state = trainer.init(jax.random.PRNGKey(0))
        state, _ = trainer.step(state, _batch(jax.random.PRNGKey(4)))
        trainer.save_adapter(str(tmp_path / 'adapter'), state)
        fresh = trainer.init(jax.random.PRNGKey(9))
        restored = trainer.load_adapter(str(tmp_path / 'adapter'), fresh)
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                       np.asarray(b)),
            lora.split_lora(state.params),
            lora.split_lora(restored.params))
        # base of `fresh` untouched by the adapter swap
        np.testing.assert_array_equal(
            np.asarray(fresh.params['embed']),
            np.asarray(restored.params['embed']))
        # sidecar metadata guards against a mis-configured serve-side
        # trainer (wrong alpha would silently mis-scale the fold)
        wrong = Trainer(dataclasses.replace(TINY_LORA, lora_alpha=999.0),
                        mesh_spec=mesh_lib.MeshSpec(dp=8),
                        train_config=TrainConfig(attn_impl='xla'))
        with pytest.raises(ValueError, match='mis-scale'):
            wrong.load_adapter(str(tmp_path / 'adapter'),
                               wrong.init(jax.random.PRNGKey(0)))


class TestLoraServing:

    def test_engine_auto_merges(self):
        """Both engines accept a LoRA param tree and serve its merged
        model."""
        from skypilot_tpu.inference.engine import InferenceEngine
        from skypilot_tpu.inference.paged import PagedInferenceEngine
        params = llama.init_params(jax.random.PRNGKey(0), TINY_LORA)
        lt = lora.split_lora(params)
        keys = iter(jax.random.split(jax.random.PRNGKey(7), 20))
        lt = jax.tree.map(
            lambda x: x + 0.05 * jax.random.normal(next(keys), x.shape,
                                                   x.dtype), lt)
        params = lora.with_lora(params, lt)
        mcfg, mparams = lora.merge(TINY_LORA, params)

        outs = []
        for cls in (InferenceEngine, PagedInferenceEngine):
            eng = cls(TINY_LORA, params, max_batch=2, max_seq=64,
                      attn_impl='xla')
            assert eng.cfg.lora_rank == 0
            rid = eng.add_request([1, 2, 3, 4], max_new_tokens=5)
            outs.append(eng.run_to_completion(horizon=4)[rid].output)
        ref_eng = InferenceEngine(mcfg, mparams, max_batch=2, max_seq=64,
                                  attn_impl='xla')
        rid = ref_eng.add_request([1, 2, 3, 4], max_new_tokens=5)
        ref = ref_eng.run_to_completion(horizon=4)[rid].output
        assert outs[0] == ref and outs[1] == ref, (outs, ref)

    def test_stock_config_with_adapters_rejected(self):
        """A trainer checkpoint served with the stock base config must
        fail loudly, not fold with a guessed (wrong) scale."""
        params = llama.init_params(jax.random.PRNGKey(0), TINY_LORA)
        with pytest.raises(ValueError, match='lora_rank'):
            lora.merge(configs.TINY, params)
        wrong_rank = dataclasses.replace(TINY_LORA, lora_rank=8)
        with pytest.raises(ValueError, match='adapter rank'):
            lora.merge(wrong_rank, params)

    def test_merge_rejects_quantized_base(self):
        from skypilot_tpu.models import quantization
        params = llama.init_params(jax.random.PRNGKey(0), TINY_LORA)
        qparams = quantization.quantize_params(params)
        with pytest.raises(ValueError, match='int8'):
            lora.merge(TINY_LORA, qparams)
