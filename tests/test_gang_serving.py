"""Multi-host gang serving (round 11): replicas as process gangs that
launch, drain, checkpoint, and die together.

The contract under test is **gang atomicity**: a gang presents exactly
one routable endpoint (rank 0), becomes READY only when every rank
passed the barrier within the join timeout, fans drain/checkpoint out
to every rank and completes them only on all-rank ack, and fails AS A
WHOLE the moment any rank dies — with the LB's in-flight recovery
holding the zero-lost, byte-identical-continuation contract across the
gang's death. On CPU the gang runs the ``replicated`` data plane: every
rank holds a full model copy, replays rank 0's op log, and lockstep is
verified byte-exactly through finished-request digests.
"""
import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import jax
import pytest

from skypilot_tpu import telemetry
from skypilot_tpu.serve import faults as faults_lib
from skypilot_tpu.serve import gang as gang_lib
from skypilot_tpu.utils import common_utils

jax.config.update('jax_platforms', 'cpu')

_FAST = dict(max_batch=2, max_seq=160)


def _leader_spec(world=2, **kw):
    kw.setdefault('join_timeout_s', 180.0)
    kw.setdefault('heartbeat_s', 0.05)
    # Generous default: a follower applying a step op that still
    # COMPILES can legitimately go seconds between heartbeats on CPU;
    # the kill test warms the compile caches first and then tightens
    # this to get fast, deliberate detection.
    kw.setdefault('heartbeat_timeout_s', 60.0)
    return gang_lib.GangSpec(gang_id=kw.pop('gang_id', 'g-test'),
                             rank=0, world=world, **kw)


def _follower_spec(coordinator, rank=1, world=2, **kw):
    kw.setdefault('join_timeout_s', 60.0)
    kw.setdefault('heartbeat_s', 0.05)
    kw.setdefault('heartbeat_timeout_s', 10.0)
    return gang_lib.GangSpec(gang_id=kw.pop('gang_id', 'g-test'),
                             rank=rank, world=world,
                             coordinator=coordinator, **kw)


def _start_leader(port, **gang_kw):
    from skypilot_tpu.serve.server import ModelServer
    srv = ModelServer('tiny', port=port, gang=_leader_spec(**gang_kw),
                      **_FAST)
    srv.start(block=False)
    return srv


def _start_thread_follower(coordinator, *, faults=None, **kw):
    """An in-process follower rank with its own (identical) engine —
    the fast-path stand-in for a separate OS process; the protocol,
    op replay, and failure modes are exactly the process ones."""
    from skypilot_tpu.serve.server import build_engine
    engine = build_engine('tiny', **_FAST)
    follower = gang_lib.GangFollower(_follower_spec(coordinator, **kw),
                                     engine, faults=faults)

    def run():
        try:
            follower.run()
        except faults_lib.InjectedFault:
            pass          # simulated process death: heartbeats stop

    t = threading.Thread(target=run, daemon=True)
    t.start()
    return follower, t


def _await_barrier(srv, timeout=60):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if srv._gang is not None and srv._gang.all_joined:
            return True
        if srv._error is not None:
            return False
        time.sleep(0.05)
    return False


def _generate(base, payload, timeout=180, headers=None):
    h = {'Content-Type': 'application/json'}
    h.update(headers or {})
    req = urllib.request.Request(base + '/generate',
                                 json.dumps(payload).encode(), h)
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


class _FakeController:
    """Answers the LB's sync POST with a fixed ready-replica list
    (the gang's rank-0 URL only — followers are never routable)."""

    def __init__(self, replica_urls):
        import http.server as hs
        outer_urls = list(replica_urls)

        class H(hs.BaseHTTPRequestHandler):
            timeout = 30

            def log_message(self, *a):
                del a

            def do_POST(self):  # noqa: N802
                body = json.dumps({
                    'ready_replica_urls': outer_urls,
                    'retry_after_s': 5,
                }).encode()
                self.send_response(200)
                self.send_header('Content-Length', str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.port = common_utils.find_free_port(22450)
        self.httpd = hs.ThreadingHTTPServer(('127.0.0.1', self.port), H)
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()

    @property
    def url(self):
        return f'http://127.0.0.1:{self.port}'

    def stop(self):
        self.httpd.shutdown()


# ---------------------------------------------------------- env contract
def test_gang_spec_env_contract(monkeypatch):
    monkeypatch.setenv(gang_lib.ENV_RANK, '2')
    monkeypatch.setenv(gang_lib.ENV_WORLD, '4')
    monkeypatch.setenv(gang_lib.ENV_COORDINATOR, 'http://h0:8081')
    monkeypatch.setenv(gang_lib.ENV_GANG_ID, 'svc-gang-7')
    monkeypatch.setenv(gang_lib.ENV_JOIN_TIMEOUT, '33')
    monkeypatch.setenv(gang_lib.ENV_HEARTBEAT, '0.2')
    spec = gang_lib.GangSpec.from_env()
    assert (spec.rank, spec.world) == (2, 4)
    assert spec.is_gang and not spec.is_leader
    assert spec.coordinator == 'http://h0:8081'
    assert spec.gang_id == 'svc-gang-7'
    assert spec.join_timeout_s == 33.0
    assert spec.heartbeat_s == 0.2
    assert spec.heartbeat_timeout_s == 2.0      # 10x heartbeat default
    # Explicit args override the env.
    spec = gang_lib.GangSpec.from_env(rank=0, world=1)
    assert not spec.is_gang
    # A nonzero rank with no coordinator is a broken launch.
    monkeypatch.delenv(gang_lib.ENV_COORDINATOR)
    with pytest.raises(ValueError, match='SKYTPU_COORDINATOR'):
        gang_lib.GangSpec.from_env()
    with pytest.raises(ValueError, match='out of range'):
        gang_lib.GangSpec.from_env(rank=5, world=2,
                                   coordinator='http://h0:1')


def test_gang_spec_service_plumbing(monkeypatch, tmp_path):
    """service spec ``parallelism.hosts`` -> placement plan ->
    per-rank launch env on the replica manager's gang tasks."""
    from skypilot_tpu.serve import placement
    from skypilot_tpu.serve.replica_managers import (ReplicaInfo,
                                                     ReplicaManager)
    from skypilot_tpu.serve.service_spec import SkyServiceSpec
    monkeypatch.setenv('SKYTPU_SERVE_DIR', str(tmp_path / 'serve'))
    spec = SkyServiceSpec.from_yaml_config(
        {'readiness_probe': '/readiness', 'parallelism': {'hosts': 3}})
    assert spec.gang_hosts == 3
    assert spec.to_yaml_config()['parallelism'] == {'hosts': 3}
    assert placement.plan_for_spec(spec).hosts == 3
    mgr = ReplicaManager('gang-env-test', spec, {})
    leader = ReplicaInfo(1, 'c1', 1, False, 10001, gang_id='g',
                         gang_rank=0, gang_world=3)
    follower = ReplicaInfo(2, 'c2', 1, False, 10002, gang_id='g',
                           gang_rank=1, gang_world=3)
    follower.coordinator = 'http://10.0.0.1:10001'
    env0 = mgr._replica_task(leader).envs
    env1 = mgr._replica_task(follower).envs
    assert env0['SKYTPU_GANG_ID'] == 'g' and env0['SKYTPU_RANK'] == '0'
    assert env0['SKYTPU_WORLD'] == '3'
    assert 'SKYTPU_COORDINATOR' not in env0
    assert env1['SKYTPU_RANK'] == '1'
    assert env1['SKYTPU_COORDINATOR'] == 'http://10.0.0.1:10001'
    assert float(env1['SKYTPU_GANG_JOIN_TIMEOUT']) > 0
    # Gangs and disaggregation cannot combine.
    from skypilot_tpu import exceptions
    with pytest.raises(exceptions.InvalidServiceSpecError,
                       match='gang'):
        SkyServiceSpec.from_yaml_config({
            'readiness_probe': '/readiness',
            'parallelism': {'hosts': 2},
            'disaggregation': {'prefill_replicas': 1,
                               'decode_replicas': 1}})


# ----------------------------------------------------- coordinator units
def test_coordinator_protocol_and_trim():
    """Op-log slicing stays correct across trims (the response base is
    captured before the trim advances), commands pin the log index,
    and acks require every rank."""
    spec = gang_lib.GangSpec(gang_id='g', rank=0, world=3,
                             join_timeout_s=10, heartbeat_s=0.05,
                             heartbeat_timeout_s=1.0)
    coord = gang_lib.GangCoordinator(spec)
    assert not coord.all_joined
    for i in range(4):
        coord.append_op({'k': 'step', 'h': 8, 'i': i})
    r1 = coord.sync(1, 0, [], {})
    assert not coord.all_joined          # rank 2 still missing
    r2 = coord.sync(2, 0, [], {})
    assert coord.all_joined
    assert [op['i'] for op in r1['ops']] == [0, 1, 2, 3]
    assert r1['base'] == 0 and r2['base'] == 0
    # Rank 1 applies everything; rank 2 lags at 2. The trim must only
    # advance past the SLOWEST rank, and rank 2's next slice must
    # resume exactly at its applied index.
    coord.sync(1, 4, [], {})
    r2 = coord.sync(2, 2, [], {})
    assert r2['base'] == 2
    assert [op['i'] for op in r2['ops']] == [2, 3]
    # Command ack: pinned at the current log index; acked only once
    # EVERY rank acked.
    cid = coord.command('drain')
    assert not coord.acked(cid)
    coord.sync(1, 4, [cid], {})
    assert not coord.acked(cid)          # rank 2 has not acked
    coord.sync(2, 4, [cid], {})
    assert coord.acked(cid)
    assert coord.wait_acked(cid, timeout=0.1)
    st = coord.status()
    assert st['barrier'] and st['world'] == 3 and st['ops'] == 4


def test_coordinator_failure_causes():
    clock = [0.0]
    spec = gang_lib.GangSpec(gang_id='g', rank=0, world=2,
                             join_timeout_s=5.0, heartbeat_s=0.1,
                             heartbeat_timeout_s=1.0)
    coord = gang_lib.GangCoordinator(spec, clock=lambda: clock[0])
    coord.check()                        # inside the join window
    clock[0] = 6.0
    with pytest.raises(gang_lib.GangFailure) as ei:
        coord.check()                    # nobody joined in time
    assert ei.value.cause == 'join_timeout'
    coord2 = gang_lib.GangCoordinator(spec, clock=lambda: clock[0])
    coord2.sync(1, 0, [], {})
    coord2.check()                       # fresh heartbeat
    clock[0] += 2.0
    with pytest.raises(gang_lib.GangFailure) as ei:
        coord2.check()
    assert ei.value.cause == 'heartbeat_lost'
    # Divergence: a follower's finished digest mismatching rank 0's
    # fails the gang immediately.
    coord3 = gang_lib.GangCoordinator(spec, clock=lambda: clock[0])
    coord3.digest.finished[7] = 'aaaa'
    resp = coord3.sync(1, 0, [], {'7': 'bbbb'})
    assert 'diverged' in resp['failed']
    with pytest.raises(gang_lib.GangFailure) as ei:
        coord3.check()
    assert ei.value.cause == 'divergence'
    # A failed gang tells every syncing rank to self-terminate.
    coord2.fail('gang is dead')
    assert coord2.sync(1, 5, [], {})['failed'] == 'gang is dead'


def test_gang_fault_rules_rank_targeted():
    inj = faults_lib.FaultInjector({'rules': [
        {'kind': 'replica_crash', 'site': 'gang_member_crash',
         'rank': 1, 'at': 2}]})
    # Rank 2's invocations advance the site counter but never match.
    assert inj.fire('gang_member_crash', rank=2) is None
    assert inj.fire('gang_member_crash', rank=1) is not None  # 2nd
    assert inj.fire('gang_member_crash', rank=1) is None
    with pytest.raises(ValueError, match='unknown fault site'):
        faults_lib.make_injector({'rules': [
            {'kind': 'replica_crash', 'site': 'gang_sneeze'}]})


# ----------------------------------------------------- 2-process gang e2e
def test_two_process_gang_boot_barrier_byte_identical():
    """THE acceptance path: a real 2-process gang (rank 1 is a
    separate OS process running the follower entry) boots, passes the
    barrier, serves — and its greedy decode output is byte-identical
    to the equivalent single-process server on CPU."""
    port = common_utils.find_free_port(22000)
    srv = _start_leader(port, gang_id='g-2proc')
    base = f'http://127.0.0.1:{port}'
    proc = None
    try:
        assert srv._ready.wait(300)
        # Pre-barrier: the replica is NOT servable (a partial gang
        # must never enter rotation).
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(base + '/readiness', timeout=10)
        assert ei.value.code == 503
        assert json.loads(ei.value.read())['status'] == 'gang_joining'
        env = dict(os.environ, JAX_PLATFORMS='cpu',
                   SKYTPU_GANG_HEARTBEAT='0.05')
        proc = subprocess.Popen(
            [sys.executable, '-m', 'skypilot_tpu.serve.server',
             '--model', 'tiny', '--max-batch', '2', '--max-seq', '160',
             '--gang-rank', '1', '--gang-world', '2',
             '--gang-coordinator', base, '--gang-id', 'g-2proc'],
            env=env, stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL)
        assert _await_barrier(srv, timeout=240), srv._error
        with urllib.request.urlopen(base + '/readiness',
                                    timeout=10) as r:
            ready = json.loads(r.read())
        assert ready['status'] == 'ready'
        assert ready['gang']['world'] == 2 and ready['gang']['barrier']
        # Byte-identity vs the equivalent single-process server.
        port2 = common_utils.find_free_port(22100)
        from skypilot_tpu.serve.server import ModelServer
        ref = ModelServer('tiny', port=port2, **_FAST)
        ref.start(block=False)
        try:
            assert ref._ready.wait(300)
            prompt, gen = [3, 1, 4, 1, 5], 24
            out_gang = _generate(base, {'prompt': prompt,
                                        'max_new_tokens': gen})
            out_ref = _generate(f'http://127.0.0.1:{port2}',
                                {'prompt': prompt,
                                 'max_new_tokens': gen})
            assert out_gang['tokens'] == out_ref['tokens']
        finally:
            ref.stop()
        # Telemetry: the barrier was observed and gang_size is live.
        reg = telemetry.get_registry()
        assert reg.histogram('skytpu_gang_join_seconds').count >= 1
        assert reg.gauge('skytpu_gang_size').value == 2
        assert srv._error is None
    finally:
        srv.stop()
        if proc is not None:
            try:
                assert proc.wait(timeout=60) == 0   # clean shutdown
            except subprocess.TimeoutExpired:
                proc.kill()
                raise


# -------------------------------------------------------- drain ordering
def test_gang_drain_ack_ordering():
    """'Gang drained' means every rank applied everything up to the
    drain command's pinned op-log index — a lagging follower holds the
    drain open; its catch-up ack completes it."""
    port = common_utils.find_free_port(22200)
    srv = _start_leader(port, gang_id='g-drain')
    base = f'http://127.0.0.1:{port}'

    def sync(rank, applied, acks):
        req = urllib.request.Request(
            base + '/gang/sync',
            data=json.dumps({'rank': rank, 'gang_id': 'g-drain',
                             'applied': applied,
                             'acks': acks}).encode(),
            headers={'Content-Type': 'application/json'})
        with urllib.request.urlopen(req, timeout=10) as r:
            return json.loads(r.read())

    try:
        assert srv._ready.wait(300)
        sync(1, 0, [])                   # join (barrier completes)
        assert _await_barrier(srv, timeout=30)
        # Serve one request so the op log is non-empty.
        _generate(base, {'prompt': [2, 7, 1], 'max_new_tokens': 8})
        # Start the drain: the leader side drains immediately (no
        # in-flight work), but the GANG is not drained until rank 1
        # acks at the pinned index.
        status = json.loads(urllib.request.urlopen(
            urllib.request.Request(
                base + '/drain',
                data=json.dumps({'deadline_s': 30}).encode(),
                headers={'Content-Type': 'application/json'}),
            timeout=10).read())
        assert status['draining'] is True
        resp = sync(1, 0, [])            # heartbeat, still at index 0
        cmds = [c for c in resp['commands'] if c['kind'] == 'drain']
        assert cmds and cmds[0]['log_index'] > 0
        cid, pinned = cmds[0]['id'], cmds[0]['log_index']
        time.sleep(0.3)
        st = json.loads(urllib.request.urlopen(base + '/drain',
                                               timeout=10).read())
        assert st['drained'] is False    # follower has not acked
        assert st['gang_drain_acked'] is False
        # An ack from a rank that has NOT reached the pinned index
        # must not count — the follower-side protocol only acks once
        # caught up; the coordinator trusts acks, so the honest
        # follower behavior is what we exercise: catch up, then ack.
        sync(1, pinned, [cid])
        deadline = time.time() + 15
        while time.time() < deadline:
            st = json.loads(urllib.request.urlopen(base + '/drain',
                                                   timeout=10).read())
            if st['drained']:
                break
            time.sleep(0.1)
        assert st['drained'] is True and st['gang_drain_acked'] is True
    finally:
        srv.stop()


# ------------------------------------------------- one dead rank = dead gang
def test_rank1_kill_whole_gang_fails_lb_zero_lost(monkeypatch):
    """THE gang-atomicity acceptance: a seeded gang_member_crash on
    rank 1 mid-stream kills the whole gang fast (rank 0 _fatals on
    heartbeat loss), the LB migrates the in-flight stream to the
    surviving replica, and the client sees ONE stream whose tokens are
    byte-identical to an uninterrupted greedy run — zero lost
    requests."""
    from skypilot_tpu.serve.load_balancer import SkyServeLoadBalancer
    from skypilot_tpu.serve.server import ModelServer
    import dataclasses
    port = common_utils.find_free_port(22300)
    # Boot with the generous heartbeat bound (a cold follower
    # legitimately pauses seconds per first-shape compile on CPU);
    # tightened below once the prewarm run has filled every compile
    # cache — fast, deliberate whole-gang death detection. The leader
    # carries a deterministic per-iteration engine stall so the
    # tracked stream is still mid-flight when the death lands (a warm
    # tiny engine otherwise finishes before detection and the
    # migration path would go unexercised).
    from skypilot_tpu.serve.server import ModelServer as _MS
    srv = _MS('tiny', port=port,
              fault_spec={'seed': 0, 'rules': [
                  {'kind': 'engine_stall', 'site': 'engine_step',
                   'every': 1, 'delay_s': 0.15}]},
              gang=_leader_spec(gang_id='g-kill', heartbeat_s=0.05,
                                heartbeat_timeout_s=60.0),
              **_FAST)
    srv.start(block=False)
    base = f'http://127.0.0.1:{port}'
    port_b = common_utils.find_free_port(22350)
    survivor = ModelServer('tiny', port=port_b, **_FAST)
    survivor.start(block=False)
    follower = lb = ctrl = None
    try:
        assert srv._ready.wait(300) and survivor._ready.wait(300)
        follower, _t = _start_thread_follower(
            base, gang_id='g-kill', heartbeat_s=0.05,
            heartbeat_timeout_s=10.0)
        assert _await_barrier(srv, timeout=60), srv._error
        # Prompt chosen so the migrated continuation is byte-identical
        # at EVERY possible cut point (verified exhaustively on CPU;
        # some prompts hit bf16 near-tie argmax flips on the
        # recomputing replica at specific cuts — a pre-existing
        # bounded-divergence caveat of cross-replica recompute, not a
        # gang property).
        prompt, gen = [3, 1, 4, 1, 5], 32
        # Prewarm BOTH replicas with the kill run's shapes (different
        # tokens — no prefix aliasing) so every later step is
        # compile-free and the tight heartbeat bound is honest.
        _generate(base, {'prompt': [1, 2, 3, 4],
                         'max_new_tokens': gen})
        _generate(f'http://127.0.0.1:{port_b}',
                  {'prompt': [1, 2, 3, 4], 'max_new_tokens': gen})
        reference = _generate(f'http://127.0.0.1:{port_b}',
                              {'prompt': prompt,
                               'max_new_tokens': gen})['tokens']
        # Follower fully caught up (compile caches warm on both
        # ranks): tighten the heartbeat bound for the kill run.
        deadline = time.time() + 60
        while time.time() < deadline:
            st = srv._gang.status()
            if st['members'].get('1', {}).get('applied') == st['ops']:
                break
            time.sleep(0.1)
        srv._gang.spec = dataclasses.replace(
            srv._gang.spec, heartbeat_timeout_s=1.0)
        # Real LB over the gang (rank 0 only) + the survivor.
        ctrl = _FakeController([base, f'http://127.0.0.1:{port_b}'])
        monkeypatch.setenv('SKYTPU_LB_SYNC', '3600')
        lb_port = common_utils.find_free_port(22400)
        lb = SkyServeLoadBalancer(controller_url=ctrl.url,
                                  port=lb_port, max_attempts=4)
        lb.start()
        lb._sync_once()
        # Stream through the LB; after a few tokens land, the seeded
        # rank-1 kill fires (rule installed at a deterministic token
        # count — the crash is mid-stream by construction).
        tokens, done, error = [], None, None
        req = urllib.request.Request(
            f'http://127.0.0.1:{lb_port}/generate',
            json.dumps({'prompt': prompt, 'max_new_tokens': gen,
                        'stream': True}).encode(),
            {'Content-Type': 'application/json'})
        with urllib.request.urlopen(req, timeout=300) as r:
            for raw in r:
                if not raw.startswith(b'data:'):
                    continue
                ev = json.loads(raw[5:].strip())
                if 'token' in ev:
                    tokens.append(int(ev['token']))
                    if len(tokens) == 5:
                        follower._faults = faults_lib.FaultInjector(
                            {'seed': 0, 'rules': [
                                {'kind': 'replica_crash',
                                 'site': 'gang_member_crash',
                                 'rank': 1, 'at': 1}]})
                if ev.get('done'):
                    done = ev
                if 'error' in ev:
                    error = ev
        # Zero lost: the one accepted stream completed, byte-identical.
        assert error is None and done is not None
        assert tokens == reference, (tokens[:8], reference[:8])
        assert done['tokens'] == reference
        # The gang really died as a unit: rank 0 _fatal'ed on
        # follower heartbeat loss (possibly after the stream finished
        # elsewhere — the death itself is unconditional).
        deadline = time.time() + 20
        while time.time() < deadline and srv._error is None:
            time.sleep(0.1)
        assert srv._error is not None
        assert 'heartbeat lost' in srv._error
        reg = telemetry.get_registry()
        fail_c = reg.get('skytpu_gang_failures_total',
                         cause='heartbeat_lost')
        assert fail_c is not None and fail_c.value >= 1
        # The gang leader now probes dead (out of rotation).
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(base + '/readiness', timeout=10)
        assert ei.value.code == 503
    finally:
        if lb is not None:
            lb.stop()
        if ctrl is not None:
            ctrl.stop()
        srv.stop()
        survivor.stop()


def test_join_timeout_fails_partial_gang():
    """A rank that never joins must fail the gang within the join
    window: rank 0 _fatals (cause join_timeout), readiness reports the
    failure, and the manager-side probe escalation replaces the gang —
    never a half-joined replica hanging forever."""
    port = common_utils.find_free_port(22500)
    srv = _start_leader(port, gang_id='g-late', join_timeout_s=3.0,
                        heartbeat_s=0.05, heartbeat_timeout_s=1.0)
    try:
        deadline = time.time() + 60
        while time.time() < deadline and srv._error is None:
            time.sleep(0.1)
        assert srv._error is not None
        assert 'join timeout' in srv._error
        assert 'missing rank(s) [1]' in srv._error
        reg = telemetry.get_registry()
        c = reg.get('skytpu_gang_failures_total', cause='join_timeout')
        assert c is not None and c.value >= 1
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f'http://127.0.0.1:{port}/readiness',
                                   timeout=10)
        assert ei.value.code == 503
        assert json.loads(ei.value.read())['status'] == 'failed'
    finally:
        srv.stop()


def test_follower_self_terminates_on_coordinator_loss():
    """The follower half of one-dead-all-dead: rank 1 outliving a dead
    rank 0 would be a half-alive replica — it must self-terminate once
    the coordinator stops answering past the heartbeat timeout."""
    port = common_utils.find_free_port(22600)
    srv = _start_leader(port, gang_id='g-loss', heartbeat_s=0.05,
                        heartbeat_timeout_s=1.0)
    base = f'http://127.0.0.1:{port}'
    try:
        assert srv._ready.wait(300)
        follower, t = _start_thread_follower(
            base, gang_id='g-loss', heartbeat_s=0.05,
            heartbeat_timeout_s=1.0)
        assert _await_barrier(srv, timeout=60)
    finally:
        srv.stop()       # rank 0 vanishes (no shutdown ack race: the
                         # bounded grace may or may not deliver it)
    t.join(timeout=30)
    assert not t.is_alive()
    assert follower.exit_cause in ('shutdown', 'coordinator_lost',
                                   'coordinator_failed')


# ------------------------------------------------ manager: gangs as units
def _make_manager(tmp_path, monkeypatch, hosts=2):
    monkeypatch.setenv('SKYTPU_SERVE_DIR', str(tmp_path / 'serve'))
    from skypilot_tpu.serve.replica_managers import ReplicaManager
    from skypilot_tpu.serve.service_spec import SkyServiceSpec
    spec = SkyServiceSpec.from_yaml_config(
        {'readiness_probe': '/readiness',
         'parallelism': {'hosts': hosts}})
    return ReplicaManager('gang-mgr-test', spec, {})


def _insert_gang(mgr, gang_id='g', world=2, base_id=1,
                 url0='http://127.0.0.1:1', spot=False):
    from skypilot_tpu.serve import serve_state
    from skypilot_tpu.serve.replica_managers import ReplicaInfo
    infos = []
    for rank in range(world):
        info = ReplicaInfo(base_id + rank, f'{gang_id}-c{rank}', 1,
                           spot, 30000 + base_id + rank,
                           gang_id=gang_id, gang_rank=rank,
                           gang_world=world)
        info.url = (url0 if rank == 0
                    else f'http://127.0.0.1:{40000 + rank}')
        info.status = serve_state.ReplicaStatus.READY
        with mgr._lock:
            mgr._replicas[info.replica_id] = info
        infos.append(info)
    return infos


def test_manager_gang_single_endpoint_and_teardown_as_unit(
        tmp_path, monkeypatch):
    from skypilot_tpu.serve import serve_state
    mgr = _make_manager(tmp_path, monkeypatch)
    leader, follower = _insert_gang(mgr, world=2)
    # Exactly ONE routable endpoint: rank 0. Followers stay out of
    # ready_urls and the role map, but ride the gang health block.
    assert mgr.ready_urls() == [leader.url]
    assert follower.url not in mgr.replica_roles()
    gangs = mgr.replica_gangs()
    assert gangs[leader.url]['world'] == 2
    assert gangs[leader.url]['follower_urls'] == [follower.url]
    # Tearing down ANY member tears down the whole gang.
    mgr.scale_down(follower.replica_id)
    deadline = time.time() + 20
    while time.time() < deadline and mgr._replicas:
        time.sleep(0.1)
    assert mgr._replicas == {}


def test_manager_drain_any_rank_drains_gang(tmp_path, monkeypatch):
    from skypilot_tpu.serve import serve_state
    mgr = _make_manager(tmp_path, monkeypatch)
    leader, follower = _insert_gang(mgr, world=2)
    # Drain aimed at the FOLLOWER routes to rank 0 and marks every
    # member DRAINING (out of ready_urls immediately). The fake URL's
    # unreachable drain endpoint degrades to teardown on the drain
    # thread, so either leaving-state may already show.
    leaving = (serve_state.ReplicaStatus.DRAINING,
               serve_state.ReplicaStatus.SHUTTING_DOWN)
    assert mgr.drain(follower.replica_id, deadline_s=5) is True
    assert leader.status in leaving
    assert follower.status in leaving
    assert mgr.ready_urls() == []
    assert mgr.drain(leader.replica_id) is False     # idempotent
    deadline = time.time() + 20
    while time.time() < deadline and mgr._replicas:
        time.sleep(0.1)
    assert mgr._replicas == {}


def test_preemption_warning_gang_keyed_checkpoint_once(
        tmp_path, monkeypatch):
    """Satellite fix: the checkpoint-once flag is keyed by GANG ID —
    a warning re-delivered to a different rank of the same gang still
    checkpoints exactly once (one POST /checkpoint against rank 0)."""
    import http.server as hs
    hits = {'checkpoint': 0}

    class H(hs.BaseHTTPRequestHandler):
        timeout = 10

        def log_message(self, *a):
            del a

        def do_POST(self):  # noqa: N802
            if self.path == '/checkpoint':
                hits['checkpoint'] += 1
                body = b'SKCK-FAKE'
                self.send_response(200)
                self.send_header('Content-Length', str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            body = json.dumps({'draining': True,
                               'inflight': 0}).encode()
            self.send_response(200)
            self.send_header('Content-Length', str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):  # noqa: N802
            body = json.dumps({'draining': True, 'drained': True,
                               'inflight': 0}).encode()
            self.send_response(200)
            self.send_header('Content-Length', str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    port = common_utils.find_free_port(22700)
    httpd = hs.ThreadingHTTPServer(('127.0.0.1', port), H)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        mgr = _make_manager(tmp_path, monkeypatch)
        leader, follower = _insert_gang(
            mgr, world=2, url0=f'http://127.0.0.1:{port}', spot=True)
        # Warning lands on the FOLLOWER first (re-delivery target),
        # then on the leader: exactly one checkpoint, one drain.
        assert mgr.handle_preemption_warning(follower.replica_id,
                                             deadline_s=5) is True
        assert mgr.handle_preemption_warning(leader.replica_id,
                                             deadline_s=5) is False
        deadline = time.time() + 10
        while time.time() < deadline and hits['checkpoint'] == 0:
            time.sleep(0.05)
        time.sleep(0.3)       # would-be window for a double POST
        assert hits['checkpoint'] == 1
        assert mgr.checkpoint_for_warmup() == b'SKCK-FAKE'
        deadline = time.time() + 20
        while time.time() < deadline and mgr._replicas:
            time.sleep(0.1)
        assert mgr._replicas == {}
    finally:
        httpd.shutdown()


def test_policies_exclude_follower_urls_from_probes(monkeypatch):
    """Satellite fix: queue_depth/phase_aware probe sweeps and
    selection must skip gang follower URLs — a gang presents one
    endpoint — while the gang stays visible in health accounting."""
    from skypilot_tpu.serve import load_balancing_policies as lbp
    probed = []
    for name in ('queue_depth', 'phase_aware'):
        policy = lbp.make_policy(name)
        monkeypatch.setattr(
            policy, '_probe',
            lambda url: (probed.append(url) or (0, None)))
        # A not-gang-aware controller leaked follower URLs into the
        # ready list; the gang block marks them.
        policy.set_ready_replicas(['http://r0:1', 'http://f1:1',
                                   'http://solo:1'])
        policy.set_replica_gangs({'http://r0:1': {
            'gang_id': 'g', 'world': 2,
            'follower_urls': ['http://f1:1'],
            'statuses': {'0': 'READY', '1': 'READY'}}})
        for _ in range(4):
            pick = policy.select_replica()
            assert pick != 'http://f1:1'
        assert 'http://f1:1' not in probed
        assert set(probed) <= {'http://r0:1', 'http://solo:1'}
        assert policy.gang_view()['http://r0:1']['world'] == 2
        probed.clear()


# --------------------------------------- gang checkpoint -> warm recovery
def test_preempt_gang_checkpoint_recover_byte_identical():
    """Preemption flow across a gang: mid-stream, POST /checkpoint
    exports the gang's state (in-flight KV + hot prefixes; every rank
    acks), a replacement single-process replica warms from the blob,
    and the resubmitted continuation is byte-identical to an
    uninterrupted run."""
    from skypilot_tpu.serve.server import ModelServer
    port = common_utils.find_free_port(22800)
    # Deterministic engine stall: the tiny engine otherwise decodes
    # the whole budget faster than the test can read 30 tokens and
    # POST /checkpoint — the request must still be IN FLIGHT when the
    # export runs, or there is nothing to snapshot.
    srv = ModelServer('tiny', port=port,
                      fault_spec={'seed': 0, 'rules': [
                          {'kind': 'engine_stall', 'site': 'engine_step',
                           'every': 1, 'delay_s': 0.2}]},
                      gang=_leader_spec(gang_id='g-ckpt'), **_FAST)
    srv.start(block=False)
    base = f'http://127.0.0.1:{port}'
    follower = None
    try:
        assert srv._ready.wait(300)
        follower, _t = _start_thread_follower(base, gang_id='g-ckpt')
        assert _await_barrier(srv, timeout=60), srv._error
        # gen pinned where the cross-replica recompute is byte-exact
        # for this prompt (the 100-ish-token near-tie caveat the
        # robustness docs carry).
        prompt, gen = [9, 2, 6, 4], 48
        # Uninterrupted reference on a fresh single-process server.
        port_r = common_utils.find_free_port(22850)
        ref_srv = ModelServer('tiny', port=port_r, **_FAST)
        ref_srv.start(block=False)
        try:
            assert ref_srv._ready.wait(300)
            reference = _generate(f'http://127.0.0.1:{port_r}',
                                  {'prompt': prompt,
                                   'max_new_tokens': gen})['tokens']
        finally:
            ref_srv.stop()
        # Start the stream on the gang; checkpoint mid-flight.
        sr = srv.submit_stream(prompt, max_new_tokens=gen,
                               temperature=0.0, top_k=0, eos_id=None)
        tokens = []
        # Far enough in that the context covers full pages —
        # warm_prefix lands page-granular KV, so a too-early
        # checkpoint would carry nothing warmable.
        while len(tokens) < 30:
            token, finished = sr.outbox.get(timeout=120)
            assert token is not None, sr.outbox.error
            tokens.append(int(token))
            assert not finished
        req = urllib.request.Request(
            base + '/checkpoint', data=json.dumps({}).encode(),
            headers={'Content-Type': 'application/json'})
        with urllib.request.urlopen(req, timeout=60) as r:
            blob = r.read()
            n_entries = int(r.headers['X-Checkpoint-Entries'])
        assert n_entries >= 1
        srv.finish_stream(sr)            # preempted: client gone
        # Replacement replica warms BEFORE serving, then continues
        # from prompt + generated prefix.
        port2 = common_utils.find_free_port(22900)
        srv2 = ModelServer('tiny', port=port2, **_FAST)
        srv2.start(block=False)
        try:
            assert srv2._ready.wait(300)
            warm_req = urllib.request.Request(
                f'http://127.0.0.1:{port2}/kv/warmup', data=blob,
                headers={'Content-Type': 'application/octet-stream'})
            with urllib.request.urlopen(warm_req, timeout=60) as r:
                warm = json.loads(r.read())
            assert warm['entries'] == n_entries
            assert warm['warmed_rows'] >= 1
            cont = _generate(
                f'http://127.0.0.1:{port2}',
                {'prompt': prompt + tokens,
                 'max_new_tokens': gen - len(tokens)})['tokens']
            assert tokens + cont == reference
        finally:
            srv2.stop()
    finally:
        srv.stop()
