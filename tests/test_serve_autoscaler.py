"""Autoscaler + LB-policy unit tests: synthetic request timestamps and
replica views in, scaling decisions out (reference pattern:
``tests/test_serve_autoscaler.py``). No clusters, no clock sleeps."""
import pytest

from skypilot_tpu.serve import autoscalers
from skypilot_tpu.serve import load_balancing_policies as lb_policies
from skypilot_tpu.serve.autoscalers import DecisionOperator, ReplicaView
from skypilot_tpu.serve.service_spec import SkyServiceSpec


def _spec(**kw):
    defaults = dict(readiness_path='/readiness', min_replicas=1,
                    max_replicas=4, target_qps_per_replica=1.0,
                    upscale_delay_seconds=20.0,
                    downscale_delay_seconds=40.0)
    defaults.update(kw)
    return SkyServiceSpec(**defaults)


def _views(n_ready, n_starting=0, spot=False, start_id=1):
    views = []
    rid = start_id
    for _ in range(n_ready):
        views.append(ReplicaView(rid, True, spot))
        rid += 1
    for _ in range(n_starting):
        views.append(ReplicaView(rid, False, spot))
        rid += 1
    return views


def _mk(spec):
    return autoscalers.Autoscaler.from_spec(spec)


class TestFixedAutoscaler:

    def test_fixed_replicas_launches_min(self):
        spec = SkyServiceSpec(readiness_path='/x', min_replicas=3)
        asc = autoscalers.Autoscaler.from_spec(spec)
        assert type(asc) is autoscalers.Autoscaler
        decisions = asc.evaluate_scaling([])
        assert len(decisions) == 3
        assert all(d.operator == DecisionOperator.SCALE_UP
                   for d in decisions)

    def test_replaces_terminal_replicas(self):
        spec = SkyServiceSpec(readiness_path='/x', min_replicas=2)
        asc = autoscalers.Autoscaler.from_spec(spec)
        views = [ReplicaView(1, True, False),
                 ReplicaView(2, False, False, is_terminal=True)]
        decisions = asc.evaluate_scaling(views)
        assert len(decisions) == 1
        assert decisions[0].operator == DecisionOperator.SCALE_UP


class TestRequestRateAutoscaler:

    def test_upscale_needs_sustained_load(self):
        asc = _mk(_spec())
        # ~3 QPS over the window → raw target 3, but only after the
        # breach persists for upscale_delay_seconds (20s) of wall clock.
        now = 1000.0
        asc.collect_request_information(
            [now - i * 0.3 for i in range(180)])
        assert asc.evaluate_scaling(_views(1), now=now) == []  # breach t0
        decisions = asc.evaluate_scaling(_views(1), now=now + 20.0)
        assert len(decisions) == 2          # target moved to 3, have 1

    def test_upscale_hysteresis_blocks_single_spike(self):
        spec = _spec(upscale_delay_seconds=60.0)
        asc = _mk(spec)
        asc._raw_target = lambda now: 3     # sustained high demand
        assert asc.evaluate_scaling(_views(1), now=1000.0) == []
        assert asc.evaluate_scaling(_views(1), now=1030.0) == []
        # Breach has now persisted 60s: scale.
        decisions = asc.evaluate_scaling(_views(1), now=1060.0)
        assert len(decisions) == 2

    def test_upscale_hysteresis_resets_when_breach_clears(self):
        spec = _spec(upscale_delay_seconds=60.0)
        asc = _mk(spec)
        asc._raw_target = lambda now: 3
        assert asc.evaluate_scaling(_views(1), now=1000.0) == []
        asc._raw_target = lambda now: 1     # spike ended
        assert asc.evaluate_scaling(_views(1), now=1030.0) == []
        asc._raw_target = lambda now: 3     # new spike: clock restarts
        assert asc.evaluate_scaling(_views(1), now=1060.0) == []
        assert asc.evaluate_scaling(_views(1), now=1090.0) == []
        assert len(asc.evaluate_scaling(_views(1), now=1120.0)) == 2

    def test_downscale_slower_than_upscale(self):
        spec = _spec(upscale_delay_seconds=20.0,
                     downscale_delay_seconds=40.0)
        asc = _mk(spec)
        asc._raw_target = lambda now: 3
        asc.evaluate_scaling(_views(3), now=1000.0)
        asc.evaluate_scaling(_views(3), now=1020.0)
        assert asc.target_num_replicas == 3
        # Traffic stops: raw target drops to 1, but only after 40s.
        asc._raw_target = lambda now: 1
        assert asc.evaluate_scaling(_views(3), now=1100.0) == []
        assert asc.evaluate_scaling(_views(3), now=1120.0) == []  # 20s < 40
        decisions = asc.evaluate_scaling(_views(3), now=1140.0)
        assert len(decisions) == 2
        assert all(d.operator == DecisionOperator.SCALE_DOWN
                   for d in decisions)
        # Newest replicas are the downscale victims.
        assert sorted(d.target['replica_id'] for d in decisions) == [2, 3]

    def test_bounded_by_max_replicas(self):
        asc = _mk(_spec(max_replicas=2))
        now = 1000.0
        asc.collect_request_information([now - i * 0.05 for i in range(
            1000)])                                   # ~17 qps
        assert asc.evaluate_scaling(_views(1), now=now) == []   # breach t0
        decisions = asc.evaluate_scaling(_views(1), now=now + 20.0)
        assert len(decisions) == 1                    # capped at 2 total

    def test_window_expires_old_requests(self):
        asc = _mk(_spec())
        now = 1000.0
        asc.collect_request_information(
            [now - 120 - i for i in range(300)])      # all outside window
        assert asc.current_qps(now=now) == 0.0

    def test_qps_zero_scales_to_min(self):
        asc = _mk(_spec(min_replicas=1, max_replicas=4,
                        downscale_delay_seconds=20.0))
        asc.target_num_replicas = 4
        assert asc.evaluate_scaling(_views(4), now=1000.0) == []
        decisions = asc.evaluate_scaling(_views(4), now=1020.0)
        assert len(decisions) == 3
        assert {d.operator for d in decisions} == \
            {DecisionOperator.SCALE_DOWN}

    def test_update_spec_rebounds_target(self):
        asc = _mk(_spec(min_replicas=1, max_replicas=4))
        asc.target_num_replicas = 4
        asc.update_spec(_spec(min_replicas=1, max_replicas=2), version=2)
        assert asc.target_num_replicas == 2
        assert asc.latest_version == 2


class TestFallbackAutoscaler:

    def test_base_ondemand_plus_spot(self):
        spec = _spec(min_replicas=3, max_replicas=6,
                     base_ondemand_fallback_replicas=1)
        asc = _mk(spec)
        assert isinstance(asc, autoscalers.FallbackRequestRateAutoscaler)
        decisions = asc.evaluate_scaling([], now=1000.0)
        ups = [d.target['use_spot'] for d in decisions
               if d.operator == DecisionOperator.SCALE_UP]
        assert sorted(ups) == [False, True, True]

    def test_preempted_spot_replaced_by_spot(self):
        spec = _spec(min_replicas=2, max_replicas=4,
                     base_ondemand_fallback_replicas=1)
        asc = _mk(spec)
        views = [ReplicaView(1, True, False),
                 ReplicaView(2, False, True, is_terminal=True)]  # preempted
        decisions = asc.evaluate_scaling(views, now=1000.0)
        assert len(decisions) == 1
        assert decisions[0].target['use_spot'] is True

    def test_dynamic_fallback_backfills_preempted_spot_with_ondemand(self):
        spec = _spec(min_replicas=2, max_replicas=4,
                     dynamic_ondemand_fallback=True)
        asc = _mk(spec)
        # Both spot replicas preempted → relaunch spot AND backfill
        # on-demand so the service keeps serving during the spot drought.
        views = [ReplicaView(1, False, True, is_terminal=True),
                 ReplicaView(2, False, True, is_terminal=True)]
        decisions = asc.evaluate_scaling(views, now=1000.0)
        ups = sorted(d.target['use_spot'] for d in decisions
                     if d.operator == DecisionOperator.SCALE_UP)
        assert ups == [False, False, True, True]

    def test_dynamic_fallback_drains_ondemand_when_spot_ready(self):
        spec = _spec(min_replicas=2, max_replicas=4,
                     dynamic_ondemand_fallback=True,
                     downscale_delay_seconds=20.0)
        asc = _mk(spec)
        # Spot recovered (2 ready); the 2 backfill on-demand replicas
        # are now excess and must drain.
        views = [ReplicaView(1, True, True), ReplicaView(2, True, True),
                 ReplicaView(3, True, False), ReplicaView(4, True, False)]
        decisions = asc.evaluate_scaling(views, now=1000.0)
        downs = [d for d in decisions
                 if d.operator == DecisionOperator.SCALE_DOWN]
        assert {d.target['replica_id'] for d in downs} == {3, 4}
        assert not [d for d in decisions
                    if d.operator == DecisionOperator.SCALE_UP]

    def test_excess_spot_downscaled_keeps_ondemand_base(self):
        spec = _spec(min_replicas=1, max_replicas=4,
                     base_ondemand_fallback_replicas=1,
                     downscale_delay_seconds=20.0)
        asc = _mk(spec)
        views = [ReplicaView(1, True, False), ReplicaView(2, True, True),
                 ReplicaView(3, True, True)]
        decisions = asc.evaluate_scaling(views, now=1000.0)
        downs = [d for d in decisions
                 if d.operator == DecisionOperator.SCALE_DOWN]
        assert {d.target['replica_id'] for d in downs} == {2, 3}


class TestLoadBalancingPolicies:

    def test_round_robin_cycles(self):
        p = lb_policies.make_policy('round_robin')
        p.set_ready_replicas(['a', 'b', 'c'])
        assert [p.select_replica() for _ in range(4)] == \
            ['a', 'b', 'c', 'a']

    def test_round_robin_empty(self):
        p = lb_policies.make_policy('round_robin')
        assert p.select_replica() is None

    def test_least_load_prefers_idle(self):
        p = lb_policies.make_policy('least_load')
        p.set_ready_replicas(['a', 'b'])
        p.pre_execute('a')
        assert p.select_replica() == 'b'
        p.pre_execute('b')
        p.pre_execute('b')
        assert p.select_replica() == 'a'
        p.post_execute('b')
        p.post_execute('b')
        p.post_execute('a')
        assert p.select_replica() in ('a', 'b')

    def test_unknown_policy_raises(self):
        with pytest.raises(ValueError):
            lb_policies.make_policy('bogus')


class TestServiceSpec:

    def test_from_yaml_minimal(self):
        spec = SkyServiceSpec.from_yaml_config(
            {'readiness_probe': '/health', 'replicas': 2})
        assert spec.readiness_path == '/health'
        assert spec.min_replicas == 2
        assert not spec.autoscaling_enabled

    def test_from_yaml_policy_roundtrip(self):
        cfg = {
            'readiness_probe': {'path': '/readiness',
                                'initial_delay_seconds': 10},
            'replica_policy': {'min_replicas': 1, 'max_replicas': 3,
                               'target_qps_per_replica': 2.5},
            'port': 9000,
            'load_balancing_policy': 'least_load',
        }
        spec = SkyServiceSpec.from_yaml_config(cfg)
        assert spec.autoscaling_enabled
        assert spec.replica_port == 9000
        spec2 = SkyServiceSpec.from_yaml_config(spec.to_yaml_config())
        assert spec2 == spec

    def test_autoscaling_requires_qps_target(self):
        from skypilot_tpu import exceptions
        with pytest.raises(exceptions.InvalidServiceSpecError):
            SkyServiceSpec.from_yaml_config({
                'readiness_probe': '/x',
                'replica_policy': {'min_replicas': 1, 'max_replicas': 3},
            })

    def test_replicas_and_policy_conflict(self):
        from skypilot_tpu import exceptions
        with pytest.raises(exceptions.InvalidServiceSpecError):
            SkyServiceSpec.from_yaml_config({
                'readiness_probe': '/x',
                'replicas': 2,
                'replica_policy': {'min_replicas': 1},
            })
