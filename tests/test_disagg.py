"""Disaggregated prefill/decode serving (round 9): KV wire codec,
engine export/ingest, phase-aware routing, handoff e2e.

The contracts under test:

- **Byte identity.** A request prefilled on one engine/server and
  handed off to another continues greedy decode BYTE-IDENTICALLY to a
  colocated run — the KV rows land at the exact original bytes
  (int8 codes + scales never dequantize on the wire).
- **Loud rejection.** Malformed, truncated, or mismatched handoffs are
  refused with ``ValueError``/HTTP 400 (and counted) before anything
  touches the pool; capacity refusals are retryable (503).
- **Zero lost requests.** A decode worker dying mid-continuation
  surfaces a retryable error with the generated prefix; the LB's
  in-flight recovery resubmits prompt+prefix and the client still sees
  one complete, byte-identical stream (extends the round-7 chaos
  harness).
"""
import json
import threading
import time
import urllib.error
import urllib.request

import jax
import numpy as np
import pytest

from skypilot_tpu import telemetry
from skypilot_tpu.inference import kv_transfer
from skypilot_tpu.serve import disagg as disagg_lib
from skypilot_tpu.serve import faults as faults_lib
from skypilot_tpu.utils import common_utils

jax.config.update('jax_platforms', 'cpu')


# ---------------------------------------------------------------- helpers
def _make_engine(kind, kv_cache_dtype, max_batch=2, max_seq=128):
    from skypilot_tpu.models import configs
    cfg = configs.get_config('tiny')
    if kind == 'paged':
        from skypilot_tpu.inference.paged import PagedInferenceEngine
        return PagedInferenceEngine(cfg, max_batch=max_batch,
                                    max_seq=max_seq,
                                    kv_cache_dtype=kv_cache_dtype)
    from skypilot_tpu.inference.engine import InferenceEngine
    return InferenceEngine(cfg, max_batch=max_batch, max_seq=max_seq,
                           kv_cache_dtype=kv_cache_dtype)


def _run_to_first_token(engine, rid):
    """Step until ``rid``'s first token event surfaces; returns it."""
    deadline = time.time() + 120
    while time.time() < deadline:
        for r, tok, _fin in engine.step(horizon=2):
            if r == rid:
                return tok
    raise TimeoutError('no first token')


def _fake_snapshot(kv_cache_dtype='int8', n_layers=2, n_kv=2, d=4,
                   prompt=(1, 2, 3, 4, 5), output=(7,), **over):
    """A structurally valid snapshot with deterministic contents."""
    n_rows = len(prompt) + len(output) - 1
    rng = np.random.default_rng(0)
    snap = {
        'kv_cache_dtype': kv_cache_dtype,
        'n_rows': n_rows,
        'model': {'n_layers': n_layers, 'n_kv_heads': n_kv,
                  'head_dim': d},
        'prompt': list(prompt), 'output': list(output),
        'max_new_tokens': 16, 'temperature': 0.0, 'top_k': 0,
        'top_p': 1.0, 'eos_id': None, 'stop': None, 'priority': 0,
    }
    shape = (n_layers, n_rows, n_kv, d)
    if kv_cache_dtype == 'int4':
        cshape = shape[:-1] + (d // 2,)
        snap['k'] = rng.integers(0, 256, cshape).astype(np.uint8)
        snap['v'] = rng.integers(0, 256, cshape).astype(np.uint8)
        snap['k_scale'] = rng.random(shape[:3]).astype(np.float32)
        snap['v_scale'] = rng.random(shape[:3]).astype(np.float32)
    elif kv_cache_dtype == 'int8':
        snap['k'] = rng.integers(-127, 128, shape).astype(np.int8)
        snap['v'] = rng.integers(-127, 128, shape).astype(np.int8)
        snap['k_scale'] = rng.random(shape[:3]).astype(np.float32)
        snap['v_scale'] = rng.random(shape[:3]).astype(np.float32)
    else:
        import ml_dtypes
        snap['k'] = rng.standard_normal(shape).astype(ml_dtypes.bfloat16)
        snap['v'] = rng.standard_normal(shape).astype(ml_dtypes.bfloat16)
        snap['k_scale'] = snap['v_scale'] = None
    snap.update(over)
    return snap


# ------------------------------------------------------------ wire codec
@pytest.mark.parametrize('dtype', ['int8', 'bf16', 'int4'])
def test_wire_roundtrip_exact(dtype):
    snap = _fake_snapshot(dtype)
    blob = kv_transfer.encode_handoff(snap)
    out = kv_transfer.decode_handoff(blob)
    assert out['kv_cache_dtype'] == dtype
    assert out['prompt'] == snap['prompt']
    assert out['output'] == snap['output']
    assert out['n_rows'] == snap['n_rows']
    # Codes/rows and scales round-trip EXACTLY (bit-for-bit) in their
    # stored dtype — no widening, no requantization, no unpacking
    # (int4 nibble rows stay packed uint8 at head_dim/2 on the wire).
    assert out['k'].dtype == snap['k'].dtype
    assert out['k'].tobytes() == snap['k'].tobytes()
    assert out['v'].tobytes() == snap['v'].tobytes()
    if dtype in ('int8', 'int4'):
        assert out['k'].dtype == (np.uint8 if dtype == 'int4'
                                  else np.int8)
        assert out['k_scale'].dtype == np.float32
        assert out['k_scale'].tobytes() == snap['k_scale'].tobytes()
        assert out['v_scale'].tobytes() == snap['v_scale'].tobytes()
    else:
        assert out['k'].dtype.name == 'bfloat16'


def test_wire_int8_half_the_bytes_of_bf16():
    """The economics of the handoff: int8 codes are half the bf16
    rows; even with fp32 scales the int8 blob must be well under the
    bf16 one at realistic head dims."""
    int8 = len(kv_transfer.encode_handoff(_fake_snapshot(
        'int8', d=128, prompt=tuple(range(1, 40)))))
    bf16 = len(kv_transfer.encode_handoff(_fake_snapshot(
        'bf16', d=128, prompt=tuple(range(1, 40)))))
    assert int8 < 0.6 * bf16, (int8, bf16)


def test_wire_malformed_rejected():
    snap = _fake_snapshot('int8')
    blob = kv_transfer.encode_handoff(snap)
    with pytest.raises(ValueError, match='bad magic'):
        kv_transfer.decode_handoff(b'XXXX' + blob[4:])
    with pytest.raises(ValueError, match='truncated'):
        kv_transfer.decode_handoff(blob[:len(blob) // 2])
    with pytest.raises(ValueError, match='trailing'):
        kv_transfer.decode_handoff(blob + b'junk')
    with pytest.raises(ValueError, match='short blob'):
        kv_transfer.decode_handoff(b'SK')
    # Header lies about n_rows vs the actual token counts.
    bad = _fake_snapshot('int8')
    bad['n_rows'] = 3
    with pytest.raises(ValueError, match='n_rows'):
        kv_transfer.encode_decode = None  # noqa: avoid accidental reuse
        kv_transfer.decode_handoff(kv_transfer.encode_handoff(bad))
    # No generated token at all.
    with pytest.raises(ValueError, match='at least the first'):
        kv_transfer.decode_handoff(kv_transfer.encode_handoff(
            _fake_snapshot('int8', output=())))


# --------------------------------------------- allocator prefix guard
def test_register_prefix_validates_page_count():
    from skypilot_tpu.inference.paged import PageAllocator
    alloc = PageAllocator(n_pages=8, page_size=4)
    pages = [alloc.alloc() for _ in range(2)]
    ctx = list(range(13))          # 3 full pages of 4 — needs 3 pages
    with pytest.raises(ValueError, match='cannot cover'):
        alloc.register_prefix(ctx, pages, 0)
    # Nothing was content-addressed by the failed call.
    assert not alloc.by_hash and not alloc.page_hash
    # A covering page list registers fine.
    pages.append(alloc.alloc())
    alloc.register_prefix(ctx, pages, 0)
    assert len(alloc.by_hash) == 3


# ------------------------------------------------ engine export/ingest
@pytest.mark.parametrize('kind', ['paged', 'slot'])
@pytest.mark.parametrize('dtype', ['int8', 'bf16', 'int4'])
def test_handoff_byte_identical_to_colocated(kind, dtype):
    """THE disaggregation contract: export after the first token, wire
    round-trip, ingest into a second engine — the greedy continuation
    is byte-identical to an uninterrupted colocated run."""
    prompt = [3, 1, 4, 1, 5, 9, 2, 6] * 4        # > 1 page, uneven tail
    ref_eng = _make_engine(kind, dtype)
    rid = ref_eng.add_request(list(prompt), max_new_tokens=20)
    reference = ref_eng.run_to_completion(horizon=4)[rid].output

    src = _make_engine(kind, dtype)
    rid = src.add_request(list(prompt), max_new_tokens=20, hold=True)
    first = _run_to_first_token(src, rid)
    snap, _events = src.export_kv_snapshot(rid)
    assert snap is not None
    # Held request: exactly the prefill-sampled first token, no local
    # decode-ahead racing the handoff.
    assert snap['output'] == [first] == reference[:1]
    assert src.cancel(rid)
    snap = kv_transfer.decode_handoff(kv_transfer.encode_handoff(snap))

    dst = _make_engine(kind, dtype)
    rid2 = dst.ingest_kv_snapshot(snap)
    out = dst.run_to_completion(horizon=4)[rid2].output
    assert out == reference, (kind, dtype)


def test_ingest_no_free_slot_is_retryable():
    eng = _make_engine('paged', 'int8', max_batch=1)
    eng.add_request([1, 2, 3, 4], max_new_tokens=30)
    for _ in range(2):
        eng.step(horizon=1)                     # occupy the only slot
    with pytest.raises(kv_transfer.HandoffCapacityError):
        eng.ingest_kv_snapshot(_fake_snapshot(
            'int8', n_layers=eng.cfg.n_layers,
            n_kv=eng.cfg.n_kv_heads, d=eng.cfg.head_dim))


def test_ingest_rejects_mismatches():
    eng = _make_engine('paged', 'int8')
    good = dict(_fake_snapshot('int8', n_layers=eng.cfg.n_layers,
                               n_kv=eng.cfg.n_kv_heads,
                               d=eng.cfg.head_dim))
    # Wrong KV dtype: int8 pools never transcode bf16 handoffs.
    bad = dict(good, kv_cache_dtype='bf16')
    with pytest.raises(ValueError, match='kv_cache_dtype'):
        eng.ingest_kv_snapshot(bad)
    # Wrong model shape.
    bad = dict(good, model=dict(good['model'], n_layers=99))
    with pytest.raises(ValueError, match='n_layers'):
        eng.ingest_kv_snapshot(bad)
    # Truncated row batch: n_rows consistent with prompt/output but
    # the arrays are short.
    bad = dict(good, k=good['k'][:, :2])
    with pytest.raises(ValueError, match='rows shape'):
        eng.ingest_kv_snapshot(bad)
    # Already-complete request.
    bad = dict(good, max_new_tokens=1)
    with pytest.raises(ValueError, match='complete'):
        eng.ingest_kv_snapshot(bad)
    # A clean snapshot still lands after all the rejections.
    assert isinstance(eng.ingest_kv_snapshot(good), int)


def test_hold_blocks_decode_until_released():
    eng = _make_engine('paged', 'bf16')
    rid = eng.add_request([5, 6, 7, 8] * 3, max_new_tokens=12,
                          hold=True)
    first = _run_to_first_token(eng, rid)
    # Held: stepping decodes nothing further.
    for _ in range(6):
        events = eng.step(horizon=4)
        assert [e for e in events if e[0] == rid] == []
    assert not eng.has_runnable_work()
    req = next(r for r in eng._slots if r is not None)
    assert req.output == [first]
    assert eng.release_hold(rid)
    out = eng.run_to_completion(horizon=4)[rid].output
    assert len(out) == 12


def test_scheduler_adopt_routes_and_skips_ttft():
    import threading as th
    from skypilot_tpu.serve import scheduler as scheduler_lib
    lock = th.Lock()
    sched = scheduler_lib.RequestScheduler(lock)

    class _Eng:
        max_batch = 4

        def pop_finished(self, rid):
            return None
    sched._engine = _Eng()
    sr = sched.adopt(7, tier='latency', prompt=[1, 2], output=[3],
                     max_new_tokens=8)
    assert sr.handoff and sr.request_id == 7
    assert sched.inflight == 1
    sched.on_events(_Eng(), [(7, 11, False)])
    assert sr.outbox.get(timeout=5) == (11, False)
    # TTFT quantiles skip handoff continuations.
    before = sched._h_ttft['latency'].count
    sr.result = type('R', (), {'ttft_ms': 0.5,
                               'first_token_time': 1.0,
                               'finish_time': 2.0,
                               'output': [3, 11]})()
    sched._record_finished(sr)
    assert sched._h_ttft['latency'].count == before


# --------------------------------------------------- phase-aware policy
class _FakeReplica:
    """A /metrics?format=json stub with settable role/load/headroom."""

    def __init__(self, role, queue_tokens=0, kv_free=1000):
        import http.server as hs
        outer = self
        self.role, self.queue_tokens, self.kv_free = \
            role, queue_tokens, kv_free

        class H(hs.BaseHTTPRequestHandler):
            timeout = 10

            def log_message(self, *a):
                del a

            def do_GET(self):  # noqa: N802
                body = json.dumps({
                    'queue_tokens_total': outer.queue_tokens,
                    'kv_pool_tokens_free': outer.kv_free,
                    'mesh': {'tp': 1, 'dp': 1},
                    'disagg': {'role': outer.role},
                }).encode()
                self.send_response(200)
                self.send_header('Content-Length', str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.port = common_utils.find_free_port(19200)
        self.httpd = hs.ThreadingHTTPServer(('127.0.0.1', self.port), H)
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()

    @property
    def url(self):
        return f'http://127.0.0.1:{self.port}'

    def stop(self):
        self.httpd.shutdown()


def test_phase_aware_policy_routing_and_handoff_target():
    from skypilot_tpu.serve import load_balancing_policies as lb_policies
    replicas = [_FakeReplica('prefill', queue_tokens=500),
                _FakeReplica('prefill', queue_tokens=100),
                _FakeReplica('decode', kv_free=50),
                _FakeReplica('decode', kv_free=5000),
                _FakeReplica('colocated', queue_tokens=0)]
    try:
        policy = lb_policies.make_policy('phase_aware')
        policy.set_ready_replicas([r.url for r in replicas])
        # New requests go to the prefill pool, least queued tokens
        # first — NOT to the idle colocated or decode replicas.
        assert policy.select_replica() == replicas[1].url
        # Handoff target: the decode worker with the most free KV.
        assert policy.handoff_target() == replicas[3].url
        # Excluding it falls to the next decode worker.
        assert policy.handoff_target(
            exclude={replicas[3].url}) == replicas[2].url
        # Prefill pool exhausted -> colocated fallback.
        assert policy.select_replica(
            exclude={replicas[0].url, replicas[1].url}) \
            == replicas[4].url
        # Everything else gone -> decode workers still answer.
        assert policy.select_replica(
            exclude={r.url for r in replicas[:2]} | {replicas[4].url}) \
            in (replicas[2].url, replicas[3].url)
    finally:
        for r in replicas:
            r.stop()


def test_phase_aware_planned_roles_fallback():
    """Cold probes (dead endpoints): the controller-planned roles
    still steer routing."""
    from skypilot_tpu.serve import load_balancing_policies as lb_policies
    policy = lb_policies.make_policy('phase_aware')
    urls = ['http://127.0.0.1:1', 'http://127.0.0.1:2',
            'http://127.0.0.1:3']
    policy.set_ready_replicas(urls)
    policy.set_replica_roles({urls[0]: 'decode', urls[1]: 'prefill',
                              urls[2]: 'colocated'})
    assert policy.select_replica() == urls[1]
    assert policy.handoff_target() == urls[0]


def test_role_resolution_and_spec():
    assert disagg_lib.resolve_role(None) == 'colocated'
    assert disagg_lib.resolve_role('prefill') == 'prefill'
    with pytest.raises(ValueError, match='unknown replica role'):
        disagg_lib.resolve_role('oracle')
    import os
    os.environ[disagg_lib.ROLE_ENV] = 'decode'
    try:
        assert disagg_lib.resolve_role(None) == 'decode'
    finally:
        del os.environ[disagg_lib.ROLE_ENV]

    from skypilot_tpu.serve import placement
    from skypilot_tpu.serve.service_spec import SkyServiceSpec
    spec = SkyServiceSpec.from_yaml_config({
        'readiness_probe': '/readiness',
        'replicas': 4,
        'load_balancing_policy': 'phase_aware',
        'disaggregation': {'prefill_replicas': 1,
                           'decode_replicas': 2},
    })
    assert spec.disagg_enabled
    assert spec.to_yaml_config()['disaggregation'] == {
        'prefill_replicas': 1, 'decode_replicas': 2}
    roles = []
    for _ in range(4):
        roles.append(placement.role_for_new_replica(spec, roles))
    assert roles == ['prefill', 'decode', 'decode', 'colocated']
    # A dead prefill worker's replacement re-fills the prefill pool.
    assert placement.role_for_new_replica(
        spec, ['decode', 'decode', 'colocated']) == 'prefill'
    # No block = everything colocated.
    plain = SkyServiceSpec.from_yaml_config(
        {'readiness_probe': '/readiness'})
    assert placement.role_for_new_replica(plain, []) == 'colocated'
    # One-sided pools are refused loudly.
    from skypilot_tpu import exceptions
    with pytest.raises(exceptions.InvalidServiceSpecError,
                       match='BOTH'):
        SkyServiceSpec.from_yaml_config({
            'readiness_probe': '/readiness',
            'disaggregation': {'prefill_replicas': 2}})


def test_handoff_fault_site_registered():
    assert 'handoff' in faults_lib.FAULT_SITES
    inj = faults_lib.FaultInjector({'rules': [
        {'kind': 'partial_response', 'site': 'handoff', 'at': 1}]})
    assert inj.fire('handoff').kind == 'partial_response'
    assert inj.fire('handoff') is None


# ----------------------------------------------------- jaxpr audit gate
def test_disagg_audit_preset():
    from skypilot_tpu.analysis import jaxpr_audit
    assert 'disagg' in jaxpr_audit.PRESETS
    assert 'disagg' in jaxpr_audit.DEFAULT_PRESETS
    report = jaxpr_audit.PRESETS['disagg']()
    assert report.ok(), report.format()
    # Phase isolation: the decode worker compiled ZERO prefill
    # programs across the whole audited run.
    key = 'decode-worker prefill programs (must stay 0)'
    assert report.compile_counts[key] == (0, 0)


# ------------------------------------------------------- server-level e2e
def _start_server(port, **kw):
    from skypilot_tpu.serve.server import ModelServer
    kw.setdefault('max_batch', 2)
    kw.setdefault('max_seq', 128)
    srv = ModelServer('tiny', port=port, **kw)
    srv.start(block=False)
    return srv


def _generate(base, payload, timeout=120, headers=None):
    h = {'Content-Type': 'application/json'}
    h.update(headers or {})
    req = urllib.request.Request(base + '/generate',
                                 json.dumps(payload).encode(), h)
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def _stream(base, payload, timeout=120, headers=None):
    h = {'Content-Type': 'application/json'}
    h.update(headers or {})
    req = urllib.request.Request(base + '/generate',
                                 json.dumps(payload).encode(), h)
    tokens, done, error = [], None, None
    with urllib.request.urlopen(req, timeout=timeout) as r:
        for raw in r:
            if not raw.startswith(b'data:'):
                continue
            ev = json.loads(raw[5:].strip())
            if 'token' in ev:
                tokens.append(int(ev['token']))
            if ev.get('done'):
                done = ev
            if 'error' in ev:
                error = ev
    return tokens, done, error


def test_server_handoff_e2e_byte_identical():
    """prefill proc → decode proc over HTTP: streaming and
    non-streaming handoffs both land int8 KV on the wire and continue
    byte-identically to a colocated run; telemetry moves."""
    pd = common_utils.find_free_port(19300)
    pp = common_utils.find_free_port(pd + 1)
    dec = _start_server(pd, role='decode', kv_cache_dtype='int8')
    pre = _start_server(pp, role='prefill', kv_cache_dtype='int8',
                        handoff_targets=[f'http://127.0.0.1:{pd}'])
    try:
        assert dec._ready.wait(180) and pre._ready.wait(180)
        prompt = [3, 1, 4, 1, 5, 9, 2, 6] * 3
        reference = _generate(f'http://127.0.0.1:{pd}',
                              {'prompt': prompt,
                               'max_new_tokens': 16})['tokens']
        reg = telemetry.get_registry()
        sent0 = reg.get('skytpu_disagg_handoff_total',
                        outcome='sent').value
        bytes0 = reg.get('skytpu_kv_transfer_bytes_total',
                         direction='export').value
        h_transfer = reg.histogram('skytpu_kv_transfer_seconds')
        t_count0 = h_transfer.count

        # Non-streaming: picked up via the static target list.
        res = _generate(f'http://127.0.0.1:{pp}',
                        {'prompt': prompt, 'max_new_tokens': 16})
        assert res['tokens'] == reference
        assert res['handoff'] is True

        # Streaming, explicit router header.
        tokens, done, error = _stream(
            f'http://127.0.0.1:{pp}',
            {'prompt': prompt, 'max_new_tokens': 16, 'stream': True},
            headers={'X-Handoff-Target': f'http://127.0.0.1:{pd}'})
        assert error is None
        assert tokens == reference
        assert done['tokens'] == reference
        assert done['finish_reason'] == 'length'

        assert reg.get('skytpu_disagg_handoff_total',
                       outcome='sent').value == sent0 + 2
        assert reg.get('skytpu_disagg_handoff_total',
                       outcome='completed').value >= 2
        moved = reg.get('skytpu_kv_transfer_bytes_total',
                        direction='export').value - bytes0
        assert moved > 0
        assert h_transfer.count >= t_count0 + 2
        # Prefill worker served only the first token locally per
        # request; the decode worker decoded the rest.
        with urllib.request.urlopen(
                f'http://127.0.0.1:{pp}/metrics?format=json',
                timeout=10) as r:
            m = json.loads(r.read())
        assert m['disagg']['role'] == 'prefill'
        assert m['disagg']['kv_transfer_bytes']['export'] > 0
    finally:
        dec.stop()
        pre.stop()


def test_server_handoff_fallback_local():
    """No decode worker (dead target / injected handoff failure): the
    prefill replica decodes locally — same tokens, nothing lost."""
    pp = common_utils.find_free_port(19350)
    pre = _start_server(
        pp, role='prefill',
        handoff_targets=['http://127.0.0.1:9'],     # nothing listening
        fault_spec=None)
    try:
        assert pre._ready.wait(180)
        prompt = [2, 7, 1, 8] * 4
        # Dead static target is never picked (headroom probe fails) →
        # no handoff attempted, local serving.
        res = _generate(f'http://127.0.0.1:{pp}',
                        {'prompt': prompt, 'max_new_tokens': 10})
        assert len(res['tokens']) == 10
        assert 'handoff' not in res
        # Explicit header to a dead target: handoff POST fails →
        # colocated fallback, same output.
        res2 = _generate(
            f'http://127.0.0.1:{pp}',
            {'prompt': prompt, 'max_new_tokens': 10},
            headers={'X-Handoff-Target': 'http://127.0.0.1:9'})
        assert res2['tokens'] == res['tokens']
        reg = telemetry.get_registry()
        assert reg.get('skytpu_disagg_handoff_total',
                       outcome='failed').value >= 1
        # Streaming with an injected handoff fault: falls back too.
        pre._faults = faults_lib.FaultInjector({'rules': [
            {'kind': 'partial_response', 'site': 'handoff', 'at': 1}]})
        tokens, done, error = _stream(
            f'http://127.0.0.1:{pp}',
            {'prompt': prompt, 'max_new_tokens': 10, 'stream': True},
            headers={'X-Handoff-Target': f'http://127.0.0.1:{pp}'})
        assert error is None and done is not None
        assert tokens == res['tokens']
    finally:
        pre.stop()


class _FakeController:
    """Answers the LB's sync POST with replica URLs + planned roles
    (the round-7 chaos harness's controller stub, extended with the
    disaggregation role payload)."""

    def __init__(self, replica_urls, roles=None, retry_after_s=5):
        import http.server as hs
        self.replica_urls = list(replica_urls)
        self.roles = dict(roles or {})
        outer = self

        class H(hs.BaseHTTPRequestHandler):
            timeout = 30

            def log_message(self, *a):
                del a

            def do_POST(self):  # noqa: N802
                body = json.dumps({
                    'ready_replica_urls': outer.replica_urls,
                    'retry_after_s': retry_after_s,
                    'replica_roles': outer.roles,
                }).encode()
                self.send_response(200)
                self.send_header('Content-Length', str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.port = common_utils.find_free_port(19500)
        self.httpd = hs.ThreadingHTTPServer(('127.0.0.1', self.port), H)
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()

    @property
    def url(self):
        return f'http://127.0.0.1:{self.port}'

    def stop(self):
        self.httpd.shutdown()


def _start_lb(controller_url, monkeypatch, policy='phase_aware',
              max_attempts=4):
    from skypilot_tpu.serve.load_balancer import SkyServeLoadBalancer
    monkeypatch.setenv('SKYTPU_LB_SYNC', '3600')
    port = common_utils.find_free_port(19600)
    lb = SkyServeLoadBalancer(controller_url=controller_url, port=port,
                              policy_name=policy,
                              max_attempts=max_attempts)
    lb.start()
    lb._sync_once()
    return lb, port


def test_decode_worker_death_midstream_zero_lost(monkeypatch):
    """Extends the round-7 chaos contract to disaggregated fleets: the
    decode worker crash-injects mid-continuation; the prefill relay
    surfaces a retryable error with the generated prefix; the LB's
    in-flight recovery resubmits prompt+prefix through the phase-aware
    policy (prefill worker → surviving decode pool, here the colocated
    fallback) — the client sees ONE stream, byte-identical to an
    uninterrupted run. Zero lost requests."""
    pd = common_utils.find_free_port(19700)
    pp = common_utils.find_free_port(pd + 1)
    # The decode worker dies early in the continuation (its engine
    # loop only ever runs for ingested work, so iteration 2 is
    # mid-continuation with most of the budget still owed).
    dec = _start_server(pd, role='decode',
                        fault_spec={'seed': 0, 'rules': [
                            {'kind': 'replica_crash',
                             'site': 'engine_step', 'at': 2}]})
    pre = _start_server(pp, role='prefill')
    urls = {pp: 'prefill', pd: 'decode'}
    try:
        assert dec._ready.wait(180) and pre._ready.wait(180)
        prompt, gen = [3, 1, 4, 1, 5] * 3, 40
        # Reference BEFORE any fault fires, from the prefill worker's
        # local (colocated-fallback) path — no target header, so no
        # handoff happens for this one.
        reference = _generate(f'http://127.0.0.1:{pp}',
                              {'prompt': prompt,
                               'max_new_tokens': gen})['tokens']
        ctrl = _FakeController(
            [f'http://127.0.0.1:{p}' for p in (pp, pd)],
            roles={f'http://127.0.0.1:{p}': r for p, r in urls.items()})
        lb, lport = _start_lb(ctrl.url, monkeypatch)
        try:
            tokens, done, error = _stream(
                f'http://127.0.0.1:{lport}',
                {'prompt': prompt, 'max_new_tokens': gen,
                 'stream': True}, timeout=180)
            assert error is None, error
            assert done is not None
            assert tokens == reference, (tokens, reference)
            assert done['tokens'] == reference
            # The crash really happened and was survived.
            reg = telemetry.get_registry()
            crash = reg.get('skytpu_faults_injected_total',
                            kind='replica_crash')
            assert crash is not None and crash.value >= 1
            assert dec._error is not None
            assert reg.get('skytpu_requests_migrated_total',
                           outcome='completed').value >= 1
        finally:
            lb.stop()
            ctrl.stop()
    finally:
        dec.stop()
        pre.stop()


def test_kv_ingest_malformed_and_capacity():
    port = common_utils.find_free_port(19400)
    srv = _start_server(port, role='decode')
    base = f'http://127.0.0.1:{port}'
    try:
        assert srv._ready.wait(180)
        reg = telemetry.get_registry()
        rej0 = reg.get('skytpu_disagg_handoff_total',
                       outcome='rejected').value
        # Garbage blob → 400, counted.
        req = urllib.request.Request(
            base + '/kv/ingest', data=b'not a handoff',
            headers={'Content-Type': 'application/octet-stream'})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=30)
        assert ei.value.code == 400
        err = json.loads(ei.value.read())['error']
        assert err['type'] == 'invalid_handoff'
        # Mismatched model shape → 400 too (valid wire, wrong engine).
        blob = kv_transfer.encode_handoff(_fake_snapshot(
            'bf16', n_layers=99))
        req = urllib.request.Request(
            base + '/kv/ingest', data=blob,
            headers={'Content-Type': 'application/octet-stream'})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=30)
        assert ei.value.code == 400
        assert reg.get('skytpu_disagg_handoff_total',
                       outcome='rejected').value >= rej0 + 2
    finally:
        srv.stop()
