"""Fault-tolerant serving (round 7): deterministic fault injection,
graceful drain, and in-flight request recovery.

The contract under test is **zero lost requests**: under any injected
fault (replica crash mid-stream, probe timeouts, preemption signals,
broken proxy streams), every accepted request either completes — with
byte-identical greedy output to an uninterrupted run — or receives a
clean retryable error carrying ``Retry-After``.
"""
import json
import os
import threading
import time
import urllib.error
import urllib.request

import jax
import pytest

from skypilot_tpu import telemetry
from skypilot_tpu.serve import faults as faults_lib
from skypilot_tpu.utils import common_utils

jax.config.update('jax_platforms', 'cpu')


# ---------------------------------------------------------------- helpers
class _FakeController:
    """Answers the LB's sync POST with a settable replica list + hint."""

    def __init__(self, replica_urls, retry_after_s=7):
        import http.server
        self.replica_urls = list(replica_urls)
        self.retry_after_s = retry_after_s
        outer = self

        class H(http.server.BaseHTTPRequestHandler):
            timeout = 30

            def log_message(self, *a):
                del a

            def do_POST(self):  # noqa: N802
                body = json.dumps({
                    'ready_replica_urls': outer.replica_urls,
                    'retry_after_s': outer.retry_after_s,
                }).encode()
                self.send_response(200)
                self.send_header('Content-Length', str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        import http.server as hs
        self.port = common_utils.find_free_port(19500)
        self.httpd = hs.ThreadingHTTPServer(('127.0.0.1', self.port), H)
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()

    @property
    def url(self):
        return f'http://127.0.0.1:{self.port}'

    def stop(self):
        self.httpd.shutdown()


def _start_server(port, fault_spec=None, **kw):
    from skypilot_tpu.serve.server import ModelServer
    kw.setdefault('max_batch', 2)
    kw.setdefault('max_seq', 128)
    srv = ModelServer('tiny', port=port, fault_spec=fault_spec, **kw)
    srv.start(block=False)
    return srv


def _start_lb(controller_url, monkeypatch, max_attempts=3):
    from skypilot_tpu.serve.load_balancer import SkyServeLoadBalancer
    monkeypatch.setenv('SKYTPU_LB_SYNC', '3600')   # no background churn
    port = common_utils.find_free_port(19600)
    lb = SkyServeLoadBalancer(controller_url=controller_url, port=port,
                              max_attempts=max_attempts)
    lb.start()
    lb._sync_once()
    return lb, port


def _generate(base, payload, timeout=120, headers=None):
    h = {'Content-Type': 'application/json'}
    h.update(headers or {})
    req = urllib.request.Request(base + '/generate',
                                 json.dumps(payload).encode(), h)
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def _stream(base, payload, timeout=120):
    """Collect a /generate SSE stream: (token list, done event|None,
    error event|None)."""
    req = urllib.request.Request(
        base + '/generate', json.dumps(payload).encode(),
        {'Content-Type': 'application/json'})
    tokens, done, error = [], None, None
    with urllib.request.urlopen(req, timeout=timeout) as r:
        for raw in r:
            if not raw.startswith(b'data:'):
                continue
            ev = json.loads(raw[5:].strip())
            if 'token' in ev:
                tokens.append(int(ev['token']))
            if ev.get('done'):
                done = ev
            if 'error' in ev:
                error = ev
    return tokens, done, error


# ------------------------------------------------------- injector units
def test_fault_injector_deterministic_counters():
    inj = faults_lib.FaultInjector({'seed': 7, 'rules': [
        {'kind': 'engine_stall', 'site': 'engine_step', 'at': 2},
        {'kind': 'probe_timeout', 'site': 'probe', 'every': 3,
         'count': 2},
    ]})
    hits = [inj.fire('engine_step') for _ in range(4)]
    assert [h.kind if h else None for h in hits] == \
        [None, 'engine_stall', None, None]
    probe_hits = [inj.fire('probe') for _ in range(9)]
    # every=3 capped at count=2: invocations 3 and 6 fire, 9 does not.
    assert [i + 1 for i, h in enumerate(probe_hits) if h] == [3, 6]
    assert inj.site_count('probe') == 9


def test_fault_injector_seeded_prob_reproducible():
    spec = {'seed': 123, 'rules': [
        {'kind': 'slow_response', 'site': 'proxy', 'prob': 0.5}]}
    a = [bool(faults_lib.FaultInjector(spec).fire('proxy'))
         for _ in range(1)]
    seq1 = [bool(r) for r in
            (lambda i: [i.fire('proxy') for _ in range(20)])(
                faults_lib.FaultInjector(spec))]
    seq2 = [bool(r) for r in
            (lambda i: [i.fire('proxy') for _ in range(20)])(
                faults_lib.FaultInjector(spec))]
    assert seq1 == seq2 and any(seq1) and not all(seq1)
    del a


def test_fault_spec_env_and_validation(monkeypatch, tmp_path):
    assert faults_lib.make_injector(None) is None or \
        os.environ.get(faults_lib.FAULT_SPEC_ENV)
    monkeypatch.setenv(faults_lib.FAULT_SPEC_ENV, json.dumps(
        {'rules': [{'kind': 'replica_crash', 'site': 'engine_step',
                    'at': 1}]}))
    inj = faults_lib.get_injector()
    assert inj is not None and inj.fire('engine_step').kind == \
        'replica_crash'
    spec_file = tmp_path / 'spec.json'
    spec_file.write_text(json.dumps({'rules': []}))
    assert faults_lib.make_injector(f'@{spec_file}') is not None
    with pytest.raises(ValueError, match='unknown fault kind'):
        faults_lib.make_injector(
            {'rules': [{'kind': 'meteor', 'site': 'probe'}]})
    with pytest.raises(ValueError, match='unknown fault site'):
        faults_lib.make_injector(
            {'rules': [{'kind': 'replica_crash', 'site': 'moon'}]})


def test_inference_layer_never_imports_faults():
    """Injection disabled ⇒ zero overhead on the hot path: the compute
    layer must not even reference the faults module (the jaxpr-audit
    presets therefore see byte-identical programs either way)."""
    import skypilot_tpu
    root = os.path.join(os.path.dirname(skypilot_tpu.__file__),
                        'inference')
    for fname in os.listdir(root):
        if not fname.endswith('.py'):
            continue
        with open(os.path.join(root, fname), encoding='utf-8') as f:
            assert 'faults' not in f.read(), fname


# ------------------------------------------------------ backoff jitter
def _make_manager(tmp_path, monkeypatch):
    monkeypatch.setenv('SKYTPU_SERVE_DIR', str(tmp_path / 'serve'))
    from skypilot_tpu.serve.replica_managers import ReplicaManager
    from skypilot_tpu.serve.service_spec import SkyServiceSpec
    spec = SkyServiceSpec.from_yaml_config(
        {'readiness_probe': '/readiness'})
    return ReplicaManager('chaos-test', spec, {})


def test_bump_backoff_jitter_and_cap(tmp_path, monkeypatch):
    import random as random_mod
    from skypilot_tpu.serve import replica_managers as rm
    monkeypatch.setenv('SKYTPU_SERVE_LAUNCH_BACKOFF', '4')
    mgr = _make_manager(tmp_path, monkeypatch)
    mgr._rng = random_mod.Random(0)
    assert not mgr.in_launch_backoff()
    assert mgr.backoff_remaining() == 0.0
    delays = []
    for _ in range(12):
        t0 = time.time()
        mgr._bump_backoff()
        delays.append(mgr._backoff_until - t0)
        assert mgr.in_launch_backoff()
    # Jittered exponential: each delay lands in
    # [frac, 1.0] x min(base 2^(n-1), cap); the cap is a hard ceiling.
    base, cap = 4.0, rm._LAUNCH_BACKOFF_CAP
    for n, d in enumerate(delays, start=1):
        target = min(base * 2 ** (n - 1), cap)
        assert rm._BACKOFF_JITTER_FRAC * target - 0.05 <= d <= \
            target + 0.05, (n, d, target)
    assert all(d <= cap + 0.05 for d in delays)
    # Jitter actually varies (not a constant multiplier).
    late = [d for n, d in enumerate(delays, start=1)
            if base * 2 ** (n - 1) >= cap]
    assert len(set(round(d, 3) for d in late)) > 1, late
    # A successful probe resets it (probe_all does this inline; the
    # fields are the contract).
    with mgr._lock:
        mgr._launch_failures = 0
        mgr._backoff_until = 0.0
    assert not mgr.in_launch_backoff()


def test_retry_after_hint_tracks_backoff(tmp_path, monkeypatch):
    monkeypatch.setenv('SKYTPU_SERVE_LAUNCH_BACKOFF', '40')
    mgr = _make_manager(tmp_path, monkeypatch)
    assert mgr.retry_after_hint() == 15          # no replicas at all
    mgr._bump_backoff()
    hint = mgr.retry_after_hint()
    assert 40 * 0.5 - 1 <= hint <= 41            # backoff remainder


# --------------------------------------------------- probe/preempt faults
def test_probe_timeout_injection(tmp_path, monkeypatch):
    """An injected probe_timeout makes a live, answering replica look
    probe-dead — the consecutive-failure escalation is exercisable."""
    import http.server

    class H(http.server.BaseHTTPRequestHandler):
        timeout = 10

        def log_message(self, *a):
            del a

        def do_GET(self):  # noqa: N802
            self.send_response(200)
            self.send_header('Content-Length', '2')
            self.end_headers()
            self.wfile.write(b'ok')

    port = common_utils.find_free_port(19700)
    httpd = http.server.ThreadingHTTPServer(('127.0.0.1', port), H)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        mgr = _make_manager(tmp_path, monkeypatch)
        from skypilot_tpu.serve.replica_managers import ReplicaInfo
        info = ReplicaInfo(1, 'c', 1, False, port)
        info.url = f'http://127.0.0.1:{port}'
        assert mgr._probe_one(info) is True         # genuinely alive
        mgr._faults = faults_lib.FaultInjector({'rules': [
            {'kind': 'probe_timeout', 'site': 'probe', 'at': 2,
             'delay_s': 0.01}]})
        assert mgr._probe_one(info) is True         # invocation 1
        assert mgr._probe_one(info) is False        # injected timeout
        assert mgr._probe_one(info) is True         # back to honest
    finally:
        httpd.shutdown()


def test_replica_manager_drain_flow(tmp_path, monkeypatch):
    """drain(): READY -> DRAINING (out of ready_urls immediately), the
    replica's /drain contract is honored, and the cluster tears down
    once the replica reports drained."""
    import http.server
    from skypilot_tpu.serve import serve_state

    state = {'drained': False}

    class H(http.server.BaseHTTPRequestHandler):
        timeout = 10

        def log_message(self, *a):
            del a

        def _send(self, payload):
            body = json.dumps(payload).encode()
            self.send_response(200)
            self.send_header('Content-Length', str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_POST(self):  # noqa: N802
            self._send({'draining': True, 'inflight': 1})

        def do_GET(self):  # noqa: N802
            self._send({'draining': True,
                        'drained': state['drained'], 'inflight': 0})

    port = common_utils.find_free_port(19750)
    httpd = http.server.ThreadingHTTPServer(('127.0.0.1', port), H)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    mgr = _make_manager(tmp_path, monkeypatch)
    try:
        from skypilot_tpu.serve.replica_managers import ReplicaInfo
        info = ReplicaInfo(1, 'chaos-drain-c', 1, False, port)
        info.url = f'http://127.0.0.1:{port}'
        info.status = serve_state.ReplicaStatus.READY
        with mgr._lock:
            mgr._replicas[1] = info
        assert mgr.ready_urls() == [info.url]
        assert mgr.drain(1, deadline_s=15) is True
        assert info.status == serve_state.ReplicaStatus.DRAINING
        assert mgr.ready_urls() == []            # out of rotation NOW
        assert mgr.drain(1) is False             # idempotent
        time.sleep(0.8)                          # mid-drain: still up
        assert info.status == serve_state.ReplicaStatus.DRAINING
        state['drained'] = True
        deadline = time.time() + 20
        while time.time() < deadline and 1 in mgr._replicas:
            time.sleep(0.1)
        assert 1 not in mgr._replicas            # torn down after drain
    finally:
        httpd.shutdown()


def test_preemption_warning_routes_through_drain(tmp_path, monkeypatch):
    from skypilot_tpu.serve import serve_state
    mgr = _make_manager(tmp_path, monkeypatch)
    from skypilot_tpu.serve.replica_managers import ReplicaInfo
    info = ReplicaInfo(2, 'chaos-warn-c', 1, True, 12345)
    info.url = 'http://127.0.0.1:1'              # nothing listening
    info.status = serve_state.ReplicaStatus.READY
    with mgr._lock:
        mgr._replicas[2] = info
    assert mgr.handle_preemption_warning(2, deadline_s=5) is True
    # DRAINING first; the unreachable drain endpoint then degrades to
    # plain teardown on the drain thread (may already have happened).
    assert info.status in (serve_state.ReplicaStatus.DRAINING,
                           serve_state.ReplicaStatus.SHUTTING_DOWN)
    deadline = time.time() + 20
    while time.time() < deadline and 2 in mgr._replicas:
        time.sleep(0.1)
    assert 2 not in mgr._replicas


# ------------------------------------------------------- engine export
@pytest.mark.parametrize('kind', ['slot', 'paged'])
def test_export_inflight_both_engines(kind):
    from skypilot_tpu.models import configs
    cfg = configs.get_config('tiny')
    if kind == 'paged':
        from skypilot_tpu.inference.paged import PagedInferenceEngine
        eng = PagedInferenceEngine(cfg, max_batch=2, max_seq=64)
    else:
        from skypilot_tpu.inference.engine import InferenceEngine
        eng = InferenceEngine(cfg, max_batch=2, max_seq=64)
    eng.add_request([1, 2, 3], max_new_tokens=8)
    eng.add_request([4, 5], max_new_tokens=4, temperature=0.7,
                    top_k=5, priority=1)
    eng.add_request([6, 7, 8, 9], max_new_tokens=4)   # queued (2 slots)
    for _ in range(3):
        eng.step(horizon=2)
    exported = eng.export_inflight()
    by_prompt = {tuple(e['prompt']): e for e in exported}
    assert (1, 2, 3) in by_prompt and (4, 5) in by_prompt
    first = by_prompt[(1, 2, 3)]
    assert first['remaining_new_tokens'] == \
        first['max_new_tokens'] - len(first['output'])
    sampled = by_prompt[(4, 5)]
    assert sampled['temperature'] == 0.7 and sampled['top_k'] == 5
    assert sampled['priority'] == 1
    # Finished requests drop out of the export.
    eng.run_to_completion(horizon=8)
    assert eng.export_inflight() == []


# ------------------------------------------------------------ drain e2e
def test_drain_endpoint_completes_within_deadline():
    port = common_utils.find_free_port(19800)
    srv = _start_server(port)
    base = f'http://127.0.0.1:{port}'
    try:
        assert srv._ready.wait(180)
        reg = telemetry.get_registry()
        h_drain = reg.histogram('skytpu_replica_drain_seconds')
        drain_count0 = h_drain.count
        streams = [srv.submit_stream([3 + i, 5, 7], max_new_tokens=24,
                                     temperature=0.0, top_k=0,
                                     eos_id=None) for i in range(2)]
        status = json.loads(urllib.request.urlopen(
            urllib.request.Request(
                base + '/drain',
                data=json.dumps({'deadline_s': 60}).encode(),
                headers={'Content-Type': 'application/json'}),
            timeout=10).read())
        assert status['draining'] is True
        # New work is refused with a retryable 503 + Retry-After.
        with pytest.raises(urllib.error.HTTPError) as ei:
            _generate(base, {'prompt': [1, 2], 'max_new_tokens': 2},
                      timeout=30)
        assert ei.value.code == 503
        assert int(ei.value.headers['Retry-After']) >= 1
        err = json.loads(ei.value.read())['error']
        assert err['reason'] == 'draining'
        # In-flight requests run to completion (not cancelled).
        for sr in streams:
            tokens = []
            while True:
                token, finished = sr.outbox.get(timeout=60)
                assert token is not None, sr.outbox.error
                tokens.append(token)
                if finished:
                    break
            assert len(tokens) == 24
            srv.finish_stream(sr)
        # Drain completes well within the deadline and is measured.
        deadline = time.time() + 30
        while time.time() < deadline:
            st = json.loads(urllib.request.urlopen(
                base + '/drain', timeout=10).read())
            if st['drained']:
                break
            time.sleep(0.1)
        assert st['drained'] is True and st['inflight'] == 0
        assert h_drain.count == drain_count0 + 1
        # Readiness reports draining (the probe pulls it from rotation).
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(base + '/readiness', timeout=10)
        assert ei.value.code == 503
        assert json.loads(ei.value.read())['status'] == 'draining'
        # Shed counter rode the stable 'draining' reason.
        shed = reg.get('skytpu_sched_shed_total', tier='latency',
                       reason='draining')
        assert shed is not None and shed.value >= 1
    finally:
        srv.stop()


# ----------------------------------------------------------- LB contract
def test_lb_503_no_replicas_json_and_retry_after(monkeypatch):
    ctrl = _FakeController([], retry_after_s=11)
    lb, port = _start_lb(ctrl.url, monkeypatch)
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f'http://127.0.0.1:{port}/x',
                                   timeout=10)
        err = ei.value
        assert err.code == 503
        assert err.headers['Retry-After'] == '11'
        payload = json.loads(err.read())
        assert payload['retryable'] is True
        assert payload['retry_after_s'] == 11
        assert 'No ready replicas' in payload['error']
    finally:
        lb.stop()
        ctrl.stop()


def test_scheduler_429_retry_after_passes_through_lb(monkeypatch):
    port = common_utils.find_free_port(19850)
    srv = _start_server(port)
    try:
        assert srv._ready.wait(180)
        srv.sched._max_queue_tokens = 4        # everything real sheds
        ctrl = _FakeController([f'http://127.0.0.1:{port}'])
        lb, lport = _start_lb(ctrl.url, monkeypatch)
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                _generate(f'http://127.0.0.1:{lport}',
                          {'prompt': [1, 2, 3, 4],
                           'max_new_tokens': 16}, timeout=30)
            err = ei.value
            assert err.code == 429
            payload = json.loads(err.read())['error']
            # Retry-After passed through the LB unmodified.
            assert int(err.headers['Retry-After']) == \
                payload['retry_after_s']
        finally:
            lb.stop()
            ctrl.stop()
    finally:
        srv.stop()


def test_request_key_idempotent_replay():
    port = common_utils.find_free_port(19860)
    srv = _start_server(port)
    base = f'http://127.0.0.1:{port}'
    try:
        assert srv._ready.wait(180)
        payload = {'prompt': [2, 4, 6], 'max_new_tokens': 6,
                   'request_key': 'idem-1'}
        first = _generate(base, payload)
        again = _generate(base, payload)
        assert again['deduped'] is True
        assert again['tokens'] == first['tokens']
        # The header spelling (what the LB mints) dedupes too.
        third = _generate(base, {'prompt': [2, 4, 6],
                                 'max_new_tokens': 6},
                          headers={'X-Request-ID': 'idem-1'})
        assert third['deduped'] is True
        assert third['tokens'] == first['tokens']
    finally:
        srv.stop()


# ------------------------------------------------------- chaos e2e (LB)
def test_mid_stream_migration_byte_identical(monkeypatch):
    """Deterministic mid-stream break (injected partial_response after
    5 token events): the LB migrates the stream to the other replica
    with the generated prefix; the client sees one stream whose final
    tokens are byte-identical to an uninterrupted greedy run."""
    pa = common_utils.find_free_port(19900)
    pb = common_utils.find_free_port(pa + 1)
    sa = _start_server(pa)
    sb = _start_server(pb)
    try:
        assert sa._ready.wait(180) and sb._ready.wait(180)
        prompt, gen = [3, 1, 4, 1, 5], 16
        reference = _generate(f'http://127.0.0.1:{pb}',
                              {'prompt': prompt,
                               'max_new_tokens': gen})['tokens']
        ctrl = _FakeController([f'http://127.0.0.1:{pa}',
                                f'http://127.0.0.1:{pb}'])
        lb, lport = _start_lb(ctrl.url, monkeypatch)
        lb._faults = faults_lib.FaultInjector({'rules': [
            {'kind': 'partial_response', 'site': 'proxy_stream',
             'at': 1, 'after_events': 5}]})
        reg = telemetry.get_registry()
        migrated0 = reg.get('skytpu_requests_migrated_total',
                            outcome='completed').value
        h_rec = reg.histogram('skytpu_replica_recovery_seconds')
        rec0 = h_rec.count
        try:
            tokens, done, error = _stream(
                f'http://127.0.0.1:{lport}',
                {'prompt': prompt, 'max_new_tokens': gen,
                 'stream': True})
            assert error is None
            assert done is not None
            assert tokens == reference, (tokens, reference)
            assert done['tokens'] == reference
            assert reg.get('skytpu_requests_migrated_total',
                           outcome='completed').value == migrated0 + 1
            assert h_rec.count == rec0 + 1
            fault_c = reg.get('skytpu_faults_injected_total',
                              kind='partial_response')
            assert fault_c is not None and fault_c.value >= 1
        finally:
            lb.stop()
            ctrl.stop()
    finally:
        sa.stop()
        sb.stop()


def test_chaos_kill_replica_mid_stream_zero_lost(monkeypatch):
    """THE chaos contract (deterministic seed): one of two replicas is
    crash-injected mid-stream under concurrent load — zero lost
    requests (every accepted stream completes), and every completed
    stream's greedy output is byte-identical to an uninterrupted run."""
    pa = common_utils.find_free_port(19950)
    pb = common_utils.find_free_port(pa + 1)
    # Replica A dies on its 4th engine-loop iteration — mid-stream for
    # whatever it is serving at that point (deterministic given the
    # fault spec; which requests land on A is load-dependent, and the
    # contract must hold either way).
    sa = _start_server(pa, fault_spec={'seed': 0, 'rules': [
        {'kind': 'replica_crash', 'site': 'engine_step', 'at': 4}]})
    sb = _start_server(pb)
    try:
        assert sa._ready.wait(180) and sb._ready.wait(180)
        prompts = [[11 + i, 3, 5, 7 + i] for i in range(6)]
        gen = 24
        reference = {
            tuple(p): _generate(f'http://127.0.0.1:{pb}',
                                {'prompt': p,
                                 'max_new_tokens': gen})['tokens']
            for p in prompts}
        ctrl = _FakeController([f'http://127.0.0.1:{pa}',
                                f'http://127.0.0.1:{pb}'])
        lb, lport = _start_lb(ctrl.url, monkeypatch, max_attempts=4)
        results = {}
        errors = {}

        def one(p):
            try:
                results[tuple(p)] = _stream(
                    f'http://127.0.0.1:{lport}',
                    {'prompt': p, 'max_new_tokens': gen,
                     'stream': True})
            except Exception as e:  # noqa: BLE001 - recorded and asserted
                errors[tuple(p)] = f'{type(e).__name__}: {e}'

        try:
            threads = [threading.Thread(target=one, args=(p,))
                       for p in prompts]
            for t in threads:
                t.start()
                time.sleep(0.05)
            for t in threads:
                t.join(timeout=180)
            assert not errors, errors
            lost = []
            for p in prompts:
                tokens, done, error = results[tuple(p)]
                if error is not None or done is None:
                    lost.append((p, error))
                    continue
                assert tokens == reference[tuple(p)], \
                    (p, tokens, reference[tuple(p)])
            # ZERO lost requests: every accepted stream completed with
            # byte-identical output (a retryable error event would have
            # been acceptable per the contract only if no replica
            # survived — here B is alive, so everything completes).
            assert lost == [], lost
            # The injected crash actually happened and was survived.
            reg = telemetry.get_registry()
            crash = reg.get('skytpu_faults_injected_total',
                            kind='replica_crash')
            assert crash is not None and crash.value >= 1
            assert sa._error is not None          # A really died
        finally:
            lb.stop()
            ctrl.stop()
    finally:
        sa.stop()
        sb.stop()
