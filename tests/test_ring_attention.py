"""Ring attention on the virtual 8-device CPU mesh: exactness vs the
single-device reference, GQA, causal/non-causal, and the trainer
integration the SURVEY §5 long-context mandate asks for."""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu.models import configs
from skypilot_tpu.ops.attention import reference_attention
from skypilot_tpu.ops.ring_attention import ring_attention
from skypilot_tpu.parallel import mesh as mesh_lib
from skypilot_tpu.train.trainer import TrainConfig, Trainer

pytestmark = pytest.mark.slow


def _mesh(sp: int, dp: int = 1) -> jax.sharding.Mesh:
    spec = mesh_lib.MeshSpec(dp=dp, fsdp=8 // (sp * dp), sp=sp, tp=1)
    return mesh_lib.make_mesh(spec)


def _rand_qkv(b=4, s=32, h=4, hkv=4, d=16, dtype=jnp.float32):
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (b, s, h, d), dtype)
    k = jax.random.normal(ks[1], (b, s, hkv, d), dtype)
    v = jax.random.normal(ks[2], (b, s, hkv, d), dtype)
    return q, k, v


@pytest.mark.parametrize('sp', [2, 4])
@pytest.mark.parametrize('causal', [True, False])
def test_matches_reference(sp, causal):
    mesh = _mesh(sp)
    q, k, v = _rand_qkv()
    ref = reference_attention(q, k, v, causal=causal)
    with mesh:
        out = jax.jit(lambda q, k, v: ring_attention(
            q, k, v, mesh, causal=causal))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_gqa_grouped_heads():
    mesh = _mesh(sp=4)
    q, k, v = _rand_qkv(h=8, hkv=2)
    ref = reference_attention(q, k, v, causal=True)
    with mesh:
        out = ring_attention(q, k, v, mesh, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_sp1_falls_back_to_reference():
    mesh = _mesh(sp=1)
    q, k, v = _rand_qkv()
    with mesh:
        out = ring_attention(q, k, v, mesh, causal=True)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_sharded_inputs_inside_jit():
    """The real call pattern: sharded global arrays, ring inside jit."""
    mesh = _mesh(sp=4, dp=2)
    q, k, v = _rand_qkv(b=4, s=64)
    qs = jax.device_put(q, jax.sharding.NamedSharding(
        mesh, mesh_lib.spec_for(('batch', 'seq', 'heads', 'head_dim'))))
    ref = reference_attention(q, k, v, causal=True)
    with mesh:
        out = jax.jit(lambda q, k, v: ring_attention(
            q, k, v, mesh, causal=True))(qs, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


class TestTrainerIntegration:

    def _loss_after_step(self, attn_impl: str, sp: int) -> float:
        cfg = dataclasses.replace(configs.TINY, remat='none')
        trainer = Trainer(
            cfg,
            mesh_spec=mesh_lib.MeshSpec(dp=1, fsdp=8 // (sp * 2), sp=sp,
                                        tp=2),
            train_config=TrainConfig(warmup_steps=1, total_steps=4,
                                     attn_impl=attn_impl))
        state = trainer.init(jax.random.PRNGKey(0))
        rng = np.random.RandomState(0)
        data = rng.randint(0, 250, size=(8, 33))
        batch = {'inputs': jnp.asarray(data[:, :-1], jnp.int32),
                 'targets': jnp.asarray(data[:, 1:], jnp.int32)}
        _, metrics = trainer.step(state, batch)
        return float(metrics['loss'])

    def test_ring_training_matches_xla_attention(self):
        """Same data, same init: ring-attention loss == xla-path loss.
        This is the 'seq: sp rule backed by a real kernel path' check —
        the trainer accepts sp>1 with exact attention semantics."""
        loss_ring = self._loss_after_step('ring', sp=2)
        loss_xla = self._loss_after_step('xla', sp=2)
        assert abs(loss_ring - loss_xla) < 2e-2, (loss_ring, loss_xla)


class TestZigzag:
    """Balanced causal ring (VERDICT r4 task 6)."""

    @pytest.mark.parametrize('sp', [2, 4, 8])
    def test_zigzag_matches_reference(self, sp):
        mesh = _mesh(sp)
        q, k, v = _rand_qkv(s=32 * (sp // 2) if sp > 2 else 32)
        ref = reference_attention(q, k, v, causal=True)
        with mesh:
            out = jax.jit(lambda q, k, v: ring_attention(
                q, k, v, mesh, causal=True, layout='zigzag',
                block_impl='einsum'))(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_zigzag_gradients_match(self):
        mesh = _mesh(2)
        q, k, v = _rand_qkv()

        def loss_ring(q, k, v):
            return jnp.sum(ring_attention(q, k, v, mesh, causal=True,
                                          layout='zigzag',
                                          block_impl='einsum') ** 2)

        def loss_ref(q, k, v):
            return jnp.sum(reference_attention(q, k, v,
                                               causal=True) ** 2)

        with mesh:
            g_ring = jax.jit(jax.grad(loss_ring, (0, 1, 2)))(q, k, v)
        g_ref = jax.grad(loss_ref, (0, 1, 2))(q, k, v)
        for a, b, name in zip(g_ring, g_ref, 'qkv'):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-4,
                                       err_msg=f'd{name}')

    def test_schedule_balanced_within_one_block(self):
        """The asserted balance property: zigzag per-rank cost is
        rank-independent; contiguous spreads 0.5 .. sp-0.5."""
        from skypilot_tpu.ops.ring_attention import ring_schedule_cost
        for sp in (2, 4, 8, 16):
            zig = [ring_schedule_cost(sp, r, 'zigzag')
                   for r in range(sp)]
            con = [ring_schedule_cost(sp, r, 'contiguous')
                   for r in range(sp)]
            assert max(zig) - min(zig) <= 1.0, (sp, zig)
            assert max(zig) - min(zig) == 0.0          # exactly even
            assert max(con) - min(con) == sp - 1
            # total work conserved (same attention, same FLOPs)
            np.testing.assert_allclose(sum(zig), sum(con))


class TestFlashBlockBody:
    """Pallas flash kernel as the per-block ring body (interpret mode
    on the CPU mesh; VERDICT r4 task 6)."""

    @pytest.mark.parametrize('layout', ['contiguous', 'zigzag'])
    def test_flash_body_matches_einsum_body(self, layout):
        mesh = _mesh(2)
        # 128-aligned halves + d=128 so the kernel tiles.
        q, k, v = _rand_qkv(b=4, s=512, h=2, hkv=2, d=128)
        with mesh:
            ref = jax.jit(lambda q, k, v: ring_attention(
                q, k, v, mesh, causal=True, layout=layout,
                block_impl='einsum'))(q, k, v)
            out = jax.jit(lambda q, k, v: ring_attention(
                q, k, v, mesh, causal=True, layout=layout,
                block_impl='flash'))(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-3, atol=2e-3)

    def test_flash_body_gradients(self):
        """Backward re-derives via the einsum reference (custom_vjp):
        grads match the dense reference."""
        mesh = _mesh(2)
        q, k, v = _rand_qkv(b=4, s=512, h=2, hkv=2, d=128)

        def loss_ring(q, k, v):
            return jnp.sum(ring_attention(q, k, v, mesh, causal=True,
                                          layout='zigzag',
                                          block_impl='flash') ** 2)

        def loss_ref(q, k, v):
            return jnp.sum(reference_attention(q, k, v,
                                               causal=True) ** 2)

        with mesh:
            g_ring = jax.jit(jax.grad(loss_ring, (0, 1, 2)))(q, k, v)
        g_ref = jax.grad(loss_ref, (0, 1, 2))(q, k, v)
        for a, b, name in zip(g_ring, g_ref, 'qkv'):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=5e-3, atol=5e-3,
                                       err_msg=f'd{name}')
