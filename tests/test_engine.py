"""Inference engine tests (CPU, tiny model)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu.inference.engine import InferenceEngine, _bucket_len
from skypilot_tpu.models import configs, llama

# Compile-heavy (jit of full models): slow tier — the fast sweep is
# the orchestration layer (SURVEY §4 offline tier analog).
pytestmark = pytest.mark.slow


@pytest.fixture(scope='module')
def engine_setup():
    cfg = configs.TINY
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def _greedy_reference(params, cfg, prompt, n):
    """Greedy decode via repeated full forwards (no cache)."""
    toks = list(prompt)
    out = []
    for _ in range(n):
        logits, _ = llama.forward(params, jnp.asarray([toks], jnp.int32), cfg)
        nxt = int(jnp.argmax(logits[0, -1]))
        out.append(nxt)
        toks.append(nxt)
    return out


class TestEngine:

    def test_bucketing(self):
        assert _bucket_len(1) == 64
        assert _bucket_len(64) == 64
        assert _bucket_len(65) == 128

    def test_greedy_matches_reference(self, engine_setup):
        cfg, params = engine_setup
        eng = InferenceEngine(cfg, params, max_batch=2, max_seq=128,
                              attn_impl='xla')
        prompt = [3, 1, 4, 1, 5]
        rid = eng.add_request(prompt, max_new_tokens=6)
        done = eng.run_to_completion()
        got = done[rid].output
        want = _greedy_reference(params, cfg, prompt, 6)
        assert got == want, (got, want)

    def test_continuous_batching_multiple_requests(self, engine_setup):
        cfg, params = engine_setup
        eng = InferenceEngine(cfg, params, max_batch=2, max_seq=128,
                              attn_impl='xla')
        prompts = [[3, 1, 4], [1, 5, 9, 2], [6, 5], [3, 5, 8, 9, 7]]
        rids = [eng.add_request(p, max_new_tokens=5) for p in prompts]
        done = eng.run_to_completion()
        assert len(done) == 4
        for rid, p in zip(rids, prompts):
            got = done[rid].output
            want = _greedy_reference(params, cfg, p, 5)
            assert got == want, (p, got, want)

    def test_more_requests_than_slots_drains(self, engine_setup):
        cfg, params = engine_setup
        eng = InferenceEngine(cfg, params, max_batch=2, max_seq=128,
                              attn_impl='xla')
        rids = [eng.add_request([i + 1, i + 2], max_new_tokens=3)
                for i in range(5)]
        done = eng.run_to_completion()
        assert set(done) == set(rids)
        assert all(len(done[r].output) == 3 for r in rids)

    def test_eos_stops_early(self, engine_setup):
        cfg, params = engine_setup
        # find what greedy emits first, use it as eos
        first = _greedy_reference(params, cfg, [3, 1, 4], 1)[0]
        eng = InferenceEngine(cfg, params, max_batch=1, max_seq=128,
                              attn_impl='xla')
        rid = eng.add_request([3, 1, 4], max_new_tokens=10, eos_id=first)
        done = eng.run_to_completion()
        assert done[rid].output == [first]

    def test_capacity_rejected(self, engine_setup):
        cfg, params = engine_setup
        eng = InferenceEngine(cfg, params, max_batch=1, max_seq=64,
                              attn_impl='xla')
        with pytest.raises(ValueError):
            eng.add_request(list(range(1, 60)), max_new_tokens=10)
        with pytest.raises(ValueError):
            eng.add_request([], max_new_tokens=1)

    def test_sampling_temperature(self, engine_setup):
        cfg, params = engine_setup
        eng = InferenceEngine(cfg, params, max_batch=1, max_seq=128,
                              rng_seed=7, attn_impl='xla')
        rid = eng.add_request([3, 1, 4], max_new_tokens=16,
                              temperature=2.0, top_k=50)
        done = eng.run_to_completion()
        toks = done[rid].output
        assert len(toks) == 16
        assert all(0 <= t < cfg.vocab_size for t in toks)
        # hot sampling at high temperature should not be constant
        assert len(set(toks)) > 1

    def test_top_p_tiny_equals_greedy(self, engine_setup):
        """top_p -> 0 collapses the nucleus to the single top token, so
        even hot sampling reproduces the greedy output."""
        cfg, params = engine_setup
        outs = []
        for top_p in (1e-6, None):      # None = greedy run
            eng = InferenceEngine(cfg, params, max_batch=1, max_seq=128,
                                  rng_seed=11, attn_impl='xla')
            if top_p is None:
                rid = eng.add_request([3, 1, 4], max_new_tokens=12)
            else:
                rid = eng.add_request([3, 1, 4], max_new_tokens=12,
                                      temperature=2.0, top_p=top_p)
            outs.append(eng.run_to_completion()[rid].output)
        assert outs[0] == outs[1], outs

    def test_top_p_validated(self, engine_setup):
        cfg, params = engine_setup
        eng = InferenceEngine(cfg, params, max_batch=1, max_seq=128,
                              attn_impl='xla')
        with pytest.raises(ValueError, match='top_p'):
            eng.add_request([1, 2], max_new_tokens=2, top_p=0.0)
        with pytest.raises(ValueError, match='top_p'):
            eng.add_request([1, 2], max_new_tokens=2, top_p=1.5)

    def test_stop_sequence_trims_and_finishes(self, engine_setup):
        cfg, params = engine_setup
        eng = InferenceEngine(cfg, params, max_batch=1, max_seq=128,
                              attn_impl='xla')
        rid = eng.add_request([3, 1, 4], max_new_tokens=12)
        full = eng.run_to_completion()[rid].output
        stop = full[2:4]                 # 2-token stop inside the output
        eng2 = InferenceEngine(cfg, params, max_batch=1, max_seq=128,
                               attn_impl='xla')
        rid = eng2.add_request([3, 1, 4], max_new_tokens=12, stop=[stop])
        req = eng2.run_to_completion()[rid]
        assert req.stop_hit
        assert req.output == full[:2], (req.output, full)

    def test_ttft_recorded(self, engine_setup):
        cfg, params = engine_setup
        eng = InferenceEngine(cfg, params, max_batch=1, max_seq=128,
                              attn_impl='xla')
        rid = eng.add_request([1, 2, 3], max_new_tokens=2)
        done = eng.run_to_completion()
        assert done[rid].ttft_ms is not None
        assert done[rid].finish_time >= done[rid].first_token_time


class TestSampleTokens:
    """Unit tests of the shared sampling op (no model)."""

    def test_nucleus_restricts_support(self):
        from skypilot_tpu.inference.engine import sample_tokens
        # Row distribution: probs ~ [0.5, 0.25, 0.125, ...]; top_p=0.6
        # keeps {0, 1} (mass before token 1 is 0.5 < 0.6; before token
        # 2 it is 0.75 >= 0.6).
        logits = jnp.log(jnp.array([[0.5, 0.25, 0.125, 0.0625, 0.0625]],
                                   jnp.float32))
        temps = jnp.ones((1,), jnp.float32)
        topks = jnp.zeros((1,), jnp.int32)
        topps = jnp.full((1,), 0.6, jnp.float32)
        seen = set()
        for i in range(50):
            tok = sample_tokens(logits, jax.random.PRNGKey(i), temps,
                                topks, topps)
            seen.add(int(tok[0]))
        assert seen == {0, 1}, seen

    def test_top_p_one_keeps_full_support(self):
        from skypilot_tpu.inference.engine import sample_tokens
        logits = jnp.zeros((1, 4), jnp.float32)      # uniform
        temps = jnp.ones((1,), jnp.float32)
        topks = jnp.zeros((1,), jnp.int32)
        topps = jnp.ones((1,), jnp.float32)
        seen = {int(sample_tokens(logits, jax.random.PRNGKey(i), temps,
                                  topks, topps)[0]) for i in range(80)}
        assert seen == {0, 1, 2, 3}, seen

    def test_composes_with_top_k(self):
        from skypilot_tpu.inference.engine import sample_tokens
        # top_k=3 cuts tokens 3-4; top_p=0.75 over the renormalized
        # top-3 ([0.4, 0.33, 0.27]) keeps all three (mass before token
        # 2 is 0.73 < 0.75). Distinct logits: ties at the k-th value
        # would all pass the threshold.
        logits = jnp.log(jnp.array(
            [[0.3, 0.25, 0.2, 0.15, 0.1]], jnp.float32))
        temps = jnp.ones((1,), jnp.float32)
        topks = jnp.full((1,), 3, jnp.int32)
        topps = jnp.full((1,), 0.75, jnp.float32)
        seen = {int(sample_tokens(logits, jax.random.PRNGKey(i), temps,
                                  topks, topps)[0]) for i in range(60)}
        assert seen <= {0, 1, 2}, seen


class TestInt8Quantization:
    """Weight-only int8 serving: halved weight stream, bounded logits
    error, engine path end to end."""

    def test_quantized_forward_close(self):
        import numpy as np
        from skypilot_tpu.models import configs, llama, quantization
        cfg = configs.TINY
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        qparams = quantization.quantize_params(params)
        toks = jnp.arange(32).reshape(1, 32) % cfg.vocab_size
        ref, _ = llama.forward(params, toks, cfg)
        got, _ = llama.forward(qparams, toks, cfg)
        ref = np.asarray(ref, np.float32)
        got = np.asarray(got, np.float32)
        # int8 per-channel: logits track closely but not exactly.
        assert np.abs(ref - got).max() < 0.35, np.abs(ref - got).max()
        # argmax (greedy decode) largely agrees
        agree = (ref.argmax(-1) == got.argmax(-1)).mean()
        assert agree > 0.9, agree

    def test_quantized_bytes_halved(self):
        from skypilot_tpu.models import configs, llama, quantization
        cfg = configs.TINY
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        full = quantization.quantized_bytes(params)
        q = quantization.quantized_bytes(
            quantization.quantize_params(params))
        assert q < 0.7 * full, (q, full)

    def test_engine_generates_int8(self):
        from skypilot_tpu.inference.engine import InferenceEngine
        from skypilot_tpu.models import configs
        eng = InferenceEngine(configs.TINY, max_batch=2, max_seq=64,
                              quantize='int8')
        rid = eng.add_request([1, 2, 3], max_new_tokens=8)
        done = eng.run_to_completion(horizon=8)
        assert len(done[rid].output) == 8

    def test_int8_kv_cache_outputs_close_to_bf16(self):
        """Same prompts, bf16 vs int8(weights+KV): outputs stay close
        (greedy tokens mostly agree on a random tiny model)."""
        from skypilot_tpu.inference.engine import InferenceEngine
        from skypilot_tpu.models import configs
        outs = {}
        for mode in (None, 'int8'):
            eng = InferenceEngine(configs.TINY, max_batch=2, max_seq=64,
                                  quantize=mode)
            assert eng.cache.quantized == (mode == 'int8')
            rid = eng.add_request(list(range(1, 12)), max_new_tokens=6)
            done = eng.run_to_completion(horizon=4)
            outs[mode] = done[rid].output
        assert len(outs['int8']) == 6
        agree = sum(a == b for a, b in zip(outs[None], outs['int8']))
        assert agree >= 3, outs


class TestShardedInt8:
    """int8 quantization combined with a device mesh — the production
    serving shape (7B-class, tp-sharded, quantized; VERDICT r3 task 2;
    ref anchor: vLLM --tensor-parallel-size recipes,
    llm/llama-3/llama3.yaml:109)."""

    def _mesh(self, tp):
        from skypilot_tpu.parallel import mesh as mesh_lib
        spec = mesh_lib.MeshSpec(dp=1, fsdp=1, sp=1, tp=tp)
        return mesh_lib.make_mesh(
            spec, devices=jax.devices()[:spec.num_devices])

    def test_int8_tp2_matches_single_device_int8(self, engine_setup):
        cfg, params = engine_setup
        prompt = [3, 1, 4, 1, 5, 9, 2, 6]
        outs = {}
        for mesh in (None, self._mesh(2)):
            eng = InferenceEngine(cfg, params, max_batch=2, max_seq=128,
                                  mesh=mesh, quantize='int8',
                                  attn_impl='xla')
            rid = eng.add_request(prompt, max_new_tokens=8)
            done = eng.run_to_completion(horizon=4)
            outs['single' if mesh is None else 'tp2'] = done[rid].output
        assert outs['single'] == outs['tp2'], outs

    def test_int8_scales_shard_with_parents(self, engine_setup):
        """Quantized leaves + scales get mesh shardings; scale unit dims
        replicate while output-channel dims follow the parent."""
        cfg, params = engine_setup
        eng = InferenceEngine(cfg, params, max_batch=2, max_seq=64,
                              mesh=self._mesh(2), quantize='int8',
                              attn_impl='xla')
        wq = eng.params['layers']['wq']
        # int8 codes: heads dim (axis 2) sharded over tp=2
        spec = wq.int8.sharding.spec
        assert 'tp' in str(spec), spec
        # scale has the contracted dim as size 1 and still lands on the
        # mesh without error
        assert wq.scale.shape[1] == 1
        # int8 KV cache sharded too: kv_heads dim rides tp
        assert eng.cache.quantized
        assert 'tp' in str(eng.cache.k.sharding.spec), \
            eng.cache.k.sharding.spec

    def test_quantize_logical_axes_structure(self):
        """Axes tree after quantization matches the quantized params
        tree structure exactly (tree_map compatibility)."""
        from skypilot_tpu.models import configs, llama, quantization
        cfg = configs.TINY
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        qparams = quantization.quantize_params(params)
        qaxes = quantization.quantize_logical_axes(
            llama.param_logical_axes(cfg))
        is_leaf = lambda x: isinstance(x, tuple) and all(
            a is None or isinstance(a, str) for a in x)
        # Must not raise: structures line up leaf-for-leaf.
        jax.tree.map(lambda a, p: None, qaxes, qparams, is_leaf=is_leaf)


class TestCancel:
    """Engine-side request cancellation (dropped streaming clients must
    release their decode slot — ADVICE r3 serve/server.py finding)."""

    def test_cancel_queued(self, engine_setup):
        cfg, params = engine_setup
        eng = InferenceEngine(cfg, params, max_batch=1, max_seq=128,
                              attn_impl='xla')
        r1 = eng.add_request([1, 2, 3], max_new_tokens=4)
        r2 = eng.add_request([4, 5, 6], max_new_tokens=4)
        assert eng.cancel(r2)
        done = eng.run_to_completion()
        assert r1 in done and r2 not in done

    def test_cancel_active_frees_slot(self, engine_setup):
        cfg, params = engine_setup
        eng = InferenceEngine(cfg, params, max_batch=1, max_seq=128,
                              attn_impl='xla')
        rid = eng.add_request([1, 2, 3], max_new_tokens=64)
        eng.step(horizon=2)          # admit + some decode
        assert eng.num_active == 1
        assert eng.cancel(rid)
        assert eng.num_active == 0
        assert not eng.has_work()
        assert eng.get_finished(rid) is None   # aborted, not served
        # engine still serves new work afterwards
        r2 = eng.add_request([7, 8], max_new_tokens=3)
        done = eng.run_to_completion()
        assert len(done[r2].output) == 3

    def test_cancel_finished_noop(self, engine_setup):
        cfg, params = engine_setup
        eng = InferenceEngine(cfg, params, max_batch=1, max_seq=128,
                              attn_impl='xla')
        rid = eng.add_request([1, 2], max_new_tokens=2)
        eng.run_to_completion()
        assert not eng.cancel(rid)
        assert eng.get_finished(rid) is not None


class TestAsyncPipeline:
    """The async dispatch pipeline (engine._pending): decode calls are
    enqueued with device-resident tokens/cache and their results read
    back up to _PIPELINE_DEPTH calls later. These tests pin the
    invariants the lag must preserve."""

    def test_results_lag_but_complete(self, engine_setup):
        cfg, params = engine_setup
        eng = InferenceEngine(cfg, params, max_batch=2, max_seq=64)
        rid = eng.add_request([1, 2, 3], max_new_tokens=6)
        all_events = []
        for _ in range(30):
            all_events.extend(eng.step(horizon=2))
            if eng.get_finished(rid):
                break
        assert eng.get_finished(rid) is not None
        toks = [t for r, t, _ in all_events if r == rid]
        assert toks == eng.get_finished(rid).output

    def test_lagged_equals_reference(self, engine_setup):
        """Tokens produced through the pipeline match the no-cache
        greedy reference — the device token chaining (call N+1 fed
        call N's last column without a host trip) must not skew the
        sequence."""
        cfg, params = engine_setup
        eng = InferenceEngine(cfg, params, max_batch=2, max_seq=64)
        prompt = [5, 9, 2, 14]
        rid = eng.add_request(prompt, max_new_tokens=8)
        done = eng.run_to_completion(horizon=4)
        assert done[rid].output == _greedy_reference(params, cfg,
                                                     prompt, 8)

    def test_inflight_bookkeeping_drains(self, engine_setup):
        cfg, params = engine_setup
        eng = InferenceEngine(cfg, params, max_batch=2, max_seq=64)
        for _ in range(4):
            eng.add_request([1, 2, 3], max_new_tokens=5)
        eng.run_to_completion(horizon=4)
        assert eng._inflight_steps == 0
        assert not eng._pending
        assert eng.num_active == 0

    def test_cancel_mid_flight_discards_tokens(self, engine_setup):
        """Cancel between enqueue and processing: the in-flight call's
        tokens for that request must be dropped, and the slot reusable."""
        cfg, params = engine_setup
        eng = InferenceEngine(cfg, params, max_batch=1, max_seq=64)
        rid = eng.add_request([1, 2, 3], max_new_tokens=30)
        eng.step(horizon=2)          # admit (prefill enqueued)
        eng.step(horizon=2)          # decode enqueued
        assert eng.cancel(rid)
        n_before = len(eng.get_finished(rid).output) \
            if eng.get_finished(rid) else 0
        assert n_before == 0         # cancelled, not finished
        rid2 = eng.add_request([4, 5], max_new_tokens=3)
        done = eng.run_to_completion(horizon=4)
        assert rid2 in done
        assert len(done[rid2].output) == 3
        assert rid not in done


class TestW8A8Prefill:
    """Opt-in int8-activation prefill (quantization.w8a8_region):
    int8 x int8 MXU dots on the compute-bound prefill, decode W8A16."""

    def test_qeinsum_w8a8_close_to_exact(self):
        from skypilot_tpu.models import quantization as q
        key = jax.random.PRNGKey(0)
        x = jax.random.normal(key, (4, 8, 64), jnp.bfloat16)
        w = q._quantize_array(
            jax.random.normal(jax.random.PRNGKey(1), (64, 96),
                              jnp.bfloat16), (0,))
        exact = q.qeinsum('bsd,df->bsf', x, w, out_dtype=jnp.float32)
        with q.w8a8_region():
            approx = q.qeinsum('bsd,df->bsf', x, w,
                               out_dtype=jnp.float32)
        # per-row int8 activations: ~0.5-1% relative error on a
        # 64-deep dot of unit-scale gaussians
        err = jnp.abs(approx - exact)
        rel = float(jnp.max(err) / (jnp.max(jnp.abs(exact)) + 1e-6))
        assert rel < 0.05, rel

    def test_engine_generates_with_w8a8_prefill(self, engine_setup):
        cfg, params = engine_setup
        from skypilot_tpu.models import quantization
        qparams = quantization.quantize_params(params)
        eng = InferenceEngine(cfg, qparams, max_batch=2, max_seq=64,
                              prefill_w8a8=True)
        rid = eng.add_request([3, 1, 4, 1, 5], max_new_tokens=6)
        done = eng.run_to_completion(horizon=4)
        assert len(done[rid].output) == 6
        # Decode is untouched: a second engine without w8a8 but the
        # same prefilled first token should continue identically given
        # the same cache content modulo prefill activation noise — we
        # only assert generation is well-formed (ids in vocab).
        assert all(0 <= t < cfg.vocab_size for t in done[rid].output)

    def test_region_is_trace_time_scoped(self):
        from skypilot_tpu.models import quantization as q
        assert not getattr(q._a8_region, 'active', False)
        with q.w8a8_region():
            assert q._a8_region.active
        assert not q._a8_region.active
