"""HF checkpoint import: logits parity against ``transformers`` (torch
CPU) for Llama/GQA, Gemma (MQA + tied embeddings + gelu + norm+1), and
Mixtral (MoE), plus save/load round-trip and tokenizer behavior.

The reference serves *real* HF checkpoints through external engines
(``llm/llama-3/llama3.yaml:109``); this proves our in-tree engine computes
the same function as the HF reference implementation for those layouts.
"""
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu.models import configs, llama, weights
from skypilot_tpu.models.tokenizer import (ByteTokenizer, load_tokenizer)

# Compile-heavy (jit of full models): slow tier — the fast sweep is
# the orchestration layer (SURVEY §4 offline tier analog).
pytestmark = pytest.mark.slow

jax.config.update('jax_platforms', 'cpu')


def _save_hf_model(model, path):
    model.save_pretrained(path, safe_serialization=True)


def _our_logits(path, tokens):
    cfg, params = weights.load_checkpoint(path, dtype=jnp.float32)
    logits, _ = llama.forward(params, jnp.asarray(tokens), cfg)
    return np.asarray(logits, np.float32), cfg


def _hf_logits(model, tokens):
    import torch
    with torch.no_grad():
        out = model(torch.tensor(tokens))
    return out.logits.float().numpy()


def _assert_close(ours, theirs, atol=2e-3):
    err = np.abs(ours - theirs).max()
    assert err < atol, f'max |logit diff| = {err}'


@pytest.fixture(scope='module')
def torch_seed():
    import torch
    torch.manual_seed(0)


def test_llama_gqa_logits_parity(tmp_path, torch_seed):
    from transformers import LlamaConfig, LlamaForCausalLM
    hf_cfg = LlamaConfig(
        vocab_size=97, hidden_size=64, intermediate_size=128,
        num_hidden_layers=3, num_attention_heads=8, num_key_value_heads=2,
        max_position_embeddings=64, rope_theta=10000.0, rms_norm_eps=1e-5,
        tie_word_embeddings=False, attention_bias=False, mlp_bias=False)
    model = LlamaForCausalLM(hf_cfg).eval()
    path = str(tmp_path / 'llama')
    _save_hf_model(model, path)

    tokens = np.random.RandomState(0).randint(0, 97, (2, 17))
    ours, cfg = _our_logits(path, tokens)
    assert cfg.n_kv_heads == 2 and not cfg.tie_embeddings
    _assert_close(ours, _hf_logits(model, tokens))


def test_gemma_mqa_logits_parity(tmp_path, torch_seed):
    from transformers import GemmaConfig, GemmaForCausalLM
    hf_cfg = GemmaConfig(
        vocab_size=89, hidden_size=48, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=1,
        head_dim=16, max_position_embeddings=64, rope_theta=10000.0,
        rms_norm_eps=1e-6, hidden_act='gelu_pytorch_tanh',
        hidden_activation='gelu_pytorch_tanh')
    model = GemmaForCausalLM(hf_cfg).eval()
    path = str(tmp_path / 'gemma')
    _save_hf_model(model, path)

    tokens = np.random.RandomState(1).randint(0, 89, (2, 11))
    ours, cfg = _our_logits(path, tokens)
    assert cfg.tie_embeddings and cfg.norm_plus_one and cfg.scale_embeddings
    assert cfg.head_dim == 16  # explicit head_dim != dim//n_heads
    _assert_close(ours, _hf_logits(model, tokens))


def test_mixtral_moe_logits_parity(tmp_path, torch_seed):
    from transformers import MixtralConfig, MixtralForCausalLM
    hf_cfg = MixtralConfig(
        vocab_size=71, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        num_local_experts=4, num_experts_per_tok=2,
        max_position_embeddings=64, rope_theta=10000.0, rms_norm_eps=1e-5,
        tie_word_embeddings=False)
    model = MixtralForCausalLM(hf_cfg).eval()
    path = str(tmp_path / 'mixtral')
    _save_hf_model(model, path)

    tokens = np.random.RandomState(2).randint(0, 71, (1, 13))
    cfg, params = weights.load_checkpoint(path, dtype=jnp.float32)
    assert cfg.is_moe and cfg.n_experts == 4
    # Our MoE uses GShard capacity-limited dispatch: with a generous
    # capacity factor no tokens are dropped and it matches HF's exact
    # (ungated-capacity) routing.
    import dataclasses
    cfg = dataclasses.replace(cfg, moe_capacity_factor=8.0)
    logits, _ = llama.forward(params, jnp.asarray(tokens), cfg)
    _assert_close(np.asarray(logits, np.float32),
                  _hf_logits(model, tokens), atol=5e-3)


def test_save_load_roundtrip(tmp_path):
    cfg = configs.TINY
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    path = str(tmp_path / 'rt')
    weights.save_hf_checkpoint(path, cfg, params)
    cfg2, params2 = weights.load_checkpoint(path, dtype=cfg.dtype)
    assert cfg2.dim == cfg.dim and cfg2.n_kv_heads == cfg.n_kv_heads
    tok = np.arange(24).reshape(1, 24) % cfg.vocab_size
    l1, _ = llama.forward(params, jnp.asarray(tok), cfg)
    l2, _ = llama.forward(params2, jnp.asarray(tok), cfg2)
    np.testing.assert_allclose(np.asarray(l1, np.float32),
                               np.asarray(l2, np.float32), atol=2e-2)


def test_byte_tokenizer_roundtrip():
    tk = ByteTokenizer()
    ids = tk.encode('hello, TPU!')
    assert ids[0] == tk.bos_id
    assert tk.decode(ids) == 'hello, TPU!'
    assert tk.vocab_size == 258


def test_byte_tokenizer_invalid_utf8_sanitizes_at_boundary():
    """decode keeps surrogateescape internally (string-stop matching
    round-trips arbitrary generated bytes), but sanitize_text must
    strip the lone surrogates before they reach a JSON body — strict
    client-side parsers reject \\udcXX escapes."""
    from skypilot_tpu.models.tokenizer import sanitize_text
    tk = ByteTokenizer()
    text = tk.decode([0x80, 0xFF, ord('a')])     # invalid UTF-8 bytes
    # Internal round trip is byte-faithful...
    assert text.encode('utf-8', 'surrogateescape') == b'\x80\xffa'
    with pytest.raises(UnicodeEncodeError):
        text.encode('utf-8')                      # ...but not JSON-safe
    clean = sanitize_text(text)
    clean.encode('utf-8')                         # wire-safe now
    assert clean.endswith('a') and '�' in clean
    # Valid text passes through untouched.
    assert sanitize_text('héllo') == 'héllo'


def test_load_tokenizer_fallback(tmp_path):
    assert isinstance(load_tokenizer(str(tmp_path)), ByteTokenizer)
    assert isinstance(load_tokenizer(None), ByteTokenizer)


def test_hf_tokenizer_from_file(tmp_path):
    # Build a minimal valid tokenizer.json (WordLevel) via the tokenizers
    # lib, then load through our wrapper.
    from tokenizers import Tokenizer
    from tokenizers.models import WordLevel
    from tokenizers.pre_tokenizers import Whitespace
    vocab = {'<s>': 0, '</s>': 1, 'hello': 2, 'tpu': 3}
    tk = Tokenizer(WordLevel(vocab, unk_token='</s>'))
    tk.pre_tokenizer = Whitespace()
    tk.save(str(tmp_path / 'tokenizer.json'))
    (tmp_path / 'tokenizer_config.json').write_text(json.dumps(
        {'bos_token': '<s>', 'eos_token': '</s>'}))
    our = load_tokenizer(str(tmp_path))
    ids = our.encode('hello tpu')
    assert ids == [0, 2, 3]
    assert our.eos_id == 1


def _serve_checkpoint(tmp_path, port_base, **server_kwargs):
    """Save a TINY checkpoint, boot a ModelServer on it, wait for
    readiness. Returns (server, port); caller must server.stop()."""
    import time as time_mod
    import urllib.request
    from skypilot_tpu.serve.server import ModelServer
    from skypilot_tpu.utils import common_utils

    cfg = configs.TINY
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    path = str(tmp_path / 'ckpt')
    weights.save_hf_checkpoint(path, cfg, params)
    port = common_utils.find_free_port(port_base)
    server = ModelServer(max_batch=2, max_seq=64, port=port,
                         model_path=path, **server_kwargs)
    server.start(block=False)
    deadline = time_mod.time() + 60
    ready = False
    while time_mod.time() < deadline:
        try:
            with urllib.request.urlopen(
                    f'http://127.0.0.1:{port}/readiness', timeout=5) as r:
                ready = r.status == 200
                break
        except Exception:
            time_mod.sleep(0.3)
    if not ready:
        server.stop()
        raise AssertionError('server never became ready')
    return server, port


@pytest.mark.slow
def test_server_serves_real_checkpoint_text(tmp_path):
    """E2e: ModelServer --model-path serves a saved checkpoint and
    answers a TEXT prompt with decoded text (the reference's real-model
    serving recipes, in-tree)."""
    import urllib.request
    server, port = _serve_checkpoint(tmp_path, 18200)
    try:
        req = urllib.request.Request(
            f'http://127.0.0.1:{port}/generate',
            data=json.dumps({'prompt': 'hello tpu',
                             'max_new_tokens': 4}).encode(),
            headers={'Content-Type': 'application/json'})
        with urllib.request.urlopen(req, timeout=30) as r:
            out = json.loads(r.read())
        assert 'text' in out and isinstance(out['text'], str)
        assert len(out['tokens']) > 0
    finally:
        server.stop()


def test_trainer_init_from_pretrained(tmp_path):
    from skypilot_tpu.train.trainer import Trainer
    cfg = configs.TINY
    params = llama.init_params(jax.random.PRNGKey(1), cfg)
    path = str(tmp_path / 'ckpt')
    weights.save_hf_checkpoint(path, cfg, params)

    tr = Trainer(cfg)
    state = tr.init_from_pretrained(path)
    assert int(state.step) == 0
    # Params match the checkpoint (post fp32 round-trip).
    got = np.asarray(jnp.asarray(state.params['layers']['wq'], jnp.float32))
    want = np.asarray(jnp.asarray(params['layers']['wq'], jnp.float32))
    np.testing.assert_allclose(got, want, atol=2e-2)
    # And one train step runs.
    batch = {
        'inputs': jnp.zeros((8, 16), jnp.int32),
        'targets': jnp.zeros((8, 16), jnp.int32),
    }
    state2, metrics = tr.step(state, batch)
    assert np.isfinite(metrics['loss'])


def test_qwen2_qkv_bias_logits_parity(tmp_path, torch_seed):
    from transformers import Qwen2Config, Qwen2ForCausalLM
    hf_cfg = Qwen2Config(
        vocab_size=83, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=8, num_key_value_heads=2,
        max_position_embeddings=64, rope_theta=10000.0, rms_norm_eps=1e-5,
        tie_word_embeddings=False)
    model = Qwen2ForCausalLM(hf_cfg).eval()
    path = str(tmp_path / 'qwen2')
    _save_hf_model(model, path)

    tokens = np.random.RandomState(5).randint(0, 83, (2, 13))
    ours, cfg = _our_logits(path, tokens)
    assert cfg.qkv_bias
    _assert_close(ours, _hf_logits(model, tokens))


def test_qwen2_save_load_roundtrip(tmp_path):
    cfg = configs.TINY_QWEN
    params = llama.init_params(jax.random.PRNGKey(2), cfg)
    # nonzero biases so the roundtrip actually tests them
    params['layers']['bq'] = params['layers']['bq'] + 0.1
    path = str(tmp_path / 'rtq')
    weights.save_hf_checkpoint(path, cfg, params)
    cfg2, params2 = weights.load_checkpoint(path, dtype=cfg.dtype)
    assert cfg2.qkv_bias
    tok = np.arange(24).reshape(1, 24) % cfg.vocab_size
    l1, _ = llama.forward(params, jnp.asarray(tok), cfg)
    l2, _ = llama.forward(params2, jnp.asarray(tok), cfg2)
    np.testing.assert_allclose(np.asarray(l1, np.float32),
                               np.asarray(l2, np.float32), atol=2e-2)


@pytest.mark.slow
def test_server_int8_quantized_serving(tmp_path):
    """ModelServer --quantize int8 serves a checkpoint with int8
    weights + KV cache."""
    import urllib.request
    server, port = _serve_checkpoint(tmp_path, 18300, quantize='int8')
    try:
        assert server.engine.cache.quantized
        req = urllib.request.Request(
            f'http://127.0.0.1:{port}/generate',
            data=json.dumps({'prompt': [1, 2, 3],
                             'max_new_tokens': 4}).encode(),
            headers={'Content-Type': 'application/json'})
        with urllib.request.urlopen(req, timeout=30) as r:
            out = json.loads(r.read())
        assert len(out['tokens']) == 4
    finally:
        server.stop()


class TestSynthAndInt8Cache:
    """Synthetic checkpoint generator + host-side int8 load + cache
    (the 7B bench path, VERDICT r4 task 1)."""

    def test_synth_checkpoint_loads_and_caches(self, tmp_path):
        import numpy as np

        from skypilot_tpu.models import configs, synth, weights
        p = synth.write_synthetic_hf_checkpoint(str(tmp_path / 'ck'),
                                                configs.TINY)
        assert p == synth.write_synthetic_hf_checkpoint(  # idempotent
            str(tmp_path / 'ck'), configs.TINY)
        cfg, q1 = weights.load_checkpoint(p, quantize='int8')
        assert cfg.dim == configs.TINY.dim
        assert os.path.exists(os.path.join(p, '.int8_cache.bin'))
        _, q2 = weights.load_checkpoint(p, quantize='int8')  # via cache
        flat1 = dict(weights._flatten_leaves(q1))
        flat2 = dict(weights._flatten_leaves(q2))
        assert set(flat1) == set(flat2)
        for k in flat1:
            assert flat1[k].dtype == flat2[k].dtype, k
            np.testing.assert_array_equal(
                np.asarray(flat1[k], np.float32),
                np.asarray(flat2[k], np.float32), err_msg=k)

    def test_host_quantize_matches_device_quantize(self, tmp_path):
        """weights._host_quantize and quantization._quantize_array agree
        bit-for-bit (same rounded-scale contract)."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        from skypilot_tpu.models import quantization, weights
        w = np.asarray(jax.random.normal(jax.random.PRNGKey(1), (64, 32),
                                         jnp.bfloat16))
        host = weights._host_quantize(np.asarray(w, np.float32), (0,),
                                      jnp.bfloat16)
        dev = quantization._quantize_array(jnp.asarray(w), (0,))
        np.testing.assert_array_equal(
            np.asarray(host.scale, np.float32),
            np.asarray(dev.scale, np.float32))
        codes_equal = (np.asarray(host.int8) == np.asarray(dev.int8))
        assert codes_equal.mean() > 0.999, codes_equal.mean()
