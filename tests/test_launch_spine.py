"""End-to-end tests of the launch spine on the local provisioner.

This is the hermetic coverage SURVEY §4 calls for (improving on the
reference, whose offline tests stop at dryrun/codegen assertions): a real
launch → agent → job → logs → teardown cycle with no cloud.
"""
import os
import time

import pytest

import skypilot_tpu as sky
from skypilot_tpu import core, execution, exceptions, global_state
from skypilot_tpu.task import Task

pytestmark = [pytest.mark.usefixtures('tmp_state_dir', 'fast_agent'), pytest.mark.slow]

TERMINAL = ('SUCCEEDED', 'FAILED', 'FAILED_DRIVER', 'CANCELLED')


@pytest.fixture()
def fast_agent(monkeypatch):
    monkeypatch.setenv('SKYTPU_AGENT_TICK', '0.1')
    monkeypatch.setenv('SKYTPU_AGENT_READY_TIMEOUT', '30')


def _wait_job(cluster: str, job_id: int, timeout: float = 30.0) -> str:
    deadline = time.time() + timeout
    status = None
    while time.time() < deadline:
        status = core.job_status(cluster, job_id)
        if status in TERMINAL:
            return status
        time.sleep(0.15)
    return status or 'TIMEOUT'


def _launch(task, cluster, **kwargs):
    return execution.launch(task, cluster_name=cluster, **kwargs)


def test_launch_end_to_end_single_node():
    task = Task(name='t1', run='echo out-$((21*2))')
    task.set_resources(sky.Resources(cloud='local', cpus='1+'))
    job_id, handle = _launch(task, 'spine-basic')
    try:
        assert job_id == 1
        assert handle.num_hosts == 1
        assert _wait_job('spine-basic', job_id) == 'SUCCEEDED'
        from skypilot_tpu.backend import tpu_backend
        logs = tpu_backend.TpuVmBackend().get_job_logs(handle, job_id)
        assert 'out-42' in logs
        queue = core.queue('spine-basic')
        assert queue[0]['job_id'] == job_id
        assert queue[0]['status'] == 'SUCCEEDED'
    finally:
        core.down('spine-basic')
    assert core.status() == []


def test_multihost_slice_env_contract():
    """A local tpu-v5e-16 'slice' = 2 hosts; every rank gets the gang env
    (the contract jax.distributed.initialize consumes)."""
    task = Task(name='gang', run=(
        'echo "R=$SKYTPU_NODE_RANK N=$SKYTPU_NUM_NODES '
        'C=$SKYTPU_NUM_CHIPS_PER_NODE COORD=$SKYTPU_COORDINATOR_ADDRESS '
        'IPS=$(echo "$SKYTPU_NODE_IPS" | tr \'\\n\' \',\')"'))
    task.set_resources(sky.Resources(cloud='local',
                                     accelerators='tpu-v5e-16'))
    job_id, handle = _launch(task, 'spine-gang')
    try:
        assert handle.num_hosts == 2
        assert _wait_job('spine-gang', job_id) == 'SUCCEEDED'
        from skypilot_tpu.backend import tpu_backend
        logs = tpu_backend.TpuVmBackend().get_job_logs(handle, job_id)
        assert 'R=0 N=2 C=8' in logs
        assert 'R=1 N=2 C=8' in logs
        assert 'COORD=127.0.0.1:8476' in logs
        assert 'IPS=127.0.0.1,127.0.0.1,' in logs
    finally:
        core.down('spine-gang')


def test_multislice_env_contract_two_slices():
    """num_nodes=2 with a 2-host slice type = a 2-slice (DCN) job on 4
    hosts: every rank gets its slice id, the global slice count, and ONE
    coordinator spanning both slices (VERDICT r2 item 5 — multi-slice
    through the real launch path, not just the mesh dryrun)."""
    task = Task(name='mslice', num_nodes=2, run=(
        'echo "R=$SKYTPU_NODE_RANK S=$SKYTPU_SLICE_ID '
        'NS=$SKYTPU_NUM_SLICES N=$SKYTPU_NUM_NODES '
        'COORD=$SKYTPU_COORDINATOR_ADDRESS"'))
    task.set_resources(sky.Resources(cloud='local',
                                     accelerators='tpu-v5e-16'))
    job_id, handle = _launch(task, 'spine-mslice')
    try:
        assert handle.num_hosts == 4
        assert _wait_job('spine-mslice', job_id) == 'SUCCEEDED'
        from skypilot_tpu.backend import tpu_backend
        logs = tpu_backend.TpuVmBackend().get_job_logs(handle, job_id)
        assert 'R=0 S=0 NS=2 N=4' in logs
        assert 'R=1 S=0 NS=2 N=4' in logs
        assert 'R=2 S=1 NS=2 N=4' in logs
        assert 'R=3 S=1 NS=2 N=4' in logs
        # One coordinator (global rank 0) spans both slices.
        assert logs.count('COORD=127.0.0.1:8476') >= 4
    finally:
        core.down('spine-mslice')


def test_exec_reuses_cluster_and_fifo_order():
    # A TPU cluster is EXCLUSIVE (chips owned by one program): strict
    # FIFO. CPU clusters multiplex jobs (reference resource-slot
    # semantics), so without an accelerator this ordering would be a
    # race — one the old per-op RPC latency used to mask.
    task = Task(name='first', run='sleep 0.3; echo first-done')
    task.set_resources(sky.Resources(cloud='local',
                                     accelerators='tpu-v5e-8'))
    job1, handle = _launch(task, 'spine-exec')
    try:
        task2 = Task(name='second', run='echo second-done')
        task2.set_resources(sky.Resources(cloud='local',
                                          accelerators='tpu-v5e-8'))
        job2, handle2 = execution.exec_cmd(task2, 'spine-exec')
        assert handle2.cluster_name == handle.cluster_name
        assert job2 == job1 + 1
        assert _wait_job('spine-exec', job2) == 'SUCCEEDED'
        # FIFO: second ran after first finished.
        jobs = {j['job_id']: j for j in core.queue('spine-exec')}
        assert jobs[job1]['status'] == 'SUCCEEDED'
        assert jobs[job2]['start_at'] >= jobs[job1]['end_at']
    finally:
        core.down('spine-exec')


def test_setup_runs_before_job_and_failure_is_reported():
    task = Task(name='s', setup='echo marker > ~/setup_done.txt',
                run='cat ~/setup_done.txt')
    task.set_resources(sky.Resources(cloud='local'))
    job_id, handle = _launch(task, 'spine-setup')
    try:
        assert _wait_job('spine-setup', job_id) == 'SUCCEEDED'
        from skypilot_tpu.backend import tpu_backend
        logs = tpu_backend.TpuVmBackend().get_job_logs(handle, job_id)
        assert 'marker' in logs
    finally:
        core.down('spine-setup')

    bad = Task(name='bad', setup='exit 3', run='echo never')
    bad.set_resources(sky.Resources(cloud='local'))
    with pytest.raises(exceptions.CommandError):
        _launch(bad, 'spine-setup-bad')
    core.down('spine-setup-bad')


def test_workdir_and_file_mounts(tmp_path):
    wd = tmp_path / 'wd'
    wd.mkdir()
    (wd / 'data.txt').write_text('workdir-data')
    extra = tmp_path / 'extra.txt'
    extra.write_text('mounted-file')
    task = Task(name='wd', run='cat data.txt && cat ~/extra/extra.txt',
                workdir=str(wd),
                file_mounts={'~/extra/extra.txt': str(extra)})
    task.set_resources(sky.Resources(cloud='local'))
    job_id, handle = _launch(task, 'spine-wd')
    try:
        assert _wait_job('spine-wd', job_id) == 'SUCCEEDED'
        from skypilot_tpu.backend import tpu_backend
        logs = tpu_backend.TpuVmBackend().get_job_logs(handle, job_id)
        assert 'workdir-data' in logs
        assert 'mounted-file' in logs
    finally:
        core.down('spine-wd')


def test_cancel_running_job():
    task = Task(name='long', run='sleep 60')
    task.set_resources(sky.Resources(cloud='local'))
    job_id, _ = _launch(task, 'spine-cancel')
    try:
        deadline = time.time() + 20
        while time.time() < deadline:
            if core.job_status('spine-cancel', job_id) == 'RUNNING':
                break
            time.sleep(0.1)
        cancelled = core.cancel('spine-cancel', job_id)
        assert cancelled == [job_id]
        assert core.job_status('spine-cancel', job_id) == 'CANCELLED'
    finally:
        core.down('spine-cancel')


def test_resources_mismatch_on_reuse():
    task = Task(name='small', run='echo hi')
    task.set_resources(sky.Resources(cloud='local'))
    _launch(task, 'spine-mismatch')
    try:
        big = Task(name='big', run='echo hi')
        big.set_resources(sky.Resources(cloud='local',
                                        accelerators='tpu-v5e-16'))
        with pytest.raises(exceptions.ResourcesMismatchError):
            _launch(big, 'spine-mismatch')
    finally:
        core.down('spine-mismatch')


def test_autostop_down_terminates_idle_cluster():
    task = Task(name='quick', run='echo done')
    task.set_resources(sky.Resources(cloud='local'))
    job_id, _ = _launch(task, 'spine-auto',
                        idle_minutes_to_autostop=0, down=True)
    # With idle=0 and fast agent ticks, teardown can race the client's
    # status polls: autostop fires the instant the job queue drains (it
    # only triggers once all jobs are terminal), so "cluster gone" is
    # itself the success signal for both the job and the autostop.
    deadline = time.time() + 30
    gone = False
    while time.time() < deadline:
        try:
            records = core.status(['spine-auto'], refresh=True)
        except exceptions.ClusterDoesNotExist:
            gone = True
            break
        if not records:
            gone = True
            break
        time.sleep(0.3)
    assert gone, 'autostop --down did not terminate the idle cluster'


def test_stop_and_restart_cycle():
    task = Task(name='cyc', run='echo alive')
    task.set_resources(sky.Resources(cloud='local'))
    job_id, _ = _launch(task, 'spine-stop')
    assert _wait_job('spine-stop', job_id) == 'SUCCEEDED'
    core.stop('spine-stop')
    records = core.status(['spine-stop'])
    assert records[0]['status'] == global_state.ClusterStatus.STOPPED
    # Relaunch restarts the stopped cluster and runs a new job.
    task2 = Task(name='cyc2', run='echo alive-again')
    task2.set_resources(sky.Resources(cloud='local'))
    job2, handle = _launch(task2, 'spine-stop')
    try:
        assert _wait_job('spine-stop', job2) == 'SUCCEEDED'
        from skypilot_tpu.backend import tpu_backend
        logs = tpu_backend.TpuVmBackend().get_job_logs(handle, job2)
        assert 'alive-again' in logs
    finally:
        core.down('spine-stop')


def test_usage_intervals_and_cost_report():
    task = Task(name='cost', run='echo ok')
    task.set_resources(sky.Resources(cloud='local'))
    job_id, _ = _launch(task, 'spine-cost')
    assert _wait_job('spine-cost', job_id) == 'SUCCEEDED'
    core.down('spine-cost')
    report = core.cost_report()
    names = [r['name'] for r in report]
    assert 'spine-cost' in names
    row = report[names.index('spine-cost')]
    assert row['duration_hours'] > 0


def test_docker_image_runtime_wraps_run(tmp_path, monkeypatch):
    """image_id: docker:<image> runs the job inside a container on each
    host (reference docker runtime). Hermetic: a PATH `docker` shim
    executes the inner command and records the invocation."""
    import stat
    shim_dir = tmp_path / 'bin'
    shim_dir.mkdir()
    record = tmp_path / 'docker_calls.txt'
    shim = shim_dir / 'docker'
    shim.write_text(f'''#!/usr/bin/env python3
import subprocess, sys
with open({str(record)!r}, 'a') as f:
    f.write(' '.join(sys.argv[1:]) + chr(10))
# find: ... <image> bash -c <cmd>
args = sys.argv[1:]
i = args.index('bash')
sys.exit(subprocess.run(['bash', args[i+1], args[i+2]]).returncode)
''')
    shim.chmod(shim.stat().st_mode | stat.S_IEXEC)
    monkeypatch.setenv('PATH', f'{shim_dir}{os.pathsep}'
                               f'{os.environ.get("PATH", "")}')

    out = tmp_path / 'out.txt'
    setup_out = tmp_path / 'setup_out.txt'
    task = Task(name='dkr', run=f'echo in-container-$MARK > {out}',
                setup=f'echo setup-in-container > {setup_out}',
                envs={'MARK': 'x7'})
    task.set_resources(sky.Resources(cloud='local', cpus='1+',
                                     image_id='docker:python:3.11-slim'))
    job_id, handle = _launch(task, 'spine-docker')
    try:
        assert _wait_job('spine-docker', job_id) == 'SUCCEEDED'
        assert out.read_text().strip() == 'in-container-x7'
        calls = record.read_text()
        assert 'run --rm --net=host --privileged' in calls
        assert '-e HOME=' in calls
        assert 'python:3.11-slim' in calls
        # setup ran through docker too (two container invocations).
        assert setup_out.read_text().strip() == 'setup-in-container'
        assert calls.count('run --rm --net=host') >= 2
    finally:
        core.down('spine-docker')
