"""Chunked prefill in the slot engine + prefill/decode interleaving.

The fast (not-slow) tests are the tier-1 scheduler smoke: CPU, tiny
config, one compile apiece — they pin that the chunked path is ON by
default, that decode makes progress while a long prompt is mid-prefill,
and the host-side scheduler arithmetic (interleave budget, page-size
auto-select) with no device work at all. The compile-heavy equivalence
matrix (chunked == monolithic across slot/paged/int8/prefix-hit) rides
the slow tier with the other engine suites.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu.inference.engine import InferenceEngine
from skypilot_tpu.inference.paged import PagedInferenceEngine
from skypilot_tpu.models import configs, llama


@pytest.fixture(scope='module')
def setup():
    cfg = configs.TINY
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def _greedy_reference(params, cfg, prompt, n):
    """Greedy decode via repeated full forwards (no cache)."""
    toks = list(prompt)
    out = []
    for _ in range(n):
        logits, _ = llama.forward(params, jnp.asarray([toks], jnp.int32),
                                  cfg)
        nxt = int(jnp.argmax(logits[0, -1]))
        out.append(nxt)
        toks.append(nxt)
    return out


# ---------------------------------------------------------------------------
# Fast tier: scheduler smoke (tier-1 exercises the chunked path)
# ---------------------------------------------------------------------------
class TestSchedulerSmoke:

    def test_chunked_on_by_default(self, setup):
        cfg, params = setup
        eng = InferenceEngine(cfg, params, max_batch=2, max_seq=128,
                              attn_impl='xla')
        assert eng.chunked and eng.chunk == 256
        assert PagedInferenceEngine(cfg, params, max_batch=2,
                                    max_seq=128, page_size=8,
                                    attn_impl='xla').chunk == 256

    def test_decode_progresses_while_long_prompt_prefills(self, setup):
        """The scheduler unit contract: with request A decoding, a long
        prompt B prefills in chunks and A gains tokens BETWEEN chunks
        (bounded TPOT during admission) — plus the chunked output
        matches the no-cache greedy reference."""
        cfg, params = setup
        eng = InferenceEngine(cfg, params, max_batch=2, max_seq=256,
                              attn_impl='xla', prefill_chunk_tokens=16)
        a = eng.add_request([3, 1, 4, 1, 5], max_new_tokens=64)
        while eng._prefill_off or eng._queue:
            eng.step(horizon=1)
        # B needs ~8 chunks; each step runs at most one chunk batch and
        # then decodes.
        prompt_b = [(i * 7 + 3) % cfg.vocab_size for i in range(120)]
        b = eng.add_request(prompt_b, max_new_tokens=4)
        saw_interleave = False
        for _ in range(10):
            events = eng.step(horizon=2)
            if eng._prefill_off and any(rid == a for rid, _, _ in events):
                saw_interleave = True
        assert saw_interleave
        done = eng.run_to_completion(horizon=4)
        assert done[b].output == _greedy_reference(params, cfg,
                                                   prompt_b, 4)

    def test_interleave_horizon_token_budget(self, setup):
        """Host-only arithmetic: the decode_priority_ratio budget
        h = r/(1-r) * chunk * n / active."""
        cfg, params = setup
        eng = InferenceEngine(cfg, params, max_batch=8, max_seq=128,
                              prefill_chunk_tokens=64,
                              decode_priority_ratio=0.5)
        # 2 decodable slots + 1 mid-prefill -> h = 1 * 64 * 1 / 2 = 32
        for s in range(3):
            eng._slots[s] = object()
        eng._prefill_off[2] = 0
        assert eng._interleave_horizon() == 32
        eng.decode_priority_ratio = 0.2        # 0.25 * 64 / 2 = 8
        assert eng._interleave_horizon() == 8
        eng.decode_priority_ratio = 1.0        # decode never capped
        assert eng._interleave_horizon() == eng._HORIZON_BUCKETS[-1]
        # no decodable slots: prefill must not wait on decode
        eng._prefill_off = {0: 0, 1: 0, 2: 0}
        eng.decode_priority_ratio = 0.5
        assert eng._interleave_horizon() == 1
        eng._slots = [None] * 8                # don't step this engine
        eng._prefill_off = {}

    def test_paged_page_size_auto_select(self, setup, monkeypatch):
        """Auto page size stays on the fast path and never warns; where
        the manual-DMA int8 kernel is reachable, an explicit misaligned
        size is auto-rounded UP to the next 128-multiple (loudly);
        elsewhere (CPU/gather path) alignment is free and the explicit
        size is kept without a warning."""
        import warnings
        cfg, params = setup
        with warnings.catch_warnings(record=True) as w_auto:
            warnings.simplefilter('always')
            eng = PagedInferenceEngine(cfg, params, max_batch=2,
                                       max_seq=96, quantize='int8',
                                       attn_impl='xla')
        assert not any('multiple of 128' in str(x.message)
                       for x in w_auto)
        # CPU/gather path: no 128-alignment constraint; short-context
        # configs get small pages instead of one page per slot, and an
        # explicit misaligned size is the user's to keep — silently.
        assert eng.page == 16
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter('always')
            eng8 = PagedInferenceEngine(cfg, params, max_batch=2,
                                        max_seq=96, quantize='int8',
                                        attn_impl='xla', page_size=8)
        assert not any('multiple of 128' in str(x.message) for x in w)
        assert eng8.page == 8
        # Fast path reachable (patched: the real condition needs a TPU
        # backend): page_size=8 would ship the ~0.7x per-page-grid
        # kernel, so it is rounded up to 128 with a loud warning — the
        # footgun the multichip dryrun hit is now un-hittable.
        monkeypatch.setattr(PagedInferenceEngine,
                            '_int8_fast_path_reachable',
                            staticmethod(lambda cfg, mesh: True))
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter('always')
            engf = PagedInferenceEngine(cfg, params, max_batch=2,
                                        max_seq=96, quantize='int8',
                                        attn_impl='xla', page_size=8)
        assert any('Auto-adjusted to 128' in str(x.message) for x in w)
        assert engf.page == 128
        # kv_cache_dtype='int8' alone (bf16 weights) triggers the same
        # guard — the knob is decoupled from the weight quantize mode.
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter('always')
            engd = PagedInferenceEngine(cfg, params, max_batch=2,
                                        max_seq=96, attn_impl='xla',
                                        kv_cache_dtype='int8',
                                        page_size=8)
        assert any('Auto-adjusted to 128' in str(x.message) for x in w)
        assert engd.page == 128 and engd.cache.quantized


# ---------------------------------------------------------------------------
# Slow tier: equivalence matrix (chunked == monolithic)
# ---------------------------------------------------------------------------
@pytest.mark.slow
class TestChunkedEquivalence:

    def _mono(self, cfg, params, prompts, n_new, **kw):
        eng = InferenceEngine(cfg, params, max_batch=4, max_seq=256,
                              attn_impl='xla', prefill_chunk_tokens=0,
                              **kw)
        rids = [eng.add_request(p, max_new_tokens=n_new)
                for p in prompts]
        done = eng.run_to_completion(horizon=4)
        return [done[r].output for r in rids]

    def test_slot_chunked_matches_monolithic(self, setup):
        cfg, params = setup
        prompts = [[3, 1, 4, 1, 5],
                   [(i * 5 + 2) % cfg.vocab_size for i in range(150)],
                   [9],
                   [(i * 11 + 7) % cfg.vocab_size for i in range(40)]]
        want = self._mono(cfg, params, prompts, 8)
        eng = InferenceEngine(cfg, params, max_batch=4, max_seq=256,
                              attn_impl='xla', prefill_chunk_tokens=32)
        rids = [eng.add_request(p, max_new_tokens=8) for p in prompts]
        done = eng.run_to_completion(horizon=4)
        got = [done[r].output for r in rids]
        assert got == want, (got, want)

    def test_slot_chunked_int8_generates(self, setup):
        cfg, params = setup
        eng = InferenceEngine(cfg, params, max_batch=2, max_seq=256,
                              quantize='int8', prefill_chunk_tokens=32)
        rid = eng.add_request(list(range(1, 100)), max_new_tokens=6)
        done = eng.run_to_completion(horizon=4)
        assert len(done[rid].output) == 6

    def test_paged_chunked_matches_monolithic_slot(self, setup):
        """Paged chunked prefill — WITHOUT and then WITH a prefix-cache
        hit (tail-only prefill) — matches monolithic slot outputs."""
        cfg, params = setup
        shared = [(i * 5 + 2) % cfg.vocab_size for i in range(64)]
        p1 = shared + [11, 12]
        p2 = shared + [13, 14, 15]
        want = self._mono(cfg, params, [p1, p2], 6)
        eng = PagedInferenceEngine(cfg, params, max_batch=2,
                                   max_seq=256, page_size=8, chunk=16,
                                   attn_impl='xla')
        r1 = eng.add_request(p1, max_new_tokens=6)
        done = eng.run_to_completion(horizon=4)
        assert done[r1].output == want[0]      # cold (no prefix hit)
        r2 = eng.add_request(p2, max_new_tokens=6)
        done = eng.run_to_completion(horizon=4)
        assert eng.alloc.prefix_hits >= 1      # tail-only prefill
        assert done[r2].output == want[1]

    def test_prefill_rows_chunked_logits_match(self, setup):
        """Model-layer equivalence: a prompt prefilled as two chunks
        against gathered cache rows produces the same last logits and
        KV rows as one monolithic prefill_rows call."""
        cfg, params = setup
        n, plen, half = 2, 64, 32
        toks = np.array([[(i * 7 + r * 13 + 3) % cfg.vocab_size
                          for i in range(plen)] for r in range(n)],
                        np.int32)
        lens = jnp.full((n,), plen, jnp.int32)
        last_mono, (k_mono, v_mono) = llama.prefill_rows(
            params, jnp.asarray(toks), lens, cfg, attn_impl='xla')
        # chunk 1: plain causal (offset 0)
        _, (k1, v1) = llama.prefill_rows(
            params, jnp.asarray(toks[:, :half]),
            jnp.full((n,), half, jnp.int32), cfg, attn_impl='xla')
        # chunk 2: attends chunk 1's rows at a nonzero cache offset
        starts = jnp.full((n,), half, jnp.int32)
        last_chunk, (k2, v2) = llama.prefill_rows(
            params, jnp.asarray(toks[:, half:]),
            jnp.full((n,), half, jnp.int32), cfg, attn_impl='xla',
            cache_kv=(k1, v1), cache_len=starts)
        np.testing.assert_allclose(np.asarray(last_chunk),
                                   np.asarray(last_mono),
                                   rtol=2e-2, atol=2e-2)
        np.testing.assert_allclose(
            np.asarray(jnp.concatenate([k1, k2], axis=2)
                       .astype(jnp.float32)),
            np.asarray(k_mono.astype(jnp.float32)),
            rtol=2e-2, atol=2e-2)

    def test_sampling_through_chunked_completion(self, setup):
        """A completing chunk samples its first token on device with
        the request's params; hot sampling still yields varied, valid
        tokens, and top_p->0 collapses to the greedy output."""
        cfg, params = setup
        eng = InferenceEngine(cfg, params, max_batch=2, max_seq=256,
                              attn_impl='xla', prefill_chunk_tokens=16,
                              rng_seed=7)
        prompt = [(i * 3 + 1) % cfg.vocab_size for i in range(40)]
        g = eng.add_request(prompt, max_new_tokens=10)
        h = eng.add_request(prompt, max_new_tokens=10,
                            temperature=2.0, top_p=1e-6)
        done = eng.run_to_completion(horizon=4)
        assert done[g].output == done[h].output
        eng2 = InferenceEngine(cfg, params, max_batch=1, max_seq=256,
                               attn_impl='xla',
                               prefill_chunk_tokens=16, rng_seed=7)
        rid = eng2.add_request(prompt, max_new_tokens=12,
                               temperature=2.0, top_k=50)
        out = eng2.run_to_completion(horizon=4)[rid].output
        assert len(out) == 12
        assert all(0 <= t < cfg.vocab_size for t in out)

    def test_cancel_mid_prefill_frees_slot(self, setup):
        cfg, params = setup
        eng = InferenceEngine(cfg, params, max_batch=1, max_seq=256,
                              attn_impl='xla', prefill_chunk_tokens=16)
        rid = eng.add_request(list(range(1, 150)), max_new_tokens=8)
        eng.step(horizon=1)                    # first chunk in flight
        assert eng._prefill_off
        assert eng.cancel(rid)
        assert not eng._prefill_off and eng.num_active == 0
        r2 = eng.add_request([7, 8], max_new_tokens=3)
        done = eng.run_to_completion(horizon=4)
        assert len(done[r2].output) == 3 and rid not in done


@pytest.mark.slow
class TestFlashChunkKernel:
    """The flash forward's nonzero-cache-offset path (interpret mode on
    CPU) matches the XLA two-block softmax exactly."""

    def test_chunk_path_matches_cached_attention(self):
        from skypilot_tpu.ops.attention import cached_attention
        from skypilot_tpu.ops.flash_attention import flash_attention
        b, s, S, h, hkv, d = 2, 128, 256, 4, 2, 128
        ks = jax.random.split(jax.random.PRNGKey(0), 5)
        q = jax.random.normal(ks[0], (b, s, h, d), jnp.float32)
        kn = jax.random.normal(ks[1], (b, s, hkv, d), jnp.float32)
        vn = jax.random.normal(ks[2], (b, s, hkv, d), jnp.float32)
        ck = jax.random.normal(ks[3], (b, S, hkv, d), jnp.float32)
        cv = jax.random.normal(ks[4], (b, S, hkv, d), jnp.float32)
        # one row mid-prompt, one at offset 0 (no live cache rows)
        cl = jnp.array([100, 0], jnp.int32)
        ref = cached_attention(q, kn, vn, ck, cv, cl)
        out = flash_attention(q, jnp.concatenate([ck, kn], 1),
                              jnp.concatenate([cv, vn], 1), causal=True,
                              cache_len=cl, kv_split=S, interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-3, atol=2e-3)

    def test_chunk_path_validates_layout(self):
        from skypilot_tpu.ops.flash_attention import flash_attention
        q = jnp.zeros((1, 128, 2, 128))
        kv = jnp.zeros((1, 200, 2, 128))
        with pytest.raises(ValueError, match='cache'):
            flash_attention(q, kv, kv, causal=True,
                            cache_len=jnp.zeros(1, jnp.int32),
                            kv_split=128, interpret=True)
