"""Auth/keys: generation, idempotence, rederivation, GCP metadata
injection (reference ``sky/authentication.py`` behaviors)."""
import os

import pytest

from skypilot_tpu import authentication as auth

pytestmark = pytest.mark.usefixtures('tmp_state_dir')


def test_generate_and_idempotent():
    priv, pub = auth.get_or_generate_keys()
    assert os.path.exists(priv) and os.path.exists(pub)
    assert oct(os.stat(priv).st_mode & 0o777) == '0o600'
    pub_text = open(pub, encoding='utf-8').read()
    assert pub_text.startswith('ssh-ed25519 ')
    # Second call returns the same material.
    priv2, pub2 = auth.get_or_generate_keys()
    assert (priv2, pub2) == (priv, pub)
    assert open(pub2, encoding='utf-8').read() == pub_text


def test_public_key_rederived_when_lost():
    priv, pub = auth.get_or_generate_keys()
    original = open(pub, encoding='utf-8').read()
    os.remove(pub)
    _, pub2 = auth.get_or_generate_keys()
    assert open(pub2, encoding='utf-8').read().split()[:2] == \
        original.split()[:2]


def test_tpu_node_body_injection():
    body = auth.configure_node_body({'acceleratorType': 'v5e-8'},
                                    kind='tpu_vm')
    assert body['metadata']['ssh-keys'].startswith('skytpu:ssh-ed25519 ')


def test_gce_body_injection_replaces_existing():
    body = {'metadata': {'items': [{'key': 'ssh-keys', 'value': 'old'}]}}
    body = auth.configure_node_body(body, kind='gce')
    items = body['metadata']['items']
    assert len(items) == 1
    assert items[0]['value'].startswith('skytpu:ssh-ed25519 ')
