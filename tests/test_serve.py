"""SkyServe end-to-end on the local provisioner: controller-on-a-cluster,
replicas-as-clusters behind the LB, readiness gating, autoscaler
replacement of preempted replicas, teardown.

Hermetic version of the reference's ``tests/smoke_tests/test_sky_serve.py``
(which launches real clouds); replica preemption is forced by terminating
the replica's local cluster out-of-band, as the reference's smoke tests do
with cloud CLIs.
"""
import json
import time
import urllib.error
import urllib.request

import pytest

import skypilot_tpu as sky
from skypilot_tpu import global_state
from skypilot_tpu import serve
from skypilot_tpu.task import Task

pytestmark = [pytest.mark.usefixtures('tmp_state_dir', 'fast_serve'), pytest.mark.slow]


@pytest.fixture()
def fast_serve(monkeypatch):
    monkeypatch.setenv('SKYTPU_AGENT_TICK', '0.1')
    monkeypatch.setenv('SKYTPU_AGENT_READY_TIMEOUT', '30')
    monkeypatch.setenv('SKYTPU_SERVE_TICK', '0.5')
    monkeypatch.setenv('SKYTPU_LB_SYNC', '0.5')


# A replica server that answers the readiness probe and echoes its
# replica id — enough to verify LB fan-out without loading a model.
_REPLICA_SERVER = r'''
import http.server, json, os

class H(http.server.BaseHTTPRequestHandler):
    def log_message(self, *a):
        pass
    def _send(self):
        body = json.dumps(
            {"replica": os.environ.get("SKYTPU_SERVE_REPLICA_ID"),
             "msg": os.environ.get("MSG", "")}).encode()
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)
    do_GET = do_POST = lambda self: self._send()

port = int(os.environ["SKYTPU_REPLICA_PORT"])
http.server.ThreadingHTTPServer(("127.0.0.1", port), H).serve_forever()
'''


def _service_task(tmp_path, n_replicas=2, policy=None) -> Task:
    script = tmp_path / 'replica_server.py'
    script.write_text(_REPLICA_SERVER)
    service = {
        'readiness_probe': {'path': '/readiness',
                            'initial_delay_seconds': 20},
    }
    if policy is not None:
        service['replica_policy'] = policy
    else:
        service['replicas'] = n_replicas
    task = Task(name='echo', run=f'python {script}')
    task.service = service
    task.set_resources(sky.Resources(cloud='local', cpus='1+'))
    return task


def _wait_ready(name: str, n_ready: int = 1, timeout: float = 60.0):
    deadline = time.time() + timeout
    last = None
    while time.time() < deadline:
        try:
            svcs = serve.status([name])
        except Exception:
            svcs = []
        if svcs:
            last = svcs[0]
            ready = [r for r in last['replicas'] if r['status'] == 'READY']
            if last['status'] == 'READY' and len(ready) >= n_ready:
                return last
        time.sleep(0.3)
    raise AssertionError(f'service never became READY: {last}')


def _get(url: str, timeout: float = 10.0) -> dict:
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read())


def _get_retry(url: str, deadline_s: float = 20.0) -> dict:
    """GET with retries: the LB learns a newly-READY replica only at its
    next controller sync, so the first request(s) after _wait_ready may
    legitimately 502 under load."""
    deadline = time.time() + deadline_s
    last: Exception = AssertionError('no attempt')
    while time.time() < deadline:
        try:
            return _get(url)
        except Exception as e:  # noqa: BLE001 — urllib HTTPError/URLError
            last = e
            time.sleep(0.3)
    raise AssertionError(f'GET {url} never succeeded: {last!r}')


def _down_all():
    try:
        for svc in serve.status():
            try:
                serve.down(svc['name'])
            except Exception:
                pass
    except Exception:
        pass
    from skypilot_tpu import core
    try:
        core.down(serve.core.CONTROLLER_CLUSTER_NAME)
    except Exception:
        pass


def test_serve_up_two_replicas_lb_and_down(tmp_path):
    task = _service_task(tmp_path, n_replicas=2)
    try:
        result = serve.up(task, service_name='echo')
        assert result['name'] == 'echo'
        svc = _wait_ready('echo', n_ready=2)
        assert len(svc['replicas']) == 2

        # LB proxies to both replicas (round robin). The LB learns a
        # newly-READY replica at its next controller sync, so poll past
        # that propagation window rather than sampling instantly.
        seen = set()
        deadline = time.time() + 20
        while time.time() < deadline and seen != {'1', '2'}:
            try:
                seen.add(_get(result['endpoint'] + '/hello')['replica'])
            except Exception:
                pass  # LB may 502 until its next sync picks up a replica
            time.sleep(0.2)
        assert seen == {'1', '2'}

        # Replica clusters exist as ordinary clusters.
        assert global_state.get_cluster_from_name(
            'echo-replica-1') is not None

        serve.down('echo')
        # Generous deadline: teardown joins two replica-cluster downs and
        # process-tree kills, which slow down on a contended host.
        deadline = time.time() + 60
        while time.time() < deadline:
            if not serve.status(['echo']):
                break
            time.sleep(0.3)
        assert serve.status(['echo']) == []
        # Replica clusters are gone.
        deadline = time.time() + 30
        while time.time() < deadline:
            if global_state.get_cluster_from_name(
                    'echo-replica-1') is None:
                break
            time.sleep(0.3)
        assert global_state.get_cluster_from_name('echo-replica-1') is None
    finally:
        _down_all()


def test_serve_recovers_preempted_replica(tmp_path):
    task = _service_task(tmp_path, n_replicas=1)
    try:
        serve.up(task, service_name='rec')
        _wait_ready('rec', n_ready=1)

        # Preempt: terminate the replica cluster out-of-band.
        from skypilot_tpu import core
        core.down('rec-replica-1')

        # Controller must notice and launch a replacement replica.
        deadline = time.time() + 60
        replacement = None
        while time.time() < deadline:
            svcs = serve.status(['rec'])
            if svcs:
                ready = [r for r in svcs[0]['replicas']
                         if r['status'] == 'READY'
                         and r['replica_id'] != 1]
                if ready:
                    replacement = ready[0]
                    break
            time.sleep(0.3)
        assert replacement is not None, 'no replacement replica appeared'
        assert replacement['replica_id'] == 2
    finally:
        _down_all()


def test_serve_update_blue_green(tmp_path):
    """serve.update rolls the service to a new task version: replacement
    replicas launch with the new env, old-version replicas drain once
    the new ones are READY, and the LB serves the new behavior."""
    task = _service_task(tmp_path, n_replicas=1)
    task.update_envs({'MSG': 'v1'})
    try:
        result = serve.up(task, service_name='upd')
        _wait_ready('upd', n_ready=1)
        assert _get_retry(result['endpoint'] + '/x')['msg'] == 'v1'

        new_task = _service_task(tmp_path, n_replicas=1)
        new_task.update_envs({'MSG': 'v2'})
        out = serve.update(new_task, 'upd')
        assert out['version'] == 2

        deadline = time.time() + 90
        drained = False
        while time.time() < deadline:
            svcs = serve.status(['upd'])
            if svcs:
                reps = svcs[0]['replicas']
                v2_ready = [r for r in reps if r['version'] == 2
                            and r['status'] == 'READY']
                v1_left = [r for r in reps if r['version'] == 1]
                if v2_ready and not v1_left:
                    drained = True
                    break
            time.sleep(0.3)
        assert drained, serve.status(['upd'])
        # The LB drops the drained v1 URL at its next controller sync;
        # poll past that propagation window (and transient 502s while
        # the old replica dies).
        deadline = time.time() + 20
        msg = None
        while time.time() < deadline:
            try:
                msg = _get(result['endpoint'] + '/x')['msg']
            except Exception:
                msg = None
            if msg == 'v2':
                break
            time.sleep(0.3)
        assert msg == 'v2'
    finally:
        _down_all()


def test_serve_rejects_task_without_service():
    task = Task(name='nosvc', run='true')
    task.set_resources(sky.Resources(cloud='local', cpus='1+'))
    from skypilot_tpu import exceptions
    with pytest.raises(exceptions.InvalidServiceSpecError):
        serve.up(task, service_name='nosvc')
