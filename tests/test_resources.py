"""Spec-layer tests: accelerators, Resources, Task, Dag.

Modeled on reference tests/unit_tests/test_resources.py + test_dag.py.
"""
import pytest

from skypilot_tpu import Dag, Resources, Task, exceptions
from skypilot_tpu import accelerators as accel_lib


class TestTpuParsing:

    def test_v5e_single_host(self):
        t = accel_lib.parse_tpu('tpu-v5e-8')
        assert t.num_chips == 8
        assert t.num_hosts == 1
        assert t.chips_per_host == 8
        assert t.name == 'tpu-v5e-8'
        assert not t.is_pod

    def test_v5e_pod(self):
        t = accel_lib.parse_tpu('tpu-v5e-64')
        assert t.num_chips == 64
        assert t.num_hosts == 8
        assert t.is_pod

    def test_v4_names_by_cores(self):
        t = accel_lib.parse_tpu('tpu-v4-8')
        assert t.num_chips == 4
        assert t.num_cores == 8
        assert t.num_hosts == 1

    def test_v5p_pod(self):
        t = accel_lib.parse_tpu('tpu-v5p-64')
        assert t.num_chips == 32
        assert t.num_hosts == 8

    def test_v5litepod_alias(self):
        t = accel_lib.parse_tpu('tpu-v5litepod-16')
        assert t.name == 'tpu-v5e-16'
        assert t.num_hosts == 2

    def test_v6e(self):
        t = accel_lib.parse_tpu('tpu-v6e-16')
        assert t.num_chips == 16
        assert t.num_hosts == 2

    def test_accelerator_api_type(self):
        assert accel_lib.parse_tpu('tpu-v5e-8').accelerator_type == 'v5litepod-8'
        assert accel_lib.parse_tpu('tpu-v4-8').accelerator_type == 'v4-8'

    def test_bad_names(self):
        with pytest.raises(exceptions.InvalidResourcesError):
            accel_lib.parse_tpu('tpu-v9-8')
        with pytest.raises(exceptions.InvalidResourcesError):
            accel_lib.parse_tpu('gpu-a100')
        with pytest.raises(exceptions.InvalidResourcesError):
            accel_lib.parse_tpu('tpu-v4-7')  # not multiple of cores/chip

    def test_mesh_factorization(self):
        assert accel_lib.parse_tpu('tpu-v5e-16').mesh_shape_2d() == (4, 4)
        assert accel_lib.parse_tpu('tpu-v5e-8').mesh_shape_2d() == (2, 4)


class TestResources:

    def test_tpu_resources(self):
        r = Resources(accelerators='tpu-v5e-8')
        assert r.is_tpu
        assert r.cloud == 'gcp'
        assert r.tpu.num_chips == 8
        assert r.accelerators == {'tpu-v5e-8': 1}

    def test_tpu_wrong_cloud(self):
        with pytest.raises(exceptions.InvalidResourcesError):
            Resources(cloud='aws', accelerators='tpu-v5e-8')

    def test_gpu_resources(self):
        r = Resources(accelerators={'A100': 8}, use_spot=True)
        assert not r.is_tpu
        assert r.accelerators == {'A100': 8}
        assert r.use_spot

    def test_gpu_string_count(self):
        r = Resources(accelerators='a100:4')
        assert r.accelerators == {'A100': 4}

    def test_cpus_at_least(self):
        r = Resources(cpus='8+')
        assert r.cpus == '8+'

    def test_copy_override(self):
        r = Resources(accelerators='tpu-v5e-8', region='us-central1')
        r2 = r.copy(use_spot=True)
        assert r2.use_spot and r2.region == 'us-central1' and r2.is_tpu
        assert not r.use_spot

    def test_yaml_roundtrip(self):
        r = Resources(accelerators='tpu-v5p-16', use_spot=True,
                      zone='us-east5-a', disk_size=512)
        r2 = Resources.from_yaml_config(r.to_yaml_config())
        assert r == r2

    def test_any_of_list(self):
        lst = Resources.from_yaml_config_list({
            'use_spot': True,
            'any_of': [{'accelerators': 'tpu-v5e-8'},
                       {'accelerators': 'A100:8'}],
        })
        assert len(lst) == 2
        assert lst[0].is_tpu and lst[0].use_spot
        assert lst[1].accelerators == {'A100': 8} and lst[1].use_spot

    def test_less_demanding(self):
        want = Resources(accelerators='tpu-v5e-8')
        have = Resources(accelerators='tpu-v5e-8', region='us-central1')
        assert want.less_demanding_than(have)
        assert not Resources(accelerators='tpu-v5e-16').less_demanding_than(have)


class TestTask:

    def test_from_yaml_config(self):
        task = Task.from_yaml_config({
            'name': 'train',
            'resources': {'accelerators': 'tpu-v5e-16'},
            'envs': {'MODEL': 'llama3-8b'},
            'run': 'python train.py --model $MODEL',
        })
        assert task.name == 'train'
        assert task.best_resources.tpu.num_chips == 16
        assert task.num_hosts() == 2

    def test_env_interpolation_in_non_script_fields(self):
        task = Task.from_yaml_config({
            'envs': {'BUCKET': 'gs://ckpts'},
            'file_mounts': {'/ckpt': {'source': '$BUCKET', 'mode': 'MOUNT'}},
        })
        assert task.storage_mounts['/ckpt']['source'] == 'gs://ckpts'

    def test_tpu_task_num_nodes_means_slices(self):
        # num_nodes on a TPU task = slice count (multi-slice DCN job);
        # total hosts = slices x hosts-per-slice.
        task = Task.from_yaml_config({
            'num_nodes': 2,
            'resources': {'accelerators': 'tpu-v5e-16'},
        })
        task.set_best_resources(task.best_resources
                                or task._resources[0])
        assert task.num_hosts(task._resources[0]) == 4

    def test_cpu_task_num_nodes(self):
        task = Task.from_yaml_config({'num_nodes': 4, 'run': 'hostname'})
        assert task.num_hosts() == 4

    def test_unknown_field_rejected(self):
        with pytest.raises(exceptions.InvalidTaskError):
            Task.from_yaml_config({'runs': 'typo'})

    def test_yaml_roundtrip(self):
        cfg = {
            'name': 'serve',
            'resources': {'accelerators': 'tpu-v5e-8', 'use_spot': True},
            'run': 'python serve.py',
        }
        task = Task.from_yaml_config(cfg)
        task2 = Task.from_yaml_config(task.to_yaml_config())
        assert task2.name == 'serve'
        assert task2.best_resources == task.best_resources


class TestDag:

    def test_chain(self):
        with Dag() as dag:
            a = Task(name='a')
            b = Task(name='b')
            c = Task(name='c')
            dag.add(a)
            a >> b >> c
        assert dag.is_chain()
        assert [t.name for t in dag.topological_order()] == ['a', 'b', 'c']

    def test_not_chain(self):
        with Dag() as dag:
            a, b, c = Task(name='a'), Task(name='b'), Task(name='c')
            a >> b
            a >> c
        assert not dag.is_chain()

    def test_cycle_detection(self):
        with Dag() as dag:
            a, b = Task(name='a'), Task(name='b')
            a >> b
            b >> a
        with pytest.raises(exceptions.InvalidDagError):
            dag.validate()


class TestCatalog:

    def test_tpu_entries(self):
        from skypilot_tpu import catalog
        tpus = catalog.get_tpus()
        assert 'tpu-v5e-8' in tpus
        assert 'tpu-v5p-128' in tpus

    def test_zones_sorted_by_price(self):
        from skypilot_tpu import catalog
        entries = catalog.zones_for_accelerator('tpu-v5e-8')
        assert entries
        prices = [e.price for e in entries]
        assert prices == sorted(prices)

    def test_spot_cheaper(self):
        from skypilot_tpu import catalog
        e = catalog.zones_for_accelerator('tpu-v5e-8')[0]
        assert e.spot_price < e.price

    def test_cpu_instance_pick(self):
        from skypilot_tpu import catalog
        e = catalog.get_instance_type_for_cpus(cpus=8)
        assert e is not None
        assert e.vcpus >= 8
        assert e.accelerator_name is None

    def test_hourly_cost_tpu(self):
        from skypilot_tpu import catalog
        cost = catalog.get_hourly_cost('TPU-VM',
                                       accelerator_name='tpu-v5e-8')
        assert cost > 0
