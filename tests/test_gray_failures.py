"""Gray-failure defense (round 13): wedge watchdog, NaN blast-radius
isolation, checksummed KV wires, byzantine-replica quarantine.

The contract under test: a replica that keeps answering HTTP while
serving wrong bytes (bit-flipped KV, corrupted weights, byzantine
responses) or nothing at all (wedged step) must be DETECTED and
CONTAINED — per-request eviction for NaN bursts, checksum refusal for
corrupt wires, degraded readiness + failover for wedges, quarantine
for byzantine replicas — with zero lost requests and byte-identical
surviving streams end to end.
"""
import json
import struct
import threading
import time
import urllib.error
import urllib.request

import jax
import numpy as np
import pytest

from skypilot_tpu.serve import faults as faults_lib
from skypilot_tpu.utils import common_utils

jax.config.update('jax_platforms', 'cpu')


# ---------------------------------------------------------------------------
# Checksummed wire formats (SKKV / SKPF / SKCK v2)
# ---------------------------------------------------------------------------
def _bf16_snapshot(n_rows=5):
    import ml_dtypes
    L, hkv, d = 2, 2, 4
    return {
        'kv_cache_dtype': 'bf16', 'n_rows': n_rows,
        'model': {'n_layers': L, 'n_kv_heads': hkv, 'head_dim': d},
        'prompt': [1, 2, 3], 'output': [4, 5, 6],
        'max_new_tokens': 10, 'temperature': 0.0, 'top_k': 0,
        'top_p': 1.0, 'eos_id': None, 'stop': None, 'priority': 0,
        'k': np.arange(L * n_rows * hkv * d, dtype=np.float32
                       ).reshape(L, n_rows, hkv, d
                                 ).astype(ml_dtypes.bfloat16),
        'v': np.ones((L, n_rows, hkv, d), ml_dtypes.bfloat16),
        'k_scale': None, 'v_scale': None,
    }


def _int8_snapshot(n_rows=5):
    L, hkv, d = 2, 2, 4
    return {
        'kv_cache_dtype': 'int8', 'n_rows': n_rows,
        'model': {'n_layers': L, 'n_kv_heads': hkv, 'head_dim': d},
        'prompt': [1, 2, 3], 'output': [4, 5, 6],
        'max_new_tokens': 10, 'temperature': 0.0, 'top_k': 0,
        'top_p': 1.0, 'eos_id': None, 'stop': None, 'priority': 0,
        'k': (np.arange(L * n_rows * hkv * d) % 127).astype(np.int8
              ).reshape(L, n_rows, hkv, d),
        'v': np.ones((L, n_rows, hkv, d), np.int8),
        'k_scale': np.full((L, n_rows, hkv), 0.5, np.float32),
        'v_scale': np.full((L, n_rows, hkv), 0.25, np.float32),
    }


@pytest.mark.parametrize('dtype', ['bf16', 'int8'])
def test_wire_fuzz_handoff_every_byte(dtype):
    """Flip EVERY byte of a v2 SKKV container, one at a time — magic,
    header, every buffer, every checksum — and assert the decoder
    refuses each mutation with ValueError. Zero silent mis-decodes:
    the property that makes a bit-flipped handoff a retryable refusal
    instead of a byte-wrong continuation."""
    from skypilot_tpu.inference import kv_transfer as kt
    snap = _bf16_snapshot() if dtype == 'bf16' else _int8_snapshot()
    blob = kt.encode_handoff(snap)
    ref = kt.decode_handoff(blob)            # pristine decodes fine
    assert ref['n_rows'] == snap['n_rows']
    for i in range(len(blob)):
        mutated = bytearray(blob)
        mutated[i] ^= 0xff
        with pytest.raises(ValueError):
            kt.decode_handoff(bytes(mutated))


def test_wire_fuzz_prefix_and_checkpoint_every_byte():
    from skypilot_tpu.inference import kv_transfer as kt
    snap = _int8_snapshot()
    pe = kt.as_prefix_entry(snap)
    pblob = kt.encode_prefix_chain(pe)
    assert kt.decode_prefix_chain(pblob)['tokens'] == pe['tokens']
    for i in range(len(pblob)):
        mutated = bytearray(pblob)
        mutated[i] ^= 0xff
        with pytest.raises(ValueError):
            kt.decode_prefix_chain(bytes(mutated))
    cblob = kt.encode_checkpoint([snap, pe])
    kinds = [e['entry_kind'] for e in kt.decode_checkpoint(cblob)]
    assert kinds == ['request', 'prefix']
    for i in range(len(cblob)):
        mutated = bytearray(cblob)
        mutated[i] ^= 0xff
        with pytest.raises(ValueError):
            kt.decode_checkpoint(bytes(mutated))


def _downgrade_handoff_to_v1(blob, magic):
    """Re-pack a v2 container as the version-1 (pre-checksum) layout:
    version=1 header, no crc32 manifest entries, no trailing header
    CRC — what an old replica's checkpoint file looks like."""
    off = len(magic)
    (hlen,) = struct.unpack_from('>I', blob, off)
    header = json.loads(blob[off + 4:off + 4 + hlen])
    header['version'] = 1
    for meta in header['buffers']:
        meta.pop('crc32', None)
    hj = json.dumps(header).encode()
    body = blob[off + 4 + hlen:len(blob) - 4]     # strip header CRC
    return magic + struct.pack('>I', len(hj)) + hj + body


def test_wire_v1_containers_still_decode():
    """Old (version-1, pre-checksum) containers stay readable — a
    checkpoint written before the CRC rollout must still warm a new
    replica."""
    from skypilot_tpu.inference import kv_transfer as kt
    snap = _int8_snapshot()
    v1 = _downgrade_handoff_to_v1(kt.encode_handoff(snap), kt.MAGIC)
    out = kt.decode_handoff(v1)
    assert out['n_rows'] == snap['n_rows']
    np.testing.assert_array_equal(out['k'], snap['k'])
    pe = kt.as_prefix_entry(snap)
    v1p = _downgrade_handoff_to_v1(kt.encode_prefix_chain(pe),
                                   kt.PREFIX_MAGIC)
    assert kt.decode_prefix_chain(v1p)['tokens'] == pe['tokens']
    # v1 SKCK: version word 1, 8-byte (crc-less) entry prefixes.
    out_blobs = [kt.encode_handoff(snap)]
    v1c = (kt.CKPT_MAGIC + struct.pack('>I', 1)
           + struct.pack('>I', len(out_blobs))
           + b''.join(struct.pack('>Q', len(b)) + b
                      for b in out_blobs))
    entries = kt.decode_checkpoint(v1c)
    assert [e['entry_kind'] for e in entries] == ['request']


def test_corrupt_container_lands_nothing(tmp_path):
    """All-or-nothing warmup: a corrupt checkpoint body raises BEFORE
    any pool/slot mutation — the pool's page accounting is untouched
    (a truncated-or-corrupt body can never partially land rows)."""
    from skypilot_tpu.inference import kv_transfer as kt
    from skypilot_tpu.inference.paged import PagedInferenceEngine
    from skypilot_tpu.models import configs
    eng = PagedInferenceEngine(configs.get_config('tiny'),
                               max_batch=2, max_seq=64)
    rid = eng.add_request(list(range(1, 20)), max_new_tokens=4)
    eng.run_to_completion()
    entries, _ = eng.export_prefix_snapshots()
    assert entries, 'expected a cached prefix chain to export'
    blob = kt.encode_checkpoint(entries)
    free0 = len(eng.alloc.free)
    retained0 = len(eng.alloc.retained)
    corrupt = bytearray(blob)
    corrupt[len(blob) // 2] ^= 0xff               # mid-buffer flip
    with pytest.raises(ValueError):
        kt.decode_checkpoint(bytes(corrupt))
    assert len(eng.alloc.free) == free0
    assert len(eng.alloc.retained) == retained0
    del rid


# ---------------------------------------------------------------------------
# NaN blast-radius isolation
# ---------------------------------------------------------------------------
def test_mask_nonfinite_tokens_unit():
    import jax.numpy as jnp
    from skypilot_tpu.models import llama
    logits = jnp.array([[1.0, 2.0, 3.0],
                        [1.0, jnp.nan, 3.0],
                        [jnp.inf, 2.0, 3.0],
                        [0.0, 0.0, 0.0]])
    toks = jnp.array([2, 1, 0, 0], jnp.int32)
    out = np.asarray(llama.mask_nonfinite_tokens(logits, toks))
    assert out.tolist() == [2, llama.NONFINITE_TOKEN,
                            llama.NONFINITE_TOKEN, 0]


@pytest.mark.parametrize('kind', ['slot', 'paged'])
def test_nan_poisoned_params_evict_all(kind):
    """Poisoned weights (every logits row NaN): every live request is
    evicted with ``nan_evicted`` — never streamed as argmax-of-NaN
    (which is token 0, silently plausible)."""
    import jax.numpy as jnp
    from skypilot_tpu.inference.engine import InferenceEngine
    from skypilot_tpu.inference.paged import PagedInferenceEngine
    from skypilot_tpu.models import configs
    cls = InferenceEngine if kind == 'slot' else PagedInferenceEngine
    eng = cls(configs.get_config('tiny'), max_batch=2, max_seq=64)
    rid0 = eng.add_request([1, 2, 3, 4], max_new_tokens=4)
    fin = eng.run_to_completion()
    assert len(fin[rid0].output) == 4            # healthy baseline
    eng.params['final_norm'] = jnp.full_like(eng.params['final_norm'],
                                             jnp.nan)
    rid = eng.add_request([5, 6, 7, 8], max_new_tokens=4)
    evicted = []
    for _ in range(50):
        if not (eng.has_work() or eng._pending):
            break
        for r, tok, done in eng.step(horizon=2):
            if r == rid and tok < 0 and done:
                evicted.append(r)
    assert evicted == [rid]
    assert eng.nan_evictions >= 1
    assert eng.num_active == 0
    assert eng.pop_finished(rid) is None          # never "finished"


def test_nan_blast_radius_is_one_request():
    """Co-batched isolation: when ONE slot's readback carries the
    sentinel, exactly that request is evicted; its neighbor's tokens
    land and the neighbor runs to completion untouched."""
    from skypilot_tpu.inference.engine import InferenceEngine
    from skypilot_tpu.models import configs
    eng = InferenceEngine(configs.get_config('tiny'), max_batch=2,
                          max_seq=64)
    ra = eng.add_request([1, 2, 3, 4], max_new_tokens=6)
    rb = eng.add_request([9, 8, 7, 6], max_new_tokens=6)
    # Drive until both are decoding with a pending decode call.
    for _ in range(20):
        eng.step(horizon=1)
        if (eng.num_active == 2 and eng._pending
                and eng._pending[0]['kind'] == 'decode'):
            break
    assert eng._pending and eng._pending[0]['kind'] == 'decode'
    entry = eng._pending[0]
    slot_a = next(s for s, r in enumerate(entry['snapshot'])
                  if r is not None and r.request_id == ra)
    toks = np.array(jax.device_get(entry['toks']))
    toks[slot_a, :] = -1                          # poison ONE slot
    entry['toks'] = toks                          # host array: readback
    events = eng._process_one()
    assert (ra, -1, True) in events
    assert all(tok >= 0 for r, tok, _ in events if r == rb)
    req_a = next(r for r in [entry['snapshot'][slot_a]])
    assert req_a.nan_evicted
    # The neighbor finishes normally.
    fin = eng.run_to_completion()
    assert rb in fin and len(fin[rb].output) == 6
    assert ra not in fin


def test_scheduler_turns_sentinel_into_retryable_error():
    """The scheduler fails exactly the poisoned request's outbox with
    a retryable NaN message and ticks the gray-failure counter; other
    events in the same batch route normally."""
    from skypilot_tpu import telemetry
    from skypilot_tpu.serve import scheduler as sched_lib

    class FakeEngine:
        max_batch = 4
        num_active = 0
        queue_depth = 0
        _next = 100

        def add_request(self, prompt, **kw):
            FakeEngine._next += 1
            return FakeEngine._next

        def pop_finished(self, rid):
            return None

        def remaining_work_tokens(self):
            return 0

    lock = threading.Lock()
    sched = sched_lib.RequestScheduler(lock)
    eng = FakeEngine()
    sched.bind_engine(eng)
    sra = sched.submit([1, 2], max_new_tokens=4)
    srb = sched.submit([3, 4], max_new_tokens=4)
    sched.fill_engine(eng)
    assert sra.request_id is not None and srb.request_id is not None
    c = telemetry.get_registry().counter(
        'skytpu_gray_failures_total',
        'Gray failures detected by the data-plane defense layer',
        kind='nan_logits')
    before = c.value
    sched.on_events(eng, [(sra.request_id, -1, True),
                          (srb.request_id, 7, False)])
    assert c.value == before + 1
    tok, done = sra.outbox.get(timeout=5)
    assert tok is None and done
    assert 'non-finite' in sra.outbox.error
    tok, done = srb.outbox.get(timeout=5)
    assert tok == 7 and not done                 # neighbor untouched


# ---------------------------------------------------------------------------
# Wedge watchdog
# ---------------------------------------------------------------------------
def _make_server(**kw):
    from skypilot_tpu.serve.server import ModelServer
    kw.setdefault('max_batch', 2)
    kw.setdefault('max_seq', 128)
    kw.setdefault('port', common_utils.find_free_port(19900))
    return ModelServer('tiny', **kw)


def test_watchdog_virtual_clock_unit():
    """Clock-injected watchdog: arming a step and advancing the
    virtual clock past the deadline flips the replica to degraded,
    fails the scheduler over, and ticks the gray counter — without
    ever loading an engine or starting HTTP."""
    from skypilot_tpu import telemetry
    clock = {'t': 100.0}
    srv = _make_server(step_watchdog_s=5.0,
                       watchdog_clock=lambda: clock['t'])
    assert srv.watchdog_age_s() == 0.0
    assert srv.watchdog_check() is False          # nothing armed
    srv._wd_arm()
    clock['t'] += 4.0
    assert srv.watchdog_check() is False          # under deadline
    assert 3.9 < srv.watchdog_age_s() < 4.1
    clock['t'] += 2.0
    c = telemetry.get_registry().counter(
        'skytpu_gray_failures_total',
        'Gray failures detected by the data-plane defense layer',
        kind='wedged_step')
    before = c.value
    assert srv.watchdog_check() is True           # fired
    assert c.value == before + 1
    assert srv._degraded is not None and 'wedged_step' in srv._degraded
    assert not srv._ready.is_set()
    with pytest.raises(RuntimeError):
        srv.sched.submit([1, 2], max_new_tokens=2)
    assert srv.watchdog_check() is False          # fires exactly once
    # A cleared stamp reports age 0 (the scrape-time gauge value).
    srv._wd_clear()
    assert srv.watchdog_age_s() == 0.0


def test_watchdog_disabled_never_fires():
    clock = {'t': 0.0}
    srv = _make_server(step_watchdog_s=0,
                       watchdog_clock=lambda: clock['t'])
    srv._wd_arm()
    clock['t'] += 1e6
    assert srv.watchdog_check() is False
    assert srv._degraded is None


def test_nan_alarm_escalates_to_degraded():
    """Repeated NaN evictions cross the replica-level alarm threshold:
    the server degrades (sick replica — bad HBM / corrupt weights),
    instead of evicting single requests forever."""
    srv = _make_server(nan_alarm_threshold=3, step_watchdog_s=0)
    assert srv.nan_alarm_threshold == 3
    # The escalation predicate the engine loop applies:
    srv._nan_seen = 3
    srv._gray_degrade('nan_logits', 'replica-level NaN storm',
                      count=False)
    assert srv._degraded is not None and 'nan_logits' in srv._degraded
    assert not srv._ready.is_set()


@pytest.mark.slow
def test_injected_wedge_detected_and_contained():
    """e2e: an injected wedged_step hangs the engine loop mid-run; the
    watchdog (tiny deadline) flips /readiness to a degraded 503, the
    in-flight stream gets a RETRYABLE error, and new submits get a
    retryable 503 — the exact surface the manager and LB act on."""
    port = common_utils.find_free_port(19920)
    srv = _make_server(
        port=port, step_watchdog_s=0.5,
        fault_spec={'seed': 0, 'rules': [
            {'kind': 'wedged_step', 'site': 'engine_step', 'at': 2}]})
    srv.start(block=False)
    try:
        deadline = time.time() + 120
        while time.time() < deadline and not srv._ready.is_set():
            time.sleep(0.2)
        assert srv._ready.is_set()
        body = json.dumps({'prompt': [3, 1, 4, 1, 5], 'stream': True,
                           'max_new_tokens': 64}).encode()
        req = urllib.request.Request(
            f'http://127.0.0.1:{port}/generate', body,
            {'Content-Type': 'application/json'})
        error_ev = None
        with urllib.request.urlopen(req, timeout=120) as r:
            for line in r:
                if not line.startswith(b'data:'):
                    continue
                ev = json.loads(line[5:].strip())
                if 'error' in ev:
                    error_ev = ev
                    break
                if ev.get('done'):
                    break
        assert error_ev is not None, 'wedge never surfaced'
        assert error_ev.get('retryable') is True
        # Readiness reports the degraded state (the manager's probe
        # escalation replaces the replica).
        try:
            with urllib.request.urlopen(
                    f'http://127.0.0.1:{port}/readiness',
                    timeout=10) as r:
                payload = json.loads(r.read())
        except urllib.error.HTTPError as e:
            assert e.code == 503
            payload = json.loads(e.read())
        assert payload.get('status') == 'degraded'
        assert 'wedged_step' in payload.get('cause', '')
        # New submits: retryable 503 (the LB retries elsewhere).
        body2 = json.dumps({'prompt': [1, 2],
                            'max_new_tokens': 2}).encode()
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(urllib.request.Request(
                f'http://127.0.0.1:{port}/generate', body2,
                {'Content-Type': 'application/json'}), timeout=10)
        assert exc.value.code == 503
        assert 'Retry-After' in exc.value.headers
    finally:
        srv.stop()


def _sse_stream(base, prompt, n, timeout=180):
    body = json.dumps({'prompt': prompt, 'stream': True,
                       'max_new_tokens': n}).encode()
    req = urllib.request.Request(
        base + '/generate', body, {'Content-Type': 'application/json'})
    toks, done, err = [], None, None
    with urllib.request.urlopen(req, timeout=timeout) as r:
        for line in r:
            if not line.startswith(b'data:'):
                continue
            ev = json.loads(line[5:].strip())
            if 'token' in ev:
                toks.append(int(ev['token']))
            if 'error' in ev:
                err = ev
                break
            if ev.get('done'):
                done = ev
                break
    return toks, done, err


@pytest.mark.slow
def test_injected_nan_evicts_one_stream_direct():
    """e2e (single replica, no LB): an injected nan_logits evicts the
    live stream with a RETRYABLE error (the event the LB's recovery
    resubmits on), a single hit never trips the replica alarm, and the
    server keeps serving afterwards."""
    port = common_utils.find_free_port(19960)
    srv = _make_server(
        port=port, step_watchdog_s=0, nan_alarm_threshold=100,
        fault_spec={'seed': 0, 'rules': [
            {'kind': 'nan_logits', 'site': 'engine_step', 'at': 2}]})
    srv.start(block=False)
    try:
        deadline = time.time() + 120
        while time.time() < deadline and not srv._ready.is_set():
            time.sleep(0.2)
        toks, done, err = _sse_stream(f'http://127.0.0.1:{port}',
                                      [3, 1, 4, 1, 5], 96)
        assert err is not None and done is None
        assert err.get('retryable') is True
        assert 'non-finite' in str(err.get('error'))
        assert srv.engine.nan_evictions == 1
        assert srv._degraded is None          # one hit: no alarm
        # The replica keeps serving (blast radius was one request).
        toks2, done2, err2 = _sse_stream(f'http://127.0.0.1:{port}',
                                         [9, 8, 7], 8)
        assert err2 is None and len(toks2) == 8
    finally:
        srv.stop()


@pytest.mark.slow
def test_nan_evicted_stream_migrates_byte_identical_through_lb(
        monkeypatch):
    """The acceptance contract: a NaN-evicted stream through the live
    LB migrates to the surviving replica and the client sees ONE
    complete stream whose tokens are byte-identical to an
    uninterrupted run — zero lost requests."""
    import sys
    sys.path.insert(0, 'tests')
    from test_chaos import _FakeController, _start_lb
    from skypilot_tpu import telemetry
    pa = common_utils.find_free_port(20200)
    pb = common_utils.find_free_port(pa + 1)
    # Replica A evicts its first live request (latched nan_logits);
    # replica B is healthy — and the byte-identity reference.
    sa = _make_server(port=pa, step_watchdog_s=0,
                      nan_alarm_threshold=100,
                      fault_spec={'seed': 0, 'rules': [
                          {'kind': 'nan_logits', 'site': 'engine_step',
                           'at': 2}]})
    sb = _make_server(port=pb, step_watchdog_s=0)
    sa.start(block=False)
    sb.start(block=False)
    ctrl = lb = None
    try:
        deadline = time.time() + 180
        while time.time() < deadline and not (
                sa._ready.is_set() and sb._ready.is_set()):
            time.sleep(0.2)
        prompt = [3, 1, 4, 1, 5]
        ref, ref_done, ref_err = _sse_stream(
            f'http://127.0.0.1:{pb}', prompt, 96)
        assert ref_err is None and len(ref) == 96
        # Round-robin selects candidates[0] == replica A for the first
        # request — it lands on the nan-injected replica.
        ctrl = _FakeController([f'http://127.0.0.1:{pa}',
                                f'http://127.0.0.1:{pb}'])
        lb, lb_port = _start_lb(ctrl.url, monkeypatch)
        reg = telemetry.get_registry()
        mig0 = reg.counter('skytpu_requests_migrated_total',
                           'In-flight requests migrated off a failed '
                           'replica', outcome='completed').value
        toks, done, err = _sse_stream(f'http://127.0.0.1:{lb_port}',
                                      prompt, 96)
        assert err is None, err               # zero lost
        assert done is not None
        assert sa.engine.nan_evictions == 1   # A really evicted it
        assert len(toks) == 96
        assert toks == ref                    # byte-identical
        assert done['tokens'] == ref
        # The migrated counter ticks right AFTER the done event flushes
        # — poll briefly instead of racing the LB thread.
        deadline = time.time() + 10
        mc = reg.counter(
            'skytpu_requests_migrated_total',
            'In-flight requests migrated off a failed replica',
            outcome='completed')
        while time.time() < deadline and mc.value < mig0 + 1:
            time.sleep(0.05)
        assert mc.value == mig0 + 1
    finally:
        if lb is not None:
            lb.stop()
        if ctrl is not None:
            ctrl.stop()
        sa.stop()
        sb.stop()


# ---------------------------------------------------------------------------
# Byzantine canary + quarantine (manager-level, fake env)
# ---------------------------------------------------------------------------
class _CanaryEnv:
    """ControlPlaneEnv double: virtual clock + canned canary answers +
    recorded drain/teardown calls."""

    def __init__(self, answers):
        # url -> token list answered to /generate canaries.
        self.answers = dict(answers)
        self.t = 1000.0
        self.drained = []
        self.downed = []
        import random as random_mod
        self._rng = random_mod.Random(0)

    # time
    def time(self):
        return self.t

    def monotonic(self):
        return self.t

    def sleep(self, s):
        self.t += s

    # concurrency: run spawned tasks INLINE (deterministic tests)
    def spawn(self, fn, *args):
        fn(*args)

    def run_parallel(self, fns):
        for fn in fns:
            fn()

    def rng(self):
        return self._rng

    # HTTP
    def http_json(self, url, payload=None, timeout=10.0):
        base, _, path = url.partition('//')[2].partition('/')
        path = '/' + path
        if path == '/generate':
            return {'tokens': list(self.answers[f'http://{base}'])}
        if path == '/drain':
            self.drained.append(f'http://{base}')
            return {'draining': True, 'drained': True, 'inflight': 0}
        raise RuntimeError(f'unexpected {url}')

    def http_post_bytes(self, url, data, content_type='', timeout=30.0):
        raise RuntimeError('unused')

    def probe_http(self, url, post_data, timeout):
        return True

    # clusters
    def launch_cluster(self, task, cluster_name):
        pass

    def cluster_head_ip(self, cluster_name):
        return '127.0.0.1'

    def down_cluster(self, cluster_name):
        self.downed.append(cluster_name)

    def cluster_gone(self, cluster_name):
        return False

    # persistence / faults
    def persist_replica(self, *a, **kw):
        pass

    def remove_replica(self, *a, **kw):
        pass

    def fault_injector(self):
        return None


def _canary_manager(tmp_path, monkeypatch, env):
    monkeypatch.setenv('SKYTPU_SERVE_DIR', str(tmp_path / 'serve'))
    from skypilot_tpu.serve.replica_managers import ReplicaManager
    from skypilot_tpu.serve.service_spec import SkyServiceSpec
    spec = SkyServiceSpec.from_yaml_config(
        {'readiness_probe': '/readiness'})
    return ReplicaManager('gray-test', spec, {}, env=env)


def _seed_ready(mgr, replica_id, url):
    from skypilot_tpu.serve import serve_state
    from skypilot_tpu.serve.replica_managers import ReplicaInfo
    info = ReplicaInfo(replica_id, f'gray-c{replica_id}', 1, False,
                       8000 + replica_id)
    info.url = url
    info.status = serve_state.ReplicaStatus.READY
    with mgr._lock:
        mgr._replicas[replica_id] = info
    return info


def test_canary_digest_stable():
    from skypilot_tpu.serve.replica_managers import canary_digest
    assert canary_digest([1, 2, 3]) == canary_digest((1, 2, 3))
    assert canary_digest([1, 2, 3]) != canary_digest([1, 2, 4])
    assert len(canary_digest([])) == 16


def test_byzantine_replica_quarantined_before_second_response(
        tmp_path, monkeypatch):
    """Two replicas: the first answers the canary honestly (reference
    digest learned), the second answers WRONG — it is quarantined on
    that very first wrong canary: out of ready_urls immediately,
    drained, torn down, counted."""
    from skypilot_tpu import telemetry
    from skypilot_tpu.serve import serve_state
    env = _CanaryEnv({'http://10.0.0.1:8001': [5, 6, 7],
                      'http://10.0.0.2:8002': [5, 6, 99]})
    mgr = _canary_manager(tmp_path, monkeypatch, env)
    mgr.configure_canary(interval_s=30.0, prompt=[11, 13],
                         max_new_tokens=3)
    good = _seed_ready(mgr, 1, 'http://10.0.0.1:8001')
    bad = _seed_ready(mgr, 2, 'http://10.0.0.2:8002')
    reg = telemetry.get_registry()
    q0 = reg.counter(
        'skytpu_replicas_quarantined_total',
        'Replicas quarantined after a byzantine (wrong-digest) '
        'canary response').value
    g0 = reg.counter(
        'skytpu_gray_failures_total',
        'Gray failures detected by the data-plane defense layer',
        kind='byzantine_response').value
    mgr.probe_all()
    # Replica 1 learned the reference; replica 2 mismatched -> gone.
    assert mgr._canary_learned is not None
    assert bad.status in (serve_state.ReplicaStatus.QUARANTINED,
                          serve_state.ReplicaStatus.SHUTTING_DOWN)
    assert good.status == serve_state.ReplicaStatus.READY
    assert mgr.ready_urls() == ['http://10.0.0.1:8001']
    assert mgr.quarantined_count == 1
    assert reg.counter(
        'skytpu_replicas_quarantined_total',
        'Replicas quarantined after a byzantine (wrong-digest) '
        'canary response').value == q0 + 1
    assert reg.counter(
        'skytpu_gray_failures_total',
        'Gray failures detected by the data-plane defense layer',
        kind='byzantine_response').value == g0 + 1
    # The quarantined replica was drained then torn down (the inline
    # env runs the spawned drain->down chain synchronously). Its
    # cluster is in the downed list; the healthy one is untouched.
    assert any('gray-c2' in c for c in env.downed)
    assert not any('gray-c1' in c for c in env.downed)
    # A second canary round against the survivor changes nothing.
    env.t += 60.0
    mgr.probe_all()
    assert mgr.quarantined_count == 1
    assert good.status == serve_state.ReplicaStatus.READY


def test_canary_expected_digest_catches_first_answerer(
        tmp_path, monkeypatch):
    """With a configured expected digest the first answerer gets no
    learn-the-reference grace — a byzantine FIRST replica is caught
    too (closing the quorum-of-one window)."""
    from skypilot_tpu.serve import serve_state
    from skypilot_tpu.serve.replica_managers import canary_digest
    env = _CanaryEnv({'http://10.0.0.9:8009': [1, 2, 3]})
    mgr = _canary_manager(tmp_path, monkeypatch, env)
    mgr.configure_canary(interval_s=10.0, prompt=[11],
                         max_new_tokens=3,
                         expected_digest=canary_digest([7, 7, 7]))
    bad = _seed_ready(mgr, 3, 'http://10.0.0.9:8009')
    mgr.probe_all()
    assert bad.status in (serve_state.ReplicaStatus.QUARANTINED,
                          serve_state.ReplicaStatus.SHUTTING_DOWN)
    assert mgr.quarantined_count == 1


def test_canary_interval_and_transport_failures(tmp_path, monkeypatch):
    """Canary cadence rides the env clock; transport failures are NOT
    byzantine (liveness belongs to the readiness probes)."""
    env = _CanaryEnv({'http://10.0.0.1:8001': [5, 6, 7]})
    mgr = _canary_manager(tmp_path, monkeypatch, env)
    mgr.configure_canary(interval_s=100.0, prompt=[11],
                         max_new_tokens=3)
    info = _seed_ready(mgr, 1, 'http://10.0.0.1:8001')
    mgr.probe_all()
    t_first = info.last_canary_t
    assert t_first > 0
    env.t += 10.0
    mgr.probe_all()                      # within cadence: no canary
    assert info.last_canary_t == t_first
    # Transport failure: replica vanishes from the answer table.
    env.t += 200.0
    env.answers.pop('http://10.0.0.1:8001')
    env.answers['http://10.0.0.1:8001'] = None  # -> TypeError inside

    def boom(url, payload=None, timeout=10.0):
        raise ConnectionRefusedError('canary transport down')

    env.http_json = boom
    mgr.probe_all()
    assert mgr.quarantined_count == 0    # not quarantined
    from skypilot_tpu.serve import serve_state
    assert info.status == serve_state.ReplicaStatus.READY


def test_injected_byzantine_fault_site(tmp_path, monkeypatch):
    """The 'canary' fault site (kind byzantine_response) forces the
    quarantine path deterministically — no corrupt replica needed."""
    from skypilot_tpu.serve import serve_state
    env = _CanaryEnv({'http://10.0.0.1:8001': [5, 6, 7],
                      'http://10.0.0.2:8002': [5, 6, 7]})
    mgr = _canary_manager(tmp_path, monkeypatch, env)
    mgr.configure_canary(interval_s=5.0, prompt=[11], max_new_tokens=3)
    mgr._faults = faults_lib.FaultInjector({'rules': [
        {'kind': 'byzantine_response', 'site': 'canary', 'at': 2}]})
    a = _seed_ready(mgr, 1, 'http://10.0.0.1:8001')
    b = _seed_ready(mgr, 2, 'http://10.0.0.2:8002')
    mgr.probe_all()
    quarantined = [i for i in (a, b)
                   if i.status in (
                       serve_state.ReplicaStatus.QUARANTINED,
                       serve_state.ReplicaStatus.SHUTTING_DOWN)]
    assert len(quarantined) == 1         # exactly the 2nd canary
    assert mgr.quarantined_count == 1


@pytest.mark.slow
def test_live_canary_quarantine_through_lb(tmp_path, monkeypatch):
    """e2e: the manager canaries two LIVE model servers over real HTTP
    (greedy /generate, digest learned from the first), an injected
    byzantine_response quarantines the second on its FIRST wrong
    canary, and an LB policy synced from ready_urls immediately stops
    selecting it — while the healthy replica keeps serving."""
    monkeypatch.setenv('SKYTPU_SERVE_DIR', str(tmp_path / 'serve'))
    from skypilot_tpu.serve import load_balancing_policies as lbp
    from skypilot_tpu.serve import serve_state
    from skypilot_tpu.serve.replica_managers import ReplicaManager
    from skypilot_tpu.serve.service_spec import SkyServiceSpec
    pa = common_utils.find_free_port(20700)
    pb = common_utils.find_free_port(pa + 1)
    sa = _make_server(port=pa, step_watchdog_s=0)
    sb = _make_server(port=pb, step_watchdog_s=0)
    sa.start(block=False)
    sb.start(block=False)
    try:
        deadline = time.time() + 180
        while time.time() < deadline and not (
                sa._ready.is_set() and sb._ready.is_set()):
            time.sleep(0.2)
        spec = SkyServiceSpec.from_yaml_config(
            {'readiness_probe': '/readiness'})
        mgr = ReplicaManager('gray-live', spec, {})
        mgr.configure_canary(interval_s=0.01, prompt=[11, 13, 17],
                             max_new_tokens=6)
        # The injected byzantine hits the SECOND canaried replica.
        mgr._faults = faults_lib.FaultInjector({'rules': [
            {'kind': 'byzantine_response', 'site': 'canary',
             'at': 2}]})
        infos = []
        for rid, port in ((1, pa), (2, pb)):
            info = _seed_ready(mgr, rid, f'http://127.0.0.1:{port}')
            infos.append(info)
        # Canary both (replica ids iterate in insertion order): the
        # first answers honestly over live HTTP and sets the learned
        # digest; the second is forced byzantine.
        assert mgr._canary_check(infos[0]) is False
        assert mgr._canary_learned is not None
        assert mgr._canary_check(infos[1]) is True
        assert infos[1].status in (
            serve_state.ReplicaStatus.QUARANTINED,
            serve_state.ReplicaStatus.SHUTTING_DOWN)
        assert mgr.quarantined_count == 1
        # ready_urls -> LB policy: the quarantined replica is excluded
        # from selection IMMEDIATELY (before it can serve a second
        # wrong response to routed traffic).
        urls = mgr.ready_urls()
        assert urls == [f'http://127.0.0.1:{pa}']
        pol = lbp.make_policy('round_robin')
        pol.set_ready_replicas(urls)
        for _ in range(4):
            assert pol.select_replica() == f'http://127.0.0.1:{pa}'
        # The healthy replica still serves.
        toks, done, err = _sse_stream(f'http://127.0.0.1:{pa}',
                                      [1, 2, 3], 6)
        assert err is None and len(toks) == 6
    finally:
        sa.stop()
        sb.stop()


def test_quarantined_is_terminal_and_excluded():
    from skypilot_tpu.serve import serve_state
    st = serve_state.ReplicaStatus.QUARANTINED
    assert st.is_terminal()
    # LB-policy exclusion: quarantined replicas never reach
    # set_ready_replicas (ready_urls filters on READY), so a policy
    # fed the post-quarantine list cannot select them.
    from skypilot_tpu.serve import load_balancing_policies as lbp
    pol = lbp.make_policy('round_robin')
    pol.set_ready_replicas(['http://a', 'http://b'])
    pol.set_ready_replicas(['http://a'])     # b quarantined
    for _ in range(4):
        assert pol.select_replica() == 'http://a'


# ---------------------------------------------------------------------------
# Corrupted wire -> fallback-local (server-level)
# ---------------------------------------------------------------------------
def test_corrupt_warmup_rejected_with_gray_tick(tmp_path):
    """A corrupted checkpoint container posted to warm_from_checkpoint
    raises (ValueError — the HTTP surface turns it into a 400) and the
    server-side gray counter path recognizes the checksum signature."""
    from skypilot_tpu.inference import kv_transfer as kt
    snap = _int8_snapshot()
    blob = kt.encode_checkpoint([snap])
    corrupt = bytearray(blob)
    corrupt[len(blob) - 20] ^= 0xff
    with pytest.raises(ValueError) as exc:
        kt.decode_checkpoint(bytes(corrupt))
    # The 400 paths key the kv_corruption gray tick on this signature.
    assert ('checksum mismatch' in str(exc.value)
            or 'malformed' in str(exc.value))


def test_corrupt_blob_deterministic():
    rule = faults_lib.FaultRule(kind='kv_corruption', site='kv_wire',
                                at=1, n=5)
    blob = bytes(range(10))
    out = faults_lib.corrupt_blob(blob, rule)
    assert out != blob and len(out) == len(blob)
    assert out == faults_lib.corrupt_blob(blob, rule)   # deterministic
    assert out[5] == blob[5] ^ 0xff
    assert faults_lib.corrupt_blob(b'', rule) == b''


def test_new_fault_kinds_and_sites_validate():
    """The four gray kinds/sites parse strictly (reusing the round-12
    loud-unknown-field machinery): valid rules parse, typo'd sites and
    trigger-less rules are loud ValueErrors."""
    inj = faults_lib.FaultInjector({'seed': 1, 'rules': [
        {'kind': 'wedged_step', 'site': 'engine_step', 'at': 2},
        {'kind': 'nan_logits', 'site': 'engine_step', 'every': 3},
        {'kind': 'kv_corruption', 'site': 'kv_wire', 'at': 1, 'n': 9},
        {'kind': 'byzantine_response', 'site': 'canary', 'at': 1},
        {'kind': 'nan_logits', 'site': 'sim_gray', 'at': 1, 'n': 4},
    ]})
    assert inj.fire('kv_wire') is not None
    with pytest.raises(ValueError, match='unknown fault site'):
        faults_lib.FaultInjector({'rules': [
            {'kind': 'wedged_step', 'site': 'engine_stepp', 'at': 1}]})
    with pytest.raises(ValueError, match='unknown fault kind'):
        faults_lib.FaultInjector({'rules': [
            {'kind': 'wedgedstep', 'site': 'engine_step', 'at': 1}]})
    with pytest.raises(ValueError, match='no.*trigger|trigger'):
        faults_lib.FaultInjector({'rules': [
            {'kind': 'byzantine_response', 'site': 'canary'}]})
    with pytest.raises(ValueError, match='unknown fault-rule field'):
        faults_lib.FaultInjector({'rules': [
            {'kind': 'kv_corruption', 'site': 'kv_wire', 'att': 1}]})


# ---------------------------------------------------------------------------
# Fleet-scale gray storm (simulator)
# ---------------------------------------------------------------------------
def test_sim_gray_failure_storm_zero_lost():
    """The fleet-scale drill: one wedged replica, a NaN burst, a
    byzantine replica, and a bit-flipped checkpoint — the REAL control
    plane (manager probes, canary quarantine, drain, autoscaler
    replacement) contains all four with zero lost requests, and the
    byzantine replica is quarantined on its first wrong canary."""
    from skypilot_tpu.serve.sim import scenarios
    rep = scenarios.run_scenario('gray_failure_storm', seed=5)
    assert rep['requests']['lost'] == 0
    assert rep['replicas']['quarantined'] == 1
    fired = rep['faults_fired']
    assert fired.get('sim_gray:wedged_step') == 1
    assert fired.get('sim_gray:nan_logits') == 1
    assert fired.get('sim_gray:byzantine_response') == 1
    assert fired.get('kv_wire:kv_corruption') == 1
    assert rep['requests']['migrated'] > 0       # NaN evictions et al.
    # Determinism: same seed, byte-identical event log.
    rep2 = scenarios.run_scenario('gray_failure_storm', seed=5)
    assert rep['event_log_sha256'] == rep2['event_log_sha256']


def test_sim_wedged_replica_is_gray():
    """A wedged SimReplica accepts work (HTTP alive) but its readiness
    degrades — the exact gray contract the live watchdog produces."""
    from skypilot_tpu.serve.sim import replica as sim_replica
    curve = sim_replica.ServiceCurve.from_bench()
    rep = sim_replica.SimReplica('c', 'http://10.0.0.1:1', curve,
                                 lambda: 0.0)
    rep.wedged = True
    job = rep.enqueue(0.0, 2, 100.0, 50.0, 'latency')
    assert job is not None                        # still ACCEPTS work
    assert job.finish_t > 1e9                     # ... that never ends
    with pytest.raises(sim_replica.SimHTTPError):
        rep.handle('/readiness', None, None)
    # Canary surface: healthy vs byzantine answers differ, healthy
    # answers are fleet-identical.
    healthy = rep.handle('/generate', {'prompt': [11, 13],
                                       'max_new_tokens': 4}, None)
    rep2 = sim_replica.SimReplica('c2', 'http://10.0.0.2:1', curve,
                                  lambda: 0.0)
    assert rep2.handle('/generate', {'prompt': [11, 13],
                                     'max_new_tokens': 4},
                       None) == healthy
    rep2.byzantine = True
    assert rep2.handle('/generate', {'prompt': [11, 13],
                                     'max_new_tokens': 4},
                       None) != healthy
