"""Model-layer tests on the virtual 8-device CPU mesh."""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu.models import configs, llama
from skypilot_tpu.parallel import mesh as mesh_lib
from skypilot_tpu.train.trainer import TrainConfig, Trainer

# Compile-heavy (jit of full models): slow tier — the fast sweep is
# the orchestration layer (SURVEY §4 offline tier analog).
pytestmark = pytest.mark.slow


@pytest.fixture(scope='module')
def tiny_params():
    return llama.init_params(jax.random.PRNGKey(0), configs.TINY)


class TestForward:

    def test_shapes(self, tiny_params):
        logits, cache = llama.forward(
            tiny_params, jnp.ones((2, 16), jnp.int32), configs.TINY)
        assert logits.shape == (2, 16, configs.TINY.vocab_size)
        assert cache is None

    def test_causality(self, tiny_params):
        """Changing a future token must not affect earlier logits."""
        t1 = jnp.arange(16, dtype=jnp.int32)[None, :] % 250
        t2 = t1.at[0, 10].set(7)
        l1, _ = llama.forward(tiny_params, t1, configs.TINY)
        l2, _ = llama.forward(tiny_params, t2, configs.TINY)
        np.testing.assert_allclose(np.asarray(l1[0, :10]),
                                   np.asarray(l2[0, :10]), atol=1e-4)
        assert not np.allclose(np.asarray(l1[0, 10:]), np.asarray(l2[0, 10:]))

    def test_prefill_decode_matches_full_forward(self, tiny_params):
        cfg = configs.TINY
        toks = jnp.array([[3, 1, 4, 1, 5], [9, 2, 6, 5, 3]], jnp.int32)
        cache = llama.KVCache.create(cfg, batch=2, max_seq=32)
        logits_p, cache = llama.forward(tiny_params, toks, cfg, cache=cache)
        nxt = jnp.argmax(logits_p[:, -1:], -1).astype(jnp.int32)
        logits_d, cache = llama.forward(tiny_params, nxt, cfg, cache=cache)
        full = jnp.concatenate([toks, nxt], axis=1)
        logits_f, _ = llama.forward(tiny_params, full, cfg)
        np.testing.assert_allclose(np.asarray(logits_d[:, -1]),
                                   np.asarray(logits_f[:, -1]),
                                   rtol=3e-2, atol=3e-2)
        np.testing.assert_array_equal(np.asarray(cache.length), [6, 6])

    def test_ragged_cache_positions(self, tiny_params):
        """Continuous batching: sequences at genuinely different lengths
        share one batched decode step and each matches its own
        full-forward logits."""
        cfg = configs.TINY
        seq_a = [3, 1, 4, 1, 5]          # length 5
        seq_b = [9, 2, 6]                # length 3
        # Prefill each sequence alone, then splice the caches into one
        # batch with ragged lengths [5, 3].
        cache_a = llama.KVCache.create(cfg, batch=1, max_seq=32)
        _, cache_a = llama.forward(
            tiny_params, jnp.array([seq_a], jnp.int32), cfg, cache=cache_a)
        cache_b = llama.KVCache.create(cfg, batch=1, max_seq=32)
        _, cache_b = llama.forward(
            tiny_params, jnp.array([seq_b], jnp.int32), cfg, cache=cache_b)
        cache = llama.KVCache(
            k=jnp.concatenate([cache_a.k, cache_b.k], axis=1),
            v=jnp.concatenate([cache_a.v, cache_b.v], axis=1),
            length=jnp.concatenate([cache_a.length, cache_b.length]))
        np.testing.assert_array_equal(np.asarray(cache.length), [5, 3])

        step = jnp.array([[7], [8]], jnp.int32)
        logits, cache = llama.forward(tiny_params, step, cfg, cache=cache)
        ref_a, _ = llama.forward(
            tiny_params, jnp.array([seq_a + [7]], jnp.int32), cfg)
        ref_b, _ = llama.forward(
            tiny_params, jnp.array([seq_b + [8]], jnp.int32), cfg)
        np.testing.assert_allclose(np.asarray(logits[0, -1]),
                                   np.asarray(ref_a[0, -1]),
                                   rtol=3e-2, atol=3e-2)
        np.testing.assert_allclose(np.asarray(logits[1, -1]),
                                   np.asarray(ref_b[0, -1]),
                                   rtol=3e-2, atol=3e-2)
        np.testing.assert_array_equal(np.asarray(cache.length), [6, 4])

    def test_moe_forward(self):
        cfg = configs.TINY_MOE
        params = llama.init_params(jax.random.PRNGKey(1), cfg)
        logits, _ = llama.forward(params, jnp.ones((2, 8), jnp.int32), cfg)
        assert logits.shape == (2, 8, cfg.vocab_size)
        assert bool(jnp.all(jnp.isfinite(logits)))

    def test_num_params_estimate(self):
        params = llama.init_params(jax.random.PRNGKey(0), configs.TINY)
        actual = sum(x.size for x in jax.tree.leaves(params))
        est = configs.TINY.num_params
        assert abs(actual - est) / actual < 0.05


class TestTrainer:

    def _mesh_spec(self):
        return mesh_lib.MeshSpec(dp=2, fsdp=2, sp=1, tp=2)

    def test_loss_decreases(self):
        cfg = configs.TINY
        trainer = Trainer(cfg, mesh_spec=self._mesh_spec(),
                          train_config=TrainConfig(
                              learning_rate=1e-2, warmup_steps=1,
                              total_steps=50, attn_impl='xla'))
        state = trainer.init(jax.random.PRNGKey(0))
        rng = np.random.RandomState(0)
        data = rng.randint(0, 250, size=(8, 33))
        batch = {'inputs': jnp.asarray(data[:, :-1], jnp.int32),
                 'targets': jnp.asarray(data[:, 1:], jnp.int32)}
        losses = []
        for _ in range(5):
            state, metrics = trainer.step(state, batch)
            losses.append(float(metrics['loss']))
        assert losses[-1] < losses[0], losses

    def test_params_sharded_fsdp(self):
        trainer = Trainer(configs.TINY, mesh_spec=self._mesh_spec())
        state = trainer.init(jax.random.PRNGKey(0))
        # wq [L, d, h, hd]: embed dim sharded over fsdp, heads over tp
        spec = state.params['layers']['wq'].sharding.spec
        assert 'fsdp' in str(spec) and 'tp' in str(spec)
        # optimizer moments follow param shardings
        adam_state = state.opt_state[1][0]
        assert adam_state.mu['layers']['wq'].sharding == (
            state.params['layers']['wq'].sharding)

    def test_moe_train_step_ep(self):
        cfg = configs.TINY_MOE
        trainer = Trainer(cfg, mesh_spec=self._mesh_spec(),
                          train_config=TrainConfig(warmup_steps=1,
                                                   total_steps=4,
                                                   attn_impl='xla'))
        state = trainer.init(jax.random.PRNGKey(0))
        batch = {'inputs': jnp.ones((8, 16), jnp.int32),
                 'targets': jnp.ones((8, 16), jnp.int32)}
        state, metrics = trainer.step(state, batch)
        assert np.isfinite(float(metrics['loss']))
        # experts sharded over (fsdp, sp) -> at least fsdp present
        spec = str(state.params['layers']['moe_gate'].sharding.spec)
        assert 'fsdp' in spec

    def test_checkpoint_roundtrip(self, tmp_path):
        cfg = configs.TINY
        trainer = Trainer(cfg, mesh_spec=self._mesh_spec(),
                          train_config=TrainConfig(warmup_steps=1,
                                                   total_steps=4,
                                                   attn_impl='xla'))
        state = trainer.init(jax.random.PRNGKey(0))
        batch = {'inputs': jnp.ones((8, 16), jnp.int32),
                 'targets': jnp.ones((8, 16), jnp.int32)}
        state, _ = trainer.step(state, batch)
        path = str(tmp_path / 'ckpt')
        trainer.save_checkpoint(path, state)
        restored = trainer.restore_checkpoint(path)
        assert int(restored.step) == int(state.step)
        np.testing.assert_allclose(
            np.asarray(jax.device_get(restored.params['embed'])),
            np.asarray(jax.device_get(state.params['embed'])))


class TestGraftEntry:

    def test_dryrun_multichip_8(self):
        import __graft_entry__
        __graft_entry__.dryrun_multichip(8)


class TestGemmaFamily:
    """Gemma-style knobs: tied embeddings, GeGLU, +1 norms, MQA,
    sqrt(dim) embedding scale."""

    def test_forward_and_tied_logits(self):
        cfg = configs.TINY_GEMMA
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        assert 'unembed' not in params          # tied: single table
        toks = jnp.arange(12, dtype=jnp.int32)[None, :] % 250
        logits, _ = llama.forward(params, toks, cfg)
        assert logits.shape == (1, 12, cfg.vocab_size)
        assert bool(jnp.all(jnp.isfinite(logits)))

    def test_prefill_decode_matches_full(self):
        cfg = configs.TINY_GEMMA
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        toks = jnp.array([[3, 1, 4, 1, 5]], jnp.int32)
        cache = llama.KVCache.create(cfg, batch=1, max_seq=32)
        logits_p, cache = llama.forward(params, toks, cfg, cache=cache)
        nxt = jnp.argmax(logits_p[:, -1:], -1).astype(jnp.int32)
        logits_d, _ = llama.forward(params, nxt, cfg, cache=cache)
        full = jnp.concatenate([toks, nxt], axis=1)
        logits_f, _ = llama.forward(params, full, cfg)
        np.testing.assert_allclose(np.asarray(logits_d[:, -1]),
                                   np.asarray(logits_f[:, -1]),
                                   rtol=3e-2, atol=3e-2)

    def test_gemma_trains(self):
        trainer = Trainer(
            configs.TINY_GEMMA,
            mesh_spec=mesh_lib.MeshSpec(dp=2, fsdp=2, sp=1, tp=2),
            train_config=TrainConfig(learning_rate=1e-2, warmup_steps=1,
                                     total_steps=20, attn_impl='xla'))
        state = trainer.init(jax.random.PRNGKey(0))
        rng = np.random.RandomState(0)
        data = rng.randint(0, 250, size=(8, 17))
        batch = {'inputs': jnp.asarray(data[:, :-1], jnp.int32),
                 'targets': jnp.asarray(data[:, 1:], jnp.int32)}
        losses = []
        for _ in range(4):
            state, metrics = trainer.step(state, batch)
            losses.append(float(metrics['loss']))
        assert losses[-1] < losses[0], losses

    def test_num_params_tied(self):
        params = llama.init_params(jax.random.PRNGKey(0),
                                   configs.TINY_GEMMA)
        actual = sum(x.size for x in jax.tree.leaves(params))
        est = configs.TINY_GEMMA.num_params
        assert abs(actual - est) / actual < 0.05


class TestMeshFromEnv:
    """The launch env contract (SKYTPU_NUM_SLICES) drives the trainer's
    default mesh — the multi-slice wiring from driver to mesh."""

    def test_spec_from_env_defaults_single_slice(self, monkeypatch):
        monkeypatch.delenv('SKYTPU_NUM_SLICES', raising=False)
        spec = mesh_lib.spec_from_env(num_devices=8)
        assert spec.num_slices == 1 and spec.num_devices == 8

    def test_spec_from_env_two_slices(self, monkeypatch):
        monkeypatch.setenv('SKYTPU_NUM_SLICES', '2')
        spec = mesh_lib.spec_from_env(num_devices=8)
        assert spec.num_slices == 2
        assert spec.shape[0] == 2 and spec.num_devices == 8

    def test_initialize_distributed_noop_without_contract(self, monkeypatch):
        monkeypatch.delenv('SKYTPU_COORDINATOR_ADDRESS', raising=False)
        assert mesh_lib.initialize_distributed_from_env() is False

    def test_initialize_distributed_noop_single_host(self, monkeypatch):
        monkeypatch.setenv('SKYTPU_COORDINATOR_ADDRESS', '10.0.0.1:8476')
        monkeypatch.setenv('SKYTPU_NUM_NODES', '1')
        assert mesh_lib.initialize_distributed_from_env() is False
