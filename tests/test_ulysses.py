"""Ulysses (head-scatter) sequence parallelism: exact equivalence with
single-device attention on the virtual CPU mesh, GQA/MQA handling, and
the trainer integration (attn_impl='ulysses')."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu.ops.attention import reference_attention
from skypilot_tpu.ops.ulysses import ulysses_attention
from skypilot_tpu.parallel import mesh as mesh_lib

pytestmark = pytest.mark.slow

jax.config.update('jax_platforms', 'cpu')


def _mesh(sp):
    spec = mesh_lib.MeshSpec(dp=1, fsdp=8 // sp // 1, sp=sp, tp=1)
    return mesh_lib.make_mesh(spec, jax.devices()[:8])


def _rand(b, s, h, d, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return (jax.random.normal(ks[0], (b, s, h, d), jnp.float32),
            jax.random.normal(ks[1], (b, s, h, d), jnp.float32),
            jax.random.normal(ks[2], (b, s, h, d), jnp.float32))


@pytest.mark.parametrize('causal', [True, False])
def test_matches_reference(causal):
    b, s, h, d = 2, 64, 8, 16
    q, k, v = _rand(b, s, h, d)
    mesh = _mesh(sp=4)
    with mesh:
        out = ulysses_attention(q, k, v, mesh, causal=causal)
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_gqa_grouping_preserved():
    b, s, h, hkv, d = 2, 32, 8, 4, 16
    q, _, _ = _rand(b, s, h, d)
    ks = jax.random.split(jax.random.PRNGKey(9), 2)
    k = jax.random.normal(ks[0], (b, s, hkv, d), jnp.float32)
    v = jax.random.normal(ks[1], (b, s, hkv, d), jnp.float32)
    mesh = _mesh(sp=4)            # hkv % sp == 0: grouped form survives
    with mesh:
        out = ulysses_attention(q, k, v, mesh, causal=True)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_mqa_expands_kv():
    b, s, h, d = 2, 32, 8, 16
    q, _, _ = _rand(b, s, h, d)
    ks = jax.random.split(jax.random.PRNGKey(3), 2)
    k = jax.random.normal(ks[0], (b, s, 1, d), jnp.float32)
    v = jax.random.normal(ks[1], (b, s, 1, d), jnp.float32)
    mesh = _mesh(sp=4)            # hkv=1 < sp: expansion path
    with mesh:
        out = ulysses_attention(q, k, v, mesh, causal=True)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_rejects_indivisible_heads():
    b, s, h, d = 2, 32, 6, 16
    q, k, v = _rand(b, s, h, d)
    mesh = _mesh(sp=4)
    with pytest.raises(ValueError, match='n_heads'):
        with mesh:
            ulysses_attention(q, k, v, mesh, causal=True)


def test_trainer_attn_impl_ulysses():
    """Training with attn_impl='ulysses' on an sp mesh converges like
    the xla path (same loss after one step on identical data)."""
    from skypilot_tpu.models import configs
    from skypilot_tpu.train.trainer import TrainConfig, Trainer
    spec = mesh_lib.MeshSpec(dp=1, fsdp=2, sp=2, tp=2)
    mesh = mesh_lib.make_mesh(spec, jax.devices()[:8])
    losses = {}
    for impl in ('xla', 'ulysses'):
        tr = Trainer(configs.TINY, mesh=mesh,
                     train_config=TrainConfig(warmup_steps=1,
                                              total_steps=4,
                                              attn_impl=impl))
        state = tr.init(jax.random.PRNGKey(0))
        data = {'inputs': jnp.ones((4, 32), jnp.int32),
                'targets': jnp.ones((4, 32), jnp.int32)}
        _, metrics = tr.step(state, data)
        losses[impl] = float(metrics['loss'])
    assert abs(losses['xla'] - losses['ulysses']) < 1e-3, losses


def test_custom_scale_honored():
    b, s, h, d = 2, 32, 8, 16
    q, k, v = _rand(b, s, h, d, seed=4)
    mesh = _mesh(sp=4)
    with mesh:
        out = ulysses_attention(q, k, v, mesh, causal=True, scale=2.0)
    ref = reference_attention(q, k, v, causal=True, scale=2.0)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
