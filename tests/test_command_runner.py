"""Command-runner unit tests: incremental streaming, timeout, node env."""
import os
import sys
import time

from skypilot_tpu.utils import command_runner


def test_local_runner_streams_logs_incrementally(tmp_path):
    """Output must reach the log file while the command still runs
    (tail/follow depends on it), not after communicate() returns."""
    node = tmp_path / 'node'
    log = tmp_path / 'run.log'
    runner = command_runner.LocalProcessRunner('n0', str(node))

    import threading
    seen_early = {}

    def watch():
        deadline = time.time() + 5
        while time.time() < deadline:
            if log.exists() and 'first-line' in log.read_text():
                seen_early['t'] = time.time()
                return
            time.sleep(0.02)

    watcher = threading.Thread(target=watch)
    watcher.start()
    t0 = time.time()
    rc = runner.run('echo first-line; sleep 1.2; echo second-line',
                    log_path=str(log))
    elapsed = time.time() - t0
    watcher.join()
    assert rc == 0
    assert elapsed >= 1.0
    assert 'first-line' in log.read_text()
    assert 'second-line' in log.read_text()
    # The first line was visible well before the command finished.
    assert 't' in seen_early, 'first line never appeared while running'
    assert seen_early['t'] - t0 < 1.0


def test_local_runner_timeout_returns_124(tmp_path):
    runner = command_runner.LocalProcessRunner('n0', str(tmp_path / 'n'))
    rc, out, err = runner.run('echo before; sleep 30',
                              require_outputs=True, timeout=0.5)
    assert rc == 124
    assert 'before' in out
    assert '[timeout]' in err


def test_local_runner_home_isolation(tmp_path):
    runner = command_runner.LocalProcessRunner('n0', str(tmp_path / 'n'))
    rc, out, _ = runner.run('echo $HOME', require_outputs=True)
    assert rc == 0
    assert out.strip() == str(tmp_path / 'n')


def test_remote_python_contract(tmp_path):
    """Local nodes reuse this interpreter; SSH hosts must not see the
    client's venv path."""
    local = command_runner.LocalProcessRunner('n0', str(tmp_path / 'n'))
    assert local.remote_python == sys.executable
    ssh = command_runner.SSHCommandRunner('1.2.3.4', 'user',
                                          os.devnull)
    assert ssh.remote_python == 'python3'
