"""Command-runner unit tests: incremental streaming, timeout, node env."""
import os
import sys
import time

from skypilot_tpu.utils import command_runner


def test_local_runner_streams_logs_incrementally(tmp_path):
    """Output must reach the log file while the command still runs
    (tail/follow depends on it), not after communicate() returns."""
    node = tmp_path / 'node'
    log = tmp_path / 'run.log'
    runner = command_runner.LocalProcessRunner('n0', str(node))

    import threading
    seen_early = {}

    def watch():
        deadline = time.time() + 5
        while time.time() < deadline:
            if log.exists() and 'first-line' in log.read_text():
                seen_early['t'] = time.time()
                return
            time.sleep(0.02)

    watcher = threading.Thread(target=watch)
    watcher.start()
    t0 = time.time()
    rc = runner.run('echo first-line; sleep 1.2; echo second-line',
                    log_path=str(log))
    elapsed = time.time() - t0
    watcher.join()
    assert rc == 0
    assert elapsed >= 1.0
    assert 'first-line' in log.read_text()
    assert 'second-line' in log.read_text()
    # The first line was visible well before the command finished.
    assert 't' in seen_early, 'first line never appeared while running'
    assert seen_early['t'] - t0 < 1.0


def test_local_runner_timeout_returns_124(tmp_path):
    runner = command_runner.LocalProcessRunner('n0', str(tmp_path / 'n'))
    rc, out, err = runner.run('echo before; sleep 30',
                              require_outputs=True, timeout=0.5)
    assert rc == 124
    assert 'before' in out
    assert '[timeout]' in err


def test_local_runner_home_isolation(tmp_path):
    runner = command_runner.LocalProcessRunner('n0', str(tmp_path / 'n'))
    rc, out, _ = runner.run('echo $HOME', require_outputs=True)
    assert rc == 0
    assert out.strip() == str(tmp_path / 'n')


def test_remote_python_contract(tmp_path):
    """Local nodes reuse this interpreter; SSH hosts must not see the
    client's venv path."""
    local = command_runner.LocalProcessRunner('n0', str(tmp_path / 'n'))
    assert local.remote_python == sys.executable
    ssh = command_runner.SSHCommandRunner('1.2.3.4', 'user',
                                          os.devnull)
    assert ssh.remote_python == 'python3'


class TestRpcChannel:
    """Persistent JSON-RPC channel: one interpreter serves many ops."""

    def _runner(self, tmp_path):
        return command_runner.LocalProcessRunner('n0', str(tmp_path / 'n'))

    def test_many_requests_one_process(self, tmp_path, monkeypatch):
        monkeypatch.setenv('SKYTPU_AGENT_DIR', str(tmp_path / 'agent'))
        from skypilot_tpu.agent import channel as channel_lib
        runner = self._runner(tmp_path)
        spawns = []
        orig = command_runner.LocalProcessRunner.popen_interactive

        def counting(self, cmd):
            proc = orig(self, cmd)
            spawns.append(proc.pid)
            return proc

        monkeypatch.setattr(command_runner.LocalProcessRunner,
                            'popen_interactive', counting)
        ch = channel_lib.RpcChannel(runner, 'skypilot_tpu.agent.rpc')
        try:
            for _ in range(3):
                resp = ch.request({'op': 'agent_health'})
                assert resp['ok']
            assert len(spawns) == 1, spawns
        finally:
            ch.close()

    def test_channel_restarts_after_death(self, tmp_path, monkeypatch):
        monkeypatch.setenv('SKYTPU_AGENT_DIR', str(tmp_path / 'agent'))
        from skypilot_tpu.agent import channel as channel_lib
        ch = channel_lib.RpcChannel(self._runner(tmp_path),
                                    'skypilot_tpu.agent.rpc')
        try:
            assert ch.request({'op': 'agent_health'})['ok']
            ch._proc.kill()
            ch._proc.wait()
            assert ch.request({'op': 'agent_health'})['ok']
        finally:
            ch.close()

    def test_streaming_tail_refused_on_channel(self, tmp_path,
                                               monkeypatch):
        monkeypatch.setenv('SKYTPU_AGENT_DIR', str(tmp_path / 'agent'))
        from skypilot_tpu.agent import channel as channel_lib
        ch = channel_lib.RpcChannel(self._runner(tmp_path),
                                    'skypilot_tpu.agent.rpc')
        try:
            resp = ch.request({'op': 'tail', 'job_id': 1})
            assert not resp['ok'] and 'tail' in resp['error']
        finally:
            ch.close()

    def test_agent_request_uses_channel_then_fallback(self, tmp_path,
                                                      monkeypatch):
        """agent_request rides the channel; a transport that cannot
        serve falls back to the one-shot exec path transparently."""
        monkeypatch.setenv('SKYTPU_AGENT_DIR', str(tmp_path / 'agent'))
        from skypilot_tpu.agent import channel as channel_lib
        from skypilot_tpu.provision import provisioner
        channel_lib.close_all()
        runner = self._runner(tmp_path)
        resp = provisioner.agent_request(runner, {'op': 'agent_health'})
        assert 'agentd_alive' in resp
        # Break the interactive transport entirely: fallback still works.
        monkeypatch.setattr(
            command_runner.LocalProcessRunner, 'popen_interactive',
            lambda self, cmd: (_ for _ in ()).throw(NotImplementedError))
        channel_lib.close_all()
        resp = provisioner.agent_request(runner, {'op': 'agent_health'})
        assert 'agentd_alive' in resp
        channel_lib.close_all()

    def test_no_retry_after_send(self, tmp_path, monkeypatch):
        """A failure AFTER the request was written must surface, not
        re-send (double-submit hazard for queue_job/cancel)."""
        monkeypatch.setenv('SKYTPU_AGENT_DIR', str(tmp_path / 'agent'))
        from skypilot_tpu.agent import channel as channel_lib
        ch = channel_lib.RpcChannel(self._runner(tmp_path),
                                    'skypilot_tpu.agent.rpc')
        starts = []
        orig_start = channel_lib.RpcChannel._start

        def counting_start(self):
            starts.append(1)
            return orig_start(self)

        monkeypatch.setattr(channel_lib.RpcChannel, '_start',
                            counting_start)
        monkeypatch.setattr(
            channel_lib.RpcChannel, '_roundtrip',
            lambda self, req: (_ for _ in ()).throw(
                channel_lib.ChannelError('EOF mid-request', sent=True)))
        try:
            import pytest as _pytest
            with _pytest.raises(channel_lib.ChannelError) as ei:
                ch.request({'op': 'queue_job'})
            assert ei.value.sent
            assert len(starts) == 1, 'must not re-establish and re-send'
        finally:
            ch.close()

    def test_startup_failure_negative_cached(self, tmp_path,
                                             monkeypatch):
        """A head without --serve support costs failed spawns ONCE;
        later agent_requests skip straight to the one-shot exec."""
        monkeypatch.setenv('SKYTPU_AGENT_DIR', str(tmp_path / 'agent'))
        from skypilot_tpu.agent import channel as channel_lib
        from skypilot_tpu.provision import provisioner
        channel_lib.close_all()
        runner = self._runner(tmp_path)
        spawns = []
        orig = command_runner.LocalProcessRunner.popen_interactive

        def failing(self, cmd):
            spawns.append(1)
            # Simulate an old runtime: the process exits immediately
            # without the ready banner.
            return orig(self, 'true')

        monkeypatch.setattr(command_runner.LocalProcessRunner,
                            'popen_interactive', failing)
        for _ in range(3):
            resp = provisioner.agent_request(runner,
                                             {'op': 'agent_health'})
            assert 'agentd_alive' in resp
        # 2 spawn attempts for the first call (retry), then the key is
        # disabled — calls 2 and 3 never touch the channel.
        assert len(spawns) == 2, spawns
        channel_lib.close_all()
