"""Prefix-affinity KV routing + the horizontal LB tier (PR 18).

Units: the consistent-hash ring's ownership stability and bounded key
movement; the BoundedStore TTL+LRU contract every LB-side map rides;
the prefix-affinity policy's longest-digest-match routing, load
tie-breaking, session stickiness and proactive-migration trigger —
all on fake replicas through the ``configure_transport`` seam, no
sockets.

Live e2e (slow): a 3-replica / 2-LB tier serving a multi-turn replay
with one LB killed mid-run — zero lost turns, byte-identical
continuations against a direct single-replica reference, and ring
convergence on the survivor.
"""
import hashlib
import json
import threading
import time
import urllib.error
import urllib.request

import jax
import numpy as np
import pytest

from skypilot_tpu.serve import load_balancing_policies as lbp
from skypilot_tpu.serve.lb_ring import HashRing
from skypilot_tpu.utils import common_utils

jax.config.update('jax_platforms', 'cpu')


# ---------------------------------------------------------------------------
# Consistent-hash ring
# ---------------------------------------------------------------------------
def _members(n):
    return {f'lb-{i}': f'http://10.0.0.{i}:8000' for i in range(n)}


def test_ring_ownership_deterministic_and_balanced():
    """Two independently built rings over the same membership agree on
    every key (no RNG, no instance state), and ownership is roughly
    uniform — no member starves."""
    keys = [f'sess-{i}' for i in range(2000)]
    a, b = HashRing(), HashRing()
    a.set_members(_members(4))
    b.set_members(_members(4))
    owners = {}
    for k in keys:
        o = a.owner(k)
        assert o == b.owner(k)
        owners[o] = owners.get(o, 0) + 1
    assert set(owners) == set(_members(4))
    for name, n in owners.items():
        assert n > len(keys) * 0.10, (name, n)   # vnode smoothing
    name, url = a.owner_url('sess-0')
    assert name == a.owner('sess-0')
    assert url == _members(4)[name]


def test_ring_ownership_stable_across_rebuilds():
    """Rebuilding with IDENTICAL membership never moves a key — the
    stability contract session affinity depends on across controller
    syncs."""
    ring = HashRing()
    ring.set_members(_members(3))
    keys = [f'k{i}' for i in range(500)]
    before = {k: ring.owner(k) for k in keys}
    for _ in range(3):
        ring.set_members(_members(3))
    assert {k: ring.owner(k) for k in keys} == before


def test_ring_remove_moves_only_the_dead_members_keys():
    """Removing one LB remaps ONLY the keys it owned; every surviving
    owner keeps every key — an LB crash never shuffles the survivors'
    sessions."""
    ring = HashRing()
    ring.set_members(_members(4))
    keys = [f'sess-{i}' for i in range(2000)]
    before = {k: ring.owner(k) for k in keys}
    gone = 'lb-3'
    ring.set_members({n: u for n, u in _members(4).items()
                      if n != gone})
    for k in keys:
        after = ring.owner(k)
        if before[k] == gone:
            assert after != gone
        else:
            assert after == before[k], k


def test_ring_add_moves_bounded_fraction():
    """Adding a 5th LB moves only keys TO the new member — about 1/5
    of the space, never a reshuffle between existing members."""
    ring = HashRing()
    ring.set_members(_members(4))
    keys = [f'sess-{i}' for i in range(2000)]
    before = {k: ring.owner(k) for k in keys}
    grown = _members(5)
    ring.set_members(grown)
    moved = 0
    for k in keys:
        after = ring.owner(k)
        if after != before[k]:
            assert after == 'lb-4', k     # only toward the newcomer
            moved += 1
    assert 0 < moved < len(keys) * 0.40   # ~1/5 + vnode slack


def test_ring_empty_and_single():
    ring = HashRing()
    assert ring.owner('x') is None
    assert ring.owner_url('x') == (None, None)
    ring.set_members({'only': 'http://a'})
    assert ring.owner('anything') == 'only'
    assert ring.owner_url('anything') == ('only', 'http://a')


# ---------------------------------------------------------------------------
# BoundedStore (the GC122-sanctioned map)
# ---------------------------------------------------------------------------
def test_bounded_store_lru_cap_and_eviction_count():
    s = lbp.BoundedStore(3, name='t')
    for i in range(5):
        s.put(i, i * 10)
    assert len(s) == 3 and s.evictions == 2
    assert 0 not in s and 1 not in s
    # get() refreshes recency: 2 survives the next insert, 3 does not.
    assert s.get(2) == 20
    s.put(9, 90)
    assert 2 in s and 3 not in s


def test_bounded_store_ttl_expiry_on_virtual_clock():
    now = [0.0]
    s = lbp.BoundedStore(8, ttl_s=10.0, monotonic=lambda: now[0],
                         name='t')
    s.put('a', 1)
    now[0] = 9.0
    assert s.get('a') == 1
    now[0] = 10.5
    assert s.get('a') is None and 'a' not in s


def test_bounded_store_incr_floor_and_pop():
    s = lbp.BoundedStore(8, name='t')
    assert s.incr('k', 1) == 1
    assert s.incr('k', -5, floor=0) == 0
    s.put('x', 7)
    assert s.pop('x') == 7 and s.pop('x', 'gone') == 'gone'


# ---------------------------------------------------------------------------
# Prefix-affinity policy on fake replicas (configure_transport seam)
# ---------------------------------------------------------------------------
PAGE = 64


def _hash_chain(tokens, covered):
    """The engine's digest recipe: sha1 over int32 bytes of the
    page-grid prefix."""
    return hashlib.sha1(np.asarray(tokens[:covered],
                                   np.int32).tobytes()).hexdigest()


def _payload(queue_tokens, tokens=None, pages=0, page=PAGE):
    entries = []
    if tokens is not None and pages > 0:
        entries = [{'hash': _hash_chain(tokens, k * page),
                    'len': k * page, 'hits': 1}
                   for k in range(1, pages + 1)]
    return {'queue_tokens_total': queue_tokens,
            'prefix_digest': {'page': page, 'entries': entries}}


def _mk_policy(payloads, now):
    pol = lbp.make_policy('prefix_affinity')
    pol.configure_transport(
        fetch_json=lambda u: payloads[u.split('/metrics')[0]],
        monotonic=lambda: now[0])
    pol.set_ready_replicas(sorted(payloads))
    return pol


def test_page_grid_hashes_match_engine_recipe():
    """The LB recomputes the engine's exact sha1 — any drift in either
    recipe silently zeroes the hit rate, so parity is pinned here."""
    tokens = [(i * 31 + 7) % 50021 for i in range(300)]
    pol = lbp.make_policy('prefix_affinity')
    grid = pol._page_grid_hashes(tokens, PAGE)
    full = (len(tokens) - 1) // PAGE
    assert len(grid) == full > 0
    for k in range(1, full + 1):
        assert grid[_hash_chain(tokens, k * PAGE)] == k * PAGE


def test_longest_digest_match_wins():
    tokens = list(range(1, 6 * PAGE + 2))          # 6 full pages
    payloads = {
        'http://a': _payload(0, tokens, pages=2),  # shorter match
        'http://b': _payload(900, tokens, pages=4),  # longest, busier
        'http://c': _payload(0),                   # no digest
    }
    outcomes = []
    pol = _mk_policy(payloads, [0.0])
    pol.configure_affinity_observer(lambda o, r: outcomes.append((o, r)))
    choice = pol.select_replica(
        context={'tokens': tokens, 'request_key': 's1'})
    assert choice == 'http://b'                    # match beats load
    assert outcomes == [('hit', 0)]


def test_digest_tie_breaks_on_queue_depth():
    tokens = list(range(1, 3 * PAGE + 2))
    payloads = {
        'http://a': _payload(800, tokens, pages=2),
        'http://b': _payload(100, tokens, pages=2),  # same match, idle
    }
    pol = _mk_policy(payloads, [0.0])
    assert pol.select_replica(
        context={'tokens': tokens}) == 'http://b'


def test_no_match_routes_by_load_and_counts_miss():
    tokens = list(range(1, 3 * PAGE + 2))
    other = list(range(9000, 9000 + 3 * PAGE + 2))
    payloads = {
        'http://a': _payload(700, other, pages=2),  # digest, no match
        'http://b': _payload(50),
    }
    outcomes = []
    pol = _mk_policy(payloads, [0.0])
    pol.configure_affinity_observer(lambda o, r: outcomes.append((o, r)))
    assert pol.select_replica(context={'tokens': tokens}) == 'http://b'
    assert outcomes == [('miss', 0)]


def test_session_stickiness_survives_digest_cold_start():
    """A key that routed once keeps routing to the same replica even
    before any digest mentions its prefix (the session's replica holds
    its whole prefix by construction) — and falls back cleanly when
    that replica leaves the ready set."""
    tokens = list(range(1, 2 * PAGE + 2))
    payloads = {'http://a': _payload(500), 'http://b': _payload(0)}
    pol = _mk_policy(payloads, [0.0])
    first = pol.select_replica(
        context={'tokens': tokens, 'request_key': 'sess-9'})
    assert first == 'http://b'                     # load winner, miss
    # Load flips — but the session stays pinned to its replica.
    payloads['http://b']['queue_tokens_total'] = 5000
    payloads['http://a']['queue_tokens_total'] = 0
    now = [pol.probe_ttl_s + 1.0]
    pol.configure_transport(monotonic=lambda: now[0])
    assert pol.select_replica(
        context={'tokens': tokens, 'request_key': 'sess-9'}) \
        == 'http://b'
    # The pinned replica drains away: the key re-routes by load.
    pol.set_ready_replicas(['http://a'])
    assert pol.select_replica(
        context={'tokens': tokens, 'request_key': 'sess-9'}) \
        == 'http://a'


def test_overload_gap_triggers_proactive_migration():
    """Affinity winner overloaded past the threshold: the request goes
    to the LOAD winner and the migration executor ships the chain from
    the affinity replica — outcome 'migrated', zero recompute (the
    prefix arrives warm)."""
    tokens = list(range(1, 4 * PAGE + 2))
    payloads = {
        'http://hot': _payload(5000, tokens, pages=4),
        'http://idle': _payload(0),
    }
    outcomes, ships = [], []
    pol = _mk_policy(payloads, [0.0])
    pol.migrate_threshold_tokens = 1600
    pol.configure_affinity_observer(lambda o, r: outcomes.append((o, r)))
    pol.configure_migration(
        lambda src, dst, h, n: ships.append((src, dst, h, n)) or True)
    choice = pol.select_replica(context={'tokens': tokens,
                                         'request_key': 'sess-m'})
    assert choice == 'http://idle'
    assert outcomes == [('migrated', 0)]
    assert ships == [('http://hot', 'http://idle',
                      _hash_chain(tokens, 4 * PAGE), 4 * PAGE)]


def test_overload_without_executor_counts_recompute_tokens():
    """Same overload, but no migration executor installed: the policy
    still routes away (latency beats locality past the threshold) and
    reports the prefix tokens the chosen replica must recompute."""
    tokens = list(range(1, 4 * PAGE + 2))
    payloads = {
        'http://hot': _payload(5000, tokens, pages=4),
        'http://idle': _payload(0),
    }
    outcomes = []
    pol = _mk_policy(payloads, [0.0])
    pol.migrate_threshold_tokens = 1600
    pol.configure_affinity_observer(lambda o, r: outcomes.append((o, r)))
    assert pol.select_replica(
        context={'tokens': tokens}) == 'http://idle'
    assert outcomes == [('migrated', 4 * PAGE)]


def test_gap_under_threshold_keeps_affinity():
    tokens = list(range(1, 4 * PAGE + 2))
    payloads = {
        'http://warm': _payload(1000, tokens, pages=4),
        'http://idle': _payload(0),
    }
    pol = _mk_policy(payloads, [0.0])
    pol.migrate_threshold_tokens = 1600          # gap 1000 < threshold
    assert pol.select_replica(
        context={'tokens': tokens}) == 'http://warm'


def test_probe_ttl_knob_and_seeded_jitter(monkeypatch):
    """SKYTPU_LB_PROBE_TTL_S replaces the hardcoded 1 s TTL, and the
    per-LB-identity jitter is deterministic and bounded — two LBs with
    the same id agree, different ids (usually) disagree, the empty id
    keeps the exact base TTL (existing sims unchanged)."""
    monkeypatch.setenv('SKYTPU_LB_PROBE_TTL_S', '4.0')
    a = lbp.make_policy('queue_depth')
    assert a._base_probe_ttl_s == 4.0
    assert a.probe_ttl_s == 4.0                  # no identity: no jitter
    a.set_probe_identity('lb-a')
    b = lbp.make_policy('queue_depth')
    b.set_probe_identity('lb-a')
    assert a.probe_ttl_s == b.probe_ttl_s        # deterministic
    assert abs(a.probe_ttl_s - 4.0) > 1e-9       # jittered off base
    assert 4.0 * 0.8 <= a.probe_ttl_s <= 4.0 * 1.2
    c = lbp.make_policy('queue_depth')
    c.set_probe_identity('lb-c')
    assert c.probe_ttl_s != a.probe_ttl_s


# ---------------------------------------------------------------------------
# Live e2e: 3 replicas, 2 LBs, one killed mid-replay
# ---------------------------------------------------------------------------
class _PeerController:
    """Answers the LB sync POST like the real controller: a fixed
    ready-replica list plus the lb_peers registry built from the
    syncing LBs' own (lb_id, lb_url) announcements."""

    def __init__(self, replica_urls):
        import http.server
        self.replica_urls = list(replica_urls)
        self.peers = {}
        self.lock = threading.Lock()
        outer = self

        class H(http.server.BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_POST(self):  # noqa: N802
                n = int(self.headers.get('Content-Length', 0))
                req = json.loads(self.rfile.read(n) or b'{}')
                with outer.lock:
                    if req.get('lb_id'):
                        outer.peers[req['lb_id']] = req.get('lb_url')
                    body = json.dumps({
                        'ready_replica_urls': outer.replica_urls,
                        'lb_peers': dict(outer.peers)}).encode()
                self.send_response(200)
                self.send_header('Content-Length', str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        import http.server as hs
        self.port = common_utils.find_free_port(21100)
        self.httpd = hs.ThreadingHTTPServer(('127.0.0.1', self.port), H)
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()

    @property
    def url(self):
        return f'http://127.0.0.1:{self.port}'

    def forget(self, lb_id):
        with self.lock:
            self.peers.pop(lb_id, None)

    def stop(self):
        self.httpd.shutdown()


def _generate(base, prompt, n, key, timeout=180):
    """Non-streaming /generate through ``base``; returns the token
    list. Retries refusals briefly — 'zero lost' means every turn
    completes, not that no attempt ever 503s."""
    body = json.dumps({'prompt': prompt,
                       'max_new_tokens': n}).encode()
    deadline = time.time() + timeout
    last = None
    while time.time() < deadline:
        req = urllib.request.Request(
            base + '/generate', body,
            {'Content-Type': 'application/json', 'X-Request-ID': key})
        try:
            with urllib.request.urlopen(req, timeout=timeout) as r:
                return list(json.loads(r.read())['tokens'])
        except (urllib.error.URLError, ConnectionError, OSError) as e:
            last = e
            time.sleep(0.5)
    raise AssertionError(f'turn lost: {last}')


@pytest.mark.slow
def test_live_multi_turn_replay_survives_lb_kill(tmp_path, monkeypatch):
    """e2e: two sessions replay 3 turns each over 3 live replicas
    behind a 2-LB prefix-affinity tier; LB-A is killed after turn 1.
    Every remaining turn completes through LB-B (zero lost), every
    turn's tokens are byte-identical to a direct single-replica
    reference (greedy decode — affinity must never change bytes), and
    the survivor's ring converges to itself once the controller drops
    the dead peer."""
    monkeypatch.setenv('SKYTPU_SERVE_DIR', str(tmp_path / 'serve'))
    monkeypatch.setenv('SKYTPU_LB_SYNC', '3600')   # manual syncs only
    from skypilot_tpu.serve.load_balancer import SkyServeLoadBalancer
    from skypilot_tpu.serve.server import ModelServer
    servers = []
    for i in range(3):
        port = common_utils.find_free_port(21200 + i * 17)
        servers.append(ModelServer('tiny', max_batch=2, max_seq=256,
                                   port=port, step_watchdog_s=0))
    lbs = {}
    ctrl = None
    try:
        for s in servers:
            s.start(block=False)
        deadline = time.time() + 240
        while time.time() < deadline and not all(
                s._ready.is_set() for s in servers):
            time.sleep(0.2)
        assert all(s._ready.is_set() for s in servers)
        replica_urls = [f'http://127.0.0.1:{s.port}' for s in servers]
        # Reference: the whole conversation directly against ONE
        # replica — greedy on identical weights, so every replica
        # (and any routing) must reproduce these bytes exactly.
        sessions = {
            's-alpha': [11, 13, 17, 19, 23, 29, 31, 37],
            's-beta': [41, 43, 47, 53, 59, 61, 67, 71],
        }
        turns = 3
        per_turn = 6
        reference = {}
        for key, seed_prompt in sessions.items():
            prompt = list(seed_prompt)
            ref_turns = []
            for t in range(turns):
                toks = _generate(replica_urls[0], prompt, per_turn,
                                 key=f'ref-{key}-{t}')
                assert len(toks) == per_turn
                ref_turns.append(toks)
                prompt = prompt + toks + [101 + t, 103 + t]
            reference[key] = ref_turns
        ctrl = _PeerController(replica_urls)
        for name in ('lb-a', 'lb-b'):
            port = common_utils.find_free_port(21300
                                               + len(lbs) * 13)
            lb = SkyServeLoadBalancer(
                controller_url=ctrl.url, port=port,
                policy_name='prefix_affinity', lb_id=name,
                advertise_url=f'http://127.0.0.1:{port}')
            lb.start()
            lb._sync_once()
            lbs[name] = lb
        # Second sync round: lb-a registered before lb-b existed.
        for lb in lbs.values():
            lb._sync_once()
        for lb in lbs.values():
            assert set(lb._ring.members) == {'lb-a', 'lb-b'}
        lb_a_url = f'http://127.0.0.1:{lbs["lb-a"].port}'
        lb_b_url = f'http://127.0.0.1:{lbs["lb-b"].port}'
        # Replay: turn 1 through LB-A; then the kill; turns 2..n
        # through the survivor, same session keys.
        # Request keys are per-TURN (idempotency: a replayed key
        # returns the recorded answer); cross-turn affinity rides the
        # prefix digest, not the key.
        prompts = {k: list(p) for k, p in sessions.items()}
        for key in sessions:
            toks = _generate(lb_a_url, prompts[key], per_turn,
                             key=f'{key}-t0')
            assert toks == reference[key][0], key
            prompts[key] = prompts[key] + toks + [101, 103]
        lbs['lb-a'].stop()
        ctrl.forget('lb-a')
        lbs['lb-b']._sync_once()
        assert set(lbs['lb-b']._ring.members) == {'lb-b'}
        for t in range(1, turns):
            for key in sessions:
                toks = _generate(lb_b_url, prompts[key], per_turn,
                                 key=f'{key}-t{t}')
                assert toks == reference[key][t], (key, t)
                prompts[key] = (prompts[key] + toks
                                + [101 + t, 103 + t])
    finally:
        if ctrl is not None:
            ctrl.stop()
        for lb in lbs.values():
            try:
                lb.stop()
            except Exception:   # already stopped mid-test
                pass
        for s in servers:
            s.stop()
