"""Every shipped example must parse, validate, and (where hermetic)
actually run — the reference ships ~50 example YAMLs exercised by smoke
tests (SURVEY §4); ours are exercised in CI via dryrun + the local
provider."""
import os
import subprocess
import sys

import pytest

import skypilot_tpu as sky
from skypilot_tpu.task import Task

EXAMPLES = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    'examples')


def _example(name: str) -> str:
    return os.path.join(EXAMPLES, name)


ALL_YAMLS = sorted(f for f in os.listdir(EXAMPLES) if f.endswith('.yaml'))


class TestParseAll:

    def test_inventory(self):
        """The documented example set ships."""
        expected = {'minimal.yaml', 'tpu_hello.yaml', 'tpuvm_mnist.yaml',
                    'train_llama_job.yaml', 'serve_llama.yaml',
                    'k8s_hello.yaml', 'multislice_train.yaml',
                    'finetune_lora.yaml', 'serve_mixtral.yaml',
                    'serve_qwen2.yaml', 'train_gemma.yaml'}
        assert expected.issubset(set(ALL_YAMLS)), ALL_YAMLS

    @pytest.mark.parametrize('yaml_name', ALL_YAMLS)
    def test_parses_and_validates(self, yaml_name):
        task = Task.from_yaml(_example(yaml_name))
        assert task.name
        assert task.run

    def test_tpu_examples_resolve_topology(self):
        for name in ('tpu_hello.yaml', 'tpuvm_mnist.yaml',
                     'multislice_train.yaml'):
            task = Task.from_yaml(_example(name))
            res = list(task.resources)[0]
            assert res.accelerators, name

    @pytest.mark.parametrize('yaml_name', ['serve_llama.yaml',
                                           'serve_mixtral.yaml',
                                           'serve_qwen2.yaml'])
    def test_serve_example_has_service(self, yaml_name):
        from skypilot_tpu.serve.service_spec import SkyServiceSpec
        task = Task.from_yaml(_example(yaml_name))
        assert task.service is not None
        spec = SkyServiceSpec.from_yaml_config(task.service)
        assert spec.readiness_path == '/readiness'

    def test_multislice_is_two_slices(self):
        task = Task.from_yaml(_example('multislice_train.yaml'))
        assert task.num_nodes == 2


@pytest.fixture()
def fast_agent(monkeypatch):
    monkeypatch.setenv('SKYTPU_AGENT_TICK', '0.1')
    monkeypatch.setenv('SKYTPU_AGENT_READY_TIMEOUT', '30')


@pytest.mark.slow
class TestRunnable:
    """Hermetic execution: dryrun through the optimizer for cloud
    examples; a real local-provider launch for minimal.yaml; the mnist
    script end-to-end on CPU."""

    def test_tpu_examples_dryrun(self, tmp_state_dir):
        from skypilot_tpu import execution
        for i, name in enumerate(('tpu_hello.yaml', 'tpuvm_mnist.yaml',
                                  'multislice_train.yaml')):
            task = Task.from_yaml(_example(name))
            result = execution.launch(task, cluster_name=f'dry-ex{i}',
                                      dryrun=True)
            assert result is not None, name

    def test_minimal_launches_locally(self, tmp_state_dir, fast_agent):
        import time

        from skypilot_tpu import core, execution
        task = Task.from_yaml(_example('minimal.yaml'))
        task.set_resources(sky.Resources(cloud='local', cpus='1+'))
        job_id, handle = execution.launch(task, cluster_name='ex-min')
        try:
            deadline = time.time() + 60
            status = None
            while time.time() < deadline:
                status = core.job_status('ex-min', job_id)
                if status in ('SUCCEEDED', 'FAILED', 'FAILED_DRIVER'):
                    break
                time.sleep(0.2)
            assert status == 'SUCCEEDED', status
            from skypilot_tpu.backend import tpu_backend
            logs = tpu_backend.TpuVmBackend().get_job_logs(handle, job_id)
            assert 'hello from' in logs
        finally:
            core.down('ex-min')

    def test_mnist_script_runs(self):
        env = dict(os.environ, JAX_PLATFORMS='cpu')
        env.pop('PALLAS_AXON_POOL_IPS', None)
        r = subprocess.run(
            [sys.executable, 'train_mnist.py', '--epochs', '1',
             '--batch', '64'],
            cwd=os.path.join(EXAMPLES, 'mnist'), env=env,
            capture_output=True, text=True, timeout=300, check=False)
        assert r.returncode == 0, r.stderr[-2000:]
        assert 'final accuracy' in r.stdout
