"""Fleet telemetry aggregation: merge exactness, burn windows, traces.

Pins the contracts ``telemetry/fleet.py`` documents:

- counters sum EXACTLY across N replica registries, and keep summing
  monotonically through a replica restart (high-water-mark reset
  detection),
- histograms with identical bucket bounds merge exactly (the merged
  exposition is byte-identical to a pooled-sample histogram), and
  quantiles from merged buckets land within one bucket width of
  pooled-sample truth,
- SLO burn rates are multi-window: a late burst flips the 5-minute
  burn above 1 while the 1-hour window stays below,
- trace assembly applies per-source clock-skew offsets so a
  multi-process odyssey renders in causal order,
- everything is bounded: trace store evicts oldest, per-trace legs
  cap, per-source series cap drops (and counts) the excess.
"""
import json

import pytest

from skypilot_tpu.telemetry import fleet as fleet_lib
from skypilot_tpu.telemetry import registry as registry_lib
from skypilot_tpu.telemetry import tracing


def _clock(start=0.0):
    state = {'now': float(start)}

    def now():
        return state['now']

    now.state = state
    return now


def _agg(clock=None, **kwargs):
    return fleet_lib.FleetAggregator(clock=clock or _clock(), **kwargs)


def _wire_counter(name, value, **labels):
    return {name: {'kind': 'counter', 'help': 'h',
                   'series': [{'labels': labels, 'value': value}]}}


def _prom_family(text, name):
    return sorted(line for line in text.splitlines()
                  if line.startswith(name) and not line.startswith('#'))


# ----------------------------------------------------------- counters
def test_counter_exact_sum_across_sources():
    agg = _agg()
    values = [3.0, 11.0, 0.0, 25.0, 7.0]
    for i, v in enumerate(values):
        agg.ingest(f'replica-{i}', {
            'clock': {'wall': 0.0},
            'registry': _wire_counter(fleet_lib.ADMIT_METRIC, v,
                                      tier='latency')})
    merged = agg.render_json()[fleet_lib.ADMIT_METRIC]['series']
    assert len(merged) == 1
    assert merged[0]['labels'] == {'tier': 'latency'}
    assert merged[0]['value'] == sum(values)     # exact, not approximate
    assert agg.source_count() == len(values)


def test_counter_monotonic_across_restart():
    """A rebooted replica's counter restarting at 0 must ADD its
    pre-reboot total as a base — the fleet sum never decreases."""
    agg = _agg()

    def total():
        return agg.render_json()[fleet_lib.SHED_METRIC][
            'series'][0]['value']

    seen = []
    for value in (10.0, 100.0, 5.0, 6.0):    # 100 -> 5 is the restart
        agg.ingest('r0', {
            'clock': {'wall': 0.0},
            'registry': _wire_counter(fleet_lib.SHED_METRIC, value,
                                      tier='latency',
                                      reason='queue_wait')})
        seen.append(total())
    assert seen == [10.0, 100.0, 105.0, 106.0]
    assert seen == sorted(seen)              # monotone through restart


def test_histogram_restart_high_water_mark():
    reg = registry_lib.MetricsRegistry()
    h = reg.histogram(fleet_lib.TTFT_METRIC, 'ttft', tier='latency')
    for _ in range(10):
        h.observe(50.0)
    agg = _agg()
    agg.ingest('r0', {'clock': {'wall': 0.0},
                      'registry': reg.export_wire()})
    # The replica restarts: a FRESH registry with fewer observations.
    reg2 = registry_lib.MetricsRegistry()
    h2 = reg2.histogram(fleet_lib.TTFT_METRIC, 'ttft', tier='latency')
    for _ in range(3):
        h2.observe(50.0)
    agg.ingest('r0', {'clock': {'wall': 0.0},
                      'registry': reg2.export_wire()})
    series = agg.render_json()[fleet_lib.TTFT_METRIC]['series'][0]
    assert series['count'] == 13             # 10 pre-reboot + 3 after
    assert series['sum'] == pytest.approx(13 * 50.0)


# --------------------------------------------------------- histograms
def test_histogram_merge_exact_vs_pooled():
    """Merged-across-replicas exposition must be byte-identical to one
    histogram fed ALL the pooled samples — elementwise-exact merge."""
    samples = {
        'a': [0.5, 3.0, 40.0, 900.0, 12000.0],
        'b': [2.0, 2.0, 75.0, 75.0, 450.0, 70000.0],
        'c': [9.0, 9.0, 9.0, 9999.0],
    }
    agg = _agg()
    for source, vals in samples.items():
        reg = registry_lib.MetricsRegistry()
        h = reg.histogram(fleet_lib.TTFT_METRIC, 'ttft', tier='lat')
        for v in vals:
            h.observe(v)
        agg.ingest(source, {'clock': {'wall': 0.0},
                            'registry': reg.export_wire()})
    pooled_reg = registry_lib.MetricsRegistry()
    pooled = pooled_reg.histogram(fleet_lib.TTFT_METRIC, 'ttft',
                                  tier='lat')
    for vals in samples.values():
        for v in vals:
            pooled.observe(v)
    assert (_prom_family(agg.render_prometheus(), fleet_lib.TTFT_METRIC)
            == _prom_family(pooled_reg.render_prometheus(),
                            fleet_lib.TTFT_METRIC))


def test_bucket_quantile_within_one_bucket_width():
    samples = [1.5, 4.0, 8.0, 30.0, 30.0, 60.0, 120.0, 300.0, 800.0,
               2000.0, 2000.0, 7000.0]
    reg = registry_lib.MetricsRegistry()
    h = reg.histogram('m', 'h')
    for v in samples:
        h.observe(v)
    snap = h.snapshot()
    buckets = list(h.buckets)
    for q in (0.5, 0.9, 0.99):
        est = fleet_lib.bucket_quantile(buckets, snap['cumulative'], q)
        truth = sorted(samples)[min(len(samples) - 1,
                                    int(q * len(samples)))]
        # Width of the bucket the true quantile lands in — the best a
        # fixed-bucket store can promise.
        prev = 0.0
        for upper in buckets:
            if truth <= upper:
                break
            prev = upper
        assert abs(est - truth) <= (upper - prev)
    assert fleet_lib.bucket_quantile(buckets, [], 0.5) == 0.0
    assert fleet_lib.bucket_quantile([], [], 0.9) == 0.0


def test_histogram_bucket_layout_mismatch_skipped_not_crashed():
    agg = _agg()
    reg = registry_lib.MetricsRegistry()
    reg.histogram('m', 'h', buckets=(1, 2, 4)).observe(1.5)
    agg.ingest('r0', {'clock': {'wall': 0.0},
                      'registry': reg.export_wire()})
    other = registry_lib.MetricsRegistry()
    other.histogram('m', 'h', buckets=(1, 2, 4, 8)).observe(1.5)
    agg.ingest('r0', {'clock': {'wall': 0.0},
                      'registry': other.export_wire()})
    skipped = agg.render_json()['skytpu_fleet_merge_skipped_total'][
        'series'][0]['value']
    assert skipped >= 1


# ---------------------------------------------------------------- SLO
def _observe_tier(reg, ttft_ms, n):
    h = reg.histogram(fleet_lib.TTFT_METRIC, 'ttft', tier='latency')
    for _ in range(n):
        h.observe(ttft_ms)
    reg.counter(fleet_lib.ADMIT_METRIC, 'admitted',
                tier='latency').inc(n)


def test_burn_rate_multi_window_burst():
    """A burst confined to the final five minutes of an hour must page
    (5m burn >> 1) without tripping the ticket window (1h burn < 1)."""
    clock = _clock()
    slo = fleet_lib.TierSLO(tier='latency', ttft_ms=100.0, target=0.9)
    agg = _agg(clock=clock, slos=[slo])
    reg = registry_lib.MetricsRegistry()
    t = 0.0
    while t <= 3300.0:                      # 55 healthy minutes
        clock.state['now'] = t
        _observe_tier(reg, 10.0, 10)
        agg.ingest('r0', {'clock': {'wall': t},
                          'registry': reg.export_wire()})
        t += 60.0
    status = agg.slo_status()['latency']
    assert status['burn_5m'] == 0.0
    assert status['attainment'] == 1.0
    while t <= 3600.0:                      # 5-minute latency burst
        clock.state['now'] = t
        _observe_tier(reg, 10000.0, 10)
        agg.ingest('r0', {'clock': {'wall': t},
                          'registry': reg.export_wire()})
        t += 60.0
    status = agg.slo_status()['latency']
    assert status['burn_5m'] > 1.0          # page
    assert status['burn_1h'] < 1.0          # no ticket
    assert status['attainment'] < slo.target
    prom = agg.render_prometheus()
    assert 'skytpu_slo_burn_rate{tier="latency",window="5m"}' in prom
    assert 'skytpu_slo_burn_rate{tier="latency",window="1h"}' in prom
    assert 'skytpu_slo_attainment{tier="latency"}' in prom


def test_shed_rate_objective_burns():
    clock = _clock()
    slo = fleet_lib.TierSLO(tier='latency', shed_rate=0.05, target=0.99)
    agg = _agg(clock=clock, slos=[slo])
    reg = registry_lib.MetricsRegistry()
    reg.counter(fleet_lib.ADMIT_METRIC, 'a', tier='latency').inc(50)
    reg.counter(fleet_lib.SHED_METRIC, 's', tier='latency').inc(50)
    agg.ingest('r0', {'clock': {'wall': 0.0},
                      'registry': reg.export_wire()})
    clock.state['now'] = 10.0
    reg.counter(fleet_lib.ADMIT_METRIC, 'a', tier='latency').inc(50)
    reg.counter(fleet_lib.SHED_METRIC, 's', tier='latency').inc(50)
    agg.ingest('r0', {'clock': {'wall': 10.0},
                      'registry': reg.export_wire()})
    # 50% shed against a 5% objective: burn = 0.5 / 0.05 = 10.
    assert agg.slo_status()['latency']['burn_5m'] == pytest.approx(10.0)


def test_set_slos_replaces_objectives():
    agg = _agg(clock=_clock(),
               slos=[fleet_lib.TierSLO(tier='latency', ttft_ms=100.0),
                     fleet_lib.TierSLO(tier='throughput',
                                       ttft_ms=5000.0)])
    reg = registry_lib.MetricsRegistry()
    _observe_tier(reg, 10.0, 5)
    agg.ingest('r0', {'clock': {'wall': 0.0},
                      'registry': reg.export_wire()})
    assert set(agg.slo_status()) == {'latency', 'throughput'}
    agg.set_slos([fleet_lib.TierSLO(tier='latency', ttft_ms=100.0)])
    agg.ingest('r0', {'clock': {'wall': 0.0},
                      'registry': reg.export_wire()})
    assert set(agg.slo_status()) == {'latency'}


def test_slos_from_config_sorted_and_typed():
    slos = fleet_lib.slos_from_config({
        'throughput': {'ttft_ms': 5000, 'target': 0.95},
        'latency': {'ttft_ms': 200, 'tpot_ms': 20,
                    'shed_rate': 0.01}})
    assert [s.tier for s in slos] == ['latency', 'throughput']
    assert slos[0].tpot_ms == 20
    assert slos[0].target == 0.99            # default
    assert slos[1].error_budget == pytest.approx(0.05)
    assert fleet_lib.slos_from_config(None) == []


# ------------------------------------------------------------- traces
def _leg(trace_id, request_id, submitted_at, spans):
    return {'trace_id': trace_id, 'request_id': request_id,
            'submitted_at': submitted_at, 'done': True, 'meta': {},
            'spans': [{'name': n, 'start_ms': s, 'dur_ms': d}
                      for n, s, d in spans]}


def test_trace_assembly_applies_skew_for_causal_order():
    """The replica's clock runs 500 s behind the LB's: raw wall stamps
    would render decode BEFORE the dispatch that caused it. The
    per-source skew recorded at scrape time must restore causal
    order."""
    clock = _clock(1000.0)
    agg = _agg(clock=clock)
    tid = 'ab' * 16
    # LB clock == controller clock (skew 0); its dispatch span starts
    # at wall 1000.
    agg.ingest('lb-0', {
        'clock': {'wall': 1000.0},
        'traces': [_leg(tid, 1, 1000.0,
                        [('lb.dispatch', 0.0, 40.0)])]})
    # Replica clock is 500 s behind: wall 500.01 at controller 1000.
    agg.ingest('replica-3', {
        'clock': {'wall': 500.0},
        'traces': [_leg(tid, 1, 500.01,
                        [('prefill', 0.0, 30.0),
                         ('decode', 30.0, 100.0)])]})
    assembled = agg.assemble_trace(tid)
    names = [s['name'] for s in assembled['spans']]
    assert names == ['lb.dispatch', 'prefill', 'decode']
    walls = [s['t_wall'] for s in assembled['spans']]
    assert walls == sorted(walls)
    assert walls[1] == pytest.approx(1000.01)    # skew-adjusted
    by_name = {s['name']: s for s in assembled['spans']}
    assert by_name['prefill']['source'] == 'replica-3'
    assert agg.assemble_trace('not-a-trace') is None


def test_migration_and_handoff_odyssey_is_one_causal_trace():
    """The acceptance odyssey: LB dispatch -> prefill worker -> KV
    handoff to a decode worker -> mid-stream migration to a second
    decode worker, four processes with three different clocks — ONE
    assembled trace, every leg present, spans in causal order after
    skew adjustment, the migration leg carrying its cause."""
    clock = _clock(10_000.0)
    agg = _agg(clock=clock)
    tid = tracing.mint_trace_id(__import__('random').Random(3))
    # LB: clock agrees with the controller.
    agg.ingest('lb-0', {
        'clock': {'wall': 10_000.0},
        'traces': [_leg(tid, 1, 10_000.0,
                        [('lb.dispatch', 0.0, 20.0)])]})
    # Prefill worker: clock 30 s ahead of the controller.
    agg.ingest('prefill-0', {
        'clock': {'wall': 10_030.0},
        'traces': [_leg(tid, 1, 10_030.01,
                        [('prefill', 0.0, 50.0),
                         ('kv.handoff', 50.0, 15.0)])]})
    # Decode worker: clock 200 s behind.
    clock.state['now'] = 10_000.2
    agg.ingest('decode-0', {
        'clock': {'wall': 9_800.2},
        'traces': [_leg(tid, 1, 9_800.3,
                        [('decode', 0.0, 80.0)])]})
    # Migration target after decode-0 died mid-stream: same skew
    # domain as the controller.
    clock.state['now'] = 10_000.5
    leg = _leg(tid, 1, 10_000.5, [('decode.resume', 0.0, 60.0)])
    leg['meta'] = {'cause': 'migration', 'migrated_from': 'decode-0'}
    agg.ingest('decode-1', {'clock': {'wall': 10_000.5},
                            'traces': [leg]})
    assert agg.trace_ids() == [tid]          # ONE trace, four legs
    assembled = agg.assemble_trace(tid)
    assert len(assembled['legs']) == 4
    assert {leg['source'] for leg in assembled['legs']} == {
        'lb-0', 'prefill-0', 'decode-0', 'decode-1'}
    names = [s['name'] for s in assembled['spans']]
    assert names == ['lb.dispatch', 'prefill', 'kv.handoff', 'decode',
                     'decode.resume']
    walls = [s['t_wall'] for s in assembled['spans']]
    assert walls == sorted(walls)
    causes = [leg['meta'].get('cause') for leg in assembled['legs']
              if leg.get('meta')]
    assert 'migration' in causes


def test_chrome_events_export(tmp_path):
    agg = _agg(clock=_clock())
    tid = 'cd' * 16
    agg.ingest('r0', {'clock': {'wall': 0.0},
                      'traces': [_leg(tid, 7, 1.0,
                                      [('prefill', 0.0, 5.0)])]})
    events = agg.chrome_events(tid)
    assert events and events[0]['ph'] == 'X'
    assert events[0]['args']['trace_id'] == tid
    from skypilot_tpu.utils import timeline
    path = timeline.write_trace(str(tmp_path / 'trace.json'), events)
    data = json.loads(open(path).read())
    assert data['traceEvents'][0]['name'] == 'prefill'
    assert agg.chrome_events('missing') is None


def test_trace_store_bounded_and_legs_capped():
    agg = _agg(clock=_clock(), trace_capacity=4)
    for i in range(10):
        agg.ingest_traces('r0', [_leg(f'{i:032x}', i, float(i),
                                      [('decode', 0.0, 1.0)])])
    ids = agg.trace_ids()
    assert len(ids) == 4
    assert ids == [f'{i:032x}' for i in range(6, 10)]   # oldest evicted
    evicted = agg.render_json()['skytpu_fleet_traces_evicted_total'][
        'series'][0]['value']
    assert evicted == 6
    tid = 'ee' * 16
    legs = [_leg(tid, 1, 0.0, [('decode', 0.0, 1.0)])
            for _ in range(fleet_lib.MAX_LEGS_PER_TRACE + 10)]
    agg.ingest_traces('r1', legs)
    assert (len(agg.assemble_trace(tid)['legs'])
            == fleet_lib.MAX_LEGS_PER_TRACE)


def test_per_source_series_cap_drops_and_counts(monkeypatch):
    monkeypatch.setattr(fleet_lib, 'MAX_SERIES_PER_SOURCE', 2)
    agg = _agg()
    reg = registry_lib.MetricsRegistry()
    for i in range(5):
        reg.counter('skytpu_thing_total', 'h', idx=str(i)).inc(1)
    agg.ingest('r0', {'clock': {'wall': 0.0},
                      'registry': reg.export_wire()})
    out = agg.render_json()
    assert len(out['skytpu_thing_total']['series']) == 2
    dropped = out['skytpu_fleet_series_dropped_total'][
        'series'][0]['value']
    assert dropped == 3


def test_forget_source_drops_live_state_keeps_merged_history():
    agg = _agg()
    agg.ingest('r0', {'clock': {'wall': 0.0},
                      'registry': _wire_counter(
                          fleet_lib.ADMIT_METRIC, 5.0, tier='t')})
    tid = 'ff' * 16
    agg.ingest_traces('r0', [_leg(tid, 1, 0.0, [('d', 0.0, 1.0)])])
    assert agg.source_count() == 1
    agg.forget_source('r0')
    assert agg.source_count() == 0
    assert agg.trace_ids() == [tid]          # history survives


# ------------------------------------------- trace ids / wire headers
def test_mint_trace_id_seeded_deterministic():
    import random
    a = tracing.mint_trace_id(random.Random(7))
    b = tracing.mint_trace_id(random.Random(7))
    assert a == b and len(a) == 32
    assert int(a, 16) >= 0
    assert len(tracing.mint_trace_id()) == 32


def test_trace_header_roundtrip_and_garbage():
    tid = tracing.mint_trace_id()
    value = tracing.format_trace_header(tid, 'lb.dispatch')
    parsed = tracing.parse_trace_header(value)
    assert parsed == {'trace_id': tid, 'parent_span': 'lb.dispatch'}
    assert tracing.parse_trace_header(tid) == {
        'trace_id': tid, 'parent_span': None}
    for garbage in (None, '', 'zz;span', 'short', 42,
                    'deadbeef' * 9):         # 72 hex > 64 cap
        assert tracing.parse_trace_header(garbage) is None
    # A malformed parent must not poison a good trace id.
    assert tracing.parse_trace_header(tid + ';bad space')[
        'parent_span'] is None


def test_request_trace_keeps_legacy_id_and_adopts_wire_context():
    trace = tracing.RequestTrace(9)
    assert trace.legacy_id and '-' in trace.legacy_id
    original = trace.trace_id
    assert len(original) == 32
    trace.adopt_wire_context(trace_id='ab' * 16,
                             parent_span='lb.dispatch')
    assert trace.trace_id == 'ab' * 16 != original
    trace.begin('decode')
    trace.finish()
    d = trace.to_dict()
    assert d['trace_id'] == 'ab' * 16
    assert d['legacy_id'] == trace.legacy_id
    assert d['parent_span'] == 'lb.dispatch'


def test_trace_buffer_cursor_ships_each_trace_once():
    buf = tracing.TraceBuffer(maxlen=8)
    for i in range(3):
        t = tracing.RequestTrace(i)
        t.begin('decode')
        t.finish()
        buf.add(t)
    cursor, out = buf.summaries_since(0)
    assert len(out) == 3 and cursor == 3
    cursor2, out2 = buf.summaries_since(cursor)
    assert out2 == [] and cursor2 == 3
    t = tracing.RequestTrace(99)
    t.finish()
    buf.add(t)
    cursor3, out3 = buf.summaries_since(cursor2)
    assert [d['request_id'] for d in out3] == [99] and cursor3 == 4
    # limit trims and resumes from the last SHIPPED trace.
    cursor4, first = buf.summaries_since(0, limit=2)
    assert len(first) == 2
    _, rest = buf.summaries_since(cursor4, limit=10)
    assert [d['request_id'] for d in first + rest] == [0, 1, 2, 99]


# -------------------------------------------------- sim end-to-end SLO
def test_slo_burst_scenario_pages_short_window_only():
    """The acceptance drill: a seeded burst in the final five minutes
    flips burn{5m} above 1 while burn{1h} stays below — on the fleet
    aggregator the controller scrapes over the virtual clock."""
    from skypilot_tpu.serve.sim import scenarios as sim_scenarios
    rep = sim_scenarios.run_scenario('slo_burst', seed=1)
    assert rep['fleet']['sources'] == 3          # every replica scraped
    latency = rep['fleet']['slo']['latency']
    assert latency['burn_5m'] > 1.0
    assert latency['burn_1h'] < 1.0
    assert latency['attainment'] < 0.9
    assert set(rep['fleet']['slo']) == {'latency', 'throughput'}
    assert rep['requests']['lost'] == 0


def test_slo_burst_scenario_deterministic():
    from skypilot_tpu.serve.sim import scenarios as sim_scenarios
    a = sim_scenarios.run_scenario('slo_burst', seed=7)
    b = sim_scenarios.run_scenario('slo_burst', seed=7)
    assert a['event_log_sha256'] == b['event_log_sha256']
    assert a['fleet'] == b['fleet']
