"""Multi-step on-device decode (``decode_steps_per_call``).

The knob pins EXACTLY k fused decode steps (with on-device sampling)
into every jitted decode call, so per-call dispatch, readback lag and
sampling host-syncs amortize k x. Contracts pinned here:

- knob validation + the pin itself: every decode dispatch runs at
  static horizon k even when the caller asks for horizon 1, and a
  lockstep budget-bound round costs exactly one dispatch per k tokens
  (the jaxpr-audit ``multistep`` preset gates the same invariant with
  the transfer/recompile interceptor attached);
- k-matrix greedy equivalence: k in {1, 2, 4, 8} byte-identical on
  BOTH engines (fp32 config — bf16 near-tie argmax flips under the
  reordered two-block ring softmax are the one documented exception,
  same caveat as the int8-KV chunked-prefill contract);
- early-EOS mid-scan: a request whose eos lands inside a fused call
  truncates exactly where k=1 does (the substeps past eos are
  discarded at readback; co-batched slots keep their tokens);
- sampling determinism: same seed + same k => identical sampled
  output, and the k>1 sampled stream is drawn from the same
  per-request distribution machinery (shared ``sample_tokens``);
- composition: ``speculate_k`` takes precedence for decode (one
  verify round per step — documented), int8/int4-KV engines serve
  under the knob, and the serve layer streams tokens in order through
  the scheduler with ``decode_steps_per_call`` set.
"""
import dataclasses
import json
import time
import urllib.request

import jax
import jax.numpy as jnp
import pytest

from skypilot_tpu.inference.engine import InferenceEngine
from skypilot_tpu.inference.paged import PagedInferenceEngine
from skypilot_tpu.models import configs, llama

ENGINES = (InferenceEngine, PagedInferenceEngine)


@pytest.fixture(scope='module')
def setup():
    cfg = configs.TINY
    # fp32: decisive argmaxes — greedy byte-identity across fused
    # horizons holds exactly (bf16 near-ties may flip under the
    # reordered two-block softmax; that caveat is documented, not
    # tested around).
    cfg32 = dataclasses.replace(cfg, dtype=jnp.float32)
    params32 = llama.init_params(jax.random.PRNGKey(0), cfg32)
    return cfg32, params32


def _run(engcls, cfg, params, prompts, n_new, *, horizon=1,
         req_kw=None, **kw):
    eng = engcls(cfg, params, max_batch=4, max_seq=128,
                 attn_impl='xla', **kw)
    rids = [eng.add_request(list(p), max_new_tokens=n_new,
                            **(req_kw or {}))
            for p in prompts]
    done = eng.run_to_completion(horizon=horizon)
    return [done[r].output for r in rids], eng


PROMPTS = [[1, 2, 3] * 5, [5, 9, 2] * 4]


def test_knob_validation():
    cfg = configs.TINY
    with pytest.raises(ValueError):
        InferenceEngine(cfg, max_batch=2, max_seq=64,
                        decode_steps_per_call=0)
    with pytest.raises(ValueError):
        PagedInferenceEngine(cfg, max_batch=2, max_seq=64,
                             decode_steps_per_call=-3)
    eng = InferenceEngine(cfg, max_batch=2, max_seq=64,
                          decode_steps_per_call=4)
    assert eng.decode_steps_per_call == 4
    assert InferenceEngine(cfg, max_batch=2, max_seq=64
                           ).decode_steps_per_call is None


@pytest.mark.parametrize('engcls', ENGINES)
def test_pin_one_dispatch_per_k_tokens(setup, engcls):
    """Every decode dispatch runs at static horizon k (caller asked
    for 1), and a lockstep budget-bound batch costs exactly
    ceil(decode_tokens / k) dispatches — the amortization contract."""
    cfg, params = setup
    k = 4
    eng = engcls(cfg, params, max_batch=4, max_seq=128,
                 attn_impl='xla', decode_steps_per_call=k)
    calls = []
    inner = eng._decode_fn

    def shim(*args, **kw):
        # horizon is a trailing positional on both engines.
        tail = [a for a in args if isinstance(a, (int, bool))]
        calls.append(tail)
        return inner(*args, **kw)

    eng._decode_fn = shim
    # Equal prompts + budget-bound (no eos/stop): all slots lockstep;
    # 2k decode tokens after the prefill-sampled first token.
    for _ in range(4):
        eng.add_request([1, 2, 3, 4, 5, 6], max_new_tokens=2 * k + 1)
    eng.run_to_completion(horizon=1)
    assert calls, 'decode never dispatched'
    horizons = [c[0] for c in calls]
    assert all(h == k for h in horizons), horizons
    if engcls is PagedInferenceEngine:
        # Early slot recycle stops dispatch the moment enqueued calls
        # cover every budget: EXACTLY one dispatch per k tokens.
        assert len(calls) == 2, calls
    else:
        # The slot engine has no early free: up to PIPELINE_DEPTH - 1
        # in-flight calls overshoot before readback marks the slots
        # finished (their tokens are discarded at readback).
        assert 2 <= len(calls) <= 2 + eng._PIPELINE_DEPTH - 1, calls


@pytest.mark.parametrize('engcls', ENGINES)
def test_greedy_byte_identity_k_matrix(setup, engcls):
    cfg, params = setup
    outs = {}
    for k in (1, 2, 4, 8):
        outs[k], _ = _run(engcls, cfg, params, PROMPTS, 20,
                          decode_steps_per_call=k)
    for k in (2, 4, 8):
        assert outs[k] == outs[1], (engcls.__name__, k)


def test_early_eos_mid_scan(setup):
    """EOS landing inside a fused call: the request finishes at the
    eos position exactly as at k=1, the post-eos substeps are
    discarded, and a co-batched slot keeps decoding unaffected."""
    cfg, params = setup
    base, _ = _run(InferenceEngine, cfg, params, PROMPTS, 20,
                   decode_steps_per_call=1)
    # Pick a FIRST-occurrence token mid-stream, at an output index
    # that keeps the eos inside a fused k=8 call (decode substeps
    # cover output indices 1..8, 9..16 — anything but the call
    # boundaries lands mid-scan).
    idx = next(i for i in range(1, 16)
               if base[0][i] not in base[0][:i] and i % 8 != 0)
    eos = base[0][idx]
    for k in (1, 8):
        eng = InferenceEngine(cfg, params, max_batch=4, max_seq=128,
                              attn_impl='xla', decode_steps_per_call=k)
        r1 = eng.add_request(list(PROMPTS[0]), max_new_tokens=20,
                             eos_id=int(eos))
        r2 = eng.add_request(list(PROMPTS[1]), max_new_tokens=20)
        done = eng.run_to_completion(horizon=1)
        if k == 1:
            want1, want2 = done[r1].output, done[r2].output
        else:
            assert done[r1].output == want1
            assert done[r2].output == want2
    assert want1[-1] == eos and len(want1) == idx + 1
    assert len(want2) == 20


@pytest.mark.parametrize('engcls', ENGINES)
def test_sampling_determinism_fixed_seed(setup, engcls):
    """Sampled decode under the knob: same seed + same k => identical
    streams; the rng rides on-device splits inside the fused scan."""
    cfg, params = setup
    kw = dict(decode_steps_per_call=4, rng_seed=7)
    a, _ = _run(engcls, cfg, params, PROMPTS, 16,
                req_kw=dict(temperature=0.9, top_k=8), **dict(kw))
    b, _ = _run(engcls, cfg, params, PROMPTS, 16,
                req_kw=dict(temperature=0.9, top_k=8), **dict(kw))
    assert a == b
    assert any(len(set(x)) > 1 for x in a)     # actually sampled


def test_speculative_takes_precedence(setup):
    """speculate_k > 0 drives decode through the verify loop; the
    multi-step knob composes without breaking it (greedy spec output
    still byte-identical to vanilla)."""
    cfg, params = setup
    rep = [3, 1, 4, 1, 5, 9, 2, 6] * 4
    want, _ = _run(InferenceEngine, cfg, params, [rep], 16,
                   decode_steps_per_call=4)
    got, eng = _run(InferenceEngine, cfg, params, [rep], 16,
                    decode_steps_per_call=4, speculate_k=4)
    assert got == want
    assert eng.spec_metrics()['spec_rounds'] > 0


@pytest.mark.slow
def test_quantized_kv_and_int4_weights(setup):
    """int8 KV and int4 weights both serve under the knob. With a
    quantized cache the k>1 scan attends this horizon's rows from the
    bf16 ring where k=1 reads them back quantized — near-tie argmaxes
    may flip (the documented int8-KV caveat), so the contract is
    bounded divergence; int4 weights with bf16 KV keep byte
    identity."""
    cfg, params = setup
    i4_1, _ = _run(PagedInferenceEngine, cfg, params, PROMPTS, 16,
                   decode_steps_per_call=1, quantize='int4',
                   kv_cache_dtype='bf16')
    i4_4, _ = _run(PagedInferenceEngine, cfg, params, PROMPTS, 16,
                   decode_steps_per_call=4, quantize='int4',
                   kv_cache_dtype='bf16')
    assert i4_4 == i4_1
    k8_1, _ = _run(PagedInferenceEngine, cfg, params, PROMPTS, 16,
                   decode_steps_per_call=1, kv_cache_dtype='int8')
    k8_4, e = _run(PagedInferenceEngine, cfg, params, PROMPTS, 16,
                   decode_steps_per_call=4, kv_cache_dtype='int8')
    assert e.cache.quantized
    for a, b in zip(k8_1, k8_4):
        agree = sum(x == y for x, y in zip(a, b))
        assert agree >= int(0.85 * len(a)), (a, b)


@pytest.mark.slow
def test_serve_e2e_streams_in_order():
    """ModelServer with --decode-steps-per-call: tokens stream through
    the scheduler in order, the full output matches the done event,
    and the knob surfaces in both metrics formats."""
    from skypilot_tpu.serve.server import ModelServer
    from skypilot_tpu.utils import common_utils
    port = common_utils.find_free_port(19750)
    server = ModelServer('tiny', max_batch=2, max_seq=64, port=port,
                         decode_steps_per_call=4)
    server.start(block=False)
    try:
        assert server._ready.wait(180)
        assert server.engine.decode_steps_per_call == 4
        body = json.dumps({'prompt': [1, 2, 3], 'max_new_tokens': 9,
                           'stream': True}).encode()
        req = urllib.request.Request(
            f'http://127.0.0.1:{port}/generate', body,
            {'Content-Type': 'application/json'})
        with urllib.request.urlopen(req, timeout=60) as r:
            events = [json.loads(ln[5:]) for ln in r
                      if ln.startswith(b'data:')]
        tokens = [e['token'] for e in events if 'token' in e]
        assert len(tokens) == 9
        assert events[-1].get('done') is True
        assert events[-1]['tokens'] == tokens
        with urllib.request.urlopen(
                f'http://127.0.0.1:{port}/metrics?format=json',
                timeout=30) as r:
            payload = json.loads(r.read())
        assert payload['decode_steps_per_call'] == 4
        assert payload['scheduler']['decode_steps_per_call'] == 4
        with urllib.request.urlopen(
                f'http://127.0.0.1:{port}/metrics', timeout=30) as r:
            prom = r.read().decode()
        assert 'skytpu_decode_steps_per_call 4' in prom
    finally:
        server.stop()
