"""SLO-aware serving core (`serve/scheduler.py`): fast unit tests for
the admission policy (priority ordering, shortest-remaining-work
tie-break, tier budget split, shed threshold, Retry-After math), e2e
smoke through the real model server (both engines: incremental
streaming off the engine loop, cancel mid-stream releases the slot,
HTTP 429 + Retry-After), the queue-depth LB policy, and a slow
saturation test asserting the latency tier's TTFT stays bounded while
the throughput tier absorbs the overload.
"""
import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from skypilot_tpu.serve import scheduler as sched_lib
from skypilot_tpu.telemetry import registry as registry_lib


@pytest.fixture()
def fresh_registry():
    """Scheduler unit tests read absolute counter values — give each
    one a clean process registry (servers/engines built later re-create
    their handles get-or-create, so this is safe to swap mid-session)."""
    yield registry_lib.reset_registry()
    registry_lib.reset_registry()


class FakeEngine:
    """The slice of the engine surface the scheduler drives: slot
    accounting, priority-carrying add_request, remaining-work."""

    def __init__(self, max_batch=4, capacity=1024):
        self.max_batch = max_batch
        self.num_active = 0
        self.queue_depth = 0
        self.capacity = capacity
        self.added = []     # (rid, prompt, max_new_tokens, priority)
        self._next_id = 0
        self.cancelled = []
        self.inflight_tokens = 0

    def kv_pool_stats(self):
        return {'pool_token_capacity': self.capacity, 'tokens_used': 0,
                'tokens_free': self.capacity, 'preemptions': 0,
                'kv_cache_dtype': 'bf16', 'kv_token_bytes': 0}

    def add_request(self, prompt, max_new_tokens=128, priority=0,
                    **sampling):
        del sampling
        rid = self._next_id
        self._next_id += 1
        self.added.append((rid, list(prompt), max_new_tokens, priority))
        self.num_active += 1
        return rid

    def remaining_work_tokens(self):
        return self.inflight_tokens

    def pop_finished(self, rid):
        del rid
        return None

    def cancel(self, rid):
        self.cancelled.append(rid)
        self.num_active = max(0, self.num_active - 1)
        return True


def make_sched(engine=None, **kw):
    kw.setdefault('default_tier', 'latency')
    sched = sched_lib.RequestScheduler(threading.Lock(), **kw)
    if engine is not None:
        sched.bind_engine(engine)
    return sched


# ---------------------------------------------------------------- units
def test_resolve_tier_default_and_validation(fresh_registry):
    sched = make_sched(default_tier='throughput')
    assert sched.resolve_tier(None) == 'throughput'
    assert sched.resolve_tier('') == 'throughput'
    assert sched.resolve_tier('latency') == 'latency'
    with pytest.raises(ValueError, match='unknown SLO tier'):
        sched.resolve_tier('realtime')
    with pytest.raises(ValueError, match='unknown SLO tier'):
        make_sched(default_tier='bogus')
    with pytest.raises(ValueError, match='latency_admit_frac'):
        make_sched(latency_admit_frac=1.0)


def test_tier_priority_hint_reaches_engine(fresh_registry):
    """Tier index IS the engine priority hint: latency=0 beats
    throughput=1 inside engine-internal requeues too."""
    eng = FakeEngine(max_batch=2)
    sched = make_sched(eng)
    sched.submit([1] * 8, max_new_tokens=8, tier='throughput')
    sched.submit([1] * 8, max_new_tokens=8, tier='latency')
    sched.fill_engine(eng)
    prios = {p for (_, _, _, p) in eng.added}
    assert prios == {0, 1}
    # Deficit split starts at the latency tier: it is admitted first.
    assert eng.added[0][3] == sched_lib.TIERS.index('latency')


def test_engine_queue_pop_orders_by_priority_fifo_within():
    """The engine-side half of the contract: queued requests pop most
    urgent (lowest priority) first, FIFO within a class — a paged
    preemption requeue cannot park a latency request behind newly
    queued throughput work."""
    from skypilot_tpu.inference.engine import InferenceEngine
    from skypilot_tpu.models import configs
    eng = InferenceEngine(configs.get_config('tiny'), max_batch=2,
                          max_seq=64)
    ids = [eng.add_request([1, 2, 3], max_new_tokens=2, priority=p)
           for p in (1, 0, 1, 0)]
    popped = [eng._queue_pop().request_id for _ in range(4)]
    assert popped == [ids[1], ids[3], ids[0], ids[2]]


def test_srw_pop_shortest_work_first_fifo_ties(fresh_registry):
    eng = FakeEngine(max_batch=8)
    sched = make_sched(eng)
    a = sched.submit([1] * 40, max_new_tokens=10, tier='latency')
    b = sched.submit([1] * 5, max_new_tokens=5, tier='latency')
    c = sched.submit([1] * 5, max_new_tokens=5, tier='latency')
    sched.fill_engine(eng)
    order = [rid for rid, *_ in eng.added]
    assert order == [b.request_id, c.request_id, a.request_id]
    assert b.request_id is not None and b.seq < c.seq  # FIFO tie-break


def test_bank_full_defers_only_blocked_request(fresh_registry):
    """AdapterBankFullError defers exactly the blocked request for the
    cycle and keeps admitting everything else — a bank-full adapter
    must not head-of-line-block base-model admission. The deferred
    request goes back to its queue for the next cycle."""
    from skypilot_tpu.inference.adapters import AdapterBankFullError

    class BankFullEngine(FakeEngine):
        def add_request(self, prompt, max_new_tokens=128, priority=0,
                        **sampling):
            if sampling.get('adapter') == 'full':
                raise AdapterBankFullError('all slots pinned')
            return super().add_request(
                prompt, max_new_tokens=max_new_tokens,
                priority=priority, **sampling)

    eng = BankFullEngine(max_batch=4)
    sched = make_sched(eng)
    # Shortest work: SRW picks the blocked request FIRST every cycle.
    blocked = sched.submit([1] * 2, max_new_tokens=2, tier='latency',
                           adapter='full')
    base_a = sched.submit([1] * 8, max_new_tokens=8, tier='latency')
    base_b = sched.submit([1] * 8, max_new_tokens=8,
                          tier='throughput')
    sched.fill_engine(eng)
    admitted = {rid for rid, *_ in eng.added}
    assert admitted == {base_a.request_id, base_b.request_id}
    assert blocked.request_id is None
    assert sched.backlog == 1          # requeued for the next cycle
    # Pins released: the deferred request admits next cycle.
    sched.fill_engine(eng)
    assert blocked.request_id is None  # still full this fake cycle
    BankFullEngine.add_request = FakeEngine.add_request
    sched.fill_engine(eng)
    assert blocked.request_id is not None
    assert sched.backlog == 0


def test_budget_split_deficit_weighted(fresh_registry):
    """With both tiers backlogged and equal request sizes, admitted
    work tracks latency_admit_frac (7/10 at 0.7)."""
    eng = FakeEngine(max_batch=10)
    sched = make_sched(eng, latency_admit_frac=0.7,
                       max_queue_tokens=100_000)
    for _ in range(10):
        sched.submit([1] * 10, max_new_tokens=10, tier='latency')
    for _ in range(10):
        sched.submit([1] * 10, max_new_tokens=10, tier='throughput')
    sched.fill_engine(eng)     # 10 free slots
    lat = sum(1 for (_, _, _, p) in eng.added if p == 0)
    assert len(eng.added) == 10
    assert lat == 7
    # An idle tier's share flows to the busy one: drain latency, refill
    # throughput only — all free slots go to throughput.
    eng2 = FakeEngine(max_batch=4)
    sched2 = make_sched(eng2, latency_admit_frac=0.7)
    for _ in range(4):
        sched2.submit([1] * 10, max_new_tokens=10, tier='throughput')
    sched2.fill_engine(eng2)
    assert all(p == 1 for (_, _, _, p) in eng2.added)


def test_shed_threshold_per_tier_and_counter(fresh_registry):
    eng = FakeEngine(max_batch=0)        # nothing admits; queues grow
    sched = make_sched(eng, max_queue_tokens=100)
    sched.submit([1] * 50, max_new_tokens=10, tier='latency')   # 60 ok
    with pytest.raises(sched_lib.ShedError) as ei:
        sched.submit([1] * 40, max_new_tokens=10, tier='latency')
    assert ei.value.reason == 'queue_full'
    assert ei.value.tier == 'latency'
    assert ei.value.retry_after_s >= 1
    # The bound is per tier: the other tier still admits.
    sched.submit([1] * 40, max_new_tokens=10, tier='throughput')
    reg = registry_lib.get_registry()
    shed = reg.get('skytpu_sched_shed_total', tier='latency',
                   reason='queue_full')
    assert shed is not None and shed.value == 1
    # Queue state unchanged by the shed.
    assert sched.json_stats()['tiers']['latency']['queue_tokens'] == 60


def test_token_rate_meter_windowed():
    m = sched_lib._TokenRateMeter(window_s=10.0)
    assert m.rate(now=100.0) == 0.0
    m.add(100, now=100.0)
    m.add(200, now=105.0)
    assert m.rate(now=105.0) == pytest.approx(300 / 5.0)
    # Events age out of the window.
    m.add(50, now=112.0)
    assert m.rate(now=112.0) == pytest.approx((200 + 50) / 7.0)


def test_retry_after_math(fresh_registry):
    from skypilot_tpu.telemetry import clock
    eng = FakeEngine(max_batch=4)
    sched = make_sched(eng, max_queue_tokens=100_000)
    # Cold meter: conservative 8 tok/s/slot floor over max_batch slots.
    assert sched.retry_after_s('latency', 64) == 2   # ceil(64 / 32)
    # Warm meter: measured throughput is the denominator. Timestamps
    # ride the real monotonic clock (retry_after_s reads it); pick
    # quotients far from integer boundaries so clock drift between
    # the add and the assert cannot flip the ceil.
    now = clock.monotonic()
    sched._rate.add(300, now=now - 10.0)
    sched._rate.add(300, now=now)                    # ~60 tok/s
    assert sched.retry_after_s('latency', 85) == 2   # ceil(85/60)
    # Work ahead counts: engine in-flight + queued tokens at or above
    # the tier.
    eng.inflight_tokens = 60
    sched.submit([1] * 20, max_new_tokens=10, tier='latency')   # 30 q
    assert sched.retry_after_s('latency', 85) == 3   # (60+30+85)/60
    # A latency arrival does not wait behind throughput backlog...
    sched.submit([1] * 290, max_new_tokens=10, tier='throughput')
    assert sched.retry_after_s('latency', 85) == 3
    # ...but a throughput arrival waits behind both tiers (+300).
    assert sched.retry_after_s('throughput', 85) == 8
    # Clamps: [1, 120].
    assert sched.retry_after_s('latency', 0) >= 1
    eng.inflight_tokens = 10_000_000
    assert sched.retry_after_s('latency', 85) == 120


def test_cancel_queued_releases_tokens(fresh_registry):
    eng = FakeEngine(max_batch=0)
    sched = make_sched(eng, max_queue_tokens=100)
    sr = sched.submit([1] * 50, max_new_tokens=10, tier='latency')
    assert sched.cancel(sr) is True
    token, finished = sr.outbox.get(timeout=1)
    assert (token, finished) == (None, True)
    assert sr.outbox.error == 'cancelled'
    # Tokens released: the bound admits a new request again.
    sched.submit([1] * 80, max_new_tokens=10, tier='latency')


def test_fail_all_wakes_every_waiter(fresh_registry):
    eng = FakeEngine(max_batch=1)
    sched = make_sched(eng, max_queue_tokens=10_000)
    admitted = sched.submit([1] * 4, max_new_tokens=4)
    sched.fill_engine(eng)
    assert admitted.request_id is not None
    queued = sched.submit([1] * 4, max_new_tokens=4)
    sched.fail_all('engine exploded')
    for sr in (admitted, queued):
        token, finished = sr.outbox.get(timeout=1)
        assert (token, finished) == (None, True)
        assert 'engine exploded' in sr.outbox.error
    with pytest.raises(RuntimeError, match='engine failed'):
        sched.submit([1] * 4, max_new_tokens=4)
    reg = registry_lib.get_registry()
    shed = reg.get('skytpu_sched_shed_total', tier='latency',
                   reason='engine_error')
    assert shed is not None and shed.value == 1   # queued one only


def test_outbox_order_fail_idempotent_and_aget():
    ob = sched_lib.Outbox()
    ob.put(7, False)
    ob.put(8, True)
    assert ob.get(timeout=1) == (7, False)
    assert ob.get(timeout=1) == (8, True)
    ob.fail('first')
    ob.fail('second')
    assert ob.error == 'first'
    assert ob.get(timeout=1) == (None, True)

    import asyncio
    ob2 = sched_lib.Outbox()
    ob2.put(42, True)
    assert asyncio.run(ob2.aget()) == (42, True)


# ---------------------------------------------------- queue-depth LB policy
class _MetricsReplica:
    """Fake replica serving only /metrics?format=json."""

    def __init__(self, port, queue_tokens):
        import http.server
        self.queue_tokens = queue_tokens
        outer = self

        class H(http.server.BaseHTTPRequestHandler):
            timeout = 10

            def log_message(self, *a):
                del a

            def do_GET(self):
                body = json.dumps(
                    {'queue_tokens_total': outer.queue_tokens}).encode()
                self.send_response(200)
                self.send_header('Content-Length', str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        import http.server as hs
        self.httpd = hs.ThreadingHTTPServer(('127.0.0.1', port), H)
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()

    def stop(self):
        self.httpd.shutdown()


def test_queue_depth_policy_prefers_least_loaded():
    from skypilot_tpu.serve import load_balancing_policies as lb
    from skypilot_tpu.utils import common_utils
    p1 = common_utils.find_free_port(19100)
    r1 = _MetricsReplica(p1, queue_tokens=5000)
    p2 = common_utils.find_free_port(p1 + 1)
    r2 = _MetricsReplica(p2, queue_tokens=10)
    try:
        policy = lb.make_policy('queue_depth')
        u1, u2 = f'http://127.0.0.1:{p1}', f'http://127.0.0.1:{p2}'
        policy.set_ready_replicas([u1, u2])
        assert policy.select_replica() == u2
        # In-flight dispatches advance the loaded score between probes
        # so a burst within one TTL window still spreads.
        for _ in range(1 + 5000 // policy.EST_TOKENS_PER_REQUEST):
            policy.pre_execute(u2)
        assert policy.select_replica() == u1
        # exclude (the LB's transparent retry) is honored.
        assert policy.select_replica(exclude={u1}) == u2
    finally:
        r1.stop()
        r2.stop()


def test_queue_depth_policy_degrades_on_probe_failure():
    from skypilot_tpu.serve import load_balancing_policies as lb
    from skypilot_tpu.utils import common_utils
    dead = f'http://127.0.0.1:{common_utils.find_free_port(19200)}'
    policy = lb.make_policy('queue_depth')
    policy.set_ready_replicas([dead])
    # Probe fails; the policy still returns the replica (least-load
    # fallback) rather than blackholing.
    assert policy.select_replica() == dead


# ------------------------------------------------------------- e2e smoke
def _post(port, payload, timeout=60, headers=None):
    body = json.dumps(payload).encode()
    h = {'Content-Type': 'application/json'}
    h.update(headers or {})
    req = urllib.request.Request(
        f'http://127.0.0.1:{port}/generate', body, h)
    return urllib.request.urlopen(req, timeout=timeout)


@pytest.fixture(params=['slot', 'paged'])
def tiny_server(request):
    from skypilot_tpu.serve.server import ModelServer
    from skypilot_tpu.utils import common_utils
    port = common_utils.find_free_port(19300)
    server = ModelServer('tiny', max_batch=2, max_seq=64, port=port,
                         kv_cache=request.param)
    server.start(block=False)
    assert server._ready.wait(180)
    yield server
    server.stop()


def test_e2e_stream_incremental_and_cancel(tiny_server):
    """Tokens arrive through the outbox BEFORE the request finishes
    (true incremental streaming off the engine loop), and finishing a
    stream early cancels engine-side, releasing the slot."""
    server = tiny_server
    sr = server.submit_stream([1, 2, 3, 4], max_new_tokens=48,
                              temperature=0.0, top_k=0, eos_id=None)
    token, finished = sr.outbox.get(timeout=60)
    # First token is live while the engine still owns the request —
    # the incremental contract (48 tokens take several fused steps).
    assert token is not None and not finished
    assert sr.result is None
    aborted_before = server._m_aborted.value
    server.finish_stream(sr)               # client walks away
    assert server._m_aborted.value == aborted_before + 1
    # The slot is released: a fresh request completes promptly.
    with _post(server.port, {'prompt': [5, 6], 'max_new_tokens': 3,
                             'slo_tier': 'latency'}) as r:
        out = json.loads(r.read())
    assert len(out['tokens']) == 3
    deadline = time.time() + 30
    while server.engine.num_active and time.time() < deadline:
        time.sleep(0.05)
    assert server.engine.num_active == 0


def test_e2e_sse_streams_all_tokens(tiny_server):
    server = tiny_server
    with _post(server.port, {'prompt': [1, 2, 3], 'max_new_tokens': 6,
                             'stream': True}) as r:
        assert 'text/event-stream' in r.headers.get('Content-Type', '')
        events = [json.loads(ln[5:]) for ln in r
                  if ln.startswith(b'data:')]
    tokens = [e['token'] for e in events if 'token' in e]
    assert len(tokens) == 6
    assert events[-1].get('done') is True
    assert events[-1]['tokens'] == tokens


def test_e2e_shed_429_with_retry_after(tiny_server):
    server = tiny_server
    server.sched._max_queue_tokens = 4     # work=prompt+gen > 4 sheds
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(server.port, {'prompt': [1, 2, 3, 4],
                                'max_new_tokens': 8}, timeout=30)
        err = ei.value
        assert err.code == 429
        retry_after = int(err.headers['Retry-After'])
        assert retry_after >= 1
        payload = json.loads(err.read())['error']
        assert payload['reason'] == 'queue_full'
        assert payload['retry_after_s'] == retry_after
        # X-SLO-Tier header routes the shed to the declared tier.
        with pytest.raises(urllib.error.HTTPError) as ei2:
            _post(server.port, {'prompt': [1, 2, 3, 4],
                                'max_new_tokens': 8}, timeout=30,
                  headers={'X-SLO-Tier': 'throughput'})
        assert json.loads(ei2.value.read())['error']['tier'] == \
            'throughput'
    finally:
        server.sched._max_queue_tokens = 10_000
    # Shed counters visible at /metrics?format=json.
    with urllib.request.urlopen(
            f'http://127.0.0.1:{server.port}/metrics?format=json',
            timeout=10) as r:
        m = json.loads(r.read())
    assert m['sched']['tiers']['latency']['shed_total'] >= 1
    assert m['sched']['tiers']['throughput']['shed_total'] >= 1


def test_e2e_unknown_tier_is_400(tiny_server):
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(tiny_server.port, {'prompt': [1, 2], 'max_new_tokens': 2,
                                 'slo_tier': 'platinum'}, timeout=30)
    assert ei.value.code == 400


# --------------------------------------------------------------- slow e2e
@pytest.mark.slow
def test_latency_tier_ttft_bounded_under_overload():
    """Saturation: a wall of throughput-tier work floods the engine;
    interactive latency-tier requests submitted into the overload must
    keep a bounded TTFT (they jump the backlog via tier priority +
    SRW) — the r05 failure mode this subsystem exists to fix."""
    from skypilot_tpu.serve.server import ModelServer
    from skypilot_tpu.utils import common_utils
    port = common_utils.find_free_port(19400)
    server = ModelServer('tiny', max_batch=2, max_seq=128, port=port,
                         kv_cache='paged', max_queue_tokens=100_000)
    server.start(block=False)
    try:
        assert server._ready.wait(180)
        # Overload: 10 long throughput requests against 2 slots.
        flood = [server.submit_stream(
            [1 + i] * 16, max_new_tokens=96, temperature=0.0, top_k=0,
            eos_id=None, tier='throughput') for i in range(10)]
        lat_ttfts = []
        for i in range(4):
            time.sleep(0.3)
            t0 = time.time()
            sr = server.submit_stream([7, 8, 9], max_new_tokens=4,
                                      temperature=0.0, top_k=0,
                                      eos_id=None, tier='latency')
            token, _ = sr.outbox.get(timeout=120)
            assert token is not None
            lat_ttfts.append(time.time() - t0)
            server.finish_stream(sr)
        for sr in flood:
            server.finish_stream(sr)
        stats = server.sched.json_stats()
        lat_med = sorted(lat_ttfts)[len(lat_ttfts) // 2]
        # Bounded: an interactive request never waits behind the whole
        # 10-deep flood (which is ~10x96 decode tokens of work).
        assert lat_med < 20.0
        # And the scheduler admitted every latency request ahead of the
        # remaining throughput backlog.
        assert stats['tiers']['latency']['admitted'] == 4
        # The backlog was real while the latency requests cut it.
        assert stats['tiers']['throughput']['admitted'] < 10 or \
            stats['tiers']['latency']['queue_wait_ms_p90'] < 20_000
    finally:
        server.stop()
