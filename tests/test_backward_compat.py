"""Backward-compat / version-skew harness (reference
``tests/backward_compatibility_tests.sh``: old cluster, new client, old
jobs must stay controllable). Hermetic version: the kubernetes kubectl
shim gives real pkg-shipping semantics (pods are 'remote' hosts that
import the shipped zip), and client 'versions' are simulated by forcing
a new package hash.
"""
import os
import stat
import sys
import time

import pytest

import skypilot_tpu as sky
from skypilot_tpu import core, execution
from skypilot_tpu.task import Task
from skypilot_tpu.utils import pkg_utils

pytestmark = pytest.mark.usefixtures('tmp_state_dir')


@pytest.fixture()
def kubectl_shim(tmp_path, monkeypatch):
    shim_dir = tmp_path / 'bin'
    shim_dir.mkdir()
    shim = shim_dir / 'kubectl'
    src = os.path.join(os.path.dirname(__file__), 'kubectl_shim.py')
    shim.write_text(f'#!/bin/sh\nexec {sys.executable} {src} "$@"\n')
    shim.chmod(shim.stat().st_mode | stat.S_IEXEC)
    monkeypatch.setenv('PATH', f'{shim_dir}{os.pathsep}'
                               f'{os.environ.get("PATH", "")}')
    monkeypatch.setenv('SKYTPU_K8S_FAKE_DIR', str(tmp_path / 'k8s'))
    monkeypatch.setenv('SKYTPU_AGENT_TICK', '0.1')
    monkeypatch.setenv('SKYTPU_AGENT_READY_TIMEOUT', '30')
    monkeypatch.setenv('SKYTPU_WHEEL_DIR', str(tmp_path / 'wheels'))
    kubeconfig = tmp_path / 'kubeconfig'
    kubeconfig.write_text('apiVersion: v1\nkind: Config\n')
    monkeypatch.setenv('KUBECONFIG', str(kubeconfig))
    from skypilot_tpu import check
    assert 'kubernetes' in check.check(quiet=True)


def _wait_job(cluster, job_id, timeout=60.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        jobs = {j['job_id']: j for j in core.queue(cluster)}
        st = jobs.get(job_id, {}).get('status')
        if st in ('SUCCEEDED', 'FAILED', 'FAILED_SETUP'):
            return st
        time.sleep(0.3)
    raise AssertionError(f'job {job_id} never finished')


def test_new_client_restarts_stale_agent_and_old_jobs_survive(
        kubectl_shim, monkeypatch):
    """Launch with client v1, then reuse the UP cluster from a 'newer'
    client: the agent restarts on the new runtime, the old job's record
    stays queryable, and a new job runs — the reference's
    backward-compatibility contract."""
    task = Task(name='v1', run='echo from-v1')
    task.set_resources(sky.Resources(cloud='kubernetes', cpus='1+'))
    job1, handle = execution.launch(task, cluster_name='bc',
                                    detach_run=True)
    try:
        assert _wait_job('bc', job1) == 'SUCCEEDED'
        from skypilot_tpu.provision import provisioner
        health1 = provisioner.agent_request(handle.head_runner(),
                                            {'op': 'agent_health'})
        assert health1['agentd_alive']
        v1 = health1['runtime_version']
        assert v1 == pkg_utils.package_hash()

        # 'Upgrade' the client: the package hash changes (as any code
        # edit would change it).
        real_hash = pkg_utils.package_hash()
        monkeypatch.setattr(pkg_utils, 'package_hash',
                            lambda: 'deadbeef' + real_hash[8:])

        task2 = Task(name='v2', run='echo from-v2')
        task2.set_resources(sky.Resources(cloud='kubernetes', cpus='1+'))
        job2, handle2 = execution.launch(task2, cluster_name='bc',
                                         detach_run=True)
        assert handle2.cluster_name == handle.cluster_name
        assert _wait_job('bc', job2) == 'SUCCEEDED'

        # The agent restarted on the new runtime version...
        deadline = time.time() + 30
        health2 = {}
        while time.time() < deadline:
            health2 = provisioner.agent_request(handle.head_runner(),
                                                {'op': 'agent_health'})
            if health2.get('runtime_version') != v1:
                break
            time.sleep(0.3)
        assert health2['runtime_version'] == 'deadbeef' + real_hash[8:]
        assert health2['agentd_alive']
        # ...and the OLD job's record is still there and terminal.
        jobs = {j['job_id']: j for j in core.queue('bc')}
        assert jobs[job1]['status'] == 'SUCCEEDED'
        assert jobs[job2]['status'] == 'SUCCEEDED'
    finally:
        core.down('bc')
