"""Aux subsystems (SURVEY §5): timeline tracing, usage telemetry,
training callbacks, and the benchmark fan-out on the local provider."""
import json
import time

import pytest

from skypilot_tpu.utils import timeline

pytestmark = pytest.mark.usefixtures('tmp_state_dir')


class TestTimeline:

    def test_disabled_records_nothing(self, tmp_path, monkeypatch):
        monkeypatch.delenv('SKYTPU_TIMELINE_FILE', raising=False)
        timeline.clear()
        with timeline.Event('noop'):
            pass
        assert timeline.save(str(tmp_path / 't.json')) is None

    def test_events_and_decorator_write_chrome_trace(self, tmp_path,
                                                     monkeypatch):
        trace = tmp_path / 'trace.json'
        monkeypatch.setenv('SKYTPU_TIMELINE_FILE', str(trace))
        timeline.clear()

        @timeline.event('decorated-op')
        def op():
            time.sleep(0.01)

        op()
        with timeline.Event('manual-op', cluster='c1'):
            time.sleep(0.01)
        timeline.save()
        data = json.loads(trace.read_text())
        names = [e['name'] for e in data['traceEvents']]
        assert 'decorated-op' in names and 'manual-op' in names
        manual = next(e for e in data['traceEvents']
                      if e['name'] == 'manual-op')
        assert manual['ph'] == 'X' and manual['dur'] >= 10_000  # >=10ms
        assert manual['args'] == {'cluster': 'c1'}

    def test_launch_emits_stage_events(self, tmp_path, monkeypatch):
        import skypilot_tpu as sky
        from skypilot_tpu import core
        from skypilot_tpu.task import Task
        monkeypatch.setenv('SKYTPU_TIMELINE_FILE',
                           str(tmp_path / 'launch.json'))
        monkeypatch.setenv('SKYTPU_AGENT_TICK', '0.1')
        monkeypatch.setenv('SKYTPU_AGENT_READY_TIMEOUT', '30')
        timeline.clear()
        task = Task(name='tl', run='true')
        task.set_resources(sky.Resources(cloud='local', cpus='1+'))
        sky.launch(task, cluster_name='tlc', detach_run=True,
                   stream_logs=False)
        try:
            timeline.save()
            data = json.loads((tmp_path / 'launch.json').read_text())
            names = {e['name'] for e in data['traceEvents']}
            assert {'optimize', 'provision', 'exec'} <= names
        finally:
            core.down('tlc')


class TestUsage:

    def test_record_and_entries(self, monkeypatch):
        from skypilot_tpu.usage import usage_lib
        monkeypatch.delenv('SKYTPU_DISABLE_USAGE_COLLECTION',
                           raising=False)
        usage_lib.record('launch', cluster='c1')
        usage_lib.record('down', cluster='c1')
        entries = usage_lib.entries()
        assert [e['event'] for e in entries] == ['launch', 'down']
        assert entries[0]['run_id'] == entries[1]['run_id']

    def test_opt_out(self, monkeypatch):
        from skypilot_tpu.usage import usage_lib
        monkeypatch.setenv('SKYTPU_DISABLE_USAGE_COLLECTION', '1')
        usage_lib.record('launch')
        assert usage_lib.entries() == []


class TestCallbacks:

    def test_timer_callback_summary(self, tmp_path):
        from skypilot_tpu.callbacks import CallbackList, TimerCallback
        timer = TimerCallback(log_dir=str(tmp_path), write_every=2)
        cbs = CallbackList([timer])
        for step in range(4):
            cbs.on_step_begin(step)
            time.sleep(0.005)
            cbs.on_step_end(step, {'loss': 2.0 - step * 0.1})
        cbs.on_train_end()
        data = json.loads((tmp_path / 'benchmark_summary.json').read_text())
        assert data['num_steps'] == 4
        assert data['mean_step_seconds'] >= 0.005
        assert data['steps_per_second'] > 0
        assert abs(data['last_metrics']['loss'] - 1.7) < 1e-6

    def test_module_level_step_api(self, tmp_path):
        """The sky_callback-style API for apps not using the in-tree
        Trainer."""
        from skypilot_tpu.callbacks import api
        api.init(log_dir=str(tmp_path), write_every=1)
        for i in range(3):
            with api.step({'loss': 1.0 - i * 0.1}):
                time.sleep(0.002)
        path = api.write_summary()
        data = json.loads(open(path, encoding='utf-8').read())
        assert data['num_steps'] == 3
        assert abs(data['last_metrics']['loss'] - 0.8) < 1e-6

    def test_hf_trainer_adapter_forwards_steps(self, tmp_path):
        pytest.importorskip('transformers')
        from skypilot_tpu.callbacks import api
        cb = api.hf_trainer_callback(log_dir=str(tmp_path))

        class _State:
            global_step = 0
        state = _State()
        for i in range(2):
            state.global_step = i
            cb.on_step_begin(None, state, None)
            time.sleep(0.002)
            # transformers delivers metrics via on_log, NOT on_step_end.
            cb.on_log(None, state, None, logs={'loss': 3.0 - i})
            cb.on_step_end(None, state, None)
        cb.on_train_end(None, state, None)
        data = json.loads(
            (tmp_path / 'benchmark_summary.json').read_text())
        assert data['num_steps'] == 2
        assert abs(data['last_metrics']['loss'] - 2.0) < 1e-6

    def test_trainer_fit_drives_callbacks(self):
        import jax
        import jax.numpy as jnp

        from skypilot_tpu.callbacks import BaseCallback
        from skypilot_tpu.models import configs
        from skypilot_tpu.parallel import mesh as mesh_lib
        from skypilot_tpu.train.trainer import TrainConfig, Trainer

        seen = []

        class Probe(BaseCallback):
            def on_step_end(self, step, metrics):
                seen.append((step, metrics['loss']))

        trainer = Trainer(
            configs.TINY,
            mesh_spec=mesh_lib.MeshSpec(dp=2, fsdp=2, sp=1, tp=2),
            train_config=TrainConfig(warmup_steps=1, total_steps=10,
                                     attn_impl='xla'))
        state = trainer.init(jax.random.PRNGKey(0))
        batch = {'inputs': jnp.ones((8, 16), jnp.int32),
                 'targets': jnp.ones((8, 16), jnp.int32)}
        state = trainer.fit(state, iter(lambda: batch, None), 3,
                            callbacks=[Probe()])
        assert [s for s, _ in seen] == [0, 1, 2]
        assert int(state.step) == 3


class TestBenchmark:

    @pytest.fixture()
    def fast_agent(self, monkeypatch):
        monkeypatch.setenv('SKYTPU_AGENT_TICK', '0.1')
        monkeypatch.setenv('SKYTPU_AGENT_READY_TIMEOUT', '30')

    def test_benchmark_fan_out_and_summary(self, fast_agent, tmp_path):
        import skypilot_tpu as sky
        from skypilot_tpu import benchmark
        from skypilot_tpu.task import Task

        task = Task(name='bm', run=f'echo bench > {tmp_path}/o.txt')
        task.set_resources(sky.Resources(cloud='local', cpus='1+'))
        candidates = [sky.Resources(cloud='local', cpus='1+'),
                      sky.Resources(cloud='local', cpus='1+')]
        clusters = benchmark.launch_benchmark(task, candidates, 'bm1')
        assert clusters == ['bm1-0', 'bm1-1']
        try:
            with pytest.raises(ValueError):
                benchmark.launch_benchmark(task, candidates, 'bm1')
            deadline = time.time() + 45
            while time.time() < deadline:
                rows = benchmark.summary('bm1')
                if all(r['status'] == 'SUCCEEDED' for r in rows):
                    break
                time.sleep(0.5)
            assert all(r['status'] == 'SUCCEEDED' for r in rows), rows
            assert all(r['duration_s'] is not None for r in rows)
            assert benchmark.list_benchmarks() == ['bm1']
        finally:
            benchmark.teardown('bm1')
        assert benchmark.list_benchmarks() == []
        from skypilot_tpu import global_state
        assert global_state.get_cluster_from_name('bm1-0') is None


class TestStorageCliAndDashboard:
    """`skytpu storage ls/delete` (reference ``sky/cli.py:3474``) and the
    dashboard page (reference ``sky/jobs/dashboard/``)."""

    def test_storage_ls_and_delete(self, tmp_state_dir, tmp_path):
        from click.testing import CliRunner
        from skypilot_tpu import cli as cli_mod
        from skypilot_tpu.data import storage as storage_lib

        src = tmp_path / 'files'
        src.mkdir()
        (src / 'a.txt').write_text('data')
        st = storage_lib.Storage(name='dash-bucket', source=str(src),
                                 stores=[storage_lib.StoreType.LOCAL])
        st.sync_to_stores()

        runner = CliRunner()
        out = runner.invoke(cli_mod.cli, ['storage', 'ls'])
        assert out.exit_code == 0, out.output
        assert 'dash-bucket' in out.output and 'READY' in out.output

        out = runner.invoke(cli_mod.cli,
                            ['storage', 'delete', 'dash-bucket', '-y'])
        assert out.exit_code == 0, out.output
        out = runner.invoke(cli_mod.cli, ['storage', 'ls'])
        assert 'No existing storage' in out.output

    def test_dashboard_renders_live_tables(self, tmp_state_dir):
        import json as json_lib
        import threading
        import urllib.request

        from skypilot_tpu import dashboard
        from skypilot_tpu.utils import common_utils

        port = common_utils.find_free_port(18600)
        server = dashboard.make_server(port)
        t = threading.Thread(target=server.serve_forever, daemon=True)
        t.start()
        try:
            with urllib.request.urlopen(
                    f'http://127.0.0.1:{port}/', timeout=10) as r:
                page = r.read().decode()
            assert 'skytpu dashboard' in page
            assert 'Clusters' in page and 'Managed jobs' in page
            with urllib.request.urlopen(
                    f'http://127.0.0.1:{port}/metrics?format=json',
                    timeout=10) as r:
                metrics = json_lib.loads(r.read())
            assert 'clusters' in metrics
            assert 'telemetry' in metrics     # the registry dump
            with urllib.request.urlopen(
                    f'http://127.0.0.1:{port}/metrics',
                    timeout=10) as r:
                prom = r.read().decode()
            assert '# TYPE skytpu_clusters gauge' in prom
        finally:
            server.shutdown()


def test_agent_rpc_batch_op(tmp_state_dir, tmp_path, monkeypatch):
    """One ssh/python round trip for N ops (VERDICT r2 weak item 10:
    per-call RPC cost)."""
    monkeypatch.setenv('HOME', str(tmp_path))
    monkeypatch.setenv('SKYTPU_AGENT_DIR', str(tmp_path / '.agent'))
    from skypilot_tpu.agent import rpc
    resp = rpc.handle({'op': 'batch', 'requests': [
        {'op': 'is_idle'},
        {'op': 'autostop_config'},
        {'op': 'nonexistent-op'},
    ]})
    assert resp['ok']
    results = resp['results']
    assert results[0]['ok'] and 'idle' in results[0]
    assert results[1]['ok'] and 'idle_minutes' in results[1]
    assert not results[2]['ok'] and 'Unknown RPC op' in results[2]['error']


def test_ambient_mesh_probe():
    """LOUD-FAIL pin on the ambient-mesh probe (VERDICT r3 weak #10):
    pipeline parallelism and activation sharding constraints key off
    `llama._ambient_mesh()`, which must see the legacy `with mesh:`
    context. jax has no public accessor for that context, so the probe
    touches private internals — if a jax upgrade breaks it, this test
    turns the silent perf degradation into a red CI."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    from skypilot_tpu.models import llama

    assert llama._ambient_mesh() is None
    devices = np.array(jax.devices()[:2]).reshape(2, 1)
    with Mesh(devices, ('pp', 'tp')) as m:
        seen = llama._ambient_mesh()
        assert seen is not None and dict(seen.shape) == {'pp': 2,
                                                         'tp': 1}
        assert llama._pp_mesh() is m
    assert llama._ambient_mesh() is None
    assert llama._pp_mesh() is None
