"""Flax MNIST-style CNN training, TPU-ready (pmap-free: pjit over the
default mesh via plain jit — a single host slice needs nothing more).

Mirrors the reference's ``examples/tpu/tpuvm_mnist.yaml`` workload
(flax examples/mnist). This environment has no dataset egress, so the
default is a synthetic digits dataset with a learnable signal (class
templates + noise); pass ``--data-dir`` with the real MNIST npz to train
on actual digits.
"""
import argparse
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn


class CNN(nn.Module):

    @nn.compact
    def __call__(self, x):
        x = nn.Conv(32, (3, 3))(x)
        x = nn.relu(x)
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(64, (3, 3))(x)
        x = nn.relu(x)
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(256)(x)
        x = nn.relu(x)
        return nn.Dense(10)(x)


def synthetic_mnist(n=8192, seed=0):
    """Class-template images + noise: learnable, zero-download."""
    rng = np.random.default_rng(seed)
    templates = rng.standard_normal((10, 28, 28, 1)).astype(np.float32)
    labels = rng.integers(0, 10, n)
    imgs = templates[labels] + 0.5 * rng.standard_normal(
        (n, 28, 28, 1)).astype(np.float32)
    return imgs, labels.astype(np.int32)


def load_data(data_dir):
    if data_dir and os.path.exists(os.path.join(data_dir, 'mnist.npz')):
        with np.load(os.path.join(data_dir, 'mnist.npz')) as d:
            return (d['x_train'].reshape(-1, 28, 28, 1) / 255.0
                    ).astype(np.float32), d['y_train'].astype(np.int32)
    return synthetic_mnist()


def main():
    p = argparse.ArgumentParser()
    p.add_argument('--epochs', type=int, default=3)
    p.add_argument('--batch', type=int, default=256)
    p.add_argument('--lr', type=float, default=1e-3)
    p.add_argument('--data-dir', default=None)
    args = p.parse_args()

    imgs, labels = load_data(args.data_dir)
    model = CNN()
    params = model.init(jax.random.PRNGKey(0), imgs[:1])
    tx = optax.adam(args.lr)
    opt_state = tx.init(params)

    @jax.jit
    def step(params, opt_state, x, y):
        def loss_fn(p):
            logits = model.apply(p, x)
            loss = optax.softmax_cross_entropy_with_integer_labels(
                logits, y).mean()
            return loss, (logits.argmax(-1) == y).mean()
        (loss, acc), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        updates, opt_state = tx.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss, acc

    n_batches = len(imgs) // args.batch
    for epoch in range(args.epochs):
        t0 = time.time()
        for i in range(n_batches):
            sl = slice(i * args.batch, (i + 1) * args.batch)
            params, opt_state, loss, acc = step(
                params, opt_state, jnp.asarray(imgs[sl]),
                jnp.asarray(labels[sl]))
        print(f'epoch {epoch}: loss={float(loss):.4f} '
              f'acc={float(acc):.3f} ({time.time() - t0:.1f}s)',
              flush=True)
    print(f'final accuracy: {float(acc):.3f}')


if __name__ == '__main__':
    main()
